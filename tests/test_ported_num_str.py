"""Ported reference numerical + string expression tests
(reference: python/pathway/tests/expressions/test_numerical.py,
test_string.py) — .num abs/round/fill_na, .str strip/count/find/rfind/
parse_int/parse_float/parse_bool (strict + optional + custom mappings),
to_string round-trips incl. nanosecond datetime rendering."""

from __future__ import annotations

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown, table_from_pandas
from pathway_tpu.debug import table_from_markdown as T

from tests.ref_utils import assert_table_equality, run_all


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    from pathway_tpu.internals.errors import clear_errors

    clear_errors()
    yield
    pw.internals.parse_graph.G.clear()


# --- numerical -------------------------------------------------------------


@pytest.mark.parametrize("use_namespace", [True, False])
def test_abs_int(use_namespace: bool) -> None:
    table = table_from_markdown(
        """
        v
        -110
        -3
        7
        -1
        12
        """
    )
    if use_namespace:
        results = table.select(v_abs=table.v.num.abs())
    else:
        results = table.select(v_abs=abs(table.v))
    expected = table_from_markdown(
        """
        v_abs
        110
        3
        7
        1
        12
        """
    )
    assert_table_equality(results, expected)


@pytest.mark.parametrize("use_namespace", [True, False])
def test_abs_float(use_namespace: bool) -> None:
    table = table_from_markdown(
        """
        v
        -110.5
        -3.8
        7.2
        -1.6
        12.9
        """
    )
    if use_namespace:
        results = table.select(v_abs=table.v.num.abs())
    else:
        results = table.select(v_abs=abs(table.v))
    expected = table_from_markdown(
        """
        v_abs
        110.5
        3.8
        7.2
        1.6
        12.9
        """
    )
    assert_table_equality(results, expected)


def test_round():
    table = table_from_markdown(
        """
        v
        1
        1.2
        1.23
        1.234
        1.2345
        """
    )
    results = table.select(v_round=table.v.num.round(2))
    expected = table_from_markdown(
        """
        v_round
        1.0
        1.20
        1.23
        1.23
        1.23
        """
    )
    assert_table_equality(results, expected)


def test_round_column():
    table = table_from_markdown(
        """
        value   | precision
        3       | 0
        3.1     | 1
        3.14    | 1
        3.141   | 2
        3.1415  | 2
        """
    )
    results = table.select(v_round=table.value.num.round(pw.this.precision))
    expected = table_from_markdown(
        """
        v_round
        3.0
        3.1
        3.1
        3.14
        3.14
        """
    )
    assert_table_equality(results, expected)


def test_fill_na_optional_float():
    table = table_from_markdown(
        """
        index | v
        1     | 1.0
        2     | None
        3     | 3.5
        4     | nan
        5     | 5.0
        """
    ).with_columns(v=pw.require(pw.apply(float, pw.this.v), pw.this.v))

    results = table.select(v_filled=table.v.num.fill_na(0))
    expected = table_from_markdown(
        """
        v_filled
        1.0
        0.0
        3.5
        0.0
        5.0
        """
    )
    assert_table_equality(results, expected)


def test_fill_na_optional_int():
    table = table_from_markdown(
        """
        index | v
        1     | 1
        2     | None
        3     | 3
        4     | 4
        5     | 5
        """
    )
    results = table.select(v_filled=table.v.num.fill_na(0))
    expected = table_from_markdown(
        """
        v_filled
        1
        0
        3
        4
        5
        """
    )
    assert_table_equality(results, expected)


def test_fill_na_float():
    table = table_from_markdown(
        """
        index | v
        1     | 1.1
        2     | 2.2
        3     | 3.3
        4     | 4.4
        5     | 5.5
        """
    )
    results = table.select(v_filled=table.v.num.fill_na(0))
    expected = table_from_markdown(
        """
        v_filled
        1.1
        2.2
        3.3
        4.4
        5.5
        """
    )
    assert_table_equality(results, expected)


def test_fill_na_int():
    table = table_from_markdown(
        """
        index | v
        1     | 1
        2     | 2
        3     | 3
        4     | 4
        5     | 5
        """
    )
    results = table.select(v_filled=table.v.num.fill_na(0))
    expected = table_from_markdown(
        """
        v_filled
        1
        2
        3
        4
        5
        """
    )
    assert_table_equality(results, expected)


# --- string ----------------------------------------------------------------


def test_strip():
    t = table_from_pandas(
        pd.DataFrame(
            {"a": ["   abc", "   def   ", "ab   cd  ", "xy  zt", "zy  "]}
        )
    )
    expected = table_from_pandas(
        pd.DataFrame({"a": ["abc", "def", "ab   cd", "xy  zt", "zy"]})
    )
    result = t.select(a=pw.this.a.str.strip())
    assert_table_equality(result, expected)


def test_count():
    t = T(
        """
          | name
        0 | Alice
        1 | olice
        2 | Hello
        3 | World
        4 | Zoo
     """
    )
    assert_table_equality(
        t.select(count=pw.this.name.str.count("o")),
        T(
            """
          | count
        0 | 0
        1 | 1
        2 | 1
        3 | 1
        4 | 2
        """
        ),
    )
    assert_table_equality(
        t.select(count=pw.this.name.str.count("o", 1)),
        T(
            """
          | count
        0 | 0
        1 | 0
        2 | 1
        3 | 1
        4 | 2
        """
        ),
    )
    assert_table_equality(
        t.select(count=pw.this.name.str.count("o", 0, 3)),
        T(
            """
          | count
        0 | 0
        1 | 1
        2 | 0
        3 | 1
        4 | 2
        """
        ),
    )
    assert_table_equality(
        t.select(count=pw.this.name.str.count("o", end=2)),
        T(
            """
          | count
        0 | 0
        1 | 1
        2 | 0
        3 | 1
        4 | 1
        """
        ),
    )


def test_find():
    t = T(
        """
          | name
        0 | Alice
        1 | olice
        2 | Hello
        3 | World
        4 | Zoo
     """
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.find("o")),
        T(
            """
          | pos
        0 | -1
        1 | 0
        2 | 4
        3 | 1
        4 | 1
        """
        ),
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.find("o", 1)),
        T(
            """
          | pos
        0 | -1
        1 | -1
        2 | 4
        3 | 1
        4 | 1
        """
        ),
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.find("o", 2)),
        T(
            """
          | pos
        0 | -1
        1 | -1
        2 | 4
        3 | -1
        4 | 2
        """
        ),
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.find("o", 0, 4)),
        T(
            """
          | pos
        0 | -1
        1 | 0
        2 | -1
        3 | 1
        4 | 1
        """
        ),
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.find("o", end=2)),
        T(
            """
          | pos
        0 | -1
        1 | 0
        2 | -1
        3 | 1
        4 | 1
        """
        ),
    )


def test_rfind():
    t = T(
        """
          | name
        0 | Alice
        1 | olice
        2 | Hello
        3 | World
        4 | Zoo
     """
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.rfind("o")),
        T(
            """
          | pos
        0 | -1
        1 | 0
        2 | 4
        3 | 1
        4 | 2
        """
        ),
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.rfind("o", 1)),
        T(
            """
          | pos
        0 | -1
        1 | -1
        2 | 4
        3 | 1
        4 | 2
        """
        ),
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.rfind("o", 2)),
        T(
            """
          | pos
        0 | -1
        1 | -1
        2 | 4
        3 | -1
        4 | 2
        """
        ),
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.rfind("o", 0, 4)),
        T(
            """
          | pos
        0 | -1
        1 | 0
        2 | -1
        3 | 1
        4 | 2
        """
        ),
    )
    assert_table_equality(
        t.select(pos=pw.this.name.str.rfind("o", end=2)),
        T(
            """
          | pos
        0 | -1
        1 | 0
        2 | -1
        3 | 1
        4 | 1
        """
        ),
    )


def move_to_pathway_with_the_right_type(list, dtype):
    df = pd.DataFrame({"a": list}, dtype=dtype)
    table = table_from_pandas(df)
    return table


def test_parse_int():
    from_ = ["10", "0", "-1", "-2", "4294967297", "35184372088833"]
    to_ = [10, 0, -1, -2, 2**32 + 1, 2**45 + 1]
    table = move_to_pathway_with_the_right_type(from_, str)
    expected = move_to_pathway_with_the_right_type(to_, int)
    table = table.select(a=pw.this.a.str.parse_int())
    assert_table_equality(table, expected)


def test_parse_float():
    from_ = [
        "10.345",
        "10.999",
        "-1.012",
        "-1.99",
        "-2.01",
        "4294967297",
        "35184372088833",
    ]
    to_ = [
        10.345,
        10.999,
        -1.012,
        -1.99,
        -2.01,
        float(2**32 + 1),
        float(2**45 + 1),
    ]
    table = move_to_pathway_with_the_right_type(from_, str)
    expected = move_to_pathway_with_the_right_type(to_, float)
    table = table.select(a=pw.this.a.str.parse_float())
    assert_table_equality(table, expected)


def test_parse_bool():
    from_ = ["On", "true", "1", "Yes", "off", "False", "0", "no"]
    to_ = [True, True, True, True, False, False, False, False]
    table = move_to_pathway_with_the_right_type(from_, str)
    expected = move_to_pathway_with_the_right_type(to_, bool)
    table = table.select(a=pw.this.a.str.parse_bool())
    assert_table_equality(table, expected)


def test_parse_bool_custom_mapping():
    from_ = ["44", "true", "a", "-5"]
    to_ = [True, False, True, False]
    table = move_to_pathway_with_the_right_type(from_, str)
    expected = move_to_pathway_with_the_right_type(to_, bool)
    table = table.select(
        a=pw.this.a.str.parse_bool(
            true_values=["a", "44", ">"], false_values=["true", "-5"]
        )
    )
    assert_table_equality(table, expected)


def test_parse_int_optional():
    from_ = ["10", "0.5", "-1", "aaaa"]
    table = move_to_pathway_with_the_right_type(from_, str)
    expected = T(
        """
        a
        10
        None
        -1
        None
        """
    )
    table = table.select(a=pw.this.a.str.parse_int(optional=True))
    assert_table_equality(table, expected)


def test_parse_float_exception():
    from_ = ["10.5", "0.5", "4.4.4", "0.5"]
    table = move_to_pathway_with_the_right_type(from_, str)
    table = table.select(a=pw.this.a.str.parse_float(optional=False))
    with pytest.raises(ValueError):
        run_all()


def test_parse_float_optional():
    from_ = ["10.5", "0.5", "4.4.4", "-66"]
    table = move_to_pathway_with_the_right_type(from_, str)
    expected = T(
        """
        a
        10.5
        0.5
        None
        -66
        """
    )
    table = table.select(a=pw.this.a.str.parse_float(optional=True))
    assert_table_equality(table, expected)


def test_parse_bool_exception():
    from_ = ["1", "Truer", "off", "aaaa"]
    table = move_to_pathway_with_the_right_type(from_, str)
    table = table.select(a=pw.this.a.str.parse_bool(optional=False))
    with pytest.raises(ValueError):
        run_all()


def test_parse_bool_optional():
    from_ = ["1", "Truer", "off", "aaaa"]
    table = move_to_pathway_with_the_right_type(from_, str)
    expected = T(
        """
        a
        True
        None
        False
        None
        """
    )
    table = table.select(a=pw.this.a.str.parse_bool(optional=True))
    assert_table_equality(table, expected)


def test_parse_bool_optional_custom_mapping():
    from_ = ["1", "True", "off", "aaaa"]
    table = move_to_pathway_with_the_right_type(from_, str)
    expected = T(
        """
        a
        None
        None
        False
        None
        """
    )
    table = table.select(
        a=pw.this.a.str.parse_bool(
            true_values=["On"], false_values=["Off"], optional=True
        )
    )
    assert_table_equality(table, expected)


def test_parse_int_exception():
    from_ = ["10", "0.5", "-1", "aaaa"]
    table = move_to_pathway_with_the_right_type(from_, str)
    table = table.select(a=pw.this.a.str.parse_int(optional=False))
    with pytest.raises(ValueError):
        run_all()


def test_to_string():
    integers = [10, 0, -1, -2, 2**32 + 1, 2**45 + 1]
    bools = [True, False]
    floats = [
        10.345,
        10.999,
        -1.012,
        -1.99,
        -2.01,
        float(2**32 + 1),
        float(2**45 + 1),
    ]
    integers_table = move_to_pathway_with_the_right_type(integers, int)
    bools_table = move_to_pathway_with_the_right_type(bools, bool)
    floats_table = move_to_pathway_with_the_right_type(floats, float)
    res_integers = integers_table.select(
        a=pw.this.a.to_string().str.parse_int()
    )
    assert_table_equality(integers_table, res_integers)
    res_bools = bools_table.select(a=pw.this.a.to_string().str.parse_bool())
    assert_table_equality(bools_table, res_bools)
    res_floats = floats_table.select(a=pw.this.a.to_string().str.parse_float())
    assert_table_equality(floats_table, res_floats)


def test_to_string_for_optional_type():
    table = T(
        """
        a
        10
        None
        -1
        -2
        None
        35184372088833
        """
    )
    expected = move_to_pathway_with_the_right_type(
        ["10", "None", "-1", "-2", "None", "35184372088833"], str
    )
    res = table.select(a=pw.this.a.to_string())
    assert_table_equality(res, expected)


def test_to_string_for_datetime_naive():
    t = T(
        """
          | t
        1 | 2019-12-31T23:49:59.999999999
        2 | 2019-12-31T23:49:59.0001
        3 | 2020-03-04T11:13:00.345612
        4 | 2023-03-26T12:00:00.000000001
        """
    )
    expected = T(
        """
          | t
        1 | 2019-12-31T23:49:59.999999999
        2 | 2019-12-31T23:49:59.000100000
        3 | 2020-03-04T11:13:00.345612000
        4 | 2023-03-26T12:00:00.000000001
        """
    )
    assert_table_equality(
        t.select(t=pw.this.t.dt.strptime("%Y-%m-%dT%H:%M:%S.%f").to_string()),
        expected,
    )


def test_to_string_for_datetime_utc():
    t = T(
        """
          | t
        1 | 2019-12-31T23:49:59.999999999+0100
        2 | 2019-12-31T23:49:59.0001+0100
        3 | 2020-03-04T11:13:00.345612+0100
        4 | 2023-03-26T12:00:00.000000001+0100
        """
    )
    expected = T(
        """
          | t
        1 | 2019-12-31T22:49:59.999999999+0000
        2 | 2019-12-31T22:49:59.000100000+0000
        3 | 2020-03-04T10:13:00.345612000+0000
        4 | 2023-03-26T11:00:00.000000001+0000
        """
    )
    assert_table_equality(
        t.select(
            t=pw.this.t.dt.strptime("%Y-%m-%dT%H:%M:%S.%f%z").to_string()
        ),
        expected,
    )
