"""Ported reference deduplicate tests
(reference: python/pathway/tests/test_deduplicate.py) — acceptor-driven
deduplication whose state survives restarts: restored accumulators re-emit
their output at time 0 of the new run, re-fed rows are filtered by the
persisted acceptor state, and SELECTIVE_PERSISTING keys state by the
operator's explicit name."""

from __future__ import annotations

import pathlib
from unittest import mock

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.parse_graph import G

from tests.ref_utils import assert_stream_equality_wo_index


@pytest.fixture(autouse=True)
def _fresh_graph():
    G.clear()
    from pathway_tpu.internals.errors import clear_errors

    clear_errors()
    yield
    G.clear()


def test_deduplicate_keeps_state(tmp_path: pathlib.Path):
    persistence_path = tmp_path / "persistence"
    persistence_config = pw.persistence.Config(
        pw.persistence.Backend.filesystem(persistence_path)
    )
    data_1 = """
    val | __time__
     1  |     2
     2  |     4
     3  |     6
     4  |     8
     5  |    10
     6  |    12
     7  |    14
     8  |    16
     9  |    16
    10  |    16
    12  |    18
    13  |    20
    """
    data_2 = """
    val | __time__
     1  |     0
     2  |     0
     3  |     0
     4  |     0
     5  |     0
     6  |     0
     7  |     0
     8  |     0
     9  |     0
    10  |     0
    12  |     0
    13  |     0
    14  |    22
    15  |    24
    16  |    26
    17  |    28
    18  |    30
    """
    # values with __time__ == 0 simulate the persistence behavior from a
    # regular connector

    def acceptor(new_value, old_value) -> bool:
        return new_value >= old_value + 2

    table = pw.debug.table_from_markdown(data_1)
    result = table.deduplicate(value=pw.this.val, acceptor=acceptor)

    expected_1 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 |  1  |     2    |     1
     1 |  1  |     6    |    -1
     1 |  3  |     6    |     1
     1 |  3  |    10    |    -1
     1 |  5  |    10    |     1
     1 |  5  |    14    |    -1
     1 |  7  |    14    |     1
     1 |  7  |    16    |    -1
     1 |  9  |    16    |     1
     1 |  9  |    18    |    -1
     1 | 12  |    18    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_1, persistence_config=persistence_config
    )
    G.clear()

    table = pw.debug.table_from_markdown(data_2)
    result = table.deduplicate(value=pw.this.val, acceptor=acceptor)

    expected_2 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 | 12  |     0    |     1
     1 | 12  |    22    |    -1
     1 | 14  |    22    |     1
     1 | 14  |    26    |    -1
     1 | 16  |    26    |     1
     1 | 16  |    30    |    -1
     1 | 18  |    30    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_2, persistence_config=persistence_config
    )


def test_deduplicate_keeps_state_after_two_restarts(tmp_path: pathlib.Path):
    persistence_path = tmp_path / "persistence"
    persistence_config = pw.persistence.Config(
        pw.persistence.Backend.filesystem(persistence_path)
    )
    data_1 = """
    val | __time__
     1  |     2
     2  |     4
     3  |     6
     4  |     8
     5  |    10
     6  |    12
     7  |    14
     8  |    16
     9  |    16
    10  |    16
    12  |    18
    13  |    20
    """
    data_2 = """
    val | __time__
     1  |     0
     2  |     0
     3  |     0
     4  |     0
     5  |     0
     6  |     0
     7  |     0
     8  |     0
     9  |     0
    10  |     0
    12  |     0
    13  |     0
    14  |    22
    15  |    24
    16  |    26
    """
    data_3 = """
    val | __time__
     1  |     0
     2  |     0
     3  |     0
     4  |     0
     5  |     0
     6  |     0
     7  |     0
     8  |     0
     9  |     0
    10  |     0
    12  |     0
    13  |     0
    14  |     0
    15  |     0
    16  |     0
    17  |    28
    18  |    30
    """

    def acceptor(new_value, old_value) -> bool:
        return new_value >= old_value + 2

    table = pw.debug.table_from_markdown(data_1)
    result = table.deduplicate(value=pw.this.val, acceptor=acceptor)

    expected_1 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 |  1  |     2    |     1
     1 |  1  |     6    |    -1
     1 |  3  |     6    |     1
     1 |  3  |    10    |    -1
     1 |  5  |    10    |     1
     1 |  5  |    14    |    -1
     1 |  7  |    14    |     1
     1 |  7  |    16    |    -1
     1 |  9  |    16    |     1
     1 |  9  |    18    |    -1
     1 | 12  |    18    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_1, persistence_config=persistence_config
    )
    G.clear()

    table = pw.debug.table_from_markdown(data_2)
    result = table.deduplicate(value=pw.this.val, acceptor=acceptor)

    expected_2 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 | 12  |     0    |     1
     1 | 12  |    22    |    -1
     1 | 14  |    22    |     1
     1 | 14  |    26    |    -1
     1 | 16  |    26    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_2, persistence_config=persistence_config
    )

    G.clear()

    table = pw.debug.table_from_markdown(data_3)
    result = table.deduplicate(value=pw.this.val, acceptor=acceptor)

    expected_3 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 | 16  |     0    |     1
     1 | 16  |    30    |    -1
     1 | 18  |    30    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_3, persistence_config=persistence_config
    )


def test_deduplicate_with_instance_keeps_state(tmp_path: pathlib.Path):
    persistence_path = tmp_path / "persistence"
    persistence_config = pw.persistence.Config(
        pw.persistence.Backend.filesystem(persistence_path)
    )
    data_1 = """
    val | instance | __time__
     1  |     1    |     2
     2  |     2    |     4
     3  |     1    |     6
     4  |     1    |     8
     5  |     1    |     8
     6  |     2    |    10
     6  |     1    |    12
    """
    data_2 = """
    val | instance | __time__
     1  |     1    |     0
     2  |     2    |     0
     3  |     1    |     0
     4  |     1    |     0
     5  |     1    |     0
     6  |     2    |     0
     6  |     1    |     0
    20  |     1    |    16
    13  |     2    |    18
    18  |     1    |    20
    24  |     1    |    22
    """

    def acceptor(new_value, old_value) -> bool:
        return new_value >= old_value + 3

    table = pw.debug.table_from_markdown(data_1)
    result = table.deduplicate(
        value=pw.this.val, instance=pw.this.instance, acceptor=acceptor
    )
    expected_1 = pw.debug.table_from_markdown(
        """
    id | val | instance | __time__ | __diff__
     1 |  1  |     1    |     2    |     1
     2 |  2  |     2    |     4    |     1
     1 |  1  |     1    |     8    |    -1
     1 |  4  |     1    |     8    |     1
     2 |  2  |     2    |    10    |    -1
     2 |  6  |     2    |    10    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_1, persistence_config=persistence_config
    )
    G.clear()

    table = pw.debug.table_from_markdown(data_2)
    result = table.deduplicate(
        value=pw.this.val, instance=pw.this.instance, acceptor=acceptor
    )
    expected_2 = pw.debug.table_from_markdown(
        """
    id | val | instance | __time__ | __diff__
     1 |  4  |     1    |     0    |     1
     2 |  6  |     2    |     0    |     1
     1 |  4  |     1    |    16    |    -1
     1 | 20  |     1    |    16    |     1
     2 |  6  |     2    |    18    |    -1
     2 | 13  |     2    |    18    |     1
     1 | 20  |     1    |    22    |    -1
     1 | 24  |     1    |    22    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_2, persistence_config=persistence_config
    )


def test_deduplicate_keeps_state_after_code_change(tmp_path: pathlib.Path):
    persistence_path = tmp_path / "persistence"
    persistence_config = pw.persistence.Config(
        pw.persistence.Backend.filesystem(persistence_path)
    )
    data_1 = """
    val | __time__
     1  |     2
     2  |     4
     3  |     6
     4  |     8
    """
    data_2 = """
    val | __time__
     1  |     0
     2  |     0
     3  |     0
     4  |     0
     5  |    10
     6  |    12
     7  |    14
     8  |    16
    """

    def acceptor_1(new_value, old_value) -> bool:
        return new_value >= old_value + 2

    table = pw.debug.table_from_markdown(data_1)
    result = table.deduplicate(value=pw.this.val, acceptor=acceptor_1)

    expected_1 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 |  1  |     2    |     1
     1 |  1  |     6    |    -1
     1 |  3  |     6    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_1, persistence_config=persistence_config
    )
    G.clear()

    def acceptor_2(new_value, old_value) -> bool:
        return new_value >= old_value + 4  # offset is now 4, was 2

    table = pw.debug.table_from_markdown(data_2)
    result = table.deduplicate(value=pw.this.val, acceptor=acceptor_2)

    expected_2 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 |  3  |     0    |     1
     1 |  3  |    14    |    -1
     1 |  7  |    14    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_2, persistence_config=persistence_config
    )


def test_deduplicate_keeps_state_with_regular_persistence(
    tmp_path: pathlib.Path,
):
    persistence_path = tmp_path / "persistence"
    persistence_config = pw.persistence.Config(
        pw.persistence.Backend.filesystem(persistence_path)
    )

    def run_computation(nb_rows: int, offset: int, expected: list[int]):
        G.clear()

        def acceptor(new_value, old_value) -> bool:
            return new_value >= old_value + 2

        table = pw.demo.range_stream(
            nb_rows, offset=offset, input_rate=25, autocommit_duration_ms=10
        )
        result = table.deduplicate(value=pw.this.value, acceptor=acceptor)
        emit = mock.Mock()

        def on_change(key, row: dict, time: int, is_addition: bool):
            if is_addition:
                emit(row["value"])

        pw.io.subscribe(result, on_change)
        pw.run(
            monitoring_level=pw.MonitoringLevel.NONE,
            persistence_config=persistence_config,
        )
        emit.assert_has_calls([mock.call(i) for i in expected])

    run_computation(6, 0, [0, 2, 4])
    run_computation(5, 6, [6, 8, 10])


def test_selective_persistence_name_set(tmp_path: pathlib.Path):
    persistence_path = tmp_path / "persistence"
    persistence_config = pw.persistence.Config(
        pw.persistence.Backend.filesystem(persistence_path),
        persistence_mode=pw.PersistenceMode.SELECTIVE_PERSISTING,
    )
    data_1 = """
    val | __time__
     1  |     2
     2  |     4
     3  |     6
     4  |     8
     5  |    10
    """
    data_2 = """
    val | __time__
     1  |     2
     2  |     4
     3  |     6
     4  |     8
     5  |    10
     6  |    12
     7  |    14
     8  |    16
     9  |    16
    """

    def acceptor(new_value, old_value) -> bool:
        return new_value >= old_value + 2

    table = pw.debug.table_from_markdown(data_1)
    result = table.deduplicate(
        value=pw.this.val, acceptor=acceptor, name="foo"
    )
    expected_1 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 |  1  |     2    |     1
     1 |  1  |     6    |    -1
     1 |  3  |     6    |     1
     1 |  3  |    10    |    -1
     1 |  5  |    10    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_1, persistence_config=persistence_config
    )
    G.clear()

    table = pw.debug.table_from_markdown(data_2)
    result = table.deduplicate(
        value=pw.this.val, acceptor=acceptor, name="foo"
    )
    expected_2 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 |  5  |     0    |     1
     1 |  5  |    14    |    -1
     1 |  7  |    14    |     1
     1 |  7  |    16    |    -1
     1 |  9  |    16    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_2, persistence_config=persistence_config
    )


@pytest.mark.parametrize(
    "first_id,second_id",
    [(None, None), ("foo", "bar"), (None, "foo"), ("bar", None)],
)
def test_selective_persistence_no_name_set_or_different_names_set(
    tmp_path: pathlib.Path,
    first_id: str | None,
    second_id: str | None,
):
    persistence_path = tmp_path / "persistence"
    persistence_config = pw.persistence.Config(
        pw.persistence.Backend.filesystem(persistence_path),
        persistence_mode=pw.PersistenceMode.SELECTIVE_PERSISTING,
    )
    data_1 = """
    val | __time__
     1  |     2
     2  |     4
     3  |     6
     4  |     8
     5  |    10
    """
    data_2 = """
    val | __time__
     1  |     2
     2  |     4
     3  |     6
     4  |     8
     5  |    10
     6  |    12
     7  |    14
     8  |    16
     9  |    16
    """

    def acceptor(new_value, old_value) -> bool:
        return new_value >= old_value + 2

    table = pw.debug.table_from_markdown(data_1)
    result = table.deduplicate(
        value=pw.this.val, acceptor=acceptor, name=first_id
    )
    expected_1 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 |  1  |     2    |     1
     1 |  1  |     6    |    -1
     1 |  3  |     6    |     1
     1 |  3  |    10    |    -1
     1 |  5  |    10    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_1, persistence_config=persistence_config
    )
    G.clear()

    table = pw.debug.table_from_markdown(data_2)
    result = table.deduplicate(
        value=pw.this.val, acceptor=acceptor, name=second_id
    )
    expected_2 = pw.debug.table_from_markdown(
        """
    id | val | __time__ | __diff__
     1 |  1  |     2    |     1
     1 |  1  |     6    |    -1
     1 |  3  |     6    |     1
     1 |  3  |    10    |    -1
     1 |  5  |    10    |     1
     1 |  5  |    14    |    -1
     1 |  7  |    14    |     1
     1 |  7  |    16    |    -1
     1 |  9  |    16    |     1
    """
    )
    assert_stream_equality_wo_index(
        result, expected_2, persistence_config=persistence_config
    )
