"""Lowering Ledger (pathway_tpu/analysis/lowering.py): the shared
Mosaic 8x128 gate, the device-free AOT prover (jax.export against the
TPU platform under JAX_PLATFORMS=cpu), the content-addressed manifest,
and live segment-program registration from the engine."""

import json

import jax
import jax.numpy as jnp
import pytest

from pathway_tpu.analysis import lowering as L

# --- shared static gate ----------------------------------------------------


def test_lane_pad_ladder():
    assert L.lane_pad(1) == 128
    assert L.lane_pad(10) == 128
    assert L.lane_pad(128) == 128
    assert L.lane_pad(129) == 256
    assert L.lane_pad(256) == 256


def test_block_rule_violation_carries_rule_id():
    with pytest.raises(L.LoweringRuleViolation) as ei:
        L.check_tpu_block_rules((8, 10), (8, 20))
    assert ei.value.rule == L.RULE_8X128
    # stays a ValueError so pre-existing gates keep working
    assert isinstance(ei.value, ValueError)
    L.check_tpu_block_rules((8, 128), (64, 256))  # aligned: fine
    L.check_tpu_block_rules((8, 20), (8, 20))  # equals array dims: fine


def test_gate_is_single_source_of_truth():
    from pathway_tpu.ops import paged_attention as pa
    from pathway_tpu.ops import pallas_topk as pt

    assert pt.check_tpu_block_rules is L.check_tpu_block_rules
    assert pa.check_tpu_block_rules is L.check_tpu_block_rules
    assert pa.lane_pad is L.lane_pad
    assert pt._kpad(10) == L.lane_pad(10)


def test_estimate_vmem_double_buffers_blocks():
    from jax.experimental import pallas as pl

    spec = pl.BlockSpec((8, 128), lambda i: (0, 0))
    est = L.estimate_vmem_bytes([(spec, (8, 256))], [(4, 128)])
    assert est == 2 * 8 * 128 * 4 + 4 * 128 * 4


def test_parse_shape_spec():
    fam, shape = L.parse_shape_spec("paged_attention:head_dim=129,b=4")
    assert fam == "paged_attention"
    assert shape == {"head_dim": 129, "b": 4}
    assert L.parse_shape_spec("pallas_topk") == ("pallas_topk", {})
    with pytest.raises(ValueError):
        L.parse_shape_spec("fam:k")
    with pytest.raises(ValueError):
        L.parse_shape_spec("fam:k=ten")
    with pytest.raises(ValueError):
        L.case_for_shape("bogus_family", {})


# --- the prover ------------------------------------------------------------


def test_prover_topk_family_lowers_pad_ladder():
    rep = L.prove_lowering(families=["pallas_topk"], include_live=False)
    assert not rep.findings, [f.message for f in rep.findings]
    lowered = rep.by_status("lowered")
    # pad ladder incl. the BENCH_r02 crash shape k=10
    assert {e["case"] for e in lowered} >= {"b8_d128_n2048_k10"}
    for e in lowered:
        assert len(e["stablehlo_sha256"]) == 64
        assert e["mlir_bytes"] > 0
        assert 0 < e["vmem_frac"] <= 1
    # and the raw un-lane-padded tile stays rejected by the gate
    rejected = rep.by_status("rejected")
    assert rejected and rejected[0]["rule"] == L.RULE_8X128


def test_prover_paged_attention_rejects_bad_head_dims():
    rep = L.prove_lowering(
        families=["paged_attention"], include_live=False
    )
    assert not rep.findings, [f.message for f in rep.findings]
    by_case = {e["case"]: e for e in rep.entries}
    for dp in (1, 32, 129):
        entry = by_case[f"b8_h4_p16_dp{dp}"]
        assert entry["status"] == "rejected"
        assert entry["rule"] == L.RULE_LANE_PAD
    assert by_case["b8_h4_p16_dp128"]["status"] == "lowered"


def test_unpadded_user_shape_is_error_finding():
    """The acceptance path: a deliberately unpadded head_dim injected
    via --prove-shape must be rejected with a finding naming the
    kernel, shape and violated rule."""
    case = L.case_for_shape("paged_attention", {"head_dim": 129})
    rep = L.prove_lowering(cases=[case])
    assert rep.entries[0]["status"] == "gate-rejected"
    (finding,) = rep.findings
    assert finding.severity.name == "ERROR"
    assert finding.data["family"] == "paged_attention"
    assert finding.data["shape"]["head_dim"] == 129
    assert finding.data["rule"] == L.RULE_LANE_PAD
    assert "paged_attention" in finding.message
    assert "129" in finding.message


def test_gate_regression_is_error():
    """A known-bad shape the gate stops rejecting is itself an ERROR."""
    case = L.LoweringCase(
        "fake",
        "now_accepted",
        {"k": 10},
        static_check=lambda: None,
        expect="reject",
    )
    rep = L.prove_lowering(cases=[case])
    assert rep.entries[0]["status"] == "gate-regression"
    (finding,) = rep.findings
    assert finding.severity.name == "ERROR"
    assert "no longer rejects" in finding.message


def test_lowering_failure_is_error_finding():
    def build():
        raise RuntimeError("synthetic lowering failure")

    case = L.LoweringCase("fake", "boom", {}, build=build)
    rep = L.prove_lowering(cases=[case])
    assert rep.entries[0]["status"] == "lowering-failed"
    (finding,) = rep.findings
    assert finding.severity.name == "ERROR"
    assert "synthetic lowering failure" in finding.message


def test_vmem_budget_finding():
    case = L.LoweringCase(
        "fake",
        "huge",
        {},
        vmem=lambda: L.VMEM_LIMIT_BYTES + 1,
    )
    rep = L.prove_lowering(cases=[case])
    (finding,) = rep.findings
    assert finding.data["rule"] == L.RULE_VMEM
    assert finding.severity.name == "ERROR"


def test_unknown_family_raises():
    with pytest.raises(ValueError, match="unknown kernel family"):
        L.prove_lowering(families=["bogus"])


# --- manifest --------------------------------------------------------------


def test_manifest_is_content_addressed(tmp_path):
    rep1 = L.prove_lowering(families=["pallas_topk"], include_live=False)
    rep2 = L.prove_lowering(families=["pallas_topk"], include_live=False)
    m1, m2 = rep1.to_manifest(), rep2.to_manifest()
    # deterministic: same cases -> same content hash
    assert m1["content_sha256"] == m2["content_sha256"]
    # any entry change moves the hash
    rep2.entries[0]["mlir_bytes"] += 1
    assert rep2.to_manifest()["content_sha256"] != m1["content_sha256"]

    path = tmp_path / "LOWERING_r16.json"
    L.write_manifest(rep1, str(path))
    doc = json.loads(path.read_text())
    assert doc["version"] == 1
    assert doc["platform"] == "tpu"
    assert doc["content_sha256"] == m1["content_sha256"]
    assert len(doc["cases"]) == len(rep1.entries)


# --- live segment-program registration -------------------------------------


def test_register_program_and_prove_live():
    L.clear_live_programs()
    try:

        @jax.jit
        def f(x):
            return x * 2 + 1

        L.register_program(
            "seg_test",
            f,
            (jax.ShapeDtypeStruct((64,), jnp.float32),),
            x64=False,
            meta={"rows": 64},
        )
        cases = L.live_cases()
        assert [c.name for c in cases] == ["seg_test"]
        rep = L.prove_lowering(cases=cases)
        assert rep.entries[0]["status"] == "lowered"
        assert not rep.findings
    finally:
        L.clear_live_programs()


def test_segment_runner_registers_with_ledger():
    """The engine hook: running a compiled tick hands the jitted
    segment program to the ledger, and the ledger proves it for TPU."""
    import numpy as np

    import pathway_tpu as pw
    from pathway_tpu.engine.compile import _build_program
    from pathway_tpu.engine.nodes import ALL_NODES

    L.clear_live_programs()
    n0 = len(ALL_NODES)
    try:
        t = pw.debug.table_from_rows(
            pw.schema_from_types(x=int), [(1,)]
        )
        mapped = t.select(y=pw.this.x * 3 + 1)
        chain = [mapped._node]
        external = list(chain[0].inputs[0].column_names)
        dtypes = {"x": np.dtype("int64")}
        prog = _build_program(chain, external, dtypes)
        args = tuple(
            jax.ShapeDtypeStruct((8,), dtypes[c]) for c in prog.in_cols
        )
        L.register_program("seg_x_rows8", prog.fn, args, meta={"rows": 8})
        rep = L.prove_lowering(cases=L.live_cases())
        assert rep.entries[0]["status"] == "lowered", rep.entries
        assert not rep.findings
    finally:
        del ALL_NODES[n0:]
        L.clear_live_programs()
