"""Indexing / KNN tests (modeled on reference
python/pathway/tests/external_index/test_usearch_knn.py + ml/test_index)."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts


def _vec_table(rows):
    """rows: list of (name, vector)"""
    import pathway_tpu.debug as dbg

    schema = pw.schema_from_types(name=str, vec=np.ndarray)
    return dbg.table_from_rows(
        schema, [(n, np.asarray(v, dtype=np.float32)) for n, v in rows]
    )


DOCS = [
    ("a", [1.0, 0.0, 0.0]),
    ("b", [0.0, 1.0, 0.0]),
    ("c", [0.0, 0.0, 1.0]),
    ("d", [0.9, 0.1, 0.0]),
]


def test_dense_topk_op():
    from pathway_tpu.ops.knn import dense_topk

    corpus = np.asarray([d[1] for d in DOCS], dtype=np.float32)
    valid = np.ones(len(DOCS), dtype=bool)
    q = np.asarray([[1.0, 0.0, 0.0]], dtype=np.float32)
    scores, idx = dense_topk(q, corpus, valid, 2, metric="cosine")
    assert list(np.asarray(idx)[0]) == [0, 3]


def test_knn_data_index_query():
    docs = _vec_table(DOCS)
    queries = _vec_table([("q1", [1.0, 0.0, 0.0]), ("q2", [0.0, 1.0, 0.0])])

    from pathway_tpu.stdlib.indexing import DataIndex, TpuKnn

    index = DataIndex(docs, TpuKnn(docs.vec, dimensions=3))
    result = index.query_as_of_now(queries.vec, number_of_matches=2).select(
        qname=pw.left.name, names=pw.right.name
    )
    _keys, cols = table_to_dicts(result)
    by_q = {cols["qname"][k]: cols["names"][k] for k in cols["qname"]}
    assert by_q["q1"] == ("a", "d")
    assert by_q["q2"][0] == "b"


def test_knn_index_incremental_updates():
    # full `query` mode: answers update when the index changes
    import pathway_tpu.debug as dbg

    schema = pw.schema_from_types(name=str, vec=np.ndarray)
    docs = dbg.table_from_rows(
        schema,
        [
            ("a", np.asarray([1.0, 0.0], dtype=np.float32), 0, 1),
            ("z", np.asarray([0.99, 0.01], dtype=np.float32), 4, 1),
        ],
        is_stream=True,
    )
    queries = _vec_table([("q", [1.0, 0.0])])
    from pathway_tpu.stdlib.indexing import DataIndex, TpuKnn

    index = DataIndex(docs, TpuKnn(docs.vec, dimensions=2))
    result = index.query(queries.vec, number_of_matches=1).select(
        names=pw.right.name
    )
    _keys, cols = table_to_dicts(result)
    # after doc 'z' at t=4 the answer should still be 'a' (cos sim 1.0)
    assert list(cols["names"].values()) == [("a",)]


def test_metadata_filter():
    import pathway_tpu.debug as dbg

    schema = pw.schema_from_types(name=str, vec=np.ndarray, meta=dict)
    docs = dbg.table_from_rows(
        schema,
        [
            ("a", np.asarray([1.0, 0.0], np.float32), {"lang": "en"}),
            ("b", np.asarray([0.9, 0.1], np.float32), {"lang": "fr"}),
        ],
    )
    queries = T(
        """
        qname | filter
        q1    | lang=='fr'
        """
    ).select(
        qname=pw.this.qname,
        filter=pw.this.filter,
        vec=pw.apply_with_type(
            lambda _: np.asarray([1.0, 0.0], np.float32), np.ndarray, pw.this.qname
        ),
    )
    from pathway_tpu.stdlib.indexing import DataIndex, TpuKnn

    index = DataIndex(
        docs, TpuKnn(docs.vec, docs.meta, dimensions=2)
    )
    result = index.query_as_of_now(
        queries.vec, number_of_matches=1, metadata_filter=queries["filter"]
    ).select(names=pw.right.name)
    _keys, cols = table_to_dicts(result)
    assert list(cols["names"].values()) == [("b",)]


def test_bm25_index():
    docs = T(
        """
        text
        the quick brown fox
        lazy dogs sleep deeply
        quick silver fox runs
        """
    )
    queries = T(
        """
        q
        quick fox
        """
    )
    from pathway_tpu.stdlib.indexing import DataIndex, TantivyBM25

    index = DataIndex(docs, TantivyBM25(docs.text))
    result = index.query_as_of_now(queries.q, number_of_matches=2).select(
        texts=pw.right.text
    )
    _keys, cols = table_to_dicts(result)
    texts = list(cols["texts"].values())[0]
    assert len(texts) == 2
    assert all("fox" in t for t in texts)


def test_hybrid_index():
    import pathway_tpu.debug as dbg

    schema = pw.schema_from_types(text=str, vec=np.ndarray)
    docs = dbg.table_from_rows(
        schema,
        [
            ("alpha beta", np.asarray([1.0, 0.0], np.float32)),
            ("gamma delta", np.asarray([0.0, 1.0], np.float32)),
        ],
    )
    queries = dbg.table_from_rows(
        pw.schema_from_types(q=str, vec=np.ndarray),
        [("alpha", np.asarray([1.0, 0.0], np.float32))],
    )
    from pathway_tpu.stdlib.indexing import (
        DataIndex,
        HybridIndex,
        TantivyBM25,
        TpuKnn,
    )

    hybrid = HybridIndex(
        [TpuKnn(docs.vec, dimensions=2), TantivyBM25(docs.text)]
    )
    # hybrid queries need the same query column for both — use vec for knn
    # and text for bm25 is not supported in one call; reference queries with
    # a single column as well.
    index = DataIndex(docs, hybrid)
    result = index.query_as_of_now(queries.vec, number_of_matches=1).select(
        texts=pw.right.text
    )
    _keys, cols = table_to_dicts(result)
    assert list(cols["texts"].values()) == [("alpha beta",)]


def test_ml_knn_index():
    docs = _vec_table(DOCS)
    queries = _vec_table([("q", [0.95, 0.05, 0.0])])
    from pathway_tpu.stdlib.ml import KNNIndex

    index = KNNIndex(docs.vec, docs, n_dimensions=3)
    res = index.get_nearest_items(queries.vec, k=2, with_distances=True)
    _keys, cols = table_to_dicts(res)
    names = list(cols["name"].values())[0]
    dists = list(cols["dist"].values())[0]
    assert set(names) == {"a", "d"}
    assert all(d >= 0 for d in dists)


def test_lsh_knn():
    rng = np.random.default_rng(0)
    base = rng.normal(size=(20, 8)).astype(np.float32)
    docs = _vec_table([(f"d{i}", base[i]) for i in range(20)])
    queries = _vec_table([("q", base[7] + 0.001)])
    from pathway_tpu.stdlib.indexing import DataIndex, LshKnn

    index = DataIndex(
        docs, LshKnn(docs.vec, dimensions=8, bucket_length=100.0, n_or=8, n_and=2)
    )
    res = index.query_as_of_now(queries.vec, number_of_matches=1).select(
        names=pw.right.name
    )
    _keys, cols = table_to_dicts(res)
    assert list(cols["names"].values()) == [("d7",)]
