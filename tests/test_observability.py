"""Fleet Lens tests — SLO signal rings, the crash-surviving incident
journal, and fleet federation (/fleet/metrics, /fleet/events,
/fleet/trace).

Tier-1 coverage of the PR-17 acceptance bars, in-process and fast:

* journal ring semantics, tmp+rename persistence + restore, and the
  postmortem bundle;
* signal sampler counter-delta rates, histogram quantiles and SLO burn
  rates against a synthetic registry;
* metrics federation: a 3-member plane's merged exposition passes
  ``validate_exposition`` (member label injected, one HELP/TYPE per
  family, dead member -> ``pathway_fleet_member_up 0``);
* event federation: (incarnation, wall, tick)-ordered merge and the
  ``window_from_events`` takeover/reshard window math the chaos bench
  now derives its windows from;
* trace stitching: one trace id cut across router -> replica -> writer
  documents, Perfetto-loadable (``validate_chrome_trace`` clean);
* the real writer -> replicas -> router plane serving /fleet/* live;
* router metric label cardinality bounded across shard-map swaps;
* the Graph Doctor ``observability-coverage`` rule.
"""

import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import pathway_tpu as pw  # noqa: F401 — parse-graph fixture parity


@pytest.fixture(autouse=True)
def _lens_env(monkeypatch):
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "fleet-lens-test-secret")
    for var in (
        "PATHWAY_JOURNAL_PATH",
        "PATHWAY_JOURNAL_MEMBER",
        "PATHWAY_POSTMORTEM_DIR",
        "PATHWAY_FLEET_MEMBERS",
        "PATHWAY_SERVING_REPLICAS",
        "PATHWAY_SERVING_SHARD_MAP",
        "PATHWAY_REPL_PORT",
        "PATHWAY_MESH_INCARNATION",
    ):
        monkeypatch.delenv(var, raising=False)
    for name in _SLO_VARS:
        monkeypatch.delenv(name, raising=False)
    from pathway_tpu.observability.journal import reset_journal
    from pathway_tpu.observability.signals import reset_sampler

    reset_journal()
    reset_sampler()
    yield
    reset_sampler()
    reset_journal()


_SLO_VARS = (
    "PATHWAY_SLO_SHED_RATE",
    "PATHWAY_SLO_TTFT_P99_MS",
    "PATHWAY_SLO_STALENESS_S",
    "PATHWAY_SLO_TOK_S",
)


def _wait(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


# ---------------------------------------------------------------------------
# incident journal


def test_journal_ring_bound_filter_and_ordering():
    from pathway_tpu.observability.journal import IncidentJournal

    j = IncidentJournal(capacity=8, member="m0")
    for i in range(20):
        j.record("tick-event", f"e{i}", tick=i, extra=i)
    evs = j.events()
    assert len(evs) == 8  # bounded ring
    assert [e["detail"] for e in evs] == [f"e{i}" for i in range(12, 20)]
    assert all(e["member"] == "m0" for e in evs)
    assert evs[-1]["data"]["extra"] == 19
    j.record("takeover", "the one that matters")
    assert [e["kind"] for e in j.events(kinds=["takeover"])] == ["takeover"]
    assert len(j.tail(3)) == 3
    # seq strictly increases and stamps ride along
    seqs = [e["seq"] for e in j.events()]
    assert seqs == sorted(seqs)
    assert all(e["wall"] > 0 and e["mono"] > 0 for e in j.events())


def test_journal_persistence_roundtrip_marks_restored(tmp_path):
    from pathway_tpu.observability.journal import IncidentJournal

    path = str(tmp_path / "journal.jsonl")
    j = IncidentJournal(capacity=32, path=path, member="writer")
    j.record("zombie-fenced", "inc 3 outranked", persist=True, incarnation=3)
    j.record("caught-up", tick=7, persist=True)
    # a fresh process (same path) picks its past back up, marked restored
    j2 = IncidentJournal(capacity=32, path=path, member="writer")
    evs = j2.events()
    assert [e["kind"] for e in evs] == ["zombie-fenced", "caught-up"]
    assert all(e["data"]["restored"] for e in evs)
    assert evs[0]["incarnation"] == 3
    assert evs[1]["tick"] == 7
    # new events append after the restored tail
    j2.record("takeover")
    assert [e["kind"] for e in j2.events()][-1] == "takeover"


def test_postmortem_bundle_layout(tmp_path):
    from pathway_tpu.observability.journal import IncidentJournal

    j = IncidentJournal(capacity=16, member="replica-1")
    j.record("router-eject", "liveness", replica="s0.replica1")
    path = j.postmortem(
        "unhandled-exception",
        ValueError("boom"),
        directory=str(tmp_path),
    )
    assert path is not None
    bundle = json.loads((tmp_path / path.split("/")[-1]).read_text())
    assert bundle["reason"] == "unhandled-exception"
    assert bundle["member"] == "replica-1"
    assert bundle["exception"]["type"] == "ValueError"
    assert "boom" in bundle["exception"]["message"]
    assert [e["kind"] for e in bundle["journal"]] == ["router-eject"]
    assert isinstance(bundle["spans"], list)
    assert isinstance(bundle["metrics"], str)  # a registry render
    assert "MainThread" in bundle["threads"]
    # nowhere to write -> explicit None, never a throw
    assert j.postmortem("nowhere") is None


def test_crash_hooks_record_and_chain(monkeypatch, tmp_path):
    import importlib

    # the package re-exports the journal() accessor under the same name
    # as the submodule, so fetch the module itself
    jmod = importlib.import_module("pathway_tpu.observability.journal")

    monkeypatch.setenv("PATHWAY_POSTMORTEM_DIR", str(tmp_path))
    jmod.reset_journal()
    import sys

    seen = []
    monkeypatch.setattr(sys, "excepthook", lambda *a: seen.append(a))
    monkeypatch.setattr(jmod, "_hooks_installed", False)
    jmod.install_crash_hooks()
    sys.excepthook(ValueError, ValueError("kapow"), None)
    assert seen, "previous hook must still run"
    evs = jmod.journal().events(kinds=["unhandled-exception"])
    assert evs and "kapow" in evs[0]["detail"]
    assert list(tmp_path.glob("postmortem-*.json")), "bundle written"


# ---------------------------------------------------------------------------
# signal sampler


def test_signal_sampler_rates_quantiles_and_burn(monkeypatch):
    from pathway_tpu.observability.registry import MetricsRegistry
    from pathway_tpu.observability.signals import SignalSampler

    reg = MetricsRegistry()
    shed = reg.counter(
        "pathway_serving_shed_total", "sheds", labelnames=("route", "reason")
    )
    admitted = reg.counter(
        "pathway_serving_admitted_total", "admits", labelnames=("route",)
    )
    queue = reg.gauge("pathway_serving_queue_depth", "queue")
    ttft = reg.histogram(
        "pathway_generate_ttft_seconds",
        "ttft",
        labelnames=("replica",),
        buckets=(0.05, 0.1, 0.5),
    )
    # materialize the children so the baseline sample snapshots zeros
    shed.labels("/query", "occupancy").inc(0)
    admitted.labels("/query").inc(0)
    s = SignalSampler(interval_s=0.1, depth=16, registry=reg)
    s.sample_once()  # baseline counter snapshot
    shed.labels("/query", "occupancy").inc(10)
    admitted.labels("/query").inc(90)
    queue.set(7)
    for _ in range(50):
        ttft.labels("0").observe(0.08)
    s.sample_once()
    assert s.rings["shed_rate"].last() == pytest.approx(0.1)
    assert s.rings["wfq_backlog"].last() == 7.0
    # p99 interpolates inside the (0.05, 0.1] bucket
    assert 50.0 < s.rings["ttft_p99_ms"].last() <= 100.0
    monkeypatch.setenv("PATHWAY_SLO_SHED_RATE", "0.05")
    burns = s.burn_rates()
    assert burns["shed_rate"]["target"] == pytest.approx(0.05)
    assert burns["shed_rate"]["burn"] == pytest.approx(2.0)
    snap = s.snapshot(series_points=4)
    assert snap["signals"]["shed_rate"]["last"] == pytest.approx(0.1)
    assert len(snap["signals"]["shed_rate"]["series"]) >= 1
    assert "shed_rate" in snap["slo"]


def test_signal_ring_window_math():
    from pathway_tpu.observability.signals import SignalRing

    r = SignalRing(depth=8)
    now = time.monotonic()
    for i in range(6):
        r.append(1000.0 + i, now - (5 - i), float(i))
    assert r.last() == 5.0
    # only the last ~3 seconds: values 3, 4, 5
    assert r.window_avg(2.5, now_mono=now) == pytest.approx(4.0)
    assert r.window_max(2.5, now_mono=now) == 5.0
    assert len(r.series(3)) == 3


# ---------------------------------------------------------------------------
# federation against fake members


class _FakeMember:
    """Minimal HTTP member serving canned /metrics, /debug/events and
    /debug/trace bodies."""

    def __init__(self, metrics="", events=None, trace=None):
        self.bodies = {
            "/metrics": (metrics, "text/plain"),
            "/debug/events": (
                json.dumps({"member": "ignored", "events": events or []}),
                "application/json",
            ),
            "/debug/trace": (
                json.dumps(trace or {"traceEvents": []}),
                "application/json",
            ),
        }
        outer = self

        class H(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0]
                body, ctype = outer.bodies.get(path, ("nope", "text/plain"))
                raw = body.encode()
                self.send_response(200 if path in outer.bodies else 404)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def log_message(self, *a):
                pass

        self.server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.port = self.server.server_address[1]
        threading.Thread(
            target=self.server.serve_forever, daemon=True
        ).start()

    @property
    def url(self):
        return f"http://127.0.0.1:{self.port}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def test_federate_metrics_three_members_passes_validator():
    from pathway_tpu.observability import validate_exposition
    from pathway_tpu.observability.exposition import parse_exposition
    from pathway_tpu.observability.fleet import federate_metrics

    body = (
        "# HELP pathway_replica_requests_total reqs\n"
        "# TYPE pathway_replica_requests_total counter\n"
        'pathway_replica_requests_total{replica="0",status="2xx"} 4\n'
        "# HELP pathway_replica_staleness_seconds s\n"
        "# TYPE pathway_replica_staleness_seconds gauge\n"
        'pathway_replica_staleness_seconds{replica="0"} 0.25\n'
    )
    members = [_FakeMember(metrics=body) for _ in range(3)]
    try:
        text, errors = federate_metrics(
            [(f"replica-{i}", m.url) for i, m in enumerate(members)]
        )
    finally:
        for m in members:
            m.close()
    assert errors == {}
    assert validate_exposition(text) == [], text
    families, perrs = parse_exposition(text)
    assert perrs == []
    reqs = families["pathway_replica_requests_total"]
    assert {s.labels["member"] for s in reqs.samples} == {
        "replica-0", "replica-1", "replica-2",
    }
    up = families["pathway_fleet_member_up"]
    assert all(s.value == 1.0 for s in up.samples)


def test_federate_metrics_dead_member_degrades_not_raises():
    from pathway_tpu.observability import validate_exposition
    from pathway_tpu.observability.exposition import parse_exposition
    from pathway_tpu.observability.fleet import federate_metrics

    alive = _FakeMember(metrics="pathway_x_total 1\n")
    try:
        text, errors = federate_metrics(
            [("alive", alive.url), ("dead", "http://127.0.0.1:9")],
            timeout=0.5,
        )
    finally:
        alive.close()
    assert "dead" in errors
    assert validate_exposition(text) == [], text
    families, _ = parse_exposition(text)
    up = {
        s.labels["member"]: s.value
        for s in families["pathway_fleet_member_up"].samples
    }
    assert up == {"alive": 1.0, "dead": 0.0}


def test_federate_events_orders_and_window_from_events():
    from pathway_tpu.observability.fleet import (
        federate_events,
        window_from_events,
    )

    t0 = 1000.0
    writer_events = [
        {"seq": 1, "kind": "writer-reshard", "wall": t0, "tick": 5,
         "incarnation": 0},
    ]
    replica_events = [
        {"seq": 1, "kind": "stream-disconnect", "wall": t0 + 1.0,
         "tick": None, "incarnation": 0},
        {"seq": 2, "kind": "caught-up", "wall": t0 + 3.5, "tick": 9,
         "incarnation": 1},
    ]
    w = _FakeMember(events=writer_events)
    r = _FakeMember(events=replica_events)
    try:
        merged = federate_events([("writer", w.url), ("replica-0", r.url)])
    finally:
        w.close()
        r.close()
    assert merged["errors"] == {}
    kinds = [e["kind"] for e in merged["events"]]
    # incarnation orders before wall: the inc-1 caught-up sorts last
    assert kinds == ["writer-reshard", "stream-disconnect", "caught-up"]
    assert merged["events"][0]["member"] == "writer"
    win = window_from_events(
        merged["events"], ["stream-disconnect"], ["caught-up"],
        min_incarnation=0,
    )
    assert win is not None
    assert win["seconds"] == pytest.approx(2.5)
    assert win["end_event"]["incarnation"] == 1
    # no end edge -> None, never a bogus window
    assert window_from_events(
        merged["events"], ["stream-disconnect"], ["never-happens"]
    ) is None


def test_fleet_trace_stitch_one_trace_id_across_three_members():
    """Satellite: the stitched multi-member /fleet/trace export is
    Perfetto-loadable and cuts ONE trace id across router -> replica ->
    writer."""
    from pathway_tpu.observability.fleet import stitch_traces
    from pathway_tpu.observability.tracing import validate_chrome_trace

    tid = "aa" * 16
    other = "bb" * 16

    def doc(name, ts, span_id, parent=None, trace=tid):
        args = {"trace_id": trace, "span_id": span_id}
        if parent:
            args["parent_id"] = parent
        return {
            "name": name, "ph": "X", "ts": ts, "dur": 100.0,
            "pid": 1, "tid": 1, "args": args,
        }

    router_doc = {"traceEvents": [
        {"name": "process_name", "ph": "M", "pid": 1, "tid": 0, "ts": 0,
         "args": {"name": "should-be-replaced"}},
        doc("router.request", 10.0, "r1"),
        doc("router.attempt", 20.0, "r2", parent="r1"),
        doc("unrelated", 5.0, "x1", trace=other),
    ]}
    replica_doc = {"traceEvents": [
        doc("replica.serve", 30.0, "p1", parent="r2"),
    ]}
    writer_doc = {"traceEvents": [
        doc("repl.publish", 40.0, "w1"),
        doc("noise", 1.0, "x2", trace=other),
    ]}
    m_rep = _FakeMember(trace=replica_doc)
    m_wr = _FakeMember(trace=writer_doc)
    try:
        stitched = stitch_traces(
            [("replica-0", m_rep.url), ("writer", m_wr.url)],
            trace_id=tid,
            local=("router", router_doc),
        )
    finally:
        m_rep.close()
        m_wr.close()
    assert validate_chrome_trace(stitched) == [], stitched
    evs = stitched["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    spans = [e for e in evs if e["ph"] == "X"]
    # one process_name per member, distinct pids
    assert {m["args"]["name"] for m in meta} == {
        "router", "replica-0", "writer",
    }
    assert len({m["pid"] for m in meta}) == 3
    # the other trace id is cut away; the requested one survives whole
    assert {s["name"] for s in spans} == {
        "router.request", "router.attempt", "replica.serve", "repl.publish",
    }
    assert all(s["args"]["trace_id"] == tid for s in spans)
    # members' own metadata got replaced, not duplicated
    assert sum(m["args"]["name"] == "router" for m in meta) == 1
    # spans are ts-ordered after the metadata block
    ts = [s["ts"] for s in spans]
    assert ts == sorted(ts)


# ---------------------------------------------------------------------------
# the real plane: writer -> 3 replicas -> router serving /fleet/*


def _corpus_responder(server, values):
    return {"keys": sorted(int(k) for k in server.index.d)}


class _ToyIndex:
    def __init__(self):
        self.d = {}

    def keys(self):
        return list(self.d)

    def upsert(self, key, data, meta):
        self.d[int(key)] = data

    def remove(self, key):
        self.d.pop(int(key), None)

    def search(self, triples):
        return [
            tuple((k, 1.0) for k in sorted(self.d)[: int(kk)])
            for _q, kk, _f in triples
        ]


def test_three_member_plane_fleet_endpoints_live():
    """Acceptance bar: /fleet/metrics scraped from a live 3-member
    plane passes validate_exposition; /fleet/events carries the
    hydration story; /fleet/trace passes the Chrome-trace validator;
    each member's own /metrics body is contract-clean too."""
    from pathway_tpu.engine.batch import DiffBatch
    from pathway_tpu.observability import validate_exposition
    from pathway_tpu.observability.exposition import parse_exposition
    from pathway_tpu.observability.tracing import validate_chrome_trace
    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving.replica import ReplicaServer
    from pathway_tpu.serving.router import FailoverRouter

    srv = DeltaStreamServer(0)
    reps = [
        ReplicaServer(
            replica_id=i,
            index_factory=_ToyIndex,
            writer_port=srv.port,
            responder=_corpus_responder,
        ).start()
        for i in range(3)
    ]
    router = None
    try:
        srv.publish(
            0,
            [DiffBatch.from_rows(
                [(k, 1, (f"v{k}", None)) for k in range(5)],
                ("_data", "_meta"),
            )],
        )
        assert _wait(lambda: all(r.ready for r in reps), timeout=20)
        router = FailoverRouter(
            replicas=[f"http://127.0.0.1:{r.http_port}" for r in reps],
            health_interval_ms=100,
        ).start()
        assert _wait(
            lambda: all(ep.ready for ep in router.endpoints), timeout=10
        )
        # satellite: each member's own exposition passes the validator
        for r in reps:
            body = _get(f"http://127.0.0.1:{r.http_port}/metrics")
            assert validate_exposition(body) == [], body[:2000]
        # the federated view passes too, member-labeled
        text = _get(f"http://127.0.0.1:{router.port}/fleet/metrics")
        assert validate_exposition(text) == [], text[:2000]
        families, perrs = parse_exposition(text)
        assert perrs == []
        up = {
            s.labels["member"]: s.value
            for s in families["pathway_fleet_member_up"].samples
        }
        assert up == {
            "router": 1.0,
            "replica0": 1.0, "replica1": 1.0, "replica2": 1.0,
        }
        stale = families["pathway_replica_staleness_seconds"]
        assert {s.labels["member"] for s in stale.samples} >= {
            "replica0", "replica1", "replica2",
        }
        # the merged incident timeline tells the hydration story
        merged = json.loads(
            _get(f"http://127.0.0.1:{router.port}/fleet/events")
        )
        kinds = {e["kind"] for e in merged["events"]}
        assert "caught-up" in kinds  # every replica's bootstrap edge
        assert all("member" in e for e in merged["events"])
        # the stitched trace is Perfetto-loadable
        doc = json.loads(
            _get(f"http://127.0.0.1:{router.port}/fleet/trace")
        )
        assert validate_chrome_trace(doc) == []
        # router's own journal rides /debug/events
        own = json.loads(
            _get(f"http://127.0.0.1:{router.port}/debug/events")
        )
        assert isinstance(own["events"], list)
    finally:
        if router is not None:
            router.stop()
        for r in reps:
            r.stop()
        srv.close()


def test_router_eject_journals_and_swap_bounds_gauge_cardinality():
    from pathway_tpu.observability import REGISTRY
    from pathway_tpu.observability.journal import journal
    from pathway_tpu.serving.router import FailoverRouter

    router = FailoverRouter(
        shards=[["http://127.0.0.1:1"], ["http://127.0.0.1:2"]]
    )
    gauge = REGISTRY.get("pathway_router_replica_inflight")

    def names():
        with gauge._lock:
            return {k[0] for k in gauge._children}

    assert {"s0.replica0", "s1.replica0"} <= names()
    # swap down to one shard: the retired series is REMOVED, not zeroed
    router.swap_shard_map([["http://127.0.0.1:1"]])
    assert "s1.replica0" not in names()
    assert "s0.replica0" in names()
    assert router._gauge_names == {"s0.replica0"}
    # repeated churn does not grow the label space
    for port in (3, 4, 5):
        router.swap_shard_map([[f"http://127.0.0.1:{port}"]])
    assert names() & {"s0.replica0"} == {"s0.replica0"}
    assert len(router._gauge_names) == 1
    # the swap journaled the topology change (the reshard window's
    # router-side edge)
    swaps = journal().events(kinds=["shard-swap"])
    assert swaps and swaps[-1]["data"]["n_shards"] == 1
    ep = router.endpoints[0]
    router._eject(ep, "liveness: test")
    ej = journal().events(kinds=["router-eject"])
    assert ej and ej[-1]["data"]["replica"] == ep.name
    router._readmit(ep)
    assert journal().events(kinds=["router-readmit"])


# ---------------------------------------------------------------------------
# monitoring server surfaces + supervisor-side federation


def test_monitoring_server_signals_events_and_fleet(monkeypatch):
    from pathway_tpu.internals.monitoring_server import start_http_server
    from pathway_tpu.observability import validate_exposition
    from pathway_tpu.observability.exposition import parse_exposition
    from pathway_tpu.observability.journal import record
    from pathway_tpu.observability.tracing import validate_chrome_trace

    monkeypatch.setenv("PATHWAY_SIGNALS_INTERVAL_MS", "50")
    peer = _FakeMember(
        metrics="pathway_peer_thing_total 3\n",
        events=[{"seq": 1, "kind": "standby-takeover", "wall": 1.0,
                 "incarnation": 1}],
    )
    server = start_http_server(None, port=0)
    port = server.server_address[1]
    try:
        monkeypatch.setenv(
            "PATHWAY_FLEET_MEMBERS",
            f"peer={peer.url},self=http://127.0.0.1:{port}",
        )
        record("group-start", "incarnation 0")
        # /debug/signals: the sampler armed by start_http_server fills
        assert _wait(
            lambda: json.loads(
                _get(f"http://127.0.0.1:{port}/debug/signals")
            ).get("samples", 0) > 1,
            timeout=10,
        )
        snap = json.loads(
            _get(f"http://127.0.0.1:{port}/debug/signals?series=2")
        )
        assert snap["enabled"] is True
        assert "tick_ms" in snap["signals"]
        # /debug/events with kind filter
        evs = json.loads(
            _get(f"http://127.0.0.1:{port}/debug/events?kind=group-start")
        )
        assert [e["kind"] for e in evs["events"]] == ["group-start"]
        # /fleet/metrics: peer + local merged, self-entry skipped
        text = _get(f"http://127.0.0.1:{port}/fleet/metrics")
        assert validate_exposition(text) == [], text[:2000]
        families, _ = parse_exposition(text)
        assert "pathway_peer_thing_total" in families
        members = {
            s.labels["member"]
            for s in families["pathway_fleet_member_up"].samples
        }
        assert "peer" in members and "self" not in members
        # /fleet/events merges the peer's takeover with our own journal
        merged = json.loads(_get(f"http://127.0.0.1:{port}/fleet/events"))
        kinds = {e["kind"] for e in merged["events"]}
        assert {"standby-takeover", "group-start"} <= kinds
        # /fleet/trace is validator-clean
        doc = json.loads(_get(f"http://127.0.0.1:{port}/fleet/trace"))
        assert validate_chrome_trace(doc) == []
    finally:
        server.shutdown()
        peer.close()


def test_ephemeral_monitoring_servers_are_distinct(monkeypatch):
    """A requested port of 0 means a FRESH server every time — fleet
    drivers start several members in one process, and handing the first
    server back to the second caller silently collapses the fleet into
    one member (its peers self-exclude and vanish from /fleet/*)."""
    import pathway_tpu.internals.monitoring_server as ms

    a = ms.start_http_server(None, port=0)
    b = ms.start_http_server(None, port=0)
    try:
        assert a is not b
        assert a.server_address[1] != b.server_address[1]
        # both stay visible to the doctor's armed check, under their
        # BOUND ports (canonical reuse stays keyed by requested port)
        with ms._servers_lock:
            registered = set(ms._servers.values())
        assert {a, b} <= registered
    finally:
        b.shutdown()
        a.shutdown()
    with ms._servers_lock:
        assert a not in ms._servers.values()
        assert b not in ms._servers.values()


def test_supervisor_stamps_fleet_members_into_rank_env():
    from pathway_tpu.parallel.supervisor import GroupSupervisor

    sup = GroupSupervisor(
        ["python", "-c", "import os; print(os.environ['PATHWAY_FLEET_MEMBERS'])"],
        n=2,
        max_restarts=0,
    )
    rc = sup.run()
    assert rc == 0
    # the journal mirrors the supervisor's lifecycle events
    from pathway_tpu.observability.journal import journal

    kinds = [e["kind"] for e in journal().events()]
    assert "group-start" in kinds and "group-done" in kinds


# ---------------------------------------------------------------------------
# doctor rule: observability-coverage


def test_doctor_observability_coverage(monkeypatch):
    from pathway_tpu.analysis import Severity, run_doctor
    from pathway_tpu.internals import monitoring_server as ms
    from pathway_tpu.observability.signals import arm_sampler, reset_sampler
    from pathway_tpu.observability.tracing import get_tracer

    monkeypatch.setenv(
        "PATHWAY_SERVING_REPLICAS", "http://127.0.0.1:9101"
    )
    monkeypatch.setattr(ms, "_servers", {})
    found = run_doctor().by_rule("observability-coverage")
    warn = [d for d in found if d.severity == Severity.WARNING]
    assert warn, "unmonitored replicated plane must warn"
    assert "monitoring" in warn[0].message
    # arming a server clears the no-monitoring warning
    monkeypatch.setattr(
        ms, "_servers", {("127.0.0.1", 1): object()}
    )
    found = run_doctor().by_rule("observability-coverage")
    assert not [
        d
        for d in found
        if d.severity == Severity.WARNING and "monitoring" in d.message
    ]
    # tracing off on a replicated plane: its own warning
    monkeypatch.setattr(get_tracer(), "enabled", False)
    found = run_doctor().by_rule("observability-coverage")
    assert [
        d
        for d in found
        if d.severity == Severity.WARNING and "tracing" in d.message.lower()
    ]
    monkeypatch.setattr(get_tracer(), "enabled", True)
    # sampler armed without SLO targets -> INFO; with a target -> clean
    monkeypatch.delenv("PATHWAY_SERVING_REPLICAS", raising=False)
    arm_sampler(start=False)
    found = run_doctor().by_rule("observability-coverage")
    assert [d for d in found if d.severity == Severity.INFO]
    monkeypatch.setenv("PATHWAY_SLO_SHED_RATE", "0.01")
    found = run_doctor().by_rule("observability-coverage")
    assert not [d for d in found if d.severity == Severity.INFO]
    reset_sampler()
