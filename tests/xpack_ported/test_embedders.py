"""Port of the reference xpack LLM test test_embedders.py (reference:
python/pathway/xpacks/llm/tests/test_embedders.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

from __future__ import annotations

import json
import os

import pytest

import pathway_tpu as pw
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm import embedders


@pytest.mark.skip(reason="fails on CI for lack of api keys")
def test_oai_vs_llm():
    if "OPENAI_API_KEY" not in os.environ:
        from common.shadows import fs

        api_key = json.loads(
            fs.open("vault://kv.v2:deployments@/legal_rag_demo").read()
        )["OPENAI_KEY"]
    else:
        api_key = os.environ["OPENAI_API_KEY"]
    embedder_llm = embedders.LiteLLMEmbedder(model="text-embedding-ada-002")
    t = pw.debug.table_from_markdown(
        """
    txt  | model
    Text | text-embedding-ada-002
    """
    )
    r1 = t.select(ret=embedder_llm(pw.this.txt, api_key=api_key))

    embedder_oai = embedders.OpenAIEmbedder(model=None, api_key=api_key)
    r2 = t.select(ret=embedder_oai(pw.this.txt, model=pw.this.model))

    assert_table_equality(r1, r2)
