"""Port of the reference xpack LLM test test_metadata.py (reference:
python/pathway/xpacks/llm/tests/test_metadata.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

import pandas as pd
import pytest

import pathway_tpu as pw
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm.utils import combine_metadata


@pytest.mark.parametrize(
    "clean_from_column",
    [True, False],
)
def test_combine_metadata(clean_from_column):
    data = {"text": [("Text", {"tag": "test"})], "metadata": [{"meta": "data"}]}
    expected = {
        "text": ["Text"] if clean_from_column else [("Text", {"tag": "test"})],
        "metadata": [{"meta": "data", "tag": "test"}],
    }

    df = pd.DataFrame(data)
    table = pw.debug.table_from_pandas(df)

    df_expected = pd.DataFrame(expected)
    table_expected = pw.debug.table_from_pandas(df_expected)

    table = combine_metadata(
        table,
        from_column="text",
        to_column="metadata",
        clean_from_column=clean_from_column,
    )
    assert_table_equality(table, table_expected)


@pytest.mark.parametrize(
    "clean_from_column",
    [True, False],
)
def test_combine_metadata_no_to_column(clean_from_column):
    data = {"text": [("Text", {"tag": "test"})]}
    expected = {
        "text": ["Text"] if clean_from_column else [("Text", {"tag": "test"})],
        "metadata": [{"tag": "test"}],
    }

    df = pd.DataFrame(data)
    table = pw.debug.table_from_pandas(df)

    df_expected = pd.DataFrame(expected)
    table_expected = pw.debug.table_from_pandas(df_expected)

    table = combine_metadata(
        table,
        from_column="text",
        to_column="metadata",
        clean_from_column=clean_from_column,
    )
    assert_table_equality(table, table_expected)


@pytest.mark.parametrize(
    "clean_from_column",
    [True, False],
)
def test_combine_metadata_no_metadata(clean_from_column):

    data = {"text": ["Text"]}
    expected = {"text": ["Text"], "metadata": [{}]}

    df = pd.DataFrame(data)
    table = pw.debug.table_from_pandas(df)

    df_expected = pd.DataFrame(expected)
    table_expected = pw.debug.table_from_pandas(df_expected)

    table = combine_metadata(
        table,
        from_column="text",
        to_column="metadata",
        clean_from_column=clean_from_column,
    )
    assert_table_equality(table, table_expected)
