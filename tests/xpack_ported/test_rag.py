"""Port of the reference xpack LLM test test_rag.py (reference:
python/pathway/xpacks/llm/tests/test_rag.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm import llms
from pathway_tpu.xpacks.llm._utils import _unwrap_udf
from pathway_tpu.xpacks.llm.question_answering import BaseRAGQuestionAnswerer
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

from tests.xpack_ported.mocks import IdentityMockChat
from tests.xpack_ported.utils import build_vector_store, create_rag_app


@pw.udf
def fake_embeddings_model(x: str) -> list[float]:
    return [
        1.0 if x == "foo" else 0.0,
        1.0 if x in ("foo", "bar") else 0.0,
        1.0,
    ]


@pw.udf
def identity_chat_model(x: list[dict[str, pw.Json]], model: str) -> str:
    return model + "," + x[0]["content"].as_str()


@pw.udf
def _prompt_template(query: str, context: str) -> str:
    return context


@pw.udf
def _summarize_template(docs: list[str]) -> str:
    return f"summarize,{','.join(docs)}"


def test_base_rag():
    schema = pw.schema_from_types(data=bytes, _metadata=dict)
    input = pw.debug.table_from_rows(
        schema=schema, rows=[("foo", {}), ("bar", {}), ("baz", {})]
    )

    vector_server = VectorStoreServer(
        input,
        embedder=fake_embeddings_model,
    )

    rag = BaseRAGQuestionAnswerer(
        IdentityMockChat(),
        vector_server,
        prompt_template=_prompt_template,
        summarize_template=_summarize_template,
        search_topk=1,
    )

    answer_queries = pw.debug.table_from_rows(
        schema=rag.AnswerQuerySchema,
        rows=[
            ("foo", None, "gpt3.5", False),
        ],
    )

    answer_output = rag.answer_query(answer_queries)

    casted_table = answer_output.select(
        result=pw.apply_with_type(lambda x: x.value, str, pw.this.result["response"])
    )

    assert_table_equality(
        casted_table,
        pw.debug.table_from_markdown(
            """
            result
            gpt3.5,foo
            """
        ),
    )

    summarize_query = pw.debug.table_from_rows(
        schema=rag.SummarizeQuerySchema,
        rows=[(["foo", "bar"], "gpt2")],
    )

    summarize_outputs = rag.summarize_query(summarize_query)

    assert_table_equality(
        summarize_outputs.select(result=pw.this.result),
        pw.debug.table_from_markdown(
            """
            result
            gpt2,summarize,foo,bar
            """
        ),
    )


def test_rag_app_set_prompt():
    prompt_template = "Answer the question. Context: {context}\nQuestion: {query}"

    rag_app = create_rag_app(prompt_template=prompt_template)

    assert isinstance(rag_app.prompt_udf, pw.UDF)

    assert _unwrap_udf(rag_app.prompt_udf)(query=" ", context=" ")


def test_rag_app_set_callable_prompt():
    def prompt_template(query: str, context: str) -> str:
        return f"Q: {query}, C: {context}"

    rag_app = create_rag_app(prompt_template=prompt_template)

    assert isinstance(rag_app.prompt_udf, pw.UDF)

    assert _unwrap_udf(rag_app.prompt_udf)(query=" ", context=" ")


def test_rag_app_set_udf_prompt():
    @pw.udf
    def prompt_template(query: str, context: str) -> str:
        return f"Q: {query}, C: {context}"

    rag_app = create_rag_app(prompt_template=prompt_template)

    assert isinstance(rag_app.prompt_udf, pw.UDF)

    assert _unwrap_udf(rag_app.prompt_udf)(query=" ", context=" ")


@pytest.mark.parametrize(
    "prompt",
    [
        "Context: {context}, query: {query}, abc: {abc}",
        "Context: {something}, query: {else}",
        "Context: {context}",
        "No placeholder template.",
    ],
)
def test_invalid_prompt_template_raises_error(prompt: str):
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    class FakeChatModel(llms.BaseChat):
        async def __wrapped__(self, *args, **kwargs) -> str:
            return "Text"

        def _accepts_call_arg(self, arg_name: str) -> bool:
            return True

    chat = FakeChatModel()

    vector_server = build_vector_store(fake_embeddings_model)

    with pytest.raises(ValueError) as exc_info:
        BaseRAGQuestionAnswerer(
            llm=chat,
            indexer=vector_server,
            prompt_template=prompt,
        )

    err_msg = str(exc_info.value)

    assert "context" in err_msg
    assert "query" in err_msg
