"""Port of the reference xpack LLM test test_document_store.py (reference:
python/pathway/xpacks/llm/tests/test_document_store.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

from __future__ import annotations

import asyncio
import pathlib

import pytest

import pathway_tpu as pw
from pathway_tpu.stdlib.indexing import BruteForceKnnMetricKind
from pathway_tpu.stdlib.indexing import (
    BruteForceKnnFactory,
    HybridIndexFactory,
    LshKnnFactory,
    TantivyBM25Factory,
    UsearchKnnFactory,
)
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm.document_store import DocumentStore
from pathway_tpu.xpacks.llm.servers import DocumentStoreServer


class DebugStatsInputSchema(DocumentStore.StatisticsQuerySchema):
    debug: str | None = pw.column_definition(default_value=None)


def _test_vs(fake_embeddings_model):
    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )
    index_factory = BruteForceKnnFactory(
        dimensions=3,
        reserved_space=10,
        embedder=fake_embeddings_model,
        metric=BruteForceKnnMetricKind.COS,
    )

    vector_server = DocumentStore(docs, retriever_factory=index_factory)

    info_queries = pw.debug.table_from_rows(
        schema=DebugStatsInputSchema,
        rows=[
            (None,),
        ],
    ).select()

    info_outputs = vector_server.statistics_query(info_queries)
    assert_table_equality(
        info_outputs.select(result=pw.unwrap(pw.this.result["file_count"].as_int())),
        pw.debug.table_from_markdown(
            """
            result
            1
            """
        ),
    )

    input_queries = pw.debug.table_from_rows(
        schema=DocumentStore.InputsQuerySchema,
        rows=[
            (None, "**/*.py"),
        ],
    )

    input_outputs = vector_server.inputs_query(input_queries)

    @pw.udf
    def get_file_name(result_js) -> str:
        if len(result_js):
            return result_js[0]["path"].value.split("/")[-1].replace('"', "")
        else:
            return str(result_js)

    assert_table_equality(
        input_outputs.select(result=pw.unwrap(get_file_name(pw.this.result))),
        pw.debug.table_from_markdown(
            """
            result
            test_vector_store.py
            """
        ),
    )

    _, rows = pw.debug.table_to_dicts(input_outputs)
    (val,) = rows["result"].values()
    val = val[0]  # type: ignore

    assert isinstance(val, pw.Json)
    input_result = val.value
    assert isinstance(input_result, dict)

    assert "path" in input_result.keys()

    # parse_graph.G.clear()
    retrieve_queries = pw.debug.table_from_markdown(
        """
        query | k | metadata_filter | filepath_globpattern
        "Foo" | 1 |                 |
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.value  # type: ignore # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["dist"] < 1.0e-6  # type: ignore # the dist is not 0 due to float normalization
    assert query_result["text"]  # just check if some text was returned


def test_sync_embedder():
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    _test_vs(fake_embeddings_model)


def test_async_embedder():
    @pw.udf
    async def fake_embeddings_model(x: str) -> list[float]:
        asyncio.sleep
        return [1.0, 1.0, 0.0]

    _test_vs(fake_embeddings_model)


@pytest.mark.parametrize(
    "glob_filter",
    [
        "",
        "**/*.py",
        "pathway/xpacks/llm/tests/test_vector_store.py",
    ],
)
@pytest.mark.parametrize(
    "index_cls",
    [
        BruteForceKnnFactory,
        UsearchKnnFactory,
        TantivyBM25Factory,
        LshKnnFactory,
    ],
)
def test_vectorstore_glob_filtering(glob_filter, index_cls):
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    if index_cls == TantivyBM25Factory:
        index_factory = index_cls()
    else:
        index_factory = index_cls(
            dimensions=3,
            embedder=fake_embeddings_model,
        )

    vector_server = DocumentStore(docs, retriever_factory=index_factory)

    retrieve_queries = pw.debug.table_from_markdown(
        f"""
        query  | k | metadata_filter | filepath_globpattern
        "test" | 1 |                 | {glob_filter}
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["dist"] < 1.0e-6  # type: ignore # the dist is not 0 due to float normalization
    assert query_result["text"]  # just check if some text was returned


@pytest.mark.parametrize(
    "glob_filter",
    [
        "**/abc.py",
    ],
)
@pytest.mark.parametrize(
    "index_cls",
    [
        TantivyBM25Factory,
    ],
)
def test_vectorstore_tantivy_negative_glob_filtering(glob_filter, index_cls):
    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    index_factory = index_cls()

    doc_store = DocumentStore(docs, retriever_factory=index_factory)

    retrieve_queries = pw.debug.table_from_markdown(
        f"""
        query  | k | metadata_filter | filepath_globpattern
        "test" | 1 |                 | {glob_filter}
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )

    retrieve_outputs = doc_store.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    assert len(val.as_list()) == 0


@pytest.mark.parametrize(
    "glob_filter",
    [
        "",
        "**/*.py",
        "pathway/xpacks/llm/tests/test_vector_store.py",
    ],
)
@pytest.mark.parametrize(
    "index_cls1",
    [
        BruteForceKnnFactory,
        UsearchKnnFactory,
        TantivyBM25Factory,
        LshKnnFactory,
    ],
)
@pytest.mark.parametrize(
    "index_cls2",
    [
        UsearchKnnFactory,
        TantivyBM25Factory,
    ],
)
def test_hybrid_docstore_glob_filtering(glob_filter, index_cls1, index_cls2):
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    vector_index_construct_args = dict(embedder=fake_embeddings_model)

    index1_args = {}
    index2_args = {}

    if index_cls1 != TantivyBM25Factory:
        index1_args = vector_index_construct_args

    if index_cls2 != TantivyBM25Factory:
        index2_args = vector_index_construct_args

    index1 = index_cls1(**index1_args)
    index2 = index_cls2(**index2_args)

    index_factory = HybridIndexFactory(retriever_factories=[index1, index2])

    vector_server = DocumentStore(docs, retriever_factory=index_factory)

    retrieve_queries = pw.debug.table_from_markdown(
        f"""
        query  | k | metadata_filter | filepath_globpattern
        "test" | 1 |                 | {glob_filter}
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["dist"] < 1.0e-6  # type: ignore
    assert query_result["text"]  # just check if some text was returned


@pytest.mark.parametrize(
    "glob_filter",
    [
        "**/*xyz.py",
        "pathway/xpacks/llm/tests/abc.py",
    ],
)
@pytest.mark.parametrize(
    "index_cls1",
    [
        BruteForceKnnFactory,
        UsearchKnnFactory,
        TantivyBM25Factory,
        LshKnnFactory,
    ],
)
@pytest.mark.parametrize(
    "index_cls2",
    [
        UsearchKnnFactory,
        TantivyBM25Factory,
    ],
)
def test_hybrid_docstore_glob_filtering_negative(glob_filter, index_cls1, index_cls2):
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    vector_index_construct_args = dict(embedder=fake_embeddings_model)

    index1_args = {}
    index2_args = {}

    if index_cls1 != TantivyBM25Factory:
        index1_args = vector_index_construct_args

    if index_cls2 != TantivyBM25Factory:
        index2_args = vector_index_construct_args

    index1 = index_cls1(**index1_args)
    index2 = index_cls2(**index2_args)

    index_factory = HybridIndexFactory(retriever_factories=[index1, index2])

    vector_server = DocumentStore(docs, retriever_factory=index_factory)

    retrieve_queries = pw.debug.table_from_markdown(
        f"""
        query  | k | metadata_filter | filepath_globpattern
        "test" | 1 |                 | {glob_filter}
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)

    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    assert len(val.as_list()) == 0


@pytest.mark.parametrize(
    "glob_filter",
    [
        "somefile.pdf",
        "**/*.txt",
        "pathway/test_vector_store.py",
        "src.py",
        "`pathway/xpacks/llm/tests/test_vector_store.py`",
    ],
)
def test_vs_filtering_negatives(glob_filter):
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    index_factory = BruteForceKnnFactory(
        dimensions=3,
        reserved_space=10,
        embedder=fake_embeddings_model,
        metric=BruteForceKnnMetricKind.COS,
    )

    vector_server = DocumentStore(docs, retriever_factory=index_factory)

    # parse_graph.G.clear()
    retrieve_queries = pw.debug.table_from_markdown(
        f"""
        query | k | metadata_filter | filepath_globpattern
        "Foo" | 1 |                 | {glob_filter}
        """,
        schema=DocumentStore.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)

    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    assert len(val.as_list()) == 0


@pytest.mark.parametrize(
    "metadata_filter",
    [
        "",
        "contains(path, `test_vector_store`)",
        'contains(path, `"test_vector_store"`)',
        "contains(path, `pathway/xpacks/llm/tests/test_vector_store.py`)",
        "path == `pathway/xpacks/llm/tests/test_vector_store.py`",
        "globmatch(`pathway/xpacks/llm/tests/test_vector_store.py`, path)",
    ],
)
def test_vs_filtering_metadata(metadata_filter):
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    index_factory = BruteForceKnnFactory(
        dimensions=3,
        reserved_space=10,
        embedder=fake_embeddings_model,
        metric=BruteForceKnnMetricKind.COS,
    )

    vector_server = DocumentStore(docs, retriever_factory=index_factory)

    retrieve_queries = pw.debug.table_from_rows(
        schema=DocumentStore.RetrieveQuerySchema,
        rows=[("Foo", 1, metadata_filter, None)],
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["dist"] < 1.0e-6  # type: ignore # the dist is not 0 due to float normalization
    assert query_result["text"]  # just check if some text was returned


@pytest.mark.parametrize(
    "metadata_filter",
    [
        "",
        "contains(path, `Document Enregistrement Universel 2023 publié à l'XYZ le 28 février 2024.pdf`)",
        "path == `Document Enregistrement Universel 2023 publié à l'XYZ le 28 février 2024.pdf`",
        'path == "`Document Enregistrement Universel 2023 publié à l\'XYZ le 28 février 2024.pdf"`',
        "contains(path, `Document Enregistrement`)",
    ],
)
@pytest.mark.parametrize("globbing_filter", [None, "*.pdf"])
def test_vs_filtering_edge_cases(metadata_filter, globbing_filter):
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {
                    "path": "Document Enregistrement Universel 2023 publié à l'XYZ le 28 février 2024.pdf"
                },
            )
        ],
    )

    index_factory = BruteForceKnnFactory(
        dimensions=3,
        reserved_space=10,
        embedder=fake_embeddings_model,
        metric=BruteForceKnnMetricKind.COS,
    )

    vector_server = DocumentStore(docs, retriever_factory=index_factory)

    retrieve_queries = pw.debug.table_from_rows(
        schema=DocumentStore.RetrieveQuerySchema,
        rows=[("Foo", 1, metadata_filter, globbing_filter)],
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["text"]  # just check if some text was returned


@pytest.mark.parametrize(
    "cache_strategy_cls",
    [
        None,
        pw.udfs.InMemoryCache,
        pw.udfs.DiskCache,
    ],
)
def test_docstore_server_hybridindex_builds(cache_strategy_cls, tmp_path: pathlib.Path):
    if cache_strategy_cls is not None:
        cache_strategy = cache_strategy_cls()
    else:
        cache_strategy = None

    persistent_storage_path = tmp_path / "PStorage"
    persistence_config = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(persistent_storage_path),
    )

    @pw.udf(cache_strategy=cache_strategy)
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )
    vector_index = UsearchKnnFactory(
        embedder=fake_embeddings_model, reserved_space=40, dimensions=3
    )
    bm25 = TantivyBM25Factory()

    hybrid_index = HybridIndexFactory([vector_index, bm25])

    document_store = DocumentStore(docs, retriever_factory=hybrid_index)

    DocumentStoreServer(host="0.0.0.0", port=8000, document_store=document_store)
    # server is not run, so host/port don't matter
    # it is just used to check if it is created correctly

    retrieve_queries = pw.debug.table_from_rows(
        schema=DocumentStore.RetrieveQuerySchema,
        rows=[("Foo", 1, None, None)],
    )

    retrieve_outputs = document_store.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(
        retrieve_outputs, persistence_config=persistence_config
    )
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["text"]  # just check if some text was returned


def test_docstore_on_table_without_metadata():
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes),
        rows=[("test".encode("utf-8"),)],
    )

    index_factory = BruteForceKnnFactory(
        dimensions=3,
        reserved_space=10,
        embedder=fake_embeddings_model,
        metric=BruteForceKnnMetricKind.COS,
    )

    document_store = DocumentStore(docs, retriever_factory=index_factory)

    retrieve_queries = pw.debug.table_from_rows(
        schema=DocumentStore.RetrieveQuerySchema,
        rows=[("Foo", 1, None, None)],
    )

    retrieve_outputs = document_store.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["text"] == "test"  # just check if some text was returned


def test_docstore_on_tables_with_different_schemas():
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs1 = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes),
        rows=[("test".encode("utf-8"),)],
    )

    docs2 = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict, val=int),
        rows=[("test2".encode("utf-8"), {}, 1)],
    )

    index_factory = BruteForceKnnFactory(
        dimensions=3,
        reserved_space=10,
        embedder=fake_embeddings_model,
        metric=BruteForceKnnMetricKind.COS,
    )

    document_store = DocumentStore([docs1, docs2], retriever_factory=index_factory)

    retrieve_queries = pw.debug.table_from_rows(
        schema=DocumentStore.RetrieveQuerySchema,
        rows=[("Foo", 2, None, None)],
    )

    retrieve_outputs = document_store.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    assert len(val.as_list()) == 2
