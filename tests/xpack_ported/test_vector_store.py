"""Port of the reference xpack LLM test test_vector_store.py (reference:
python/pathway/xpacks/llm/tests/test_vector_store.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

from __future__ import annotations

import asyncio
import pathlib

import pytest

import pathway_tpu as pw
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm import parsers
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer


class DebugStatsInputSchema(VectorStoreServer.StatisticsQuerySchema):
    debug: str | None = pw.column_definition(default_value=None)


def _test_vs(fake_embeddings_model, **run_kwargs):
    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    vector_server = VectorStoreServer(
        docs,
        embedder=fake_embeddings_model,
    )

    info_queries = pw.debug.table_from_rows(
        schema=DebugStatsInputSchema,
        rows=[
            (None,),
        ],
    ).select()

    info_outputs = vector_server.statistics_query(info_queries)
    assert_table_equality(
        info_outputs.select(result=pw.unwrap(pw.this.result["file_count"].as_int())),
        pw.debug.table_from_markdown(
            """
            result
            1
            """
        ),
        **run_kwargs,
    )

    input_queries = pw.debug.table_from_rows(
        schema=VectorStoreServer.InputsQuerySchema,
        rows=[
            (None, "**/*.py"),
        ],
    )

    input_outputs = vector_server.inputs_query(input_queries)

    @pw.udf
    def get_file_name(result_js) -> str:
        if len(result_js):
            return result_js[0]["path"].value.split("/")[-1].replace('"', "")
        else:
            return str(result_js)

    assert_table_equality(
        input_outputs.select(result=pw.unwrap(get_file_name(pw.this.result))),
        pw.debug.table_from_markdown(
            """
            result
            test_vector_store.py
            """
        ),
        **run_kwargs,
    )

    _, rows = pw.debug.table_to_dicts(input_outputs, **run_kwargs)
    (val,) = rows["result"].values()
    val = val[0]  # type: ignore

    assert isinstance(val, pw.Json)
    input_result = val.value
    assert isinstance(input_result, dict)

    assert "path" in input_result.keys()

    # parse_graph.G.clear()
    retrieve_queries = pw.debug.table_from_markdown(
        """
        query | k | metadata_filter | filepath_globpattern
        "Foo" | 1 |                 |
        """,
        schema=VectorStoreServer.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs, **run_kwargs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.value  # type: ignore # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["dist"] < 1.0e-6  # type: ignore # the dist is not 0 due to float normalization
    assert query_result["text"]  # just check if some text was returned


def test_sync_embedder():
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    _test_vs(fake_embeddings_model)


def test_async_embedder():
    @pw.udf
    async def fake_embeddings_model(x: str) -> list[float]:
        await asyncio.sleep(0.001)
        return [1.0, 1.0, 0.0]

    _test_vs(fake_embeddings_model)


def test_embedder_preserves_params():
    call_count = 0

    @pw.udf(cache_strategy=pw.udfs.InMemoryCache())
    def fake_embeddings_model(x: str) -> list[float]:
        nonlocal call_count
        call_count += 1
        return [1.0, 1.0, 0.0]

    _test_vs(fake_embeddings_model)
    _test_vs(fake_embeddings_model)
    assert call_count == 4  # dimension x 2 (no cache used), doc, query


@pytest.mark.parametrize(
    "cache_strategy_cls",
    [
        None,
        pw.udfs.InMemoryCache,
        pw.udfs.DiskCache,
    ],
)
def test_embedder_cache_strategy(cache_strategy_cls, tmp_path: pathlib.Path):
    if cache_strategy_cls is not None:
        cache_strategy = cache_strategy_cls()
    else:
        cache_strategy = None

    persistent_storage_path = tmp_path / "PStorage"
    persistence_config = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(persistent_storage_path),
    )

    @pw.udf(cache_strategy=cache_strategy)
    async def fake_embeddings_model(x: str) -> list[float]:
        await asyncio.sleep(0.001)
        return [1.0, 1.0, 0.0]

    _test_vs(fake_embeddings_model, persistence_config=persistence_config)


def test_async_embedder_preserves_params():
    call_count = 0

    @pw.udf(cache_strategy=pw.udfs.InMemoryCache())
    async def fake_embeddings_model(x: str) -> list[float]:
        await asyncio.sleep(0.001)
        nonlocal call_count
        call_count += 1
        return [1.0, 1.0, 0.0]

    _test_vs(fake_embeddings_model)
    _test_vs(fake_embeddings_model)
    assert call_count == 4  # dimension x 2 (no cache used), doc, query


@pytest.mark.environment_changes  # unstructured parser adds env vars after first use
@pytest.mark.parametrize("parser_cls", [parsers.UnstructuredParser])
def test_vs_parsing(parser_cls):
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    vector_server = VectorStoreServer(
        docs,
        parser=parser_cls(),
        embedder=fake_embeddings_model,
    )

    retrieve_queries = pw.debug.table_from_markdown(
        """
        query | k | metadata_filter | filepath_globpattern
        "Foo" | 1 |                 |
        """,
        schema=VectorStoreServer.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["dist"] < 1.0e-6  # type: ignore # the dist is not 0 due to float normalization
    assert query_result["text"] == "test"


@pytest.mark.parametrize(
    "glob_filter",
    [
        "",
        "**/*.py",
        "pathway/xpacks/llm/tests/test_vector_store.py",
    ],
)
def test_vs_filtering(glob_filter):
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    vector_server = VectorStoreServer(
        docs,
        embedder=fake_embeddings_model,
    )

    # parse_graph.G.clear()
    retrieve_queries = pw.debug.table_from_markdown(
        f"""
        query | k | metadata_filter | filepath_globpattern
        "Foo" | 1 |                 | {glob_filter}
        """,
        schema=VectorStoreServer.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["dist"] < 1.0e-6  # type: ignore # the dist is not 0 due to float normalization
    assert query_result["text"]  # just check if some text was returned


@pytest.mark.parametrize(
    "glob_filter",
    [
        "somefile.pdf",
        "**/*.txt",
        "pathway/test_vector_store.py",
        "src.py",
        "`pathway/xpacks/llm/tests/test_vector_store.py`",
    ],
)
def test_vs_filtering_negatives(glob_filter):
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "pathway/xpacks/llm/tests/test_vector_store.py"},
            )
        ],
    )

    vector_server = VectorStoreServer(
        docs,
        embedder=fake_embeddings_model,
    )

    # parse_graph.G.clear()
    retrieve_queries = pw.debug.table_from_markdown(
        f"""
        query | k | metadata_filter | filepath_globpattern
        "Foo" | 1 |                 | {glob_filter}
        """,
        schema=VectorStoreServer.RetrieveQuerySchema,
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)

    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    assert len(val.as_list()) == 0


@pytest.mark.parametrize(
    "metadata_filter",
    [
        "",
        "contains(path, `test_vector_store`)",
        'contains(path, `"test_vector_store"`)',
        "contains(path, `pathway/xpacks/llm/tests/test_vector_store.py`)",
        "path == `pathway/xpacks/llm/tests/test_vector_store.py`",
        "globmatch(`pathway/xpacks/llm/tests/test_vector_store.py`, path)",
        "(path == `pathway/xpacks/llm/tests/test_vector_store.py`) && (published_date >= to_number(`1724351400`))",
    ],
)
def test_vs_filtering_metadata(metadata_filter):
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {
                    "path": "pathway/xpacks/llm/tests/test_vector_store.py",
                    "published_date": 1724351401,
                },
            )
        ],
    )

    vector_server = VectorStoreServer(
        docs,
        embedder=fake_embeddings_model,
    )

    retrieve_queries = pw.debug.table_from_rows(
        schema=VectorStoreServer.RetrieveQuerySchema,
        rows=[("Foo", 1, metadata_filter, None)],
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["dist"] < 1.0e-6  # type: ignore # the dist is not 0 due to float normalization
    assert query_result["text"]  # just check if some text was returned


@pytest.mark.parametrize(
    "metadata_filter",
    [
        "",
        "contains(path, `Document Enregistrement Universel 2023 publié à l'XYZ le 28 février 2024.pdf`)",
        "path == `Document Enregistrement Universel 2023 publié à l'XYZ le 28 février 2024.pdf`",
        'path == "`Document Enregistrement Universel 2023 publié à l\'XYZ le 28 février 2024.pdf"`',
        "contains(path, `Document Enregistrement`)",
    ],
)
@pytest.mark.parametrize("globbing_filter", [None, "*.pdf"])
def test_vs_filtering_edge_cases(metadata_filter, globbing_filter):
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {
                    "path": "Document Enregistrement Universel 2023 publié à l'XYZ le 28 février 2024.pdf"
                },
            )
        ],
    )

    vector_server = VectorStoreServer(
        docs,
        embedder=fake_embeddings_model,
    )

    retrieve_queries = pw.debug.table_from_rows(
        schema=VectorStoreServer.RetrieveQuerySchema,
        rows=[("Foo", 1, metadata_filter, globbing_filter)],
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["text"]  # just check if some text was returned


def test_docstore_on_table_without_metadata():
    @pw.udf
    def fake_embeddings_model(x: str) -> list[float]:
        return [1.0, 1.0, 0.0]

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes),
        rows=[("test".encode("utf-8"),)],
    )

    vector_server = VectorStoreServer(
        docs,
        embedder=fake_embeddings_model,
    )

    retrieve_queries = pw.debug.table_from_rows(
        schema=vector_server.RetrieveQuerySchema,
        rows=[("Foo", 1, None, None)],
    )

    retrieve_outputs = vector_server.retrieve_query(retrieve_queries)
    _, rows = pw.debug.table_to_dicts(retrieve_outputs)
    (val,) = rows["result"].values()
    assert isinstance(val, pw.Json)
    (query_result,) = val.as_list()  # extract the single match
    assert isinstance(query_result, dict)
    assert query_result["text"] == "test"  # just check if some text was returned
