"""Port of the reference xpack LLM test test_rerankers.py (reference:
python/pathway/xpacks/llm/tests/test_rerankers.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

import pytest

import pathway_tpu as pw
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm import llms
from pathway_tpu.xpacks.llm.rerankers import LLMReranker, rerank_topk_filter


def _test_llm_reranker(llm, expected):
    schema = pw.schema_from_types(query=str, doc=str)
    input = pw.debug.table_from_rows(schema=schema, rows=[("foo", "bar")])

    reranker = LLMReranker(llm)

    ranking = input.select(rank=reranker(input.doc, input.query))
    assert_table_equality(
        ranking,
        pw.debug.table_from_rows(pw.schema_from_types(rank=float), [(expected,)]),
    )


def _test_llm_reranker_raises(llm):
    schema = pw.schema_from_types(query=str, doc=str)
    input = pw.debug.table_from_rows(schema=schema, rows=[("foo", "bar")])

    reranker = LLMReranker(llm)

    ranking = input.select(rank=reranker(input.doc, input.query))
    with pytest.raises(ValueError):
        pw.debug._compute_tables(ranking)


def test_llm_reranker():
    class LLM1(llms.OpenAIChat):
        async def __wrapped__(self, *args, **kwargs) -> str:
            return '{"score": 1}'

    _test_llm_reranker(LLM1(), 1.0)

    class LLM2(llms.OpenAIChat):
        async def __wrapped__(self, *args, **kwargs) -> str:
            return '{"score": 5}'

    _test_llm_reranker(LLM2(), 5.0)

    class LLM3(llms.OpenAIChat):
        async def __wrapped__(self, *args, **kwargs) -> str:
            return "text"

    _test_llm_reranker_raises(LLM3())


def test_rerank_topk_filter():
    input_schema = pw.schema_from_types(docs=list[dict], scores=list[float])

    docs = [{"text": str(i)} for i in range(10)]

    input = pw.debug.table_from_rows(
        input_schema,
        [
            (
                docs,
                [1, 2.0, 5.5, -10.333, 2, 9.5, 5.555, 4.3, 2.8, 9.5],
            )
        ],
    )
    filtered = input.select(docs=rerank_topk_filter(pw.this.docs, pw.this.scores, 3))

    expected_docs = [pw.Json({"text": str(i)}) for i in [5, 9, 6]]

    assert_table_equality(
        filtered,
        pw.debug.table_from_rows(
            pw.schema_from_types(docs=tuple[list[dict], list[float]]),
            [((expected_docs, [9.5, 9.5, 5.555]),)],
        ),
    )
