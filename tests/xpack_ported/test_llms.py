"""Port of the reference xpack LLM test test_llms.py (reference:
python/pathway/xpacks/llm/tests/test_llms.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

from __future__ import annotations

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.internals.udfs import DiskCache, ExponentialBackoffRetryStrategy
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm import llms


def test_prompt_chat_single_qa():
    func = llms.prompt_chat_single_qa
    txt = "Pójdź, kińże tę chmurność w głąb flaszy 🍾."
    input_table = pw.debug.table_from_pandas(pd.DataFrame([dict(ret=txt)]))
    result = input_table.select(ret=pw.unwrap(func(pw.this.ret)[0]["content"].as_str()))

    assert_table_equality(result, input_table)


@pytest.mark.parametrize(
    "model",
    ["gpt-4", None],
)
@pytest.mark.parametrize(
    "retry_strategy",
    [ExponentialBackoffRetryStrategy(max_retries=6, backoff_factor=2.5), None],
)
@pytest.mark.parametrize(
    "cache_strategy",
    [DiskCache(), None],
)
def test_openai_chat_init(model, retry_strategy, cache_strategy):
    llm = llms.OpenAIChat(
        model=model, retry_strategy=retry_strategy, cache_strategy=cache_strategy
    )

    assert llm is not None
    assert llm.kwargs is not None
    assert llm.executor is not None

    if cache_strategy is None:
        assert llm.cache_strategy is None
    else:
        assert llm.cache_strategy is not None


@pytest.mark.parametrize("model", ["gpt-4", "gpt-4o", None])
def test_llm_model_field(model):
    llm = llms.OpenAIChat(model=model)

    if model is None:
        assert llm.model is None
    else:
        assert model == llm.model


def test_empty_init_kwargs():
    llm = llms.OpenAIChat(model=None)

    assert llm.kwargs == {}

    assert llm.model is None


@pytest.mark.parametrize(
    "kwargs",
    [{"base_url": "openai_api"}, {}],
)
def test_init_kwargs(kwargs):
    llm = llms.OpenAIChat(**kwargs)

    assert llm.kwargs.get("base_url", "not_set") == kwargs.get("base_url", "not_set")


VALID_ARGS = ["top_p", "temperature", "max_tokens"]
INVALID_ARGS = ["made_up_arg"]


@pytest.mark.parametrize("model", ["gpt-4", "gpt-4o", None])
@pytest.mark.parametrize("call_arg", [*VALID_ARGS, *INVALID_ARGS])
def test_openai_call_args(model, call_arg):
    llm = llms.OpenAIChat(model=model)

    if model is None:
        assert llm._accepts_call_arg(call_arg) is False
    else:
        assert llm._accepts_call_arg(call_arg) is (call_arg in VALID_ARGS)


@pytest.mark.parametrize(
    "model",
    [
        "claude-3-5-sonnet-20240620",
        "claude-3-opus-20240229",
        "antrophic/claude-3-5-sonnet-20240620",
        None,
    ],
)
@pytest.mark.parametrize("call_arg", [*VALID_ARGS, *INVALID_ARGS])
def test_antrophic_call_args(model, call_arg):
    llm = llms.LiteLLMChat(model=model)

    if model is None:
        assert llm._accepts_call_arg(call_arg) is False
    else:
        assert llm._accepts_call_arg(call_arg) is (call_arg in VALID_ARGS)


@pytest.mark.parametrize("model", ["cohere/command-r", "antrophic/claude-3-5-sonnet"])
@pytest.mark.parametrize("call_arg", ["stream_options", "response_format"])
def test_mixed_call_args(model, call_arg):
    # arguments that antrophic supports but the cohere does not

    llm = llms.LiteLLMChat(model=model)

    if model is None:
        assert llm._accepts_call_arg(call_arg) is False
    else:
        if llm.model == "command-r":
            assert llm._accepts_call_arg(call_arg) is False
        elif llm.model == "claude-3-5-sonnet":
            assert llm._accepts_call_arg(call_arg)
