"""Port of the reference xpack LLM test utils.py (reference:
python/pathway/xpacks/llm/tests/utils.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

import pathway_tpu as pw
from pathway_tpu.xpacks.llm.question_answering import BaseRAGQuestionAnswerer
from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

from tests.xpack_ported.mocks import FakeChatModel, fake_embeddings_model

DEFAULT_PATHWAY_HOST: str = "127.0.0.1"


def build_vector_store(embedder: pw.UDF | None = None) -> VectorStoreServer:
    """Build vector store instance from an optional embedder, with a single demo doc."""

    if embedder is None:
        embedder = fake_embeddings_model

    docs = pw.debug.table_from_rows(
        schema=pw.schema_from_types(data=bytes, _metadata=dict),
        rows=[
            (
                "test".encode("utf-8"),
                {"path": "test_module.py"},
            )
        ],
    )

    vector_server = VectorStoreServer(
        docs,
        embedder=embedder,
    )

    return vector_server


def create_rag_app(**kwargs) -> BaseRAGQuestionAnswerer:
    """Create RAG app with fake embedder and LLM."""
    chat = FakeChatModel()

    vector_server = build_vector_store(fake_embeddings_model)

    rag_app = BaseRAGQuestionAnswerer(
        llm=chat,
        indexer=vector_server,
        default_llm_name="gpt-4o-mini",
        **kwargs,
    )
    return rag_app


def create_build_rag_app(
    port: int, host: str = DEFAULT_PATHWAY_HOST, **kwargs
) -> BaseRAGQuestionAnswerer:
    """Create and build RAG app with fake embedder and LLM.
    Builds the server with optional host and the given port.

    Host and the port will not be occupied until the app is run.
    """

    rag_app = create_rag_app(**kwargs)

    rag_app.build_server(host=host, port=port)

    return rag_app
