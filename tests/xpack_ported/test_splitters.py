"""Port of the reference xpack LLM test test_splitters.py (reference:
python/pathway/xpacks/llm/tests/test_splitters.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

from __future__ import annotations

import pandas as pd

import pathway_tpu as pw
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm.splitters import NullSplitter, TokenCountSplitter


def test_null():
    splitter = NullSplitter()
    txt = "Pójdź, kińże tę chmurność w głąb flaszy 🍾."
    input_table = pw.debug.table_from_pandas(pd.DataFrame([dict(ret=txt)]))
    result = input_table.select(ret=splitter(pw.this.ret)[0][0])

    assert_table_equality(result, input_table)


def test_tokencount():
    splitter = TokenCountSplitter()
    txt = "Pójdź, kińże tę chmurność w głąb flaszy 🍾."
    input_table = pw.debug.table_from_pandas(pd.DataFrame([dict(ret=txt)]))
    result = input_table.select(ret=splitter(pw.this.ret)[0][0])

    assert_table_equality(result, input_table)
