"""Port of the reference xpack LLM test test_parsers.py (reference:
python/pathway/xpacks/llm/tests/test_parsers.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

from __future__ import annotations

import os
from pathlib import Path

import pytest

nltk = pytest.importorskip("nltk")  # reference test deps absent -> skip
import pandas as pd

FPDF = pytest.importorskip("fpdf").FPDF

import pathway_tpu as pw
from tests.ref_utils import assert_table_equality
from pathway_tpu.xpacks.llm.parsers import PypdfParser, UnstructuredParser, Utf8Parser

for _ in range(10):
    try:
        nltk.download("stopwords", force=True)
        nltk.download("wordnet", force=True)
        nltk.download("punkt", force=True)
        nltk.download("punkt_tab", force=True)
        nltk.download("averaged_perceptron_tagger", force=True)
        nltk.download("averaged_perceptron_tagger_eng", force=True)
    except Exception:
        pass
    else:
        break


def test_utf8parser():
    parser = Utf8Parser()
    txt = "Pójdź, kińże tę chmurność w głąb flaszy 🍾."
    input_df = pd.DataFrame([dict(raw=txt.encode("utf8"))])

    class schema(pw.Schema):
        raw: bytes

    input_table = pw.debug.table_from_pandas(input_df, schema=schema)
    result = input_table.select(ret=parser(pw.this.raw)[0][0])

    assert_table_equality(
        result, pw.debug.table_from_pandas(pd.DataFrame([dict(ret=txt)]))
    )


@pytest.mark.environment_changes
def test_parse_unstructured(monkeypatch):
    parser = UnstructuredParser()
    txt = "Pójdź, kińże tę chmurność w głąb flaszy 🍾."
    input_df = pd.DataFrame([dict(raw=txt.encode("utf8"))])

    class schema(pw.Schema):
        raw: bytes

    input_table = pw.debug.table_from_pandas(input_df, schema=schema)
    result = input_table.select(ret=parser(pw.this.raw)[0][0])

    assert_table_equality(
        result, pw.debug.table_from_pandas(pd.DataFrame([dict(ret=txt)]))
    )


@pytest.mark.environment_changes
@pytest.mark.asyncio
def test_parse_unstructured_unk_exception(monkeypatch):
    parser = UnstructuredParser()

    binary_data = b"NONEXISTING_FMT" + os.urandom(2048)

    input_df = pd.DataFrame([dict(raw=binary_data)])

    class schema(pw.Schema):
        raw: bytes

    input_table = pw.debug.table_from_pandas(input_df, schema=schema)

    with pytest.raises(Exception) as excinfo:
        result = input_table.select(ret=parser(pw.this.raw)[0][0])
        pw.debug.compute_and_print(result)

    exception_msg = str(excinfo.value)

    assert (
        "This error may indicate libmagic (magic) dependency is missing."
        in exception_msg
    )
    assert "FileType.UNK" in exception_msg


def _create_temp_pdf_with_text(text: str, path: Path) -> Path:
    class PDF(FPDF):
        def header(self):
            self.set_font("Arial", size=12)
            self.cell(0, 10, "", ln=1)

        def footer(self):
            pass

    pdf_path: Path = path / "generated_test_file.pdf"
    pdf = PDF()
    pdf.add_page()
    pdf.set_font("Arial", size=12)
    pdf.multi_cell(0, 10, text)
    pdf.output(pdf_path)

    return pdf_path


def test_parse_pypdf(tmp_path: Path):
    parser = PypdfParser()

    txt = (
        "Lorem ipsum dolor sit amet, consectetur adipiscing elit, sed do eiusmod"
        "tempor incididunt ut labore et dolore magna aliqua. Ut enim ad minim veniam,"
        "quis nostrud exercitation ullamco laboris nisi ut aliquip ex ea commodo consequat."
    )

    pdf_path = _create_temp_pdf_with_text(txt, tmp_path)

    with open(pdf_path, "rb") as pdf_file:
        raw_pdf_data = pdf_file.read()

    input_df = pd.DataFrame([dict(raw=raw_pdf_data)])

    class Schema(pw.Schema):
        raw: bytes

    input_table = pw.debug.table_from_pandas(input_df, schema=Schema)
    result = input_table.select(ret=parser(pw.this.raw)[0][0])

    assert_table_equality(
        result, pw.debug.table_from_pandas(pd.DataFrame([dict(ret=txt)]))
    )
