"""Port of the reference xpack LLM test mocks.py (reference:
python/pathway/xpacks/llm/tests/mocks.py). Mechanical port:
package and imports adapted, fixtures kept identical."""

import pathway_tpu as pw
from pathway_tpu.xpacks.llm import llms


class IdentityMockChat(llms.BaseChat):
    def _accepts_call_arg(self, arg_name: str) -> bool:
        return False

    async def __wrapped__(self, messages: list[dict] | pw.Json, model: str) -> str:
        return model + "," + messages[0]["content"].as_str()


class FakeChatModel(llms.BaseChat):
    """Returns `"Text"` literal."""

    async def __wrapped__(self, *args, **kwargs) -> str:
        return "Text"

    def _accepts_call_arg(self, arg_name: str) -> bool:
        return True


@pw.udf
def fake_embeddings_model(x: str) -> list[float]:
    return [1.0, 1.0, 0.0]
