"""Trace Weaver (pathway_tpu/observability/tracing.py): W3C traceparent
contract, span ring semantics, the Chrome trace-event validator, the
slow-query log, thread-safe Telemetry timings, and the end-to-end
acceptance paths — a REST request yields one stitched root→embed→KNN
span tree, and a 2-process host-mesh run carries the same trace id
across the wire (frames stamp a traceparent; the lockstep barrier agrees
on one tick trace group-wide)."""

import json
import logging
import socket
import textwrap
import threading
import time
import urllib.error
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.observability import tracing

FIXED_TRACE = "ab" * 16
FIXED_SPAN = "cd" * 8
FIXED_TRACEPARENT = f"00-{FIXED_TRACE}-{FIXED_SPAN}-01"


@pytest.fixture(autouse=True)
def _fresh_tracer():
    tracer = tracing.get_tracer()
    tracer.clear()
    saved_slow = tracer.slow_ms
    saved_enabled = tracer.enabled
    with tracing._pending_lock:
        tracing._pending.clear()
    yield
    tracer.clear()
    tracer.slow_ms = saved_slow
    tracer.enabled = saved_enabled
    with tracing._pending_lock:
        tracing._pending.clear()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --- traceparent contract -------------------------------------------------


def test_traceparent_generate_parse_roundtrip():
    tracer = tracing.Tracer(capacity=16)
    with tracer.span("root") as sp:
        tp = sp.context.traceparent()
    ctx = tracing.parse_traceparent(tp)
    assert ctx is not None
    assert ctx.trace_id == sp.context.trace_id
    assert ctx.span_id == sp.context.span_id
    assert ctx.flags == 1
    # parse accepts uppercase-ish whitespace-padded input, case-folded
    assert tracing.parse_traceparent("  " + tp.upper() + " ") == ctx


@pytest.mark.parametrize(
    "header",
    [
        None,
        1234,
        "",
        "not-a-traceparent",
        "00-" + "ab" * 16 + "-" + "cd" * 8,  # missing flags
        "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",  # all-zero trace id
        "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero span id
        "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",  # forbidden version
        "00-" + "ab" * 15 + "-" + "cd" * 8 + "-01",  # short trace id
        "00-" + "xy" * 16 + "-" + "cd" * 8 + "-01",  # non-hex
    ],
)
def test_traceparent_malformed_headers_rejected(header):
    assert tracing.parse_traceparent(header) is None


def test_span_parent_child_links_and_explicit_parent():
    tracer = tracing.Tracer(capacity=64)
    remote = tracing.parse_traceparent(FIXED_TRACEPARENT)
    with tracer.span("ingress", parent=remote, root=True) as root:
        assert root.trace_id == FIXED_TRACE
        with tracer.span("inner") as child:
            assert child.trace_id == FIXED_TRACE
            # a root=True span breaks out of the ambient trace
            with tracer.span("fresh", root=True) as fresh:
                assert fresh.trace_id != FIXED_TRACE
    recs = {r.name: r for r in tracer.spans()}
    assert recs["ingress"].parent_id == FIXED_SPAN
    assert recs["inner"].parent_id == recs["ingress"].span_id
    assert recs["fresh"].parent_id is None


def test_ring_buffer_is_bounded():
    tracer = tracing.Tracer(capacity=10)
    for i in range(50):
        with tracer.span(f"s{i}"):
            pass
    recs = tracer.spans()
    assert len(recs) == 10
    assert recs[-1].name == "s49"  # newest kept, oldest evicted


def test_disabled_tracer_is_noop():
    tracer = tracing.Tracer(capacity=16, enabled=False)
    before = tracing.current_context()
    with tracer.span("x") as sp:
        assert sp is tracing.NOOP_SPAN
        assert sp.trace_id is None
        sp.set_attribute("k", "v")  # must not raise
        assert tracing.current_context() is before
    assert tracer.spans() == []


def test_pending_request_registry():
    ctx = tracing.parse_traceparent(FIXED_TRACEPARENT)
    tracing.register_pending(1, ctx)
    tracing.register_pending(2, tracing.SpanContext("ef" * 16, "12" * 8))
    # oldest pending wins; unregistering it promotes the next
    assert tracing.pending_context() == ctx
    assert tracing.pending_traceparent() == ctx.traceparent()
    tracing.unregister_pending(1)
    assert tracing.pending_context().trace_id == "ef" * 16
    tracing.unregister_pending(2)
    assert tracing.pending_context() is None
    tracing.register_pending(3, None)  # None context is ignored
    assert tracing.pending_context() is None


# --- Chrome trace-event export + validator --------------------------------


def test_chrome_trace_export_validates_and_links_spans():
    tracer = tracing.Tracer(capacity=64)
    with tracer.span("outer", route="/x"):
        with tracer.span("inner"):
            pass
    doc = tracer.chrome_trace()
    assert tracing.validate_chrome_trace(doc) == []
    events = {
        e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"
    }
    assert events["inner"]["args"]["parent_id"] == (
        events["outer"]["args"]["span_id"]
    )
    assert events["outer"]["args"]["route"] == "/x"
    assert events["outer"]["dur"] >= events["inner"]["dur"]
    # round-trips through JSON (what /debug/trace serves)
    assert tracing.validate_chrome_trace(json.loads(json.dumps(doc))) == []


def test_chrome_trace_validator_catches_violations():
    v = tracing.validate_chrome_trace
    assert v({"traceEvents": "nope"})
    assert v("nope")
    assert v({"traceEvents": [{"ph": "Z", "name": "x"}]})  # unknown phase
    assert v({"traceEvents": [["not", "an", "object"]]})
    assert v(
        {"traceEvents": [{"ph": "X", "name": "", "pid": 1, "tid": 1,
                          "ts": 0, "dur": 1}]}
    )  # empty name
    assert v(
        {"traceEvents": [{"ph": "X", "name": "x", "pid": "p", "tid": 1,
                          "ts": 0, "dur": 1}]}
    )  # non-int pid
    assert v(
        {"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 1,
                          "ts": -5, "dur": 1}]}
    )  # negative ts
    assert v(
        {"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 1,
                          "ts": 0}]}
    )  # X without dur
    assert v(
        {"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 1,
                          "ts": 0, "dur": 1, "args": "no"}]}
    )  # args not an object
    ok = {"traceEvents": [{"ph": "X", "name": "x", "pid": 1, "tid": 1,
                           "ts": 0.5, "dur": 1.5, "args": {"a": 1}}]}
    assert v(ok) == []
    assert v(ok["traceEvents"]) == []  # bare-array form


def test_spans_trailing_window_filter():
    tracer = tracing.Tracer(capacity=64)
    with tracer.span("old"):
        pass
    assert [r.name for r in tracer.spans(seconds=60)] == ["old"]
    assert tracer.spans(seconds=1e-9) == []


# --- slow-query log -------------------------------------------------------


def test_slow_query_log_dumps_child_breakdown(caplog):
    tracer = tracing.Tracer(capacity=64)
    tracer.slow_ms = 1.0
    with caplog.at_level(logging.WARNING, logger="pathway_tpu"):
        with tracer.span("http.request") as root:
            with tracer.span("knn.search"):
                time.sleep(0.005)
    msgs = [r.message for r in caplog.records if "slow trace" in r.message]
    assert msgs, "slow root span did not log"
    assert root.trace_id in msgs[0]
    assert "knn.search" in msgs[0]  # full child breakdown rides along
    # an ingress span that JOINED a caller's trace (non-None parent_id)
    # is still slow-log eligible — it is this process's local root
    caplog.clear()
    with caplog.at_level(logging.WARNING, logger="pathway_tpu"):
        with tracer.span(
            "http.request",
            parent=tracing.parse_traceparent(FIXED_TRACEPARENT),
            root=True,
            ingress=True,
        ):
            time.sleep(0.005)
    assert any(
        "slow trace" in r.message and FIXED_TRACE in r.message
        for r in caplog.records
    ), "slow ingress span did not log"
    # fast root spans below the threshold stay quiet
    caplog.clear()
    tracer.slow_ms = 10_000.0
    with caplog.at_level(logging.WARNING, logger="pathway_tpu"):
        with tracer.span("http.request"):
            pass
    assert not [
        r for r in caplog.records if "slow trace" in r.message
    ]


def test_trace_tree_default_selects_joined_trace():
    """pw.debug.trace_tree() with no trace id picks the most recent LOCAL
    root — including a request that joined a caller's trace (its parent
    span id lives outside the ring), not just parentless spans."""
    tracer = tracing.get_tracer()
    with tracer.span("engine.tick"):  # older, unrelated fresh-root trace
        pass
    with tracer.span(
        "http.request",
        parent=tracing.parse_traceparent(FIXED_TRACEPARENT),
        root=True,
        ingress=True,
    ):
        with tracer.span("knn.search"):
            pass
    tree = pw.debug.trace_tree()
    assert "http.request" in tree and "knn.search" in tree, tree


# --- Telemetry absorption -------------------------------------------------


def test_telemetry_span_records_into_tracer():
    from pathway_tpu.internals.telemetry import Telemetry

    tel = Telemetry()
    tracer = tracing.get_tracer()
    with tel.span("pathway.run", nodes=3):
        inner_tp = tel.trace_parent()
    assert inner_tp is not None
    ctx = tracing.parse_traceparent(inner_tp)
    assert ctx is not None
    recs = [r for r in tracer.spans() if r.name == "pathway.run"]
    assert recs and recs[-1].trace_id == ctx.trace_id
    assert recs[-1].attributes["nodes"] == 3
    assert tel.timings["pathway.run"] > 0


def test_telemetry_timings_accumulation_is_thread_safe():
    from pathway_tpu.internals.telemetry import Telemetry

    tel = Telemetry()
    n_threads, n_iter = 8, 5000

    def hammer():
        for _ in range(n_iter):
            tel._add_timing("k", 1.0)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # 1.0 sums exactly in binary; a dropped read-modify-write shows up as
    # a short total (the pre-lock failure mode under the worker pool)
    assert tel.timings["k"] == float(n_threads * n_iter)


def test_sdk_provider_detection_is_shared_and_inactive_here():
    from pathway_tpu.internals import telemetry as tel_mod

    # one helper: the metrics gate delegates to the tracer module's
    # detection (no SDK in this image, so both read False)
    assert tel_mod._sdk_provider_active() is False
    assert tracing.otel_sdk_provider_active("metrics") is False
    assert tracing.otel_sdk_provider_active("trace") is False
    assert tel_mod._OtelMetrics().enabled is False


# --- histogram exemplars --------------------------------------------------


def test_histogram_exemplars_link_metrics_to_traces():
    from pathway_tpu.observability import MetricsRegistry, validate_exposition

    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "x", labelnames=("route",))
    h.labels("/a").observe(0.25, exemplar="t1" * 16)
    h.labels("/a").observe(0.5)  # no exemplar: previous one sticks
    (ex,) = reg.exemplars()
    assert ex["metric"] == "lat_seconds"
    assert ex["labels"] == {"route": "/a"}
    assert ex["trace_id"] == "t1" * 16
    assert ex["value"] == 0.25
    # the 0.0.4 text exposition has no exemplar syntax: output unchanged
    assert validate_exposition(reg.render()) == []
    assert "t1t1" not in reg.render()


# --- end-to-end: REST request → stitched trace ----------------------------


def _post_retrieve(port: int, payload: dict, traceparent: str):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/retrieve",
        data=json.dumps(payload).encode(),
        headers={
            "Content-Type": "application/json",
            "traceparent": traceparent,
        },
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read().decode()), dict(resp.headers)


def test_rest_request_yields_one_stitched_trace():
    """Acceptance: one REST query produces root (HTTP), embedder,
    KNN/index, and operator-tick spans sharing a single trace id,
    retrievable as valid Chrome trace-event JSON from /debug/trace —
    with no OpenTelemetry SDK installed."""
    from pathway_tpu.internals.monitoring_server import start_http_server
    from pathway_tpu.observability import REGISTRY
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder
    from pathway_tpu.xpacks.llm.vector_store import VectorStoreServer

    class DocSchema(pw.Schema):
        data: str

    embedder = SentenceTransformerEmbedder(
        dim=16, depth=1, heads=2, max_len=32, batch_size=8
    )
    docs = pw.debug.table_from_rows(
        DocSchema, [(f"doc {i} topic {i % 3}",) for i in range(4)]
    )
    server = VectorStoreServer(docs, embedder=embedder)
    port = _free_port()
    thread = server.run_server(host="127.0.0.1", port=port, threaded=True)
    try:
        result, headers = None, {}
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                result, headers = _post_retrieve(
                    port, {"query": "topic 1", "k": 2}, FIXED_TRACEPARENT
                )
                if result:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        assert result, "server did not answer a retrieve query"

        # response echoes the trace id with our span id (the header
        # contract: same trace, server-side parent for the caller's logs)
        echoed = tracing.parse_traceparent(headers.get("traceparent"))
        assert echoed is not None and echoed.trace_id == FIXED_TRACE
        assert echoed.span_id != FIXED_SPAN

        names = {
            r.name
            for r in tracing.get_tracer().spans()
            if r.trace_id == FIXED_TRACE
        }
        assert "http.request" in names
        assert "engine.tick" in names
        assert "embed.batch" in names
        assert "knn.search" in names
        assert "vector_store.retrieve" in names
        assert any(n.startswith("op.") for n in names)

        # parent links actually stitch: walking up from knn.search
        # reaches the HTTP root inside one trace
        recs = {
            r.span_id: r
            for r in tracing.get_tracer().spans()
            if r.trace_id == FIXED_TRACE
        }
        knn = next(r for r in recs.values() if r.name == "knn.search")
        hops = []
        cur = knn
        while cur.parent_id is not None and cur.parent_id in recs:
            cur = recs[cur.parent_id]
            hops.append(cur.name)
        assert cur.name == "http.request", hops

        # exemplars: each serving histogram has a child whose exemplar
        # points at this trace. The registry is process-global, so OTHER
        # tests' routes/models own sibling children of the same metric —
        # assert membership, not "the only exemplar".
        exemplars = REGISTRY.exemplars()
        for metric in (
            "pathway_rest_request_seconds",
            "pathway_knn_query_seconds",
            "pathway_embed_batch_seconds",
        ):
            assert any(
                e["metric"] == metric and e["trace_id"] == FIXED_TRACE
                for e in exemplars
            ), (metric, exemplars)

        # /debug/trace round-trips through the schema validator
        mon = start_http_server(None, port=_free_port())
        try:
            url = (
                f"http://127.0.0.1:{mon.server_address[1]}"
                "/debug/trace?seconds=600"
            )
            with urllib.request.urlopen(url, timeout=10) as resp:
                doc = json.loads(resp.read().decode())
            assert tracing.validate_chrome_trace(doc) == []
            traced_names = {
                e["name"]
                for e in doc["traceEvents"]
                if e.get("args", {}).get("trace_id") == FIXED_TRACE
            }
            assert {"http.request", "engine.tick", "knn.search"} <= (
                traced_names
            )
            assert any(
                ex["trace_id"] == FIXED_TRACE
                for ex in doc["otherData"]["exemplars"]
            )
            # bad seconds is a 400, not a 500
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{mon.server_address[1]}"
                    "/debug/trace?seconds=abc",
                    timeout=10,
                )
            assert exc_info.value.code == 400
        finally:
            mon.shutdown()

        # pw.debug notebook surfaces read the same ring
        doc2 = pw.debug.trace(seconds=600)
        assert tracing.validate_chrome_trace(doc2) == []
        tree = pw.debug.trace_tree(FIXED_TRACE)
        assert "http.request" in tree and "knn.search" in tree
    finally:
        try:
            pw.internals.parse_graph.G.runtime.stop()
        except Exception:
            pass
        thread.join(timeout=15)


# --- end-to-end: 2-process host-mesh trace propagation --------------------

DCN_TRACE_SCRIPT = textwrap.dedent(
    """
    import json
    import os

    import pathway_tpu as pw
    from pathway_tpu.observability import tracing

    FIXED = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    pid = int(os.environ["PATHWAY_PROCESS_ID"])
    if pid == 0:
        # simulate a REST request in flight on process 0: its span
        # context must reach process 1 through the mesh frames
        tracing.register_pending(
            7, tracing.parse_traceparent(FIXED)
        )

    class S(pw.Schema):
        word: str

    rows = [(w,) for w in ["a", "b", "a", "c", "b", "a"]]
    t = pw.debug.table_from_rows(S, rows)
    r = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    pw.io.null.write(r)
    # go through pw.run (NOT a debug capture): its ambient pathway.run
    # span is exactly what the tick barrier must ignore in favor of the
    # pending request context
    pw.run(monitoring_level="none")

    recs = tracing.get_tracer().spans()
    tick_traces = sorted(
        {r.trace_id for r in recs if r.name == "engine.tick"}
    )
    dcn_names = sorted(
        {r.name for r in recs if r.name.startswith("dcn.")}
    )
    print("TICK_TRACES " + json.dumps(tick_traces), flush=True)
    print("DCN_SPANS " + json.dumps(dcn_names), flush=True)
    """
)


def test_two_process_run_shares_one_trace_id(tmp_path):
    """Acceptance: with two host-mesh processes, spans from both
    processes appear under the same trace id — the traceparent crosses
    the wire inside mesh frames and the lockstep barrier picks one
    group-wide tick trace."""
    from tests.test_distributed import _free_dcn_port, _spawn_group

    script = tmp_path / "dcn_trace.py"
    script.write_text(DCN_TRACE_SCRIPT)
    procs, outs = _spawn_group(script, 2, _free_dcn_port())
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    per_proc = []
    for out in outs:
        traces = next(
            json.loads(line.split(" ", 1)[1])
            for line in out.splitlines()
            if line.startswith("TICK_TRACES ")
        )
        per_proc.append(set(traces))
    fixed = "ab" * 16
    for i, traces in enumerate(per_proc):
        assert fixed in traces, (
            f"process {i} tick spans missed the propagated trace: "
            f"{per_proc}\n{outs}"
        )
    # the DCN exchange hop is visible on both sides
    for out in outs:
        dcn = next(
            json.loads(line.split(" ", 1)[1])
            for line in out.splitlines()
            if line.startswith("DCN_SPANS ")
        )
        assert "dcn.exchange" in dcn, out
