"""Multi-process execution smoke tests (VERDICT r2 item 9): two real OS
processes join via jax.distributed (gloo CPU collectives) and run the
corpus-sharded KNN with a true cross-process collective merge, asserting
exact equality with a single-process reference. Pattern: reference
integration_tests/wordcount spawns real process groups."""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_WORKER = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pathway_tpu.parallel import distributed as dist

    assert dist.maybe_initialize(), "expected multi-process mode"
    assert jax.process_count() == 2, jax.process_count()

    pid = jax.process_index()
    n_global, dim, k = 64, 16, 5
    rng = np.random.default_rng(0)
    corpus = rng.normal(size=(n_global, dim)).astype(np.float32)
    valid = np.ones(n_global, bool)
    valid[7] = False
    queries = rng.normal(size=(3, dim)).astype(np.float32)

    half = n_global // 2
    lo, hi = pid * half, (pid + 1) * half
    sc, ix = dist.sharded_topk_global(
        queries, corpus[lo:hi], valid[lo:hi], k, metric="cosine"
    )

    # single-device reference on the full corpus
    from pathway_tpu.ops.knn import dense_topk
    import jax.numpy as jnp
    s_ref, i_ref = dense_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid),
        k, metric="cosine",
    )
    assert (np.asarray(i_ref) == ix).all(), (np.asarray(i_ref), ix)
    assert np.allclose(np.asarray(s_ref), sc, atol=1e-5)
    print(f"WORKER-OK pid={pid}", flush=True)
    """
)


def test_two_process_sharded_knn(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            PATHWAY_PROCESSES="2",
            PATHWAY_PROCESS_ID=str(pid),
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PYTHONPATH=os.path.dirname(os.path.dirname(__file__)),
        )
        env.pop("XLA_FLAGS", None)  # one CPU device per process
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    try:
        outs = [p.communicate(timeout=150)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid={pid} failed:\n{out[-3000:]}"
        assert f"WORKER-OK pid={pid}" in out


def test_process_env_defaults(monkeypatch):
    from pathway_tpu.parallel import distributed as dist

    monkeypatch.delenv("PATHWAY_PROCESSES", raising=False)
    monkeypatch.delenv("PATHWAY_PROCESS_ID", raising=False)
    monkeypatch.delenv("PATHWAY_FIRST_PORT", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    n, pid, coord = dist.process_env()
    assert (n, pid) == (1, 0) and coord.startswith("127.0.0.1:")
    assert dist.maybe_initialize() is False  # single process: no-op

    monkeypatch.setenv("PATHWAY_PROCESSES", "4")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "3")
    monkeypatch.setenv("PATHWAY_FIRST_PORT", "12345")
    n, pid, coord = dist.process_env()
    assert (n, pid, coord) == (4, 3, "127.0.0.1:12345")
