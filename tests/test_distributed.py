"""Multi-process execution smoke tests (VERDICT r2 item 9): two real OS
processes join via jax.distributed (gloo CPU collectives) and run the
corpus-sharded KNN with a true cross-process collective merge, asserting
exact equality with a single-process reference. Pattern: reference
integration_tests/wordcount spawns real process groups."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_WORKER = textwrap.dedent(
    """
    import os, sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")

    from pathway_tpu.parallel import distributed as dist

    assert dist.maybe_initialize(), "expected multi-process mode"
    assert jax.process_count() == 2, jax.process_count()

    pid = jax.process_index()
    n_global, dim, k = 64, 16, 5
    rng = np.random.default_rng(0)
    corpus = rng.normal(size=(n_global, dim)).astype(np.float32)
    valid = np.ones(n_global, bool)
    valid[7] = False
    queries = rng.normal(size=(3, dim)).astype(np.float32)

    half = n_global // 2
    lo, hi = pid * half, (pid + 1) * half
    sc, ix = dist.sharded_topk_global(
        queries, corpus[lo:hi], valid[lo:hi], k, metric="cosine"
    )

    # single-device reference on the full corpus
    from pathway_tpu.ops.knn import dense_topk
    import jax.numpy as jnp
    s_ref, i_ref = dense_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid),
        k, metric="cosine",
    )
    assert (np.asarray(i_ref) == ix).all(), (np.asarray(i_ref), ix)
    assert np.allclose(np.asarray(s_ref), sc, atol=1e-5)
    print(f"WORKER-OK pid={pid}", flush=True)
    """
)


def test_two_process_sharded_knn(tmp_path):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            PATHWAY_PROCESSES="2",
            PATHWAY_PROCESS_ID=str(pid),
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            PYTHONPATH=os.path.dirname(os.path.dirname(__file__)),
        )
        env.pop("XLA_FLAGS", None)  # one CPU device per process
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    try:
        outs = [p.communicate(timeout=150)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid={pid} failed:\n{out[-3000:]}"
        assert f"WORKER-OK pid={pid}" in out


def test_process_env_defaults(monkeypatch):
    from pathway_tpu.parallel import distributed as dist

    monkeypatch.delenv("PATHWAY_PROCESSES", raising=False)
    monkeypatch.delenv("PATHWAY_PROCESS_ID", raising=False)
    monkeypatch.delenv("PATHWAY_FIRST_PORT", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    n, pid, coord = dist.process_env()
    assert (n, pid) == (1, 0) and coord.startswith("127.0.0.1:")
    assert dist.maybe_initialize() is False  # single process: no-op

    monkeypatch.setenv("PATHWAY_PROCESSES", "4")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "3")
    monkeypatch.setenv("PATHWAY_FIRST_PORT", "12345")
    n, pid, coord = dist.process_env()
    assert (n, pid, coord) == (4, 3, "127.0.0.1:12345")


# ---------------------------------------------------------------------------
# DCN rung: cross-process host-row exchange (VERDICT r3 item 2)

_DCN_WORDCOUNT = textwrap.dedent(
    """
    import os, json
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pid = int(os.environ["PATHWAY_PROCESS_ID"])

    class S(pw.Schema):
        word: str

    words_all = [f"w{i % 7}" for i in range(100)]
    mine = [(w,) for i, w in enumerate(words_all) if i % 2 == pid]
    t = pw.debug.table_from_rows(S, mine)
    r = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    keys, cols = pw.debug.table_to_dicts(r)
    out = {cols["word"][k]: cols["count"][k] for k in keys}
    rt = pw.internals.parse_graph.G.last_runtime
    from pathway_tpu.engine.dcn import DcnGroupByExec
    gbs = [e for e in rt.execs.values() if isinstance(e, DcnGroupByExec)]
    assert gbs, "expected a DCN groupby exec"
    assert gbs[0].router.exchanges > 0, "no cross-process exchange ran"
    owned = sorted(gbs[0].owned_group_keys())
    print("RESULT " + json.dumps(out), flush=True)
    print("OWNED " + json.dumps(owned), flush=True)
    """
)


def _spawn_group(script_path, n, port, extra_env=None, timeout=150):
    procs = []
    job_secret = "test-job-secret-%d" % port
    for pid in range(n):
        env = dict(os.environ)
        env.update(
            PATHWAY_PROCESSES=str(n),
            PATHWAY_PROCESS_ID=str(pid),
            PATHWAY_DCN_PORT=str(port),
            PATHWAY_DCN_SECRET=job_secret,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=os.path.dirname(os.path.dirname(__file__)),
        )
        env.pop("XLA_FLAGS", None)
        if extra_env:
            env.update(extra_env(pid) or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script_path)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def _free_dcn_port() -> int:
    from pathway_tpu.testing.chaos import free_dcn_port

    return free_dcn_port(2)


def test_two_process_wordcount_dcn(tmp_path):
    """Host rows cross processes: 2-process wordcount where each process
    owns disjoint group-key shards and merged totals equal the
    single-process result (reference: timely TCP mesh Exchange,
    external/timely-dataflow/communication/src/networking.rs:16-33)."""
    script = tmp_path / "worker.py"
    script.write_text(_DCN_WORDCOUNT)
    procs, outs = _spawn_group(script, 2, _free_dcn_port())
    results, owned = [], []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid={pid} failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                results.append(json.loads(line[len("RESULT "):]))
            elif line.startswith("OWNED "):
                owned.append(set(json.loads(line[len("OWNED "):])))
    assert len(results) == 2 and len(owned) == 2
    # disjoint ownership, both processes hold real state
    assert owned[0] and owned[1] and not (owned[0] & owned[1])
    # no word is reported by both processes
    assert not (set(results[0]) & set(results[1]))
    merged: dict[str, int] = {}
    for r in results:
        merged.update(r)
    expected = {f"w{j}": len([i for i in range(100) if i % 7 == j]) for j in range(7)}
    assert merged == expected


_DCN_KILL_WORKER = textwrap.dedent(
    """
    import os, json, threading, time, pathlib
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pid = int(os.environ["PATHWAY_PROCESS_ID"])
    base = pathlib.Path(os.environ["PW_TEST_DIR"])
    in_dir = base / f"in{pid}"
    pdir = base / f"pstorage{pid}"
    out_file = base / f"out{pid}_{os.environ['PW_PHASE']}.jsonl"
    stop_file = base / "STOP"
    die_after = int(os.environ.get("PW_DIE_AFTER_ROWS", "0"))

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read(str(in_dir), schema=S, mode="streaming")
    r = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    pw.io.jsonlines.write(r, str(out_file))

    def watch():
        while True:
            time.sleep(0.05)
            try:
                n = sum(1 for _ in open(out_file))
            except OSError:
                n = 0
            if die_after and n >= die_after:
                os._exit(17)
            if stop_file.exists():
                rt = pw.internals.parse_graph.G.runtime
                if rt is not None:
                    rt.stop()
                return

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=20)
    print("CLEAN-EXIT", flush=True)
    """
)


def _fold_updates(paths) -> dict:
    state: dict = {}
    for p in paths:
        try:
            lines = open(p).read().splitlines()
        except OSError:
            continue
        for line in lines:
            if not line.strip():
                continue
            o = json.loads(line)
            if o["diff"] > 0:
                state[o["word"]] = o["count"]
            elif state.get(o["word"]) == o["count"]:
                del state[o["word"]]
    return state


def test_two_process_wordcount_kill_restart(tmp_path):
    """One process is killed mid-stream; the group fail-stops; a full
    restart resumes from persisted state (per-process input logs +
    group-safe operator snapshots) and the merged totals exactly match —
    no row lost, none double-counted (reference recovery model:
    whole-cluster restart from the persisted frontier,
    src/persistence/state.rs:291)."""
    base = tmp_path / "work"
    for pid in range(2):
        (base / f"in{pid}").mkdir(parents=True)
    script = tmp_path / "worker.py"
    script.write_text(_DCN_KILL_WORKER)
    port = _free_dcn_port()

    def write_words(pid, fname, words):
        with open(base / f"in{pid}" / fname, "w") as f:
            for w in words:
                f.write(json.dumps({"word": w}) + "\n")

    write_words(0, "f1.jsonl", ["a", "b", "a", "c", "a", "d", "b"])
    write_words(1, "f1.jsonl", ["b", "c", "e", "a", "e", "f", "a"])

    # phase 1: process 1 kills itself after 3 output rows; process 0
    # fail-stops at the next barrier (HostMeshError)
    procs, outs = _spawn_group(
        script,
        2,
        port,
        extra_env=lambda pid: {
            "PW_TEST_DIR": str(base),
            "PW_PHASE": "1",
            **({"PW_DIE_AFTER_ROWS": "3"} if pid == 1 else {}),
        },
        timeout=90,
    )
    assert procs[1].returncode == 17, outs[1][-2000:]
    assert procs[0].returncode != 0, outs[0][-2000:]
    assert "HostMeshError" in outs[0]

    # phase 2: more input, full-group restart from persistence
    write_words(0, "f2.jsonl", ["a", "g", "d"])
    write_words(1, "f2.jsonl", ["g", "b", "e"])
    expected = {"a": 6, "b": 4, "c": 2, "d": 2, "e": 3, "f": 1, "g": 2}

    import threading

    def stopper():
        deadline = time.time() + 70
        while time.time() < deadline:
            merged = {}
            for pid in range(2):
                merged.update(
                    _fold_updates(
                        [
                            base / f"out{pid}_1.jsonl",
                            base / f"out{pid}_2.jsonl",
                        ]
                    )
                )
            if merged == expected:
                break
            time.sleep(0.2)
        (base / "STOP").touch()

    stop_thread = threading.Thread(target=stopper, daemon=True)
    stop_thread.start()
    procs2, outs2 = _spawn_group(
        script,
        2,
        port,
        extra_env=lambda pid: {"PW_TEST_DIR": str(base), "PW_PHASE": "2"},
        timeout=120,
    )
    stop_thread.join(timeout=90)
    for pid, (p, out) in enumerate(zip(procs2, outs2)):
        assert p.returncode == 0, f"phase2 pid={pid}:\n{out[-3000:]}"
        assert "CLEAN-EXIT" in out
    merged = {}
    for pid in range(2):
        merged.update(
            _fold_updates(
                [base / f"out{pid}_1.jsonl", base / f"out{pid}_2.jsonl"]
            )
        )
    assert merged == expected


_DCN_MATRIX_WORKER = textwrap.dedent(
    """
    import os, sys, json, time, pathlib, threading
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pid = int(os.environ["PATHWAY_PROCESS_ID"])
    base = pathlib.Path(os.environ["PW_TEST_DIR"])
    in_dir = base / f"in{pid}"
    pdir = base / f"pstorage{pid}"
    out_file = base / f"out{pid}_{os.environ['PW_PHASE']}.jsonl"
    stop_file = base / "STOP"
    die_after = int(os.environ.get("PW_DIE_AFTER_ROWS", "0"))
    pipeline = os.environ["PW_PIPELINE"]

    class S(pw.Schema):
        k: str
        t: int
        v: int

    # the kill trigger counts BOTH processes' outputs: row ownership is
    # hash-routed, so any single process may legitimately own zero rows
    phase_outs = [
        base / f"out{p}_{os.environ['PW_PHASE']}.jsonl" for p in range(2)
    ]

    rows = pw.io.jsonlines.read(str(in_dir), schema=S, mode="streaming")
    if pipeline == "groupby_sum":
        r = rows.groupby(rows.k).reduce(
            rows.k,
            s=pw.reducers.sum(rows.v),
            mx=pw.reducers.max(rows.v),
            cnt=pw.reducers.count(),
        )
    elif pipeline == "windowby":
        r = rows.windowby(
            rows.t,
            window=pw.temporal.tumbling(duration=4),
            instance=rows.k,
            behavior=pw.temporal.common_behavior(
                delay=2, cutoff=100, keep_results=True
            ),
        ).reduce(
            k=pw.this._pw_instance,
            start=pw.this._pw_window_start,
            cnt=pw.reducers.count(),
            s=pw.reducers.sum(pw.this.v),
        )
    else:
        raise SystemExit(f"unknown pipeline {pipeline}")
    pw.io.jsonlines.write(r, str(out_file))

    def watch():
        while True:
            time.sleep(0.05)
            n = 0
            for p in phase_outs:
                try:
                    n += sum(1 for _ in open(p))
                except OSError:
                    pass
            if die_after and n >= die_after:
                os._exit(17)
            if stop_file.exists():
                rt = pw.internals.parse_graph.G.runtime
                if rt is not None:
                    rt.stop()
                return

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_every=int(os.environ.get("PW_SNAPSHOT_EVERY", "8")),
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=20)
    print("CLEAN-EXIT", flush=True)
    """
)


def _fold_keyed(paths, key_fields):
    from pathway_tpu.testing.chaos import fold_diff_stream

    return fold_diff_stream(paths, key_fields)


def _run_matrix_kill_restart(tmp_path, pipeline, key_fields, expected, live_expected=None):
    """Shared 2-process kill/restart driver: phase 1 kills pid 1
    mid-stream (pid 0 fail-stops at the next barrier), phase 2 restarts
    the whole group from persistence and must converge on the exact
    merged state of an uninterrupted run."""
    base = tmp_path / "work"
    for pid in range(2):
        (base / f"in{pid}").mkdir(parents=True)
    script = tmp_path / "worker.py"
    script.write_text(_DCN_MATRIX_WORKER)
    port = _free_dcn_port()

    def write_rows(pid, fname, rows):
        with open(base / f"in{pid}" / fname, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    def phase(n, extra):
        return _spawn_group(
            script,
            2,
            port,
            extra_env=lambda pid: {
                "PW_TEST_DIR": str(base),
                "PW_PIPELINE": pipeline,
                **extra(pid),
            },
            timeout=120,
        )

    yield write_rows

    procs, outs = phase(
        1,
        lambda pid: {
            "PW_PHASE": "1",
            **({"PW_DIE_AFTER_ROWS": "2"} if pid == 1 else {}),
        },
    )
    assert procs[1].returncode == 17, outs[1][-2000:]
    assert procs[0].returncode != 0, outs[0][-2000:]

    yield write_rows

    import threading

    all_outs = [
        base / f"out{pid}_{ph}.jsonl" for pid in range(2) for ph in (1, 2)
    ]
    target = live_expected if live_expected is not None else expected

    def stopper():
        deadline = time.time() + 70
        while time.time() < deadline:
            merged = _fold_keyed(all_outs, key_fields)
            if live_expected is not None:
                merged = {
                    k: v for k, v in merged.items() if k in live_expected
                }
            if merged == target:
                break
            time.sleep(0.2)
        (base / "STOP").touch()

    st = threading.Thread(target=stopper, daemon=True)
    st.start()
    procs2, outs2 = phase(2, lambda pid: {"PW_PHASE": "2"})
    st.join(timeout=90)
    for pid, (p, out) in enumerate(zip(procs2, outs2)):
        assert p.returncode == 0, f"phase2 pid={pid}:\n{out[-3000:]}"
        assert "CLEAN-EXIT" in out
    assert _fold_keyed(all_outs, key_fields) == expected


def test_two_process_groupby_sum_kill_restart(tmp_path):
    """Kill/restart matrix, 2-process groupby with sum/max reducers: a
    mid-stream kill + full-group restart recovers from the persisted
    snapshots and the merged totals are exact."""
    rows1 = {
        0: [
            {"k": "x", "t": 0, "v": 3},
            {"k": "y", "t": 1, "v": 5},
            {"k": "x", "t": 2, "v": 4},
        ],
        1: [
            {"k": "y", "t": 0, "v": 2},
            {"k": "z", "t": 1, "v": 7},
            {"k": "x", "t": 2, "v": 1},
        ],
    }
    rows2 = {
        0: [{"k": "z", "t": 3, "v": 10}],
        1: [{"k": "x", "t": 3, "v": 6}],
    }
    # (cnt, mx, s) per key over ALL rows
    expected = {
        ("x",): (4, 6, 14),
        ("y",): (2, 5, 7),
        ("z",): (2, 10, 17),
    }
    gen = _run_matrix_kill_restart(
        tmp_path, "groupby_sum", ["k"], expected
    )
    write_rows = next(gen)
    for pid, rows in rows1.items():
        write_rows(pid, "f1.jsonl", rows)
    write_rows = next(gen)
    for pid, rows in rows2.items():
        write_rows(pid, "f2.jsonl", rows)
    for _ in gen:
        pass


def test_two_process_windowby_behavior_kill_restart(tmp_path):
    """Kill/restart matrix, 2-process windowby + common_behavior: the
    Buffer/Forget watermark state and window aggregates survive a
    mid-stream kill + group restart; merged final windows match the full
    input's window aggregation exactly."""
    rows1 = {
        0: [{"k": "a", "t": t, "v": t} for t in (0, 1, 3, 5, 6)],
        1: [{"k": "b", "t": t, "v": 2 * t} for t in (2, 4, 7)],
    }
    # phase 2 ends with high sentinel times on both processes so every
    # earlier window crosses the delay threshold group-wide
    rows2 = {
        0: [{"k": "a", "t": 9, "v": 9}, {"k": "a", "t": 40, "v": 0}],
        1: [{"k": "b", "t": 11, "v": 22}, {"k": "b", "t": 41, "v": 0}],
    }
    expected = {
        ("a", 0): (3, 4),
        ("a", 4): (2, 11),
        ("a", 8): (1, 9),
        ("a", 40): (1, 0),
        ("b", 0): (1, 4),
        ("b", 4): (2, 22),
        ("b", 8): (1, 22),
        ("b", 40): (1, 0),
    }
    # sentinel windows flush only on clean shutdown; converge on the rest
    live_expected = {k: v for k, v in expected.items() if k[1] < 40}
    gen = _run_matrix_kill_restart(
        tmp_path, "windowby", ["k", "start"], expected, live_expected
    )
    write_rows = next(gen)
    for pid, rows in rows1.items():
        write_rows(pid, "f1.jsonl", rows)
    write_rows = next(gen)
    for pid, rows in rows2.items():
        write_rows(pid, "f2.jsonl", rows)
    for _ in gen:
        pass


_DCN_JOIN = textwrap.dedent(
    """
    import os, json
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pid = int(os.environ["PATHWAY_PROCESS_ID"])

    class L(pw.Schema):
        k: int
        a: int

    class R(pw.Schema):
        k: int
        b: int

    # left rows split across processes; right table only on process 0 —
    # the exchange must co-locate matching rows regardless of origin
    lrows = [(i % 5, i) for i in range(40) if i % 2 == pid]
    rrows = [(i, i * 100) for i in range(5)] if pid == 0 else []
    lt = pw.debug.table_from_rows(L, lrows)
    rt = pw.debug.table_from_rows(R, rrows)
    j = lt.join(rt, lt.k == rt.k).select(lt.a, rt.b)
    keys, cols = pw.debug.table_to_dicts(j)
    out = sorted((cols["a"][k], cols["b"][k]) for k in keys)
    rtm = pw.internals.parse_graph.G.last_runtime
    from pathway_tpu.engine.dcn import DcnJoinExec
    js = [e for e in rtm.execs.values() if isinstance(e, DcnJoinExec)]
    assert js, "expected a DCN join exec"
    print("RESULT " + json.dumps(out), flush=True)
    """
)


def test_two_process_join_dcn(tmp_path):
    """2-process equijoin: both sides exchanged by join-key hash so
    matches co-locate; union of per-process outputs equals the
    single-process join."""
    script = tmp_path / "worker.py"
    script.write_text(_DCN_JOIN)
    procs, outs = _spawn_group(script, 2, _free_dcn_port())
    results = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid={pid} failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                results.append(json.loads(line[len("RESULT "):]))
    merged = sorted(tuple(x) for r in results for x in r)
    expected = sorted((i, (i % 5) * 100) for i in range(40))
    assert merged == expected


@pytest.mark.parametrize("wire_fmt", ["codec", "pickle"])
def test_two_process_wordcount_wire_formats(tmp_path, wire_fmt):
    """The PWHX7 columnar codec and the pickle escape hatch produce
    IDENTICAL results end-to-end: same per-process ownership contract,
    same merged totals (acceptance: differential 2-process run with
    PATHWAY_DCN_WIRE=codec vs =pickle)."""
    script = tmp_path / "worker.py"
    script.write_text(_DCN_WORDCOUNT)
    procs, outs = _spawn_group(
        script,
        2,
        _free_dcn_port(),
        extra_env=lambda pid: {"PATHWAY_DCN_WIRE": wire_fmt},
    )
    results = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid={pid} failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                results.append(json.loads(line[len("RESULT "):]))
    assert len(results) == 2
    assert not (set(results[0]) & set(results[1]))
    merged: dict[str, int] = {}
    for r in results:
        merged.update(r)
    expected = {
        f"w{j}": len([i for i in range(100) if i % 7 == j]) for j in range(7)
    }
    assert merged == expected


def test_host_mesh_rejects_unauthenticated_frames(monkeypatch):
    """A client without the per-job PATHWAY_DCN_SECRET must not get its
    bytes anywhere near pickle.loads (ADVICE r4: pickle over TCP is RCE
    without authentication)."""
    import pickle
    import struct
    import threading

    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "mesh-auth-test")
    base = _free_port()
    meshes = [None, None]

    def build(pid):
        meshes[pid] = hx.HostMesh(2, pid, base, connect_timeout=30.0)

    threads = [threading.Thread(target=build, args=(pid,)) for pid in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    m0, m1 = meshes
    assert m0 is not None and m1 is not None
    try:
        # rogue client: reads the challenge but answers with a garbage MAC
        rogue_payload = ("data", 1, "evil", 0, "boom")
        body = pickle.dumps(rogue_payload)
        rogue = socket.create_connection(("127.0.0.1", base), timeout=5)
        rogue.settimeout(5)
        nonce = rogue.recv(hx._NONCE_LEN)
        assert len(nonce) == hx._NONCE_LEN
        rogue.sendall(hx._HELLO_MAGIC + struct.pack("<ii", 1, 0) + b"\0" * hx._MAC_LEN)
        rogue.sendall(struct.pack("<I", len(body)) + b"\0" * hx._MAC_LEN + body)
        rogue.close()
        # legitimate traffic still flows
        m0.send(1, "ch", 0, {"ok": True})
        got = m1.gather("ch", 0, timeout=30)
        assert got == {0: {"ok": True}}
        time.sleep(0.3)
        assert ("evil", 0) not in m1._data and ("evil", 0) not in m0._data
        # a mesh without the secret refuses to construct at all
        monkeypatch.delenv("PATHWAY_DCN_SECRET")
        with pytest.raises(hx.HostMeshError, match="PATHWAY_DCN_SECRET"):
            hx.HostMesh(2, 0, _free_port())
    finally:
        m0.close()
        m1.close()


# ---------------------------------------------------------------------------
# Phoenix Mesh chaos matrix (Fault Forge, PR 8)

_DCN_CHAOS_WORKER = textwrap.dedent(
    """
    import os, sys, json, time, pathlib, threading
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pid = int(os.environ["PATHWAY_PROCESS_ID"])
    inc = os.environ.get("PATHWAY_MESH_INCARNATION", "0")
    base = pathlib.Path(os.environ["PW_TEST_DIR"])
    in_dir = base / f"in{pid}"
    pdir = base / f"pstorage{pid}"
    out_file = base / f"out{pid}_inc{inc}.jsonl"
    stop_file = base / "STOP"

    class S(pw.Schema):
        k: str
        t: int
        v: int

    rows = pw.io.jsonlines.read(str(in_dir), schema=S, mode="streaming")
    r = rows.groupby(rows.k).reduce(
        rows.k,
        s=pw.reducers.sum(rows.v),
        cnt=pw.reducers.count(),
    )
    pw.io.jsonlines.write(r, str(out_file))

    def watch():
        while True:
            time.sleep(0.05)
            if stop_file.exists():
                rt = pw.internals.parse_graph.G.runtime
                if rt is not None:
                    rt.stop()
                return

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_every=int(os.environ.get("PW_SNAPSHOT_EVERY", "2")),
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=20)
    drv = getattr(pw.internals.parse_graph.G.runtime, "persistence_driver", None)
    print("REPLAYED %d" % (drv.replayed_events if drv else -1), flush=True)
    print("CLEAN-EXIT", flush=True)
    """
)


def test_two_process_kill_mid_tick_supervised_recovery(tmp_path):
    """ACCEPTANCE (Phoenix Mesh): Fault Forge kills rank 1 at the tail
    of a data tick (processed but uncommitted — the group-visible
    mid-tick death); the survivor fail-stops, the GroupSupervisor
    restarts the WHOLE group, incarnation 1 restores the latest
    group-committed snapshot generation + log tail and converges on
    output identical to an uninterrupted run."""
    import threading

    from pathway_tpu.parallel.supervisor import GroupSupervisor
    from pathway_tpu.testing import faults as faults_mod

    base = tmp_path / "work"
    for pid in range(2):
        (base / f"in{pid}").mkdir(parents=True)
    script = tmp_path / "worker.py"
    script.write_text(_DCN_CHAOS_WORKER)
    port = _free_dcn_port()

    def write_rows(pid, fname, rows):
        with open(base / f"in{pid}" / fname, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    # trickle input so incarnation 0 sees several data ticks before the
    # injected death: the first file is written up front, the rest only
    # AFTER the group's first output appears (workers boot slowly — a
    # pre-written pile would collapse into one tick)
    all_rows = {0: [], 1: []}

    def trickler():
        def rows_for(i, pid):
            return [
                {"k": f"k{(i + j + pid) % 4}", "t": i, "v": i + j}
                for j in range(3)
            ]

        for pid in range(2):
            write_rows(pid, "f0.jsonl", rows_for(0, pid))
        deadline = time.time() + 90
        while time.time() < deadline:
            if any(
                p.stat().st_size > 0
                for p in base.glob("out*_inc0.jsonl")
            ):
                break
            time.sleep(0.2)
        for i in range(1, 6):
            for pid in range(2):
                write_rows(pid, f"f{i}.jsonl", rows_for(i, pid))
            time.sleep(0.4)

    # rows are deterministic: precompute them (and the expected fold)
    # without racing the writer thread
    for i in range(6):
        for pid in range(2):
            all_rows[pid].extend(
                {"k": f"k{(i + j + pid) % 4}", "t": i, "v": i + j}
                for j in range(3)
            )
    expected: dict = {}
    for pid in range(2):
        for r in all_rows[pid]:
            cnt, s = expected.get((r["k"],), (0, 0))
            expected[(r["k"],)] = (cnt + 1, s + r["v"])
    all_rows = {0: [], 1: []}  # reset: the trickler re-derives them

    out_paths = lambda: sorted(base.glob("out*_inc*.jsonl"))  # noqa: E731

    def stopper():
        deadline = time.time() + 120
        while time.time() < deadline:
            if _fold_keyed(out_paths(), ["k"]) == expected:
                break
            time.sleep(0.25)
        (base / "STOP").touch()

    tr = threading.Thread(target=trickler, daemon=True)
    st = threading.Thread(target=stopper, daemon=True)
    sup = GroupSupervisor(
        [sys.executable, str(script)],
        2,
        env={
            "PW_TEST_DIR": str(base),
            "PATHWAY_DCN_PORT": str(port),
            "PATHWAY_DCN_SECRET": f"chaos-secret-{port}",
            "PATHWAY_DCN_TIMEOUT": "60",
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": os.path.dirname(os.path.dirname(__file__)),
            "PATHWAY_FAULTS": "kill=tick:3,pid:1,at:tail",
        },
        max_restarts=2,
        backoff_s=0.1,
        log_dir=str(base / "logs"),
    )
    tr.start()
    st.start()
    rc = sup.run()
    st.join(timeout=150)
    tr.join(timeout=10)
    logs = "\n".join(
        f"--- {p.name}\n{p.read_text()[-2000:]}"
        for p in sorted((base / "logs").glob("*.log"))
    )
    assert rc == 0, logs
    assert sup.restarts_used >= 1, sup.events
    died = [d for _t, k, d in sup.events if k == "rank-died"]
    assert any(
        f"exited {faults_mod.FAULT_EXIT}" in d for d in died
    ), sup.events
    assert _fold_keyed(out_paths(), ["k"]) == expected, logs


def test_two_process_torn_manifest_recovery(tmp_path):
    """Fault Forge torn snapshot on rank 0 (death between segment
    writes and the metadata commit at a group-safe snapshot point): the
    group fail-stops, a clean restart restores the previous consistent
    generation on rank 0 / the group-min on rank 1, and the merged
    totals equal the uninterrupted run."""
    base = tmp_path / "work"
    for pid in range(2):
        (base / f"in{pid}").mkdir(parents=True)
    script = tmp_path / "worker.py"
    script.write_text(_DCN_MATRIX_WORKER)
    port = _free_dcn_port()

    def write_rows(pid, fname, rows):
        with open(base / f"in{pid}" / fname, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    rows1 = {
        0: [{"k": "x", "t": i, "v": i} for i in range(5)],
        1: [{"k": "y", "t": i, "v": 2 * i} for i in range(5)],
    }
    for pid, rows in rows1.items():
        write_rows(pid, "f1.jsonl", rows)

    def phase(extra):
        return _spawn_group(
            script,
            2,
            port,
            extra_env=lambda pid: {
                "PW_TEST_DIR": str(base),
                "PW_PIPELINE": "groupby_sum",
                "PW_SNAPSHOT_EVERY": "1",
                **extra(pid),
            },
            timeout=120,
        )

    from pathway_tpu.testing import faults as faults_mod

    import threading

    # the group-safe snapshot fires at the HEAD of the 2nd data tick
    # (snapshot_every=1): feed a second batch only once the first tick's
    # output is visible, so the torn directive deterministically hits
    # that snapshot's metadata commit on rank 0
    phase1_outs = [base / f"out{p}_1.jsonl" for p in range(2)]

    def feed_second_tick():
        deadline = time.time() + 90
        while time.time() < deadline:
            if _fold_keyed(phase1_outs, ["k"]):
                break
            time.sleep(0.2)
        for pid in range(2):
            write_rows(
                pid, "f1b.jsonl", [{"k": "w", "t": 6 + pid, "v": 1}]
            )

    feeder = threading.Thread(target=feed_second_tick, daemon=True)
    feeder.start()
    procs, outs = phase(
        lambda pid: {
            "PW_PHASE": "1",
            **(
                {"PATHWAY_FAULTS": "torn=nth:1,pid:0"} if pid == 0 else {}
            ),
        }
    )
    feeder.join(timeout=10)
    assert procs[0].returncode == faults_mod.FAULT_EXIT, outs[0][-2000:]
    assert procs[1].returncode != 0, outs[1][-2000:]

    rows2 = {
        0: [{"k": "x", "t": 9, "v": 100}],
        1: [{"k": "z", "t": 9, "v": 7}],
    }
    for pid, rows in rows2.items():
        write_rows(pid, "f2.jsonl", rows)
    # (cnt, mx, s) per key over ALL rows — matrix worker emits cnt/mx/s;
    # "w" is the second-tick trigger batch (one v=1 row per rank)
    expected = {
        ("x",): (6, 100, 110),
        ("y",): (5, 8, 20),
        ("w",): (2, 1, 2),
        ("z",): (1, 7, 7),
    }

    import threading

    all_outs = [
        base / f"out{pid}_{ph}.jsonl" for pid in range(2) for ph in (1, 2)
    ]

    def stopper():
        deadline = time.time() + 70
        while time.time() < deadline:
            if _fold_keyed(all_outs, ["k"]) == expected:
                break
            time.sleep(0.2)
        (base / "STOP").touch()

    st = threading.Thread(target=stopper, daemon=True)
    st.start()
    procs2, outs2 = phase(lambda pid: {"PW_PHASE": "2"})
    st.join(timeout=90)
    for pid, (p, out) in enumerate(zip(procs2, outs2)):
        assert p.returncode == 0, f"phase2 pid={pid}:\n{out[-3000:]}"
        assert "CLEAN-EXIT" in out
    assert _fold_keyed(all_outs, ["k"]) == expected


def test_two_process_duplicated_frame_is_idempotent(tmp_path):
    """Fault Forge duplicates a groupby exchange frame on each rank:
    delivery is keyed per (channel, tick, src), so the duplicate is
    absorbed and the merged wordcount is EXACTLY the uninterrupted
    result — no double-counted rows."""
    script = tmp_path / "worker.py"
    script.write_text(_DCN_WORDCOUNT)
    procs, outs = _spawn_group(
        script,
        2,
        _free_dcn_port(),
        extra_env=lambda pid: {
            "PATHWAY_FAULTS": "dup=ch:gb,nth:1,inc:*"
        },
    )
    results = []
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid={pid} failed:\n{out[-3000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT "):
                results.append(json.loads(line[len("RESULT "):]))
    assert len(results) == 2
    assert not (set(results[0]) & set(results[1]))
    merged: dict[str, int] = {}
    for r in results:
        merged.update(r)
    expected = {
        f"w{j}": len([i for i in range(100) if i % 7 == j]) for j in range(7)
    }
    assert merged == expected


# ---------------------------------------------------------------------------
# Replica Shield chaos leg: writer + 2 subprocess replicas + router, with a
# Fault-Forge replica kill and a supervised (incarnation-gated) restart.



@pytest.mark.slow
def test_replica_shield_chaos_kill_and_supervised_restart(tmp_path):
    """Full replication chaos leg: a real writer pipeline streams deltas
    to two subprocess replicas behind the failover router; Fault Forge
    kills replica 1 after its 12th applied tick; its Phoenix-Mesh
    supervisor restarts it (incarnation 1 runs fault-free), it
    re-hydrates + replays, and the router re-admits it — while the
    client-visible error count stays zero."""
    import secrets
    import threading

    import requests

    from pathway_tpu.parallel.supervisor import GroupSupervisor
    from pathway_tpu.serving.router import FailoverRouter
    from pathway_tpu.testing import faults

    base = tmp_path
    (base / "docs").mkdir()
    (base / "q").mkdir()
    DIM = 16
    repl_port = _free_port()
    http_ports = [_free_port(), _free_port()]
    secret = secrets.token_hex(16)
    env_common = {
        "PW_WRITER_DIR": str(base),
        "PATHWAY_DCN_SECRET": secret,
        "PATHWAY_REPLICA_DIM": str(DIM),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ),
    }

    def write_docs(lo, hi, tag):
        with open(base / "docs" / f"{tag}.jsonl", "w") as f:
            for i in range(lo, hi):
                f.write(json.dumps({"text": f"doc {i}"}) + "\n")

    write_docs(0, 8, "f0")
    from pathway_tpu.testing.chaos import REPL_WRITER_SCRIPT

    script = base / "writer.py"
    script.write_text(REPL_WRITER_SCRIPT)
    writer_env = dict(os.environ)
    writer_env.update(env_common)
    writer_env["PATHWAY_REPL_PORT"] = str(repl_port)
    writer = subprocess.Popen(
        [sys.executable, str(script)],
        env=writer_env,
        stdout=open(base / "writer.log", "wb"),
        stderr=subprocess.STDOUT,
    )
    sups: list[GroupSupervisor] = []
    sup_threads: list = []
    router = None
    try:
        # wait for the writer's delta stream port to answer
        deadline = time.monotonic() + 120
        up = False
        while time.monotonic() < deadline:
            s = socket.socket()
            try:
                s.connect(("127.0.0.1", repl_port))
                up = True
                break
            except OSError:
                time.sleep(0.5)
            finally:
                s.close()
        assert up, (base / "writer.log").read_text()[-3000:]

        # two supervised replicas; replica 1 carries the fault spec
        for rid in range(2):
            renv = dict(env_common)
            renv["PATHWAY_REPLICA_ID"] = str(rid)
            renv["PATHWAY_REPLICA_STORE"] = str(base / "pstorage")
            renv["PATHWAY_REPL_PORT"] = str(repl_port)
            renv["PATHWAY_REPLICA_HTTP_PORT"] = str(http_ports[rid])
            if rid == 1:
                renv["PATHWAY_FAULTS"] = "kill=replica:1,tick:12"
            sup = GroupSupervisor(
                [sys.executable, "-m", "pathway_tpu.serving.replica"],
                1,
                env=renv,
                max_restarts=2,
                backoff_s=0.2,
                log_dir=str(base / f"replica{rid}-logs"),
            )
            sups.append(sup)
            th = threading.Thread(target=sup.run, daemon=True)
            sup_threads.append(th)
            th.start()

        router = FailoverRouter(
            [f"http://127.0.0.1:{p}" for p in http_ports],
            health_interval_ms=150,
        ).start()
        failures: list = []
        router.add_failure_listener(
            lambda name, why: failures.append((name, why))
        )

        def health(rid):
            try:
                return requests.get(
                    f"http://127.0.0.1:{http_ports[rid]}/replica/health",
                    timeout=2,
                ).json()
            except Exception:
                return None

        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            hs = [health(0), health(1)]
            if all(h is not None and h["ready"] for h in hs):
                break
            time.sleep(0.5)
        else:
            pytest.fail(f"replicas never became ready: {hs}")

        # drive load while trickling docs so replica 1 accumulates
        # applied ticks toward its injected death
        statuses: dict = {}
        url = f"http://127.0.0.1:{router.port}/query"
        killed_seen = restarted_ready = False
        for i in range(200):
            if i % 4 == 0:
                write_docs(8 + i, 9 + i, f"t{i}")
            try:
                r = requests.post(
                    url, json={"query": f"doc {i % 8}", "k": 1}, timeout=15
                )
                statuses[r.status_code] = statuses.get(r.status_code, 0) + 1
            except Exception:
                statuses["transport"] = statuses.get("transport", 0) + 1
            if failures and not killed_seen:
                killed_seen = True
            h1 = health(1)
            if (
                killed_seen
                and h1 is not None
                and h1.get("incarnation") == 1
                and h1.get("ready")
            ):
                restarted_ready = True
                break
            time.sleep(0.15)

        assert killed_seen, "router never observed the replica death"
        assert restarted_ready, (
            "restarted replica never became ready again",
            health(1),
            statuses,
        )
        # the kill was the injected one, and the supervisor restarted it
        assert sups[1].restarts_used >= 1
        died = [e for e in sups[1].events if e[1] == "rank-died"]
        assert died and f"exited {faults.FAULT_EXIT}" in died[0][2]
        # client-visible contract: shed only explicitly, NEVER an error
        errors = sum(
            v
            for k, v in statuses.items()
            if k not in (200, 429, 503)
        )
        assert errors == 0, statuses
        assert statuses.get(200, 0) > 0, statuses
        # the router re-admitted the restarted replica
        ep1 = router.endpoints[1]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and ep1.ejected:
            time.sleep(0.2)
        assert not ep1.ejected
    finally:
        (base / "STOP").touch()
        if router is not None:
            router.stop()
        for sup in sups:
            sup.stop()
        for th in sup_threads:
            th.join(timeout=30)
        writer.terminate()
        try:
            writer.wait(timeout=30)
        except subprocess.TimeoutExpired:
            writer.kill()


# ---------------------------------------------------------------------------
# mesh teardown determinism (the wordcount wire-format flake fix)


def test_mesh_atexit_flush_hook():
    """The atexit hook flush-closes the mesh singleton exactly once:
    the PR-6 overlapped sender means a rank can complete its final
    barrier while its own frame still sits in an outbox — interpreter
    exit used to kill the sender mid-queue and the peer EOF'd
    (test_two_process_wordcount_wire_formats under load).  close()
    queues the stop sentinel BEHIND pending frames, so registering it
    at exit makes the teardown deterministic."""
    from pathway_tpu.parallel import host_exchange as hx

    class _Stub:
        def __init__(self):
            self._closed = False
            self.closes = 0

        def close(self):
            self.closes += 1
            self._closed = True

    stub = _Stub()
    old = hx._mesh
    try:
        hx._mesh = stub
        hx._flush_mesh_at_exit()
        assert stub.closes == 1
        hx._flush_mesh_at_exit()  # already closed: no double close
        assert stub.closes == 1
        hx._mesh = None
        hx._flush_mesh_at_exit()  # no mesh: no-op
    finally:
        hx._mesh = old


def test_mesh_close_delivers_queued_frames(monkeypatch):
    """What the atexit hook relies on: frames already queued on an
    outbox are ON THE WIRE before close() returns — the stop sentinel
    queues behind them."""
    import threading

    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "flush-test")
    base = _free_port()
    meshes = [None, None]

    def build(pid):
        meshes[pid] = hx.HostMesh(2, pid, base, connect_timeout=30.0)

    threads = [
        threading.Thread(target=build, args=(pid,)) for pid in (0, 1)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    m0, m1 = meshes
    assert m0 is not None and m1 is not None
    try:
        for i in range(8):
            m0.send(1, "flushch", i, {"i": i})
        m0.close()  # what the atexit hook calls
        # every queued frame arrived despite the immediate close
        for i in range(8):
            got = m1.gather("flushch", i, timeout=30)
            assert got == {0: {"i": i}}
    finally:
        m1.close()
