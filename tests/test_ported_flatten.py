"""Ported reference flatten suite (reference:
python/pathway/tests/test_flatten.py)."""

from typing import Any

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T, table_from_pandas
from ref_utils import assert_table_equality_wo_index


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    yield
    pw.internals.parse_graph.G.clear()


def test_flatten_simple():
    tab = table_from_pandas(pd.DataFrame.from_dict({"col": [[1, 2, 3, 4]]}))
    assert_table_equality_wo_index(
        tab.flatten(pw.this.col, origin_id="origin_id"),
        T(
            """
    col | origin_id
      1 | 0
      2 | 0
      3 | 0
      4 | 0
    """,
        ).with_columns(origin_id=tab.pointer_from(pw.this.origin_id)),
    )


def test_flatten_no_origin():
    tab = table_from_pandas(pd.DataFrame.from_dict({"col": [[1, 2, 3, 4]]}))
    assert_table_equality_wo_index(
        tab.flatten(pw.this.col),
        T(
            """
    col
      1
      2
      3
      4
    """,
        ),
    )


def test_flatten_inner_repeats():
    tab = table_from_pandas(pd.DataFrame.from_dict({"col": [[1, 1, 1, 3]]}))
    assert_table_equality_wo_index(
        tab.flatten(pw.this.col, origin_id="origin_id"),
        T(
            """
    col | origin_id
      1 | 0
      1 | 0
      1 | 0
      3 | 0
    """,
        ).with_columns(origin_id=tab.pointer_from(pw.this.origin_id)),
    )


def test_flatten_more_repeats():
    tab = table_from_pandas(
        pd.DataFrame.from_dict({"col": [[1, 1, 1, 3], [1]]})
    )
    assert_table_equality_wo_index(
        tab.flatten(pw.this.col, origin_id="origin_id"),
        T(
            """
    col | origin_id
      1 | 0
      1 | 0
      1 | 0
      3 | 0
      1 | 1
    """,
        ).with_columns(origin_id=tab.pointer_from(pw.this.origin_id)),
    )


def test_flatten_empty_lists():
    tab = table_from_pandas(pd.DataFrame.from_dict({"col": [[], []]}))
    assert_table_equality_wo_index(
        tab.flatten(pw.this.col, origin_id="origin_id"),
        pw.Table.empty(col=Any, origin_id=pw.Pointer),
    )
