"""Row transformer semantics — ported from the reference's
python/pathway/tests/test_transformers.py (the spec for @pw.transformer)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T


def _vals(table, col):
    _k, cols = pw.debug.table_to_dicts(table)
    return sorted(cols[col].values())


def test_simple_transformer():
    class OutputSchema(pw.Schema):
        ret: int

    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg, output=OutputSchema):
            arg = pw.input_attribute()

            @pw.output_attribute
            def ret(self) -> int:
                return self.arg + 1

    table = T(
        """
            | arg
        1   | 1
        2   | 2
        3   | 3
        """
    )
    ret = foo_transformer(table).table
    assert ret.column_names() == ["ret"]
    assert _vals(ret, "ret") == [2, 3, 4]


def test_aux_objects():
    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg):
            arg = pw.input_attribute()

            const = 10

            def fun(self, a) -> int:
                return a * self.arg + self.const

            @staticmethod
            def sfun(b) -> int:
                return b * 100

            @pw.attribute
            def attr(self) -> int:
                return self.arg / 2

            @pw.output_attribute
            def ret(self) -> int:
                return (
                    self.arg
                    + self.const
                    + self.fun(1)
                    + self.sfun(self.arg)
                    + self.attr
                )

    table = T(
        """
            | arg
        1   | 10
        2   | 20
        3   | 30
        """
    )
    ret = foo_transformer(table).table
    assert _vals(ret, "ret") == [1045, 2070, 3095]


def test_skips_list_traversal():
    """Demand-driven pointer chasing across rows and tables (reference
    test_skips; engine analog of complex_columns.rs)."""

    @pw.transformer
    class list_traversal:
        class nodes(pw.ClassArg):
            next = pw.input_attribute()
            val = pw.input_attribute()

        class requests(pw.ClassArg):
            node = pw.input_attribute()
            steps = pw.input_attribute()

            @pw.output_attribute
            def reached_node(self):
                node = self.transformer.nodes[self.node]
                for _ in range(self.steps):
                    node = self.transformer.nodes[node.next]
                return node.id

            @pw.output_attribute
            def reached_value(self) -> int:
                node = self.transformer.nodes[self.reached_node]
                return node.val

    nodes = T(
        """
            | next | val
        1   | 2    | 11
        2   | 3    | 12
        3   |      | 13
        """
    )
    nodes = nodes.with_columns(next=pw.this.pointer_from(pw.this.next))

    requests = T(
        """
            | node | steps
        10  | 1    | 1
        20  | 3    | 0
        """
    ).with_columns(node=nodes.pointer_from(pw.this.node))

    replies = list_traversal(nodes, requests).requests
    assert _vals(replies, "reached_value") == [12, 13]
    # reached node pointers equal the hash of the original row labels
    _k, cols = pw.debug.table_to_dicts(replies)
    from pathway_tpu.internals.api import ref_scalar

    reached = sorted(int(p) for p in cols["reached_node"].values())
    assert reached == sorted(int(ref_scalar(v)) for v in (2, 3))


def test_output_attribute_rename():
    class OutputSchema(pw.Schema):
        foo: int

    @pw.transformer
    class foo_transformer:
        class table(pw.ClassArg, output=OutputSchema):
            arg = pw.input_attribute()

            @pw.output_attribute(output_name="foo")
            def ret(self) -> int:
                return self.arg + 1

    ret = foo_transformer(T("""
            | arg
        1   | 1
        """)).table
    assert ret.column_names() == ["foo"]
    assert _vals(ret, "foo") == [2]


def test_output_schema_validation_error():
    with pytest.raises(Exception):

        class OutputSchema(pw.Schema):
            foo: int

        @pw.transformer
        class foo_transformer:
            class table(pw.ClassArg, output=OutputSchema):
                arg = pw.input_attribute()

                @pw.output_attribute
                def ret(self) -> int:  # pragma: no cover
                    return self.arg + 1


def test_method_output_and_incremental_update():
    """method columns emit callables bound to live operator state, and a
    changed input re-derives dependents incrementally (diff output)."""

    @pw.transformer
    class calc:
        class table(pw.ClassArg):
            a = pw.input_attribute()

            @pw.output_attribute
            def double(self) -> int:
                return self.a * 2

            @pw.method
            def scaled(self, factor) -> int:
                return self.a * factor

    class S(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        a: int

    rows = [(1, 5, 0, 1), (2, 7, 0, 1), (1, 5, 2, -1), (1, 9, 2, 1)]
    t = pw.debug.table_from_rows(S, rows, is_stream=True)
    res = calc(t).table
    _k, cols = pw.debug.table_to_dicts(res)
    assert sorted(cols["double"].values()) == [14, 18]
    fns = list(cols["scaled"].values())
    assert sorted(f(10) for f in fns) == [70, 90]


def test_transformer_cross_row_dependency_updates():
    """A row's output depending on ANOTHER row must update when only that
    other row changes — the demand-driven property."""

    @pw.transformer
    class follow:
        class items(pw.ClassArg):
            ref = pw.input_attribute()
            val = pw.input_attribute()

            @pw.output_attribute
            def other_val(self):
                if self.ref is None:
                    return self.val
                return self.transformer.items[self.ref].val

    class S(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        refname: int
        val: int

    from pathway_tpu.internals.api import ref_scalar

    # row 1 follows row 2; at t=2 row 2's value changes — row 1's output
    # must follow even though row 1 itself never ticks
    rows = [(1, 2, 100, 0, 1), (2, 0, 200, 0, 1),
            (2, 0, 200, 2, -1), (2, 0, 999, 2, 1)]
    t = pw.debug.table_from_rows(S, rows, is_stream=True)
    t2 = t.select(
        ref=pw.if_else(
            t.refname != 0,
            t.pointer_from(t.refname),
            None,
        ),
        val=t.val,
    )
    res = follow(t2).items
    _k, cols = pw.debug.table_to_dicts(res)
    vals = dict(zip((int(x) for x in _k), cols["other_val"].values()))
    key1 = int(ref_scalar(1))
    key2 = int(ref_scalar(2))
    assert cols["other_val"][key1] == 999
    assert cols["other_val"][key2] == 999
