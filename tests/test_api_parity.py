"""Public-API parity vs the reference's __all__ plus the reference's
pandas_transformer doc example."""

import ast
import os

import pytest

import pathway_tpu as pw

_REF_INIT = "/root/reference/python/pathway/__init__.py"

# stale entries in the reference's own __all__ (listed but never imported
# there — pw.window / pw.asynchronous AttributeError in the reference too)
_REF_STALE = {"window", "asynchronous"}


@pytest.mark.skipif(
    not os.path.exists(_REF_INIT), reason="reference checkout not present"
)
def test_reference_public_names_present():
    tree = ast.parse(open(_REF_INIT).read())
    names = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    names = [ast.literal_eval(e) for e in node.value.elts]
    assert names
    missing = [
        n for n in names if n not in _REF_STALE and not hasattr(pw, n)
    ]
    assert missing == [], f"missing public names: {missing}"


def test_pandas_transformer_doc_example():
    import pandas as pd

    table = pw.debug.table_from_markdown(
        """
        | foo  | bar
    0   | 10   | 100
    1   | 20   | 200
    2   | 30   | 300
    """
    )

    class Output(pw.Schema):
        sum: int

    @pw.pandas_transformer(output_schema=Output)
    def sum_cols(t: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame(t.sum(axis=1))

    output = sum_cols(table)
    _k, cols = pw.debug.table_to_dicts(output)
    assert sorted(cols["sum"].values()) == [110, 220, 330]


def test_pandas_transformer_incremental():
    import pandas as pd

    class S(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        v: int

    rows = [(1, 10, 0, 1), (2, 20, 0, 1), (1, 10, 2, -1), (1, 99, 2, 1)]
    t = pw.debug.table_from_rows(S, rows, is_stream=True)

    class Out(pw.Schema):
        total: int

    @pw.pandas_transformer(output_schema=Out)
    def totals(df: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({"total": df["v"] + 1})

    _k, cols = pw.debug.table_to_dicts(totals(t))
    assert sorted(cols["total"].values()) == [21, 100]


def test_universes_promises():
    class S(pw.Schema):
        v: int

    a = pw.debug.table_from_rows(S, [(1,)])
    b = pw.debug.table_from_rows(S, [(2,)])
    pw.universes.promise_are_pairwise_disjoint(a, b)
    pw.universes.promise_are_equal(a, b)
    pw.universes.promise_is_subset_of(a, b)


def test_submodule_parity():
    """Every public name of the reference's io/udfs/temporal/indexing/ml/
    debug/demo namespaces resolves on ours."""
    import pathway_tpu as pw

    ref = "/root/reference/python/pathway"
    if not os.path.exists(ref):
        pytest.skip("reference checkout not present")

    def names_of(path):
        tree = ast.parse(open(path).read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if getattr(t, "id", None) == "__all__":
                        return [ast.literal_eval(e) for e in node.value.elts]
        return [
            n.name
            for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.ClassDef))
            and not n.name.startswith("_")
        ]

    checks = {
        "io": (f"{ref}/io/__init__.py", pw.io),
        "udfs": (f"{ref}/udfs.py", pw.udfs),
        "temporal": (f"{ref}/stdlib/temporal/__init__.py", pw.temporal),
        "indexing": (f"{ref}/stdlib/indexing/__init__.py", pw.indexing),
        "ml": (f"{ref}/stdlib/ml/__init__.py", pw.ml),
        "debug": (f"{ref}/debug/__init__.py", pw.debug),
        "demo": (f"{ref}/demo/__init__.py", pw.demo),
        "reducers": (f"{ref}/reducers.py", pw.reducers),
    }
    problems = {}
    for name, (path, mod) in checks.items():
        missing = [n for n in names_of(path) if not hasattr(mod, n)]
        if missing:
            problems[name] = missing
    assert problems == {}, problems


def test_stream_generator():
    import pandas as pd

    sg = pw.debug.StreamGenerator()

    class S(pw.Schema):
        v: int

    t = sg.table_from_list_of_batches([[{"v": 1}], [{"v": 2}, {"v": 3}]], S)
    _k, cols = pw.debug.table_to_dicts(t)
    assert sorted(cols["v"].values()) == [1, 2, 3]

    df = pd.DataFrame(
        {"v": [10, 20, 20], "_time": [2, 2, 4], "_diff": [1, 1, -1]}
    )
    t2 = sg.table_from_pandas(df, id_from=["v"])
    _k2, c2 = pw.debug.table_to_dicts(t2)
    assert sorted(c2["v"].values()) == [10]  # 20 inserted then retracted
    assert sg.persistence_config() is None


def test_parquet_roundtrip(tmp_path):
    import pandas as pd

    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    path = tmp_path / "t.parquet"
    df.to_parquet(path)
    t = pw.debug.table_from_parquet(path)
    out = tmp_path / "out.parquet"
    pw.internals.parse_graph.G.clear()
    t2 = pw.debug.table_from_parquet(path)
    pw.debug.table_to_parquet(t2.select(a=t2.a * 10, b=t2.b), out)
    back = pd.read_parquet(out)
    assert sorted(back["a"]) == [10, 20, 30]


def test_stream_generator_odd_times_double_all():
    """Reference semantics: ANY odd timestamp doubles ALL timestamps,
    preserving relative order (a retraction after an odd-time insert must
    still land after it)."""
    import warnings

    import pandas as pd

    sg = pw.debug.StreamGenerator()
    df = pd.DataFrame(
        {"v": [7, 7], "_time": [3, 4], "_diff": [1, -1]}
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t = sg.table_from_pandas(df, id_from=["v"])
    _k, cols = pw.debug.table_to_dicts(t)
    assert cols["v"] == {}  # insert at 6, retract at 8 -> empty


def test_stream_generator_markdown_preserves_similar_names():
    sg = pw.debug.StreamGenerator()
    t = sg.table_from_markdown(
        """
          | event_time | _time | _diff
        1 | 11         | 2     | 1
        2 | 22         | 2     | 1
        2 | 22         | 4     | -1
        """
    )
    assert t.column_names() == ["event_time"]
    _k, cols = pw.debug.table_to_dicts(t)
    assert sorted(cols["event_time"].values()) == [11]


def test_pandas_transformer_duplicate_index_raises():
    import pandas as pd
    import pytest

    class S(pw.Schema):
        v: int

    t = pw.debug.table_from_rows(S, [(1,), (2,)])

    class Out(pw.Schema):
        x: int

    @pw.pandas_transformer(output_schema=Out)
    def dup(df: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({"x": [1, 2]}, index=[5, 5])

    with pytest.raises(ValueError, match="unique"):
        pw.debug.table_to_dicts(dup(t))


def test_stream_generator_markdown_schema_and_worker():
    """schema= plus a _worker column must work (reference supports it),
    and odd markdown timestamps double like every other entry point."""
    import warnings

    sg = pw.debug.StreamGenerator()

    class S(pw.Schema):
        v: int

    t = sg.table_from_markdown(
        """
        v  | _worker | _time
        1  | 0       | 2
        2  | 1       | 2
        """,
        schema=S,
    )
    _k, cols = pw.debug.table_to_dicts(t)
    assert sorted(cols["v"].values()) == [1, 2]
    assert t.column_names() == ["v"]

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        t2 = sg.table_from_markdown(
            """
            v | _time
            5 | 3
            """
        )
        pw.debug.table_to_dicts(t2)
    assert any("doubled" in str(x.message) for x in w)
