"""Public-API parity vs the reference's __all__ plus the reference's
pandas_transformer doc example."""

import ast
import os

import pytest

import pathway_tpu as pw

_REF_INIT = "/root/reference/python/pathway/__init__.py"

# stale entries in the reference's own __all__ (listed but never imported
# there — pw.window / pw.asynchronous AttributeError in the reference too)
_REF_STALE = {"window", "asynchronous"}


@pytest.mark.skipif(
    not os.path.exists(_REF_INIT), reason="reference checkout not present"
)
def test_reference_public_names_present():
    tree = ast.parse(open(_REF_INIT).read())
    names = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if getattr(t, "id", None) == "__all__":
                    names = [ast.literal_eval(e) for e in node.value.elts]
    assert names
    missing = [
        n for n in names if n not in _REF_STALE and not hasattr(pw, n)
    ]
    assert missing == [], f"missing public names: {missing}"


def test_pandas_transformer_doc_example():
    import pandas as pd

    table = pw.debug.table_from_markdown(
        """
        | foo  | bar
    0   | 10   | 100
    1   | 20   | 200
    2   | 30   | 300
    """
    )

    class Output(pw.Schema):
        sum: int

    @pw.pandas_transformer(output_schema=Output)
    def sum_cols(t: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame(t.sum(axis=1))

    output = sum_cols(table)
    _k, cols = pw.debug.table_to_dicts(output)
    assert sorted(cols["sum"].values()) == [110, 220, 330]


def test_pandas_transformer_incremental():
    import pandas as pd

    class S(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        v: int

    rows = [(1, 10, 0, 1), (2, 20, 0, 1), (1, 10, 2, -1), (1, 99, 2, 1)]
    t = pw.debug.table_from_rows(S, rows, is_stream=True)

    class Out(pw.Schema):
        total: int

    @pw.pandas_transformer(output_schema=Out)
    def totals(df: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({"total": df["v"] + 1})

    _k, cols = pw.debug.table_to_dicts(totals(t))
    assert sorted(cols["total"].values()) == [21, 100]


def test_universes_promises():
    class S(pw.Schema):
        v: int

    a = pw.debug.table_from_rows(S, [(1,)])
    b = pw.debug.table_from_rows(S, [(2,)])
    pw.universes.promise_are_pairwise_disjoint(a, b)
    pw.universes.promise_are_equal(a, b)
    pw.universes.promise_is_subset_of(a, b)
