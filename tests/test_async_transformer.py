"""AsyncTransformer semantics (reference:
python/pathway/stdlib/utils/async_transformer.py:281-511 and its tests):
successful/failed split, instance consistency, options, retractions."""

import asyncio

import pytest

import pathway_tpu as pw


class OutSchema(pw.Schema):
    ret: int


class InSchema(pw.Schema):
    value: int


def _input(rows):
    return pw.debug.table_from_rows(InSchema, rows)


def test_basic_success():
    class Inc(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            await asyncio.sleep(0.001)
            return {"ret": value + 1}

    t = _input([(42,), (44,)])
    res = Inc(input_table=t).successful
    _k, cols = pw.debug.table_to_dicts(res)
    assert sorted(cols["ret"].values()) == [43, 45]


def test_failed_split():
    class Flaky(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            if value % 2 == 0:
                raise RuntimeError("boom")
            return {"ret": value * 10}

    t = _input([(1,), (2,), (3,), (4,)])
    tr = Flaky(input_table=t)
    _k, ok = pw.debug.table_to_dicts(tr.successful)
    assert sorted(ok["ret"].values()) == [10, 30]
    pw.internals.parse_graph.G.clear()
    t = _input([(1,), (2,), (3,), (4,)])
    tr = Flaky(input_table=t)
    _k2, bad = pw.debug.table_to_dicts(tr.failed)
    assert len(bad["ret"]) == 2
    assert all(v is None for v in bad["ret"].values())


def test_finished_status_column():
    class Flaky(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            if value == 2:
                raise RuntimeError("boom")
            return {"ret": value}

    t = _input([(1,), (2,)])
    fin = Flaky(input_table=t).finished
    _k, cols = pw.debug.table_to_dicts(fin)
    assert sorted(cols["_async_status"].values()) == ["-FAILURE-", "-SUCCESS-"]


def test_instance_consistency():
    """A failure poisons same-instance successes (reference `failed` doc:
    rows executed successfully whose instance saw a failure at <= time are
    reported as failed)."""

    class InSchema2(pw.Schema):
        value: int
        group: int

    class Flaky(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value, group) -> dict:
            if value == 2:
                raise RuntimeError("boom")
            return {"ret": value}

    rows = [(1, 0), (2, 0), (3, 1)]
    t = pw.debug.table_from_rows(InSchema2, rows)
    tr = Flaky(input_table=t, instance=t.group)
    _k, cols = pw.debug.table_to_dicts(tr.successful)
    # group 0 contains the failing row -> row (1, 0) must not be successful
    assert list(cols["ret"].values()) == [3]


def test_bad_result_schema_is_failure():
    class Wrong(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            return {"unexpected": 1}

    t = _input([(7,)])
    tr = Wrong(input_table=t)
    _k, cols = pw.debug.table_to_dicts(tr.failed)
    assert len(cols["ret"]) == 1


def test_signature_check():
    class Inc(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, wrong_name) -> dict:  # pragma: no cover
            return {"ret": 0}

    with pytest.raises(TypeError, match="wrong_name"):
        Inc(input_table=_input([(1,)]))


def test_with_options_timeout_and_retry():
    calls = {"n": 0}

    class Slow(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("transient")
            return {"ret": value}

    t = _input([(5,)])
    tr = Slow(input_table=t).with_options(
        capacity=2,
        retry_strategy=pw.udfs.FixedDelayRetryStrategy(
            max_retries=4, delay_ms=1
        ),
    )
    _k, cols = pw.debug.table_to_dicts(tr.successful)
    assert list(cols["ret"].values()) == [5]
    assert calls["n"] == 3


def test_retraction_reemits():
    class Inc(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, i, value) -> dict:
            return {"ret": value + 1}

    class InK(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        value: int

    rows = [(0, 10, 0, 1), (1, 20, 0, 1), (0, 10, 2, -1)]
    t = pw.debug.table_from_rows(InK, rows, is_stream=True)
    tr = Inc(input_table=t)
    _k, cols = pw.debug.table_to_dicts(tr.successful)
    assert list(cols["ret"].values()) == [21]


def test_open_close_called():
    seen = []

    class Inc(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            return {"ret": value}

        def open(self):
            seen.append("open")

        def close(self):
            seen.append("close")

    t = _input([(1,)])
    pw.debug.table_to_dicts(Inc(input_table=t).successful)
    assert seen == ["open", "close"]


def test_same_tick_insert_retract_no_ghost():
    class Inc(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, i, value) -> dict:
            return {"ret": value + 1}

    class InK(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        value: int

    rows = [(0, 10, 0, 1), (0, 10, 0, -1), (1, 20, 0, 1)]
    t = pw.debug.table_from_rows(InK, rows, is_stream=True)
    _k, cols = pw.debug.table_to_dicts(Inc(input_table=t).successful)
    assert list(cols["ret"].values()) == [21]


def test_cache_strategy_memoizes_results():
    calls = {"n": 0}

    class Inc(pw.AsyncTransformer, output_schema=OutSchema):
        async def invoke(self, value) -> dict:
            calls["n"] += 1
            return {"ret": value + 1}

    t = _input([(5,), (5,), (6,)])
    tr = Inc(input_table=t).with_options(
        cache_strategy=pw.udfs.InMemoryCache()
    )
    _k, cols = pw.debug.table_to_dicts(tr.successful)
    assert sorted(cols["ret"].values()) == [6, 6, 7]
    assert calls["n"] == 2  # (5,) invoked once, cached for the twin row
