"""Fault Forge (pathway_tpu/testing/faults.py) + Phoenix Mesh units:
deterministic fault-spec parsing, the wire/store/tick hooks, the group
supervisor's restart budget, heartbeat failure detection, the serving
degradation controller, and a tier-1-safe single-process chaos smoke
(torn snapshot -> clean recovery equals the uninterrupted run)."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from pathway_tpu.testing import faults


def _plan(spec: str, pid: int = 0, inc: int = 0) -> faults.FaultPlan:
    return faults.FaultPlan(spec, pid, inc)


# --- spec parsing ----------------------------------------------------------


def test_spec_parses_all_directives():
    p = _plan(
        "seed=7;kill=tick:5,pid:1,at:tail;drop=ch:gb,nth:2;"
        "dup=ch:jl,nth:1;delay=ch:wm,nth:3,ms:200;torn=nth:2;"
        "slow_store=ms:10"
    )
    assert [d.name for d in p.directives] == [
        "kill", "drop", "dup", "delay", "torn", "slow_store",
    ]
    assert p._slow_store_s == pytest.approx(0.010)


@pytest.mark.parametrize(
    "bad",
    [
        "explode=now",  # unknown directive
        "kill=pid:1",  # kill without tick
        "drop=nth:1",  # wire directive without channel
        "kill=tick:notanint",
        "kill=tick 5",  # malformed arg
        "delay=ch:x,nth:1",  # delay without ms
        "kill=tick:1,at:sideways",
    ],
)
def test_spec_rejects_garbage(bad):
    with pytest.raises(faults.FaultSpecError):
        _plan(bad)


def test_active_caches_and_resets(monkeypatch):
    faults.reset()
    monkeypatch.delenv("PATHWAY_FAULTS", raising=False)
    assert faults.active() is None
    monkeypatch.setenv("PATHWAY_FAULTS", "drop=ch:gb,nth:1")
    assert faults.active() is None  # cached: env is read once per process
    faults.reset()
    p = faults.active()
    assert p is not None and p.directives[0].name == "drop"
    assert faults.active() is p  # same plan, counters persist
    faults.reset()
    monkeypatch.delenv("PATHWAY_FAULTS", raising=False)
    faults.reset()


def test_incarnation_scoping():
    # default inc:0 — a restarted group (incarnation 1) is fault-free
    p0 = _plan("drop=ch:gb,nth:1", pid=0, inc=0)
    assert p0.on_wire_send("gb7") == ("drop", 0.0)
    p1 = _plan("drop=ch:gb,nth:1", pid=0, inc=1)
    for _ in range(5):
        assert p1.on_wire_send("gb7") is None
    # inc:* fires in every incarnation
    pstar = _plan("drop=ch:gb,nth:1,inc:*", pid=0, inc=3)
    assert pstar.on_wire_send("gb7") == ("drop", 0.0)


def test_wire_counters_deterministic():
    p = _plan("drop=ch:gb,nth:2;dup=ch:jl,nth:1;delay=ch:wm,nth:2,ms:50")
    assert p.on_wire_send("gb1") is None
    assert p.on_wire_send("gb1") == ("drop", 0.0)
    assert p.on_wire_send("gb1") is None  # fired once, never again
    assert p.on_wire_send("jl9") == ("dup", 0.0)
    assert p.on_wire_send("wm3") is None
    assert p.on_wire_send("wm3") == ("delay", pytest.approx(0.05))
    # pid-scoped directive on another pid never fires
    p2 = _plan("drop=ch:gb,nth:1,pid:1", pid=0)
    assert p2.on_wire_send("gb1") is None


def test_slow_store_wraps_put_get(tmp_path):
    class Store:
        def __init__(self):
            self.data = {}

        def put(self, key, data):
            self.data[key] = data

        def get(self, key):
            return self.data.get(key)

        def list_keys(self, prefix):
            return [k for k in self.data if k.startswith(prefix)]

    p = _plan("slow_store=ms:30")
    s = p.wrap_store(Store())
    t0 = time.monotonic()
    s.put("a", b"x")
    assert s.get("a") == b"x"
    assert time.monotonic() - t0 >= 0.055  # two ops, 30 ms each
    assert s.list_keys("a") == ["a"]  # passthrough attrs survive
    # no slow_store directive -> wrap is the identity
    inner = Store()
    assert _plan("drop=ch:x,nth:1").wrap_store(inner) is inner


# --- group supervisor ------------------------------------------------------

_SUP_CHILD = (
    "import os,sys;"
    "inc=int(os.environ.get('PATHWAY_MESH_INCARNATION','0'));"
    "sys.exit(23 if inc==0 else 0)"
)


def test_supervisor_restarts_group_once_then_succeeds():
    from pathway_tpu.parallel.supervisor import GroupSupervisor

    sup = GroupSupervisor(
        [sys.executable, "-c", _SUP_CHILD],
        2,
        max_restarts=2,
        backoff_s=0.05,
        poll_s=0.02,
    )
    assert sup.run() == 0
    assert sup.restarts_used == 1
    kinds = [k for _ts, k, _d in sup.events]
    assert "rank-died" in kinds and "group-restart" in kinds
    assert kinds[-1] == "group-done"


def test_supervisor_budget_exhausted_propagates_failure():
    """Drive run() on a worker thread and poll the event log with a
    deadline: on a loaded box the two incarnations (4 interpreter
    spawns + jittered backoff) can take arbitrarily long, so a direct
    synchronous assert is a timing lottery — the event log reaching
    "gave-up" IS the completion signal, and the deadline turns a hang
    into a diagnosable failure instead of a suite timeout."""
    from pathway_tpu.parallel.supervisor import GroupSupervisor

    sup = GroupSupervisor(
        [sys.executable, "-c", "import sys; sys.exit(23)"],
        2,
        max_restarts=1,
        backoff_s=0.05,
        poll_s=0.02,
    )
    rc: list[int] = []
    runner = threading.Thread(target=lambda: rc.append(sup.run()))
    runner.start()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if any(k == "gave-up" for _ts, k, _d in sup.events):
            break
        time.sleep(0.05)
    else:
        sup.stop()  # unwedge before failing so the thread dies
        runner.join(10)
        raise AssertionError(
            f"no gave-up event within deadline; events={sup.events}"
        )
    runner.join(30)
    assert not runner.is_alive(), "run() did not return after gave-up"
    assert rc == [23]
    assert sup.restarts_used == 1
    kinds = [k for _ts, k, _d in sup.events]
    assert kinds[-1] == "gave-up"
    assert kinds.count("rank-died") == 2  # one per incarnation


def test_supervisor_env_budget(monkeypatch):
    from pathway_tpu.parallel import supervisor

    monkeypatch.setenv("PATHWAY_MESH_MAX_RESTARTS", "7")
    assert supervisor.max_restarts_env() == 7


# --- heartbeat failure detection ------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_failure_listener_fires_on_peer_eof(monkeypatch):
    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "phoenix-eof-test")
    base = _free_port()
    meshes = [None, None]

    def build(pid):
        meshes[pid] = hx.HostMesh(2, pid, base, connect_timeout=30.0)

    threads = [threading.Thread(target=build, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    m0, m1 = meshes
    assert m0 is not None and m1 is not None
    failures: list = []
    try:
        m0.add_failure_listener(lambda peer, reason: failures.append(
            (peer, reason)
        ))
        m1.close()  # peer death: EOF on m0's reader
        deadline = time.monotonic() + 10
        while not failures and time.monotonic() < deadline:
            time.sleep(0.05)
        assert failures and failures[0][0] == 1
        # the pending gather names the dead peer and the recorded cause
        with pytest.raises(hx.HostMeshError, match="peer"):
            m0.gather("ch", 0, timeout=5)
        # a listener registered late still hears about it
        late: list = []
        m0.add_failure_listener(lambda p, r: late.append(p))
        assert late == [1]
    finally:
        m0.close()


def test_liveness_timeout_detects_wedged_peer(monkeypatch):
    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "phoenix-liveness-test")
    monkeypatch.setenv("PATHWAY_MESH_HEARTBEAT_MS", "100")
    monkeypatch.setenv("PATHWAY_MESH_LIVENESS_TIMEOUT_MS", "700")
    base = _free_port()
    meshes = [None, None]

    def build(pid):
        meshes[pid] = hx.HostMesh(2, pid, base, connect_timeout=30.0)

    threads = [threading.Thread(target=build, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    m0, m1 = meshes
    assert m0 is not None and m1 is not None
    failures: list = []
    try:
        m0.add_failure_listener(lambda peer, reason: failures.append(
            (peer, reason)
        ))
        # wedge peer 1 WITHOUT closing its sockets: stop its heartbeat
        # loop (and senders' will to live) — sockets stay open, so only
        # the liveness monitor can catch this
        m1._closed = True
        deadline = time.monotonic() + 10
        while not failures and time.monotonic() < deadline:
            time.sleep(0.05)
        assert failures, "liveness monitor never fired"
        peer, reason = failures[0]
        assert peer == 1 and "liveness timeout" in reason
    finally:
        m1._closed = False
        m0.close()
        m1.close()


# --- serving degradation controller ---------------------------------------


def test_degrade_controller_state_and_staleness():
    from pathway_tpu.serving import degrade

    degrade.reset()
    try:
        assert degrade.recovering() is None
        degrade.enter_recovery("peer 1 failed: test")
        degrade.enter_recovery("replay")
        assert degrade.recovering() == "peer 1 failed: test"  # oldest
        degrade.exit_recovery("peer 1 failed: test")
        assert degrade.recovering() == "replay"
        degrade.exit_recovery("replay")
        assert degrade.recovering() is None
        assert degrade.staleness_seconds() is None  # no index registered
        degrade.mark_fresh()
        s = degrade.staleness_seconds()
        assert s is not None and s < 1.0
        calls = []
        degrade.register_stale_responder("/r", lambda vals: calls.append(
            vals
        ) or {"ok": 1})
        assert degrade.stale_responder("/r")({"q": 2}) == {"ok": 1}
        assert degrade.stale_responder("/other") is None
    finally:
        degrade.reset()


def test_stale_knn_search_uses_registered_index():
    from pathway_tpu.serving import degrade

    degrade.reset()
    try:
        with pytest.raises(RuntimeError):
            degrade.stale_knn_search([("q", 1, None)])

        class FakeIndex:
            def search(self, triples):
                return [((7, 0.5),) for _ in triples]

        class FakeExec:
            index = FakeIndex()

        holder = FakeExec()
        degrade.register_index_reader(holder)
        assert degrade.stale_knn_search([("q", 1, None)]) == [((7, 0.5),)]
        assert degrade.staleness_seconds() is not None
    finally:
        degrade.reset()


def test_rest_serves_stale_during_recovery():
    """Phoenix degradation e2e: while recovery is active, a Surge-Gated
    endpoint answers from the registered stale responder with explicit
    staleness headers, honors x-pathway-max-staleness-ms, and flips back
    to the live engine path when recovery ends."""
    import requests

    import pathway_tpu as pw
    from pathway_tpu.io.http import rest_connector
    from pathway_tpu.serving import QoSConfig, degrade, drain_all

    degrade.reset()

    class QuerySchema(pw.Schema):
        text: str

    port = _free_port()
    queries, writer = rest_connector(
        host="127.0.0.1",
        port=port,
        schema=QuerySchema,
        route="/echo",
        qos=QoSConfig(max_batch_size=4, max_wait_ms=5),
    )
    writer(queries.select(query_id=queries.id, result=queries.text))
    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{port}/echo"
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                if requests.post(
                    url, json={"text": "up"}, timeout=5
                ).status_code == 200:
                    break
            except Exception:
                pass
            time.sleep(0.2)
        else:
            raise RuntimeError("server did not come up")

        degrade.enter_recovery("chaos test")
        degrade.mark_fresh()
        # no responder yet: explicit 503, never a hang
        r = requests.post(url, json={"text": "x"}, timeout=10)
        assert r.status_code == 503
        assert r.headers.get("x-pathway-stale") == "true"
        assert "Retry-After" in r.headers

        degrade.register_stale_responder(
            "/echo", lambda vals: {"stale_echo": vals.get("text")}
        )
        r = requests.post(url, json={"text": "y"}, timeout=10)
        assert r.status_code == 200
        assert r.json() == {"stale_echo": "y"}
        assert r.headers.get("x-pathway-stale") == "true"
        assert float(r.headers["x-pathway-staleness-seconds"]) >= 0.0

        # bounded staleness: snapshot is fresh, generous bound passes
        r = requests.post(
            url,
            json={"text": "z"},
            headers={"x-pathway-max-staleness-ms": "60000"},
            timeout=10,
        )
        assert r.status_code == 200
        # zero bound always sheds (staleness > 0 by the time we check)
        r = requests.post(
            url,
            json={"text": "w"},
            headers={"x-pathway-max-staleness-ms": "0"},
            timeout=10,
        )
        assert r.status_code == 503
        assert "Retry-After" in r.headers

        degrade.exit_recovery("chaos test")
        r = requests.post(url, json={"text": "live"}, timeout=30)
        assert r.status_code == 200
        assert r.headers.get("x-pathway-stale") is None
    finally:
        degrade.reset()
        drain_all()
        rt = pw.internals.parse_graph.G.runtime
        if rt is not None:
            rt.stop()
        t.join(timeout=30)


# --- single-process chaos smoke: torn snapshot -----------------------------

_TORN_WORKER = textwrap.dedent(
    """
    import os, json, pathlib, threading, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    base = pathlib.Path(os.environ["PW_TEST_DIR"])
    out_file = base / ("out_%s.jsonl" % os.environ["PW_PHASE"])
    stop_file = base / "STOP"

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read(str(base / "in"), schema=S, mode="streaming")
    r = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    pw.io.jsonlines.write(r, str(out_file))

    def watch():
        while True:
            time.sleep(0.05)
            if stop_file.exists():
                rt = pw.internals.parse_graph.G.runtime
                if rt is not None:
                    rt.stop()
                return

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(base / "pstorage")),
        snapshot_every=1,
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=20)
    print("CLEAN-EXIT", flush=True)
    """
)


def _fold_counts(paths) -> dict:
    state: dict = {}
    for p in paths:
        try:
            lines = open(p).read().splitlines()
        except OSError:
            continue
        for line in lines:
            if not line.strip():
                continue
            o = json.loads(line)
            if o["diff"] > 0:
                state[o["word"]] = o["count"]
            elif state.get(o["word"]) == o["count"]:
                del state[o["word"]]
    return state


def test_single_process_torn_snapshot_recovers(tmp_path):
    """Chaos smoke (tier-1 safe, one process at a time): Fault Forge
    kills the run between segment writes and the metadata commit (torn
    snapshot); the restart recovers from the previous consistent cut +
    log tail and converges on exactly the uninterrupted run's totals."""
    base = tmp_path / "work"
    (base / "in").mkdir(parents=True)
    script = tmp_path / "worker.py"
    script.write_text(_TORN_WORKER)

    def write_words(fname, words):
        with open(base / "in" / fname, "w") as f:
            for w in words:
                f.write(json.dumps({"word": w}) + "\n")

    def run_phase(phase, fault=None, timeout=90):
        env = dict(os.environ)
        env.update(
            PW_TEST_DIR=str(base),
            PW_PHASE=phase,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=os.path.dirname(os.path.dirname(__file__)),
        )
        env.pop("PATHWAY_FAULTS", None)
        if fault:
            env["PATHWAY_FAULTS"] = fault
        proc = subprocess.Popen(
            [sys.executable, str(script)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        out = proc.communicate(timeout=timeout)[0]
        return proc.returncode, out

    write_words("f1.jsonl", ["a", "b", "a", "c", "a", "b"])
    rc, out = run_phase("1", fault="torn=nth:1")
    assert rc == faults.FAULT_EXIT, out[-2000:]
    assert "CLEAN-EXIT" not in out

    write_words("f2.jsonl", ["b", "d", "a"])
    expected = {"a": 4, "b": 3, "c": 1, "d": 1}

    stop = threading.Thread(
        target=lambda: _await_fold_then_stop(base, expected), daemon=True
    )
    stop.start()
    rc, out = run_phase("2", timeout=120)
    stop.join(timeout=60)
    assert rc == 0, out[-3000:]
    assert "CLEAN-EXIT" in out
    merged = _fold_counts(
        [base / "out_1.jsonl", base / "out_2.jsonl"]
    )
    assert merged == expected


def _await_fold_then_stop(base, expected, deadline_s=60):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if _fold_counts(
            [base / "out_1.jsonl", base / "out_2.jsonl"]
        ) == expected:
            break
        time.sleep(0.2)
    (base / "STOP").touch()


# --- Replica Shield fault specs (replica-scoped kills + delta-stream wire) --


def test_replica_kill_spec_parses_fires_on_applied_tick():
    p = _plan("kill=replica:1,tick:3")
    exits: list[str] = []
    p._exit = lambda what: exits.append(what)
    # a different replica never fires
    for n in range(1, 6):
        p.on_replica_tick(0, n)
    assert not exits
    p.on_replica_tick(1, 1)
    p.on_replica_tick(1, 2)
    assert not exits
    p.on_replica_tick(1, 3)
    assert exits and "replica 1" in exits[0]
    # fired once, never again
    p.on_replica_tick(1, 4)
    assert len(exits) == 1


def test_replica_kill_ignored_by_engine_tick_hook():
    p = _plan("kill=replica:0,tick:1")
    exits: list[str] = []
    p._exit = lambda what: exits.append(what)
    for t in range(1, 8):
        p.on_tick(t, "head")
        p.on_tick(t, "tail")
    assert not exits  # replica-scoped kills never fire on engine ticks
    p.on_replica_tick(0, 1)
    assert len(exits) == 1


def test_replica_kill_tick_defaults_to_first_applied():
    p = _plan("kill=replica:2")
    exits: list[str] = []
    p._exit = lambda what: exits.append(what)
    p.on_replica_tick(2, 1)
    assert len(exits) == 1


def test_replica_kill_incarnation_scoped():
    # default inc:0 — a supervised replica restart runs fault-free
    p1 = _plan("kill=replica:0,tick:1", inc=1)
    exits: list[str] = []
    p1._exit = lambda what: exits.append(what)
    for n in range(1, 6):
        p1.on_replica_tick(0, n)
    assert not exits
    pstar = _plan("kill=replica:0,tick:1,inc:*", inc=4)
    pstar._exit = lambda what: exits.append(what)
    pstar.on_replica_tick(0, 1)
    assert len(exits) == 1


@pytest.mark.parametrize(
    "bad",
    [
        "kill=replica:notanint",  # replica must be an int
        "kill=replica:1,at:head",  # `at` is meaningless for replicas
        "kill=replica:1,tick:x",  # tick must be an int when given
    ],
)
def test_replica_kill_spec_validation(bad):
    with pytest.raises(faults.FaultSpecError):
        _plan(bad)


def test_delta_stream_wire_faults_deterministic(monkeypatch):
    """drop/dup/delay=ch:repl target the replication delta stream with
    the same deterministic counters as the mesh wire hooks: the N-th
    matching frame is affected, exactly once."""
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "fault-test-secret")
    monkeypatch.setenv("PATHWAY_FAULTS", "drop=ch:repl,nth:2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    monkeypatch.delenv("PATHWAY_MESH_INCARNATION", raising=False)
    faults.reset()
    try:
        from pathway_tpu.engine.batch import DiffBatch
        from pathway_tpu.parallel.replicate import (
            DeltaStreamClient,
            DeltaStreamServer,
        )

        srv = DeltaStreamServer(0)
        applied: list[int] = []
        cl = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            0,
            from_tick=-1,
            on_deltas=lambda t, bs: applied.append(t),
        )
        cl.start()
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not cl.connected:
                time.sleep(0.05)
            for t in range(4):
                srv.publish(
                    t,
                    [
                        DiffBatch.from_rows(
                            [(t, 1, ("x", None))], ("_data", "_meta")
                        )
                    ],
                )
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and (
                not applied or applied[-1] < 3
            ):
                time.sleep(0.05)
            # the 2nd data frame (tick 1) was dropped on the wire —
            # deterministic by count, not timing
            assert applied == [0, 2, 3], applied
        finally:
            cl.close()
            srv.close()
    finally:
        faults.reset()


# --- Shard Harbor fault specs (writer-scoped kills + standby leg) ----------


def test_writer_kill_spec_parses_fires_on_published_tick():
    p = _plan("kill=writer:1,tick:3")
    exits: list[str] = []
    p._exit = lambda what: exits.append(what)
    p.on_writer_tick(1)
    p.on_writer_tick(2)
    assert not exits
    p.on_writer_tick(3)
    assert exits and "writer" in exits[0]
    # fired once, never again
    p.on_writer_tick(4)
    assert len(exits) == 1


def test_writer_kill_defaults_to_first_published_tick():
    p = _plan("kill=writer:1")
    exits: list[str] = []
    p._exit = lambda what: exits.append(what)
    p.on_writer_tick(1)
    assert len(exits) == 1


def test_writer_kill_ignored_by_other_hooks():
    p = _plan("kill=writer:1,tick:1")
    exits: list[str] = []
    p._exit = lambda what: exits.append(what)
    for t in range(1, 6):
        p.on_tick(t, "head")
        p.on_tick(t, "tail")
        p.on_replica_tick(0, t)
    assert not exits  # writer-scoped kills never fire elsewhere
    p.on_writer_tick(1)
    assert len(exits) == 1
    # and conversely: engine/replica kills never fire on writer ticks
    p2 = _plan("kill=tick:1;kill=replica:0,tick:1")
    exits2: list[str] = []
    p2._exit = lambda what: exits2.append(what)
    for n in range(1, 6):
        p2.on_writer_tick(n)
    assert not exits2


def test_writer_kill_incarnation_scoped():
    # default inc:0 — the standby's takeover writer runs fault-free
    p1 = _plan("kill=writer:1,tick:1", inc=1)
    exits: list[str] = []
    p1._exit = lambda what: exits.append(what)
    for n in range(1, 6):
        p1.on_writer_tick(n)
    assert not exits


@pytest.mark.parametrize(
    "bad",
    [
        "kill=writer:notanint",
        "kill=writer:1,at:head",  # `at` is meaningless for writers
        "kill=writer:1,tick:x",
    ],
)
def test_writer_kill_spec_validation(bad):
    with pytest.raises(faults.FaultSpecError):
        _plan(bad)


def test_publisher_fires_writer_kill_deterministically(monkeypatch):
    """The delta publisher drives on_writer_tick with its distinct-tick
    counter: a same-tick merge (second index node) does not advance
    it."""
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "fault-test-secret")
    monkeypatch.setenv("PATHWAY_FAULTS", "kill=writer:1,tick:3")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    monkeypatch.delenv("PATHWAY_MESH_INCARNATION", raising=False)
    faults.reset()
    try:
        from pathway_tpu.parallel.replicate import DeltaStreamServer

        srv = DeltaStreamServer(0)
        exits: list[str] = []
        srv._fault_plan._exit = lambda what: exits.append(what)
        try:
            srv.publish(0, [])
            srv.publish(1, [])
            srv.publish(1, [])  # same-tick merge: not a new tick
            assert not exits
            srv.publish(2, [])
            assert exits and "published tick 3" in exits[0]
        finally:
            srv.close()
    finally:
        faults.reset()


def test_standby_leg_wire_faults_target_only_standby(monkeypatch):
    """drop=ch:repl:standby drops frames on the writer→standby leg
    ONLY — the replica fan-out (channel repl:idx) is untouched, so
    takeover determinism is testable without perturbing the read
    plane."""
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "fault-test-secret")
    monkeypatch.setenv("PATHWAY_FAULTS", "drop=ch:repl:standby,nth:2")
    monkeypatch.setenv("PATHWAY_PROCESS_ID", "0")
    monkeypatch.delenv("PATHWAY_MESH_INCARNATION", raising=False)
    faults.reset()
    try:
        from pathway_tpu.engine.batch import DiffBatch
        from pathway_tpu.parallel.replicate import (
            STANDBY_ID,
            DeltaStreamClient,
            DeltaStreamServer,
        )

        srv = DeltaStreamServer(0)
        replica_applied: list[int] = []
        standby_applied: list[int] = []
        cl = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            0,
            from_tick=-1,
            on_deltas=lambda t, bs: replica_applied.append(t),
        )
        sb = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            STANDBY_ID,
            from_tick=-1,
            on_deltas=lambda t, bs: standby_applied.append(t),
        )
        cl.start()
        sb.start()
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and not (
                cl.connected and sb.connected
            ):
                time.sleep(0.05)
            for t in range(4):
                srv.publish(
                    t,
                    [
                        DiffBatch.from_rows(
                            [(t, 1, ("x", None))], ("_data", "_meta")
                        )
                    ],
                )
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and (
                not replica_applied
                or replica_applied[-1] < 3
                or not standby_applied
                or standby_applied[-1] < 3
            ):
                time.sleep(0.05)
            # the standby missed exactly its 2nd frame; the replica saw
            # every tick
            assert replica_applied == [0, 1, 2, 3], replica_applied
            assert standby_applied == [0, 2, 3], standby_applied
        finally:
            cl.close()
            sb.close()
            srv.close()
    finally:
        faults.reset()
