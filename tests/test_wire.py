"""DCN wire codec (parallel/wire.py) + PWHX mesh behaviors: bit-exact
columnar roundtrips vs the pickle path, opt-in quantization, the
version-mismatch fast-fail handshake, and the overlapped per-peer
outbox."""

from __future__ import annotations

import hmac
import os
import pickle
import socket
import struct
import threading
import time

import numpy as np
import pytest

from pathway_tpu.engine.batch import DiffBatch, uniform_element_spec
from pathway_tpu.parallel import wire


def _roundtrip(batches, quant=None):
    frame = ("data", 3, "chan7", 12, list(batches), None)
    body, stats = wire.encode_frame(frame, "codec", quant)
    assert body[:1] == wire.FRAME_CODEC
    out = wire.decode_frame(body)
    assert out[:4] == frame[:4] and out[5] is None
    return out[4], body, stats


def _rand_batch(rng, n, sorted_keys=True, with_obj=True):
    keys = rng.integers(0, 2**64, n, dtype=np.uint64)
    if sorted_keys:
        keys = np.sort(keys)
    cols = {
        "i": rng.integers(-3, 3, n).astype(np.int64),
        "f": rng.normal(size=n),
        "f32": rng.normal(size=n).astype(np.float32),
        "b": rng.integers(0, 2, n).astype(bool),
    }
    if with_obj:
        cols["s"] = np.array(
            [None if i % 11 == 0 else f"s{i % 5}" for i in range(n)],
            dtype=object,
        )
        tup = np.empty(n, dtype=object)
        for i in range(n):
            tup[i] = (i, "x", None)
        cols["t"] = tup
    return DiffBatch(
        keys, rng.choice([1, -1], n).astype(np.int64), cols
    )


# --- varint / primitives ---------------------------------------------------


def test_uvarint_roundtrip_edges():
    edges = [0, 1, 127, 128, 16383, 16384, 2**32, 2**63 - 1, 2**63, 2**64 - 1]
    vals = np.array(edges, dtype=np.uint64)
    enc = wire.uvarint_encode(vals)
    dec = wire.uvarint_decode(np.frombuffer(enc, np.uint8), len(vals))
    assert np.array_equal(dec, vals)
    assert wire.uvarint_encode(np.empty(0, np.uint64)) == b""


def test_uvarint_roundtrip_random():
    rng = np.random.default_rng(0)
    for n in (1, 7, 1000):
        vals = rng.integers(0, 2**64, n, dtype=np.uint64)
        enc = wire.uvarint_encode(vals)
        dec = wire.uvarint_decode(np.frombuffer(enc, np.uint8), n)
        assert np.array_equal(dec, vals)


def test_uvarint_rejects_wrong_count():
    enc = wire.uvarint_encode(np.array([5, 6], dtype=np.uint64))
    with pytest.raises(wire.WireError):
        wire.uvarint_decode(np.frombuffer(enc, np.uint8), 3)


def test_zigzag_roundtrip():
    rng = np.random.default_rng(1)
    x = rng.integers(-(2**62), 2**62, 500).astype(np.int64)
    x[:4] = (-(2**63), 2**63 - 1, 0, -1)
    assert np.array_equal(wire.unzigzag(wire.zigzag(x)), x)


# --- codec roundtrips ------------------------------------------------------


def test_roundtrip_mixed_batch_bit_exact_vs_pickle():
    rng = np.random.default_rng(2)
    b = _rand_batch(rng, 500)
    frame = ("data", 0, "ch", 3, [b], "00-aa-bb-01")
    codec_body, stats = wire.encode_frame(frame, "codec", None)
    pickle_body, pstats = wire.encode_frame(frame, "pickle", None)
    assert pstats is None and pickle_body[:1] == wire.FRAME_PICKLE
    got_c = wire.decode_frame(codec_body)
    got_p = wire.decode_frame(pickle_body)
    assert got_c[:4] == got_p[:4] == frame[:4]
    assert got_c[5] == got_p[5] == "00-aa-bb-01"
    assert wire.batches_equal(got_c[4], [b])
    assert wire.batches_equal(got_p[4], [b])
    # dtype preservation, column order, writability
    out = got_c[4][0]
    assert out.column_names == b.column_names
    for name in b.column_names:
        assert out.columns[name].dtype == b.columns[name].dtype
    out.diffs[0] = 5  # decoded arrays must be writable
    assert stats["rows"] == 500 and stats["raw_bytes"] > 0


def test_roundtrip_empty_and_no_columns():
    batches, _body, _ = _roundtrip(
        [DiffBatch.empty(["a", "b"]), DiffBatch.empty([])]
    )
    assert wire.batches_equal(
        batches, [DiffBatch.empty(["a", "b"]), DiffBatch.empty([])]
    )
    batches, _body, _ = _roundtrip([])
    assert batches == []
    # no-column batch with rows (pure key/diff traffic)
    b = DiffBatch(
        np.array([7, 7, 9], np.uint64), np.array([1, -1, 1], np.int64), {}
    )
    batches, _body, _ = _roundtrip([b])
    assert wire.batches_equal(batches, [b])


def test_roundtrip_unsorted_and_extreme_keys():
    rng = np.random.default_rng(3)
    keys = rng.integers(0, 2**64, 300, dtype=np.uint64)  # adversarial
    keys[:3] = (0, 2**64 - 1, 1)
    b = DiffBatch(
        keys,
        rng.integers(-5, 6, 300).astype(np.int64),
        {"v": rng.integers(0, 2**63, 300, dtype=np.uint64)},
    )
    batches, _body, _ = _roundtrip([b])
    assert wire.batches_equal(batches, [b])


def test_roundtrip_embedding_column_stacked_not_pickled():
    rng = np.random.default_rng(4)
    n, dim = 64, 16
    emb = np.empty(n, dtype=object)
    for i in range(n):
        emb[i] = rng.normal(size=dim).astype(np.float32)
    assert uniform_element_spec(emb) == (np.dtype(np.float32), (dim,))
    b = DiffBatch(
        np.arange(n, dtype=np.uint64), np.ones(n, np.int64), {"emb": emb}
    )
    batches, body, _ = _roundtrip([b])
    assert wire.batches_equal(batches, [b])
    # stacked raw block beats a pickle of 64 tiny ndarrays
    assert len(body) < len(pickle.dumps([b]))


def test_ragged_object_column_falls_back_to_pickle():
    col = np.empty(3, dtype=object)
    col[0] = np.zeros(2, np.float32)
    col[1] = np.zeros(3, np.float32)  # ragged
    col[2] = np.zeros(2, np.float32)
    assert uniform_element_spec(col) is None
    b = DiffBatch(
        np.arange(3, dtype=np.uint64), np.ones(3, np.int64), {"r": col}
    )
    batches, _body, _ = _roundtrip([b])
    assert wire.batches_equal(batches, [b])


def test_roundtrip_property_random_batches():
    rng = np.random.default_rng(5)
    for trial in range(25):
        bs = [
            _rand_batch(
                rng,
                int(rng.integers(0, 80)),
                sorted_keys=bool(rng.integers(0, 2)),
                with_obj=bool(rng.integers(0, 2)),
            )
            for _ in range(int(rng.integers(1, 4)))
        ]
        batches, _body, _ = _roundtrip(bs)
        assert wire.batches_equal(batches, bs), f"trial {trial}"


def test_varint_int_columns_and_general_diffs():
    # small ints varint-pack; huge-magnitude ints fall back to raw
    b = DiffBatch(
        np.arange(1000, dtype=np.uint64),
        np.array([3] * 999 + [-7], np.int64),  # non-±1, non-const diffs
        {
            "small": np.arange(-500, 500, dtype=np.int64),
            "huge": np.full(1000, -(2**62), dtype=np.int64),
            "u16": np.arange(1000, dtype=np.uint16),
        },
    )
    batches, body, _ = _roundtrip([b])
    assert wire.batches_equal(batches, [b])
    # key-heavy lossless tier: ≥3× fewer bytes than pickle
    narrow = DiffBatch(
        np.arange(10_000, dtype=np.uint64) * np.uint64(7),
        np.ones(10_000, np.int64),
        {"count": np.arange(10_000, dtype=np.int64) % 100},
    )
    body, _ = wire.encode_frame(
        ("data", 0, "c", 0, [narrow], None), "codec", None
    )
    praw = len(pickle.dumps(("data", 0, "c", 0, [narrow], None)))
    assert praw / len(body) >= 3.0, (praw, len(body))


# --- quantization (opt-in lossy tier) --------------------------------------


def test_quant_off_by_default_floats_bit_exact():
    rng = np.random.default_rng(6)
    vals = rng.normal(size=200)
    vals[:3] = (np.inf, -np.inf, np.nan)
    b = DiffBatch(
        np.arange(200, dtype=np.uint64),
        np.ones(200, np.int64),
        {"f": vals, "f32": vals.astype(np.float32)},
    )
    batches, _body, _ = _roundtrip([b])  # quant=None
    assert wire.batches_equal(batches, [b])


def test_quant_bf16_lossy_floats_lossless_everything_else():
    rng = np.random.default_rng(7)
    n = 256
    b = DiffBatch(
        rng.integers(0, 2**64, n, dtype=np.uint64),
        rng.choice([1, -1], n).astype(np.int64),
        {
            "f": rng.normal(size=n),
            "i": rng.integers(-(2**40), 2**40, n).astype(np.int64),
        },
    )
    batches, _body, _ = _roundtrip([b], quant="bf16")
    out = batches[0]
    assert np.array_equal(out.keys, b.keys)  # keys NEVER quantized
    assert np.array_equal(out.diffs, b.diffs)  # diffs NEVER quantized
    assert np.array_equal(out.columns["i"], b.columns["i"])  # ints lossless
    f = out.columns["f"]
    assert f.dtype == np.float64  # dtype restored
    assert not np.array_equal(f, b.columns["f"])  # actually lossy
    assert np.allclose(f, b.columns["f"], rtol=1e-2)  # bf16 tolerance


def test_quant_bf16_specials_survive():
    vals = np.array([np.inf, -np.inf, np.nan, 0.0, -0.0, 1.0], np.float32)
    b = DiffBatch(
        np.arange(6, dtype=np.uint64), np.ones(6, np.int64), {"f": vals}
    )
    out = _roundtrip([b], quant="bf16")[0][0].columns["f"]
    assert np.isinf(out[0]) and out[0] > 0
    assert np.isinf(out[1]) and out[1] < 0
    assert np.isnan(out[2])
    assert out[3] == 0.0 and out[5] == 1.0


def test_quant_int8_blockwise_and_nonfinite_fallback():
    rng = np.random.default_rng(8)
    n = 3000  # spans multiple 1024 blocks with uneven tail
    vals = rng.normal(size=n).astype(np.float32) * 10
    b = DiffBatch(
        np.arange(n, dtype=np.uint64), np.ones(n, np.int64), {"f": vals}
    )
    out = _roundtrip([b], quant="int8")[0][0].columns["f"]
    assert out.dtype == np.float32
    scale = np.abs(vals).max() / 127
    assert np.abs(out - vals).max() <= scale * 1.01
    # non-finite data refuses the absmax scale: lossless fallback
    vals2 = vals.copy()
    vals2[7] = np.nan
    b2 = DiffBatch(
        np.arange(n, dtype=np.uint64), np.ones(n, np.int64), {"f": vals2}
    )
    out2 = _roundtrip([b2], quant="int8")[0][0].columns["f"]
    assert np.array_equal(out2, vals2, equal_nan=True)


def test_quant_embedding_column_bf16():
    rng = np.random.default_rng(9)
    n, dim = 32, 24
    emb = np.empty(n, dtype=object)
    for i in range(n):
        emb[i] = rng.normal(size=dim).astype(np.float32)
    b = DiffBatch(
        np.arange(n, dtype=np.uint64), np.ones(n, np.int64), {"emb": emb}
    )
    lossless_body, _ = wire.encode_frame(
        ("data", 0, "c", 0, [b], None), "codec", None
    )
    body, _ = wire.encode_frame(
        ("data", 0, "c", 0, [b], None), "codec", "bf16"
    )
    assert len(body) < len(lossless_body)
    out = wire.decode_frame(body)[4][0].columns["emb"]
    for i in range(n):
        assert out[i].dtype == np.float32 and out[i].shape == (dim,)
        assert np.allclose(out[i], emb[i], rtol=1e-2)


# --- frame-level behaviors -------------------------------------------------


def test_non_batch_payloads_stay_pickled():
    for frame in [
        ("bar", 1, 4, ("tick", 9), None),
        ("data", 0, "sc", 2, {"scalar": 1}, None),
        ("data", 0, "sc", 2, [1, 2, 3], None),  # list, but not batches
    ]:
        body, stats = wire.encode_frame(frame, "codec", None)
        assert stats is None and body[:1] == wire.FRAME_PICKLE
        assert wire.decode_frame(body) == frame


def test_decode_rejects_garbage():
    with pytest.raises(wire.WireError):
        wire.decode_frame(b"Xjunk")
    with pytest.raises(Exception):
        wire.decode_frame(wire.FRAME_CODEC + b"\x99short")


# --- mesh integration: PWHX7 handshake + overlapped outbox -----------------


def _free_port_pair() -> int:
    import random

    for _ in range(50):
        base = random.randint(20000, 40000)
        ok = True
        for off in range(2):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", base + off))
            except OSError:
                ok = False
            finally:
                s.close()
            if not ok:
                break
        if ok:
            return base
    raise RuntimeError("no free port pair")


def test_dialer_fails_fast_on_version_reject(monkeypatch):
    """A PWHX peer speaking another version answers the hello with the
    explicit version-reject — the dialer must raise a clear
    HostMeshError immediately, not retry until the connect deadline."""
    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-vtest")
    base = _free_port_pair()
    # fake OLD acceptor on peer 1's port: nonce, read hello, send the
    # version-reject naming PWHX5
    lst = socket.socket()
    lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lst.bind(("127.0.0.1", base + 1))
    lst.listen(1)

    def fake_acceptor():
        conn, _ = lst.accept()
        conn.sendall(b"\x01" * hx._NONCE_LEN)
        hello = b""
        while len(hello) < len(hx._HELLO_MAGIC) + 8 + hx._MAC_LEN:
            chunk = conn.recv(64)
            if not chunk:
                break
            hello += chunk
        reject = hx._VREJECT_TAG + b"PWHX5"
        conn.sendall(reject + b"\x00" * (hx._MAC_LEN - len(reject)))
        time.sleep(0.5)
        conn.close()

    th = threading.Thread(target=fake_acceptor, daemon=True)
    th.start()
    t0 = time.monotonic()
    try:
        with pytest.raises(hx.HostMeshError, match="version mismatch"):
            hx.HostMesh(2, 0, base, connect_timeout=30.0)
    finally:
        lst.close()
    # fast fail: nowhere near the 30 s connect deadline
    assert time.monotonic() - t0 < 10.0


def test_acceptor_detects_old_dialer_and_aborts_own_dial(monkeypatch):
    """An AUTHENTICATED hello with an older PWHX magic gets the explicit
    version-reject blob naming OUR version, and — because a genuinely
    old build cannot parse that blob — the skew is recorded so our own
    dial loop toward that peer aborts fast with the version diagnosis
    instead of retrying into the connect deadline."""
    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-vtest2")
    base = _free_port_pair()
    err_holder: list = []

    def build():
        try:
            hx.HostMesh(2, 0, base, connect_timeout=30.0)
        except Exception as e:
            err_holder.append(e)

    th = threading.Thread(target=build, daemon=True)
    th.start()
    time.sleep(0.3)  # listener is up before the constructor's dial wait
    t0 = time.monotonic()
    dialer = socket.create_connection(("127.0.0.1", base), timeout=5)
    dialer.settimeout(5)
    nonce = b""
    while len(nonce) < hx._NONCE_LEN:
        nonce += dialer.recv(hx._NONCE_LEN - len(nonce))
    hello = b"PWHX5" + struct.pack("<ii", 1, 0)
    key = hx._job_key()
    dialer.sendall(
        hello + hmac.new(key, hello + nonce, "sha256").digest()
    )
    resp = b""
    while len(resp) < hx._MAC_LEN:
        chunk = dialer.recv(hx._MAC_LEN - len(resp))
        if not chunk:
            break
        resp += chunk
    dialer.close()
    assert resp[: len(hx._VREJECT_TAG)] == hx._VREJECT_TAG
    assert hx._HELLO_MAGIC in resp
    th.join(20)
    assert err_holder, "constructor should have aborted on version skew"
    assert isinstance(err_holder[0], hx.HostMeshError)
    assert "version mismatch" in str(err_holder[0])
    assert time.monotonic() - t0 < 15.0  # nowhere near the 30 s deadline


def test_unauthenticated_old_hello_cannot_plant_version_skew(monkeypatch):
    """A prober without the job secret sending an old-version hello must
    NOT be able to abort the mesh construction (that would be an
    off-path job-kill primitive); it gets the PWVN blob and nothing
    else happens."""
    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-vtest3")
    base = _free_port_pair()
    holder: list = []

    def build():
        try:
            holder.append(hx.HostMesh(2, 0, base, connect_timeout=6.0))
        except Exception as e:
            holder.append(e)

    th = threading.Thread(target=build, daemon=True)
    th.start()
    time.sleep(0.3)
    rogue = socket.create_connection(("127.0.0.1", base), timeout=5)
    rogue.settimeout(5)
    nonce = b""
    while len(nonce) < hx._NONCE_LEN:
        nonce += rogue.recv(hx._NONCE_LEN - len(nonce))
    hello = b"PWHX5" + struct.pack("<ii", 1, 0)
    rogue.sendall(hello + b"\x00" * hx._MAC_LEN)  # garbage MAC
    rogue.close()
    th.join(20)
    # the construction failed on the (absent) peer-1 connect timeout,
    # NOT on a forged version skew
    assert holder and isinstance(holder[0], hx.HostMeshError)
    assert "version mismatch" not in str(holder[0])


def _mesh_pair(base):
    from pathway_tpu.parallel import host_exchange as hx

    meshes = [None, None]

    def build(pid):
        meshes[pid] = hx.HostMesh(2, pid, base, connect_timeout=30.0)

    ts = [threading.Thread(target=build, args=(p,)) for p in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(60)
    assert meshes[0] is not None and meshes[1] is not None
    return meshes


def test_outbox_overlapped_sends_preserve_order(monkeypatch):
    """Many enqueued frames arrive complete and in order through the
    sender threads (MAC seq numbers would kill the link otherwise)."""
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-outbox")
    monkeypatch.setenv("PATHWAY_DCN_OUTBOX", "4")  # force backpressure
    m0, m1 = _mesh_pair(_free_port_pair())
    try:
        rng = np.random.default_rng(10)
        sent = []
        for t in range(40):
            b = _rand_batch(rng, 50, with_obj=False)
            sent.append(b)
            m0.send(1, "ch", t, [b])
        for t in range(40):
            got = m1.gather("ch", t, timeout=30)
            assert wire.batches_equal(got[0], [sent[t]])
        # codec actually used: the per-channel ratio gauge exists
        from pathway_tpu.observability import REGISTRY

        g = REGISTRY.get("pathway_host_exchange_compression_ratio")
        assert g is not None
        assert g.labels("ch").current() > 1.0
    finally:
        m0.close()
        m1.close()


def test_close_flushes_queued_frames(monkeypatch):
    """close() must deliver frames still sitting in the outbox (the
    stop sentinel queues BEHIND them) — dropping a queued barrier/data
    frame would make the peer see a spurious dead-peer EOF."""
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-flush")
    monkeypatch.setenv("PATHWAY_DCN_OUTBOX", "1")
    m0, m1 = _mesh_pair(_free_port_pair())
    try:
        b = _rand_batch(np.random.default_rng(12), 10, with_obj=False)
        m0.send(1, "last", 0, [b])
        m0.close()  # frame may still be queued; close must flush it
        got = m1.gather("last", 0, timeout=20)
        assert wire.batches_equal(got[0], [b])
    finally:
        m1.close()


def test_dead_peer_fails_stop_via_barrier(monkeypatch):
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-dead")
    m0, m1 = _mesh_pair(_free_port_pair())
    from pathway_tpu.parallel import host_exchange as hx

    m1.close()
    with pytest.raises(hx.HostMeshError):
        m0.barrier("x", timeout=20.0)
    m0.close()


def test_pickle_wire_knob(monkeypatch):
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-pkl")
    monkeypatch.setenv("PATHWAY_DCN_WIRE", "pickle")
    m0, m1 = _mesh_pair(_free_port_pair())
    try:
        assert m0.wire_format == "pickle"
        b = _rand_batch(np.random.default_rng(11), 20)
        m0.send(1, "ch", 0, [b])
        got = m1.gather("ch", 0, timeout=30)
        assert wire.batches_equal(got[0], [b])
    finally:
        m0.close()
        m1.close()


def test_bad_wire_knob_rejected(monkeypatch):
    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-bad")
    monkeypatch.setenv("PATHWAY_DCN_WIRE", "zstd")
    with pytest.raises(hx.HostMeshError, match="PATHWAY_DCN_WIRE"):
        hx.HostMesh(2, 0, _free_port_pair())
    monkeypatch.delenv("PATHWAY_DCN_WIRE")
    monkeypatch.setenv("PATHWAY_DCN_QUANT", "fp4")
    with pytest.raises(hx.HostMeshError, match="PATHWAY_DCN_QUANT"):
        hx.HostMesh(2, 0, _free_port_pair())


# --- receive-side decode pool (wide fan-in long tail) ----------------------


def test_decode_pool_roundtrip_many_channels(monkeypatch):
    """With the decode pool forced on, data frames and barriers still
    deliver completely and correctly: delivery slots are keyed
    (channel, tick, src), so unordered pool decode cannot corrupt a
    gather."""
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-decode-pool")
    monkeypatch.setenv("PATHWAY_DCN_DECODE_POOL", "3")
    m0, m1 = _mesh_pair(_free_port_pair())
    try:
        assert m0._decode_pool is not None
        rng = np.random.default_rng(21)
        sent = {}
        for t in range(30):
            ch = f"ch{t % 3}"
            b = _rand_batch(rng, 40, with_obj=False)
            sent[(ch, t)] = b
            m0.send(1, ch, t, [b])
        for (ch, t), b in sent.items():
            got = m1.gather(ch, t, timeout=30)
            assert wire.batches_equal(got[0], [b])
        # barriers ride the pool too
        import threading as _threading

        res = {}

        def bar(m, key):
            res[key] = m.barrier(key, timeout=30)

        ts = [
            _threading.Thread(target=bar, args=(m, k))
            for m, k in ((m0, "a"), (m1, "b"))
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert res["a"] == {0: "a", 1: "b"}
        assert res["b"] == {0: "a", 1: "b"}
    finally:
        m0.close()
        m1.close()


def test_decode_pool_auto_off_for_narrow_fanin(monkeypatch):
    """Default auto mode keeps a 2-process mesh on inline decode (each
    peer already has a dedicated reader; the pool only pays off on
    wide fan-ins)."""
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-decode-auto")
    monkeypatch.delenv("PATHWAY_DCN_DECODE_POOL", raising=False)
    m0, m1 = _mesh_pair(_free_port_pair())
    try:
        assert m0._decode_pool is None
        assert m1._decode_pool is None
    finally:
        m0.close()
        m1.close()


def test_decode_pool_bad_knob_rejected(monkeypatch):
    from pathway_tpu.parallel import host_exchange as hx

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "wire-decode-bad")
    monkeypatch.setenv("PATHWAY_DCN_DECODE_POOL", "many")
    with pytest.raises(hx.HostMeshError, match="PATHWAY_DCN_DECODE_POOL"):
        hx.HostMesh(2, 0, _free_port_pair())
