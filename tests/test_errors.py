"""Error model: ERROR poison propagation, fill_error, error log tables
(reference: python/pathway/tests/test_errors.py, 1,493 LoC — representative
coverage; engine model src/engine/error.rs Value::Error)."""

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts
from pathway_tpu.internals.api import ERROR


def test_division_by_zero_poisons_row_not_run():
    t = T(
        """
        a | b
        6 | 2
        5 | 0
        8 | 4
        """
    )
    res = t.select(q=t.a // t.b)
    _k, cols = table_to_dicts(res)
    vals = sorted(cols["q"].values(), key=lambda v: repr(v))
    assert ERROR in vals
    assert 3 in vals and 2 in vals


def test_fill_error_replaces_poison():
    t = T(
        """
        a | b
        6 | 2
        5 | 0
        """
    )
    res = t.select(q=pw.fill_error(t.a // t.b, -1))
    _k, cols = table_to_dicts(res)
    assert sorted(cols["q"].values()) == [-1, 3]


def test_error_in_udf_poisons():
    @pw.udf
    def boom(x: int) -> int:
        if x == 2:
            raise RuntimeError("nope")
        return x * 10

    t = T(
        """
        v
        1
        2
        3
        """
    )
    res = t.select(out=boom(t.v))
    _k, cols = table_to_dicts(res)
    vals = list(cols["out"].values())
    assert ERROR in vals
    assert 10 in vals and 30 in vals


def test_global_error_log_records():
    from pathway_tpu.internals.errors import clear_errors, peek_errors

    clear_errors()
    t = T(
        """
        a | b
        5 | 0
        """
    )
    res = t.select(q=t.a // t.b)
    table_to_dicts(res)
    errs = peek_errors()
    assert errs, "expected a recorded error"
    assert any("zero" in e["message"].lower() for e in errs)


def test_error_poison_flows_through_groupby():
    t = T(
        """
        g | a | b
        x | 6 | 2
        x | 5 | 0
        y | 8 | 4
        """
    )
    poisoned = t.select(t.g, q=t.a // t.b)
    # _skip_errors=False: an ERROR arg poisons the aggregate while present
    # (the reference's propagate mode; the default SKIPS error args)
    res = poisoned.groupby(poisoned.g, _skip_errors=False).reduce(
        poisoned.g, total=pw.reducers.sum(poisoned.q)
    )
    _k, cols = table_to_dicts(res)
    got = {cols["g"][k]: cols["total"][k] for k in cols["g"]}
    # y is clean; x contains a poisoned row -> aggregate poisons
    assert got["y"] == 2
    assert got["x"] is ERROR
    # default mode: error args skipped, aggregate over clean rows
    pw.internals.parse_graph.G.clear()
    t2 = T(
        """
        g | a | b
        x | 6 | 2
        x | 5 | 0
        y | 8 | 4
        """
    )
    p2 = t2.select(t2.g, q=t2.a // t2.b)
    res2 = p2.groupby(p2.g).reduce(p2.g, total=pw.reducers.sum(p2.q))
    _k2, cols2 = table_to_dicts(res2)
    got2 = {cols2["g"][k]: cols2["total"][k] for k in cols2["g"]}
    assert got2 == {"x": 3, "y": 2}


def test_retracting_poisoned_row_unpoisons_aggregate():
    """A streaming correction of a bad row restores the aggregate
    (review regression: poison must be retractable, not sticky)."""
    t = T(
        """
          | g | a | b | __time__ | __diff__
        1 | x | 6 | 2 | 2        | 1
        2 | x | 5 | 0 | 2        | 1
        2 | x | 5 | 0 | 4        | -1
        3 | x | 4 | 2 | 4        | 1
        """
    )
    poisoned = t.select(t.g, q=t.a // t.b)
    res = poisoned.groupby(poisoned.g).reduce(
        poisoned.g, total=pw.reducers.sum(poisoned.q)
    )
    _k, cols = table_to_dicts(res)
    assert list(cols["total"].values()) == [5]


def test_comparison_with_error_stays_error():
    t = T(
        """
        a | b
        5 | 0
        """
    )
    res = t.select(flag=pw.fill_error((t.a // t.b) > 2, False))
    _k, cols = table_to_dicts(res)
    assert list(cols["flag"].values()) == [False]
