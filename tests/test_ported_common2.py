"""More ported reference core tests (reference:
python/pathway/tests/test_common.py — set ops, concat, flatten, filter,
from_columns, if_else/coalesce, update_rows/cells, groupby variants,
join composition)."""

import numpy as np
import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T, table_from_pandas
from ref_utils import assert_table_equality, assert_table_equality_wo_index


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    yield
    pw.internals.parse_graph.G.clear()


def test_intersect():
    t1 = T(
        """
            | col
        1   | 11
        2   | 12
        3   | 13
        """
    )
    t2 = T(
        """
            | col
        2   | 11
        3   | 11
        4   | 11
        """
    )
    assert_table_equality(
        t1.intersect(t2),
        T(
            """
                | col
            2   | 12
            3   | 13
            """
        ),
    )


def test_intersect_empty():
    t1 = T(
        """
            | col
        1   | 11
        2   | 12
        3   | 13
        """
    )
    ret = t1.intersect()
    assert_table_equality(ret, t1)


def test_intersect_many_tables():
    t1 = T(
        """
            | col
        1   | 11
        2   | 12
        3   | 13
        4   | 14
        """
    )
    t2 = T(
        """
            | col
        2   | 11
        3   | 11
        4   | 11
        5   | 11
        """
    )
    t3 = T(
        """
            | col
        1   | 11
        3   | 11
        4   | 11
        5   | 11
        """
    )
    assert_table_equality(
        t1.intersect(t2, t3),
        T(
            """
                | col
            3   | 13
            4   | 14
            """
        ),
    )


def test_difference():
    t1 = T(
        """
            | col
        1   | 11
        2   | 12
        3   | 13
        """
    )
    t2 = T(
        """
            | col
        2   | 11
        3   | 11
        4   | 11
        """
    )
    assert_table_equality(
        t1.difference(t2),
        T(
            """
                | col
            1   | 11
            """
        ),
    )


def test_concat():
    t1 = T(
        """
    lower | upper
    a     | A
    b     | B
    """
    )
    t2 = T(
        """
    lower | upper
    c     | C
    """
    )
    res = pw.Table.concat_reindex(t1, t2)
    expected = T(
        """
    lower | upper
    a     | A
    b     | B
    c     | C
        """,
    )
    assert_table_equality_wo_index(res, expected)


def test_concat_unsafe():
    t1 = T(
        """
       | lower | upper
    1  | a     | A
    2  | b     | B
    """
    )
    t2 = T(
        """
       | lower | upper
    3  | c     | C
    """
    )
    pw.universes.promise_are_pairwise_disjoint(t1, t2)
    res = pw.Table.concat(t1, t2)
    expected = T(
        """
       | lower | upper
    1  | a     | A
    2  | b     | B
    3  | c     | C
        """,
    )
    assert_table_equality(res, expected)


@pytest.mark.parametrize("dtype", [np.int64, np.float64])
def test_flatten(dtype):
    df = pd.DataFrame(
        {
            "array": [
                np.array([1, 2], dtype=dtype),
                np.array([], dtype=dtype),
                np.array([3, 4], dtype=dtype),
                np.array([10, 11, 12], dtype=dtype),
                np.array([4, 5, 6, 1, 2], dtype=dtype),
            ],
            "other": [-1, -2, -3, -4, -5],
        }
    )
    expected_df = pd.DataFrame(
        {
            "array": np.array(
                [1, 2, 3, 4, 10, 11, 12, 4, 5, 6, 1, 2], dtype=dtype
            ),
            "other": [-1, -1, -3, -3, -4, -4, -4, -5, -5, -5, -5, -5],
        }
    )
    t1 = table_from_pandas(df)
    t1 = t1.flatten(t1.array)
    expected = table_from_pandas(expected_df)
    assert_table_equality_wo_index(t1, expected)


def test_filter():
    t_latin = T(
        """
            | lower | upper
        1  | a     | A
        2  | b     | B
        26 | z     | Z
        """
    )
    t_tmp = T(
        """
            | bool
        1   | True
        2   | True
        26  | False
        """
    )
    res = t_latin.filter(t_tmp["bool"])
    assert_table_equality(
        res,
        T(
            """
                | lower | upper
            1  | a     | A
            2  | b     | B
            """
        ),
    )


def test_from_columns():
    first = T(
        """
    pet | owner | age
     1  | Alice | 10
     1  | Bob   | 9
     2  | Alice | 8
    """
    )
    second = T(
        """
    foo | aux | baz
    a   | 70  | a
    b   | 80  | c
    c   | 90  | b
    """
    )
    expected = T(
        """
    pet | foo
    1   | a
    1   | b
    2   | c
        """
    )
    assert_table_equality(
        pw.Table.from_columns(first.pet, second.foo), expected
    )


def test_if_else_int_float():
    table = T(
        """
        a |  b
        1 | 1.2
        2 | 2.3
        3 | 3.4
        4 | 4.5
        """
    )
    expected = T(
        """
        res
        1.3
        2.4
        3.1
        4.1
    """
    )
    ret = table.select(
        res=pw.if_else(pw.this.a > 2, pw.this.a, pw.this.b) + 0.1
    )
    assert_table_equality_wo_index(ret, expected)


def test_if_else_optional_int_float():
    table = T(
        """
          | a |  b  | c
        1 | 1 | 1.2 | False
        2 | 2 | 2.3 | False
        3 | 3 | 3.4 | True
        4 |   | 4.5 | True
    """
    )
    expected = T(
        """
          | res
        1 | 1.2
        2 | 2.3
        3 | 3.0
        4 |
    """
    )
    ret = table.select(res=pw.if_else(pw.this.c, pw.this.a, pw.this.b))
    assert_table_equality(ret, expected)


def test_coalesce_optional_int_float():
    table = T(
        """
          | a |  b
        1 | 1 | 1.2
        2 |   | 2.3
        3 | 3 | 3.4
        4 |   | 4.5
    """
    )
    expected = T(
        """
          | res
        1 | 1.5
        2 | 2.8
        3 | 3.5
        4 | 5.0
    """
    )
    ret = table.select(res=pw.coalesce(pw.this.a, pw.this.b) + 0.5)
    assert_table_equality(ret, expected)


def test_update_rows():
    old = T(
        """
            | pet  |  owner  | age
        1   |  1   | Alice   | 10
        2   |  1   | Bob     | 9
        3   |  2   | Alice   | 8
        4   |  1   | Bob     | 7
        """
    )
    update = T(
        """
            | pet |  owner  | age
        1   | 7   | Bob     | 11
        5   | 0   | Eve     | 10
        """
    )
    expected = T(
        """
            | pet  |  owner  | age
        1   |  7   | Bob     | 11
        2   |  1   | Bob     | 9
        3   |  2   | Alice   | 8
        4   |  1   | Bob     | 7
        5   |  0   | Eve     | 10
        """
    )
    new = old.update_rows(update)
    assert_table_equality(new, expected)


def test_update_cells():
    old = T(
        """
            | pet  |  owner  | age
        1   |  1   | Alice   | 10
        2   |  1   | Bob     | 9
        3   |  2   | Alice   | 8
        4   |  1   | Bob     | 7
        """
    )
    update = T(
        """
            | owner  | age
        1   | Eve    | 10
        4   | Eve    | 3
        """
    )
    expected = T(
        """
            | pet  |  owner  | age
        1   |  1   | Eve     | 10
        2   |  1   | Bob     | 9
        3   |  2   | Alice   | 8
        4   |  1   | Eve     | 3
        """
    )
    pw.universes.promise_is_subset_of(update, old)
    new = old.update_cells(update)
    assert_table_equality(new, expected)
    assert_table_equality(old << update, expected)


def test_groupby_instance():
    t = T(
        """
        a | b | col
        0 | 0 |   1
        0 | 0 |   2
        1 | 0 |   3
        1 | 0 |   4
        0 | 1 |   5
        0 | 1 |   6
        """
    )
    expected = T(
        """
        a | b | col
        0 | 0 |   3
        1 | 0 |   7
        0 | 1 |  11
        """
    ).with_id_from(pw.this.b, instance=pw.this.a)
    res = t.groupby(pw.this.b, instance=pw.this.a).reduce(
        pw.this.a, pw.this.b, col=pw.reducers.sum(pw.this.col)
    )
    assert_table_equality(res, expected)


def test_groupby_setid():
    left = T(
        """
      | pet  |  owner  | age
    1 |  1   | Alice   | 10
    2 |  1   | Bob     | 9
    3 |  2   | Alice   | 8
    4 |  1   | Bob     | 7
    """
    ).with_columns(pet=pw.this.pointer_from(pw.this.pet))
    res = left.groupby(id=left.pet).reduce(
        left.pet,
        agesum=pw.reducers.sum(left.age),
    )
    expected = T(
        """
          | pet | agesum
        1 | 1   | 26
        2 | 2   | 8
        """
    ).with_columns(pet=left.pointer_from(pw.this.pet))
    assert_table_equality(res, expected)


def test_join_filter_1():
    left = T(
        """
            val
            10
            11
            12
        """
    )
    right = T(
        """
            val
            10
            11
            12
        """,
    )
    joined = (
        left.join(right)
        .filter(pw.left.val < pw.right.val)
        .select(left_val=pw.left.val, right_val=pw.right.val)
    )
    assert_table_equality_wo_index(
        joined,
        T(
            """
            left_val | right_val
                  10 |        11
                  10 |        12
                  11 |        12
            """
        ),
    )


def test_join_groupby_1():
    left = T(
        """
            a  | lcol
            10 |    1
            11 |    1
            12 |    2
            13 |    2
        """
    )
    right = T(
        """
            b  | rcol
            11 |    1
            12 |    1
            13 |    2
            14 |    2
        """,
    )
    result = (
        left.join(right)
        .groupby(pw.this.lcol, pw.this.rcol)
        .reduce(
            pw.this.lcol,
            pw.this.rcol,
            res=pw.reducers.sum(pw.this.a * pw.this.b),
        )
    )
    expected = T(
        f"""
    lcol | rcol | res
       1 |    1 | {(10 + 11) * (11 + 12)}
       1 |    2 | {(10 + 11) * (13 + 14)}
       2 |    1 | {(12 + 13) * (11 + 12)}
       2 |    2 | {(12 + 13) * (13 + 14)}
    """
    )
    assert_table_equality_wo_index(result, expected)


def test_apply_more_args():
    a = T(
        """
        foo
        1
        2
        3
        """
    )
    b = T(
        """
        bar
        2
        -1
        4
        """
    )

    def add(x: int, y: int) -> int:
        return x + y

    result = a.select(ret=pw.apply(add, x=a.foo, y=b.bar))
    assert_table_equality(
        result,
        T(
            """
            ret
            3
            1
            7
            """
        ),
    )


def test_apply_consts():
    a = T(
        """
        foo
        1
        2
        3
        """
    )

    def inc(x: int) -> int:
        return x + 1

    result = a.select(ret=pw.apply(inc, 1))
    assert_table_equality(
        result,
        T(
            """
            ret
            2
            2
            2
            """
        ),
    )


def test_apply_async():
    import asyncio

    async def inc(a: int) -> int:
        await asyncio.sleep(0.1)
        return a + 1

    input = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        """
    )
    result = input.select(ret=pw.apply_async(inc, pw.this.a))
    assert_table_equality(
        result,
        T(
            """
            ret
            2
            3
            4
            """,
        ),
    )


def test_apply_async_more_args():
    import asyncio

    async def add(a: int, b: int, *, c: int) -> int:
        await asyncio.sleep(0.1)
        return a + b + c

    input = pw.debug.table_from_markdown(
        """
        a | b  | c
        1 | 10 | 100
        2 | 20 | 200
        3 | 30 | 300
        """
    )
    result = input.select(
        ret=pw.apply_async(add, pw.this.a, pw.this.b, c=pw.this.c)
    )
    assert_table_equality(
        result,
        T(
            """
            ret
            111
            222
            333
            """,
        ),
    )


@pytest.mark.parametrize("limit", [2, 10])
def test_iterate_with_limit(limit):
    def iteration_step(iterated):
        iterated = iterated.select(foo=iterated.foo + 1)
        return iterated

    ret = pw.iterate(
        iteration_step,
        iteration_limit=limit,
        iterated=T(
            """
                | foo
            1   | 0
            """
        ),
    )
    expected_ret = T(
        f"""
            | foo
        1   | {limit}
        """
    )
    assert_table_equality(ret, expected_ret)


def test_join_chain_1():
    edges1 = T(
        """
        u | v
        a | b
        b | c
        c | d
        d | e
        e | f
        f | g
        g | a
    """
    )
    edges2 = edges1.copy()
    edges3 = edges1.copy()
    path3 = (
        edges1.join(edges2, edges1.v == edges2.u)
        .join(edges3, edges2.v == edges3.u)
        .select(edges1.u, edges3.v)
    )
    assert_table_equality_wo_index(
        path3,
        T(
            """
        u | v
        a | d
        b | e
        c | f
        d | g
        e | a
        f | b
        g | c
        """
        ),
    )


def test_join_chain_2():
    edges1 = T(
        """
        u | v
        a | b
        b | c
        c | d
        d | e
        e | f
        f | g
        g | a
    """
    )
    edges2 = edges1.copy()
    edges3 = edges1.copy()
    path3 = edges1.join(
        edges2.join(edges3, edges2.v == edges3.u), edges1.v == edges2.u
    ).select(edges1.u, edges3.v)
    assert_table_equality_wo_index(
        path3,
        T(
            """
        u | v
        a | d
        b | e
        c | f
        d | g
        e | a
        f | b
        g | c
        """
        ),
    )


def test_join_leftrightthis():
    left_table = T(
        """
           | a | b | c
        1  | 1 | 2 | 3
        """
    )
    right_table = T(
        """
           | b | c | d
        1  | 2 | 3 | 4
        """
    )
    assert_table_equality_wo_index(
        left_table.join(right_table, pw.left.b == pw.right.b).select(
            pw.left.a, pw.this.b, pw.right.c, pw.right.d
        ),
        T(
            """
        a | b | c | d
        1 | 2 | 3 | 4
        """
        ),
    )
    with pytest.raises(KeyError):
        left_table.join(right_table, pw.left.b == pw.right.b).select(
            pw.this.c
        )


def test_any():
    left = T(
        """
    pet  |  owner  | age
    dog  | Bob     | 10
    cat  | Alice   | 9
    cat  | Alice   | 8
    dog  | Bob     | 7
    foo  | Charlie | 6
    """
    )
    left_res = left.reduce(
        pw.reducers.any(left.pet),
        pw.reducers.any(left.owner),
        pw.reducers.any(left.age),
    )
    joined = left.join(
        left_res,
        left.pet == left_res.pet,
        left.owner == left_res.owner,
        left.age == left_res.age,
    ).reduce(cnt=pw.reducers.count())
    assert_table_equality_wo_index(
        joined,
        T(
            """
    cnt
    1
    """
        ),
    )


def test_wildcard_basic_usage():
    tab1 = T(
        """
           | a | b
        1  | 1 | 2
        """
    )
    tab2 = T(
        """
           | c | d
        1  | 3 | 4
        """
    )
    left = tab1.select(*tab1, *tab2)
    right = tab1.select(tab1.a, tab1.b, tab2.c, tab2.d)
    assert_table_equality(left, right)


def test_wildcard_shadowing():
    tab = T(
        """
           | a | b | c | d
        1  | 1 | 2 | 3 | 4
        """
    )
    left = tab.select(*tab.without(tab.a, "b"), e=pw.this.a)
    right = tab.select(tab.c, tab.d, e=tab.a)
    assert_table_equality(left, right)


def test_rename_columns_1():
    old = T(
        """
    pet  |  owner  | age
     1   | Alice   | 10
     1   | Bob     | 9
    """
    )
    expected = T(
        """
    owner   | animal | winters
    Alice   |  1     | 10
    Bob     |  1     | 9
    """
    )
    new = old.rename_columns(animal=old.pet, winters=old.age)
    assert_table_equality(new, expected)


def test_rename_by_dict():
    old = T(
        """
    t0  |  t1  | t2
     1   | Alice   | 10
     1   | Bob     | 9
    """
    )
    expected = T(
        """
    col_0  | col_1   | col_2
       1   | Alice   | 10
       1   | Bob     | 9
    """
    )
    new = old.rename_by_dict({f"t{i}": f"col_{i}" for i in range(3)})
    assert_table_equality(new, expected)


def test_with_columns():
    old = T(
        """
            | pet | owner | age
        1   |  1  | Alice | 10
        2   |  1  | Bob   | 9
        3   |  2  | Alice | 8
        """
    )
    update = T(
        """
            | owner | age | weight
        1   | Bob   | 11  | 7
        2   | Eve   | 10  | 11
        3   | Eve   | 15  | 13
        """
    )
    expected = T(
        """
            | pet | owner | age | weight
        1   | 1   | Bob   | 11  | 7
        2   | 1   | Eve   | 10  | 11
        3   | 2   | Eve   | 15  | 13
        """
    )
    new = old.with_columns(*update)
    assert_table_equality(new, expected)


def test_ix_ref_with_primary_keys():
    indexed_table = T(
        """
    colA   | colB
    10     | A
    20     | B
    """
    )
    indexed_table = indexed_table.with_id_from(pw.this.colB)
    tested_table = T(
        """
    colC
    10
    20
    """
    )
    returned = tested_table.select(
        *pw.this, new_value=indexed_table.ix_ref("A").colA
    )
    expected = T(
        """
    colC   | new_value
    10     | 10
    20     | 10
    """
    )
    assert_table_equality(returned, expected)


def test_groupby_ix_this():
    left = T(
        """
    pet  |  owner  | age
    dog  | Alice   | 10
    dog  | Bob     | 9
    cat  | Alice   | 8
    cat  | Bob     | 7
    """
    )
    res = left.groupby(left.pet).reduce(
        age=pw.reducers.max(pw.this.age),
        owner=pw.this.ix(pw.reducers.argmax(pw.this.age)).owner,
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
        age | owner
        10  | Alice
        8   | Alice
    """
        ),
    )


def test_join_foreign_col():
    left = T(
        """
           | a
        1  | 1
        2  | 2
        3  | 3
        """
    )
    right = T(
        """
           | b
        0  | baz
        1  | foo
        2  | bar
        """
    )
    joiner = left.join(right, left.id == right.id)
    t1 = joiner.select(col=left.a * 2)
    t2 = joiner.select(col=left.a + t1.col)
    assert_table_equality_wo_index(
        t2,
        T(
            """
                | col
            1   | 3
            2   | 6
            """
        ),
    )


def test_cast_optional():
    from typing import Optional

    tab = T(
        """
          | a
        1 | 1
        2 |
        3 | 1
        """
    )
    ret = tab.select(a=pw.cast(Optional[float], pw.this.a))
    expected = T(
        """
          | a
        1 | 1.0
        2 |
        3 | 1.0
        """
    ).update_types(a=Optional[float])
    assert_table_equality(ret, expected)


def test_join_filter_2():
    tA = T(
        """
             a
            10
            11
            12
        """
    )
    tB = T(
        """
             b
            10
            11
            12
        """
    )
    tC = T(
        """
             c
            10
            11
            12
        """
    )
    tD = T(
        """
             d
            10
            11
            12
        """
    )
    result = (
        tA.join(tB)
        .filter(pw.this.a <= pw.this.b)
        .join(tC)
        .join(tD)
        .filter(pw.this.c <= pw.this.d)
        .filter(pw.this.a + pw.this.b == pw.this.c + pw.this.d)
        .select(*pw.this)
    )
    expected = T(
        """
 a  | b  | c  | d
 10 | 10 | 10 | 10
 10 | 11 | 10 | 11
 10 | 12 | 10 | 12
 10 | 12 | 11 | 11
 11 | 11 | 10 | 12
 11 | 11 | 11 | 11
 11 | 12 | 11 | 12
 12 | 12 | 12 | 12
        """
    )
    assert_table_equality_wo_index(result, expected)


def test_join_groupby_2():
    left = T(
        """
            a  |  col
            10 |    1
            11 |    1
            12 |    2
            13 |    2
        """
    )
    right = T(
        """
            b  |  col
            11 |    1
            12 |    1
            13 |    2
            14 |    2
        """,
    )
    result = (
        left.join(right, left.col == right.col)
        .groupby(pw.this.col)
        .reduce(pw.this.col, res=pw.reducers.sum(pw.this.a * pw.this.b))
    )
    expected = T(
        f"""
    col | res
      1 | {(10 + 11) * (11 + 12)}
      2 | {(12 + 13) * (13 + 14)}
    """
    )
    assert_table_equality_wo_index(result, expected)


def test_join_filter_reduce():
    left = T(
        """
            a
            10
            11
            12
        """
    )
    right = T(
        """
            b
            11
            12
            13
        """,
    )
    result = (
        left.join(right)
        .filter(pw.this.a >= pw.this.b)
        .reduce(col=pw.reducers.count())
    )
    expected = T(
        """
        col
        3
    """
    )
    assert_table_equality_wo_index(result, expected)


def test_groupby_ix():
    tab = T(
        """
        grouper | val | output
              0 |   1 |    abc
              0 |   2 |    def
              1 |   1 |    ghi
              1 |   2 |    jkl
              2 |   1 |    mno
              2 |   2 |    pqr
        """,
    ).with_columns(grouper=pw.this.pointer_from(pw.this.grouper))
    res = tab.groupby(id=tab.grouper).reduce(
        col=pw.reducers.argmax(tab.val),
        output=tab.ix(pw.reducers.argmax(tab.val), context=pw.this).output,
    )
    expected = T(
        """
        col | output
          1 | def
          3 | jkl
          5 | pqr
        """,
    ).with_columns(col=tab.pointer_from(pw.this.col))
    assert_table_equality(res, expected)
