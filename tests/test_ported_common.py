"""Ported core-semantics tests from the reference's
python/pathway/tests/test_common.py — the parity proof for expression
operators, indexing, concat/flatten/rename/filter/reindex and iterate."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import (
    assert_table_equality,
    assert_table_equality_wo_index,
)


def test_select_int_binary():
    input = T(
        """
        a | b
        1 | 2
        """
    )
    result = input.select(
        input.a,
        input.b,
        add=input.a + input.b,
        sub=input.a - input.b,
        truediv=input.a / input.b,
        floordiv=input.a // input.b,
        mul=input.a * input.b,
    )
    assert_table_equality(
        result,
        T(
            """
            a | b | add | sub | truediv | floordiv | mul
            1 | 2 | 3   | -1  | 0.5     | 0        | 2
            """
        ),
    )


def test_select_int_comparison():
    input = T(
        """
        a | b
        1 | 2
        2 | 2
        3 | 2
        """
    )
    result = input.select(
        input.a,
        input.b,
        eq=input.a == input.b,
        ne=input.a != input.b,
        lt=input.a < input.b,
        le=input.a <= input.b,
        gt=input.a > input.b,
        ge=input.a >= input.b,
    )
    assert_table_equality(
        result,
        T(
            """
            a | b | eq    | ne    | lt    | le    | gt    | ge
            1 | 2 | false | true  | true  | true  | false | false
            2 | 2 | true  | false | false | true  | false | true
            3 | 2 | false | true  | false | false | true  | true
            """
        ),
    )


def test_select_int_unary():
    input = T(
        """
        a
        1
        -2
        """
    )
    result = input.select(input.a, neg=-input.a)
    assert_table_equality(
        result,
        T(
            """
            a  | neg
            1  | -1
            -2 | 2
            """
        ),
    )


def test_select_bool_binary():
    input = T(
        """
        a     | b
        true  | true
        true  | false
        false | true
        false | false
        """
    )
    result = input.select(
        input.a,
        input.b,
        land=input.a & input.b,
        lor=input.a | input.b,
        lxor=input.a ^ input.b,
    )
    assert_table_equality(
        result,
        T(
            """
            a     | b     | land  | lor   | lxor
            true  | true  | true  | true  | false
            true  | false | false | true  | true
            false | true  | false | true  | true
            false | false | false | false | false
            """
        ),
    )


def test_broadcasting_singlerow():
    table = T(
        """
    pet  |  owner  | age
     1   | Alice   | 10
     1   | Bob     | 9
     2   | Alice   | 8
     1   | Bob     | 7
     0   | Eve     | 10
        """
    )
    row = table.reduce(val=1)
    returned = table.select(newval=row.ix_ref().val)
    expected = T(
        """
    newval
     1
     1
     1
     1
     1
        """
    )
    assert_table_equality_wo_index(returned, expected)


def test_indexing_single_value_groupby():
    indexed_table = T(
        """
    colA | colB
    1    | A
    2    | A
    10   | B
    20   | B
    """
    )
    grouped_table = indexed_table.groupby(pw.this.colB).reduce(
        pw.this.colB, sum=pw.reducers.sum(pw.this.colA)
    )
    returned = indexed_table.select(
        indexed_table.colB,
        sum=grouped_table.ix_ref(indexed_table.colB).sum,
    )
    assert_table_equality_wo_index(
        returned,
        T(
            """
        colB | sum
        A    | 3
        A    | 3
        B    | 30
        B    | 30
        """
        ),
    )


def test_ixref_optional():
    indexed_table = T(
        """
    colA  | colB | colC
    1     | A    | D
    2     | A    | D
    10    | A    | E
    20    | A    | E
    100   | B    | F
    200   | B    | F
    1000  | B    | G
    2000  | B    | G
    """
    )
    grouped_table = indexed_table.groupby(pw.this.colB, pw.this.colC).reduce(
        pw.this.colB, pw.this.colC, sum=pw.reducers.sum(pw.this.colA)
    )
    indexer = T(
        """
        refB | refC
        A    | D
        A    | E
        B    | F
        B    | G
             | D
        A    |
             |
        """
    )
    returned = indexer.select(
        *pw.this,
        sum=grouped_table.ix_ref(
            indexer.refB, indexer.refC, optional=True
        ).sum,
    )
    expected = T(
        """
    refB  | refC | sum
     A    | D    | 3
     A    | E    | 30
     B    | F    | 300
     B    | G    | 3000
          | D    |
     A    |      |
          |      |
    """
    )
    assert_table_equality_wo_index(returned, expected)


def test_concat_reversed_columns():
    t1 = T(
        """
        a | b
        1 | 2
        """
    )
    t2 = T(
        """
        b | a
        4 | 3
        """
    )
    result = pw.Table.concat_reindex(t1, t2)
    assert_table_equality_wo_index(
        result,
        T(
            """
            a | b
            1 | 2
            3 | 4
            """
        ),
    )


def test_flatten_multidimensional():
    t = T(
        """
        i
        0
        """
    ).select(a=pw.apply_with_type(lambda i: np.ones((2, 3)), np.ndarray, pw.this.i))
    flat = t.flatten(pw.this.a)
    _k, cols = pw.debug.table_to_dicts(flat)
    rows = list(cols["a"].values())
    assert len(rows) == 2
    assert all(r.shape == (3,) for r in rows)


def test_flatten_string():
    t = T(
        """
        s
        ab
        c
        """
    )
    flat = t.flatten(pw.this.s)
    _k, cols = pw.debug.table_to_dicts(flat)
    assert sorted(cols["s"].values()) == ["a", "b", "c"]


def test_flatten_explode():
    t = T(
        """
        a | n
        1 | 3
        2 | 0
        3 | 1
        """
    ).select(
        pw.this.a,
        rep=pw.apply_with_type(
            lambda a, n: tuple([a] * n), tuple, pw.this.a, pw.this.n
        ),
    )
    flat = t.flatten(pw.this.rep)
    _k, cols = pw.debug.table_to_dicts(flat)
    assert sorted(cols["rep"].values()) == [1, 1, 1, 3]


def test_rename_with_dict():
    t = T(
        """
        a | b
        1 | 2
        """
    )
    renamed = t.rename({"a": "c"})
    assert renamed.column_names() == ["c", "b"]


def test_drop_columns():
    t = T(
        """
        a | b | c
        1 | 2 | 3
        """
    )
    assert t.without(pw.this.a, "b").column_names() == ["c"]


def test_filter_no_columns():
    t = T(
        """
        a
        1
        2
        """
    )
    filtered = t.filter(pw.this.a > 1).select()
    _k, cols = pw.debug.table_to_dicts(filtered)
    assert len(_k) == 1 and cols == {}


def test_reindex():
    t = T(
        """
        a
        10
        20
        """
    )
    reindexed = t.with_id_from(pw.this.a)
    from pathway_tpu.internals.api import ref_scalar

    _k, cols = pw.debug.table_to_dicts(reindexed)
    assert set(_k) == {int(ref_scalar(10)), int(ref_scalar(20))}


def test_column_fixpoint():
    """Collatz-style iterate (reference: test_common.py:1442)."""

    def collatz_transformer(iterated):
        def collatz_step(x: float) -> float:
            if x == 1:
                return 1
            elif x % 2 == 0:
                return x / 2
            else:
                return 3 * x + 1

        return iterated.select(val=pw.apply(collatz_step, iterated.val))

    tab = T(
        """
        val
        1
        2
        3
        4
        5
        6
        7
        8
        """
    ).select(val=pw.cast(float, pw.this.val))
    ret = pw.iterate(collatz_transformer, iterated=tab)
    expected = tab.select(val=1.0)
    assert_table_equality_wo_index(ret, expected)


def test_update_cells():
    old = T(
        """
          | a | b
        1 | 1 | 10
        2 | 2 | 20
        """
    )
    new = T(
        """
          | b
        1 | 99
        """
    )
    pw.universes.promise_is_subset_of(new, old)
    res = old.update_cells(new)
    assert_table_equality_wo_index(
        res,
        T(
            """
            a | b
            1 | 99
            2 | 20
            """
        ),
    )


def test_update_rows():
    old = T(
        """
          | a | b
        1 | 1 | 10
        2 | 2 | 20
        """
    )
    new = T(
        """
          | a | b
        2 | 5 | 50
        3 | 9 | 90
        """
    )
    res = old.update_rows(new)
    assert_table_equality_wo_index(
        res,
        T(
            """
            a | b
            1 | 10
            5 | 50
            9 | 90
            """
        ),
    )


def test_coalesce_and_require():
    t = T(
        """
        a    | b
        1    | 10
        None | 20
        """
    )
    res = t.select(
        c=pw.coalesce(t.a, 0),
        r=pw.require(t.b, t.a),
    )
    _k, cols = pw.debug.table_to_dicts(res)
    assert sorted(cols["c"].values()) == [0, 1]
    assert sorted([v for v in cols["r"].values()], key=str) == [10, None]


def test_groupby_two_levels():
    t = T(
        """
        g1 | g2 | v
        a  | x  | 1
        a  | x  | 2
        a  | y  | 4
        b  | x  | 8
        """
    )
    lvl1 = t.groupby(t.g1, t.g2).reduce(t.g1, t.g2, s=pw.reducers.sum(t.v))
    lvl2 = lvl1.groupby(lvl1.g1).reduce(lvl1.g1, s=pw.reducers.sum(lvl1.s))
    assert_table_equality_wo_index(
        lvl2,
        T(
            """
            g1 | s
            a  | 7
            b  | 8
            """
        ),
    )


def test_difference_intersect_restrict():
    t1 = T(
        """
          | a
        1 | 10
        2 | 20
        3 | 30
        """
    )
    t2 = T(
        """
          | b
        2 | x
        3 | y
        """
    )
    diff = t1.difference(t2)
    inter = t1.intersect(t2)
    _kd, cd = pw.debug.table_to_dicts(diff)
    _ki, ci = pw.debug.table_to_dicts(inter)
    assert sorted(cd["a"].values()) == [10]
    assert sorted(ci["a"].values()) == [20, 30]
    restricted = t1.restrict(t2.promise_universe_is_subset_of(t1))
    _kr, cr = pw.debug.table_to_dicts(restricted)
    assert sorted(cr["a"].values()) == [20, 30]


def test_cast_and_declare():
    t = T(
        """
        a
        1
        2
        """
    )
    res = t.select(f=pw.cast(float, t.a))
    _k, cols = pw.debug.table_to_dicts(res)
    assert all(isinstance(v, float) for v in cols["f"].values())


def test_argmax_tie_break_deterministic():
    """Equal-count ties resolve to the smallest arg by stable sort key,
    never a salted hash (reproducibility across process runs)."""
    t = T(
        """
        g | v | a
        1 | 5 | zz
        1 | 5 | aa
        """
    )
    res = t.groupby(t.g).reduce(
        t.g, best=pw.reducers.argmax(t.v, t.a)
    )
    _k, cols = pw.debug.table_to_dicts(res)
    assert list(cols["best"].values()) == ["aa"]
