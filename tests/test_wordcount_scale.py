"""5M-row streaming wordcount with retractions through the engine
(VERDICT r4 item 6; reference scale proxy:
integration_tests/wordcount/base.py — 5M-line wordcount CI run)."""

from __future__ import annotations

import time

import numpy as np

from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import GroupByNode, InputNode, OutputNode
from pathway_tpu.engine.reducers import ReducerSpec
from pathway_tpu.engine.runtime import Runtime, StaticSource


def test_wordcount_5m_rows_with_retractions():
    n = 5_000_000
    n_vocab = 10_000
    tick_rows = 100_000
    vocab = np.array([f"word{i}" for i in range(n_vocab)])
    rng = np.random.default_rng(0)
    idx = rng.integers(0, n_vocab, size=n)
    words = vocab[idx]
    keys = np.arange(n, dtype=np.uint64)

    batches = []
    for lo in range(0, n, tick_rows):
        hi = min(n, lo + tick_rows)
        batches.append(
            DiffBatch(
                keys=keys[lo:hi],
                diffs=np.ones(hi - lo, np.int64),
                columns={"word": words[lo:hi]},
            )
        )
    # 2% retractions of rows already ingested, arriving as the final tick
    retr = rng.choice(n // 2, size=n // 50, replace=False).astype(np.uint64)
    batches.append(
        DiffBatch(
            keys=retr,
            diffs=-np.ones(len(retr), np.int64),
            columns={"word": words[retr]},
        )
    )

    class Src(StaticSource):
        def events(self):
            for i, b in enumerate(batches):
                yield i, b

    inp = InputNode(Src(["word"]), ["word"])
    gb = GroupByNode(
        inp, ["word"], {"count": ReducerSpec(kind="count", arg_cols=())}
    )
    final: dict = {}

    def on_batch(t, b):
        for k, d, vals in b.iter_rows():
            if d > 0:
                final[vals[0]] = vals[1]
            elif final.get(vals[0]) == vals[1]:
                del final[vals[0]]

    out = OutputNode(gb, on_batch)
    rt = Runtime([out])
    t0 = time.perf_counter()
    rt.run()
    dt = time.perf_counter() - t0

    # exact expected counts: inserts minus retractions, per word
    expected = np.bincount(idx, minlength=n_vocab)
    np.subtract.at(expected, idx[retr], 1)
    got = np.zeros(n_vocab, np.int64)
    for w, c in final.items():
        got[int(str(w)[4:])] = c
    assert (got == expected).all()
    rows = n + len(retr)
    # engine-throughput floor: even this 1-core dev box does >500k rows/s;
    # a regression to the per-row path would show up as a 6x drop
    assert rows / dt > 250_000, f"wordcount too slow: {rows / dt:,.0f} rows/s"
