"""Token Loom (pathway_tpu/generate/): the continuous-batching decode
scheduler over the paged, arrangement-backed KV cache, the /generate
serving route (ask -> retrieve -> generate), deadline drops MID-decode
with page reclaim, the kill/restore acceptance (restored decode equals
the uninterrupted run), the generation-serving doctor rule, and the
kill=decode fault directive."""

from __future__ import annotations

import json
import socket
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pathway_tpu.generate.kv_cache import KvLedger, PagePool
from pathway_tpu.generate.scheduler import (
    DecodeScheduler,
    GenerateConfig,
    GenerationRequest,
)
from pathway_tpu.serving.admission import ShedError
from pathway_tpu.xpacks.llm import decoder as dec

# a tiny decoder so the jit cost stays test-friendly; every scheduler
# in this module shares the shape so XLA compiles each bucket once
_SMALL = dict(
    dim=64, n_layers=1, n_heads=2, head_dim=32, ffn_dim=128,
)


def _cfg(**kw) -> GenerateConfig:
    base = dict(
        n_pages=32, page_size=8, max_batch=4, max_len=96,
        max_new_tokens=8, **_SMALL,
    )
    base.update(kw)
    return GenerateConfig(**base)


def _req(rid: str, text: str, *, budget_s: float = 60.0, **kw):
    kw.setdefault("max_new_tokens", 6)
    return GenerationRequest(
        rid,
        dec.encode_text(text),
        deadline=time.monotonic() + budget_s,
        **kw,
    )


# --- page pool -------------------------------------------------------------


def test_page_pool_accounting():
    pool = PagePool(8)
    assert pool.capacity == 7  # page 0 is the null page
    got = pool.try_alloc(3)
    assert got is not None and len(got) == 3 and 0 not in got
    assert pool.in_use == 3
    assert pool.try_alloc(5) is None  # never a partial grant
    assert pool.in_use == 3
    pool.free(got)
    assert pool.in_use == 0
    with pytest.raises(ValueError):
        pool.free([0])  # the null page is not freeable
    got = pool.try_alloc(1)
    pool.free(got)
    with pytest.raises(ValueError):
        pool.free(got)  # double free


# --- scheduler -------------------------------------------------------------


def test_generate_completes_and_reclaims_pages():
    s = DecodeScheduler(_cfg(), replica_label="g1")
    try:
        reqs = [_req(f"r{i}", f"hello {i}", seed=i) for i in range(3)]
        for r in reqs:
            s.submit(r)
        for r in reqs:
            res = r.wait(60)
            assert res is not None and res["status"] == 200
            assert res["token_count"] == 6
            assert len(res["tokens"]) == 6
        assert s.pool.in_use == 0  # finished sequences freed every page
        assert s.stats()["active_seqs"] == 0
    finally:
        s.stop()


def test_continuous_batching_beyond_max_batch():
    """More requests than max_batch: sequences join BETWEEN steps as
    slots free, and everyone completes — no request is lost to the
    batch bound."""
    s = DecodeScheduler(_cfg(max_batch=2), replica_label="g2")
    try:
        reqs = [_req(f"r{i}", f"word {i}") for i in range(6)]
        for r in reqs:
            s.submit(r)
        for r in reqs:
            res = r.wait(120)
            assert res is not None and res["status"] == 200, res
        assert s.pool.in_use == 0
    finally:
        s.stop()


def test_deadline_drops_mid_decode_and_reclaims():
    """The acceptance's drop leg: an expired deadline 504s MID-decode,
    pages return to baseline, and the sequence never takes another
    step."""
    s = DecodeScheduler(_cfg(max_len=160), replica_label="g3")
    try:
        r = _req("drop", "x" * 50, budget_s=0.15, max_new_tokens=64)
        s.submit(r)
        res = r.wait(30)
        assert res is not None and res["status"] == 504
        assert "mid-decode" in res["error"]
        assert res["tokens"] < 64  # dropped before completion
        deadline = time.monotonic() + 5
        while s.pool.in_use and time.monotonic() < deadline:
            time.sleep(0.02)
        assert s.pool.in_use == 0  # page count back to baseline
        steps_at_drop = s.stats()["decode_steps"]
        time.sleep(0.3)
        assert s.stats()["decode_steps"] == steps_at_drop  # never again
    finally:
        s.stop()


def test_expired_before_decode_never_dispatched():
    """A dead deadline is 504'd at the batcher flush — the EDF queue's
    expiry sweep, not a decode step."""
    s = DecodeScheduler(_cfg(), replica_label="g4")
    try:
        r = _req("late", "hello", budget_s=-0.5)
        s.submit(r)
        res = r.wait(15)
        assert res is not None and res["status"] == 504
        assert "before decode" in res["error"]
    finally:
        s.stop()


def test_oversized_request_shed_explicitly():
    s = DecodeScheduler(_cfg(), replica_label="g5")
    try:
        with pytest.raises(ShedError) as ei:
            s.submit(_req("big", "x" * 500, max_new_tokens=64))
        assert ei.value.status == 400
    finally:
        s.stop()


def test_page_starved_request_waits_then_runs():
    """A request the pool cannot cover YET parks and joins when pages
    free (work-conserving), instead of shedding."""
    s = DecodeScheduler(
        _cfg(n_pages=8, max_batch=2), replica_label="g6"
    )
    try:
        # each needs ceil((~12+16)/8) = 4 pages; pool holds 7
        a = _req("a", "aaaaaa", max_new_tokens=16)
        b = _req("b", "bbbbbb", max_new_tokens=16)
        s.submit(a)
        s.submit(b)
        ra = a.wait(60)
        rb = b.wait(60)
        assert ra["status"] == 200 and rb["status"] == 200
        assert s.pool.in_use == 0
    finally:
        s.stop()


# --- the kill/restore acceptance -------------------------------------------


def test_kill_restore_decode_equals_uninterrupted(tmp_path):
    """ISSUE 14 acceptance: a kill/restart restores in-flight KV-cache
    state from the arrangement snapshot and the restored decode output
    EQUALS the uninterrupted run (greedy AND seeded sampling)."""
    prompt = dec.encode_text("the quick brown fox")
    kw = dict(max_new_tokens=12, temperature=0.7, top_k=20, seed=5)
    cfg = _cfg(n_pages=16, max_batch=1, max_len=64)

    s0 = DecodeScheduler(cfg, replica_label="u")
    r0 = GenerationRequest(
        "u", list(prompt), deadline=time.monotonic() + 60, **kw
    )
    s0.submit(r0)
    res0 = r0.wait(60)
    s0.stop()
    assert res0["status"] == 200

    root = str(tmp_path / "kv")
    cfg1 = _cfg(
        n_pages=16, max_batch=1, max_len=64,
        snapshot_every=3, store_root=root,
    )
    s1 = DecodeScheduler(cfg1, replica_label="k")
    r1 = GenerationRequest(
        "k", list(prompt), deadline=time.monotonic() + 60, **kw
    )
    s1.submit(r1)
    deadline = time.monotonic() + 60
    while (
        s1.stats()["decode_steps"] < 9 and time.monotonic() < deadline
    ):
        time.sleep(0.005)
    # simulated SIGKILL: freeze the loop mid-flight; no drain, no stop,
    # no final snapshot — only what the periodic snapshot committed
    s1._step = lambda: time.sleep(0.05)
    time.sleep(0.2)

    s2 = DecodeScheduler(cfg1, replica_label="r")
    try:
        assert getattr(s2, "restored_seqs", 0) == 1
        deadline = time.monotonic() + 90
        while not s2.finished and time.monotonic() < deadline:
            time.sleep(0.05)
        assert s2.finished, "restored sequence never completed"
        res2 = next(iter(s2.finished.values()))
        assert res2["status"] == 200
        assert res2["tokens"] == res0["tokens"]
        assert res2["text"] == res0["text"]
    finally:
        s2.stop()
        s1.stop()  # the frozen "killed" scheduler's threads


def test_ledger_snapshot_incremental_and_drop(tmp_path):
    """Snapshot bytes track churn (pages already persisted are not
    rewritten) and a dropped sequence's rows leave the ledger."""
    led = KvLedger()
    page = lambda x: np.full((1, 2, 8, 128), x, np.float32)  # noqa: E731
    led.put_page(1, 0, page(1.0), page(1.5))
    led.put_page(1, 1, page(2.0), page(2.5))
    led.put_seq(1, {"seq_id": 1, "tokens": [1, 2], "prompt_len": 2,
                    "max_new": 4, "temperature": 0.0, "top_k": 1,
                    "seed": 0, "n_fed": 2, "n_generated": 0,
                    "remaining_ms": 1000.0, "n_pages": 2})
    root = str(tmp_path / "led")
    s1 = led.snapshot(root)
    assert s1["segments_written"] >= 1 and s1["bytes_written"] > 0
    # an unchanged ledger re-snapshots for free (same sealed segments)
    s2 = led.snapshot(root)
    assert s2["segments_written"] == 0 and s2["bytes_written"] == 0
    # churn one page per snapshot: AMORTIZED bytes ∝ the churned rows
    # (a geometric-merge tick legitimately rewrites the merged run, so
    # the claim is over the min of a few cycles — the State Ledger
    # contract, CKPT_r07 wording)
    churn_bytes = []
    for i in range(4):
        led.put_page(1, 1, page(3.0 + i), page(3.5 + i))
        si = led.snapshot(root)
        assert si["bytes_written"] > 0
        churn_bytes.append(si["bytes_written"])
    assert min(churn_bytes) < s1["bytes_written"]
    # restore sees exactly the live state
    led2 = KvLedger.restore(root)
    assert set(led2.live_pages()) == {(1, 0), (1, 1)}
    assert np.allclose(led2.live_pages()[(1, 1)][0], page(6.0))
    assert led2.live_seqs()[1]["tokens"] == [1, 2]
    # dropping the sequence retracts everything
    led2.drop_seq(1)
    led2.snapshot(root)
    led3 = KvLedger.restore(root)
    assert not led3.live_pages() and not led3.live_seqs()


# --- serving e2e -----------------------------------------------------------


def _post(url, body, headers=None, timeout=60):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"content-type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture
def gen_replica():
    from pathway_tpu.generate.serving import attach_generate
    from pathway_tpu.serving.replica import ReplicaServer, text_vector
    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    dim = 16
    srv = ReplicaServer(
        replica_id=0,
        index_factory=lambda: TpuDenseKnnIndex(dimensions=dim),
        dim=dim,
    )
    for i, text in enumerate(
        ["alpha beta", "gamma delta", "epsilon zeta", "eta theta"]
    ):
        srv.index.upsert(i, text_vector(text, dim), None)
    sched = attach_generate(
        srv,
        DecodeScheduler(_cfg(max_len=128), replica_label="e2e"),
    )
    srv.start()
    try:
        yield srv, sched
    finally:
        srv.stop()


def test_e2e_ask_retrieve_generate(gen_replica):
    """ISSUE 14 acceptance: a /generate request returns retrieved-
    context-conditioned tokens with staleness headers."""
    srv, sched = gen_replica
    url = f"http://127.0.0.1:{srv.http_port}/generate"
    st, body, hdrs = _post(
        url, {"prompt": "what is alpha?", "k": 2, "max_tokens": 8}
    )
    assert st == 200
    assert body["token_count"] == 8
    assert len(body["retrieved"]) == 2
    # freshness + token-count headers (the degrade contract holds
    # through the generation stage)
    assert hdrs["x-pathway-replica"] == "0"
    assert "x-pathway-applied-tick" in hdrs
    assert "x-pathway-staleness-seconds" in hdrs
    assert hdrs["x-pathway-generate-tokens"] == "8"
    # retrieval really is the /query index: the top doc matches the
    # replica's own KNN answer for the same text
    from pathway_tpu.serving.replica import text_vector

    direct = srv.search([(text_vector("what is alpha?", srv.dim), 2, None)])
    assert body["retrieved"][0][0] == int(direct[0][0][0])
    # CONDITIONED on the corpus: changing a retrieved doc changes the
    # generation (same prompt, same seed)
    from pathway_tpu.serving.replica import text_vector as tv

    srv.index.upsert(99, tv("what is alpha? exact", srv.dim), None)
    st2, body2, _ = _post(
        url, {"prompt": "what is alpha?", "k": 2, "max_tokens": 8}
    )
    assert st2 == 200
    assert body2["retrieved"] != body["retrieved"]
    assert body2["tokens"] != body["tokens"]


def test_e2e_deadline_drop_reclaims_pages(gen_replica):
    """ISSUE 14 acceptance: an expired deadline drops the generation
    mid-decode (504) and the page count returns to baseline."""
    srv, sched = gen_replica
    url = f"http://127.0.0.1:{srv.http_port}/generate"
    baseline = sched.pool.in_use
    st, body, hdrs = _post(
        url,
        # warm decode of 48 tokens measures ~130-160 ms on a 1-core
        # box, so the deadline must sit well below that floor or the
        # generation occasionally finishes first (200) and flakes
        {"prompt": "y" * 60, "k": 0, "max_tokens": 48},
        headers={"x-pathway-deadline-ms": "60"},
    )
    assert st == 504
    assert "mid-decode" in body["error"] or "deadline" in body["error"]
    assert "Retry-After" in hdrs
    deadline = time.monotonic() + 5
    while sched.pool.in_use != baseline and time.monotonic() < deadline:
        time.sleep(0.02)
    assert sched.pool.in_use == baseline
    # dropped generations are visible in the metric
    from pathway_tpu.observability import REGISTRY

    rendered = REGISTRY.render()
    assert "pathway_generate_dropped_mid_decode_total" in rendered
    assert "pathway_generate_tokens_total" in rendered
    assert "pathway_generate_page_pool_occupancy" in rendered
    assert "pathway_generate_decode_batch_size" in rendered


def test_e2e_streaming_ndjson(gen_replica):
    srv, _sched = gen_replica
    url = f"http://127.0.0.1:{srv.http_port}/generate"
    req = urllib.request.Request(
        url,
        data=json.dumps(
            {"prompt": "stream me", "k": 1, "max_tokens": 5,
             "stream": True}
        ).encode(),
        headers={"content-type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.headers["content-type"].startswith(
            "application/x-ndjson"
        )
        lines = [json.loads(l) for l in r.read().decode().splitlines()]
    assert "meta" in lines[0]
    assert len(lines[0]["meta"]["retrieved"]) == 1
    token_lines = [l for l in lines if "token" in l]
    assert len(token_lines) == 5
    assert lines[-1]["done"] is True and lines[-1]["token_count"] == 5


def test_e2e_bad_requests(gen_replica):
    srv, _sched = gen_replica
    url = f"http://127.0.0.1:{srv.http_port}/generate"
    st, body, _ = _post(url, {"k": 2})  # no prompt
    assert st == 400
    st, body, _ = _post(url, {"prompt": "x", "max_tokens": "lots"})
    assert st == 400
    # an over-long PROMPT is truncated to fit (RAG contexts clip), but
    # max_tokens that leaves no prompt room at all is a named 400
    st, body, _ = _post(url, {"prompt": "x", "max_tokens": 10_000})
    assert st == 400
    assert "no room" in body["error"]
    # the scheduler-level bound still sheds a direct oversized submit
    sched = gen_replica[1]
    with pytest.raises(ShedError) as ei:
        sched.submit(
            _req("big", "x" * 500, max_new_tokens=64)
        )
    assert ei.value.status == 400


def test_e2e_staleness_bound_sheds(gen_replica):
    """x-pathway-max-staleness-ms applies to the RETRIEVAL corpus the
    generation is grounded on: a snapshot-only replica (no stream, so
    staleness is unknown) must shed a bounded generate."""
    srv, _sched = gen_replica
    url = f"http://127.0.0.1:{srv.http_port}/generate"
    st, body, hdrs = _post(
        url,
        {"prompt": "fresh only", "k": 1, "max_tokens": 4},
        headers={"x-pathway-max-staleness-ms": "50"},
    )
    assert st == 503
    assert "Retry-After" in hdrs


def test_e2e_through_router(gen_replica):
    """The router forwards /generate through the same single-member
    machinery (deadline budget propagated, freshness headers back)."""
    from pathway_tpu.serving.router import FailoverRouter

    srv, _sched = gen_replica
    router = FailoverRouter(
        [f"http://127.0.0.1:{srv.http_port}"]
    ).start()
    try:
        deadline = time.monotonic() + 10
        while (
            not all(ep.ready for ep in router.endpoints)
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        url = f"http://127.0.0.1:{router.port}/generate"
        st, body, hdrs = _post(
            url,
            {"prompt": "via router", "k": 2, "max_tokens": 6},
            headers={"x-pathway-deadline-ms": "30000"},
        )
        assert st == 200
        assert body["token_count"] == 6
        assert hdrs.get("x-pathway-replica") == "0"
    finally:
        router.stop()


def test_generate_route_never_scattered():
    """On a sharded plane /generate takes the single-member route —
    scatter-gather is a retrieval concept, not a generation one."""
    from pathway_tpu.generate.serving import is_generate_route

    assert is_generate_route("/generate")
    assert is_generate_route("/v1/generate/")
    assert not is_generate_route("/query")
    assert not is_generate_route("/generate/status")
    # segment-exact: a route merely ENDING in the word must not divert
    # a sharded read off the scatter-gather path
    assert not is_generate_route("/regenerate")
    assert not is_generate_route("/shard-generate")


# --- doctor rule -----------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _generate_graph(qos):
    import pathway_tpu as pw
    from pathway_tpu.io.http import rest_connector

    class QuerySchema(pw.Schema):
        text: str

    gated, writer = rest_connector(
        host="127.0.0.1",
        port=_free_port(),
        schema=QuerySchema,
        route="/generate",
        qos=qos,
    )
    writer(gated.select(query_id=gated.id, result=gated.text))


def test_doctor_generation_serving_rule(monkeypatch):
    import pathway_tpu as pw
    from pathway_tpu.analysis import run_doctor
    from pathway_tpu.serving import QoSConfig

    for var in (
        "PATHWAY_GENERATE",
        "PATHWAY_GENERATE_PAGES",
        "PATHWAY_SERVING_DEADLINE_MS",
        "PATHWAY_SERVING_MAX_DEADLINE_MS",
    ):
        monkeypatch.delenv(var, raising=False)
    # ungated /generate ingress + no deadline bound: two WARNINGs +
    # the defaulted-pool INFO
    _generate_graph(qos=None)
    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    hits = report.by_rule("generation-serving")
    sev = sorted(h.severity.name for h in hits)
    assert sev == ["INFO", "WARNING", "WARNING"], [h.message for h in hits]
    assert any("admission" in h.message for h in hits)
    assert any("deadline" in h.message for h in hits)
    assert any("page pool" in h.message for h in hits)
    # gated + bounded + explicit pool: clean
    monkeypatch.setenv("PATHWAY_SERVING_DEADLINE_MS", "10000")
    monkeypatch.setenv("PATHWAY_GENERATE_PAGES", "128")
    pw.internals.parse_graph.G.clear()
    _generate_graph(qos=QoSConfig())
    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    assert not report.by_rule("generation-serving")
    # a NON-generate graph with the env-armed plane (the standard
    # `python -m pathway_tpu.serving.replica` + PATHWAY_GENERATE=1
    # deployment: no graph-declared generate ingress at all) still
    # gets the plane-level findings, anchored at <graph> (node=None)
    monkeypatch.delenv("PATHWAY_SERVING_DEADLINE_MS", raising=False)
    monkeypatch.delenv("PATHWAY_GENERATE_PAGES", raising=False)
    monkeypatch.setenv("PATHWAY_GENERATE", "1")
    pw.internals.parse_graph.G.clear()
    t = pw.debug.table_from_rows(
        pw.schema_from_types(x=int), [(1,), (2,)]
    )
    pw.io.null.write(t.select(y=t.x + 1))
    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    hits = report.by_rule("generation-serving")
    assert sorted(h.severity.name for h in hits) == ["INFO", "WARNING"]
    assert all(h.node is None for h in hits)
    for h in hits:
        assert "<graph>" in h.format()  # None anchor renders cleanly


# --- fault forge -----------------------------------------------------------


def test_fault_kill_decode_parse_and_fire(monkeypatch):
    from pathway_tpu.testing import faults

    plan = faults.FaultPlan("kill=decode:3", pid=0, incarnation=0)
    died = []
    monkeypatch.setattr(
        faults.FaultPlan, "_exit", lambda self, what: died.append(what)
    )
    plan.on_decode_step(1)
    plan.on_decode_step(2)
    assert not died
    plan.on_decode_step(3)
    assert died and "decode step 3" in died[0]
    plan.on_decode_step(4)
    assert len(died) == 1  # fires once
    # engine-tick kills ignore the decode counter and vice versa
    plan2 = faults.FaultPlan("kill=tick:1", pid=0, incarnation=0)
    monkeypatch.setattr(
        faults.FaultPlan, "_exit", lambda self, what: died.append(what)
    )
    plan2.on_decode_step(10)
    assert len(died) == 1
    # incarnation scoping: the takeover process runs fault-free
    plan3 = faults.FaultPlan("kill=decode:1", pid=0, incarnation=1)
    plan3.on_decode_step(5)
    assert len(died) == 1
    # `at:` is rejected for decode-scoped kills
    with pytest.raises(faults.FaultSpecError):
        faults.FaultPlan("kill=decode:1,at:head", pid=0, incarnation=0)


def test_scheduler_reports_decode_steps_to_fault_plan(monkeypatch):
    """The scheduler's step counter IS the chaos clock: a plan armed
    with kill=decode:N sees every step."""
    from pathway_tpu.testing import faults

    seen = []
    plan = faults.FaultPlan("kill=decode:999999", pid=0, incarnation=0)
    monkeypatch.setattr(faults, "active", lambda: plan)
    real = plan.on_decode_step
    monkeypatch.setattr(
        plan, "on_decode_step", lambda n: (seen.append(n), real(n))
    )
    s = DecodeScheduler(_cfg(), replica_label="fp")
    try:
        r = _req("f", "count me", max_new_tokens=3)
        s.submit(r)
        assert r.wait(60)["status"] == 200
        assert seen and seen == sorted(seen)
    finally:
        s.stop()


# --- multi-process leg (slow: tier-1 keeps the in-process e2e above) -------


@pytest.mark.slow
def test_subprocess_replica_generate_kill_restore(tmp_path):
    """The process role end-to-end: `python -m
    pathway_tpu.serving.replica` with PATHWAY_GENERATE=1 serves
    /generate; SIGKILL mid-generation loses nothing the periodic
    arrangement snapshot committed — the restarted process restores
    the in-flight sequence from PATHWAY_GENERATE_STORE and finishes
    it."""
    import os
    import signal
    import subprocess
    import sys
    import threading

    store = str(tmp_path / "genstore")
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PYTHONPATH=os.path.dirname(os.path.dirname(__file__)),
        PATHWAY_GENERATE="1",
        PATHWAY_GENERATE_PAGES="64",
        PATHWAY_GENERATE_PAGE_SIZE="8",
        PATHWAY_GENERATE_MAX_LEN="160",
        PATHWAY_GENERATE_SNAPSHOT_EVERY="3",
        PATHWAY_GENERATE_STORE=store,
        PATHWAY_REPLICA_ID="7",
    )
    env.pop("XLA_FLAGS", None)

    def spawn():
        p = subprocess.Popen(
            [sys.executable, "-m", "pathway_tpu.serving.replica"],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        port = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = p.stdout.readline()
            if line.startswith("REPLICA-READY"):
                port = int(line.split()[1])
                break
        assert port, "replica never came up"
        return p, port

    p1, port = spawn()
    try:
        url = f"http://127.0.0.1:{port}/generate"

        # a long generation in the background so the kill is MID-decode
        def fire():
            try:
                _post(
                    url,
                    {"prompt": "z" * 60, "k": 0, "max_tokens": 64,
                     "seed": 3},
                    headers={"x-pathway-deadline-ms": "600000"},
                    timeout=120,
                )
            except Exception:
                pass  # the kill severs the connection

        t = threading.Thread(target=fire, daemon=True)
        t.start()
        # wait until at least one snapshot manifest is committed
        deadline = time.monotonic() + 60
        manifest = tmp_path / "genstore" / "manifest.json"
        while time.monotonic() < deadline and not manifest.exists():
            time.sleep(0.05)
        assert manifest.exists(), "no snapshot before the kill"
        time.sleep(0.3)  # a few more decode steps into the snapshot
        os.kill(p1.pid, signal.SIGKILL)
        p1.wait(timeout=30)
    finally:
        if p1.poll() is None:
            p1.kill()

    p2, port2 = spawn()
    try:
        import urllib.request

        hurl = f"http://127.0.0.1:{port2}/replica/health"
        with urllib.request.urlopen(hurl, timeout=10) as r:
            h = json.loads(r.read())
        # the restored sequence decodes to completion in the new process
        deadline = time.monotonic() + 90
        active = h["generate"]["active_seqs"]
        assert active >= 1 or h["generate"]["decode_steps"] > 0
        while time.monotonic() < deadline:
            with urllib.request.urlopen(hurl, timeout=10) as r:
                h = json.loads(r.read())
            if h["generate"]["active_seqs"] == 0 and h["generate"][
                "decode_steps"
            ] > 0:
                break
            time.sleep(0.2)
        assert h["generate"]["active_seqs"] == 0
        assert h["generate"]["free_pages"] == h["generate"]["page_capacity"]
    finally:
        p2.terminate()
        try:
            p2.wait(timeout=15)
        except subprocess.TimeoutExpired:
            p2.kill()


# --- review-round regressions ----------------------------------------------


def test_negative_seed_never_kills_the_batch(gen_replica):
    """Review round: a client-supplied NEGATIVE seed used to raise in
    sample_token mid-step and the scheduler dropped the WHOLE decode
    batch with 500 — co-batched tenants lost their generations to one
    bad request."""
    srv, _sched = gen_replica
    url = f"http://127.0.0.1:{srv.http_port}/generate"
    st, body, _ = _post(
        url,
        {"prompt": "neg", "k": 0, "max_tokens": 4,
         "temperature": 0.7, "seed": -1},
    )
    assert st == 200 and body["token_count"] == 4
    # determinism holds for negative seeds too
    st2, body2, _ = _post(
        url,
        {"prompt": "neg", "k": 0, "max_tokens": 4,
         "temperature": 0.7, "seed": -1},
    )
    assert st2 == 200 and body2["tokens"] == body["tokens"]


def test_bad_vec_is_a_named_400_not_a_raw_500(gen_replica):
    """Review round: a non-numeric `vec` used to escape the handler as
    an uncounted raw aiohttp 500; now it is a structured 400 carrying
    the freshness headers, and anything else a handler bug raises
    comes back as a COUNTED structured 500."""
    srv, _sched = gen_replica
    url = f"http://127.0.0.1:{srv.http_port}/generate"
    st, body, hdrs = _post(
        url, {"prompt": "x", "k": 2, "vec": "abc"}
    )
    assert st == 400
    assert "vec" in body["error"]
    assert "x-pathway-replica" in hdrs


def test_queue_bound_sheds_429_with_active_set_full():
    """Review round: the queue-full 429 counts the EDF heap too — with
    the active set saturated the batcher never dispatches, and without
    the heap term the bound could never fire (the burst would grow the
    heap until every entry 504'd at flush)."""
    from pathway_tpu.serving.config import QoSConfig

    s = DecodeScheduler(
        _cfg(max_batch=1, max_len=160),
        qos=QoSConfig(max_batch_size=1, max_queue=2, max_wait_ms=2.0),
        replica_label="qb",
    )
    try:
        # saturate the single active slot with a long generation
        long = _req("long", "x" * 40, max_new_tokens=64)
        s.submit(long)
        deadline = time.monotonic() + 30
        while s.stats()["active_seqs"] < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        # fill the bounded queue, then the next submit must shed 429
        queued = [_req(f"q{i}", "y") for i in range(2)]
        for r in queued:
            s.submit(r)
        with pytest.raises(ShedError) as ei:
            s.submit(_req("overflow", "z"))
        assert ei.value.status == 429
        assert "queue full" in ei.value.reason
    finally:
        s.stop()


def test_out_of_thread_snapshot_runs_at_step_boundary(tmp_path):
    """Review round: snapshot() from a non-decode thread must not
    touch the donated pools mid-step — it is executed AT the next step
    boundary by the decode thread and the caller gets the result."""
    root = str(tmp_path / "snap")
    s = DecodeScheduler(
        _cfg(max_len=160, store_root=root), replica_label="snapth"
    )
    try:
        r = _req("bg", "w" * 40, max_new_tokens=32)
        s.submit(r)
        deadline = time.monotonic() + 30
        while s.stats()["active_seqs"] < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        out = s.snapshot()  # test thread != decode thread
        assert out is not None and out["bytes_written"] > 0
        led = KvLedger.restore(root)
        assert led is not None and len(led.live_seqs()) == 1
        assert r.wait(60)["status"] == 200
        # idle scheduler still serves out-of-thread snapshots (the
        # loop wakes on the waiter, not only on work)
        out2 = s.snapshot()
        assert out2 is not None
    finally:
        s.stop()


# --- Shard Flux: the generation plane rides the ferry ----------------------


def test_kv_handoff_resumes_on_new_owner(tmp_path, monkeypatch):
    """Elastic resharding, generation plane: a member's in-flight KV
    ledger splits by the system-wide jk-hash ownership, the owning
    half rides the SegmentFerry to the new owner's store, and the new
    owner's scheduler RESUMES the decode — tokens bit-equal to the
    uninterrupted run (the kill/restore machinery, now cross-owner)."""
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "kv-handoff-secret")
    from pathway_tpu.elastic.ferry import FerryReceiver
    from pathway_tpu.elastic.kv import seq_owner, split_kv_store

    prompt = dec.encode_text("the quick brown fox")
    kw = dict(max_new_tokens=12, temperature=0.7, top_k=20, seed=5)
    cfg = _cfg(n_pages=16, max_batch=1, max_len=64)

    s0 = DecodeScheduler(cfg, replica_label="hu")
    r0 = GenerationRequest(
        "hu", list(prompt), deadline=time.monotonic() + 60, **kw
    )
    s0.submit(r0)
    res0 = r0.wait(60)
    s0.stop()
    assert res0["status"] == 200

    root = str(tmp_path / "kv-src")
    cfg1 = _cfg(
        n_pages=16, max_batch=1, max_len=64,
        snapshot_every=3, store_root=root,
    )
    s1 = DecodeScheduler(cfg1, replica_label="hk")
    r1 = GenerationRequest(
        "hk", list(prompt), deadline=time.monotonic() + 60, **kw
    )
    s1.submit(r1)
    deadline = time.monotonic() + 60
    while (
        s1.stats()["decode_steps"] < 9 and time.monotonic() < deadline
    ):
        time.sleep(0.005)
    # freeze mid-flight (the in-process SIGKILL stand-in): only what
    # the periodic snapshot committed survives the handoff
    s1._step = lambda: time.sleep(0.05)
    time.sleep(0.2)

    # split 1 -> 2 owners; the OWNING destination sits behind a real
    # ferry endpoint (remote-owner shape), the other is a local dir
    owner = seq_owner(1, 2)
    roots = [str(tmp_path / "kv-p0"), str(tmp_path / "kv-p1")]
    recv = FerryReceiver(roots[owner])
    try:
        dests: list = [roots[0], roots[1]]
        dests[owner] = (recv.host, recv.port)
        stats = split_kv_store(root, dests)
        assert stats["total_seqs"] == 1
        assert stats["destinations"][owner]["seqs"] == 1
        assert stats["destinations"][1 - owner]["seqs"] == 0
        assert stats["bytes_ferried"] > 0
        assert stats["destinations"][owner]["ferry"]["committed"]
    finally:
        recv.close()

    cfg_new = _cfg(
        n_pages=16, max_batch=1, max_len=64,
        snapshot_every=3, store_root=roots[owner],
    )
    s2 = DecodeScheduler(cfg_new, replica_label="ho")
    cfg_other = _cfg(
        n_pages=16, max_batch=1, max_len=64, store_root=roots[1 - owner],
    )
    s3 = DecodeScheduler(cfg_other, replica_label="hn")
    try:
        assert getattr(s2, "restored_seqs", 0) == 1
        assert getattr(s3, "restored_seqs", 0) == 0
        deadline = time.monotonic() + 90
        while not s2.finished and time.monotonic() < deadline:
            time.sleep(0.05)
        assert s2.finished, "handed-off sequence never completed"
        res2 = next(iter(s2.finished.values()))
        assert res2["status"] == 200
        assert res2["tokens"] == res0["tokens"]
        assert res2["text"] == res0["text"]
    finally:
        s3.stop()
        s2.stop()
        s1.stop()


# --- Tenant Weave: WFQ ordering extends into decode batching ---------------


def test_tenant_wfq_orders_decode_queue():
    """ROADMAP gen (f): with the tenant ledger attached, the decode
    batcher orders by the WFQ (vfinish, deadline) tag — a noisy
    neighbor's queued backlog drains BEHIND a tail tenant's fresh
    request even though the tail arrived last."""
    from pathway_tpu.serving.tenancy import TenancyConfig, TenantLedger

    ledger = TenantLedger(
        TenancyConfig(weights={"default": 1.0}), route="/gen"
    )
    s = DecodeScheduler(
        _cfg(max_batch=1, n_pages=31), replica_label="wfq", ledger=ledger
    )
    try:
        hot = [
            _req(
                f"hot{i}",
                "alpha beta gamma delta epsilon zeta",
                tenant="hot",
                max_new_tokens=4,
            )
            for i in range(4)
        ]
        for r in hot:
            s.submit(r)
            assert isinstance(r.order, tuple)  # (vfinish, deadline)
        tail = _req("tail", "hi", tenant="tail", max_new_tokens=4)
        s.submit(tail)
        for r in hot + [tail]:
            assert r.wait(120)["status"] == 200, r.request_id
        done_order = list(s.finished)
        # the tail's single request must NOT drain behind the whole hot
        # backlog (plain EDF would finish every earlier-deadline hot
        # request first) — at least the last hot request follows it
        tail_pos = done_order.index("tail")
        hots_after_tail = sum(
            1 for rid in done_order[tail_pos + 1:] if rid.startswith("hot")
        )
        assert hots_after_tail >= 1, done_order
    finally:
        s.stop()
