"""Intra-tick worker parallelism (reference: PATHWAY_THREADS timely
workers, src/engine/dataflow/config.rs:63-86): independent topo-level
nodes process concurrently; results equal the sequential run."""

import time

import pathway_tpu as pw


class S(pw.Schema):
    v: int


def _graph():
    t = pw.debug.table_from_rows(S, [(i,) for i in range(20)])

    @pw.udf
    def slow_a(v: int) -> int:
        time.sleep(0.005)
        return v * 2

    @pw.udf
    def slow_b(v: int) -> int:
        time.sleep(0.005)
        return v * 3

    a = t.select(x=slow_a(t.v)).reduce(s=pw.reducers.sum(pw.this.x))
    b = t.select(x=slow_b(t.v)).reduce(s=pw.reducers.sum(pw.this.x))
    return a, b


def test_threads_equal_results_and_overlap(monkeypatch):
    from pathway_tpu.debug import _run_capture

    # sequential reference: both branches in ONE graph/run
    a, b = _graph()
    t0 = time.perf_counter()
    caps = _run_capture([a, b])
    seq_elapsed = time.perf_counter() - t0
    seq = sorted(v[0] for c in caps for v in c.rows.values())

    pw.internals.parse_graph.G.clear()
    monkeypatch.setenv("PATHWAY_THREADS", "4")
    a2, b2 = _graph()
    t0 = time.perf_counter()
    caps2 = _run_capture([a2, b2])
    par_elapsed = time.perf_counter() - t0
    par = sorted(v[0] for c in caps2 for v in c.rows.values())
    expected = sorted([sum(i * 2 for i in range(20)),
                       sum(i * 3 for i in range(20))])
    assert par == seq == expected
    # the two slow branches (>=100ms each serial) must have overlapped
    assert par_elapsed < seq_elapsed * 0.8, (seq_elapsed, par_elapsed)


def test_threads_worker_exception_fails_stop(monkeypatch):
    import pytest

    monkeypatch.setenv("PATHWAY_THREADS", "4")
    t = pw.debug.table_from_rows(S, [(1,)])

    # two branches so a multi-node level actually forms
    ok = t.select(x=t.v + 1)
    from pathway_tpu.engine.nodes import Node, NodeExec, OutputNode

    class _BoomNode(Node):
        def __init__(self, inp):
            super().__init__([inp], ["x"])

        def make_exec(self):
            return _BoomExec(self)

    class _BoomExec(NodeExec):
        def process(self, t_, inputs):
            raise ValueError("worker-crash")

    boom = _BoomNode(t._node)
    from pathway_tpu.engine.runtime import Runtime

    sink1 = OutputNode(ok._node, lambda t_, b: None)
    sink2 = OutputNode(boom, lambda t_, b: None)
    rt = Runtime([sink1, sink2])
    with pytest.raises(ValueError, match="worker-crash"):
        rt.run()
