"""Replica Shield tests — delta stream, replica hydration, failover
router semantics, and the tier-1 single-host 2-replica failover smoke.

The heavy multi-process chaos legs (supervised replica kills under a
real writer pipeline) live in test_distributed.py behind the ``slow``
marker; everything here is in-process and fast.
"""

import json
import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw


@pytest.fixture(autouse=True)
def _repl_env(monkeypatch):
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "replication-test-secret")
    from pathway_tpu.parallel import replicate

    yield
    replicate.reset_publisher()


class ToyIndex:
    """Dict-backed index: deterministic, no device work — the unit-test
    stand-in for TpuDenseKnnIndex."""

    def __init__(self):
        self.d = {}

    def upsert(self, key, data, meta):
        self.d[key] = (data, meta)

    def remove(self, key):
        self.d.pop(key, None)

    def search(self, triples):
        out = []
        for _q, k, _f in triples:
            out.append(tuple((key, 1.0) for key in sorted(self.d)[: int(k)]))
        return out


def _batch(rows):
    from pathway_tpu.engine.batch import DiffBatch

    return DiffBatch.from_rows(rows, ("_data", "_meta"))


def _wait(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# delta stream


def test_consolidate_rows_last_op_wins():
    from pathway_tpu.parallel.replicate import consolidate_rows

    rows = [
        (1, 1, ("a", None)),
        (2, 1, ("b", None)),
        (1, -1, (None, None)),
        (1, 1, ("a2", None)),
        (3, 1, ("c", None)),
        (2, -1, (None, None)),
    ]
    out = consolidate_rows(rows)
    assert [(r[0], r[1]) for r in out] == [(1, 1), (3, 1), (2, -1)]
    assert out[0][2] == ("a2", None)


def test_delta_stream_roundtrip_replay_and_staleness():
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )

    srv = DeltaStreamServer(0)
    applied = []
    cl = DeltaStreamClient(
        "127.0.0.1",
        srv.port,
        0,
        from_tick=-1,
        on_deltas=lambda t, bs: applied.append(
            (t, sum(len(b) for b in bs))
        ),
    )
    cl.start()
    try:
        srv.publish(0, [_batch([(1, 1, ("x", None)), (2, 1, ("y", "m"))])])
        srv.publish(1, [])  # idle marker still advances freshness
        srv.publish(2, [_batch([(1, -1, (None, None))])])
        assert _wait(lambda: applied and applied[-1][0] == 2)
        assert applied == [(0, 2), (1, 0), (2, 1)]
        assert cl.applied_tick == 2
        assert cl.caught_up
        # caught-up replica reads ~0 staleness continuously
        assert cl.staleness_seconds() == 0.0

        # a late subscriber replays the ring tail INCLUDING its
        # boundary tick (consolidated deltas are idempotent state ops;
        # re-applying the boundary is how a same-tick merge from a
        # second index node is never lost)
        late = []
        cl2 = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            1,
            from_tick=0,
            on_deltas=lambda t, bs: late.append(t),
        )
        cl2.start()
        assert _wait(lambda: late and late[-1] == 2)
        assert late == [0, 1, 2]
        cl2.close()
    finally:
        cl.close()
        srv.close()


def test_delta_stream_resync_beyond_ring():
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )

    srv = DeltaStreamServer(0, ring_ticks=2)
    for t in range(10):
        srv.publish(t, [])
    resyncs = []

    def on_resync():
        resyncs.append(1)
        return 8  # "re-hydrated from a generation at tick 8"

    applied = []
    cl = DeltaStreamClient(
        "127.0.0.1",
        srv.port,
        0,
        from_tick=1,  # far below the ring floor
        on_deltas=lambda t, bs: applied.append(t),
        on_resync=on_resync,
    )
    cl.start()
    try:
        assert _wait(lambda: cl.applied_tick >= 9)
        assert resyncs == [1]
        assert cl.resyncs == 1
        # nothing before the re-hydrate tick was (incorrectly) replayed
        # (the boundary tick itself may re-apply — idempotent)
        assert all(t >= 8 for t in applied)
        assert cl.caught_up
    finally:
        cl.close()
        srv.close()


def test_delta_stream_rejects_wrong_secret(monkeypatch):
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
        ReplicationError,
    )

    srv = DeltaStreamServer(0)
    try:
        monkeypatch.setenv("PATHWAY_DCN_SECRET", "a-different-secret")
        cl = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            0,
            from_tick=-1,
            on_deltas=lambda t, bs: None,
            connect_timeout=5.0,
        )
        with pytest.raises(ReplicationError, match="authentication"):
            cl._dial()
    finally:
        srv.close()


def test_writer_never_blocks_on_slow_replica():
    """A replica that stops draining is dropped (bounded outbox), the
    writer's publish cadence is unaffected, and the counter records the
    drop."""
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )

    srv = DeltaStreamServer(0, outbox_depth=8)
    gate = threading.Event()

    def stall(t, bs):
        gate.wait(30.0)

    cl = DeltaStreamClient(
        "127.0.0.1", srv.port, 0, from_tick=-1, on_deltas=stall
    )
    cl.start()
    _wait(lambda: len(srv._subs) == 1, timeout=10)
    t0 = time.monotonic()
    for t in range(200):
        srv.publish(t, [_batch([(t, 1, ("x", None))])])
    publish_wall = time.monotonic() - t0
    assert publish_wall < 5.0  # never blocked on the stalled replica
    assert _wait(lambda: len(srv._subs) == 0, timeout=10)
    gate.set()
    cl.close()
    srv.close()


# ---------------------------------------------------------------------------
# hydration


def _fake_store_with_generations(tmp_path):
    """A persistence store shaped like the writer's: metadata naming a
    newest generation (torn: blob missing) and a retained older one
    (intact)."""
    import pickle

    from pathway_tpu.persistence.backends import FilesystemStore

    store = FilesystemStore(str(tmp_path / "pstorage"))
    old_state = {
        "live_queries": {},
        "emitted": {},
        "index_state": ("dict", {"corpus": "OLD", "metadata": {}}),
    }
    store.put("states/gen-000004/00007.pkl", pickle.dumps(old_state))
    meta = {
        "last_time": 40,
        "chunks": {},
        "state": {
            "gen": 5,
            "time": 50,
            "nodes": {"7": "ExternalIndexNode"},
            # gen-5 blob deliberately missing: a torn latest generation
        },
        "retained_states": [
            {
                "state": {
                    "gen": 4,
                    "time": 40,
                    "nodes": {"7": "ExternalIndexNode"},
                },
                "chunks": {},
            }
        ],
    }
    store.put("metadata.json", json.dumps(meta).encode())
    return store


def test_hydrate_prefers_newest_but_survives_torn_generation(tmp_path):
    from pathway_tpu.serving.replica import hydrate_index_state

    store = _fake_store_with_generations(tmp_path)
    got = hydrate_index_state(store)
    assert got is not None
    index_state, tick, gen = got
    assert gen == 4 and tick == 40
    assert index_state == ("dict", {"corpus": "OLD", "metadata": {}})

    # an intact newest generation wins
    import pickle

    new_state = {
        "live_queries": {},
        "emitted": {},
        "index_state": ("dict", {"corpus": "NEW", "metadata": {}}),
    }
    store.put("states/gen-000005/00007.pkl", pickle.dumps(new_state))
    index_state, tick, gen = hydrate_index_state(store)
    assert gen == 5 and tick == 50
    assert index_state[1]["corpus"] == "NEW"


def test_hydrate_empty_store(tmp_path):
    from pathway_tpu.persistence.backends import FilesystemStore
    from pathway_tpu.serving.replica import hydrate_index_state

    assert (
        hydrate_index_state(FilesystemStore(str(tmp_path / "empty")))
        is None
    )


# ---------------------------------------------------------------------------
# replica HTTP serving


def test_replica_serves_and_sheds_on_staleness_bound():
    import requests

    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving.replica import ReplicaServer

    srv = DeltaStreamServer(0)
    rep = ReplicaServer(
        replica_id=7,
        index_factory=ToyIndex,
        writer_port=srv.port,
        responder=lambda s, v: {
            "n": len(s.index.d),
            "matches": s.search([(None, v.get("k", 3), None)])[0],
        },
        stale_after_ms=500,
    ).start()
    try:
        srv.publish(0, [_batch([(i, 1, (f"d{i}", None)) for i in range(4)])])
        assert _wait(lambda: rep.ready)
        url = f"http://127.0.0.1:{rep.http_port}/query"
        r = requests.post(url, json={"k": 2}, timeout=10)
        assert r.status_code == 200
        assert r.json()["n"] == 4
        assert r.headers["x-pathway-replica"] == "7"
        assert "x-pathway-stale" not in r.headers
        assert float(r.headers["x-pathway-staleness-seconds"]) < 1.0
        # fresh replica passes a zero staleness bound
        r = requests.post(
            url,
            json={},
            headers={"x-pathway-max-staleness-ms": "0"},
            timeout=10,
        )
        assert r.status_code == 200

        # writer dies: staleness grows past the bound -> explicit shed,
        # unbounded reads still answer WITH the stale headers
        srv.close()
        assert _wait(lambda: rep.is_stale(), timeout=10)
        r = requests.post(
            url,
            json={},
            headers={"x-pathway-max-staleness-ms": "100"},
            timeout=10,
        )
        assert r.status_code == 503
        assert "Retry-After" in r.headers
        r = requests.post(url, json={}, timeout=10)
        assert r.status_code == 200
        assert r.headers["x-pathway-stale"] == "true"
        assert float(r.headers["x-pathway-staleness-seconds"]) > 0.4
    finally:
        rep.stop()
        srv.close()


def test_replica_health_endpoint_reports_freshness():
    import requests

    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving.replica import ReplicaServer

    srv = DeltaStreamServer(0)
    rep = ReplicaServer(
        replica_id=3, index_factory=ToyIndex, writer_port=srv.port
    ).start()
    try:
        srv.publish(5, [_batch([(1, 1, ("a", None))])])
        assert _wait(lambda: rep.applied_tick == 5)
        h = requests.get(
            f"http://127.0.0.1:{rep.http_port}/replica/health", timeout=5
        ).json()
        assert h["replica"] == 3
        assert h["applied_tick"] == 5
        assert h["ready"] is True
        assert h["connected"] is True
    finally:
        rep.stop()
        srv.close()


# ---------------------------------------------------------------------------
# failover router


def _start_plane(n_replicas=2, qos=None, stale_after_ms=3000):
    """writer + N toy replicas + router, all in-process."""
    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving.replica import ReplicaServer
    from pathway_tpu.serving.router import FailoverRouter

    srv = DeltaStreamServer(0)
    reps = []
    for rid in range(n_replicas):
        reps.append(
            ReplicaServer(
                replica_id=rid,
                index_factory=ToyIndex,
                writer_port=srv.port,
                responder=lambda s, v: _toy_responder(s, v),
                qos=qos,
                stale_after_ms=stale_after_ms,
            ).start()
        )
    router = FailoverRouter(
        [f"http://127.0.0.1:{r.http_port}" for r in reps],
        health_interval_ms=100,
    ).start()
    return srv, reps, router


def _toy_responder(server, values):
    delay = float(values.get("delay_s", 0.0))
    if delay:
        time.sleep(delay)
    res = server.search([(None, int(values.get("k", 3)), None)])[0]
    return {"matches": [[k, s] for k, s in res], "replica": server.replica_id}


def test_router_two_replica_failover_smoke():
    """Tier-1 failover smoke (<60 s): queries keep answering across a
    replica death; the killed replica's restart is only re-admitted
    once fresh; a mid-query kill is retried on the sibling within the
    original deadline with the retry hop visible in the trace."""
    import requests

    from pathway_tpu.observability import tracing
    from pathway_tpu.serving.replica import ReplicaServer

    srv, reps, router = _start_plane(2)
    try:
        srv.publish(0, [_batch([(i, 1, (f"d{i}", None)) for i in range(3)])])
        assert _wait(lambda: all(r.ready for r in reps))
        assert _wait(
            lambda: all(ep.ready for ep in router.endpoints), timeout=10
        )
        url = f"http://127.0.0.1:{router.port}/query"
        r = requests.post(url, json={"k": 2}, timeout=10)
        assert r.status_code == 200

        failures = []
        router.add_failure_listener(lambda name, why: failures.append(name))

        # mid-query kill: whichever replica holds the in-flight request
        # dies with it (its responder wedges, its server is torn down
        # mid-response); the router retries the SAME request on the
        # sibling within the original deadline
        wedge = threading.Semaphore(1)  # only the FIRST attempt wedges
        gate = threading.Event()

        def wedging_responder(s, v):
            if v.get("block") and wedge.acquire(blocking=False):
                gate.wait(30.0)
                raise RuntimeError("victim never answers")
            return _toy_responder(s, v)

        for rep in reps:
            rep.responder = wedging_responder

        result: dict = {}

        def do_request():
            t0 = time.monotonic()
            r = requests.post(
                url,
                json={"k": 2, "block": True},
                headers={"x-pathway-deadline-ms": "20000"},
                timeout=25,
            )
            result["elapsed"] = time.monotonic() - t0
            result["resp"] = r

        req_t = threading.Thread(target=do_request)
        req_t.start()
        # find the replica holding the wedged in-flight attempt
        assert _wait(
            lambda: any(ep.inflight > 0 for ep in router.endpoints),
            timeout=10,
        )
        victim = next(ep for ep in router.endpoints if ep.inflight > 0)
        victim_idx = int(victim.name.replace("replica", ""))
        reps[victim_idx]._http.stop()  # mid-query death
        req_t.join(timeout=25)
        r = result["resp"]
        assert r.status_code == 200, r.text
        assert r.json()["replica"] != victim_idx
        assert result["elapsed"] < 20.0  # within the original deadline
        # the retry hop is a visible child attempt in the stitched trace
        trace_id = r.headers["traceparent"].split("-")[1]
        attempts = [
            s
            for s in tracing.get_tracer().spans(seconds=60)
            if s.trace_id == trace_id and s.name == "router.attempt"
        ]
        assert len(attempts) == 2, [s.attributes for s in attempts]
        assert {s.attributes.get("replica") for s in attempts} == {
            "replica0",
            "replica1",
        }
        assert _wait(lambda: failures, timeout=10)
        assert failures[0] == victim.name
        gate.set()

        # steady failover: every subsequent request answers 200
        for _ in range(10):
            r = requests.post(url, json={"k": 1}, timeout=10)
            assert r.status_code == 200

        # restart the victim on ITS OLD PORT: re-admitted only once it
        # reports ready (hydrated + caught up with the stream)
        old_port = reps[victim_idx].http_port
        reps[victim_idx].stop()  # release the dead server's stream client
        reps[victim_idx] = ReplicaServer(
            replica_id=victim_idx,
            index_factory=ToyIndex,
            writer_port=srv.port,
            http_port=old_port,
            responder=lambda s, v: _toy_responder(s, v),
        ).start()
        assert _wait(lambda: reps[victim_idx].ready, timeout=15)
        assert _wait(lambda: not victim.ejected, timeout=15)
    finally:
        router.stop()
        for r in reps:
            r.stop()
        srv.close()


def test_router_max_staleness_zero_routes_fresh_or_sheds():
    import requests

    srv, reps, router = _start_plane(2, stale_after_ms=400)
    try:
        srv.publish(0, [_batch([(1, 1, ("a", None))])])
        assert _wait(lambda: all(r.ready for r in reps))
        assert _wait(lambda: all(ep.ready for ep in router.endpoints))
        url = f"http://127.0.0.1:{router.port}/query"
        # fresh plane: a zero bound still routes (staleness == 0)
        r = requests.post(
            url,
            json={},
            headers={"x-pathway-max-staleness-ms": "0"},
            timeout=10,
        )
        assert r.status_code == 200
        # writer gone: every replica exceeds the bound -> explicit 503 +
        # Retry-After from the router (no replica qualifies)
        srv.close()
        assert _wait(lambda: all(r.is_stale() for r in reps), timeout=10)
        assert _wait(
            lambda: all(
                ep.staleness_s is None or ep.staleness_s > 0.4
                for ep in router.endpoints
            ),
            timeout=10,
        )
        r = requests.post(
            url,
            json={},
            headers={"x-pathway-max-staleness-ms": "200"},
            timeout=10,
        )
        assert r.status_code == 503
        assert "Retry-After" in r.headers
        # unbounded reads degrade to a stale answer instead (explicit
        # stale headers — PR 8's contract through the new hop)
        r = requests.post(url, json={}, timeout=10)
        assert r.status_code == 200
        assert r.headers.get("x-pathway-stale") == "true"
    finally:
        router.stop()
        for r in reps:
            r.stop()
        srv.close()


def test_router_occupancy_weighted_pick():
    from pathway_tpu.serving.router import ReplicaEndpoint

    a = ReplicaEndpoint("replica0", "http://a")
    b = ReplicaEndpoint("replica1", "http://b")
    for ep in (a, b):
        ep.ready = True
        ep.staleness_s = 0.0
    a.inflight = 5
    b.inflight = 1
    assert sorted([a, b], key=ReplicaEndpoint.score)[0] is b
    b.reported_inflight = 10  # replica-reported admission occupancy
    assert sorted([a, b], key=ReplicaEndpoint.score)[0] is a
    # ejection disqualifies regardless of load
    a.ejected = True
    assert not a.qualifies(None)
    assert b.qualifies(None)
    # staleness bound disqualifies
    b.staleness_s = 2.0
    assert not b.qualifies(1000.0)
    assert b.qualifies(3000.0)


def test_router_hedges_slow_replica(monkeypatch):
    """PATHWAY_SERVING_HEDGE_MS: a slow primary gets a duplicate on the
    sibling; the fast response wins and exactly one response returns."""
    import requests

    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving.replica import ReplicaServer
    from pathway_tpu.serving.router import FailoverRouter

    srv = DeltaStreamServer(0)
    slow_gate = threading.Event()

    def slow_responder(s, v):
        if s.replica_id == 0:
            slow_gate.wait(10.0)
        return {"replica": s.replica_id}

    reps = [
        ReplicaServer(
            replica_id=rid,
            index_factory=ToyIndex,
            writer_port=srv.port,
            responder=slow_responder,
        ).start()
        for rid in range(2)
    ]
    router = FailoverRouter(
        [f"http://127.0.0.1:{r.http_port}" for r in reps],
        hedge_ms=150,
        health_interval_ms=100,
    ).start()
    try:
        srv.publish(0, [])
        assert _wait(lambda: all(r.ready for r in reps))
        assert _wait(lambda: all(ep.ready for ep in router.endpoints))
        # force the slow replica primary: bias occupancy
        router.endpoints[1].reported_inflight = 5
        t0 = time.monotonic()
        r = requests.post(
            f"http://127.0.0.1:{router.port}/query", json={}, timeout=10
        )
        elapsed = time.monotonic() - t0
        assert r.status_code == 200
        assert r.json()["replica"] == 1  # the hedge won
        assert elapsed < 5.0
    finally:
        slow_gate.set()
        router.stop()
        for r in reps:
            r.stop()
        srv.close()


# ---------------------------------------------------------------------------
# end-to-end: real writer pipeline -> snapshot hydration -> delta stream


def test_pipeline_writer_snapshot_hydration_and_stream(
    tmp_path, monkeypatch
):
    """The full writer path: a real pipeline with persistence publishes
    per-tick corpus deltas; a replica hydrates from the newest committed
    generation, replays the stream tail, reaches freshness, and answers
    KNN reads that match the writer's corpus."""
    import requests

    from pathway_tpu.parallel import replicate
    from pathway_tpu.serving.replica import ReplicaServer, text_vector

    monkeypatch.setenv("PATHWAY_REPL_PORT", "0")
    replicate.reset_publisher()
    DIM = 8
    in_dir = tmp_path / "in"
    q_dir = tmp_path / "q"
    in_dir.mkdir()
    q_dir.mkdir()

    class DocS(pw.Schema):
        text: str

    with open(in_dir / "f0.jsonl", "w") as f:
        for i in range(8):
            f.write(json.dumps({"text": f"doc {i}"}) + "\n")

    docs = pw.io.jsonlines.read(str(in_dir), schema=DocS, mode="streaming")
    docs = docs.select(
        vec=pw.apply(lambda t: text_vector(t, DIM), docs.text),
        text=docs.text,
    )
    queries = pw.io.jsonlines.read(
        str(q_dir), schema=DocS, mode="streaming"
    )
    queries = queries.select(
        vec=pw.apply(lambda t: text_vector(t, DIM), queries.text)
    )
    from pathway_tpu.stdlib.indexing import DataIndex, TpuKnn

    index = DataIndex(docs, TpuKnn(docs.vec, dimensions=DIM))
    res = index.query_as_of_now(queries.vec, number_of_matches=2).select(
        texts=pw.right.text
    )
    pw.io.null.write(res)

    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(tmp_path / "pstorage")),
        snapshot_every=2,
    )
    run_t = threading.Thread(
        target=lambda: pw.run(
            persistence_config=cfg, autocommit_duration_ms=25
        ),
        daemon=True,
    )
    run_t.start()
    rep = None
    try:
        assert _wait(
            lambda: replicate.publisher() is not None
            and replicate.publisher().newest_tick() >= 0,
            timeout=60,
        )
        pub = replicate.publisher()
        for i in range(8, 20):
            with open(in_dir / f"f{i}.jsonl", "w") as f:
                f.write(json.dumps({"text": f"doc {i}"}) + "\n")
            time.sleep(0.05)
        from pathway_tpu.persistence.backends import FilesystemStore
        from pathway_tpu.serving.replica import hydrate_index_state

        assert _wait(
            lambda: hydrate_index_state(
                FilesystemStore(str(tmp_path / "pstorage"))
            )
            is not None,
            timeout=60,
        )
        from pathway_tpu.stdlib.indexing._index_impls import (
            TpuDenseKnnIndex,
        )

        rep = ReplicaServer(
            replica_id=0,
            index_factory=lambda: TpuDenseKnnIndex(dimensions=DIM),
            store_root=str(tmp_path / "pstorage"),
            writer_port=pub.port,
            dim=DIM,
        ).start()
        assert rep.hydrated_tick >= 0  # came from a real generation
        assert _wait(lambda: rep.ready, timeout=30)
        assert _wait(
            lambda: rep.index.corpus is not None
            and len(rep.index.corpus) == 20,
            timeout=30,
        )
        r = requests.post(
            f"http://127.0.0.1:{rep.http_port}/query",
            json={"query": "doc 12", "k": 1},
            timeout=15,
        )
        assert r.status_code == 200
        # exact self-match under the deterministic pseudo-embedder:
        # cosine distance score -(1-cos) == 0 for the identical vector
        top = r.json()["matches"][0]
        assert abs(top[1]) < 1e-5
    finally:
        if rep is not None:
            rep.stop()
        rt = pw.internals.parse_graph.G.runtime
        if rt is not None:
            rt.stop()
        run_t.join(timeout=30)


def test_gated_replica_sheds_429_not_500():
    """A replica behind a Surge-Gate admission envelope sheds with an
    explicit 429 + Retry-After — never a 500 (regression: the ShedError
    handler used to miss its import and turn every shed into an
    error)."""
    import requests

    from pathway_tpu.parallel.replicate import DeltaStreamServer
    from pathway_tpu.serving import QoSConfig
    from pathway_tpu.serving.replica import ReplicaServer

    srv = DeltaStreamServer(0)
    rep = ReplicaServer(
        replica_id=9,
        index_factory=ToyIndex,
        writer_port=srv.port,
        responder=lambda s, v: {"ok": True},
        qos=QoSConfig(rate_limit_rps=1.0, rate_limit_burst=1.0),
    ).start()
    try:
        srv.publish(0, [])
        assert _wait(lambda: rep.ready)
        url = f"http://127.0.0.1:{rep.http_port}/query"
        codes = []
        for _ in range(8):
            r = requests.post(url, json={}, timeout=10)
            codes.append(r.status_code)
            if r.status_code == 429:
                assert "Retry-After" in r.headers
        assert 200 in codes and 429 in codes, codes
        assert 500 not in codes, codes
    finally:
        rep.stop()
        srv.close()


def test_second_publish_same_tick_reaches_live_subscribers():
    """Two index nodes publishing the SAME lockstep tick: the second
    frame merges into the ring AND still applies on live subscribers
    (equal-tick frames are not skipped — consolidated deltas are
    idempotent), so connected replicas and ring-replaying replicas
    converge to the same corpus."""
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )

    srv = DeltaStreamServer(0)
    seen: dict[int, set] = {}
    cl = DeltaStreamClient(
        "127.0.0.1",
        srv.port,
        0,
        from_tick=-1,
        on_deltas=lambda t, bs: seen.setdefault(t, set()).update(
            k for b in bs for k, _d, _v in b.iter_rows()
        ),
    )
    cl.start()
    try:
        _wait(lambda: len(srv._subs) == 1, timeout=10)
        srv.publish(5, [_batch([(1, 1, ("a", None))])])
        srv.publish(5, [_batch([(2, 1, ("b", None))])])  # second node
        assert _wait(lambda: seen.get(5) == {1, 2}, timeout=10), seen
        # ...and a late ring-replayer sees the merged entry too
        late: dict[int, set] = {}
        cl2 = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            1,
            from_tick=-1,
            on_deltas=lambda t, bs: late.setdefault(t, set()).update(
                k for b in bs for k, _d, _v in b.iter_rows()
            ),
        )
        cl2.start()
        assert _wait(lambda: late.get(5) == {1, 2}, timeout=10), late
        cl2.close()
    finally:
        cl.close()
        srv.close()


def test_deep_rejoin_backlog_larger_than_outbox():
    """A replica rejoining from hundreds of ticks behind (backlog far
    beyond the sender outbox bound) replays the whole tail — the
    backlog rides a dedicated list, never put_nowait into the bounded
    outbox (regression: queue.Full used to kill the handshake thread
    and livelock the rejoin)."""
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )

    srv = DeltaStreamServer(0, outbox_depth=16)
    for t in range(600):
        srv.publish(t, [_batch([(t, 1, ("x", None))])])
    applied = []
    cl = DeltaStreamClient(
        "127.0.0.1",
        srv.port,
        0,
        from_tick=100,
        on_deltas=lambda t, bs: applied.append(t),
    )
    cl.start()
    try:
        assert _wait(lambda: cl.applied_tick == 599, timeout=20)
        assert applied == list(range(100, 600))
        assert cl.resyncs == 0  # within the ring: replay, not resync
    finally:
        cl.close()
        srv.close()
