"""Data-lake writer depth (VERDICT r3 item 8; reference:
src/connectors/data_lake/{delta,iceberg,writer}.rs): transactional
append/overwrite, schema-evolution guards, object storage, compaction,
round-trip write->read for both formats."""

import json
import os

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_to_dicts
from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.io.deltalake import _DeltaWriter, _Store, _replay_log


class KV(pw.Schema):
    k: str = pw.column_definition(primary_key=True)
    v: int


def _write_rows(writer, rows, t=0):
    writer.write_batch(
        t, DiffBatch.from_rows([(i, 1, r) for i, r in enumerate(rows)], ["k", "v"])
    )


def _read_static_delta(uri):
    pw.internals.parse_graph.G.clear()
    t = pw.io.deltalake.read(uri, schema=KV, mode="static")
    _keys, cols = table_to_dicts(t)
    return {cols["k"][key]: cols["v"][key] for key in cols["k"]}


def test_delta_overwrite_mode(tmp_path):
    lake = str(tmp_path / "lake")
    w = _DeltaWriter(_Store(lake), ["k", "v"])
    _write_rows(w, [("a", 1), ("b", 2)])
    assert _read_static_delta(lake) == {"a": 1, "b": 2}
    # overwrite: old parts removed via log actions, only new data remains
    w2 = _DeltaWriter(_Store(lake), ["k", "v"], mode="overwrite")
    _write_rows(w2, [("c", 3)])
    assert _read_static_delta(lake) == {"c": 3}
    # old parquet parts still on disk (no vacuum), but log replay drops them
    files, _meta = _replay_log(_Store(lake))
    assert len(files) == 1


def test_delta_schema_evolution_guard(tmp_path):
    lake = str(tmp_path / "lake")
    w = _DeltaWriter(
        _Store(lake), ["k", "v"], [{"name": "k", "type": "str"}, {"name": "v", "type": "int"}]
    )
    _write_rows(w, [("a", 1)])
    # dropping a column is refused
    with pytest.raises(ValueError, match="drops existing"):
        _DeltaWriter(_Store(lake), ["k"], [{"name": "k", "type": "str"}])
    # changing a type is refused
    with pytest.raises(ValueError, match="changes type"):
        _DeltaWriter(
            _Store(lake),
            ["k", "v"],
            [{"name": "k", "type": "str"}, {"name": "v", "type": "str"}],
        )
    # adding a column needs opt-in
    three = [
        {"name": "k", "type": "str"},
        {"name": "v", "type": "int"},
        {"name": "w", "type": "int"},
    ]
    with pytest.raises(ValueError, match="allow_add"):
        _DeltaWriter(_Store(lake), ["k", "v", "w"], three)
    w3 = _DeltaWriter(
        _Store(lake), ["k", "v", "w"], three, schema_evolution="allow_add"
    )
    w3.write_batch(
        1, DiffBatch.from_rows([(9, 1, ("c", 3, 30))], ["k", "v", "w"])
    )
    # evolved metadata is now the table schema
    _files, meta = _replay_log(_Store(lake))
    assert {f["name"] for f in meta["fields"]} == {"k", "v", "w"}
    # old rows read back with None for the new column
    class KVW(pw.Schema):
        k: str = pw.column_definition(primary_key=True)
        v: int
        w: int | None

    pw.internals.parse_graph.G.clear()
    t = pw.io.deltalake.read(lake, schema=KVW, mode="static")
    _keys, cols = table_to_dicts(t)
    got = {cols["k"][key]: (cols["v"][key], cols["w"][key]) for key in cols["k"]}
    assert got == {"a": (1, None), "c": (3, 30)}


def test_delta_compaction(tmp_path):
    lake = str(tmp_path / "lake")
    w = _DeltaWriter(_Store(lake), ["k", "v"], compact_every=3)
    for i in range(7):
        _write_rows(w, [(f"k{i}", i)], t=i)
    files, _meta = _replay_log(_Store(lake))
    # 7 appends with compact_every=3: active files merged periodically
    assert len(files) <= 3, files
    assert _read_static_delta(lake) == {f"k{i}": i for i in range(7)}


def test_delta_optimistic_concurrency(tmp_path):
    lake = str(tmp_path / "lake")
    w1 = _DeltaWriter(_Store(lake), ["k", "v"])
    w2 = _DeltaWriter(_Store(lake), ["k", "v"])
    # both writers believe they own the same next version; the commit
    # protocol must keep BOTH batches (exclusive create + retry)
    _write_rows(w1, [("a", 1)])
    _write_rows(w2, [("b", 2)])
    assert _read_static_delta(lake) == {"a": 1, "b": 2}


def test_delta_object_store_roundtrip():
    """The same writer/reader path over an fsspec object store (memory://
    here; s3:// uses the identical code path)."""
    import uuid

    uri = f"memory://lake-{uuid.uuid4().hex}"
    w = _DeltaWriter(_Store(uri), ["k", "v"])
    _write_rows(w, [("a", 1), ("b", 2)])
    assert _read_static_delta(uri) == {"a": 1, "b": 2}


def test_delta_streaming_retracts_on_overwrite(tmp_path):
    """The streaming reader emits retractions for removed files, so an
    overwrite flows as an incremental update."""
    import threading
    import time

    lake = str(tmp_path / "lake")
    w = _DeltaWriter(_Store(lake), ["k", "v"])
    _write_rows(w, [("a", 1), ("b", 2)])

    pw.internals.parse_graph.G.clear()
    t = pw.io.deltalake.read(lake, schema=KV, mode="streaming")
    seen = {}
    lock = threading.Lock()

    def on_change(key, row, time, is_addition):
        with lock:
            if is_addition:
                seen[row["k"]] = row["v"]
            else:
                seen.pop(row["k"], None)

    pw.io.subscribe(t, on_change)
    th = threading.Thread(
        target=lambda: pw.run(autocommit_duration_ms=20), daemon=True
    )
    th.start()
    deadline = time.time() + 15
    while time.time() < deadline and seen != {"a": 1, "b": 2}:
        time.sleep(0.05)
    assert seen == {"a": 1, "b": 2}, seen
    w2 = _DeltaWriter(_Store(lake), ["k", "v"], mode="overwrite")
    _write_rows(w2, [("c", 3)], t=1)
    while time.time() < deadline and seen != {"c": 3}:
        time.sleep(0.05)
    rt = pw.internals.parse_graph.G.runtime
    if rt is not None:
        rt.stop()
    th.join(timeout=10)
    assert seen == {"c": 3}, seen


# --- iceberg ---------------------------------------------------------------


def _read_static_iceberg(uri):
    pw.internals.parse_graph.G.clear()
    t = pw.io.iceberg.read(uri, schema=KV, mode="static")
    _keys, cols = table_to_dicts(t)
    return {cols["k"][key]: cols["v"][key] for key in cols["k"]}


def test_iceberg_roundtrip_append_overwrite(tmp_path):
    from pathway_tpu.io.iceberg import _IcebergWriter

    root = str(tmp_path / "warehouse")
    desc = [{"name": "k", "type": "str"}, {"name": "v", "type": "int"}]
    w = _IcebergWriter(root, ["k", "v"], desc)
    _write_rows(w, [("a", 1)])
    w2 = _IcebergWriter(root, ["k", "v"], desc)  # append continues
    _write_rows(w2, [("b", 2)])
    assert _read_static_iceberg(root) == {"a": 1, "b": 2}
    w3 = _IcebergWriter(root, ["k", "v"], desc, mode="overwrite")
    _write_rows(w3, [("c", 3)])
    assert _read_static_iceberg(root) == {"c": 3}
    # snapshot history retained in metadata
    from pathway_tpu.io.iceberg import _current_version, _snapshot_meta

    meta = _snapshot_meta(root, _current_version(root))
    assert len(meta["snapshots"]) >= 3
    assert meta["schema"]["fields"] == desc


def test_iceberg_schema_guard(tmp_path):
    from pathway_tpu.io.iceberg import _IcebergWriter

    root = str(tmp_path / "warehouse")
    desc = [{"name": "k", "type": "str"}, {"name": "v", "type": "int"}]
    w = _IcebergWriter(root, ["k", "v"], desc)
    _write_rows(w, [("a", 1)])
    with pytest.raises(ValueError, match="drops existing"):
        _IcebergWriter(root, ["k"], [{"name": "k", "type": "str"}])
    with pytest.raises(ValueError, match="allow_add"):
        _IcebergWriter(
            root,
            ["k", "v", "w"],
            desc + [{"name": "w", "type": "int"}],
        )
    _IcebergWriter(
        root,
        ["k", "v", "w"],
        desc + [{"name": "w", "type": "int"}],
        schema_evolution="allow_add",
    )


def test_iceberg_streaming_retracts_on_overwrite(tmp_path):
    import threading
    import time

    from pathway_tpu.io.iceberg import _IcebergWriter

    root = str(tmp_path / "warehouse")
    desc = [{"name": "k", "type": "str"}, {"name": "v", "type": "int"}]
    w = _IcebergWriter(root, ["k", "v"], desc)
    _write_rows(w, [("a", 1)])

    pw.internals.parse_graph.G.clear()
    t = pw.io.iceberg.read(root, schema=KV, mode="streaming")
    seen = {}
    lock = threading.Lock()

    def on_change(key, row, time, is_addition):
        with lock:
            if is_addition:
                seen[row["k"]] = row["v"]
            else:
                seen.pop(row["k"], None)

    pw.io.subscribe(t, on_change)
    th = threading.Thread(
        target=lambda: pw.run(autocommit_duration_ms=20), daemon=True
    )
    th.start()
    deadline = time.time() + 15
    while time.time() < deadline and seen != {"a": 1}:
        time.sleep(0.05)
    assert seen == {"a": 1}, seen
    w2 = _IcebergWriter(root, ["k", "v"], desc, mode="overwrite")
    _write_rows(w2, [("z", 9)], t=1)
    while time.time() < deadline and seen != {"z": 9}:
        time.sleep(0.05)
    rt = pw.internals.parse_graph.G.runtime
    if rt is not None:
        rt.stop()
    th.join(timeout=10)
    assert seen == {"z": 9}, seen


def test_delta_overwrite_is_atomic_with_first_batch(tmp_path):
    """Constructing an overwrite writer must NOT empty the table; the
    removes commit together with the first data batch (one atomic delta
    commit — an aborted pipeline leaves the table intact)."""
    lake = str(tmp_path / "lake")
    w = _DeltaWriter(_Store(lake), ["k", "v"])
    _write_rows(w, [("a", 1)])
    w2 = _DeltaWriter(_Store(lake), ["k", "v"], mode="overwrite")
    # no batch written yet: table unchanged
    assert _read_static_delta(lake) == {"a": 1}
    _write_rows(w2, [("b", 2)])
    assert _read_static_delta(lake) == {"b": 2}


def test_iceberg_overwrite_is_atomic_with_first_batch(tmp_path):
    from pathway_tpu.io.iceberg import _IcebergWriter

    root = str(tmp_path / "warehouse")
    desc = [{"name": "k", "type": "str"}, {"name": "v", "type": "int"}]
    w = _IcebergWriter(root, ["k", "v"], desc)
    _write_rows(w, [("a", 1)])
    w2 = _IcebergWriter(root, ["k", "v"], desc, mode="overwrite")
    assert _read_static_iceberg(root) == {"a": 1}
    _write_rows(w2, [("b", 2)])
    assert _read_static_iceberg(root) == {"b": 2}
