"""Ported reference groupby/reducer tests
(reference: python/pathway/tests/test_common.py groupby section) — group
key derivation, reducers over expressions and expressions over reducers,
multi-column groups, id= grouping, argmin/argmax tie-break by lowest key,
avg, element-wise ndarray sums, ndarray reducer with sort_by, and
earliest/latest streaming semantics."""

from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T

from tests.ref_utils import (
    assert_stream_equality,
    assert_table_equality,
    assert_table_equality_wo_index,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    yield
    pw.internals.parse_graph.G.clear()


def test_groupby_simplest():
    left = T(
        """
    pet  |  owner  | age
    dog  | Alice   | 10
    dog  | Bob     | 9
    cat  | Alice   | 8
    dog  | Bob     | 7
    """
    )
    left_res = left.groupby(left.pet).reduce(left.pet)
    assert_table_equality_wo_index(
        left_res,
        T(
            """
        pet
        dog
        cat
    """
        ),
    )


def test_groupby_singlecol():
    left = T(
        """
    pet  |  owner  | age
    dog  | Alice   | 10
    dog  | Bob     | 9
    cat  | Alice   | 8
    dog  | Bob     | 7
    """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet, ageagg=pw.reducers.sum(left.age)
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
        pet  | ageagg
        dog  | 26
        cat  | 8
    """
        ),
    )


def test_groupby_int_sum():
    left = T(
        """
    owner   | val
    Alice   | 1
    Alice   | -1
    Bob     | 0
    Bob     | 0
    Charlie | 1
    Charlie | 0
    Dee     | 5
    Dee     | 5
    """
    )
    left_res = left.groupby(left.owner).reduce(
        left.owner, val=pw.reducers.sum(left.val)
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
        owner   | val
        Alice   | 0
        Bob     | 0
        Charlie | 1
        Dee     | 10
    """
        ),
    )


def test_groupby_filter_singlecol():
    left = T(
        """
      pet  |  owner  | age
      dog  | Alice   | 10
      dog  | Bob     | 9
      cat  | Alice   | 8
      dog  | Bob     | 7
      cat  | Alice   | 6
      dog  | Bob     | 5
    """
    )
    left_res = (
        left.filter(left.age > 6)
        .groupby(pw.this.pet)
        .reduce(pw.this.pet, ageagg=pw.reducers.sum(pw.this.age))
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
        pet  | ageagg
        dog  | 26
        cat  | 8
    """
        ),
    )


def test_groupby_reducer_on_expression():
    left = T(
        """
    pet  |  owner  | age
    dog  | Alice   | 10
    dog  | Bob     | 9
    cat  | Alice   | 8
    dog  | Bob     | 7
    """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet, ageagg=pw.reducers.min(left.age + left.age)
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
    pet  | ageagg
    dog  | 14
    cat  | 16
    """
        ),
    )


def test_groupby_expression_on_reducers():
    left = T(
        """
    pet  |  owner  | age
    dog  | Alice   | 10
    dog  | Bob     | 9
    cat  | Alice   | 8
    dog  | Bob     | 7
    """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet,
        ageagg=pw.reducers.min(left.age) + pw.reducers.sum(left.age),
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
    pet  | ageagg
    dog  | 33
    cat  | 16
    """
        ),
    )


def test_groupby_reduce_no_columns():
    input = T(
        """
        a
        1
        2
        """
    )
    ret = input.reduce().select(col=42)
    assert_table_equality_wo_index(
        ret,
        T(
            """
            col
            42
            """
        ),
    )


def test_groupby_mutlicol():
    left = T(
        """
    pet  |  owner  | age
    dog  | Alice   | 10
    dog  | Bob     | 9
    cat  | Alice   | 8
    dog  | Bob     | 7
    """
    )
    left_res = left.groupby(left.pet, left.owner).reduce(
        left.pet, left.owner, ageagg=pw.reducers.sum(left.age)
    )
    assert_table_equality_wo_index(
        left_res,
        T(
            """
    pet  |  owner  | ageagg
    dog  | Alice   | 10
    dog  | Bob     | 16
    cat  | Alice   | 8
    """
        ),
    )


def test_groupby_mix_key_val():
    left = T(
        """
    pet  |  owner  | age
     1   | Alice   | 10
     1   | Bob     | 9
     2   | Alice   | 8
     1   | Bob     | 7
    """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet, ageagg=pw.reducers.min(left.age + left.pet)
    )
    right = T(
        """
        pet | ageagg
        1   |      8
        2   |     10
        """
    )
    assert_table_equality_wo_index(left_res, right)


def test_groupby_mix_key_val2():
    left = T(
        """
    pet  |  owner  | age
     1   | Alice   | 10
     1   | Bob     | 9
     2   | Alice   | 8
     1   | Bob     | 7
    """
    )
    right = T(
        """
          | pet | ageagg
        1 | 1   |      8
        2 | 2   |     10
        """
    )
    res = right.with_id_from(right.pet)
    assert_table_equality(
        res,
        left.groupby(left.pet).reduce(
            left.pet, ageagg=pw.reducers.min(left.age) + left.pet
        ),
    )
    assert_table_equality(
        res,
        left.groupby(left.pet).reduce(
            left.pet, ageagg=pw.reducers.min(left.age + left.pet)
        ),
    )


def test_groupby_key_expressions():
    left = T(
        """
    pet  |  owner  | age
     1   | Alice   | 10
     1   | Bob     | 9
     2   | Alice   | 8
     1   | Bob     | 7
    """
    )
    right = T(
        """
        pet  | pet2
        1    | 1
        2    | 2
        """
    )
    res = right.with_id_from(right.pet)
    assert_table_equality(
        res, left.groupby(left.pet).reduce(left.pet, pet2=left.pet)
    )
    with pytest.raises(Exception):
        left.groupby(left.pet).reduce(age2=left.age)


def test_groupby_similar_tables():
    a = T(
        """
            | pet  |  owner  | age
        1   | dog  | Alice   | 10
        2   | dog  | Bob     | 9
        3   | cat  | Alice   | 8
        4   | dog  | Bob     | 7
        """
    )
    b = a.select(*pw.this)
    r1 = a.groupby(b.pet).reduce(
        a.pet, agemin=pw.reducers.min(a.age), agemax=pw.reducers.max(b.age)
    )
    r2 = b.groupby(a.pet).reduce(
        b.pet, agemin=pw.reducers.min(b.age), agemax=pw.reducers.max(a.age)
    )
    expected = T(
        """
        pet | agemin | agemax
        cat | 8      | 8
        dog | 7      | 10
        """,
        id_from=["pet"],
    )
    assert_table_equality(r1, expected)
    assert_table_equality(r2, expected)


def test_argmin_argmax_tie():
    table = T(
        """
       name   | age
      Charlie |  18
      Alice   |  18
      Bob     |  18
      David   |  19
      Erin    |  19
      Frank   |  20
    """,
        unsafe_trusted_ids=True,
    )
    # adaptation: argmin/argmax pointers resolve via ix on the reduced
    # table (in-reduce ix(context=pw.this) lookups are not supported here)
    agg = table.groupby(table.age).reduce(
        table.age,
        amin=pw.reducers.argmin(table.age),
        amax=pw.reducers.argmax(table.age),
    )
    res = agg.select(
        agg.age,
        min=table.ix(agg.amin).name,
        max=table.ix(agg.amax).name,
    )
    expected = T(
        """
        age |     min |     max
         18 | Charlie | Charlie
         19 | David   | David
         20 | Frank   | Frank
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_avg_reducer():
    t1 = T(
        """
    owner   | age
    Alice   | 10
    Bob     | 5
    Alice   | 20
    Bob     | 10
    """
    )
    res = t1.groupby(pw.this.owner).reduce(
        pw.this.owner, avg=pw.reducers.avg(pw.this.age)
    )
    expected = T(
        """
     owner  | avg
     Alice  | 15
     Bob    | 7.5
    """
    )
    assert_table_equality_wo_index(res, expected)


def test_npsum_reducer_ints():
    t = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "data": [
                    np.array([1, 2, 3]),
                    np.array([4, 5, 6]),
                    np.array([7, 8, 9]),
                ]
            }
        )
    )
    result = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "sum": [
                    np.array([12, 15, 18]),
                ]
            }
        )
    )
    assert_table_equality_wo_index(
        t.reduce(sum=pw.reducers.sum(pw.this.data)), result
    )


def test_npsum_reducer_floats():
    t = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "data": [
                    np.array([1.1, 2.1, 3.1]),
                    np.array([4.1, 5.1, 6.1]),
                    np.array([7.1, 8.1, 9.1]),
                ]
            }
        )
    )
    result = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "sum": [
                    np.array([12.3, 15.3, 18.3]),
                ]
            }
        )
    )
    assert_table_equality_wo_index(
        t.reduce(sum=pw.reducers.sum(pw.this.data)), result
    )


def test_ndarray_reducer():
    t = pw.debug.table_from_markdown(
        """
       | colA | colB
    3  | valA | -1
    2  | valA | 1
    5  | valA | 2
    4  | valB | 4
    6  | valB | 4
    1  | valB | 7
    """,
        unsafe_trusted_ids=True,
    )
    expected = pw.debug.table_from_pandas(
        pd.DataFrame(
            {"tuple": [np.array([1, -1, 2]), np.array([7, 4, 4])]}
        )
    )
    res = t.groupby(t.colA).reduce(tuple=pw.reducers.ndarray(t.colB))
    assert_table_equality_wo_index(res, expected)


def test_ndarray_reducer_on_ndarrays():
    t = pw.debug.table_from_markdown(
        """
        a | b | val
        0 | 0 | 1
        0 | 0 | 2
        0 | 1 | 3
        0 | 1 | 4
        1 | 0 | 5
        1 | 0 | 6
        1 | 0 | 7
        1 | 1 | 8
        1 | 1 | 9
        1 | 1 | 0
    """
    )
    s = t.groupby(pw.this.a, pw.this.b, sort_by=pw.this.val).reduce(
        pw.this.a, val=pw.reducers.ndarray(pw.this.val)
    )
    res = s.groupby(pw.this.a, sort_by=pw.this.val).reduce(
        pw.this.a, val=pw.reducers.ndarray(pw.this.val)
    )
    expected = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "a": [0, 1],
                "val": [
                    np.array([[1, 2], [3, 4]]),
                    np.array([[0, 8, 9], [5, 6, 7]]),
                ],
            }
        )
    )
    assert_table_equality_wo_index(res, expected)


def test_earliest_and_latest_reducer():
    t = T(
        """
        a | b | __time__
        1 | 2 |     2
        2 | 3 |     2
        1 | 4 |     4
        2 | 2 |     6
        1 | 1 |     8
    """
    )
    res = t.groupby(pw.this.a).reduce(
        pw.this.a,
        earliest=pw.reducers.earliest(pw.this.b),
        latest=pw.reducers.latest(pw.this.b),
    )
    expected = T(
        """
        a | earliest | latest | __time__ | __diff__
        1 |     2    |    2   |     2    |     1
        2 |     3    |    3   |     2    |     1
        1 |     2    |    2   |     4    |    -1
        1 |     2    |    4   |     4    |     1
        2 |     3    |    3   |     6    |    -1
        2 |     3    |    2   |     6    |     1
        1 |     2    |    4   |     8    |    -1
        1 |     2    |    1   |     8    |     1
    """,
        id_from=["a"],
    )
    assert_stream_equality(res, expected)


def test_earliest_and_latest_reducer_tie():
    t = T(
        """
        a
        1
        2
        3
    """
    )
    res = t.reduce(
        earliest=pw.reducers.earliest(pw.this.a),
        latest=pw.reducers.latest(pw.this.a),
    )
    # single-tick ties break by key order (reference: the row with the
    # lowest key is earliest, the greatest key is latest). Keys are hashed
    # row numbers, so derive the expected winners from the actual key
    # order instead of the reference's literal 2/1.
    src_keys, src_cols = pw.debug.table_to_dicts(t)
    by_key = sorted((int(k), v) for k, v in src_cols["a"].items())
    exp_earliest, exp_latest = by_key[0][1], by_key[-1][1]
    pw.internals.parse_graph.G.clear()
    t2 = T(
        """
        a
        1
        2
        3
    """
    )
    res2 = t2.reduce(
        earliest=pw.reducers.earliest(pw.this.a),
        latest=pw.reducers.latest(pw.this.a),
    )
    keys, cols = pw.debug.table_to_dicts(res2)
    assert list(cols["earliest"].values()) == [exp_earliest]
    assert list(cols["latest"].values()) == [exp_latest]
