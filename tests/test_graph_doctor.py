"""Graph Doctor (pathway_tpu.analysis): one positive and one negative
case per rule, the three severity modes of ``pw.run(diagnostics=...)``,
the ``python -m pathway_tpu.analysis`` CLI, and regressions for the
round-5 advice fixes that shipped in the same change."""

import json
import pathlib
import shutil
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.analysis import (
    GraphDoctorError,
    Severity,
    rule,
    run_doctor,
    suppress,
)
from pathway_tpu.analysis.rules import RULES

REPO = pathlib.Path(__file__).resolve().parent.parent


# --- fixtures --------------------------------------------------------------


class _ClosedSubject(pw.io.python.ConnectorSubject):
    """Streaming source that produces nothing: enough to mark the input
    unbounded for the static pass without running anything."""

    def run(self) -> None:
        self.close()


class _KV(pw.Schema):
    k: str
    v: int


def _stream():
    return pw.io.python.read(_ClosedSubject(), schema=_KV)


def _static():
    return pw.debug.table_from_markdown(
        """
        k | v
        a | 1
        b | 2
        """
    )


def _static_other():
    # different key set: debug fixtures with identical keys share one
    # Universe, which would defeat the universe-safety cases
    return pw.debug.table_from_markdown(
        """
        k | v
        c | 3
        """
    )


def _rules_of(report):
    return {d.rule for d in report}


# --- rule: dead-node -------------------------------------------------------


def test_dead_node_positive():
    t = _static()
    orphan = t.select(doubled=pw.this.v * 2)  # noqa: F841 — deliberately dead
    pw.io.null.write(t.select(pw.this.k))
    report = run_doctor()
    dead = report.by_rule("dead-node")
    assert len(dead) == 1
    assert dead[0].severity == Severity.WARNING
    assert dead[0].node is orphan._node
    # provenance points at THIS test file
    assert dead[0].node.trace[0].endswith("test_graph_doctor.py")


def test_dead_node_negative():
    t = _static()
    pw.io.null.write(t.select(doubled=pw.this.v * 2))
    assert not run_doctor().by_rule("dead-node")


def test_dead_node_flags_frontier_only():
    # a dead CHAIN yields one diagnostic (the deepest table), not one per node
    t = _static()
    a = t.select(x=pw.this.v + 1)
    b = a.select(y=pw.this.x + 1)  # noqa: F841
    pw.io.null.write(t.select(pw.this.k))
    assert len(run_doctor().by_rule("dead-node")) == 1


# --- rule: dead-column -----------------------------------------------------


def test_dead_column_positive():
    t = _static()
    t2 = t.select(pw.this.k, unused=pw.this.v * 10)
    pw.io.null.write(t2.select(pw.this.k))
    dead = run_doctor().by_rule("dead-column")
    assert [d.data["column"] for d in dead] == ["unused"]
    assert dead[0].severity == Severity.INFO


def test_dead_column_negative_consumed_and_passthrough():
    t = _static()
    # `v` is a zero-cost passthrough reference, `used` is consumed: neither
    # may be flagged
    t2 = t.select(pw.this.k, pw.this.v, used=pw.this.v * 10)
    pw.io.null.write(t2.select(pw.this.k, pw.this.used))
    assert not run_doctor().by_rule("dead-column")


# --- rule: unbounded-state -------------------------------------------------


def test_unbounded_state_streaming_groupby():
    t = _stream()
    r = t.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.v))
    pw.io.null.write(r)
    found = run_doctor().by_rule("unbounded-state")
    assert len(found) == 1
    assert found[0].severity == Severity.WARNING
    assert "groupby" in found[0].message


def test_unbounded_state_static_groupby_negative():
    t = _static()
    r = t.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.v))
    pw.io.null.write(r)
    assert not run_doctor().by_rule("unbounded-state")


def test_unbounded_state_streaming_join():
    left, right = _stream(), _stream()
    j = left.join(right, left.k == right.k).select(v1=left.v, v2=right.v)
    pw.io.null.write(j)
    found = run_doctor().by_rule("unbounded-state")
    assert len(found) == 1
    assert "retains every row" in found[0].message


def test_unbounded_state_windowed_with_behavior_negative():
    class _TimedSchema(pw.Schema):
        k: str
        t: int

    t = pw.io.python.read(_ClosedSubject(), schema=_TimedSchema)
    counts = t.windowby(
        pw.this.t,
        window=pw.temporal.tumbling(duration=10),
        instance=pw.this.k,
        behavior=pw.temporal.common_behavior(cutoff=30),
    ).reduce(k=pw.this._pw_instance, n=pw.reducers.count())
    pw.io.null.write(counts)
    # the behavior desugars into a Forget/Freeze guard on the path: no
    # warning-level unbounded-state finding survives
    report = run_doctor()
    assert not [
        d
        for d in report.by_rule("unbounded-state")
        if d.severity >= Severity.WARNING
    ]


# --- rule: universe-safety -------------------------------------------------


def test_universe_safety_unrelated_restrict():
    t1, t2 = _static(), _static_other()
    pw.io.null.write(t2.with_universe_of(t1))
    found = run_doctor().by_rule("universe-safety")
    assert len(found) == 1
    assert found[0].severity == Severity.WARNING


def test_universe_safety_promised_subset_negative():
    t1, t2 = _static(), _static_other()
    t2p = t2.promise_universe_is_subset_of(t1)
    pw.io.null.write(t2p.with_universe_of(t1))
    assert not run_doctor().by_rule("universe-safety")


def test_universe_safety_having_negative():
    # having() IS the sanctioned drop-missing-keys filter; it must not
    # trip the unchecked-restrict warning
    t = _static()
    keys = _static_other().select(ptr=t.pointer_from(pw.this.k))
    pw.io.null.write(t.having(keys.ptr))
    assert not run_doctor().by_rule("universe-safety")


def test_universe_safety_concat_promise_is_info():
    t1, t2 = _static(), _static_other()
    pw.universes.promise_are_pairwise_disjoint(t1, t2)
    pw.io.null.write(t1.concat(t2))
    found = run_doctor().by_rule("universe-safety")
    assert found and all(d.severity == Severity.INFO for d in found)
    assert "PROMISE" in found[0].message


# --- rules: shard safety ---------------------------------------------------


def test_shard_exchange_groupby():
    t = _static()
    r = t.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.v))
    pw.io.null.write(r)
    found = run_doctor().by_rule("shard-exchange")
    assert len(found) == 1
    # anchored at the GroupByNode (where the exchange happens), which the
    # reduce's rowwise projection consumes
    assert found[0].node is r._node.inputs[0]
    assert type(found[0].node).__name__ == "GroupByNode"
    # routing keys reported in user terms, not prep-column names (_g0)
    assert found[0].data["edges"] == [["k"]]


def test_shard_exchange_map_only_negative():
    t = _static()
    pw.io.null.write(t.select(doubled=pw.this.v * 2))
    assert not run_doctor().by_rule("shard-exchange")


def test_shard_nondeterminism_udf_feeding_groupby():
    @pw.udf(deterministic=False)
    def wobble(x: int) -> int:
        return x

    t = _static()
    t2 = t.select(pw.this.k, w=wobble(pw.this.v))
    r = t2.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.w))
    pw.io.null.write(r)
    found = run_doctor().by_rule("shard-nondeterminism")
    assert len(found) == 1
    assert "wobble" in found[0].message


def test_shard_nondeterminism_deterministic_udf_negative():
    @pw.udf
    def stable(x: int) -> int:
        return x + 1

    t = _static()
    t2 = t.select(pw.this.k, w=stable(pw.this.v))
    r = t2.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.w))
    pw.io.null.write(r)
    assert not run_doctor().by_rule("shard-nondeterminism")


def test_shard_reducer_tuple_vs_sum():
    t = _static()
    r = t.groupby(pw.this.k).reduce(
        pw.this.k,
        hist=pw.reducers.tuple(pw.this.v),
        total=pw.reducers.sum(pw.this.v),
    )
    pw.io.null.write(r)
    found = run_doctor().by_rule("shard-reducer")
    assert len(found) == 1
    assert found[0].data["reducer"] == "tuple"
    # named as the user declared it, not the internal slot (_agg0)
    assert found[0].data["column"] == "hist"


# --- rule: graph-stats -----------------------------------------------------


def test_join_vectorization_env_forced(monkeypatch):
    monkeypatch.setenv("PATHWAY_JOIN_ROWWISE", "1")
    t = _static()
    u = _static()
    j = t.join(u, t.k == u.k).select(t.v)
    pw.io.null.write(j)
    found = run_doctor().by_rule("join-vectorization")
    assert found and found[0].severity == Severity.WARNING
    assert "PATHWAY_JOIN_ROWWISE" in found[0].message


def test_join_vectorization_negative(monkeypatch):
    monkeypatch.delenv("PATHWAY_JOIN_ROWWISE", raising=False)
    t = _static()
    u = _static()
    pw.io.null.write(t.join(u, t.k == u.k).select(t.v))
    assert not run_doctor().by_rule("join-vectorization")


def test_join_vectorization_temporal_joins_info(monkeypatch):
    monkeypatch.delenv("PATHWAY_JOIN_ROWWISE", raising=False)

    class TS(pw.Schema):
        t: int
        v: int

    a = pw.debug.table_from_rows(TS, [(1, 1), (5, 2)])
    b = pw.debug.table_from_rows(TS, [(2, 3), (6, 4)])
    j = a.interval_join_inner(
        b, a.t, b.t, pw.temporal.interval(-2, 2)
    ).select(a.v)
    pw.io.null.write(j)
    found = run_doctor().by_rule("join-vectorization")
    assert found and all(d.severity == Severity.INFO for d in found)
    assert "rowwise" in found[0].message


def test_graph_stats_report():
    t = _static()
    r = t.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.v))
    pw.io.null.write(r)
    found = run_doctor().by_rule("graph-stats")
    assert len(found) == 1
    msg = found[0].message
    assert "GroupByNode=1" in msg and "stateful" in msg and "exchange" in msg


# --- registry / suppression ------------------------------------------------


def test_custom_rule_registration():
    @rule("test-custom")
    def my_rule(facts):
        from pathway_tpu.analysis import Diagnostic

        yield Diagnostic("test-custom", Severity.INFO, "hello", None)

    try:
        t = _static()
        pw.io.null.write(t.select(pw.this.k))
        assert len(run_doctor().by_rule("test-custom")) == 1
    finally:
        del RULES["test-custom"]


def test_suppress_reaches_operator_under_result_table():
    # unbounded-state anchors at the internal GroupByNode; the user only
    # holds the reduce result — suppressing it must silence the finding
    t = _stream()
    r = t.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.v))
    pw.io.null.write(r)
    assert run_doctor().by_rule("unbounded-state")
    suppress(r, "unbounded-state")
    assert not run_doctor().by_rule("unbounded-state")
    # other rules anchored at the same operator stay live
    assert run_doctor().by_rule("shard-exchange")


def test_suppress_is_per_node():
    t = _static()
    orphan_a = t.select(x=pw.this.v + 1)
    orphan_b = t.select(y=pw.this.v + 2)  # noqa: F841
    pw.io.null.write(t.select(pw.this.k))
    suppress(orphan_a, "dead-node")
    dead = run_doctor().by_rule("dead-node")
    assert len(dead) == 1
    assert dead[0].node is orphan_b._node


# --- pw.run(diagnostics=...) ----------------------------------------------


def _sick_streaming_pipeline():
    rows = []
    t = _stream()
    r = t.groupby(pw.this.k).reduce(pw.this.k, s=pw.reducers.sum(pw.this.v))
    pw.io.subscribe(r, on_change=lambda **kw: rows.append(kw))
    return rows


def test_run_diagnostics_error_raises_before_execution():
    rows = _sick_streaming_pipeline()
    with pytest.raises(GraphDoctorError) as exc_info:
        pw.run(diagnostics="error")
    assert rows == []  # not a single batch executed
    assert exc_info.value.report.by_rule("unbounded-state")
    assert "unbounded-state" in str(exc_info.value)


def test_run_diagnostics_warn_logs_and_executes(caplog):
    import logging

    rows = _sick_streaming_pipeline()
    with caplog.at_level(logging.WARNING, logger="pathway_tpu.analysis"):
        pw.run(diagnostics="warn")
    assert any("unbounded-state" in r.message for r in caplog.records)


def test_run_diagnostics_off_and_default_execute():
    _sick_streaming_pipeline()
    pw.run(diagnostics="off")
    _sick_streaming_pipeline()
    pw.run()  # default: no doctor pass


def test_run_diagnostics_invalid_value():
    _sick_streaming_pipeline()
    with pytest.raises(ValueError, match="diagnostics"):
        pw.run(diagnostics="loud")


def test_debug_diagnose_scopes_to_table(capsys):
    t = _static()
    unrelated = _static().select(z=pw.this.v * 3)  # noqa: F841
    t2 = t.select(pw.this.k, unused=pw.this.v * 10)
    out = t2.select(pw.this.k)
    report = pw.debug.diagnose(out)
    assert "dead-column" in _rules_of(report)
    # the unrelated pipeline is out of view: no dead-node finding
    assert "dead-node" not in _rules_of(report)
    assert "graph doctor" in capsys.readouterr().out


# --- CLI -------------------------------------------------------------------


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=240,
    )


def test_cli_demo_reports_five_rule_categories():
    res = _run_cli(
        "--json", "--fail-on", "never", "examples/diagnostics_demo.py"
    )
    assert res.returncode == 0, res.stderr
    findings = json.loads(res.stdout)
    rules_hit = {f["rule"] for f in findings}
    assert len(rules_hit) >= 5, rules_hit
    # every anchored finding carries node provenance
    anchored = [f for f in findings if f["node"] is not None]
    assert anchored
    assert all(
        f["trace"]["file"].endswith("diagnostics_demo.py") for f in anchored
    )


def test_cli_fail_on_threshold():
    assert (
        _run_cli(
            "--fail-on", "warning", "examples/diagnostics_demo.py"
        ).returncode
        == 1
    )
    assert (
        _run_cli("--fail-on", "error", "examples/diagnostics_demo.py").returncode
        == 0
    )


def test_cli_gates_example_pipelines():
    """The CI gate: every in-repo example must be free of error-severity
    findings, and the flagship streaming example free of warnings too.
    The flagship also passes the deployment-plane gate (`--plane
    --json`): plane rules plus the device-free TPU lowering proofs, so
    an unpadded kernel shape fails this suite, not the bench."""
    for script in sorted((REPO / "examples").glob("*.py")):
        res = _run_cli(str(script.relative_to(REPO)))
        assert res.returncode == 0, f"{script.name}:\n{res.stdout}{res.stderr}"
    res = _run_cli("--fail-on", "warning", "examples/streaming_wordcount.py")
    assert res.returncode == 0, res.stdout
    res = _run_cli_plane(
        "--plane",
        "--json",
        "--manifest",
        "none",
        "examples/streaming_wordcount.py",
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert json.loads(res.stdout)["lowering"]["cases"]


def test_cli_rule_filter():
    res = _run_cli(
        "--json",
        "--fail-on",
        "never",
        "--rule",
        "graph-stats",
        "examples/streaming_wordcount.py",
    )
    assert res.returncode == 0, res.stderr
    findings = json.loads(res.stdout)
    assert {f["rule"] for f in findings} == {"graph-stats"}


def test_cli_unknown_rule_id_is_usage_error():
    res = _run_cli(
        "--rule", "bogus-rule", "examples/streaming_wordcount.py"
    )
    assert res.returncode == 2
    assert "unknown rule id" in res.stderr


@pytest.mark.skipif(shutil.which("ruff") is None, reason="ruff not installed")
def test_analysis_package_is_lint_clean():
    res = subprocess.run(
        [
            "ruff",
            "check",
            "pathway_tpu/analysis",
            "tests/test_graph_doctor.py",
        ],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    assert res.returncode == 0, res.stdout + res.stderr


# --- regressions for the round-5 advice fixes ------------------------------


class _NdArraySchema(pw.Schema):
    key: np.ndarray
    v: int


def test_join_on_object_column_with_ndarray_values():
    """nodes.py null-join-key mask: object-dtype on-columns holding
    ndarrays used to raise 'truth value of an array is ambiguous'."""
    t1 = pw.debug.table_from_rows(
        _NdArraySchema,
        [(np.array([1, 2]), 10), (np.array([3, 4]), 20)],
    )
    t2 = pw.debug.table_from_rows(
        _NdArraySchema,
        [(np.array([1, 2]), 100), (np.array([9, 9]), 200)],
    )
    j = t1.join(t2, t1.key == t2.key).select(v1=t1.v, v2=t2.v)
    keys, cols = pw.debug.table_to_dicts(j)
    assert [(cols["v1"][k], cols["v2"][k]) for k in keys] == [(10, 100)]


def test_host_mesh_secret_mismatch_fails_fast(monkeypatch):
    """host_exchange handshake: a PATHWAY_DCN_SECRET mismatch must fail
    at dial time with an authentication error, not a later EPIPE."""
    from pathway_tpu.parallel import host_exchange as hx

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    base = sock.getsockname()[1]
    sock.close()

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "secret-A")
    mesh0_box = {}

    def build_mesh0():
        try:
            mesh0_box["mesh"] = hx.HostMesh(2, 0, base, connect_timeout=30.0)
        except hx.HostMeshError as e:  # peer 1 dials us with the wrong key
            mesh0_box["err"] = e

    t0 = threading.Thread(target=build_mesh0, daemon=True)
    t0.start()
    time.sleep(0.3)  # mesh0's listener is up; now dial with the wrong key
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "secret-B")
    with pytest.raises(hx.HostMeshError, match="authentication failed"):
        hx.HostMesh(2, 1, base, connect_timeout=8.0)
    t0.join(30)
    mesh = mesh0_box.get("mesh")
    if mesh is not None:
        mesh.close()


def test_host_mesh_matching_secret_still_connects(monkeypatch):
    from pathway_tpu.parallel import host_exchange as hx

    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    base = sock.getsockname()[1]
    sock.close()

    monkeypatch.setenv("PATHWAY_DCN_SECRET", "shared-secret")
    meshes = [None, None]

    def build(pid):
        meshes[pid] = hx.HostMesh(2, pid, base, connect_timeout=30.0)

    threads = [
        threading.Thread(target=build, args=(pid,), daemon=True)
        for pid in (0, 1)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    m0, m1 = meshes
    assert m0 is not None and m1 is not None
    try:
        m0.send(1, "ch", 0, {"ok": True})
        assert m1.gather("ch", 0, timeout=30) == {0: {"ok": True}}
    finally:
        m0.close()
        m1.close()


def test_asof_now_duplicate_id_poisons_row_not_run():
    """AsofNowJoin id=pw.left.id duplicate matches: recorded via
    record_error so terminate_on_error=False runs keep going, while the
    default run surfaces the ValueError."""
    from pathway_tpu.internals.errors import peek_errors

    def declare():
        queries = pw.debug.table_from_markdown(
            """
            q | __time__
            1 | 4
            2 | 4
            """
        )
        state = pw.debug.table_from_markdown(
            """
            q  | v  | __time__
            1  | 10 | 2
            1  | 11 | 2
            2  | 20 | 2
            """
        )
        res = queries.asof_now_join(
            state, queries.q == state.q, id=queries.id
        ).select(q=queries.q, v=state.v)
        rows = []
        pw.io.subscribe(
            res, on_change=lambda key, row, time, is_addition: rows.append(row)
        )
        return rows

    rows = declare()
    pw.run(terminate_on_error=False)
    # q=1 matched two rows -> poisoned/skipped; q=2 still flows
    assert rows == [{"q": 2, "v": 20}]
    errs = peek_errors()
    assert any("id contract" in e["message"] for e in errs)

    from pathway_tpu.internals import parse_graph
    from pathway_tpu.internals.errors import clear_errors

    parse_graph.G.clear()
    clear_errors()
    declare()
    with pytest.raises(ValueError, match="id contract"):
        pw.run()  # terminate_on_error=True default


# --- rule: unreplicated-serving (Replica Shield) ---------------------------


def _gated_index_graph(tmp_port=18099):
    """Gated REST ingress + an external index: the serving topology the
    unreplicated-serving rule inspects."""
    from pathway_tpu.io.http import rest_connector
    from pathway_tpu.serving import QoSConfig
    from pathway_tpu.stdlib.indexing import DataIndex, TpuKnn

    docs = pw.debug.table_from_rows(
        pw.schema_from_types(vec=np.ndarray),
        [(np.asarray([1.0, 0.0], dtype=np.float32),)],
    )
    queries, _writer = rest_connector(
        host="127.0.0.1",
        port=tmp_port,
        schema=pw.schema_from_types(q=str),
        route="/knn",
        qos=QoSConfig(),
    )
    qvec = queries.select(
        vec=pw.apply(
            lambda s: np.asarray([1.0, 0.0], dtype=np.float32), queries.q
        )
    )
    index = DataIndex(docs, TpuKnn(docs.vec, dimensions=2))
    reply = index.query_as_of_now(qvec.vec, number_of_matches=1)
    pw.io.null.write(reply.select(score=pw.right._pw_index_reply_score))
    return queries


def test_unreplicated_serving_warns_without_responder_or_replicas(
    monkeypatch,
):
    from pathway_tpu.serving import degrade

    monkeypatch.delenv("PATHWAY_SERVING_REPLICAS", raising=False)
    degrade.reset()
    _gated_index_graph()
    found = run_doctor().by_rule("unreplicated-serving")
    assert len(found) == 1
    assert found[0].severity == Severity.WARNING
    assert "hard-503" in found[0].message


def test_unreplicated_serving_negative_with_stale_responder(monkeypatch):
    from pathway_tpu.serving import degrade

    monkeypatch.delenv("PATHWAY_SERVING_REPLICAS", raising=False)
    degrade.reset()
    _gated_index_graph(tmp_port=18100)
    degrade.register_stale_responder("/knn", lambda vals: {"stale": True})
    try:
        assert not run_doctor().by_rule("unreplicated-serving")
    finally:
        degrade.reset()


def test_unreplicated_serving_info_when_staleness_unbounded(monkeypatch):
    from pathway_tpu.serving import degrade

    degrade.reset()
    monkeypatch.setenv(
        "PATHWAY_SERVING_REPLICAS",
        "http://127.0.0.1:9101,http://127.0.0.1:9102",
    )
    monkeypatch.delenv("PATHWAY_SERVING_MAX_STALENESS_MS", raising=False)
    # a standby writer is configured: the ingest-SPOF facet stays quiet
    monkeypatch.setenv("PATHWAY_REPL_STANDBY", "127.0.0.1:9200")
    _gated_index_graph(tmp_port=18101)
    found = run_doctor().by_rule("unreplicated-serving")
    assert len(found) == 1
    assert found[0].severity == Severity.INFO
    assert "max-staleness" in found[0].message
    # bounding staleness clears the finding
    monkeypatch.setenv("PATHWAY_SERVING_MAX_STALENESS_MS", "2000")
    assert not run_doctor().by_rule("unreplicated-serving")


def test_unreplicated_serving_warns_missing_standby_writer(monkeypatch):
    """Shard Harbor facet: a replicated read plane whose single ingest
    writer has no standby is still an SPOF — kill the writer and every
    replica serves permanently stale data."""
    from pathway_tpu.serving import degrade

    degrade.reset()
    monkeypatch.setenv(
        "PATHWAY_SERVING_REPLICAS",
        "http://127.0.0.1:9101,http://127.0.0.1:9102",
    )
    monkeypatch.setenv("PATHWAY_SERVING_MAX_STALENESS_MS", "2000")
    monkeypatch.delenv("PATHWAY_REPL_STANDBY", raising=False)
    _gated_index_graph(tmp_port=18103)
    found = run_doctor().by_rule("unreplicated-serving")
    assert len(found) == 1
    assert found[0].severity == Severity.WARNING
    assert "standby" in found[0].message
    # configuring the standby clears it
    monkeypatch.setenv("PATHWAY_REPL_STANDBY", "127.0.0.1:9200")
    assert not run_doctor().by_rule("unreplicated-serving")


def test_unreplicated_serving_info_single_owner_shard(monkeypatch):
    """Shard Harbor facet: a shard with one owner turns any member
    death into a partial-corpus outage (503 naming the shard)."""
    from pathway_tpu.serving import degrade

    degrade.reset()
    monkeypatch.setenv(
        "PATHWAY_SERVING_REPLICAS",
        "http://127.0.0.1:9101,http://127.0.0.1:9102",
    )
    monkeypatch.setenv("PATHWAY_SERVING_MAX_STALENESS_MS", "2000")
    monkeypatch.setenv("PATHWAY_REPL_STANDBY", "127.0.0.1:9200")
    monkeypatch.setenv(
        "PATHWAY_SERVING_SHARD_MAP",
        "http://127.0.0.1:9101,http://127.0.0.1:9102|http://127.0.0.1:9103",
    )
    _gated_index_graph(tmp_port=18104)
    found = run_doctor().by_rule("unreplicated-serving")
    assert len(found) == 1
    assert found[0].severity == Severity.INFO
    assert "single owner" in found[0].message
    assert found[0].data["single_owner_shards"] == [1]
    # two members per shard clears it
    monkeypatch.setenv(
        "PATHWAY_SERVING_SHARD_MAP",
        "http://127.0.0.1:9101,http://127.0.0.1:9102"
        "|http://127.0.0.1:9103,http://127.0.0.1:9104",
    )
    assert not run_doctor().by_rule("unreplicated-serving")
    # shard-count form (no map): 2 replicas over 3 shards pigeonholes
    # at least one single-owner shard — the finding names the counts,
    # not invented shard ids
    monkeypatch.delenv("PATHWAY_SERVING_SHARD_MAP", raising=False)
    monkeypatch.setenv("PATHWAY_SERVING_SHARDS", "3")
    found = run_doctor().by_rule("unreplicated-serving")
    assert [f.severity for f in found] == [Severity.INFO]
    assert "at least one shard" in found[0].message
    assert found[0].data == {"shards": 3, "replicas": 2}
    # 6 replicas over 3 shards CAN give every shard two owners: quiet
    monkeypatch.setenv(
        "PATHWAY_SERVING_REPLICAS",
        ",".join(f"http://127.0.0.1:91{i:02d}" for i in range(6)),
    )
    assert not run_doctor().by_rule("unreplicated-serving")


def test_unreplicated_serving_negative_without_index(monkeypatch):
    """A gated REST endpoint with no external index in the graph is not
    a serving plane — the rule stays quiet."""
    from pathway_tpu.io.http import rest_connector
    from pathway_tpu.serving import QoSConfig, degrade

    monkeypatch.delenv("PATHWAY_SERVING_REPLICAS", raising=False)
    degrade.reset()
    queries, writer = rest_connector(
        host="127.0.0.1",
        port=18102,
        schema=pw.schema_from_types(q=str),
        route="/echo",
        qos=QoSConfig(),
    )
    writer(queries.select(query_id=queries.id, result=queries.q))
    assert not run_doctor().by_rule("unreplicated-serving")


# --- plane doctor: deployment-scope rules (analysis/plane.py) --------------


import os  # noqa: E402

from pathway_tpu.analysis import run_plane_doctor  # noqa: E402


@pytest.fixture
def _clean_knobs(monkeypatch):
    """Strip ambient PATHWAY_* knobs so env-lint assertions are exact."""
    for k in list(os.environ):
        if k.startswith("PATHWAY_"):
            monkeypatch.delenv(k, raising=False)


def _monolith_graph():
    """One graph touching all four arranged-state gaps (ROADMAP 5c):
    UpdateRows, instance-less Sort, Ix, UniverseSetOp."""
    t = _static()
    u = _static_other()
    t.update_rows(u)
    t.sort(key=pw.this.v)
    keys = u.select(ptr=t.pointer_from(pw.this.k))
    t.ix(keys.ptr)
    u.with_universe_of(t)
    return t


def test_snapshot_coverage_names_the_four_monoliths(_clean_knobs):
    _monolith_graph()
    found = run_plane_doctor().by_rule("snapshot-coverage")
    execs = {d.data["exec"] for d in found}
    assert execs >= {
        "UpdateRowsExec",
        "SortExec",
        "IxExec",
        "UniverseSetOpExec",
    }, execs
    assert all(d.severity == Severity.WARNING for d in found)


def test_snapshot_coverage_skips_arrangement_backed_execs(_clean_knobs):
    t = _stream()
    t.groupby(pw.this.k).reduce(
        pw.this.k, total=pw.reducers.sum(pw.this.v)
    )
    execs = {
        d.data["exec"]
        for d in run_plane_doctor().by_rule("snapshot-coverage")
    }
    assert "GroupByExec" not in execs


def test_snapshot_coverage_clears_when_arranged_state_lands(
    _clean_knobs, monkeypatch
):
    """The audit is driven by the exec metadata, not a hardcoded list:
    giving UpdateRowsExec an arranged_state override clears it."""
    from pathway_tpu.engine import nodes as en

    t = _static()
    t.update_rows(_static_other())
    before = {
        d.data["exec"]
        for d in run_plane_doctor().by_rule("snapshot-coverage")
    }
    assert "UpdateRowsExec" in before

    monkeypatch.setattr(
        en.UpdateRowsExec,
        "arranged_state",
        lambda self: {},
        raising=False,
    )
    after = {
        d.data["exec"]
        for d in run_plane_doctor().by_rule("snapshot-coverage")
    }
    assert "UpdateRowsExec" not in after


def test_snapshot_coverage_per_node_suppression(_clean_knobs):
    t = _static()
    upd = t.update_rows(_static_other())
    suppress(upd, "snapshot-coverage")
    execs = {
        d.data["exec"]
        for d in run_plane_doctor().by_rule("snapshot-coverage")
    }
    assert "UpdateRowsExec" not in execs


def test_pickle_hot_path_flags_object_exchange_key(_clean_knobs):
    t = _static()  # k: str
    t.groupby(pw.this.k).reduce(
        pw.this.k, total=pw.reducers.sum(pw.this.v)
    )
    found = run_plane_doctor().by_rule("pickle-hot-path")
    assert found, "str groupby key should be flagged on the wire"
    assert any("str" in d.data["dtype"] for d in found)


def test_pickle_hot_path_quiet_on_numeric_columns(_clean_knobs):
    t = _static()
    t.groupby(pw.this.v).reduce(
        pw.this.v, n=pw.reducers.count()
    )
    numeric_only = t.select(v=pw.this.v)
    numeric_only.groupby(pw.this.v).reduce(
        pw.this.v, n=pw.reducers.count()
    )
    found = run_plane_doctor().by_rule("pickle-hot-path")
    # the int key column itself must not be flagged
    assert all("int" not in d.data["dtype"] for d in found)


def test_knob_lint_shard_count_disagreement(_clean_knobs, monkeypatch):
    """The satellite case: PATHWAY_SERVING_SHARDS says 3 but the shard
    map describes 2 — an ERROR before any process boots."""
    monkeypatch.setenv("PATHWAY_SERVING_SHARDS", "3")
    monkeypatch.setenv(
        "PATHWAY_SERVING_SHARD_MAP", "h1:9000|h2:9001"
    )
    found = run_plane_doctor().by_rule("knob-coherence")
    conflict = [d for d in found if "conflicting shard counts" in d.message]
    assert conflict and conflict[0].severity == Severity.ERROR
    assert conflict[0].data["shards"] == 3
    assert conflict[0].data["map_shards"] == 2

    # agreement clears it
    monkeypatch.setenv("PATHWAY_SERVING_SHARDS", "2")
    found = run_plane_doctor().by_rule("knob-coherence")
    assert not [d for d in found if "conflicting" in d.message]


def test_knob_lint_torn_shard_map_and_bad_qos(_clean_knobs, monkeypatch):
    monkeypatch.setenv("PATHWAY_SERVING_SHARD_MAP", "|||")
    monkeypatch.setenv("PATHWAY_SERVING_MAX_QUEUE", "many")
    found = run_plane_doctor().by_rule("knob-coherence")
    msgs = [d.message for d in found if d.severity == Severity.ERROR]
    assert any("SHARD_MAP" in m for m in msgs)
    assert any("MAX_QUEUE" in m for m in msgs)


def test_knob_lint_gated_ingress_without_deadline(
    _clean_knobs, monkeypatch
):
    monkeypatch.setenv("PATHWAY_SERVING_ENABLED", "1")
    monkeypatch.setenv("PATHWAY_SERVING_DEADLINE_MS", "0")
    found = run_plane_doctor().by_rule("knob-coherence")
    assert any(
        "without deadline bounds" in d.message
        and d.severity == Severity.WARNING
        for d in found
    )


def test_knob_lint_cache_without_stream_and_inert_tenancy(
    _clean_knobs, monkeypatch
):
    monkeypatch.setenv("PATHWAY_ROUTER_CACHE", "1")
    monkeypatch.setenv("PATHWAY_TENANT_QOS", "1")
    found = run_plane_doctor().by_rule("knob-coherence")
    assert any(
        "PATHWAY_ROUTER_CACHE_WRITER" in d.message
        and d.severity == Severity.WARNING
        for d in found
    )
    assert any(
        "PATHWAY_TENANT_QOS" in d.message
        and d.severity == Severity.INFO
        for d in found
    )


def test_knob_lint_quiet_on_clean_env(_clean_knobs):
    assert not run_plane_doctor().by_rule("knob-coherence")


# --- plane mode CLI (the tier-1 lowering gate) -----------------------------


def _run_cli_plane(*args, env_overrides=None):
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith("PATHWAY_")
    }
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_overrides or {})
    return subprocess.run(
        [sys.executable, "-m", "pathway_tpu.analysis", *args],
        capture_output=True,
        text=True,
        cwd=REPO,
        env=env,
        timeout=240,
    )


def test_cli_plane_proves_all_families_and_writes_manifest(tmp_path):
    """The tier-1 gate half 1: `--plane` lowers every kernel family
    across the pad ladder with zero device access (JAX_PLATFORMS=cpu)
    and writes the content-addressed manifest."""
    manifest = tmp_path / "LOWERING_r16.json"
    res = _run_cli_plane(
        "--plane", "--json", "--manifest", str(manifest)
    )
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert doc["findings"] == []
    cases = doc["lowering"]["cases"]
    families = {c["family"] for c in cases}
    assert families >= {"pallas_topk", "paged_attention", "tick_forge"}
    assert all(
        c["status"] in ("lowered", "rejected") for c in cases
    ), cases
    # every expected-lower case really went through Mosaic lowering
    for c in cases:
        if c["status"] == "lowered":
            assert len(c["stablehlo_sha256"]) == 64
    ondisk = json.loads(manifest.read_text())
    assert ondisk["content_sha256"] == doc["lowering"]["content_sha256"]


def test_cli_plane_fails_suite_on_unpadded_shape(tmp_path):
    """The tier-1 gate half 2: a newly introduced unpadded kernel shape
    fails the suite (exit 1) with a finding naming the kernel, the
    shape and the violated rule — not the bench."""
    res = _run_cli_plane(
        "--plane",
        "--json",
        "--manifest",
        "none",
        "--prove-shape",
        "paged_attention:head_dim=129",
    )
    assert res.returncode == 1, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    (finding,) = [
        f for f in doc["findings"] if f["rule"] == "tpu-lowering"
    ]
    assert finding["severity"] == "error"
    assert finding["data"]["family"] == "paged_attention"
    assert finding["data"]["shape"]["head_dim"] == 129
    assert finding["data"]["rule"] == "lane-pad"

    # same for an un-lane-padded raw top-k tile
    res = _run_cli_plane(
        "--plane",
        "--json",
        "--manifest",
        "none",
        "--prove-shape",
        "pallas_topk:k=10,pad=0",
    )
    assert res.returncode == 1, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert any(
        f["data"].get("rule") == "mosaic-8x128"
        for f in doc["findings"]
    )


def test_cli_plane_env_findings_and_knob_snapshot(tmp_path):
    res = _run_cli_plane(
        "--plane",
        "--json",
        "--manifest",
        "none",
        env_overrides={
            "PATHWAY_SERVING_SHARDS": "3",
            "PATHWAY_SERVING_SHARD_MAP": "h1:9000|h2:9001",
        },
    )
    assert res.returncode == 1, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    assert any(
        f["rule"] == "knob-coherence" and f["severity"] == "error"
        for f in doc["findings"]
    )
    # the knob snapshot records the deployment the verdict applied to
    assert doc["knobs"]["PATHWAY_SERVING_SHARDS"] == "3"


def test_cli_plane_with_script_runs_both_scopes():
    res = _run_cli_plane(
        "--plane",
        "--json",
        "--manifest",
        "none",
        "--fail-on",
        "never",
        "examples/diagnostics_demo.py",
    )
    assert res.returncode == 0, res.stdout + res.stderr
    doc = json.loads(res.stdout)
    rules_hit = {f["rule"] for f in doc["findings"]}
    # graph rules and the lowering proofs land in ONE report
    assert "dead-node" in rules_hit or "dead-column" in rules_hit
    assert doc["lowering"] is not None
    assert {c["family"] for c in doc["lowering"]["cases"]} >= {
        "pallas_topk",
        "paged_attention",
    }


def test_cli_requires_script_unless_plane():
    res = _run_cli()
    assert res.returncode == 2
    assert "script is required" in res.stderr
