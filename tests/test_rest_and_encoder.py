"""REST server end-to-end + flax encoder tests."""

import socket
import threading
import time

import numpy as np
import pytest

import pathway_tpu as pw


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_rest_connector_roundtrip():
    """HTTP request -> graph -> response (reference pattern:
    io/http/_server.py rest_connector + response_writer)."""
    import requests

    from pathway_tpu.io.http import rest_connector

    port = _free_port()

    class QuerySchema(pw.Schema):
        text: str

    queries, writer = rest_connector(
        host="127.0.0.1", port=port, schema=QuerySchema, route="/upper"
    )
    result = queries.select(
        query_id=queries.id, result=queries.text.str.upper()
    )
    writer(result)

    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    # wait for server
    deadline = time.time() + 10
    out = None
    while time.time() < deadline:
        try:
            resp = requests.post(
                f"http://127.0.0.1:{port}/upper",
                json={"text": "hello"},
                timeout=5,
            )
            out = resp.json()
            break
        except Exception:
            time.sleep(0.2)
    assert out == "HELLO"
    pw.internals.parse_graph.G.runtime.stop()
    t.join(timeout=5)


def test_vector_store_rest_server():
    """Full VectorStoreServer REST flow with a fake embedder."""
    from pathway_tpu.xpacks.llm.vector_store import (
        VectorStoreClient,
        VectorStoreServer,
    )
    from pathway_tpu.debug import T

    @pw.udf
    def emb(text: str) -> np.ndarray:
        v = np.zeros(4, dtype=np.float32)
        for ch in str(text).lower():
            v[ord(ch) % 4] += 1.0
        n = np.linalg.norm(v)
        return v / n if n > 0 else v

    docs = T(
        """
        data
        apple apple
        banana banana
        """
    )
    server = VectorStoreServer(docs, embedder=emb)
    port = _free_port()
    thread = server.run_server(
        host="127.0.0.1", port=port, threaded=True
    )
    client = VectorStoreClient(host="127.0.0.1", port=port, timeout=10)
    deadline = time.time() + 15
    results = None
    while time.time() < deadline:
        try:
            results = client.query("apple", k=1)
            if results:
                break
        except Exception:
            time.sleep(0.3)
    assert results and results[0]["text"] == "apple apple"
    stats = client.get_vectorstore_statistics()
    assert stats["file_count"] == 2
    pw.internals.parse_graph.G.runtime.stop()
    thread.join(timeout=5)


def test_flax_encoder_shapes():
    from pathway_tpu.xpacks.llm._encoder import EncoderRuntime
    from pathway_tpu.xpacks.llm._tokenizer import HashingTokenizer

    tok = HashingTokenizer(vocab_size=1000)
    rt = EncoderRuntime(vocab_size=1000, dim=32, depth=1, heads=2, max_len=64)
    ids, mask = tok.encode_batch(["hello world", "a much longer text here"], 64)
    out = rt.forward_ids(ids, mask)
    assert out.shape == (2, 32)
    norms = np.linalg.norm(out, axis=1)
    assert np.allclose(norms, 1.0, atol=1e-3)
    # deterministic
    out2 = rt.forward_ids(ids, mask)
    assert np.allclose(out, out2)


def test_sentence_transformer_embedder_in_graph():
    from pathway_tpu.debug import T, table_to_dicts
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    embedder = SentenceTransformerEmbedder(dim=32, depth=1, heads=2, max_len=64)
    t = T(
        """
        text
        hello world
        goodbye world
        """
    )
    res = t.select(e=embedder(t.text))
    _keys, cols = table_to_dicts(res)
    vecs = list(cols["e"].values())
    assert all(v.shape == (32,) for v in vecs)
    assert embedder.get_embedding_dimension() == 32


def test_cross_encoder_reranker():
    from pathway_tpu.debug import T, table_to_dicts
    from pathway_tpu.xpacks.llm.rerankers import CrossEncoderReranker

    rr = CrossEncoderReranker(dim=32, depth=1, heads=2, max_len=64)
    t = T(
        """
        doc   | query
        alpha | alpha
        beta  | alpha
        """
    )
    res = t.select(score=rr(t.doc, t.query))
    _keys, cols = table_to_dicts(res)
    scores = list(cols["score"].values())
    assert len(scores) == 2 and all(isinstance(s, float) for s in scores)
