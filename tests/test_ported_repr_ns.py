"""Ported reference expression-repr / colnamespace / argtuple tests
(reference: python/pathway/tests/test_expression_repr.py,
test_colnamespace.py, test_argtuple.py) — the expression pretty-printer
(<tableN> numbering), the .C column namespace over reserved names, and the
ArgTuple multi-value return wrapper."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.internals.arg_tuple import wrap_arg_tuple
from pathway_tpu.internals.expression_printer import ExpressionFormatter


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    yield
    pw.internals.parse_graph.G.clear()


# --- colnamespace ----------------------------------------------------------


def test_namespace_1():
    tab = pw.Table.empty(select=int)
    assert isinstance(tab.C.select, pw.ColumnReference)


def test_namespace_2():
    tab = pw.Table.empty(select=int)
    assert isinstance(tab.C["select"], pw.ColumnReference)


def test_namespace_3():
    tab = pw.Table.empty(C=int)
    assert isinstance(tab.C.C, pw.ColumnReference)


def test_namespace_4():
    tab = pw.Table.empty(select=int)
    tab2 = tab.select(pw.this.C.select)
    assert tab.schema == tab2.schema


def test_namespace_5():
    tab = pw.Table.empty(C=int)
    tab2 = tab.select(pw.this.C.C)
    assert tab.schema == tab2.schema


def test_namespace_6():
    tab = pw.Table.empty(C=int)
    tab2 = tab.select(pw.this.C["C"])
    assert tab.schema == tab2.schema


def test_namespace_7():
    tab = pw.Table.empty(C=int)
    tab2 = tab.select(pw.this["C"])
    assert tab.schema == tab2.schema


# --- arg tuple -------------------------------------------------------------


def test_arg_tuple_wrapper_scalar():
    result = wrap_arg_tuple(lambda: 1)()
    assert result == 1


def test_arg_tuple_wrapper_dict():
    result = wrap_arg_tuple(lambda: {"a": 1, "b": 2})()
    a, b = result
    assert a == 1
    assert b == 2
    assert result.a == 1
    assert result.b == 2
    assert result["a"] == 1
    assert result["b"] == 2


def test_arg_tuple_wrapper_dict_with_one_element():
    result = wrap_arg_tuple(lambda: {"a": 1})()
    assert result.a == 1
    assert result["a"] == 1


def test_arg_tuple_wrapper_iterable():
    result = wrap_arg_tuple(lambda: [1, 2])()
    a, b = result
    assert a == 1
    assert b == 2
    assert result["0"] == 1
    assert result["1"] == 2


def test_arg_tuple_wrapper_iterable_with_one_element():
    result = wrap_arg_tuple(lambda: (1,))()
    assert result == 1


# --- expression repr -------------------------------------------------------


def _pet_table():
    return T(
        """
    pet  |  owner  | age
     1   | Alice   | 10
        """
    )


def test_column_reference():
    t = _pet_table()
    assert repr(t.pet) == "<table1>.pet"


def test_column_binary_op():
    t = _pet_table()
    assert repr(t.pet + t.age) == "(<table1>.pet + <table1>.age)"
    assert repr(t.pet - t.age) == "(<table1>.pet - <table1>.age)"
    assert repr(t.pet * t.age) == "(<table1>.pet * <table1>.age)"
    assert repr(t.pet / t.age) == "(<table1>.pet / <table1>.age)"
    assert repr(t.pet // t.age) == "(<table1>.pet // <table1>.age)"
    assert repr(t.pet**t.age) == "(<table1>.pet ** <table1>.age)"
    assert repr(t.pet % t.age) == "(<table1>.pet % <table1>.age)"
    assert repr(t.pet == t.age) == "(<table1>.pet == <table1>.age)"
    assert repr(t.pet != t.age) == "(<table1>.pet != <table1>.age)"
    assert repr(t.pet < t.age) == "(<table1>.pet < <table1>.age)"
    assert repr(t.pet <= t.age) == "(<table1>.pet <= <table1>.age)"
    assert repr(t.pet > t.age) == "(<table1>.pet > <table1>.age)"
    assert repr(t.pet >= t.age) == "(<table1>.pet >= <table1>.age)"


def test_2_args():
    t = _pet_table()
    tt = t.copy()
    assert repr(t.pet + tt.age) == "(<table1>.pet + <table2>.age)"


def test_3_args():
    t = _pet_table()
    tt = t.copy()
    assert (
        repr(pw.if_else(t.pet == 1, tt.pet, t.age))
        == "pathway.if_else((<table1>.pet == 1), <table2>.pet, <table1>.age)"
    )


def test_column_unary_op():
    t = _pet_table()
    assert repr(-t.pet) == "(-<table1>.pet)"
    assert repr(~t.pet) == "(~<table1>.pet)"


def test_reducer():
    t = _pet_table()
    assert repr(pw.reducers.min(t.pet)) == "pathway.reducers.min(<table1>.pet)"
    assert repr(pw.reducers.max(t.pet)) == "pathway.reducers.max(<table1>.pet)"
    assert repr(pw.reducers.sum(t.pet)) == "pathway.reducers.sum(<table1>.pet)"
    assert repr(pw.reducers.count()) == "pathway.reducers.count()"
    assert (
        repr(pw.reducers.argmin(t.pet))
        == "pathway.reducers.argmin(<table1>.pet)"
    )
    assert (
        repr(pw.reducers.argmax(t.pet))
        == "pathway.reducers.argmax(<table1>.pet)"
    )


def test_apply():
    t = _pet_table()
    assert (
        repr(pw.apply(lambda x, y: x + y, t.pet, t.age))
        == "pathway.apply(<lambda>, <table1>.pet, <table1>.age)"
    )


def test_cast():
    t = _pet_table()
    assert repr(pw.cast(int, t.pet)) == "pathway.cast(INT, <table1>.pet)"
    assert repr(pw.cast(float, t.pet)) == "pathway.cast(FLOAT, <table1>.pet)"


def test_convert():
    t = _pet_table()
    assert repr(t.pet.as_int()) == "pathway.as_int(<table1>.pet)"
    assert repr(t.pet.as_float()) == "pathway.as_float(<table1>.pet)"
    assert repr(t.pet.as_str()) == "pathway.as_str(<table1>.pet)"
    assert repr(t.pet.as_bool()) == "pathway.as_bool(<table1>.pet)"


def test_declare_type():
    t = _pet_table()
    assert (
        repr(pw.declare_type(int, t.pet))
        == "pathway.declare_type(INT, <table1>.pet)"
    )
    assert (
        repr(pw.declare_type(float, t.pet))
        == "pathway.declare_type(FLOAT, <table1>.pet)"
    )


def test_coalesce():
    t = _pet_table()
    assert (
        repr(pw.coalesce(t.pet, t.age))
        == "pathway.coalesce(<table1>.pet, <table1>.age)"
    )


def test_require():
    t = _pet_table()
    assert (
        repr(pw.require(t.pet, t.age))
        == "pathway.require(<table1>.pet, <table1>.age)"
    )


def test_if_else():
    t = _pet_table()
    assert (
        repr(pw.if_else(t.pet == 1, t.pet, t.age))
        == "pathway.if_else((<table1>.pet == 1), <table1>.pet, <table1>.age)"
    )


def test_pointer():
    t = _pet_table()
    assert repr(t.pointer_from(4)) == "<table1>.pointer_from(4)"
    assert (
        repr(t.pointer_from(t.pet))
        == "<table1>.pointer_from(<table1>.pet)"
    )


def test_method_call():
    t = T(
        """
      | ts
    1 | 1
        """
    ).select(ts=pw.this.ts.dt.from_timestamp(unit="s"))
    assert repr(t.ts.dt.nanosecond()) == "(<table1>.ts).dt.nanosecond()"
    assert repr(t.ts.dt.microsecond()) == "(<table1>.ts).dt.microsecond()"
    assert repr(t.ts.dt.millisecond()) == "(<table1>.ts).dt.millisecond()"
    assert repr(t.ts.dt.second()) == "(<table1>.ts).dt.second()"
    assert repr(t.ts.dt.minute()) == "(<table1>.ts).dt.minute()"
    assert repr(t.ts.dt.hour()) == "(<table1>.ts).dt.hour()"


def test_formatter_table_infos():
    t = _pet_table()
    tt = t.copy()
    fmt = ExpressionFormatter()
    out = fmt.print_expression(t.pet + tt.age)
    assert out == "(<table1>.pet + <table2>.age)"
    infos = fmt.print_table_infos()
    assert "<table1>=" in infos and "<table2>=" in infos
