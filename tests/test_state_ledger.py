"""State Ledger: the arrangement-backed state substrate + incremental
segment snapshots (engine/arrangement.py seg ids, persistence/segments.py
codec, persistence/_runtime_glue.py incremental path).

Covers: segment codec roundtrips (raw / stacked / pickle columns),
manifest save/load equivalence under churn+compaction, differential
oracle equality for the rebased DeduplicateExec / temporal joins /
session assignment (PATHWAY_STATE_ROWWISE=1 vs the columnar path),
acceptor-exception atomicity, checkpoint-bytes ∝ churn, segment GC, and
mmap recovery without input-log replay (bit-identical outputs vs an
uninterrupted run)."""

import os

import numpy as np
import pytest

import pathway_tpu as pw  # noqa: F401  (conftest clears its graph)
from pathway_tpu.engine.arrangement import Arrangement
from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import (
    DeduplicateNode,
    InputNode,
    JoinNode,
    OutputNode,
)
from pathway_tpu.engine.runtime import Runtime, StaticSource
from pathway_tpu.engine.temporal_nodes import (
    AsofJoinNode,
    IntervalJoinNode,
    SessionAssignNode,
)
from pathway_tpu.internals.api import _value_bytes
from pathway_tpu.persistence._runtime_glue import attach_persistence
from pathway_tpu.persistence.segments import (
    load_arrangement,
    manifest_of,
    segment_from_buffer,
    segment_to_bytes,
)


# ---------------------------------------------------------------------------
# Segment codec


def _entries_equal(a, b):
    assert (a.jk == b.jk).all()
    assert (a.key == b.key).all()
    assert (a.count == b.count).all()
    assert (a.age == b.age).all()
    for ca, cb in zip(a.cols, b.cols):
        for x, y in zip(ca.tolist(), cb.tolist()):
            if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
                assert np.array_equal(np.asarray(x), np.asarray(y))
            else:
                assert x == y or (x is None and y is None)


def test_segment_codec_roundtrip_mixed_columns():
    arr = Arrangement(4)
    rng = np.random.default_rng(0)
    n = 400
    emb = np.empty(n, object)
    objs = np.empty(n, object)
    for i in range(n):
        emb[i] = (np.arange(8, dtype=np.float32) + i)
        objs[i] = None if i % 5 == 0 else ("tag%d" % (i % 3), i)
    arr.append(
        rng.integers(0, 50, n).astype(np.uint64),
        np.arange(n, dtype=np.uint64),
        np.where(rng.random(n) < 0.2, -1, 1).astype(np.int64),
        [
            rng.integers(-5, 5, n),          # raw int64
            rng.normal(size=n),              # raw float64
            emb,                             # stacked embeddings
            objs,                            # pickle fallback
        ],
    )
    arr.seal()
    for seg in arr.segments:
        blob = segment_to_bytes(seg)
        rt = segment_from_buffer(blob)
        assert rt.seg_id == seg.seg_id and rt.clean == seg.clean
        assert (rt.jks == seg.jks).all() and (rt.diffs == seg.diffs).all()
        assert (rt.mix_sorted == seg.mix_sorted).all()
        assert rt.cols[0].dtype == seg.cols[0].dtype
        assert np.array_equal(
            np.stack(list(rt.cols[2])), np.stack(list(seg.cols[2]))
        )


def test_arrangement_manifest_roundtrip_with_churn_and_compaction():
    rng = np.random.default_rng(1)
    arr = Arrangement(2)
    store: dict[int, bytes] = {}
    for tick in range(10):
        n = 300
        jks = rng.integers(0, 40, n).astype(np.uint64)
        keys = (np.arange(n) + tick * n).astype(np.uint64)
        diffs = np.where(rng.random(n) < 0.4, -1, 1).astype(np.int64)
        arr.append(jks, keys, diffs, [rng.integers(0, 9, n), rng.normal(size=n)])
        man = manifest_of(arr)  # seals; may compact (heavy retractions)
        for seg in arr.segments:
            store.setdefault(seg.seg_id, segment_to_bytes(seg))
        arr2 = load_arrangement(man, lambda sid: store.get(sid))
        _entries_equal(arr.entries(), arr2.entries())
        assert arr2.epoch == arr.epoch
        assert arr2._next_seg_id == arr._next_seg_id
    assert arr.compactions > 0, "test meant to cover the compaction path"


def test_manifest_missing_segment_raises():
    arr = Arrangement(1)
    arr.append(
        np.arange(5, dtype=np.uint64),
        np.arange(5, dtype=np.uint64),
        np.ones(5, np.int64),
        [np.arange(5)],
    )
    man = manifest_of(arr)
    with pytest.raises(KeyError):
        load_arrangement(man, lambda sid: None)


# ---------------------------------------------------------------------------
# Differential oracles: arranged path vs PATHWAY_STATE_ROWWISE=1


def _consolidated(emitted: dict) -> dict:
    return {k: v for k, v in emitted.items() if v != 0}


def _drive(build, ticks, rowwise):
    """build() -> (input nodes, stateful node); ticks: per-tick dict
    input_node_index -> row list. Returns consolidated emissions and the
    exec (to assert which path really ran)."""
    if rowwise:
        os.environ["PATHWAY_STATE_ROWWISE"] = "1"
    try:
        inputs, node = build()
        emitted: dict = {}

        def on_batch(t, b):
            for k, d, vals in b.iter_rows():
                key = (k, _value_bytes(vals))
                emitted[key] = emitted.get(key, 0) + d

        out = OutputNode(node, on_batch)
        rt = Runtime([out], worker_threads=False)
        for i, per_input in enumerate(ticks):
            inj = {}
            for ii, rows in per_input.items():
                if rows:
                    inj[inputs[ii].id] = [
                        DiffBatch.from_rows(rows, inputs[ii].column_names)
                    ]
            if inj:
                rt.tick(2 * i, inj)
        ex = rt.execs[node.id]
        assert ex._rowwise == rowwise, (
            "unexpected path",
            rowwise,
            ex._fallback_reason,
        )
        return _consolidated(emitted)
    finally:
        os.environ.pop("PATHWAY_STATE_ROWWISE", None)


DCOLS = ["inst", "v", "x"]


def _dedupe_ticks(seed, n_ticks=10):
    rng = np.random.default_rng(seed)
    nk = [1]
    ticks = []
    for _ in range(n_ticks):
        rows = []
        for _ in range(int(rng.integers(0, 18))):
            inst = int(rng.integers(0, 6))
            v = [
                int(rng.integers(0, 4)),
                None,
                float(rng.integers(0, 3)),
                "s%d" % rng.integers(0, 3),
            ][int(rng.integers(0, 4))]
            rows.append((nk[0], 1, (inst, v, int(rng.integers(0, 100)))))
            nk[0] += 1
        ticks.append({0: rows})
    return ticks


def _ge_acceptor(new, old):
    if isinstance(new, str) or isinstance(old, str):
        return str(new) >= str(old)
    return (new or 0) >= (old or 0)


@pytest.mark.parametrize(
    "acceptor,value_col",
    [(None, "v"), (None, None), (_ge_acceptor, "v")],
    ids=["novalcol-eq", "wholerow-eq", "acceptor"],
)
def test_deduplicate_oracle_differential(acceptor, value_col):
    for seed in range(8):
        ticks = _dedupe_ticks(seed)

        def build():
            inp = InputNode(StaticSource(DCOLS), DCOLS)
            return [inp], DeduplicateNode(inp, ["inst"], acceptor, value_col)

        assert _drive(build, ticks, False) == _drive(build, ticks, True)


def test_deduplicate_acceptor_exception_is_atomic():
    """A poisoned row (acceptor raises) must emit nothing and leave the
    stored state untouched — on BOTH paths — and later rows keep folding
    against the unchanged accepted value."""

    def acceptor(new, old):
        if new == 666:
            raise RuntimeError("boom")
        return new >= old

    rows1 = [(1, 1, (0, 5, 0))]
    rows2 = [(2, 1, (0, 666, 1)), (3, 1, (0, 7, 2))]  # poison then good
    ticks = [{0: rows1}, {0: rows2}]

    def build():
        inp = InputNode(StaticSource(DCOLS), DCOLS)
        return [inp], DeduplicateNode(inp, ["inst"], acceptor, "v")

    for rowwise in (False, True):
        got = _drive(build, ticks, rowwise)
        vals = sorted(_value for (_k, _value), d in got.items() if d > 0)
        assert len(vals) == 1  # only the final accepted row is live
        # the accepted value is 7 (folded over unchanged state 5), and no
        # emission ever mentioned 666
        assert not any(b"666" in v for (_k, v) in got)
    # same number of live rows on both paths, and identical content
    assert _drive(build, ticks, False) == _drive(build, ticks, True)


TCOLS_L = ["k", "t", "a"]
TCOLS_R = ["k", "t", "b"]


def _temporal_ticks(seed, n_ticks=8):
    rng = np.random.default_rng(seed)
    nk = [1]
    live = [{}, {}]
    ticks = []
    for _ in range(n_ticks):
        per = {}
        for s in (0, 1):
            rows = []
            for _ in range(int(rng.integers(0, 10))):
                if rng.random() < 0.25 and live[s]:
                    k = list(live[s])[int(rng.integers(0, len(live[s])))]
                    rows.append((k, -1, live[s].pop(k)))
                else:
                    k = nk[0]
                    nk[0] += 1
                    vals = (
                        int(rng.integers(0, 4)),
                        float(rng.integers(0, 20)),
                        int(rng.integers(0, 100)),
                    )
                    live[s][k] = vals
                    rows.append((k, 1, vals))
            per[s] = rows
        ticks.append(per)
    return ticks


@pytest.mark.parametrize(
    "maker",
    [
        lambda il, ir: IntervalJoinNode(
            il, ir, ["k"], ["k"], "t", "t", -2.0, 2.0, "inner"
        ),
        lambda il, ir: IntervalJoinNode(
            il, ir, ["k"], ["k"], "t", "t", -1.0, 3.0, "outer"
        ),
        lambda il, ir: AsofJoinNode(
            il, ir, ["k"], ["k"], "t", "t", "backward", "left"
        ),
        lambda il, ir: AsofJoinNode(
            il, ir, ["k"], ["k"], "t", "t", "nearest", "outer"
        ),
    ],
    ids=["interval-inner", "interval-outer", "asof-back-left", "asof-near-outer"],
)
def test_temporal_join_oracle_differential(maker):
    for seed in range(6):
        ticks = _temporal_ticks(seed)

        def build():
            il = InputNode(StaticSource(TCOLS_L), TCOLS_L)
            ir = InputNode(StaticSource(TCOLS_R), TCOLS_R)
            return [il, ir], maker(il, ir)

        assert _drive(build, ticks, False) == _drive(build, ticks, True)


def test_session_assign_oracle_differential():
    for seed in range(6):
        raw = _temporal_ticks(seed)
        ticks = [{0: per[0]} for per in raw]

        def build():
            il = InputNode(StaticSource(TCOLS_L), TCOLS_L)
            return [il], SessionAssignNode(il, "t", "k", None, 2.5)

        assert _drive(build, ticks, False) == _drive(build, ticks, True)


# ---------------------------------------------------------------------------
# Incremental snapshots + recovery (engine-level, filesystem store)


def _cfg(root):
    class Cfg:
        backend = pw.persistence.Backend.filesystem(str(root))
        snapshot_interval_ms = 0
        snapshot_every = 1

    return Cfg()


def _seg_files(store):
    return {k: len(store.get(k)) for k in store.list_keys("segments/")}


def _build_mixed_pipeline(sink):
    """dedupe + join + interval-join + groupby (state ledger) over two
    inputs — every incrementally-persisted exec in one graph."""
    from pathway_tpu.engine.nodes import GroupByNode
    from pathway_tpu.engine.reducers import ReducerSpec

    il = InputNode(StaticSource(TCOLS_L), TCOLS_L)
    ir = InputNode(StaticSource(TCOLS_R), TCOLS_R)
    ded = DeduplicateNode(il, ["k"], None, "a")
    join = JoinNode(il, ir, ["k"], ["k"], "inner", None)
    ivj = IntervalJoinNode(il, ir, ["k"], ["k"], "t", "t", -2.0, 2.0, "inner")
    gby = GroupByNode(
        il,
        ["k"],
        {
            "cnt": ReducerSpec(kind="count", arg_cols=()),
            "s": ReducerSpec(kind="sum", arg_cols=("a",)),
        },
    )
    sink.setdefault("gby", [])
    outs = [
        OutputNode(ded, lambda t, b: sink["ded"].extend(b.iter_rows())),
        OutputNode(join, lambda t, b: sink["join"].extend(b.iter_rows())),
        OutputNode(ivj, lambda t, b: sink["ivj"].extend(b.iter_rows())),
        OutputNode(gby, lambda t, b: sink["gby"].extend(b.iter_rows())),
    ]
    rt = Runtime(outs, worker_threads=False)
    return rt, il, ir, (ded, join, ivj, gby)


def _bulk_batches(n):
    ks = np.arange(n, dtype=np.int64) % (n // 4)
    lt = np.asarray(ks % 7, dtype=np.float64)
    lb = DiffBatch(
        np.arange(n, dtype=np.uint64) + 1,
        np.ones(n, np.int64),
        {"k": ks, "t": lt, "a": np.arange(n, dtype=np.int64)},
    )
    rb = DiffBatch(
        np.arange(n, dtype=np.uint64) + 10_000_000,
        np.ones(n, np.int64),
        {"k": ks, "t": lt + 1.0, "b": np.arange(n, dtype=np.int64)},
    )
    return lb, rb


def _delta_batches(i, m):
    ks = (np.arange(m, dtype=np.int64) + i * m) % 1000
    lt = np.asarray(ks % 7, dtype=np.float64)
    lb = DiffBatch(
        np.arange(m, dtype=np.uint64) + 20_000_000 + i * m,
        np.ones(m, np.int64),
        {"k": ks, "t": lt, "a": ks + i},
    )
    rb = DiffBatch(
        np.arange(m, dtype=np.uint64) + 30_000_000 + i * m,
        np.ones(m, np.int64),
        {"k": ks, "t": lt + 0.5, "b": ks - i},
    )
    return lb, rb


def test_incremental_snapshot_bytes_proportional_to_churn(tmp_path):
    """After a large bulk load + one small delta tick, the next
    checkpoint writes only the new (small) segments: base segment files
    are reused by name, and the per-generation state blobs carry
    manifests, not pickled state."""
    sink = {"ded": [], "join": [], "ivj": []}
    rt, il, ir, _nodes = _build_mixed_pipeline(sink)
    drv = attach_persistence(rt, _cfg(tmp_path / "p"))
    n = 40_000
    lb, rb = _bulk_batches(n)
    rt.tick(0, {il.id: [lb], ir.id: [rb]})
    drv.commit(snapshot=True)
    files1 = _seg_files(drv.store)
    bulk_bytes = sum(files1.values())
    state_blob_bytes = sum(
        len(drv.store.get(k)) for k in drv.store.list_keys("states/")
    )
    # manifests+residuals are tiny compared to the segment payloads
    assert state_blob_bytes < bulk_bytes / 10, (state_blob_bytes, bulk_bytes)

    dl, dr = _delta_batches(1, 200)
    rt.tick(2, {il.id: [dl], ir.id: [dr]})
    drv.commit(snapshot=True)
    files2 = _seg_files(drv.store)
    new_keys = set(files2) - set(files1)
    new_bytes = sum(files2[k] for k in new_keys)
    assert set(files1) & set(files2), "base segments must be retained"
    assert new_bytes < bulk_bytes / 20, (
        f"checkpoint not incremental: delta snapshot wrote {new_bytes} "
        f"of {bulk_bytes} bulk bytes"
    )


def test_recovery_without_replay_matches_uninterrupted_run(tmp_path):
    """Kill after a bulk + deltas, restart from the incremental snapshot
    (zero replayed events), keep streaming — final consolidated outputs
    are identical to a never-interrupted run."""

    def consolidate(rows):
        state: dict = {}
        for k, d, vals in rows:
            key = (k, _value_bytes(vals))
            state[key] = state.get(key, 0) + d
        return {k: v for k, v in state.items() if v}

    def run(with_restart):
        root = tmp_path / ("r" if with_restart else "u")
        sink = {"ded": [], "join": [], "ivj": []}
        rt, il, ir, _nodes = _build_mixed_pipeline(sink)
        drv = attach_persistence(rt, _cfg(root))
        lb, rb = _bulk_batches(4000)
        rt.tick(0, {il.id: [lb], ir.id: [rb]})
        for i in range(1, 4):
            dl, dr = _delta_batches(i, 100)
            rt.tick(2 * i, {il.id: [dl], ir.id: [dr]})
        drv.commit(snapshot=True)  # "crash" here: state durable, rt dropped
        if with_restart:
            rt2, il2, ir2, nodes2 = _build_mixed_pipeline(sink)
            drv2 = attach_persistence(rt2, _cfg(root))
            assert drv2.restored_from_snapshot
            assert drv2.replayed_events == 0, drv2.replayed_events
            # arrangement-backed execs really did come back via segments
            ded_ex = rt2.execs[nodes2[0].id]
            assert len(ded_ex.arr.entries()) > 0
            assert not ded_ex.arr.segments[0].jks.flags.writeable  # mmap
            gby_ex = rt2.execs[nodes2[3].id]
            assert gby_ex.groups and gby_ex._ledger_enabled
            rt, il, ir = rt2, il2, ir2
        for i in range(4, 7):
            dl, dr = _delta_batches(i, 100)
            rt.tick(2 * i, {il.id: [dl], ir.id: [dr]})
        return {name: consolidate(rows) for name, rows in sink.items()}

    uninterrupted = run(False)
    restarted = run(True)
    # the restarted run's sink accumulated pre-crash + post-restart diffs;
    # consolidation makes both orders comparable
    assert restarted == uninterrupted


def test_monolith_escape_hatch_differential(tmp_path, monkeypatch):
    """PATHWAY_PERSIST_MONOLITH=1 keeps the old whole-pickle behavior and
    restores the same state (no segment files written)."""
    monkeypatch.setenv("PATHWAY_PERSIST_MONOLITH", "1")
    sink = {"ded": [], "join": [], "ivj": []}
    rt, il, ir, nodes = _build_mixed_pipeline(sink)
    drv = attach_persistence(rt, _cfg(tmp_path / "m"))
    lb, rb = _bulk_batches(2000)
    rt.tick(0, {il.id: [lb], ir.id: [rb]})
    drv.commit(snapshot=True)
    assert not drv.store.list_keys("segments/")
    rt2, _il2, _ir2, nodes2 = _build_mixed_pipeline(sink)
    drv2 = attach_persistence(rt2, _cfg(tmp_path / "m"))
    assert drv2.restored_from_snapshot and drv2.replayed_events == 0
    a = rt.execs[nodes[0].id].arr.entries()
    b = rt2.execs[nodes2[0].id].arr.entries()
    _entries_equal(a, b)


def test_segment_gc_retires_dead_segments(tmp_path):
    """Heavy retraction churn compacts the arrangement; the snapshot GC
    then deletes segment files no retained generation references."""
    sink = {"ded": [], "join": [], "ivj": []}
    rt, il, ir, _nodes = _build_mixed_pipeline(sink)
    drv = attach_persistence(rt, _cfg(tmp_path / "gc"))
    lb, rb = _bulk_batches(4000)
    rt.tick(0, {il.id: [lb], ir.id: [rb]})
    drv.commit(snapshot=True)
    before = set(_seg_files(drv.store))
    # retract the whole left bulk: compaction rewrites, old files die
    neg = DiffBatch(lb.keys, -lb.diffs, lb.columns)
    rt.tick(2, {il.id: [neg]})
    drv.commit(snapshot=True)
    after = set(_seg_files(drv.store))
    assert before - after, "no segment files were retired"
    # live set is exactly what the latest generation references
    import json as _json

    meta = _json.loads(drv.store.get("metadata.json").decode())
    assert after == set(meta["state"]["segment_keys"])


def test_aborted_snapshot_orphans_never_mask_new_segments(tmp_path):
    """Crash window: segment files written by a snapshot whose metadata
    never committed are orphans; after restore the seg-id counter rolls
    back with the durable manifest and mints the same ids again with
    DIFFERENT content.  Those keys must be overwritten, not skipped as
    already-present (regression: priming the dedup set from a store
    listing instead of the durable metadata)."""
    root = tmp_path / "p"

    def _cfg_manual(r):
        # interval commits OFF: only explicit commit() calls snapshot, so
        # the _snapshot_operators call below really is a torn snapshot
        # (segments + state blobs written, metadata never lands)
        cfg = _cfg(r)
        cfg.snapshot_interval_ms = 10**9
        return cfg

    sink = {"ded": [], "join": [], "ivj": []}
    rt, il, ir, _n = _build_mixed_pipeline(sink)
    drv = attach_persistence(rt, _cfg_manual(root))
    lb, rb = _bulk_batches(2000)
    rt.tick(0, {il.id: [lb], ir.id: [rb]})
    drv.commit(snapshot=True)  # durable gen 1

    # a delta tick + a snapshot attempt whose METADATA never lands
    dl, dr = _delta_batches(1, 100)
    rt.tick(2, {il.id: [dl], ir.id: [dr]})
    import json as _json

    meta = _json.loads(drv.store.get("metadata.json").decode())
    assert drv._snapshot_operators(dict(meta)) is not None  # orphans now

    # restart: restores gen 1; a SAME-SHAPE delta with different values
    # re-mints exactly the orphan ids (same keys, same merge cascade,
    # different bytes) — the worst case for stale-skip
    sink2 = {"ded": [], "join": [], "ivj": []}
    rt2, il2, ir2, nodes2 = _build_mixed_pipeline(sink2)
    drv2 = attach_persistence(rt2, _cfg_manual(root))
    assert drv2.restored_from_snapshot
    dl2, dr2 = _delta_batches(1, 100)
    dl2 = DiffBatch(
        dl2.keys, dl2.diffs, {**dl2.columns, "a": dl2.columns["a"] + 999}
    )
    rt2.tick(2, {il2.id: [dl2], ir2.id: [dr2]})
    drv2.commit(snapshot=True)
    expected = rt2.execs[nodes2[0].id].arr.entries()

    # final restart must see gen-1 + the SECOND delta, not orphan bytes
    sink3 = {"ded": [], "join": [], "ivj": []}
    rt3, _il3, _ir3, nodes3 = _build_mixed_pipeline(sink3)
    drv3 = attach_persistence(rt3, _cfg_manual(root))
    assert drv3.restored_from_snapshot and drv3.replayed_events == 0
    _entries_equal(expected, rt3.execs[nodes3[0].id].arr.entries())


def test_session_fallback_mid_tick_does_not_drop_diffs(monkeypatch):
    """An exception on the session columnar path AFTER the arrangement
    append (the exact window the fallback exists for) must still deliver
    the tick's output diffs — emitted state mirrors what downstream
    actually received, so the rowwise retry emits the full pre-tick →
    post-tick difference."""
    from pathway_tpu.engine import temporal_nodes as tn

    rows1 = [(1, 1, (0, 1.0, 0)), (2, 1, (0, 2.0, 0))]
    rows2 = [(3, 1, (1, 10.0, 0)), (4, 1, (0, 2.5, 0))]
    ticks = [{0: rows1}, {0: rows2}]

    def build():
        il = InputNode(StaticSource(TCOLS_L), TCOLS_L)
        return [il], SessionAssignNode(il, "t", "k", None, 2.0)

    expected = _drive(build, ticks, True)  # oracle

    calls = {"n": 0}
    orig = tn.SessionAssignExec._view_by_jk

    def flaky(self, rows):
        calls["n"] += 1
        if calls["n"] == 2:  # second tick: post-append probe explodes
            raise RuntimeError("probe boom")
        return orig(self, rows)

    monkeypatch.setattr(tn.SessionAssignExec, "_view_by_jk", flaky)

    inputs, node = build()
    emitted: dict = {}

    def on_batch(t, b):
        for k, d, vals in b.iter_rows():
            key = (k, _value_bytes(vals))
            emitted[key] = emitted.get(key, 0) + d

    out = OutputNode(node, on_batch)
    rt = Runtime([out], worker_threads=False)
    for i, per in enumerate(ticks):
        rt.tick(2 * i, {inputs[0].id: [DiffBatch.from_rows(per[0], TCOLS_L)]})
    ex = rt.execs[node.id]
    assert ex._rowwise and ex._fallback_reason == "exception"
    assert calls["n"] >= 2
    assert _consolidated(emitted) == expected


def test_legacy_monolith_states_upgrade_into_arrangements():
    """Snapshots written by the pre-ledger code (plain dict state, no
    arrangement keys) must restore onto the columnar path: dedupe keeps
    suppressing already-accepted values, temporal joins keep their
    buffered sides, session keeps its windows, and groupby seeds its
    ledger so the next incremental snapshot covers every restored
    group."""
    from pathway_tpu.engine.nodes import GroupByNode
    from pathway_tpu.engine.reducers import ReducerSpec
    from pathway_tpu.engine.temporal_nodes import _TimedSide

    # --- dedupe: legacy {state: ik -> (value, vals, ik)} ------------------
    inp = InputNode(StaticSource(DCOLS), DCOLS)
    ded = DeduplicateNode(inp, ["inst"], None, "v")
    ex = ded._make_local_exec()
    from pathway_tpu.internals.api import ref_scalar

    ik = int(ref_scalar(7))
    legacy = {
        "inst_idx": ex.inst_idx,
        "val_idx": ex.val_idx,
        "state": {ik: (5, (7, 5, 0), ik)},
    }
    ex.load_state(legacy)
    assert not ex._rowwise and len(ex.arr.entries()) == 1
    ex._restore_emit = None
    out = ex.process(
        0, [[DiffBatch.from_rows([(9, 1, (7, 5, 1))], DCOLS)]]
    )
    assert out == [], "already-accepted value must stay suppressed"

    # --- temporal join: legacy _TimedSide dict sides ----------------------
    il = InputNode(StaticSource(TCOLS_L), TCOLS_L)
    ir = InputNode(StaticSource(TCOLS_R), TCOLS_R)
    ivj = IntervalJoinNode(il, ir, ["k"], ["k"], "t", "t", -2.0, 2.0, "inner")
    tex = ivj.make_exec()
    side = _TimedSide()
    jk = int(ref_scalar(1))
    side.apply(jk, 11, 1, 4.0, (1, 4.0, 100))
    legacy_t = {
        "l_on_idx": tex.l_on_idx,
        "r_on_idx": tex.r_on_idx,
        "left": side,
        "right": _TimedSide(),
    }
    tex.load_state(legacy_t)
    assert not tex._rowwise
    out = tex.process(
        0,
        [[], [DiffBatch.from_rows([(21, 1, (1, 5.0, 200))], TCOLS_R)]],
    )
    # restored left row at t=4 matches the new right row at t=5
    assert len(out) == 1 and int(out[0].diffs.sum()) == 1

    # --- groupby: legacy {groups}; ledger must be seeded ------------------
    gin = InputNode(StaticSource(["k", "v"]), ["k", "v"])
    gby = GroupByNode(
        gin, ["k"], {"cnt": ReducerSpec(kind="count", arg_cols=())}
    )
    gex = gby._make_local_exec()
    gex.enable_state_ledger()
    b = DiffBatch.from_rows([(1, 1, ("a", 1)), (2, 1, ("b", 2))], ["k", "v"])
    gex.process(0, [[b]])
    donor_groups = dict(gex.groups)
    legacy_g = {"g_idx": gex.g_idx, "groups": donor_groups}
    gex2 = gby._make_local_exec()
    gex2.enable_state_ledger()
    gex2.load_state(legacy_g)
    assert gex2._ledger_enabled
    assert gex2._ledgered == set(donor_groups), "ledger not seeded"
    arranged = gex2.arranged_state()
    assert arranged is not None
    assert len(arranged[1]["ledger"].entries()) == len(donor_groups)


def test_legacy_arrangement_pickle_regains_persistence_identity():
    """Arrangements unpickled from pre-State-Ledger snapshots (no epoch /
    seg-id state, segments with seg_id=-1) must mint a fresh identity so
    the next manifest_of works instead of aborting every snapshot."""
    import pickle

    arr = Arrangement(1)
    arr.append(
        np.arange(10, dtype=np.uint64),
        np.arange(10, dtype=np.uint64),
        np.ones(10, np.int64),
        [np.arange(10)],
    )
    arr.seal()
    legacy_state = dict(arr.__dict__)
    del legacy_state["epoch"]
    del legacy_state["_next_seg_id"]
    for seg in legacy_state["segments"]:
        seg.seg_id = -1
    blob = pickle.dumps(legacy_state)
    restored = Arrangement.__new__(Arrangement)
    restored.__setstate__(pickle.loads(blob))
    man = manifest_of(restored)  # must not raise
    ids = [s["id"] for s in man["segments"]]
    assert all(i >= 0 for i in ids) and len(set(ids)) == len(ids)
    assert restored.epoch and restored.epoch != arr.epoch
    assert restored._next_seg_id > max(ids)


def test_env_rowwise_knob_wins_over_arranged_snapshot(tmp_path, monkeypatch):
    """Restarting from a columnar snapshot with the rowwise escape hatch
    set must land on the rowwise path (the knob exists to dodge columnar
    bugs — silently resuming the columnar path would defeat it)."""
    sink = {"ded": [], "join": [], "ivj": []}
    rt, il, ir, _n = _build_mixed_pipeline(sink)
    drv = attach_persistence(rt, _cfg(tmp_path / "p"))
    lb, rb = _bulk_batches(2000)
    rt.tick(0, {il.id: [lb], ir.id: [rb]})
    drv.commit(snapshot=True)

    monkeypatch.setenv("PATHWAY_STATE_ROWWISE", "1")
    monkeypatch.setenv("PATHWAY_JOIN_ROWWISE", "1")
    sink2 = {"ded": [], "join": [], "ivj": []}
    rt2, _il2, _ir2, nodes2 = _build_mixed_pipeline(sink2)
    drv2 = attach_persistence(rt2, _cfg(tmp_path / "p"))
    assert drv2.restored_from_snapshot
    ded_ex = rt2.execs[nodes2[0].id]
    join_ex = rt2.execs[nodes2[1].id]
    ivj_ex = rt2.execs[nodes2[2].id]
    assert ded_ex._rowwise and ded_ex.state  # materialized from segments
    assert join_ex._rowwise and join_ex.left is not None
    assert ivj_ex._rowwise and ivj_ex.left.by_jk


def test_persistence_metrics_exposed():
    from pathway_tpu.observability import REGISTRY

    names = REGISTRY.render()
    for metric in (
        "pathway_persistence_snapshot_bytes",
        "pathway_persistence_snapshot_seconds",
        "pathway_persistence_segments_written_total",
        "pathway_persistence_segments_retired_total",
        "pathway_persistence_recovery_seconds",
    ):
        assert metric in names, metric
