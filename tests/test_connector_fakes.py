"""Protocol-level fakes for the broker/database connectors, so
io/kafka.py, io/nats.py, io/elasticsearch.py and io/mongodb.py execute
their real parse/format/offset logic in CI without services (reference
technique: python/pathway/tests mock-based connector tests)."""

from __future__ import annotations

import json
import sys
import threading
import time
import types

import pytest

import pathway_tpu as pw


class InSchema(pw.Schema):
    name: str
    n: int


def _run_streaming_until(res_table, n_rows, timeout_s=10.0):
    seen = []

    def on_change(key, row, time, is_addition):
        seen.append((row, is_addition))

    pw.io.subscribe(res_table, on_change)

    def stopper():
        deadline = __import__("time").time() + timeout_s
        while __import__("time").time() < deadline and len(seen) < n_rows:
            __import__("time").sleep(0.02)
        pw.internals.parse_graph.G.runtime.stop()

    threading.Thread(target=stopper, daemon=True).start()
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    return seen


# --------------------------------------------------------------------------
# Kafka


def _fake_confluent_kafka(broker: dict):
    mod = types.ModuleType("confluent_kafka")

    class _Msg:
        def __init__(self, topic, partition, offset, value):
            self._topic, self._partition = topic, partition
            self._offset, self._value = offset, value

        def value(self):
            return self._value

        def error(self):
            return None

        def partition(self):
            return self._partition

        def offset(self):
            return self._offset

    class TopicPartition:
        def __init__(self, topic, partition=0, offset=-1):
            self.topic, self.partition, self.offset = topic, partition, offset

    class Consumer:
        def __init__(self, settings):
            self.settings = settings
            self._topic = None
            self._pos = 0
            self._assigned = None

        def subscribe(self, topics, on_assign=None):
            self._topic = topics[0]
            if on_assign is not None:
                on_assign(self, [TopicPartition(self._topic, 0)])

        def assign(self, partitions):
            # honour seek offsets like rdkafka's assign after on_assign
            self._assigned = partitions
            for p in partitions:
                if p.offset >= 0:
                    self._pos = p.offset

        def poll(self, timeout):
            msgs = broker.get(self._topic, [])
            if self._pos < len(msgs):
                value = msgs[self._pos]
                m = _Msg(self._topic, 0, self._pos, value)
                self._pos += 1
                return m
            __import__("time").sleep(min(timeout, 0.01))
            return None

        def close(self):
            pass

    class Producer:
        def __init__(self, settings):
            self.settings = settings

        def produce(self, topic, key=None, value=None):
            broker.setdefault(topic, []).append(value)

        def flush(self):
            pass

    mod.Consumer = Consumer
    mod.Producer = Producer
    mod.TopicPartition = TopicPartition
    return mod


def test_kafka_read_json_roundtrip(monkeypatch):
    broker = {
        "t1": [
            json.dumps({"name": "a", "n": 1}).encode(),
            json.dumps({"name": "b", "n": 2}).encode(),
            json.dumps({"name": "a", "n": 3}).encode(),
        ]
    }
    monkeypatch.setitem(
        sys.modules, "confluent_kafka", _fake_confluent_kafka(broker)
    )
    t = pw.io.kafka.read(
        {"bootstrap.servers": "fake:9092", "group.id": "g"},
        topic="t1",
        schema=InSchema,
        format="json",
    )
    seen = _run_streaming_until(t, 3)
    rows = sorted((r["name"], r["n"]) for r, add in seen if add)
    assert rows == [("a", 1), ("a", 3), ("b", 2)]


def test_kafka_read_seek_offsets(monkeypatch):
    """The offset state produced by the source must make a resumed consumer
    skip already-ingested messages (reference: KafkaReader seek)."""
    broker = {"t2": [b"one", b"two", b"three"]}
    monkeypatch.setitem(
        sys.modules, "confluent_kafka", _fake_confluent_kafka(broker)
    )
    t = pw.io.kafka.read({}, topic="t2", format="plaintext")
    t._node.source.seek({"offsets": {0: 2}})
    seen = _run_streaming_until(t, 1)
    assert [r["data"] for r, add in seen if add] == ["three"]
    assert t._node.source.offset_state() == {"offsets": {0: 3}}


def test_kafka_write(monkeypatch):
    broker: dict = {}
    monkeypatch.setitem(
        sys.modules, "confluent_kafka", _fake_confluent_kafka(broker)
    )
    t = pw.debug.table_from_rows(InSchema, [("x", 1), ("y", 2)])
    pw.io.kafka.write(t, {"bootstrap.servers": "fake"}, topic_name="out")
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    payloads = sorted(
        (json.loads(v)["name"], json.loads(v)["n"], json.loads(v)["diff"])
        for v in broker["out"]
    )
    assert payloads == [("x", 1, 1), ("y", 2, 1)]


# --------------------------------------------------------------------------
# NATS


def _fake_nats(published: dict, queues: dict):
    mod = types.ModuleType("nats")

    class _Msg:
        def __init__(self, data):
            self.data = data

    class _Sub:
        def __init__(self, topic):
            self._topic = topic
            self._pos = 0

        async def next_msg(self, timeout=None):
            q = queues.get(self._topic, [])
            if self._pos < len(q):
                m = _Msg(q[self._pos])
                self._pos += 1
                return m
            import asyncio

            await asyncio.sleep(min(timeout or 0.01, 0.01))
            raise TimeoutError

    class _NC:
        async def subscribe(self, topic):
            return _Sub(topic)

        async def publish(self, topic, data):
            published.setdefault(topic, []).append(data)

        async def close(self):
            pass

    async def connect(uri):
        return _NC()

    mod.connect = connect
    return mod


def test_nats_read_and_write(monkeypatch):
    queues = {
        "in": [
            json.dumps({"name": "n1", "n": 5}).encode(),
            json.dumps({"name": "n2", "n": 6}).encode(),
        ]
    }
    published: dict = {}
    monkeypatch.setitem(sys.modules, "nats", _fake_nats(published, queues))
    t = pw.io.nats.read(
        "nats://fake:4222", "in", schema=InSchema, format="json"
    )
    pw.io.nats.write(t, "nats://fake:4222", "out")
    seen = _run_streaming_until(t, 2)
    assert sorted(r["name"] for r, add in seen if add) == ["n1", "n2"]
    out = sorted(json.loads(p)["name"] for p in published["out"])
    assert out == ["n1", "n2"]


# --------------------------------------------------------------------------
# Elasticsearch


def test_elasticsearch_bulk_write(monkeypatch):
    posts = []

    class _Resp:
        status_code = 200

        def raise_for_status(self):
            pass

        def json(self):
            return {"errors": False, "items": []}

    class _Session:
        def __init__(self):
            self.headers = {}
            self.auth = None

        def post(self, url, data=None, headers=None, timeout=None):
            posts.append((url, data))
            return _Resp()

    import requests

    monkeypatch.setattr(requests, "Session", _Session)

    class S2(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        name: str

    rows = [(1, "a", 0, 1), (2, "b", 0, 1), (1, "a", 2, -1)]
    t = pw.debug.table_from_rows(S2, rows, is_stream=True)
    pw.io.elasticsearch.write(
        t,
        "http://fake:9200",
        auth=pw.io.elasticsearch.ElasticSearchAuth.basic("u", "p"),
        index_name="idx",
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    actions = []
    for _url, body in posts:
        lines = [json.loads(x) for x in body.decode().strip().split("\n")]
        i = 0
        while i < len(lines):
            if "index" in lines[i]:
                actions.append(("index", lines[i + 1]["name"]))
                i += 2
            else:
                actions.append(("delete", lines[i]["delete"]["_id"]))
                i += 1
    kinds = [a[0] for a in actions]
    assert kinds.count("index") == 2 and kinds.count("delete") == 1
    assert all(u.endswith("/_bulk") for u, _ in posts)


# --------------------------------------------------------------------------
# MongoDB


def _fake_pymongo(written: list):
    mod = types.ModuleType("pymongo")

    class InsertOne:
        def __init__(self, doc):
            self.doc = doc

    class _Coll:
        def bulk_write(self, ops):
            written.extend(op.doc for op in ops)

    class _Db(dict):
        def __getitem__(self, name):
            return _Coll()

    class MongoClient:
        def __init__(self, conn):
            self.conn = conn

        def __getitem__(self, name):
            return _Db()

        def close(self):
            pass

    mod.MongoClient = MongoClient
    mod.InsertOne = InsertOne
    return mod


def test_mongodb_write(monkeypatch):
    written: list = []
    monkeypatch.setitem(sys.modules, "pymongo", _fake_pymongo(written))
    t = pw.debug.table_from_rows(InSchema, [("m1", 1), ("m2", 2)])
    pw.io.mongodb.write(t, "mongodb://fake", "db", "coll")
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert sorted(d["name"] for d in written) == ["m1", "m2"]
    assert all(d["diff"] == 1 and "key" in d and "time" in d for d in written)


# --------------------------------------------------------------------------
# Slack / Logstash (HTTP writers)


def test_slack_alerts(monkeypatch):
    posts = []

    class _Resp:
        def raise_for_status(self):
            pass

    class _Session:
        def __init__(self):
            self.headers = {}

        def post(self, url, json=None, timeout=None):
            posts.append((url, json, dict(self.headers)))
            return _Resp()

    import requests

    monkeypatch.setattr(requests, "Session", _Session)

    class A(pw.Schema):
        message: str

    t = pw.debug.table_from_rows(A, [("disk full",), ("cpu hot",)])
    pw.io.slack.send_alerts(t, "C12345", "xoxb-token")
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(posts) == 2
    assert all(u.endswith("chat.postMessage") for u, _j, _h in posts)
    assert sorted(j["text"] for _u, j, _h in posts) == ["cpu hot", "disk full"]
    assert all(j["channel"] == "C12345" for _u, j, _h in posts)
    assert all(
        h.get("Authorization") == "Bearer xoxb-token" for _u, _j, h in posts
    )


def test_logstash_write_with_retry(monkeypatch):
    calls = {"n": 0}
    docs = []

    class _Resp:
        def raise_for_status(self):
            pass

    class _Session:
        def __init__(self):
            self.headers = {}

        def post(self, url, json=None, timeout=None):
            calls["n"] += 1
            if calls["n"] == 1:
                import requests

                raise requests.RequestException("transient")
            docs.append(json)
            return _Resp()

    import requests

    monkeypatch.setattr(requests, "Session", _Session)

    t = pw.debug.table_from_rows(InSchema, [("l1", 1)])
    pw.io.logstash.write(t, "http://fake:8080", n_retries=2)
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert [d["name"] for d in docs] == ["l1"]
    assert calls["n"] == 2  # one failure + one retry success


# --------------------------------------------------------------------------
# BigQuery / PubSub


def test_bigquery_write(monkeypatch):
    inserted = []

    class _Client:
        def insert_rows_json(self, target, rows):
            inserted.append((target, rows))
            return []

        def close(self):
            pass

    bq_mod = types.ModuleType("google.cloud.bigquery")
    bq_mod.Client = _Client
    google = types.ModuleType("google")
    cloud = types.ModuleType("google.cloud")
    google.cloud = cloud
    cloud.bigquery = bq_mod
    monkeypatch.setitem(sys.modules, "google", google)
    monkeypatch.setitem(sys.modules, "google.cloud", cloud)
    monkeypatch.setitem(sys.modules, "google.cloud.bigquery", bq_mod)

    t = pw.debug.table_from_rows(InSchema, [("b1", 1), ("b2", 2)])
    pw.io.bigquery.write(t, "ds", "tbl")
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert inserted and inserted[0][0] == "ds.tbl"
    names = sorted(r["name"] for _t, rows in inserted for r in rows)
    assert names == ["b1", "b2"]
    assert all(
        "time" in r and "diff" in r for _t, rows in inserted for r in rows
    )


def test_pubsub_write():
    published = []

    class _Future:
        def result(self, timeout=None):
            return "msgid"

    class _Publisher:
        def topic_path(self, project, topic):
            return f"projects/{project}/topics/{topic}"

        def publish(self, topic_path, data, **attrs):
            published.append((topic_path, json.loads(data), attrs))
            return _Future()

    t = pw.debug.table_from_rows(InSchema, [("p1", 9)])
    pw.io.pubsub.write(
        t, publisher=_Publisher(), project_id="proj", topic_id="top"
    )
    pw.run(monitoring_level=pw.MonitoringLevel.NONE)
    assert len(published) == 1
    path, doc, attrs = published[0]
    assert path == "projects/proj/topics/top"
    assert doc["name"] == "p1" and doc["n"] == 9
    assert attrs["pathway_diff"] == "1" and "pathway_key" in attrs
