"""Ported reference ordered/statistical/flatten tests
(reference: python/pathway/tests/ordered/test_diff.py,
statistical/test_interpolate.py, test_flatten.py) — prev/next-based diff
with instance partitioning, linear interpolation over a sorted axis,
flatten with origin ids."""

from __future__ import annotations

from typing import Any

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu import Table, this
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.debug import table_from_pandas

from tests.ref_utils import (
    assert_table_equality_wo_index,
    assert_table_equality_wo_index_types,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    yield
    pw.internals.parse_graph.G.clear()


def test_diff_single_column():
    t = T(
        """
            | t |  v
        1   | 1 |  1
        2   | 2 |  2
        3   | 3 |  4
        4   | 4 |  7
        5   | 5 |  11
        6   | 6 |  16
        7   | 7 |  22
        8   | 8 |  29
        9   | 9 |  37
    """
    )
    res = t.diff(t.t, t.v)

    expected = T(
        """
            | diff_v
        1   |
        2   | 1
        3   | 2
        4   | 3
        5   | 4
        6   | 5
        7   | 6
        8   | 7
        9   | 8
    """
    )

    assert_table_equality_wo_index(res, expected)


def test_diff_multiple_columns():
    t = T(
        """
            | t |  v1  | v2
        1   | 1 |  1   | 0
        2   | 2 |  2   | 10
        3   | 3 |  4   | 54
        4   | 4 |  7   | 64
        5   | 5 |  11  | 12
        6   | 6 |  16  | 24
        7   | 7 |  22  | 18
        8   | 8 |  29  | -45
        9   | 9 |  37  | 100
    """
    )
    res = t.diff(t.t, t.v1, t.v2)

    expected = T(
        """
            | diff_v1 | diff_v2
        1   |    |
        2   | 1  | 10
        3   | 2  | 44
        4   | 3  | 10
        5   | 4  | -52
        6   | 5  | 12
        7   | 6  | -6
        8   | 7  | -63
        9   | 8  | 145
    """
    )

    assert_table_equality_wo_index(res, expected)


def test_diff_instance():
    t = T(
        """
            | t | i |  v
        1   | 1 | 0 |  1
        2   | 2 | 1 |  2
        3   | 3 | 1 |  4
        4   | 3 | 0 |  7
        5   | 5 | 1 |  11
        6   | 5 | 0 |  16
        7   | 7 | 0 |  22
        8   | 8 | 1 |  29
        9   | 9 | 0 |  37
    """
    )
    res = t.diff(t.t, t.v, instance=t.i)

    expected = T(
        """
            | diff_v
        1   |
        2   |
        3   |  2
        4   |  6
        5   |  7
        6   |  9
        7   |  6
        8   | 18
        9   | 15
    """
    )

    assert_table_equality_wo_index(res, expected)


def test_interpolate_already_sorted():
    t = T(
        """
            | t |  v
        1   | 1 |  1
        2   | 2 |  2
        3   | 3 |  3
        4   | 4 |  4
        5   | 5 |  5
        6   | 6 |  6
        7   | 7 |  7
        8   | 8 |  8
        9   | 9 |  9
    """
    )
    res = pw.statistical.interpolate(t, t.t, t.v)

    assert_table_equality_wo_index_types(res, t)


def test_interpolate_multiple_columns():
    t = T(
        """
            | t |  v1 | v2
        1   | 1 |  1  |
        2   | 2 |     | 10
        3   | 3 |  3  | 40
        4   | 4 |     |
        5   | 5 |  5  | 50
        6   | 6 |     |
        7   | 7 |     |
        8   | 8 |     | 80
        9   | 9 |  9  |
    """
    )
    res = pw.statistical.interpolate(t, t.t, t.v1, t.v2)

    expected = T(
        """
            | t |  v1   | v2
        1   | 1 |  1    | 10.0
        2   | 2 |  2.0  | 10
        3   | 3 |  3    | 40
        4   | 4 |  4.0  | 45.0
        5   | 5 |  5    | 50
        6   | 6 |  6.0  | 60.0
        7   | 7 |  7.0  | 70.0
        8   | 8 |  8.0  | 80
        9   | 9 |  9    | 80.0
    """
    )

    assert_table_equality_wo_index_types(res, expected)


def test_flatten_simple():
    tab = table_from_pandas(pd.DataFrame.from_dict({"col": [[1, 2, 3, 4]]}))

    assert_table_equality_wo_index(
        tab.flatten(this.col, origin_id="origin_id"),
        T(
            """
    col | origin_id
      1 | 0
      2 | 0
      3 | 0
      4 | 0
    """,
        ).with_columns(origin_id=tab.pointer_from(this.origin_id)),
    )


def test_flatten_no_origin():
    tab = table_from_pandas(pd.DataFrame.from_dict({"col": [[1, 2, 3, 4]]}))

    assert_table_equality_wo_index(
        tab.flatten(this.col),
        T(
            """
    col
      1
      2
      3
      4
    """,
        ),
    )


def test_flatten_inner_repeats():
    tab = table_from_pandas(pd.DataFrame.from_dict({"col": [[1, 1, 1, 3]]}))

    assert_table_equality_wo_index(
        tab.flatten(this.col, origin_id="origin_id"),
        T(
            """
    col | origin_id
      1 | 0
      1 | 0
      1 | 0
      3 | 0
    """,
        ).with_columns(origin_id=tab.pointer_from(this.origin_id)),
    )


def test_flatten_more_repeats():
    tab = table_from_pandas(
        pd.DataFrame.from_dict({"col": [[1, 1, 1, 3], [1]]})
    )

    assert_table_equality_wo_index(
        tab.flatten(this.col, origin_id="origin_id"),
        T(
            """
    col | origin_id
      1 | 0
      1 | 0
      1 | 0
      3 | 0
      1 | 1
    """,
        ).with_columns(origin_id=tab.pointer_from(this.origin_id)),
    )


def test_flatten_empty_lists():
    tab = table_from_pandas(pd.DataFrame.from_dict({"col": [[], []]}))

    assert_table_equality_wo_index(
        tab.flatten(this.col, origin_id="origin_id"),
        Table.empty(col=Any, origin_id=pw.Pointer),
    )
