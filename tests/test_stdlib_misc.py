"""HMM reducer, LiveTable, viz, telemetry (reference: stdlib/ml/hmm.py,
internals/interactive.py, stdlib/viz, telemetry stack)."""

import time

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts


def test_hmm_reducer_viterbi_filtering():
    from pathway_tpu.stdlib.ml.hmm import (
        DenseHMM,
        create_hmm_reducer,
        most_likely_state,
    )

    # weather HMM: observations strongly indicate the hidden state
    hmm = DenseHMM(
        states=["rain", "sun"],
        initial={"rain": 0.5, "sun": 0.5},
        transitions={
            ("rain", "rain"): 0.7,
            ("rain", "sun"): 0.3,
            ("sun", "rain"): 0.3,
            ("sun", "sun"): 0.7,
        },
        emission=lambda s, o: (
            0.9 if (s == "rain") == (o == "umbrella") else 0.1
        ),
    )
    reducer = create_hmm_reducer(hmm)
    t = T(
        """
        g | obs      | __time__
        1 | umbrella | 2
        1 | umbrella | 4
        1 | shades   | 6
        1 | shades   | 8
        """
    )
    res = t.groupby(t.g).reduce(t.g, beam=reducer(t.obs))
    out = res.select(res.g, state=pw.apply(most_likely_state, res.beam))
    _keys, cols = table_to_dicts(out)
    assert list(cols["state"].values()) == ["sun"]


def test_live_table_background_updates():
    t = T(
        """
        v
        1
        2
        3
        """
    )
    agg = t.groupby().reduce(total=pw.reducers.sum(t.v))
    lt = pw.live(agg)
    deadline = time.time() + 10
    while time.time() < deadline and len(lt) == 0:
        time.sleep(0.05)
    df = lt.to_pandas()
    assert list(df["total"]) == [6]
    lt.stop()


def test_viz_table_and_show(capsys):
    t = T(
        """
        a | b
        1 | x
        """
    )
    df = pw.viz.table_viz(t)
    assert list(df.columns) == ["a", "b"]
    pw.internals.parse_graph.G.clear()
    t2 = T(
        """
        a
        7
        """
    )
    pw.viz.show(t2)
    out = capsys.readouterr().out
    assert "7" in out


def test_telemetry_span_timings():
    from pathway_tpu.internals.telemetry import get_telemetry

    tel = get_telemetry()
    with tel.span("test.block"):
        pass
    assert "test.block" in tel.timings
