"""HMM reducer, LiveTable, viz, telemetry (reference: stdlib/ml/hmm.py,
internals/interactive.py, stdlib/viz, telemetry stack)."""

import time

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts


def test_hmm_reducer_viterbi_filtering():
    from pathway_tpu.stdlib.ml.hmm import (
        DenseHMM,
        create_hmm_reducer,
        most_likely_state,
    )

    # weather HMM: observations strongly indicate the hidden state
    hmm = DenseHMM(
        states=["rain", "sun"],
        initial={"rain": 0.5, "sun": 0.5},
        transitions={
            ("rain", "rain"): 0.7,
            ("rain", "sun"): 0.3,
            ("sun", "rain"): 0.3,
            ("sun", "sun"): 0.7,
        },
        emission=lambda s, o: (
            0.9 if (s == "rain") == (o == "umbrella") else 0.1
        ),
    )
    reducer = create_hmm_reducer(hmm)
    t = T(
        """
        g | obs      | __time__
        1 | umbrella | 2
        1 | umbrella | 4
        1 | shades   | 6
        1 | shades   | 8
        """
    )
    res = t.groupby(t.g).reduce(t.g, beam=reducer(t.obs))
    out = res.select(res.g, state=pw.apply(most_likely_state, res.beam))
    _keys, cols = table_to_dicts(out)
    assert list(cols["state"].values()) == ["sun"]


def test_live_table_background_updates():
    t = T(
        """
        v
        1
        2
        3
        """
    )
    agg = t.groupby().reduce(total=pw.reducers.sum(t.v))
    lt = pw.live(agg)
    deadline = time.time() + 10
    while time.time() < deadline and len(lt) == 0:
        time.sleep(0.05)
    df = lt.to_pandas()
    assert list(df["total"]) == [6]
    lt.stop()


def test_viz_table_and_show(capsys):
    t = T(
        """
        a | b
        1 | x
        """
    )
    df = pw.viz.table_viz(t)
    assert list(df.columns) == ["a", "b"]
    pw.internals.parse_graph.G.clear()
    t2 = T(
        """
        a
        7
        """
    )
    pw.viz.show(t2)
    out = capsys.readouterr().out
    assert "7" in out


def test_telemetry_span_timings():
    from pathway_tpu.internals.telemetry import get_telemetry

    tel = get_telemetry()
    with tel.span("test.block"):
        pass
    assert "test.block" in tel.timings


def test_lsh_bucketers_and_flatten():
    """LSH bucketers are deterministic and locality-sensitive; lsh()
    expands rows into (band, bucket) candidates (reference:
    classifiers/_lsh.py)."""
    import numpy as np

    from pathway_tpu.stdlib.ml.classifiers import (
        generate_cosine_lsh_bucketer,
        generate_euclidean_lsh_bucketer,
        lsh,
    )

    buck = generate_euclidean_lsh_bucketer(d=8, M=4, L=5, A=2.0)
    x = np.ones(8)
    assert (buck(x) == buck(x.copy())).all()  # deterministic
    assert len(buck(x)) == 5  # one bucket per band
    # near points collide in at least one band far more often than far ones
    near = buck(x + 0.01)
    far = buck(x + 100.0)
    assert (buck(x) == near).sum() >= (buck(x) == far).sum()

    cos = generate_cosine_lsh_bucketer(d=8, M=6, L=3)
    assert (cos(x) == cos(2 * x)).all()  # scale-invariant

    class V(pw.Schema):
        data: pw.internals.dtype.ANY  # type: ignore[valid-type]

    import pathway_tpu as _pw

    t = _pw.debug.table_from_rows(
        V, [(np.ones(8),), (np.zeros(8) + 5,)]
    )
    flat = lsh(t, buck, origin_id="oid", include_data=True)
    _k, cols = _pw.debug.table_to_dicts(flat)
    assert len(cols["band"]) == 2 * 5  # rows x bands
    assert set(cols.keys()) == {"oid", "bucketing", "band", "data"}


def test_clustering_via_lsh():
    import numpy as np

    import pathway_tpu as pw2
    from pathway_tpu.stdlib.ml.classifiers import (
        clustering_via_lsh,
        generate_euclidean_lsh_bucketer,
    )

    rng = np.random.default_rng(0)
    a = rng.normal(0, 0.1, size=(10, 4)) + np.array([10, 0, 0, 0])
    b = rng.normal(0, 0.1, size=(10, 4)) + np.array([-10, 0, 0, 0])

    class V(pw2.Schema):
        data: pw2.internals.dtype.ANY  # type: ignore[valid-type]

    t = pw2.debug.table_from_rows(V, [(v,) for v in np.vstack([a, b])])
    buck = generate_euclidean_lsh_bucketer(d=4, M=3, L=4, A=4.0)
    res = clustering_via_lsh(t, buck, k=2)
    _k, cols = pw2.debug.table_to_dicts(res)
    labels = list(cols["label"].values())
    assert len(labels) == 20 and set(labels) <= {0, 1}
    # the two blobs separate: each cluster has 10 members
    assert sorted([labels.count(0), labels.count(1)]) == [10, 10]


def test_knn_lsh_classify_with_separate_labels():
    """Reference pattern: train on vectors only, provide labels separately
    (reference: _knn_lsh.py:306 knn_lsh_classify)."""
    import numpy as np

    from pathway_tpu.stdlib.ml.classifiers import (
        knn_lsh_classify,
        knn_lsh_train,
    )

    class V(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        data: pw.internals.dtype.ANY  # type: ignore[valid-type]

    class L(pw.Schema):
        i: int = pw.column_definition(primary_key=True)
        label: str

    vecs = [np.array([10.0, 0]), np.array([11.0, 0]),
            np.array([-10.0, 0]), np.array([-11.0, 0])]
    data = pw.debug.table_from_rows(V, [(i, v) for i, v in enumerate(vecs)])
    labels = pw.debug.table_from_rows(
        L, [(0, "right"), (1, "right"), (2, "left"), (3, "left")]
    )
    model = knn_lsh_train(data, d=2)
    queries = pw.debug.table_from_rows(
        V, [(100, np.array([9.0, 0])), (101, np.array([-9.0, 0]))]
    )
    res = knn_lsh_classify(model, labels, queries, k=2)
    _k, cols = pw.debug.table_to_dicts(res)
    assert sorted(cols["predicted_label"].values()) == ["left", "right"]


def test_groupby_reduce_majority_is_a_real_majority():
    from pathway_tpu.stdlib.utils.col import groupby_reduce_majority

    class S(pw.Schema):
        g: int
        v: str

    rows = [(1, "a"), (1, "a"), (1, "b"), (2, "x")]
    t = pw.debug.table_from_rows(S, rows)
    res = groupby_reduce_majority(t.g, t.v)
    _k, cols = pw.debug.table_to_dicts(res)
    got = dict(zip(cols["g"].values(), cols["majority"].values()))
    assert got == {1: "a", 2: "x"}
