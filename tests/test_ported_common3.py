"""Port of further reference core tests (reference:
python/pathway/tests/test_common.py — select/expression, this-magic,
slices, sequence get, joins incl. id assignment and chains, ix,
update_cells/rows, rename, set ops, groupby indexing, apply, iterate).
Mechanical port: package and imports adapted, fixtures kept identical."""

from __future__ import annotations

import operator
import re
from typing import Any, Optional

import numpy as np
import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.debug import table_from_pandas, table_to_pandas
from pathway_tpu.internals import dtype as dt
import contextlib


@contextlib.contextmanager
def warns_here(match=None):
    """reference tests.utils.warns_here: pytest.warns scoped shim"""
    with pytest.warns(Warning, match=match) as rec:
        yield rec


def empty_from_schema(schema):
    """reference: pathway.internals.table_io.empty_from_schema"""
    return pw.Table.empty(**schema.typehints())


from tests.ref_utils import (
    assert_stream_equality,
    assert_table_equality,
    assert_table_equality_wo_index,
    assert_table_equality_wo_index_types,
    assert_table_equality_wo_types,
    run_all,
)

def _create_tuple(n: int) -> tuple[int, ...]:
    return tuple(range(n, 0, -1))


def test_input_operator():
    input = T(
        """
        foo
        1
        2
        """
    )

    assert_table_equality(
        input,
        T(
            """
            foo
            1
            2
            """
        ),
    )


def test_select_column_ref():
    t_latin = T(
        """
            | lower | upper
        1   | a     | A
        2   | b     | B
        26  | z     | Z
        """
    )
    t_num = T(
        """
            | num
        1   | 1
        2   | 2
        26  | 26
        """
    )

    res = t_latin.select(num=t_num.num, upper=t_latin["upper"])

    assert_table_equality(
        res,
        T(
            """
                | num | upper
            1   | 1   | A
            2   | 2   | B
            26  | 26  | Z
            """
        ),
    )


def test_select_arithmetic_with_const():
    table = T(
        """
        a
        42
        """
    )

    res = table.select(
        table.a,
        add=table.a + 1,
        radd=1 + table.a,
        sub=table.a - 1,
        rsub=1 - table.a,
        mul=table.a * 2,
        rmul=2 * table.a,
        truediv=table.a / 4,
        rtruediv=63 / table.a,
        floordiv=table.a // 4,
        rfloordiv=63 // table.a,
        mod=table.a % 4,
        rmod=63 % table.a,
        pow=table.a**2,
        rpow=2**table.a,
    )

    assert_table_equality(
        res,
        T(
            """
            a  | add | radd | sub | rsub | mul | rmul | truediv | rtruediv | floordiv | rfloordiv | mod | rmod | pow  | rpow
            42 | 43  | 43   | 41  | -41  | 84  | 84   | 10.5    | 1.5      | 10       | 1         | 2   | 21   | 1764 | 4398046511104
            """  # noqa: E501
        ),
    )


def test_select_values():
    t1 = T(
        """
    lower | upper
    a     | A
    b     | B
    """
    )

    res = t1.select(foo="alpha", bar="beta")
    assert_table_equality(
        res,
        T(
            """
    foo   | bar
    alpha | beta
    alpha | beta
        """
        ),
    )


def test_select_column_different_universe():
    foo = T(
        """
       | col
    1  | a
    2  | b
    """
    )
    bar = T(
        """
           | col
        3  | a
        4  | b
        5  | c
        """
    )
    with pytest.raises(ValueError):
        foo.select(ret=bar.col)


def test_select_const_expression():
    input = T(
        """
        foo | bar
        1   | 3
        2   | 4
        """
    )

    result = input.select(a=42)

    assert_table_equality(
        result,
        T(
            """
        a
        42
        42
        """
        ),
    )


def test_select_simple_expression():
    input = T(
        """
        foo | bar
        1   | 3
        2   | 4
        """
    )

    result = input.select(a=input.bar + input.foo)

    assert_table_equality(
        result,
        T(
            """
            a
            4
            6
            """
        ),
    )


def test_select_float_comparison():
    input = T(
        """
        a   | b
        1.5 | 2.5
        2.5 | 2.5
        3.5 | 2.5
        """
    )

    result = input.select(
        input.a,
        input.b,
        eq=input.a == input.b,
        ne=input.a != input.b,
        lt=input.a < input.b,
        le=input.a <= input.b,
        gt=input.a > input.b,
        ge=input.a >= input.b,
    )

    assert_table_equality(
        result,
        T(
            """
            a   | b   | eq    | ne    | lt    | le    | gt    | ge
            1.5 | 2.5 | false | true  | true  | true  | false | false
            2.5 | 2.5 | true  | false | false | true  | false | true
            3.5 | 2.5 | false | true  | false | false | true  | true
            """
        ),
    )


def test_select_mixed_comparison():
    input = T(
        """
        a   | b
        1.5 | 2
        2.0 | 2
        3.5 | 2
        """
    )
    result = input.select(
        input.a,
        input.b,
        eq=input.a == input.b,
        ne=input.a != input.b,
        lt=input.a < input.b,
        le=input.a <= input.b,
        gt=input.a > input.b,
        ge=input.a >= input.b,
    )

    assert_table_equality(
        result,
        T(
            """
            a   | b | eq    | ne    | lt    | le    | gt    | ge
            1.5 | 2 | false | true  | true  | true  | false | false
            2.0 | 2 | true  | false | false | true  | false | true
            3.5 | 2 | false | true  | false | false | true  | true
            """
        ),
    )


def test_select_float_unary():
    input = T(
        """
        a
        1.25
        """
    )

    result = input.select(
        input.a,
        minus=-input.a,
    )

    assert_table_equality(
        result,
        T(
            """
            a    | minus
            1.25 | -1.25
            """
        ),
    )


def test_select_float_binary():
    input = T(
        """
        a    | b
        1.25 | 2.5
        """
    )

    result = input.select(
        input.a,
        input.b,
        add=input.a + input.b,
        sub=input.a - input.b,
        truediv=input.a / input.b,
        floordiv=input.a // input.b,
        mul=input.a * input.b,
    )

    assert_table_equality(
        result,
        T(
            """
            a    | b   | add  | sub   | truediv | floordiv | mul
            1.25 | 2.5 | 3.75 | -1.25 | 0.5     | 0.0        | 3.125
            """
        ).update_types(floordiv=float),
    )


def test_select_bool_unary():
    input = T(
        """
        a
        true
        false
        """
    )

    result = input.select(
        input.a,
        not_=~input.a,
    )

    assert_table_equality(
        result,
        T(
            """
            a     | not_
            true  | false
            false | true
            """
        ),
    )


def test_indexing_single_value_groupby_hardcoded_value():
    indexed_table = T(
        """
    colA   | colB
    10     | A
    20     | A
    30     | B
    40     | B
    """
    )
    grouped_table = indexed_table.groupby(pw.this.colB).reduce(
        pw.this.colB, sum=pw.reducers.sum(pw.this.colA)
    )
    returned = indexed_table + grouped_table.ix_ref("A", context=indexed_table)[["sum"]]
    returned2 = indexed_table.select(*pw.this, sum=grouped_table.ix_ref("A").sum)
    expected = T(
        """
    colA   | colB | sum
    10     | A    | 30
    20     | A    | 30
    30     | B    | 30
    40     | B    | 30
    """
    )
    assert_table_equality_wo_index(returned, expected)
    assert_table_equality(returned, returned2)


def test_indexing_two_values_groupby():
    indexed_table = T(
        """
    colA  | colB | colC
    1     | A    | D
    2     | A    | D
    10    | A    | E
    20    | A    | E
    100   | B    | F
    200   | B    | F
    1000  | B    | G
    2000  | B    | G
    """
    )
    grouped_table = indexed_table.groupby(pw.this.colB, pw.this.colC).reduce(
        pw.this.colB, pw.this.colC, sum=pw.reducers.sum(pw.this.colA)
    )
    returned = (
        indexed_table
        + grouped_table.ix_ref(indexed_table.colB, indexed_table.colC)[["sum"]]
    )
    expected = T(
        """
    colA  | colB | colC | sum
    1     | A    | D    | 3
    2     | A    | D    | 3
    10    | A    | E    | 30
    20    | A    | E    | 30
    100   | B    | F    | 300
    200   | B    | F    | 300
    1000  | B    | G    | 3000
    2000  | B    | G    | 3000
    """
    )
    assert_table_equality_wo_index(returned, expected)


def test_indexing_two_values_groupby_hardcoded_values():
    indexed_table = T(
        """
    colA   | colB
    10     | A
    20     | B
    """
    )
    indexed_table = indexed_table.groupby(pw.this.colA, pw.this.colB).reduce(*pw.this)
    tested_table = T(
        """
    colC
    10
    20
    """
    )
    returned = tested_table.select(
        *pw.this,
        new_value=indexed_table.ix_ref(10, "A").colA,
    )
    expected = T(
        """
    colC   | new_value
    10     | 10
    20     | 10
    """
    )
    assert_table_equality(returned, expected)


def test_select_in_multiple_independent_tables():
    t = T(
        """
         a  |  c  | b
        1.1 | 1.2 | 1
        2.0 | 2.3 | 2
        3.0 | 3.4 | 0
        4.0 | 4.5 | 3
        """
    )

    u = t.select(a=pw.this.a + pw.this.c, x=10)
    v = u.select(a=pw.this.a, x=20)
    t = t.select(pw.this.c, pw.this.b)
    t += v
    t += t.select(z=pw.this.a + pw.this.x, u=u.x)
    t = t.without(pw.this.b)

    expected = T(
        """
         c  |  a  |  x |   z  |  u
        1.2 | 2.3 | 20 | 22.3 | 10
        2.3 | 4.3 | 20 | 24.3 | 10
        3.4 | 6.4 | 20 | 26.4 | 10
        4.5 | 8.5 | 20 | 28.5 | 10
        """
    )

    assert_table_equality(t, expected)


def test_concat_unsafe_collision():
    t1 = T(
        """
       | lower | upper
    1  | a     | A
    2  | b     | B
    """
    )
    t2 = T(
        """
       | lower | upper
    1  | c     | C
    """
    )

    with pytest.raises(ValueError):
        pw.Table.concat(t1, t2)


def test_rename_columns_2():
    old = T(
        """
    pet | age
     1  | 10
     1  | 9
    """
    )
    expected = T(
        """
    age | pet
     1  | 10
     1  | 9
    """
    )
    new = old.rename_columns(age="pet", pet="age")
    assert_table_equality(new, expected)


def test_rename_with_kwargs():
    old = T(
        """
    pet  |  owner  | age
     1   | Alice   | 10
     1   | Bob     | 9
    """
    )

    new = old.rename(animal=old.pet, winters=old.age)
    expected = old.rename_columns(animal=old.pet, winters=old.age)
    assert_table_equality(new, expected)


def test_rename_columns_unknown_column_name():
    old = T(
        """
    pet |  owner  | age
     1  | Alice   | 10
     1  | Bob     | 9
    """
    )
    with pytest.raises(Exception):
        old.rename_columns(pet="animal", habitat="location")


def test_filter_different_universe():
    t_latin = T(
        """
            | lower | upper
        1  | a     | A
        2  | b     | B
        26 | z     | Z
        """
    )
    t_wrong = T(
        """
            | bool
        1   | True
        7   | False
        """
    )

    with pytest.raises(ValueError):
        t_latin.filter(t_wrong.bool)


def test_reindex_no_columns():
    t1 = T(
        """
            |
        1   |
        2   |
        3   |
        """
    ).select()
    t2 = T(
        """
            | new_id
        1   | 2
        2   | 3
        3   | 4
        """
    ).select(new_id=t1.pointer_from(pw.this.new_id))
    pw.universes.promise_is_subset_of(t1, t2)
    t2_restricted = t2.restrict(t1)

    assert_table_equality(
        t1.with_id(t2_restricted.new_id),
        T(
            """
                |
            2   |
            3   |
            4   |
            """
        ).select(),
    )


def test_rows_fixpoint():
    def min_id_remove(iterated: pw.Table):
        min_id_table = iterated.reduce(min_id=pw.reducers.min(iterated.id))
        return iterated.filter(iterated.id != min_id_table.ix_ref().min_id)

    ret = pw.iterate(
        min_id_remove,
        iterated=pw.iterate_universe(
            T(
                """
                | foo
            1   | 1
            2   | 2
            3   | 3
            4   | 4
            5   | 5
            """
            )
        ),
    )

    expected_ret = T(
        """
            | foo
        """
    ).update_types(foo=int)

    assert_table_equality_wo_index(ret, expected_ret)


def test_iteration_column_order():
    def iteration_step(iterated):
        iterated = iterated.select(bar=iterated.bar, foo=iterated.foo - iterated.foo)
        return iterated

    ret = pw.iterate(
        iteration_step,
        iterated=T(
            """
                | foo   | bar
            1   | 1     | None
            2   | 2     | None
            3   | 3     | None
            """
        ),
    )

    expected_ret = T(
        """
            | foo   | bar
        1   | 0     | None
        2   | 0     | None
        3   | 0     | None
        """
    )

    assert_table_equality_wo_index(ret, expected_ret)


@pytest.mark.parametrize("limit", [-1, 0])
def test_iterate_with_wrong_limit(limit):
    def iteration_step(iterated):
        iterated = iterated.select(foo=iterated.foo + 1)
        return iterated

    with pytest.raises(ValueError):
        pw.iterate(
            iteration_step,
            iteration_limit=limit,
            iterated=T(
                """
                    | foo
                1   | 0
                """
            ),
        )


def test_apply():
    a = T(
        """
        foo
        1
        2
        3
        """
    )

    def inc(x: int) -> int:
        return x + 1

    result = a.select(ret=pw.apply(inc, a.foo))

    assert_table_equality(
        result,
        T(
            """
            ret
            2
            3
            4
            """
        ),
    )


def test_apply_incompatible_keys():
    a = T(
        """
            | foo
        1   | 1
        2   | 2
        3   | 3
        """
    )
    b = T(
        """
            | bar
        1   | 2
        """
    )

    def add(x: float, y: float) -> float:
        return x + y

    with pytest.raises(ValueError):
        a.select(ret=pw.apply(add, x=a.foo, y=b.bar))


def test_apply_wrong_number_of_args():
    a = T(
        """
        foo
        1
        2
        """
    )

    def add(x: float, y: float) -> float:
        return x + y

    with pytest.raises(AssertionError):
        a.select(ret=pw.apply(add))


def test_empty_join():
    left = T(
        """
                col | on
            1 | a   | 11
            2 | b   | 12
            3 | c   | 13
        """
    )
    right = T(
        """
                col | on
            1 | d   | 12
            2 | e   | 13
            3 | f   | 14
        """,
    )
    joined = left.join(right, left.on == right.on).select()
    assert_table_equality_wo_index(
        joined,
        T(
            """
                |
            2   |
            3   |
            """
        ).select(),
    )


def test_join_left_assign_id():
    left = T(
        """
                col | on
            1 | a   | 11
            2 | b   | 12
            3 | c   | 13
            4 | d   | 13
        """
    )
    right = T(
        """
                col | on
            1 | d   | 12
            2 | e   | 13
            3 | f   | 14
        """,
    )
    joined = left.join(right, left.on == right.on, id=left.id).select(
        lcol=left.col, rcol=right.col
    )

    assert_table_equality(
        joined,
        T(
            """
        | lcol | rcol
        2 |  b |    d
        3 |  c |    e
        4 |  d |    e
    """
        ),
    )

    with pytest.raises(AssertionError):
        left.join(right, left.on == right.on, id=left.on)

    left.join(right, left.on == right.on, id=right.id).select(
        lcol=left.col, rcol=right.col
    )
    with pytest.raises(KeyError):
        run_all()


def test_join_right_assign_id():
    left = T(
        """
                col | on
            1 | a   | 11
            2 | b   | 12
            3 | c   | 13
        """
    )
    right = T(
        """
                col | on
            0 | c   | 12
            1 | d   | 12
            2 | e   | 13
            3 | f   | 14
        """,
    )
    joined = left.join(right, left.on == right.on, id=right.id).select(
        lcol=left.col, rcol=right.col
    )
    assert_table_equality(
        joined,
        T(
            """
          | lcol | rcol
        0 |    b |    c
        1 |    b |    d
        2 |    c |    e
    """
        ),
    )

    with pytest.raises(AssertionError):
        left.join(right, left.on == right.on, id=right.on)

    left.join(right, left.on == right.on, id=left.id).select(
        lcol=left.col, rcol=right.col
    )
    with pytest.raises(KeyError):
        run_all()


def test_join():
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |   9 |    L
        13  |   1 |   Tom |   8 |   XL
        """
    )
    expected = T(
        """
            owner_name | L | R  | age
            Bob        | 2 | 12 |   9
            """,
    ).with_columns(
        L=t1.pointer_from(pw.this.L),
        R=t2.pointer_from(pw.this.R),
    )
    res = t1.join(t2, t1.pet == t2.pet, t1.owner == t2.owner).select(
        owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age
    )
    assert_table_equality_wo_index(
        res,
        expected,
    )


def test_join_instance():
    t1 = T(
        """
            | owner | age | instance
        1   | Alice |  10 | 1
        2   |   Bob |   9 | 1
        3   |   Tom |   8 | 1
        4   | Alice |  10 | 2
        5   |   Bob |   9 | 2
        6   |   Tom |   8 | 2
        """
    )
    t2 = T(
        """
            | owner | age | size | instance
        11  | Alice |  10 |    M | 1
        12  |   Bob |   9 |    L | 1
        13  |   Tom |   8 |   XL | 1
        14  | Alice |  10 |    M | 2
        15  |   Bob |   9 |    L | 2
        16  |   Tom |   8 |   XL | 2
        """
    )
    expected = T(
        """
            owner_name | L | R  | age
            Alice      | 1 | 11 |  10
            Bob        | 2 | 12 |   9
            Tom        | 3 | 13 |   8
            Alice      | 4 | 14 |  10
            Bob        | 5 | 15 |   9
            Tom        | 6 | 16 |   8
            """,
    ).with_columns(
        L=t1.pointer_from(pw.this.L),
        R=t2.pointer_from(pw.this.R),
    )
    res = t1.join(
        t2, t1.owner == t2.owner, left_instance=t1.instance, right_instance=t2.instance
    ).select(owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age)
    assert_table_equality_wo_index(
        res,
        expected,
    )


def test_join_swapped_condition():
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        1   |   3 | Alice |  10 |    M
        2   |   1 |   Bob |   9 |    L
        3   |   1 |   Tom |   8 |   XL
        """
    )
    with pytest.raises(ValueError):
        t1.join(t2, t2.pet == t1.pet).select(
            owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age
        )


def test_join_default():
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |   9 |    L
        13  |   1 |   Tom |   8 |   XL
        """
    )
    res = t1.join(t2, t1.pet == t2.pet).select(
        owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age
    )
    expected = T(
        """
            owner_name  | L | R  | age
            Bob         | 1 | 12 | 10
            Tom         | 1 | 13 | 10
            Bob         | 2 | 12 |  9
            Tom         | 2 | 13 |  9
        """,
    ).with_columns(
        L=t1.pointer_from(pw.this.L),
        R=t2.pointer_from(pw.this.R),
    )

    assert_table_equality_wo_index(res, expected)


def test_join_self():
    input = T(
        """
        foo   | bar
        1     | 1
        1     | 2
        1     | 3
        """
    )
    with pytest.raises(Exception):
        input.join(input, input.foo == input.bar)


def test_join_select_no_columns():
    left = T(
        """
           | a
        1  | 1
        2  | 2
        """
    )
    right = T(
        """
           | b
        1  | foo
        2  | bar
        """
    )

    ret = left.join(right, left.id == right.id).select().select(col=42)
    assert_table_equality_wo_index(
        ret,
        T(
            """
                | col
            1   | 42
            2   | 42
            """
        ),
    )


def test_cross_join():
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |  9  |    L
        13  |   1 |   Tom |  8  |   XL
        """
    )
    res = t1.join(t2).select(owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age)
    expected = T(
        """
            owner_name  | L | R | age
            Alice       | 1 | 11 |  10
            Bob         | 1 | 12 |  10
            Tom         | 1 | 13 |  10
            Alice       | 2 | 11 |   9
            Bob         | 2 | 12 |   9
            Tom         | 2 | 13 |   9
            Alice       | 3 | 11 |   8
            Bob         | 3 | 12 |   8
            Tom         | 3 | 13 |   8
        """,
    ).with_columns(
        L=t1.pointer_from(pw.this.L),
        R=t2.pointer_from(pw.this.R),
    )
    assert_table_equality_wo_index(res, expected)


def test_empty_join_2():
    t1 = T(
        """
        v1
        1
        2
        """,
    )
    t2 = T(
        """
        v2
        10
        20
        """,
    )
    t = t1.join(t2).select(t1.v1, t2.v2)
    expected_t = T(
        """
        v1  | v2
        1   | 10
        1   | 20
        2   | 10
        2   | 20
        """,
    )
    assert_table_equality_wo_index(t, expected_t)


@pytest.mark.xfail(reason="References from universe superset are not allowed.")
def test_groupby_universes():
    left = T(
        """
      | pet  |  owner
    1 | dog  | Alice
    2 | dog  | Bob
    3 | cat  | Alice
    4 | dog  | Bob
    """
    )

    left_prim = T(
        """
      | age
    1 | 10
    2 | 9
    3 | 8
    4 | 7
    5 | 6
    """
    )

    left_bis = T(
        """
      | age
    1 | 10
    2 | 9
    3 | 8
    """
    )
    pw.universes.promise_is_subset_of(left, left_prim)

    left_res = left.groupby(left.pet).reduce(
        left.pet, ageagg=pw.reducers.sum(left_prim.age)
    )

    assert_table_equality_wo_index(
        left_res,
        T(
            """
    pet  | ageagg
    dog  | 26
    cat  | 8
    """
        ),
    )

    with pytest.raises(AssertionError):
        left.groupby(left.pet).reduce(ageagg=pw.reducers.sum(left_bis.age))


def test_intersect_no_columns():
    t1 = T(
        """
            |
        1   |
        2   |
        3   |
        """
    ).select()
    t2 = T(
        """
            |
        2   |
        3   |
        4   |
        """
    ).select()

    assert_table_equality(
        t1.intersect(t2),
        T(
            """
                |
            2   |
            3   |
            """
        ).select(),
    )


def test_intersect_subset():
    t1 = T(
        """
            | col
        1   | 11
        2   | 12
        3   | 13
        """
    )
    t2 = T(
        """
            | col
        2   | 11
        3   | 11
        """
    )
    pw.universes.promise_is_subset_of(t2, t1)

    res = t1.intersect(t2)

    assert_table_equality(
        res,
        T(
            """
                | col
            2   | 12
            3   | 13
            """
        ),
    )
    assert res._universe != t2._universe


def test_update_cells_0_rows():
    old = T(
        """
            | pet  |  owner  | age
        """
    )
    update = T(
        """
            | owner  | age
        """
    )
    expected = T(
        """
            | pet  |  owner  | age
        """
    )

    match = re.escape(
        "Key sets of self and other in update_cells are the same. "
        "Using with_columns instead of update_cells."
    )

    with warns_here(match=match):
        new = old.update_cells(update)
    with warns_here(match=match):
        new2 = old << update
    assert_table_equality(new, expected)
    assert_table_equality(new2, expected)


def test_update_cells_ids_dont_match():
    old = T(
        """
            | pet  |  owner  | age
        1   |  1   | Alice   | 10
        2   |  1   | Bob     | 9
        3   |  2   | Alice   | 8
        4   |  1   | Bob     | 7
        """
    )
    update = T(
        """
            | pet  |  owner  | age
        5   |  0   | Eve     | 10
        """
    )
    with pytest.raises(Exception):
        old.update_cells(update)


def test_update_rows_no_columns():
    old = T(
        """
            |
        1   |
        2   |
        3   |
        4   |
        """
    ).select()
    update = T(
        """
            |
        1   |
        5   |
        """
    ).select()
    expected = T(
        """
            |
        1   |
        2   |
        3   |
        4   |
        5   |
        """
    ).select()
    new = old.update_rows(update)
    assert_table_equality(new, expected)


def test_update_rows_0_rows():
    old = T(
        """
            | pet  |  owner  | age
        """
    )
    update = T(
        """
            | pet |  owner  | age
        """
    )

    expected = T(
        """
            | pet  |  owner  | age
        """
    )
    with warns_here(
        match=re.escape(
            "Universe of self is a subset of universe of other in update_rows. "
            "Returning other."
        ),
    ):
        new = old.update_rows(update)
    assert_table_equality(new, expected)


def test_update_rows_columns_dont_match():
    old = T(
        """
            | pet  |  owner  | age
        1   |  1   | Alice   | 10
        2   |  1   | Bob     | 9
        3   |  2   | Alice   | 8
        4   |  1   | Bob     | 7
        """
    )
    update = T(
        """
            | pet  |  owner  | age | weight
        5   |  0   | Eve     | 10  | 42
        """
    )
    with pytest.raises(Exception):
        old.update_rows(update)


def test_update_rows_subset():
    old = T(
        """
            | pet  |  owner  | age
        1   |  1   | Alice   | 10
        2   |  1   | Bob     | 9
        3   |  2   | Alice   | 8
        4   |  1   | Bob     | 7
        """
    )
    update = T(
        """
            | pet |  owner  | age
        1   | 7   | Bob     | 11
        """
    )
    pw.universes.promise_is_subset_of(update, old)
    expected = T(
        """
            | pet  |  owner  | age
        1   |  7   | Bob     | 11
        2   |  1   | Bob     | 9
        3   |  2   | Alice   | 8
        4   |  1   | Bob     | 7
        """
    )

    new = old.update_rows(update)
    assert_table_equality(new, expected)
    assert new._universe == old._universe


def test_with_columns_0_rows():
    old = T(
        """
            | pet | owner | age
        """
    )
    update = T(
        """
            | owner | age | weight
        """
    )
    expected = T(
        """
            | pet | owner | age | weight
        """
    )

    assert_table_equality(old.with_columns(**update), expected)


def test_with_columns_ids_dont_match():
    old = T(
        """
            | pet  |  owner  | age
        1   |  1   | Alice   | 10
        2   |  1   | Bob     | 9
        """
    )
    update = T(
        """
            | pet  |  owner  | age
        5   |  0   | Eve     | 10
        """
    )
    with pytest.raises(Exception):
        old.with_columns(update)


@pytest.mark.xfail(
    reason="Foreign columns are not supported in reduce because their universe is different."
)
def test_groupby_foreign_column():
    tab = T(
        """
        grouper | col
              0 |   1
              0 |   2
              1 |   3
              1 |   4
              2 |   5
              2 |   6
        """,
    ).with_columns(grouper=pw.this.pointer_from(pw.this.grouper))
    tab2 = tab.select(tab.col)
    grouped = tab.groupby(id=tab.grouper)
    reduced1 = grouped.reduce(
        col=pw.reducers.sum(tab.col),
    )
    reduced2 = grouped.reduce(col=reduced1.col + pw.reducers.sum(tab2.col))
    assert_table_equality_wo_index(
        reduced2,
        T(
            """
            col
            6
            14
            22
            """,
        ),
    )


def test_join_ix():
    left = T(
        """
           | a
        1  | 3
        2  | 2
        3  | 1
        """
    ).with_columns(a=pw.this.pointer_from(pw.this.a))
    right = T(
        """
           | b
        0  | baz
        1  | foo
        2  | bar
        """
    )

    ret = left.join(right, left.a == right.id, id=left.id).select(
        col=right.ix(left.a, context=pw.this).b
    )

    ret3 = (
        right.ix(left.a, allow_misses=True)
        .select(col=pw.this.b)
        .filter(pw.this.col.is_not_none())
    )

    # below is the desugared version of above computation
    # it works, and it's magic
    keys_table = left.join(right, left.a == right.id, id=left.id).select(
        join_column=left.a
    )
    desugared_ix = keys_table.join(
        right,
        keys_table.join_column == right.id,
        id=keys_table.id,
    ).select(right.b)
    tmp = left.join(
        right, left.a == right.id, id=left.id
    ).promise_universe_is_subset_of(desugared_ix)
    ret2 = tmp.select(col=desugared_ix.restrict(tmp).b)
    assert_table_equality(
        ret,
        T(
            """
                | col
            3   | foo
            2   | bar
            """
        ),
    )
    assert_table_equality(ret2, ret)
    assert_table_equality(ret3, ret)


def test_this_magic_1():
    tab = T(
        """
           | a | b | c | d
        1  | 1 | 2 | 3 | 4
        """
    )

    left = tab.select(pw.this.without("a").b)

    right = tab.select(tab.b)

    assert_table_equality(left, right)


def test_this_magic_2():
    tab = T(
        """
           | a | b | c | d
        1  | 1 | 2 | 3 | 4
        """
    )

    with pytest.raises(KeyError):
        tab.select(pw.this.without(pw.this.a).a)


def test_this_magic_3():
    tab = T(
        """
           | a | b | c | d
        1  | 1 | 2 | 3 | 4
        """
    )

    left = tab.select(*pw.this.without(pw.this.a))

    right = tab.select(tab.b, tab.c, tab.d)

    assert_table_equality(left, right)


def test_this_magic_4():
    tab = T(
        """
           | a | b | c | d
        1  | 1 | 2 | 3 | 4
        """
    )

    left = tab.select(*pw.this[["a", "b", pw.this.c]].without(pw.this.a))

    right = tab.select(tab.b, tab.c)

    assert_table_equality(left, right)


def test_join_this():
    t1 = T(
        """
     age  | owner  | pet
      10  | Alice  | 1
       9  | Bob    | 1
       8  | Alice  | 2
     """
    )
    t2 = T(
        """
     age  | owner  | pet | size
      10  | Alice  | 3   | M
      9   | Bob    | 1   | L
      8   | Tom    | 1   | XL
     """
    )
    t3 = t1.join(
        t2, pw.left.pet == pw.right.pet, pw.left.owner == pw.right.owner
    ).select(age=pw.left.age, owner_name=pw.right.owner, size=pw.this.size)

    expected = T(
        """
    age | owner_name | size
    9   | Bob        | L
    """
    )
    assert_table_equality_wo_index(t3, expected)


def test_chained_join_leftrightthis():
    left_table = T(
        """
           | a | b
        1  | 1 | 2
        """
    )

    middle_table = T(
        """
           | b | c
        1  | 2 | 3
        """
    )

    right_table = T(
        """
           | b | d
        1  | 2 | 4
        """
    )

    assert_table_equality_wo_index(
        left_table.join(middle_table, pw.left.b == pw.right.b)
        .join(right_table, pw.left.b == pw.right.b)
        .select(*pw.this),
        T(
            """
        a | b | c | d
        1 | 2 | 3 | 4
        """
        ),
    )


def test_chained_join_ids():
    left_table = T(
        """
           | a | b
        1  | 1 | 2
        """
    )

    middle_table = T(
        """
           | b | c
        1  | 2 | 3
        """
    )

    right_table = T(
        """
           | b | d
        1  | 2 | 4
        """
    )

    manually = (
        left_table.join(middle_table, pw.left.b == pw.right.b)
        .select(pw.left.b)
        .with_columns(left_id=pw.this.id)
        .join(right_table, pw.left.b == pw.right.b)
        .select(pw.left.left_id, right_id=pw.right.id)
        .with_columns(this_id=pw.this.id)
    )

    assert_table_equality(
        left_table.join(middle_table, pw.left.b == pw.right.b)
        .join(right_table, pw.left.b == pw.right.b)
        .select(left_id=pw.left.id, right_id=pw.right.id, this_id=pw.this.id),
        manually,
    )


def test_multiple_ix():
    indexed_table = T(
        """
           | col
        2  | a
        3  | b
        4  | c
        5  | d
        """
    )

    indexer1 = T(
        """
          | key
        1 | 4
        2 | 3
        3 | 2
        4 | 1
    """
    ).with_columns(key=indexed_table.pointer_from(pw.this.key))

    indexer2 = T(
        """
          | key
        1 | 6
        2 | 5
        3 | 4
        4 | 3
    """
    ).with_columns(key=indexed_table.pointer_from(pw.this.key))

    a = (
        indexed_table.ix(indexer1.key, allow_misses=True)
        .filter(pw.this.col.is_not_none())
        .select(col1=pw.this.col)
    )
    b = (
        indexed_table.ix(indexer2.key, allow_misses=True)
        .filter(pw.this.col.is_not_none())
        .select(col2=pw.this.col)
    )
    result = a.intersect(b)
    result = a.restrict(result) + b.restrict(result)
    assert_table_equality_wo_index(
        result,
        T(
            """
        col1 | col2
           a |    c
           b |    d
        """
        ),
    )


def test_join_desugaring_assign_id():
    left = T(
        """
              | col | on
            1 | a   | 11
            2 | b   | 12
            3 | c   | 13
        """
    )
    right = T(
        """
              | col | on
            1 | d   | 12
            2 | e   | 13
            3 | f   | 14
        """,
    )
    joined_lr = left.join(right, left.on == right.on, id=left.id).select(
        lcol=pw.left.col, rcol=pw.right.col
    )
    assert_table_equality_wo_index(
        joined_lr,
        T(
            """
          | lcol | rcol
        1 |    b |    d
        2 |    c |    e
    """
        ),
    )

    joined_rl = right.join(left, right.on == left.on, id=left.id).select(
        lcol=pw.right.col, rcol=pw.left.col
    )
    assert_table_equality_wo_index(joined_lr, joined_rl)


def test_join_chain_assign_id():
    left_table = T(
        """
           | a  | b
        1  | a1 | b1
        2  | a2 | b2
        3  | a3 | b3
        4  | a4 | b4
        """
    )

    middle_table = T(
        """
            | b  | c
        11  | b2 | c2
        12  | b3 | c3
        13  | b4 | c4
        14  | b5 | c5
        """
    )

    right_table = T(
        """
           | c  | d
        21 | c3 | d3
        22 | c4 | d4
        23 | c5 | d5
        24 | c6 | d6
        """
    )

    assert_table_equality(
        left_table.join(middle_table, pw.left.b == pw.right.b, id=pw.left.id)
        .join(right_table, pw.left.c == pw.right.c, id=pw.left.id)
        .select(*pw.this),
        T(
            """
          | a  | b  | c  | d
        3 | a3 | b3 | c3 | d3
        4 | a4 | b4 | c4 | d4
        """
        ),
    )


@pytest.mark.parametrize(
    "from_,to_",
    [
        (
            [10, 0, -1, -2, 2**32 + 1, 2**45 + 1],
            [10.0, 0, -1.0, -2, float(2**32 + 1), float(2**45 + 1)],
        ),
        (
            [10, 0, -1, -2, 2**32 + 1, 2**45 + 1],
            [True, False, True, True, True, True],
        ),
        (
            [10, 0, -1, -2, 2**32 + 1, 2**45 + 1],
            ["10", "0", "-1", "-2", "4294967297", "35184372088833"],
        ),
        (
            [
                10.345,
                10.999,
                -1.012,
                -1.99,
                -2.01,
                float(2**32 + 1),
                float(2**45 + 1),
                float(2**60 + 1),
            ],
            [10, 10, -1, -1, -2, 2**32 + 1, 2**45 + 1, 2**60],
        ),
        ([10.345, 10.999, -1.012, -1.99, 0.0], [True, True, True, True, False]),
        (
            [
                10.345,
                10.999,
                -1.012,
                -1.99,
                -2.01,
                2**32 + 0.2,
                2**45 + 0.1,
            ],
            [
                "10.345",
                "10.999",
                "-1.012",
                "-1.99",
                "-2.01",
                "4294967296.2",
                "35184372088832.1",
            ],
        ),
        ([False, True], [0, 1]),
        ([False, True], [0.0, 1.0]),
        ([False, True], ["False", "True"]),
        (
            ["10", "0", "-1", "-2", "4294967297", "35184372088833"],
            [10, 0, -1, -2, 2**32 + 1, 2**45 + 1],
        ),
        (
            [
                "10.345",
                "10.999",
                "-1.012",
                "-1.99",
                "-2.01",
                "4294967297",
                "35184372088833",
            ],
            [
                10.345,
                10.999,
                -1.012,
                -1.99,
                -2.01,
                float(2**32 + 1),
                float(2**45 + 1),
            ],
        ),
        (["", "False", "True", "12", "abc"], [False, True, True, True, True]),
    ],
)
def test_cast(from_: list, to_: list):
    from_dtype = type(from_[0])
    to_dtype = type(to_[0])

    def move_to_pathway_with_the_right_type(list: list, dtype: Any):
        df = pd.DataFrame({"a": list}, dtype=dtype)
        table = table_from_pandas(df)
        return table

    table = move_to_pathway_with_the_right_type(from_, from_dtype)
    expected = move_to_pathway_with_the_right_type(to_, to_dtype)
    table = table.select(a=pw.cast(to_dtype, pw.this.a))
    assert_table_equality(table, expected)


def test_lazy_coalesce():
    tab = T(
        """
    col
    1
    2
    3
    """
    )
    ret = tab.select(col=pw.coalesce(tab.col, tab.col // 0))
    assert_table_equality(ret, tab)


def test_require_01():
    tab = T(
        """
    col1 | col2
    2   | 2
    1   |
    3   | 3
    """
    )

    expected = T(
        """
    sum | dummy
    4   | 1
        | 1
    6   | 1
    """
    ).select(pw.this.sum)

    def f(a, b):
        return a + b

    app_expr = pw.apply(f, tab.col1, tab.col2)
    req_expr = pw.require(app_expr, tab.col2)

    res = tab.select(sum=req_expr)

    assert_table_equality_wo_index_types(res, expected)

    assert req_expr._dependencies() == app_expr._dependencies()


def test_if_else():
    tab = T(
        """
    a | b
    1 | 0
    2 | 2
    3 | 3
    4 | 2
        """
    )

    ret = tab.select(res=pw.if_else(tab.b != 0, tab.a // tab.b, 0))

    assert_table_equality(
        ret,
        T(
            """
        res
        0
        1
        1
        2
        """
        ),
    )


def test_outerjoin_filter_1():
    left = T(
        """
            val
            10
            11
            12
        """
    )
    right = T(
        """
            val
            11
            12
            13
        """,
    )
    joined = (
        left.join_outer(right, left.val == right.val)
        .filter(pw.left.val.is_not_none())
        .filter(pw.right.val.is_not_none())
        .select(left_val=pw.left.val, right_val=pw.right.val)
    )
    assert_table_equality_wo_index(
        joined,
        T(
            """
            left_val | right_val
                  11 |        11
                  12 |        12
            """
        ),
    )


def test_outerjoin_filter_2():
    left = T(
        """
            val
            10
            11
            12
        """
    )
    right = T(
        """
            val
            11
            12
            13
        """,
    )
    joined = (
        left.join_outer(right, left.val == right.val)
        .filter(pw.left.val.is_not_none())
        .filter(pw.right.val.is_not_none())
        .select(val=pw.unwrap(pw.left.val) + pw.unwrap(pw.right.val))
    )
    assert_table_equality_wo_index(
        joined,
        T(
            """
            val
             22
             24
            """
        ),
    )


def test_join_reduce_1():
    left = T(
        """
            a
            10
            11
            12
        """
    )
    right = T(
        """
            b
            11
            12
            13
        """,
    )
    result = left.join(right).reduce(col=pw.reducers.count())
    expected = T(
        """
        col
        9
    """
    )
    assert_table_equality_wo_index(result, expected)


def test_join_reduce_2():
    left = T(
        """
            a
            10
            11
            12
        """
    )
    right = T(
        """
            b
            11
            12
            13
        """,
    )
    result = left.join(right).reduce(col=pw.reducers.sum(pw.left.a * pw.right.b))
    result2 = left.join(right).reduce(col=pw.reducers.sum(pw.this.a * pw.this.b))
    expected = T(
        f"""
        col
        {(10+11+12)*(11+12+13)}
    """
    )
    assert_table_equality_wo_index(result, expected)
    assert_table_equality_wo_index(result2, expected)


def test_make_tuple():
    t = T(
        """
        a | b  | c
        1 | 10 | a
        2 | 20 |
        3 | 30 | c
        """
    )
    result = t.select(zip_column=pw.make_tuple(t.a * 2, pw.this.b, pw.this.c))

    def three_args_tuple(x, y, z) -> tuple:
        return (x, y, z)

    expected = t.select(
        zip_column=pw.apply_with_type(
            three_args_tuple,
            tuple[int, int, Optional[str]],  # type: ignore[arg-type]
            pw.this.a * 2,
            pw.this.b,
            pw.this.c,
        )
    )
    assert_table_equality_wo_index(result, expected)


def test_sequence_get_unchecked_fixed_length():
    t1 = T(
        """
    i | s
    4 | xyz
    3 | abc
    7 | d
    """
    )

    t2 = t1.select(tup=pw.make_tuple(pw.this.i, pw.this.s))
    t3 = t2.select(i=pw.this.tup[0], s=pw.this.tup[1])

    assert_table_equality(t3, t1)


def test_sequence_get_unchecked_fixed_length_dynamic_index_1():
    t1 = T(
        """
    i | s   | a
    4 | xyz | 0
    3 | abc | 1
    7 | d   | 0
    """
    )

    t2 = t1.select(tup=pw.make_tuple(pw.this.i, pw.this.s), a=pw.this.a)
    t3 = t2.select(r=pw.this.tup[pw.this.a])
    assert t3.schema._dtypes() == {"r": dt.ANY}


def test_sequence_get_unchecked_fixed_length_dynamic_index_2():
    t1 = T(
        """
    a | b | c
    4 | 1 | 0
    3 | 2 | 1
    7 | 3 | 1
    """
    )
    expected = T(
        """
    r
    4
    2
    3
    """
    )

    t2 = t1.select(tup=pw.make_tuple(pw.this.a, pw.this.b), c=pw.this.c)
    t3 = t2.select(r=pw.this.tup[pw.this.c])

    assert_table_equality(t3, expected)


def test_sequence_get_checked_fixed_length_dynamic_index():
    t1 = T(
        """
    a | b | c
    4 | 1 | 0
    3 | 2 | 1
    7 | 3 | 1
    """
    )
    expected = T(
        """
    r
    4
    2
    3
    """
    )

    t2 = t1.select(tup=pw.make_tuple(pw.this.a, pw.this.b), c=pw.this.c)
    t3 = t2.select(r=pw.this.tup.get(pw.this.c))

    assert t3.schema._dtypes() == {"r": dt.Optional(dt.INT)}
    assert_table_equality_wo_types(t3, expected)


def test_sequence_get_unchecked_variable_length():
    t1 = T(
        """
    a
    3
    4
    5
    """
    )
    expected = T(
        """
    x | y
    1 | 3
    2 | 3
    3 | 3
    """
    )

    t2 = t1.select(tup=pw.apply(_create_tuple, pw.this.a))
    t3 = t2.select(x=pw.this.tup[2], y=pw.this.tup[-3])

    assert_table_equality(t3, expected)


def test_sequence_get_unchecked_variable_length_untyped():
    t1 = T(
        """
    a
    3
    4
    5
    """
    )
    expected = T(
        """
    x | y
    1 | 3
    2 | 3
    3 | 3
    """
    )

    t2 = t1.select(tup=pw.apply(_create_tuple, pw.this.a))
    t3 = t2.select(x=pw.this.tup[2], y=pw.this.tup[-3])

    assert_table_equality(t3, expected)


def test_sequence_get_checked_variable_length():
    t1 = T(
        """
    a
    1
    2
    3
    """
    )
    expected = T(
        """
    x | y
      | 1
    1 | 1
    2 | 1
    """
    ).update_types(y=int | None)

    t2 = t1.select(tup=pw.apply(_create_tuple, pw.this.a))
    t3 = t2.select(x=pw.this.tup.get(1), y=pw.this.tup.get(-1))

    assert_table_equality(t3, expected)


def test_sequence_get_unchecked_variable_length_errors():
    t1 = T(
        """
    a
    1
    2
    5
    """
    )

    t2 = t1.select(tup=pw.apply(_create_tuple, pw.this.a))
    t2.select(x=pw.this.tup[1])
    with pytest.raises(IndexError):
        run_all()


def test_sequence_get_unchecked_fixed_length_errors():
    t1 = T(
        """
    a | b
    4 | 10
    3 | 9
    7 | 8
    """
    )

    t2 = t1.select(tup=pw.make_tuple(pw.this.a, pw.this.b))
    with pytest.raises(
        IndexError,
        match=(
            re.escape(f"Index 2 out of range for a tuple of type {tuple[int,int]}.")
        ),
    ):
        t2.select(i=pw.this.tup[2])


def test_sequence_get_checked_fixed_length_errors():
    t1 = T(
        """
    a | b  |  c
    4 | 10 | abc
    3 | 9  | def
    7 | 8  | xx
    """
    )
    expected = T(
        """
     c
    abc
    def
    xx
    """
    )

    t2 = t1.with_columns(tup=pw.make_tuple(pw.this.a, pw.this.b))
    with pytest.warns(
        match=(
            "(?s)"  # make dot match newlines
            + re.escape(f"Index 2 out of range for a tuple of type {tuple[int,int]}. ")
            + ".*"
            + re.escape("Consider using just the default value without .get().")
        ),
    ):
        t3 = t2.select(c=pw.this.tup.get(2, default=pw.this.c))
        assert_table_equality(t3, expected)


@pytest.mark.parametrize("dtype", [int, float])
@pytest.mark.parametrize("index", [pw.this.index_pos, pw.this.index_neg])
@pytest.mark.parametrize("checked", [True, False])
def test_sequence_get_from_1d_ndarray(dtype, index, checked):
    t = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "a": [
                    np.array([1, 2, 3], dtype=dtype),
                    np.array([4, 5], dtype=dtype),
                    np.array([0, 0], dtype=dtype),
                ],
                "index_pos": [1, 1, 1],
                "index_neg": [-2, -1, -1],
            }
        )
    )
    expected = T(
        """
        a
        2
        5
        0
    """
    ).update_types(a=dtype)
    if checked:
        result = t.select(a=pw.this.a.get(index))
    else:
        result = t.select(a=pw.this.a[index])
    assert_table_equality_wo_index(result, expected)


@pytest.mark.parametrize("dtype", [int, float])
@pytest.mark.parametrize("index", [1, -1])
@pytest.mark.parametrize("checked", [True, False])
def test_sequence_get_from_2d_ndarray(dtype, index, checked):
    t = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "a": [
                    np.array([[1, 2, 3], [4, 5, 6]], dtype=dtype),
                    np.array([[4, 5], [6, 7]], dtype=dtype),
                    np.array([[0, 0], [1, 1]], dtype=dtype),
                ]
            }
        )
    )
    expected = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "a": [
                    np.array([4, 5, 6], dtype=dtype),
                    np.array([6, 7], dtype=dtype),
                    np.array([1, 1], dtype=dtype),
                ]
            }
        )
    )

    if checked:
        result = t.select(a=pw.this.a.get(index))
    else:
        result = t.select(a=pw.this.a[index])

    assert_table_equality_wo_index(result, expected)


@pytest.mark.parametrize("dtype", [int, float])
@pytest.mark.parametrize(
    "index,expected", [([2, 2, 2], [3, -1, -1]), ([-3, -2, -3], [1, 4, -1])]
)
def test_sequence_get_from_1d_ndarray_default(dtype, index, expected):
    t = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "a": [
                    np.array([1, 2, 3], dtype=dtype),
                    np.array([4, 5], dtype=dtype),
                    np.array([0, 0], dtype=dtype),
                ],
                "index": index,
            }
        )
    )
    expected = pw.debug.table_from_pandas(
        pd.DataFrame({"a": expected}).astype(
            dtype={"a": {int: "int", float: "float"}[dtype]}
        )
    )
    result = t.select(a=pw.this.a.get(pw.this.index, default=-1))
    assert_table_equality_wo_index(result, expected)


@pytest.mark.parametrize("dtype", [int, float])
@pytest.mark.parametrize("index", [[2, 2, 2], [-3, -2, -3]])
def test_sequence_get_from_1d_ndarray_out_of_bounds(dtype, index):
    t = pw.debug.table_from_pandas(
        pd.DataFrame(
            {
                "a": [
                    np.array([1, 2, 3], dtype=dtype),
                    np.array([4, 5], dtype=dtype),
                    np.array([0, 0], dtype=dtype),
                ],
                "index": index,
            }
        )
    )
    t.select(a=pw.this.a[pw.this.index])
    with pytest.raises(IndexError):
        run_all()


def test_unique():
    left = T(
        """
    pet  |  owner  | age
    dog  | Bob     | 10
    cat  | Alice   | 9
    cat  | Alice   | 8
    dog  | Bob     | 7
    foo  | Charlie | 6
    """
    )

    left_res = left.groupby(left.pet).reduce(left.pet, pw.reducers.unique(left.owner))

    assert_table_equality_wo_index(
        left_res,
        T(
            """
        pet | owner
        dog | Bob
        cat | Alice
        foo | Charlie
    """
        ),
    )
    left.groupby(left.pet).reduce(pw.reducers.unique(left.age))
    with pytest.raises(Exception):
        run_all()


def test_slices_1():
    left = T(
        """
            col | on
            a   | 11
            b   | 12
            c   | 13
        """
    )
    right = T(
        """
            col | on
            d   | 12
            e   | 13
            f   | 14
        """,
    )
    res = left.join(right, left.on == right.on).select(
        **left.slice.with_suffix("_l").with_prefix("t"),
        **right.slice.with_suffix("_r").with_prefix("t"),
    )
    expected = T(
        """
tcol_l | ton_l | tcol_r | ton_r
b      | 12    | d      | 12
c      | 13    | e      | 13
    """
    )
    assert_table_equality_wo_index(res, expected)


def test_slices_2():
    left = T(
        """
            col | on
            a   | 11
            b   | 12
            c   | 13
        """
    )
    right = T(
        """
            col | on
            d   | 12
            e   | 13
            f   | 14
        """,
    )
    res = left.join(right, left.on == right.on).select(
        **pw.left.with_suffix("_l").with_prefix("t"),
        **pw.right.with_suffix("_r").with_prefix("t"),
    )
    expected = T(
        """
tcol_l | ton_l | tcol_r | ton_r
b      | 12    | d      | 12
c      | 13    | e      | 13
    """
    )
    assert_table_equality_wo_index(res, expected)


def test_slices_3():
    left = T(
        """
            col | on
            a   | 11
            b   | 12
            c   | 13
        """
    )
    right = T(
        """
            col | on
            d   | 12
            e   | 13
            f   | 14
        """,
    )
    res = left.join(right, left.on == right.on).select(
        **pw.left.without("col"),
        **pw.right.rename({"col": "col2"}),
    )
    expected = T(
        """
on | col2
12 | d
13 | e
    """
    )
    assert_table_equality_wo_index(res, expected)


def test_slices_4():
    left = T(
        """
            col | on
            a   | 11
            b   | 12
            c   | 13
        """
    )
    right = T(
        """
            col | on
            d   | 12
            e   | 13
            f   | 14
        """,
    )
    res = left.join(right, left.on == right.on).select(
        **pw.left.without(pw.this.col),
        **pw.right.rename({pw.this.col: pw.this.col2}),
    )
    expected = T(
        """
on | col2
12 | d
13 | e
    """
    )
    assert_table_equality_wo_index(res, expected)


def test_slices_5():
    left = T(
        """
            col | on
            a   | 11
            b   | 12
            c   | 13
        """
    )
    right = T(
        """
            col | on
            d   | 12
            e   | 13
            f   | 14
        """,
    )
    res = left.join(right, left.on == right.on).select(
        **pw.left.without(left.col),
        **pw.right.rename({right.col: pw.this.col2})[["col2"]],
    )
    expected = T(
        """
on | col2
12 | d
13 | e
    """
    )
    assert_table_equality_wo_index(res, expected)


def test_slices_6():
    left = T(
        """
            col | on
            a   | 11
            b   | 12
            c   | 13
        """
    )
    right = T(
        """
            col | on
            d   | 12
            e   | 13
            f   | 14
        """,
    )
    res = left.join(right, left.on == right.on).select(
        left.slice.on,
    )
    expected = T(
        """
on
12
13
    """
    )
    assert_table_equality_wo_index(res, expected)
    assert_table_equality_wo_index(res, expected)


def test_unwrap():
    a = T(
        """
        foo
        1
        2
        3
        None
        """
    )
    result = a.filter(a.foo.is_not_none()).select(ret=pw.unwrap(pw.this.foo))

    assert_table_equality(
        result,
        T(
            """
            ret
            1
            2
            3
            """
        ),
    )


def test_unwrap_with_nones():
    a = T(
        """
        foo
        1
        2
        3
        None
        """
    )
    a.select(ret=pw.unwrap(pw.this.foo))

    with pytest.raises(ValueError):
        run_all()


@pytest.mark.parametrize(
    "reducer, skip_nones, expected",
    [
        # NOTE: pw.reducers.tuple orders same-tick elements by row-key
        # hash; the reference's expected order reflects ITS hash, ours
        # differs on the tied rows (same values, different sequence)
        (
            pw.reducers.tuple,
            False,
            [(1, None, -1), (4, 4, 7)],
        ),
        (
            pw.reducers.tuple,
            True,
            [(1, -1), (4, 4, 7)],
        ),
        (
            pw.reducers.sorted_tuple,
            False,
            [(None, -1, 1), (4, 4, 7)],
        ),
        (
            pw.reducers.sorted_tuple,
            True,
            [(-1, 1), (4, 4, 7)],
        ),
    ],
)
def test_tuple_reducer(reducer, skip_nones, expected):
    t = pw.debug.table_from_markdown(
        """
           | colA | colB
        3  | valA | -1
        2  | valA | 1
        5  | valA |
        4  | valB | 4
        6  | valB | 4
        1  | valB | 7
        """,
    )

    df = pd.DataFrame({"tuple": expected})
    expected = pw.debug.table_from_pandas(
        df,
        schema=pw.schema_from_types(
            tuple=list[int] if skip_nones else list[Optional[int]]
        ),
    )

    res = t.groupby(t.colA).reduce(tuple=reducer(t.colB, skip_nones=skip_nones))
    assert_table_equality_wo_index(res, expected)


def test_tuple_reducer_consistency():
    left = T(
        """
    pet  |  owner  | age
    dog  | Bob     | 10
    cat  | Alice   | 9
    cat  | Alice   | 8
    dog  | Bob     | 7
    foo  | Charlie | 6
    """
    )

    left_res = left.reduce(
        pet=pw.reducers.tuple(left.pet),
        owner=pw.reducers.tuple(left.owner),
        age=pw.reducers.tuple(left.age),
    )

    t2 = left_res.select(
        pet=pw.this.pet.get(3), owner=pw.this.owner.get(3), age=pw.this.age.get(3)
    )
    print(t2.schema)

    joined = left.join(
        t2,
        left.pet == t2.pet,
        left.owner == t2.owner,
        left.age == t2.age,
    ).reduce(cnt=pw.reducers.count())

    assert_table_equality_wo_index(
        joined,
        T(
            """
            cnt
            1
            """
        ),
    )


@pytest.mark.parametrize(
    "reducer, expected, expected_type",
    [
        # NOTE: same-tick element order inside tuple()/choice of any()
        # follows the row-key hash; the reference's expectations encode
        # ITS hash order — same value sets, different sequences here
        (
            pw.reducers.tuple,
            [(1, 3), (2, 3), (2, 3, 9)],
            list[int],
        ),
        (
            pw.reducers.min,
            [1, 2, 2],
            int,
        ),
        (
            pw.reducers.any,
            [1, 2, 2],
            int,
        ),
    ],
)
def test_reducers_ix(reducer, expected, expected_type):
    values = T(
        """
        | v
    1   | 1
    2   | 2
    3   | 6
    4   | 3
    5   | 9
    """
    )
    t = T(
        """
        | t |  ptr
    1   | 1 |  4
    2   | 2 |  1
    3   | 3 |  4
    4   | 3 |  2
    5   | 2 |  4
    6   | 3 |  5
    7   | 1 |  2
    """
    ).select(pw.this.t, ptr=values.pointer_from(pw.this.ptr))
    result = t.groupby(t.t).reduce(v=reducer(values.ix(t.ptr).v))

    df = pd.DataFrame({"v": expected})
    expected = pw.debug.table_from_pandas(
        df,
        schema=pw.schema_from_types(v=expected_type),
    )

    assert_table_equality_wo_index(result, expected)


def test_groupby_pointer_type():
    tab = pw.Table.empty(a=int)
    index = tab.groupby(pw.this.a).reduce()
    assert index.schema.id.dtype == dt.Pointer(dt.INT)


def test_remove_retractions():
    t = T(
        """
        a | __time__ | __diff__
        1 |     2    |     1
        2 |     4    |     1
        3 |     6    |     1
        2 |     8    |    -1
        4 |    10    |     1
        3 |    12    |    -1
    """,
        id_from=["a"],
    )

    expected_with_retractions = T(
        """
        a
        1
        4
    """,
        id_from=["a"],
    )
    expected_without_retractions = T(
        """
        a
        1
        2
        3
        4
    """,
        id_from=["a"],
    )

    res = t._remove_retractions()

    assert_table_equality(
        (t, res),
        (expected_with_retractions, expected_without_retractions),
    )

    expected_stream = T(
        """
        a | __time__ | __diff__
        1 |     2    |     1
        2 |     4    |     1
        3 |     6    |     1
        4 |    10    |     1
    """,
        id_from=["a"],
    )

    assert_stream_equality(res, expected_stream)
