"""Persistence: input-log record, replay, offset seek, kill/restart.

Modeled on the reference's recovery tests
(reference: integration_tests/wordcount/test_recovery.py — run a streaming
wordcount, kill mid-stream, restart from the persisted snapshot, assert the
final counts are exact) and the persistence unit tests
(tests/integration/test_seek.rs: write -> restart -> rewind cycles).
"""

import json
import threading
import time

import pathway_tpu as pw


def _write_words(path, words):
    with open(path, "w") as f:
        for w in words:
            f.write(json.dumps({"word": w}) + "\n")


class WordSchema(pw.Schema):
    word: str


def _build_wordcount(input_dir, out_path, mode="streaming"):
    words = pw.io.fs.read(
        str(input_dir), format="json", schema=WordSchema, mode=mode
    )
    counts = words.groupby(words.word).reduce(
        words.word, count=pw.reducers.count()
    )
    pw.io.jsonlines.write(counts, str(out_path))


def _final_counts(out_path):
    """Consolidate the output diff stream into final state."""
    state: dict[str, int] = {}
    with open(out_path) as f:
        for line in f:
            if not line.strip():
                continue
            obj = json.loads(line)
            if obj["diff"] > 0:
                state[obj["word"]] = obj["count"]
            else:
                if state.get(obj["word"]) == obj["count"]:
                    del state[obj["word"]]
    return state


def _run_until(predicate, timeout=15.0):
    """pw.run in a thread; stop once predicate() holds (or timeout)."""
    t = threading.Thread(
        target=lambda: pw.run(
            persistence_config=_run_until.cfg, autocommit_duration_ms=20
        ),
        daemon=True,
    )
    t.start()
    deadline = time.time() + timeout
    ok = False
    while time.time() < deadline:
        if predicate():
            ok = True
            break
        time.sleep(0.05)
    rt = pw.internals.parse_graph.G.runtime
    if rt is not None:
        rt.stop()
    t.join(timeout=10)
    return ok


def test_streaming_kill_restart_wordcount(tmp_path):
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir))
    )

    _write_words(input_dir / "f1.jsonl", ["a", "b", "a", "c", "a"])

    # --- round A: ingest f1, then "crash" (stop mid-stream) -------------------
    _build_wordcount(input_dir, out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return _final_counts(out_a).get("a") == 3
        except OSError:
            return False

    assert _run_until(_a_done)
    assert _final_counts(out_a) == {"a": 3, "b": 1, "c": 1}

    # --- round B: restart from snapshot, add f2 -------------------------------
    pw.internals.parse_graph.G.clear()
    _write_words(input_dir / "f2.jsonl", ["b", "d"])
    _build_wordcount(input_dir, out_b)

    def _b_done():
        try:
            got = _final_counts(out_b)
        except OSError:
            return False
        return got.get("b") == 2 and got.get("d") == 1

    assert _run_until(_b_done)
    # exact counts: f1 rows came from the replay log (not re-read), f2 rows
    # from the live scan — each ingested exactly once
    assert _final_counts(out_b) == {"a": 3, "b": 2, "c": 1, "d": 1}


def test_static_finished_source_not_rerun(tmp_path):
    """A finished static source is not re-ingested on restart; the replay
    log alone reproduces the output (reference: finished sources skipped
    after recovery, src/connectors/mod.rs rewind path)."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    _write_words(input_dir / "f1.jsonl", ["x", "y", "x"])
    pdir = tmp_path / "pstorage"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir))
    )

    out_a = tmp_path / "out_a.jsonl"
    _build_wordcount(input_dir, out_a, mode="static")
    pw.run(persistence_config=cfg)
    assert _final_counts(out_a) == {"x": 2, "y": 1}

    pw.internals.parse_graph.G.clear()
    out_b = tmp_path / "out_b.jsonl"
    _build_wordcount(input_dir, out_b, mode="static")
    pw.run(persistence_config=cfg)
    assert _final_counts(out_b) == {"x": 2, "y": 1}


def test_memory_backend_roundtrip(tmp_path):
    """MemoryStore registry survives engine 'restarts' in-process."""
    from pathway_tpu.persistence.backends import MemoryStore

    a = MemoryStore("t1")
    a.put("inputs/x/chunk-00000000.pkl", b"abc")
    a.put("metadata.json", b"{}")
    b = MemoryStore("t1")
    assert b.get("inputs/x/chunk-00000000.pkl") == b"abc"
    assert b.list_keys("inputs/") == ["inputs/x/chunk-00000000.pkl"]
    b.remove("metadata.json")
    assert MemoryStore("t1").get("metadata.json") is None


def test_filesystem_store_atomic(tmp_path):
    from pathway_tpu.persistence.backends import FilesystemStore

    s = FilesystemStore(str(tmp_path / "blobs"))
    s.put("a/b/c.bin", b"\x00\x01")
    assert s.get("a/b/c.bin") == b"\x00\x01"
    assert s.list_keys() == ["a/b/c.bin"]
    assert s.list_keys("a/") == ["a/b/c.bin"]
    s.remove("a/b/c.bin")
    assert s.get("a/b/c.bin") is None
