"""Persistence: input-log record, replay, offset seek, kill/restart.

Modeled on the reference's recovery tests
(reference: integration_tests/wordcount/test_recovery.py — run a streaming
wordcount, kill mid-stream, restart from the persisted snapshot, assert the
final counts are exact) and the persistence unit tests
(tests/integration/test_seek.rs: write -> restart -> rewind cycles).
"""

import json
import threading
import time

import pathway_tpu as pw


def _write_words(path, words):
    with open(path, "w") as f:
        for w in words:
            f.write(json.dumps({"word": w}) + "\n")


class WordSchema(pw.Schema):
    word: str


def _build_wordcount(input_dir, out_path, mode="streaming"):
    words = pw.io.fs.read(
        str(input_dir), format="json", schema=WordSchema, mode=mode
    )
    counts = words.groupby(words.word).reduce(
        words.word, count=pw.reducers.count()
    )
    pw.io.jsonlines.write(counts, str(out_path))


def _final_counts(out_path):
    """Consolidate the output diff stream into final state."""
    state: dict[str, int] = {}
    with open(out_path) as f:
        for line in f:
            if not line.strip():
                continue
            obj = json.loads(line)
            if obj["diff"] > 0:
                state[obj["word"]] = obj["count"]
            else:
                if state.get(obj["word"]) == obj["count"]:
                    del state[obj["word"]]
    return state


def _run_until(predicate, timeout=15.0):
    """pw.run in a thread; stop once predicate() holds (or timeout)."""
    t = threading.Thread(
        target=lambda: pw.run(
            persistence_config=_run_until.cfg, autocommit_duration_ms=20
        ),
        daemon=True,
    )
    t.start()
    deadline = time.time() + timeout
    ok = False
    while time.time() < deadline:
        if predicate():
            ok = True
            break
        time.sleep(0.05)
    rt = pw.internals.parse_graph.G.runtime
    if rt is not None:
        rt.stop()
    t.join(timeout=10)
    return ok


def test_streaming_kill_restart_wordcount(tmp_path):
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    # snapshot_access="full": record/replay debugging keeps the input log
    # verbatim, so the restarted run reproduces every output row (the
    # default mode instead restores operator snapshots and only emits
    # post-restart deltas, reference recovery semantics)
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_access="full",
    )

    _write_words(input_dir / "f1.jsonl", ["a", "b", "a", "c", "a"])

    # --- round A: ingest f1, then "crash" (stop mid-stream) -------------------
    _build_wordcount(input_dir, out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return _final_counts(out_a).get("a") == 3
        except OSError:
            return False

    assert _run_until(_a_done)
    assert _final_counts(out_a) == {"a": 3, "b": 1, "c": 1}

    # --- round B: restart from snapshot, add f2 -------------------------------
    pw.internals.parse_graph.G.clear()
    _write_words(input_dir / "f2.jsonl", ["b", "d"])
    _build_wordcount(input_dir, out_b)

    def _b_done():
        try:
            got = _final_counts(out_b)
        except OSError:
            return False
        return got.get("b") == 2 and got.get("d") == 1

    assert _run_until(_b_done)
    # exact counts: f1 rows came from the replay log (not re-read), f2 rows
    # from the live scan — each ingested exactly once
    assert _final_counts(out_b) == {"a": 3, "b": 2, "c": 1, "d": 1}


def test_static_finished_source_not_rerun(tmp_path):
    """A finished static source is not re-ingested on restart; the replay
    log alone reproduces the output (reference: finished sources skipped
    after recovery, src/connectors/mod.rs rewind path)."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    _write_words(input_dir / "f1.jsonl", ["x", "y", "x"])
    pdir = tmp_path / "pstorage"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_access="full",  # keep the log: replay reproduces output
    )

    out_a = tmp_path / "out_a.jsonl"
    _build_wordcount(input_dir, out_a, mode="static")
    pw.run(persistence_config=cfg)
    assert _final_counts(out_a) == {"x": 2, "y": 1}

    pw.internals.parse_graph.G.clear()
    out_b = tmp_path / "out_b.jsonl"
    _build_wordcount(input_dir, out_b, mode="static")
    pw.run(persistence_config=cfg)
    assert _final_counts(out_b) == {"x": 2, "y": 1}


def test_memory_backend_roundtrip(tmp_path):
    """MemoryStore registry survives engine 'restarts' in-process."""
    from pathway_tpu.persistence.backends import MemoryStore

    a = MemoryStore("t1")
    a.put("inputs/x/chunk-00000000.pkl", b"abc")
    a.put("metadata.json", b"{}")
    b = MemoryStore("t1")
    assert b.get("inputs/x/chunk-00000000.pkl") == b"abc"
    assert b.list_keys("inputs/") == ["inputs/x/chunk-00000000.pkl"]
    b.remove("metadata.json")
    assert MemoryStore("t1").get("metadata.json") is None


def test_filesystem_store_atomic(tmp_path):
    from pathway_tpu.persistence.backends import FilesystemStore

    s = FilesystemStore(str(tmp_path / "blobs"))
    s.put("a/b/c.bin", b"\x00\x01")
    assert s.get("a/b/c.bin") == b"\x00\x01"
    assert s.list_keys() == ["a/b/c.bin"]
    assert s.list_keys("a/") == ["a/b/c.bin"]
    s.remove("a/b/c.bin")
    assert s.get("a/b/c.bin") is None


# ---------------------------------------------------------------------------
# Operator-state snapshots + log compaction
# (reference: src/persistence/operator_snapshot.rs:21-31,342 + persist.rs)


def test_operator_snapshot_bounded_replay_and_compaction(tmp_path):
    """Restart restores groupby state from the snapshot and replays ZERO
    logged events; each snapshot truncates the input log."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_every=1,
    )

    _write_words(input_dir / "f1.jsonl", ["a", "b", "a", "c", "a"])
    _build_wordcount(input_dir, out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return _final_counts(out_a).get("a") == 3
        except OSError:
            return False

    assert _run_until(_a_done)
    assert _final_counts(out_a) == {"a": 3, "b": 1, "c": 1}

    # snapshot written, covered log chunks deleted (compaction)
    import os

    state_files = []
    for root, _dirs, files in os.walk(pdir):
        for f in files:
            p = os.path.join(root, f)
            rel = os.path.relpath(p, pdir)
            if rel.startswith("states/"):
                state_files.append(rel)
            assert not rel.startswith("inputs/"), f"uncompacted chunk {rel}"
    assert state_files, "no operator snapshot written"

    # --- restart: new data only; replay must be empty ---------------------
    pw.internals.parse_graph.G.clear()
    _write_words(input_dir / "f2.jsonl", ["b", "d"])
    _build_wordcount(input_dir, out_b)

    def _b_done():
        try:
            got = _final_counts(out_b)
        except OSError:
            return False
        return got.get("b") == 2 and got.get("d") == 1

    assert _run_until(_b_done)
    rt = pw.internals.parse_graph.G.last_runtime
    drv = rt.persistence_driver
    assert drv.restored_from_snapshot, "state not restored from snapshot"
    assert drv.replayed_events == 0, (
        f"replay not bounded: {drv.replayed_events} events re-run"
    )
    # after restore, the restart emits ONLY the deltas; merging them onto
    # round A's final state gives the exact combined counts
    merged = _final_counts(out_a)
    import json as _json

    with open(out_b) as f:
        for line in f:
            if not line.strip():
                continue
            obj = _json.loads(line)
            if obj["diff"] > 0:
                merged[obj["word"]] = obj["count"]
            elif merged.get(obj["word"]) == obj["count"]:
                del merged[obj["word"]]
    assert merged == {"a": 3, "b": 2, "c": 1, "d": 1}


def test_log_stays_bounded_under_churn(tmp_path):
    """With operator snapshots on, the input log never accumulates: every
    snapshot deletes the covered chunks (the compaction the reference gets
    from background merge, operator_snapshot.rs:342)."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out = tmp_path / "out.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_every=1,
    )
    for i in range(5):
        _write_words(input_dir / f"f{i}.jsonl", [f"w{i}", "common"])
    _build_wordcount(input_dir, out)
    _run_until.cfg = cfg

    def _done():
        try:
            return _final_counts(out).get("common") == 5
        except OSError:
            return False

    assert _run_until(_done)
    import os

    chunk_files = []
    gens = set()
    for root, _dirs, files in os.walk(pdir):
        for f in files:
            rel = os.path.relpath(os.path.join(root, f), pdir)
            if rel.startswith("inputs/"):
                chunk_files.append(rel)
            if rel.startswith("states/"):
                gens.add(rel.split("/")[1])
    assert not chunk_files, f"log not compacted: {chunk_files}"
    assert len(gens) == 1, f"stale snapshot generations kept: {gens}"


def test_knn_index_state_roundtrip():
    """TpuDenseKnnIndex snapshots its host-side content exactly."""
    import numpy as np

    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(20, 8)).astype(np.float32)
    a = TpuDenseKnnIndex(dimensions=8)
    for i in range(20):
        a.upsert(i, vecs[i], {"i": i})
    a.remove(5)

    b = TpuDenseKnnIndex(dimensions=8)
    b.load_state(a.state_dict())
    res_a = a.search([(vecs[7], 3, None)])
    res_b = b.search([(vecs[7], 3, None)])
    assert [r[0] for r in res_a[0]] == [r[0] for r in res_b[0]]
    assert b.metadata[7] == {"i": 7}
    assert all(r[0] != 5 for r in res_b[0])


def test_fsspec_object_store_backend():
    """Real client-based object-store backend (reference: backends/s3.rs
    over rust-s3; here FsspecStore): round trip + prefix listing via the
    in-process memory:// object store."""
    import uuid

    from pathway_tpu.persistence.backends import FsspecStore, store_for_backend

    url = f"memory://pwtest-{uuid.uuid4().hex}"
    st = FsspecStore(url)
    st.put("inputs/a/chunk-00000001.pkl", b"one")
    st.put("inputs/a/chunk-00000002.pkl", b"two")
    st.put("offsets/a.pkl", b"off")
    assert st.get("inputs/a/chunk-00000001.pkl") == b"one"
    assert st.get("missing") is None
    assert st.list_keys("inputs/") == [
        "inputs/a/chunk-00000001.pkl",
        "inputs/a/chunk-00000002.pkl",
    ]
    st.remove("inputs/a/chunk-00000001.pkl")
    assert st.list_keys("inputs/") == ["inputs/a/chunk-00000002.pkl"]
    st.remove("missing")  # no-op

    # the Backend.s3 factory routes URLs to the fsspec store
    be = pw.persistence.Backend.s3(url)
    st2 = store_for_backend(be)
    assert isinstance(st2, FsspecStore)
    assert st2.get("offsets/a.pkl") == b"off"


def test_kill_restart_on_object_store(tmp_path):
    """Full kill/restart durability against the object-store backend — the
    same wordcount cycle the filesystem backend passes."""
    import uuid

    input_dir = tmp_path / "in"
    input_dir.mkdir()
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.s3(f"memory://pwtest-{uuid.uuid4().hex}"),
        snapshot_access="full",  # keep the log: replay reproduces output
    )

    _write_words(input_dir / "f1.jsonl", ["a", "b", "a", "c", "a"])
    _build_wordcount(input_dir, out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return _final_counts(out_a).get("a") == 3
        except OSError:
            return False

    assert _run_until(_a_done)

    pw.internals.parse_graph.G.clear()
    _write_words(input_dir / "f2.jsonl", ["b", "d"])
    _build_wordcount(input_dir, out_b)

    def _b_done():
        try:
            got = _final_counts(out_b)
        except OSError:
            return False
        return got.get("b") == 2 and got.get("d") == 1

    assert _run_until(_b_done)
    assert _final_counts(out_b) == {"a": 3, "b": 2, "c": 1, "d": 1}


def test_fsspec_file_protocol_nested_keys(tmp_path):
    from pathway_tpu.persistence.backends import FsspecStore

    st = FsspecStore(f"file://{tmp_path}/ckpt")
    st.put("inputs/a/chunk-00000001.pkl", b"x")  # parents auto-created
    assert st.get("inputs/a/chunk-00000001.pkl") == b"x"

    import pytest

    with pytest.raises(TypeError, match="bucket_settings"):
        from pathway_tpu.persistence.backends import store_for_backend

        store_for_backend(pw.persistence.Backend.s3("memory://x", object()))
