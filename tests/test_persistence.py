"""Persistence: input-log record, replay, offset seek, kill/restart.

Modeled on the reference's recovery tests
(reference: integration_tests/wordcount/test_recovery.py — run a streaming
wordcount, kill mid-stream, restart from the persisted snapshot, assert the
final counts are exact) and the persistence unit tests
(tests/integration/test_seek.rs: write -> restart -> rewind cycles).
"""

import json
import threading
import time

import pathway_tpu as pw


def _write_words(path, words):
    with open(path, "w") as f:
        for w in words:
            f.write(json.dumps({"word": w}) + "\n")


class WordSchema(pw.Schema):
    word: str


def _build_wordcount(input_dir, out_path, mode="streaming"):
    words = pw.io.fs.read(
        str(input_dir), format="json", schema=WordSchema, mode=mode
    )
    counts = words.groupby(words.word).reduce(
        words.word, count=pw.reducers.count()
    )
    pw.io.jsonlines.write(counts, str(out_path))


def _final_counts(out_path):
    """Consolidate the output diff stream into final state."""
    state: dict[str, int] = {}
    with open(out_path) as f:
        for line in f:
            if not line.strip():
                continue
            obj = json.loads(line)
            if obj["diff"] > 0:
                state[obj["word"]] = obj["count"]
            else:
                if state.get(obj["word"]) == obj["count"]:
                    del state[obj["word"]]
    return state


def _run_until(predicate, timeout=15.0):
    """pw.run in a thread; stop once predicate() holds (or timeout)."""
    t = threading.Thread(
        target=lambda: pw.run(
            persistence_config=_run_until.cfg, autocommit_duration_ms=20
        ),
        daemon=True,
    )
    t.start()
    deadline = time.time() + timeout
    ok = False
    while time.time() < deadline:
        if predicate():
            ok = True
            break
        time.sleep(0.05)
    rt = pw.internals.parse_graph.G.runtime
    if rt is not None:
        rt.stop()
    t.join(timeout=10)
    return ok


def test_streaming_kill_restart_wordcount(tmp_path):
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    # snapshot_access="full": record/replay debugging keeps the input log
    # verbatim, so the restarted run reproduces every output row (the
    # default mode instead restores operator snapshots and only emits
    # post-restart deltas, reference recovery semantics)
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_access="full",
    )

    _write_words(input_dir / "f1.jsonl", ["a", "b", "a", "c", "a"])

    # --- round A: ingest f1, then "crash" (stop mid-stream) -------------------
    _build_wordcount(input_dir, out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return _final_counts(out_a).get("a") == 3
        except OSError:
            return False

    assert _run_until(_a_done)
    assert _final_counts(out_a) == {"a": 3, "b": 1, "c": 1}

    # --- round B: restart from snapshot, add f2 -------------------------------
    pw.internals.parse_graph.G.clear()
    _write_words(input_dir / "f2.jsonl", ["b", "d"])
    _build_wordcount(input_dir, out_b)

    def _b_done():
        try:
            got = _final_counts(out_b)
        except OSError:
            return False
        return got.get("b") == 2 and got.get("d") == 1

    assert _run_until(_b_done)
    # exact counts: f1 rows came from the replay log (not re-read), f2 rows
    # from the live scan — each ingested exactly once
    assert _final_counts(out_b) == {"a": 3, "b": 2, "c": 1, "d": 1}


def test_static_finished_source_not_rerun(tmp_path):
    """A finished static source is not re-ingested on restart; the replay
    log alone reproduces the output (reference: finished sources skipped
    after recovery, src/connectors/mod.rs rewind path)."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    _write_words(input_dir / "f1.jsonl", ["x", "y", "x"])
    pdir = tmp_path / "pstorage"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_access="full",  # keep the log: replay reproduces output
    )

    out_a = tmp_path / "out_a.jsonl"
    _build_wordcount(input_dir, out_a, mode="static")
    pw.run(persistence_config=cfg)
    assert _final_counts(out_a) == {"x": 2, "y": 1}

    pw.internals.parse_graph.G.clear()
    out_b = tmp_path / "out_b.jsonl"
    _build_wordcount(input_dir, out_b, mode="static")
    pw.run(persistence_config=cfg)
    assert _final_counts(out_b) == {"x": 2, "y": 1}


def test_memory_backend_roundtrip(tmp_path):
    """MemoryStore registry survives engine 'restarts' in-process."""
    from pathway_tpu.persistence.backends import MemoryStore

    a = MemoryStore("t1")
    a.put("inputs/x/chunk-00000000.pkl", b"abc")
    a.put("metadata.json", b"{}")
    b = MemoryStore("t1")
    assert b.get("inputs/x/chunk-00000000.pkl") == b"abc"
    assert b.list_keys("inputs/") == ["inputs/x/chunk-00000000.pkl"]
    b.remove("metadata.json")
    assert MemoryStore("t1").get("metadata.json") is None


def test_filesystem_store_atomic(tmp_path):
    from pathway_tpu.persistence.backends import FilesystemStore

    s = FilesystemStore(str(tmp_path / "blobs"))
    s.put("a/b/c.bin", b"\x00\x01")
    assert s.get("a/b/c.bin") == b"\x00\x01"
    assert s.list_keys() == ["a/b/c.bin"]
    assert s.list_keys("a/") == ["a/b/c.bin"]
    s.remove("a/b/c.bin")
    assert s.get("a/b/c.bin") is None


# ---------------------------------------------------------------------------
# Operator-state snapshots + log compaction
# (reference: src/persistence/operator_snapshot.rs:21-31,342 + persist.rs)


def test_operator_snapshot_bounded_replay_and_compaction(tmp_path):
    """Restart restores groupby state from the snapshot and replays ZERO
    logged events; each snapshot truncates the input log."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_every=1,
    )

    _write_words(input_dir / "f1.jsonl", ["a", "b", "a", "c", "a"])
    _build_wordcount(input_dir, out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return _final_counts(out_a).get("a") == 3
        except OSError:
            return False

    assert _run_until(_a_done)
    assert _final_counts(out_a) == {"a": 3, "b": 1, "c": 1}

    # snapshot written, covered log chunks deleted (compaction)
    import os

    state_files = []
    for root, _dirs, files in os.walk(pdir):
        for f in files:
            p = os.path.join(root, f)
            rel = os.path.relpath(p, pdir)
            if rel.startswith("states/"):
                state_files.append(rel)
            assert not rel.startswith("inputs/"), f"uncompacted chunk {rel}"
    assert state_files, "no operator snapshot written"

    # --- restart: new data only; replay must be empty ---------------------
    pw.internals.parse_graph.G.clear()
    _write_words(input_dir / "f2.jsonl", ["b", "d"])
    _build_wordcount(input_dir, out_b)

    def _b_done():
        try:
            got = _final_counts(out_b)
        except OSError:
            return False
        return got.get("b") == 2 and got.get("d") == 1

    assert _run_until(_b_done)
    rt = pw.internals.parse_graph.G.last_runtime
    drv = rt.persistence_driver
    assert drv.restored_from_snapshot, "state not restored from snapshot"
    assert drv.replayed_events == 0, (
        f"replay not bounded: {drv.replayed_events} events re-run"
    )
    # after restore, the restart emits ONLY the deltas; merging them onto
    # round A's final state gives the exact combined counts
    merged = _final_counts(out_a)
    import json as _json

    with open(out_b) as f:
        for line in f:
            if not line.strip():
                continue
            obj = _json.loads(line)
            if obj["diff"] > 0:
                merged[obj["word"]] = obj["count"]
            elif merged.get(obj["word"]) == obj["count"]:
                del merged[obj["word"]]
    assert merged == {"a": 3, "b": 2, "c": 1, "d": 1}


def test_log_stays_bounded_under_churn(tmp_path):
    """With operator snapshots on, the input log never accumulates: every
    snapshot deletes the covered chunks (the compaction the reference gets
    from background merge, operator_snapshot.rs:342)."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out = tmp_path / "out.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
        snapshot_every=1,
    )
    for i in range(5):
        _write_words(input_dir / f"f{i}.jsonl", [f"w{i}", "common"])
    _build_wordcount(input_dir, out)
    _run_until.cfg = cfg

    def _done():
        try:
            return _final_counts(out).get("common") == 5
        except OSError:
            return False

    assert _run_until(_done)
    import os

    chunk_files = []
    gens = set()
    for root, _dirs, files in os.walk(pdir):
        for f in files:
            rel = os.path.relpath(os.path.join(root, f), pdir)
            if rel.startswith("inputs/"):
                chunk_files.append(rel)
            if rel.startswith("states/"):
                gens.add(rel.split("/")[1])
    assert not chunk_files, f"log not compacted: {chunk_files}"
    assert len(gens) == 1, f"stale snapshot generations kept: {gens}"


def _write_rows(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


class NumSchema(pw.Schema):
    k: str
    t: int
    v: int


def _final_rows(out_path, key_fields):
    state: dict = {}
    with open(out_path) as f:
        for line in f:
            if not line.strip():
                continue
            obj = json.loads(line)
            key = tuple(obj[k] for k in key_fields)
            val = tuple(
                v
                for k, v in sorted(obj.items())
                if k not in ("diff", "time", "id", *key_fields)
            )
            if obj["diff"] > 0:
                state[key] = val
            elif state.get(key) == val:
                del state[key]
    return state


def test_groupby_sum_kill_restart_bounded_replay(tmp_path):
    """Kill/restart matrix — groupby with sum/max reducers: restart
    restores groupby state from the snapshot (zero replayed events) and
    the merged totals are exact."""
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)), snapshot_every=1
    )

    def build(out_path):
        rows = pw.io.fs.read(
            str(input_dir), format="json", schema=NumSchema, mode="streaming"
        )
        agg = rows.groupby(rows.k).reduce(
            rows.k,
            s=pw.reducers.sum(rows.v),
            mx=pw.reducers.max(rows.v),
            cnt=pw.reducers.count(),
        )
        pw.io.jsonlines.write(agg, str(out_path))

    _write_rows(
        input_dir / "f1.jsonl",
        [
            {"k": "x", "t": 0, "v": 3},
            {"k": "y", "t": 1, "v": 5},
            {"k": "x", "t": 2, "v": 4},
        ],
    )
    build(out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return _final_rows(out_a, ["k"]).get(("x",)) == (2, 4, 7)
        except OSError:
            return False

    assert _run_until(_a_done)

    pw.internals.parse_graph.G.clear()
    _write_rows(
        input_dir / "f2.jsonl",
        [{"k": "x", "t": 3, "v": 10}, {"k": "z", "t": 4, "v": 1}],
    )
    build(out_b)

    def _b_done():
        try:
            got = _final_rows(out_b, ["k"])
        except OSError:
            return False
        return got.get(("x",)) == (3, 10, 17) and got.get(("z",)) == (1, 1, 1)

    assert _run_until(_b_done)
    rt = pw.internals.parse_graph.G.last_runtime
    drv = rt.persistence_driver
    assert drv.restored_from_snapshot
    assert drv.replayed_events == 0, drv.replayed_events


def test_windowby_behavior_kill_restart_matches_uninterrupted(tmp_path):
    """Kill/restart matrix — windowby + common_behavior (Buffer/Forget
    state): a run killed mid-stream and restarted from the incremental
    snapshot converges to the exact final windows of an uninterrupted
    run over the same input sequence."""
    f1 = [
        {"k": "a", "t": t, "v": t} for t in (0, 1, 3, 5, 6)
    ] + [{"k": "b", "t": t, "v": 2 * t} for t in (2, 4, 7)]
    # phase-2 rows end with a high sentinel time so every earlier window
    # crosses the behavior's delay threshold deterministically
    f2 = [
        {"k": "a", "t": 9, "v": 9},
        {"k": "b", "t": 11, "v": 22},
        {"k": "a", "t": 40, "v": 0},
        {"k": "b", "t": 41, "v": 0},
    ]

    def build(input_dir, out_path):
        rows = pw.io.fs.read(
            str(input_dir), format="json", schema=NumSchema, mode="streaming"
        )
        win = rows.windowby(
            rows.t,
            window=pw.temporal.tumbling(duration=4),
            instance=rows.k,
            behavior=pw.temporal.common_behavior(
                delay=2, cutoff=100, keep_results=True
            ),
        ).reduce(
            k=pw.this._pw_instance,
            start=pw.this._pw_window_start,
            cnt=pw.reducers.count(),
            s=pw.reducers.sum(pw.this.v),
        )
        pw.io.jsonlines.write(win, str(out_path))

    # --- reference: uninterrupted run over f1+f2 --------------------------
    ref_dir = tmp_path / "ref_in"
    ref_dir.mkdir()
    _write_rows(ref_dir / "f1.jsonl", f1)
    _write_rows(ref_dir / "f2.jsonl", f2)
    ref_out = tmp_path / "ref.jsonl"
    ref_pdir = tmp_path / "ref_pstorage"
    build(ref_dir, ref_out)
    _run_until.cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(ref_pdir)), snapshot_every=1
    )

    def _ref_done():
        try:
            got = _final_rows(ref_out, ["k", "start"])
        except OSError:
            return False
        return ("a", 8) in got and ("b", 8) in got

    assert _run_until(_ref_done)
    expected = _final_rows(ref_out, ["k", "start"])
    assert expected.get(("a", 0)) == (3, 4)  # t=0,1,3 -> cnt=3 sum=4
    # the sentinel rows' own windows only flush on shutdown (END_OF_TIME),
    # so the live-run predicate below compares the pre-shutdown set
    live_expected = {k: v for k, v in expected.items() if k[1] < 40}

    # --- kill/restart run over the same sequence --------------------------
    pw.internals.parse_graph.G.clear()
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)), snapshot_every=1
    )
    _write_rows(input_dir / "f1.jsonl", f1)
    build(input_dir, out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return len(_final_rows(out_a, ["k", "start"])) >= 2
        except OSError:
            return False

    assert _run_until(_a_done)  # "crash" mid-stream with buffered windows

    pw.internals.parse_graph.G.clear()
    _write_rows(input_dir / "f2.jsonl", f2)
    build(input_dir, out_b)

    def _merged():
        merged = _final_rows(out_a, ["k", "start"])
        merged.update(_final_rows(out_b, ["k", "start"]))
        return merged

    def _b_done():
        try:
            m = _merged()
        except OSError:
            return False
        return {k: v for k, v in m.items() if k[1] < 40} == live_expected

    assert _run_until(_b_done), (_merged(), expected)
    # after shutdown the sentinel windows flushed too: full equality with
    # the uninterrupted run, bit for bit
    assert _merged() == expected
    rt = pw.internals.parse_graph.G.last_runtime
    drv = rt.persistence_driver
    assert drv.restored_from_snapshot
    assert drv.replayed_events == 0, drv.replayed_events


def test_knn_index_state_roundtrip():
    """TpuDenseKnnIndex snapshots its host-side content exactly."""
    import numpy as np

    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    rng = np.random.default_rng(0)
    vecs = rng.normal(size=(20, 8)).astype(np.float32)
    a = TpuDenseKnnIndex(dimensions=8)
    for i in range(20):
        a.upsert(i, vecs[i], {"i": i})
    a.remove(5)

    b = TpuDenseKnnIndex(dimensions=8)
    b.load_state(a.state_dict())
    res_a = a.search([(vecs[7], 3, None)])
    res_b = b.search([(vecs[7], 3, None)])
    assert [r[0] for r in res_a[0]] == [r[0] for r in res_b[0]]
    assert b.metadata[7] == {"i": 7}
    assert all(r[0] != 5 for r in res_b[0])


def test_fsspec_object_store_backend():
    """Real client-based object-store backend (reference: backends/s3.rs
    over rust-s3; here FsspecStore): round trip + prefix listing via the
    in-process memory:// object store."""
    import uuid

    from pathway_tpu.persistence.backends import FsspecStore, store_for_backend

    url = f"memory://pwtest-{uuid.uuid4().hex}"
    st = FsspecStore(url)
    st.put("inputs/a/chunk-00000001.pkl", b"one")
    st.put("inputs/a/chunk-00000002.pkl", b"two")
    st.put("offsets/a.pkl", b"off")
    assert st.get("inputs/a/chunk-00000001.pkl") == b"one"
    assert st.get("missing") is None
    assert st.list_keys("inputs/") == [
        "inputs/a/chunk-00000001.pkl",
        "inputs/a/chunk-00000002.pkl",
    ]
    st.remove("inputs/a/chunk-00000001.pkl")
    assert st.list_keys("inputs/") == ["inputs/a/chunk-00000002.pkl"]
    st.remove("missing")  # no-op

    # the Backend.s3 factory routes URLs to the fsspec store
    be = pw.persistence.Backend.s3(url)
    st2 = store_for_backend(be)
    assert isinstance(st2, FsspecStore)
    assert st2.get("offsets/a.pkl") == b"off"


def test_kill_restart_on_object_store(tmp_path):
    """Full kill/restart durability against the object-store backend — the
    same wordcount cycle the filesystem backend passes."""
    import uuid

    input_dir = tmp_path / "in"
    input_dir.mkdir()
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.s3(f"memory://pwtest-{uuid.uuid4().hex}"),
        snapshot_access="full",  # keep the log: replay reproduces output
    )

    _write_words(input_dir / "f1.jsonl", ["a", "b", "a", "c", "a"])
    _build_wordcount(input_dir, out_a)
    _run_until.cfg = cfg

    def _a_done():
        try:
            return _final_counts(out_a).get("a") == 3
        except OSError:
            return False

    assert _run_until(_a_done)

    pw.internals.parse_graph.G.clear()
    _write_words(input_dir / "f2.jsonl", ["b", "d"])
    _build_wordcount(input_dir, out_b)

    def _b_done():
        try:
            got = _final_counts(out_b)
        except OSError:
            return False
        return got.get("b") == 2 and got.get("d") == 1

    assert _run_until(_b_done)
    assert _final_counts(out_b) == {"a": 3, "b": 2, "c": 1, "d": 1}


def test_fsspec_file_protocol_nested_keys(tmp_path):
    from pathway_tpu.persistence.backends import FsspecStore

    st = FsspecStore(f"file://{tmp_path}/ckpt")
    st.put("inputs/a/chunk-00000001.pkl", b"x")  # parents auto-created
    assert st.get("inputs/a/chunk-00000001.pkl") == b"x"

    import pytest

    with pytest.raises(TypeError, match="bucket_settings"):
        from pathway_tpu.persistence.backends import store_for_backend

        store_for_backend(pw.persistence.Backend.s3("memory://x", object()))


def test_sharded_groupby_kill_restart_incremental_snapshot(tmp_path):
    """Kill/restart matrix extended to a device-mesh SHARDED pipeline
    (Replica Shield satellite): sharded wrapper execs delegate
    arranged_state to their inner shard execs, so device-mesh runs
    snapshot incrementally (segment files on disk, zero replayed events
    on restart) instead of falling back to monolith pickles."""
    import pytest

    from pathway_tpu.parallel.mesh import (
        make_mesh,
        set_engine_mesh,
    )

    try:
        mesh = make_mesh(2)
    except Exception:
        pytest.skip("no 2-device mesh available")
    input_dir = tmp_path / "in"
    input_dir.mkdir()
    pdir = tmp_path / "pstorage"
    out_a = tmp_path / "out_a.jsonl"
    out_b = tmp_path / "out_b.jsonl"
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)), snapshot_every=1
    )

    set_engine_mesh(mesh)
    try:

        def build(out_path):
            rows = pw.io.fs.read(
                str(input_dir),
                format="json",
                schema=NumSchema,
                mode="streaming",
            )
            agg = rows.groupby(rows.k).reduce(
                rows.k,
                s=pw.reducers.sum(rows.v),
                cnt=pw.reducers.count(),
            )
            pw.io.jsonlines.write(agg, str(out_path))

        _write_rows(
            input_dir / "f1.jsonl",
            [
                {"k": "x", "t": 0, "v": 3},
                {"k": "y", "t": 1, "v": 5},
                {"k": "x", "t": 2, "v": 4},
                {"k": "z", "t": 3, "v": 9},
            ],
        )
        build(out_a)
        _run_until.cfg = cfg

        def _a_done():
            try:
                return _final_rows(out_a, ["k"]).get(("x",)) == (2, 7)
            except OSError:
                return False

        assert _run_until(_a_done)
        rt = pw.internals.parse_graph.G.last_runtime
        from pathway_tpu.engine.sharded import ShardedGroupByExec

        sharded = [
            ex
            for ex in rt.execs.values()
            if isinstance(ex, ShardedGroupByExec)
        ]
        assert sharded, "pipeline did not shard under the engine mesh"
        # the sharded exec exposes the ledger protocol: per-shard parts
        arranged = sharded[0].arranged_state()
        assert arranged is not None
        residual, arrs = arranged
        assert set(arrs) == {"s0.ledger", "s1.ledger"}
        # ...and the store holds real segment files for it
        segs = list((pdir).rglob("*.seg"))
        assert segs, "sharded snapshot wrote no segment files"

        pw.internals.parse_graph.G.clear()
        _write_rows(
            input_dir / "f2.jsonl",
            [{"k": "x", "t": 4, "v": 10}, {"k": "w", "t": 5, "v": 1}],
        )
        build(out_b)

        def _b_done():
            try:
                got = _final_rows(out_b, ["k"])
            except OSError:
                return False
            return got.get(("x",)) == (3, 17) and got.get(("w",)) == (
                1,
                1,
            )

        assert _run_until(_b_done)
        rt = pw.internals.parse_graph.G.last_runtime
        drv = rt.persistence_driver
        assert drv.restored_from_snapshot
        assert drv.replayed_events == 0, drv.replayed_events
    finally:
        set_engine_mesh(None)
