"""Port of the reference test_asof_now_joins.py (reference:
python/pathway/tests/temporal/test_asof_now_joins.py). Mechanical port: package and
imports adapted, fixtures and assertions kept identical."""

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import assert_table_equality_wo_index


class ValueInstanceSchema(pw.Schema):
    value: int
    instance: int
    is_query: bool


def stream_data() -> tuple[pw.Table, pw.Table]:
    data = T(
        """
           | value | instance | __time__ | __diff__
         2 |   4   |    1     |     2    |     1
         2 |   4   |    1     |     8    |    -1
         5 |   5   |    1     |    10    |     1
         7 |   2   |    2     |    14    |     1
         7 |   2   |    2     |    22    |    -1
        11 |   3   |    2     |    24    |     1
         5 |   5   |    1     |    30    |    -1
        14 |   9   |    1     |    32    |     1
        """
    )
    queries = T(
        """
        value | instance | __time__
          1   |    1     |     0
          2   |    1     |     4
          3   |    1     |     6
          4   |    1     |    12
          5   |    2     |    16
          6   |    1     |    18
          7   |    2     |    20
          8   |    1     |    26
          9   |    2     |    28
        """
    )
    return data, queries


def test_update_old():
    data, queries = stream_data()
    result = queries.join(data, pw.left.instance == pw.right.instance).select(
        query=pw.left.value, ans=pw.right.value
    )
    expected = T(
        """
        query | ans
          1   |  9
          2   |  9
          3   |  9
          4   |  9
          5   |  3
          6   |  9
          7   |  3
          8   |  9
          9   |  3
        """
    )
    assert_table_equality_wo_index(result, expected)


@pytest.mark.parametrize("set_id", [True, False])
def test_asof_now_inner(set_id: bool):
    if set_id:
        id = pw.left.id
    else:
        id = None
    data, queries = stream_data()
    result = queries.asof_now_join(
        data, pw.left.instance == pw.right.instance, id=id
    ).select(query=pw.left.value, ans=pw.right.value)
    expected = T(
        """
        query | ans
          2   |  4
          3   |  4
          4   |  5
          5   |  2
          6   |  5
          7   |  2
          8   |  5
          9   |  3
        """
    )
    if set_id:
        assert result._universe.is_subset_of(queries._universe)
    assert_table_equality_wo_index(result, expected)


@pytest.mark.parametrize("set_id", [True, False])
def test_asof_now_left(set_id: bool):
    if set_id:
        id = pw.left.id
    else:
        id = None
    data, queries = stream_data()
    result = queries.asof_now_join_left(
        data, pw.left.instance == pw.right.instance, id=id
    ).select(query=pw.left.value, ans=pw.right.value)
    expected = T(
        """
        query | ans
          1   |
          2   |  4
          3   |  4
          4   |  5
          5   |  2
          6   |  5
          7   |  2
          8   |  5
          9   |  3
        """
    )
    if set_id:
        assert result._universe == queries._universe
    assert_table_equality_wo_index(result, expected)
