"""Monitoring endpoint + runtime stats (reference: src/engine/http_server.rs
OpenMetrics endpoint; ProberStats src/engine/graph.rs:533) and the Flight
Recorder (pathway_tpu/observability): registry semantics, histogram
quantiles, exposition-format conformance of the scraped `/metrics` body,
and the `/debug/*` surfaces."""

import json
import math
import socket
import threading
import urllib.error
import urllib.request

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_pandas


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_runtime_stats_counters():
    t = T(
        """
        v
        1
        2
        3
        """
    )
    res = t.groupby().reduce(total=pw.reducers.sum(t.v))
    table_to_pandas(res)
    rt = pw.internals.parse_graph.G.last_runtime
    assert rt is not None
    s = rt.stats
    assert s.ticks >= 1
    assert sum(s.rows_in.values()) >= 3
    snap = s.snapshot()
    assert snap["rows_in_total"] >= 3
    assert snap["ticks"] >= 1


def test_metrics_http_endpoint():
    from pathway_tpu.engine.nodes import InputNode
    from pathway_tpu.engine.runtime import Runtime, StaticSource
    from pathway_tpu.internals.monitoring_server import start_http_server

    class _Empty(StaticSource):
        def events(self):
            return iter(())

    node = InputNode(_Empty(["a"]), ["a"])
    rt = Runtime([node])
    rt.run_static()
    port = _free_port()
    server = start_http_server(rt, port=port)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode()
        assert "pathway_ticks_total" in body
        assert "pathway_logical_time" in body
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=5
        ) as resp:
            status = json.loads(resp.read().decode())
        assert status["ticks"] >= 1
    finally:
        server.shutdown()


def test_monitoring_server_reused_across_runs_without_pinning_runtime():
    """A second monitored run must re-attach to the existing server on
    the same port (no thread leak, no ephemeral-port fallback serving
    stale stats) and a finished run's graph must stay collectable — the
    handler holds the runtime weakly."""
    import gc
    import weakref

    from pathway_tpu.engine.nodes import InputNode
    from pathway_tpu.engine.runtime import Runtime, StaticSource
    from pathway_tpu.internals import monitoring_server as ms

    class _Empty(StaticSource):
        def events(self):
            return iter(())

    port = _free_port()
    rt1 = Runtime([InputNode(_Empty(["a"]), ["a"])])
    rt1.run_static()
    server = ms.start_http_server(rt1, port=port)
    try:
        rt2 = Runtime([InputNode(_Empty(["a"]), ["a"])])
        rt2.run_static()
        assert ms.start_http_server(rt2, port=port) is server
        ref = weakref.ref(rt1)
        del rt1
        gc.collect()
        assert ref() is None, "monitoring handler pinned a finished run"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=5
        ) as resp:
            status = json.loads(resp.read().decode())
        assert status["ticks"] >= 1  # rt2's stats, served live
    finally:
        server.shutdown()
    # shutdown deregisters AND releases the socket: a fresh start must
    # bind the canonical port again, not fall back to ephemeral
    assert (ms._monitoring_host(), port) not in ms._servers
    fresh = ms.start_http_server(None, port=port)
    try:
        assert fresh is not server
        assert fresh.server_address[1] == port
    finally:
        fresh.shutdown()


def test_process_gauges_and_metrics_endpoint():
    """Process CPU/mem gauges (reference: telemetry.rs:359-416) surface on
    the Prometheus endpoint alongside operator latency and frontier lag."""
    from pathway_tpu.internals.telemetry import process_gauges

    g = process_gauges()
    assert g["process_cpu_seconds_total"] > 0
    assert g["process_memory_rss_bytes"] > 1024 * 1024  # at least 1 MiB

    import pathway_tpu as pw
    from pathway_tpu.internals.monitoring_server import _render_metrics

    class S(pw.Schema):
        v: int

    t = pw.debug.table_from_rows(S, [(1,), (2,)])
    res = t.reduce(s=pw.reducers.sum(t.v))
    pw.debug.table_to_dicts(res)
    rt = pw.internals.parse_graph.G.last_runtime
    body = _render_metrics(rt)
    assert "pathway_process_cpu_seconds_total" in body
    assert "pathway_process_memory_rss_bytes" in body
    assert "pathway_frontier_lag_ms" in body
    assert "pathway_operator_seconds_total" in body


# --- Flight Recorder: registry unit tests --------------------------------


def _registry():
    from pathway_tpu.observability import MetricsRegistry

    return MetricsRegistry()


def test_registry_counter_gauge_semantics():
    reg = _registry()
    c = reg.counter("x_total", "help")
    c.inc()
    c.inc(2.5)
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g", "help")
    g.set(5)
    g.dec(2)
    body = reg.render()
    assert "x_total 3.5" in body
    assert "\ng 3" in body
    # get-or-create is idempotent; a type/label mismatch is an error
    assert reg.counter("x_total", "help") is c
    with pytest.raises(ValueError):
        reg.gauge("x_total", "help")
    with pytest.raises(ValueError):
        reg.counter("x_total", "help", labelnames=("a",))


def test_registry_labels_and_escaping():
    from pathway_tpu.observability import parse_exposition

    reg = _registry()
    c = reg.counter("rows_total", "rows", labelnames=("table",))
    evil = 'my "table"\nwith\\escapes'
    c.labels(evil).inc(7)
    body = reg.render()
    assert "\n" not in body.split("rows_total{")[1].split("}")[0]
    families, errors = parse_exposition(body)
    assert errors == []
    (sample,) = families["rows_total"].samples
    # the parser must round-trip the exact original label value
    assert sample.labels["table"] == evil
    assert sample.value == 7


def test_registry_gauge_function_and_collectors():
    reg = _registry()
    reg.gauge("live", "fn-backed").set_function(lambda: 42.0)
    calls = []
    reg.register_collector(lambda: calls.append(1))

    def boom():
        raise RuntimeError("broken bridge")

    reg.register_collector(boom)  # must not take down the scrape
    body = reg.render()
    assert "live 42" in body
    assert calls == [1]


def test_histogram_buckets_and_quantiles():
    from pathway_tpu.observability import log_linear_buckets

    reg = _registry()
    h = reg.histogram("lat_seconds", "latency", buckets=log_linear_buckets())
    # 100 samples at ~1ms, 5 at ~100ms: p50 lands in the 1ms bucket,
    # p99 in the 100ms one. Log-linear bounds keep relative error small.
    for _ in range(100):
        h.observe(0.001)
    for _ in range(5):
        h.observe(0.1)
    p50 = h.quantile(0.5)
    p99 = h.quantile(0.99)
    assert 0.0005 < p50 < 0.002, p50
    assert 0.05 < p99 < 0.2, p99
    assert h.quantile(0.0) <= p50 <= p99 <= h.quantile(1.0)
    empty = reg.histogram("empty_seconds", "no samples")
    assert math.isnan(empty.quantile(0.5))
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_exposition_shape():
    from pathway_tpu.observability import validate_exposition

    reg = _registry()
    h = reg.histogram(
        "req_seconds", "latency", labelnames=("route",),
        buckets=(0.1, 1.0, 10.0),
    )
    h.labels("/v1/retrieve").observe(0.05)
    h.labels("/v1/retrieve").observe(5.0)
    body = reg.render()
    assert 'req_seconds_bucket{route="/v1/retrieve",le="0.1"} 1' in body
    assert 'req_seconds_bucket{route="/v1/retrieve",le="+Inf"} 2' in body
    assert 'req_seconds_count{route="/v1/retrieve"} 2' in body
    assert validate_exposition(body) == []


def test_registry_histogram_bucket_mismatch_raises():
    reg = _registry()
    h = reg.histogram("h_seconds", "x", buckets=(1.0, 2.0))
    # omitting buckets means "whatever is registered"
    assert reg.histogram("h_seconds", "x") is h
    with pytest.raises(ValueError):
        reg.histogram("h_seconds", "x", buckets=(5.0,))


def test_build_info_placeholder_retired_after_backend_init(monkeypatch):
    from pathway_tpu.observability import jax_metrics

    reg = _registry()
    monkeypatch.setattr(
        jax_metrics, "_backend_if_initialized", lambda: None
    )
    jax_metrics._install_build_info(reg)
    assert 'platform="uninitialized"' in reg.render()

    class FakeDevice:
        platform = "tpu"
        device_kind = "TPU v4"

    monkeypatch.setattr(
        jax_metrics, "_backend_if_initialized", lambda: [FakeDevice()]
    )
    body = reg.render()
    # exactly ONE build_info series, and it is the resolved one
    assert "uninitialized" not in body
    lines = [
        l for l in body.splitlines() if l.startswith("pathway_build_info{")
    ]
    assert len(lines) == 1 and 'platform="tpu"' in lines[0], lines


def test_log_linear_buckets_monotone():
    from pathway_tpu.observability import log_linear_buckets

    bounds = log_linear_buckets()
    assert all(b2 > b1 for b1, b2 in zip(bounds, bounds[1:]))
    assert bounds[0] <= 2e-4  # resolves sub-ms device top-k
    assert bounds[-1] >= 60.0  # and a hung 90s backend init


# --- exposition-format validator -----------------------------------------


def test_validator_catches_violations():
    from pathway_tpu.observability import validate_exposition

    assert validate_exposition(
        "# TYPE a counter\n# TYPE a counter\na_total 1\n"
    )  # duplicate TYPE
    assert validate_exposition("# TYPE b counter\nb 1\n")  # no _total
    assert validate_exposition(
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 5\nh_bucket{le="2"} 3\nh_bucket{le="+Inf"} 5\n'
        "h_sum 1\nh_count 5\n"
    )  # non-monotone buckets
    assert validate_exposition(
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 1\nh_sum 1\nh_count 1\n'
    )  # missing +Inf
    assert validate_exposition("x{bad 1\n")  # malformed sample
    assert validate_exposition("x 1\nx 2\n")  # duplicate sample
    assert validate_exposition("ok_total 1\nother 2.5e-3\n") == []


# --- end-to-end: scrape a live run ---------------------------------------


def _scrape(port: int, path: str = "/metrics") -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as resp:
        return resp.read().decode()


def test_scraped_metrics_pass_validator_with_knn_and_tick_histograms():
    """Acceptance: a scrape during a run exposes _bucket/_sum/_count for
    KNN query latency AND per-operator tick time, and the whole body
    passes the exposition validator."""
    import numpy as np

    from pathway_tpu.debug import table_to_dicts
    from pathway_tpu.internals.monitoring_server import start_http_server
    from pathway_tpu.observability import validate_exposition
    from pathway_tpu.stdlib.indexing import DataIndex, TpuKnn

    class VS(pw.Schema):
        name: str
        vec: np.ndarray

    docs = pw.debug.table_from_rows(
        VS,
        [("a", np.array([1.0, 0.0])), ("b", np.array([0.0, 1.0]))],
    )
    queries = pw.debug.table_from_rows(
        VS, [("q", np.array([1.0, 0.1]))]
    )
    index = DataIndex(docs, TpuKnn(docs.vec, dimensions=2))
    result = index.query_as_of_now(
        queries.vec, number_of_matches=1
    ).select(qname=pw.left.name, names=pw.right.name)
    table_to_dicts(result)

    rt = pw.internals.parse_graph.G.last_runtime
    server = start_http_server(rt, port=_free_port())
    try:
        body = _scrape(server.server_address[1])
    finally:
        server.shutdown()
    for fam in ("pathway_knn_query_seconds", "pathway_operator_tick_seconds"):
        for suffix in ("_bucket", "_sum", "_count"):
            assert f"{fam}{suffix}" in body, f"{fam}{suffix} missing"
    assert "pathway_knn_queries_total" in body
    assert "pathway_build_info" in body
    violations = validate_exposition(body)
    assert violations == [], violations


def test_debug_threads_endpoint_lists_every_live_thread():
    from pathway_tpu.internals.monitoring_server import start_http_server

    ready = threading.Event()
    done = threading.Event()

    def parked():
        ready.set()
        done.wait(30)

    t = threading.Thread(target=parked, name="flight-recorder-probe")
    t.start()
    ready.wait(5)
    server = start_http_server(None, port=_free_port())
    try:
        dump = _scrape(server.server_address[1], "/debug/threads")
    finally:
        done.set()
        server.shutdown()
        t.join(5)
    for thread in threading.enumerate():
        if thread.ident is not None and thread is not t:
            assert f"ident={thread.ident}" in dump
    assert "'flight-recorder-probe'" in dump
    assert "in parked" in dump  # the dump shows WHERE it is parked


def test_debug_graph_endpoint():
    from pathway_tpu.internals.monitoring_server import start_http_server

    t = T(
        """
        v
        1
        2
        """
    )
    res = t.groupby().reduce(total=pw.reducers.sum(t.v))
    table_to_pandas(res)
    rt = pw.internals.parse_graph.G.last_runtime
    server = start_http_server(rt, port=_free_port())
    try:
        rows = json.loads(_scrape(server.server_address[1], "/debug/graph"))
    finally:
        server.shutdown()
    assert len(rows) == len(rt.order)
    for row in rows:
        assert {"id", "name", "type", "rows", "ns", "backlog"} <= set(row)
    # standalone mode (no runtime) serves an empty table, not a 500
    server = start_http_server(None, port=_free_port())
    try:
        assert json.loads(
            _scrape(server.server_address[1], "/debug/graph")
        ) == []
    finally:
        server.shutdown()


def test_debug_profile_501_when_profiler_unavailable(monkeypatch):
    from pathway_tpu.internals.monitoring_server import start_http_server
    from pathway_tpu.observability import debug as obs_debug

    monkeypatch.setattr(obs_debug, "_get_profiler", lambda: None)
    server = start_http_server(None, port=_free_port())
    port = server.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _scrape(port, "/debug/profile?seconds=0.1")
        assert exc_info.value.code == 501
        # bad duration is a 400 regardless of profiler availability
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _scrape(port, "/debug/profile?seconds=abc")
        assert exc_info.value.code == 400
    finally:
        server.shutdown()


def test_debug_profile_writes_trace_when_available():
    import os

    from pathway_tpu.internals.monitoring_server import start_http_server
    from pathway_tpu.observability.debug import _get_profiler

    if _get_profiler() is None:
        pytest.skip("jax profiler unavailable in this environment")
    server = start_http_server(None, port=_free_port())
    try:
        out = json.loads(
            _scrape(server.server_address[1], "/debug/profile?seconds=0.1")
        )
    finally:
        server.shutdown()
    assert os.path.isdir(out["trace_dir"])


# --- monitoring server bind host / port fallback -------------------------


def test_monitoring_host_env(monkeypatch):
    from pathway_tpu.internals import monitoring_server

    monkeypatch.setenv("PATHWAY_MONITORING_HOST", "0.0.0.0")
    assert monitoring_server._monitoring_host() == "0.0.0.0"
    monkeypatch.delenv("PATHWAY_MONITORING_HOST")
    assert monitoring_server._monitoring_host() == "127.0.0.1"


def test_port_conflict_falls_back_to_ephemeral(caplog):
    """A port held by a FOREIGN process falls back to ephemeral with a
    warning; this process's own server on that port is reused instead
    (no per-run server leak)."""
    import logging

    from pathway_tpu.internals.monitoring_server import start_http_server

    blocker = socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    taken = blocker.getsockname()[1]
    try:
        with caplog.at_level(logging.WARNING, logger="pathway_tpu"):
            second = start_http_server(None, port=taken)
        try:
            actual = second.server_address[1]
            assert actual != taken
            assert any(
                "ephemeral" in rec.message for rec in caplog.records
            )
            assert "pathway_build_info" in _scrape(actual)
            # same requested port from THIS process: reuse, not another
            # fallback server
            assert start_http_server(None, port=taken) is second
        finally:
            second.shutdown()
    finally:
        blocker.close()
