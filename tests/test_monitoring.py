"""Monitoring endpoint + runtime stats (reference: src/engine/http_server.rs
OpenMetrics endpoint; ProberStats src/engine/graph.rs:533)."""

import json
import socket
import urllib.request

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_pandas


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_runtime_stats_counters():
    t = T(
        """
        v
        1
        2
        3
        """
    )
    res = t.groupby().reduce(total=pw.reducers.sum(t.v))
    table_to_pandas(res)
    rt = pw.internals.parse_graph.G.last_runtime
    assert rt is not None
    s = rt.stats
    assert s.ticks >= 1
    assert sum(s.rows_in.values()) >= 3
    snap = s.snapshot()
    assert snap["rows_in_total"] >= 3
    assert snap["ticks"] >= 1


def test_metrics_http_endpoint():
    from pathway_tpu.engine.nodes import InputNode
    from pathway_tpu.engine.runtime import Runtime, StaticSource
    from pathway_tpu.internals.monitoring_server import start_http_server

    class _Empty(StaticSource):
        def events(self):
            return iter(())

    node = InputNode(_Empty(["a"]), ["a"])
    rt = Runtime([node])
    rt.run_static()
    port = _free_port()
    server = start_http_server(rt, port=port)
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as resp:
            body = resp.read().decode()
        assert "pathway_ticks_total" in body
        assert "pathway_logical_time" in body
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=5
        ) as resp:
            status = json.loads(resp.read().decode())
        assert status["ticks"] >= 1
    finally:
        server.shutdown()


def test_process_gauges_and_metrics_endpoint():
    """Process CPU/mem gauges (reference: telemetry.rs:359-416) surface on
    the Prometheus endpoint alongside operator latency and frontier lag."""
    from pathway_tpu.internals.telemetry import process_gauges

    g = process_gauges()
    assert g["process_cpu_seconds_total"] > 0
    assert g["process_memory_rss_bytes"] > 1024 * 1024  # at least 1 MiB

    import pathway_tpu as pw
    from pathway_tpu.internals.monitoring_server import _render_metrics

    class S(pw.Schema):
        v: int

    t = pw.debug.table_from_rows(S, [(1,), (2,)])
    res = t.reduce(s=pw.reducers.sum(t.v))
    pw.debug.table_to_dicts(res)
    rt = pw.internals.parse_graph.G.last_runtime
    body = _render_metrics(rt)
    assert "pathway_process_cpu_seconds_total" in body
    assert "pathway_process_memory_rss_bytes" in body
    assert "pathway_frontier_lag_ms" in body
    assert "pathway_operator_seconds_total" in body
