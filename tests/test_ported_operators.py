"""Ported reference operator tests
(reference: python/pathway/tests/test_operators.py) — per-dtype binary and
unary operator semantics checked against pandas: bool/int/float incl.
division-by-zero errors and pow/shift, strings, pointers (total order),
durations (incl. div-by-zero and int/float scaling), datetimes (naive and
UTC) and datetime-duration arithmetic, matrix multiplication over ndarray
cells, optional comparisons, and tuple comparisons with type gating."""

from __future__ import annotations

import copy
import datetime
import operator
import re
from collections.abc import Callable, Mapping
from typing import Any

import numpy as np
import pandas as pd
import pytest
from dateutil import tz

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.debug import table_from_pandas, table_to_pandas

from tests.ref_utils import assert_table_equality, run_all


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    from pathway_tpu.internals.errors import clear_errors

    clear_errors()
    yield
    pw.internals.parse_graph.G.clear()


@pytest.mark.parametrize(
    "op_fun,data",
    [
        (operator.not_, [False, True]),
        (
            operator.neg,
            [
                -2, -1, 0, 1, 2, 3, 4, 5,
                90623803388717388, 88814567067209860, -2502820103020854,
            ],
        ),
        (
            operator.neg,
            [
                -2, -1, 0, 1, 2, 3, 4, 5,
                90623803388717388, 88814567067209860, -2502820103020854,
                0.69213224, -0.04078913, 0.37567623, -0.53781894, 0.71950524,
            ],
        ),
        (
            operator.neg,
            [
                pd.Timedelta(0),
                pd.Timedelta(-1),
                pd.Timedelta(-2),
                pd.Timedelta(1),
                pd.Timedelta(2),
                pd.Timedelta(milliseconds=3),
                pd.Timedelta(seconds=-2),
                pd.Timedelta(days=24),
                pd.Timedelta(weeks=-13),
                pd.Timedelta(-560647988758320624),
                pd.Timedelta(21569578082613316),
            ],
        ),
    ],
)
def test_unary(op_fun: Callable, data: list[Any]) -> None:
    if isinstance(data[0], pd.Timedelta):
        # pandas 3 infers [us] resolution for Timedelta lists (lossy)
        df = pd.DataFrame({"a": pd.Series(data, dtype="timedelta64[ns]")})
    else:
        df = pd.DataFrame({"a": data})
    table = table_from_pandas(df)
    if op_fun == operator.not_:
        table_pw = table.select(c=~table.a)
        df_new = pd.DataFrame({"c": ~df["a"]})
    else:
        table_pw = table.select(c=op_fun(table.a))
        df_new = pd.DataFrame({"c": op_fun(df["a"])})
    table_pd = table_from_pandas(df_new)
    assert_table_equality(table_pw, table_pd)


def _check_pandas_pathway_return_the_same(
    df: pd.DataFrame,
    op_fun: Any,
    dtypes: Mapping[str, type] = {},
    res_dtype: type | None = None,
):
    table = table_from_pandas(copy.deepcopy(df))
    table = table.update_types(**dtypes)
    table_pw = table.select(
        pw.this.a, pw.this.b, c=op_fun(pw.this.a, pw.this.b)
    )
    df["c"] = op_fun(df["a"], df["b"])
    table_pd = table_from_pandas(df)
    if res_dtype:
        table_pd = table_pd.update_types(c=res_dtype)
    assert_table_equality(table_pw, table_pd)


@pytest.mark.parametrize("op_fun", [operator.and_, operator.or_, operator.xor])
def test_bool(op_fun: Any):
    df = pd.DataFrame(
        {"a": [False, False, True, True], "b": [False, True, False, True]}
    )
    _check_pandas_pathway_return_the_same(df, op_fun)


_INT_PAIRS = np.array(
    [
        [-2, 0], [-1, 3], [0, 1], [1, 10], [2, -9], [3, 8], [4, -7], [5, 6],
        [-331399, -227463], [253173, -207184], [-741012, -856821],
        [-935893, 341112], [-284786, -559808], [825347, 802488],
        [-778696, 740473], [-763723, 431098], [-980333, 562122],
        [12035, 846654], [490378, -106109], [-93465, -348397],
        [262849, -473516], [908064, 450927], [217134, 217134], [10, 10],
        [-10, -3], [-10, 3], [10, -3], [10, 3],
    ],
    dtype=np.int64,
)


@pytest.mark.parametrize(
    "op_fun",
    [
        operator.eq, operator.ne, operator.lt, operator.le, operator.gt,
        operator.ge, operator.add, operator.sub, operator.mul,
        operator.floordiv, operator.truediv, operator.mod,
        operator.and_, operator.or_, operator.xor,
    ],
)
def test_int(op_fun: Any):
    pairs = _INT_PAIRS
    df = pd.DataFrame({"a": pairs[:, 0], "b": pairs[:, 1]})
    if op_fun in (operator.floordiv, operator.truediv, operator.mod):
        df.loc[df["b"] == 0, "b"] = 1
    _check_pandas_pathway_return_the_same(df, op_fun)


@pytest.mark.parametrize(
    "op_fun",
    [operator.floordiv, operator.truediv, operator.mod],
)
def test_int_div_zero(op_fun: Any):
    pairs = np.array(
        [[1, 0], [10000, 0], [-1, 0], [0, 0], [-9829480, 0]]
    ).reshape(-1, 2, 1)
    for pair in pairs:
        pw.internals.parse_graph.G.clear()
        from pathway_tpu.internals.errors import clear_errors

        clear_errors()
        df = pd.DataFrame({"a": pair[0], "b": pair[1]})
        table = table_from_pandas(df)
        table.select(c=op_fun(pw.this["a"], pw.this["b"]))

        with pytest.raises(ZeroDivisionError):
            run_all()


@pytest.mark.parametrize(
    "op_fun",
    [operator.pow, operator.lshift, operator.rshift],
)
def test_int_pow_shift(op_fun: Any):
    pairs = np.array(
        [
            [0, 1], [0, 2], [0, 63], [1, 0], [1, 1], [1, 2], [1, 3],
            [1, 62], [2, 0], [2, 1], [2, 2], [2, 61], [3, 0], [3, 1],
            [3, 2], [3, 39], [4, 0], [4, 1], [4, 31], [9, 18], [10, 18],
            [14, 16], [23, 13], [-1, 0], [-1, 1], [-1, 2], [-1, 3],
            [-1, 62], [-1, 63], [-2, 0], [-2, 1], [-2, 2],
        ],
        dtype=np.int64,
    )
    df = pd.DataFrame({"a": pairs[:, 0], "b": pairs[:, 1]})
    table = table_from_pandas(df)
    table_pw = table.select(c=op_fun(pw.this["a"], pw.this["b"]))
    result = op_fun(pairs[:, 0], pairs[:, 1])
    df_new = pd.DataFrame({"c": result})
    table_pd = table_from_pandas(df_new)
    assert_table_equality(table_pw, table_pd)


_FLOAT_PAIRS = np.array(
    [
        [-2, 0], [-1, 3], [0, 1], [1, 10], [2, -9], [3, 8], [4, -7],
        [5, 6], [-331399, -227463], [253173, -207184], [-741012, -856821],
        [-935893, 341112], [-284786, -559808], [825347, 802488],
        [-778696, 740473], [-0.691, 0.72], [-0.411, -0.541],
        [0.623, -0.452], [0.16, 0.93], [0.5, -0.5], [2.5, 2.5],
    ],
    dtype=np.float64,
)


@pytest.mark.parametrize(
    "op_fun",
    [
        operator.eq, operator.ne, operator.lt, operator.le, operator.gt,
        operator.ge, operator.add, operator.sub, operator.mul,
        operator.floordiv, operator.truediv, operator.mod,
    ],
)
def test_float(op_fun: Any):
    pairs = _FLOAT_PAIRS.copy()
    df = pd.DataFrame({"a": pairs[:, 0], "b": pairs[:, 1]})
    if op_fun in (operator.floordiv, operator.truediv, operator.mod):
        df.loc[df["b"] == 0, "b"] = 1
    _check_pandas_pathway_return_the_same(df, op_fun)


@pytest.mark.parametrize(
    "op_fun",
    [operator.floordiv, operator.truediv, operator.mod],
)
def test_float_div_zero(op_fun: Any):
    for a in [1.0, -1.0, 0.0, 1e30]:
        pw.internals.parse_graph.G.clear()
        from pathway_tpu.internals.errors import clear_errors

        clear_errors()
        df = pd.DataFrame({"a": [a], "b": [0.0]})
        table = table_from_pandas(df)
        table.select(c=op_fun(pw.this["a"], pw.this["b"]))

        with pytest.raises(ZeroDivisionError):
            run_all()


@pytest.mark.parametrize("reverse", [True, False])
@pytest.mark.parametrize(
    "op_fun",
    [
        operator.eq, operator.ne, operator.lt, operator.le, operator.gt,
        operator.ge, operator.add, operator.sub, operator.mul,
        operator.floordiv, operator.truediv, operator.mod,
    ],
)
def test_mixed_int_float(op_fun: Any, reverse: bool):
    n = min(len(_INT_PAIRS), len(_FLOAT_PAIRS))
    ints = _INT_PAIRS[:n, 0].astype(np.int64)
    floats = _FLOAT_PAIRS[:n, 1].astype(np.float64)
    floats = np.where(floats == 0, 1.0, floats)
    if reverse:
        df = pd.DataFrame({"a": floats, "b": ints})
        if op_fun in (operator.floordiv, operator.truediv, operator.mod):
            df.loc[df["b"] == 0, "b"] = 1
    else:
        df = pd.DataFrame({"a": ints, "b": floats})
    _check_pandas_pathway_return_the_same(df, op_fun)


@pytest.mark.parametrize(
    "op_fun",
    [operator.eq, operator.ne, operator.lt, operator.le, operator.gt,
     operator.ge, operator.add],
)
def test_string(op_fun: Any):
    df = pd.DataFrame(
        {
            "a": ["", "abc", "defg", "defg", "zzz", "ą", "ź"],
            "b": ["abc", "", "defg", "xyz", "zz", "ą", "ś"],
        }
    )
    _check_pandas_pathway_return_the_same(df, op_fun)


@pytest.mark.parametrize("reverse_columns", [True, False])
def test_string_mul(reverse_columns: bool):
    df = pd.DataFrame(
        {
            "a": ["", "abc", "defg", "zzz"],
            "b": [3, 0, -1, 2],
        }
    )
    if reverse_columns:
        df = pd.DataFrame({"a": df["b"], "b": df["a"]})
    _check_pandas_pathway_return_the_same(df, operator.mul)


def test_pointer_eq():
    t = T(
        """
       | true_id | false_id
    1  | 1       |  2
    2  | 2       |  3
    3  | 3       |  1
    """
    )
    t = t.select(
        *pw.this,
        true_pointer=pw.this.pointer_from(t.true_id),
        false_pointer=pw.this.pointer_from(t.false_id),
    )
    res = t.select(
        a=(t.id == t.true_pointer),
        b=(t.id == t.false_pointer),
        c=(t.id != t.true_pointer),
        d=(t.id != t.false_pointer),
    )
    expected = T(
        """
       |   a  |   b   |   c   |   d
    1  | True | False | False | True
    2  | True | False | False | True
    3  | True | False | False | True
    """
    )
    assert_table_equality(res, expected)


def test_pointer_order():
    t = T(
        """
    ptrA | ptrB | ptrC
       1 |   11 |   21
       2 |   12 |   22
       3 |   13 |   23
       4 |   14 |   24
       5 |   15 |   25
    """
    ).with_columns(
        ptrA=pw.this.pointer_from(pw.this.ptrA),
        ptrB=pw.this.pointer_from(pw.this.ptrB),
        ptrC=pw.this.pointer_from(pw.this.ptrC),
    )
    res = t.select(
        a1=(t.ptrA < t.ptrB) == (t.ptrA <= t.ptrB),
        a2=(t.ptrA > t.ptrB) == (t.ptrA >= t.ptrB),
        a3=(t.ptrB < t.ptrC) == (t.ptrB <= t.ptrC),
        a4=(t.ptrB > t.ptrC) == (t.ptrB >= t.ptrC),
        a5=(t.ptrA < t.ptrC) == (t.ptrA <= t.ptrC),
        a6=(t.ptrA > t.ptrC) == (t.ptrA >= t.ptrC),
        b1=(t.ptrA < t.ptrB) != (t.ptrA > t.ptrB),
        b2=(t.ptrA < t.ptrC) != (t.ptrA > t.ptrC),
        b3=(t.ptrB < t.ptrC) != (t.ptrB > t.ptrC),
        # <= below on bools is -> implies
        c1=((t.ptrA < t.ptrB) & (t.ptrB < t.ptrC)) <= (t.ptrA < t.ptrC),
        c2=((t.ptrA < t.ptrC) & (t.ptrC < t.ptrB)) <= (t.ptrA < t.ptrB),
        c3=((t.ptrB < t.ptrA) & (t.ptrA < t.ptrC)) <= (t.ptrB < t.ptrC),
        c4=((t.ptrB < t.ptrC) & (t.ptrC < t.ptrA)) <= (t.ptrB < t.ptrA),
        c5=((t.ptrC < t.ptrA) & (t.ptrA < t.ptrB)) <= (t.ptrC < t.ptrB),
        c6=((t.ptrC < t.ptrB) & (t.ptrB < t.ptrA)) <= (t.ptrC < t.ptrA),
    )

    expected = t.select(
        a1=True, a2=True, a3=True, a4=True, a5=True, a6=True,
        b1=True, b2=True, b3=True,
        c1=True, c2=True, c3=True, c4=True, c5=True, c6=True,
    )
    assert_table_equality(res, expected)


_DURATION_PAIRS = [
    [pd.Timedelta(0), pd.Timedelta(0)],
    [pd.Timedelta(1), pd.Timedelta(0)],
    [pd.Timedelta(0), pd.Timedelta(1)],
    [pd.Timedelta(2), pd.Timedelta(1)],
    [pd.Timedelta(2), pd.Timedelta(0)],
    [pd.Timedelta(2), pd.Timedelta(-1)],
    [pd.Timedelta(-2), pd.Timedelta(-2)],
    [pd.Timedelta(-331399), pd.Timedelta(-227463)],
    [pd.Timedelta(253173), pd.Timedelta(-207184)],
    [pd.Timedelta(-741012), pd.Timedelta(-856821)],
    [pd.Timedelta(-935893), pd.Timedelta(341112)],
    [pd.Timedelta(-284786), pd.Timedelta(-559808)],
    [pd.Timedelta(825347), pd.Timedelta(802488)],
    [pd.Timedelta(-778696), pd.Timedelta(740473)],
    [pd.Timedelta(-763723), pd.Timedelta(431098)],
    [pd.Timedelta(-980333), pd.Timedelta(562122)],
    [pd.Timedelta(milliseconds=1), pd.Timedelta(milliseconds=2)],
    [pd.Timedelta(milliseconds=-2), pd.Timedelta(milliseconds=3)],
    [pd.Timedelta(seconds=1), pd.Timedelta(seconds=2)],
    [pd.Timedelta(seconds=-2), pd.Timedelta(seconds=3)],
    [pd.Timedelta(minutes=1), pd.Timedelta(minutes=2)],
    [pd.Timedelta(minutes=-2), pd.Timedelta(minutes=3)],
    [pd.Timedelta(hours=1), pd.Timedelta(hours=2)],
    [pd.Timedelta(hours=-2), pd.Timedelta(hours=3)],
    [pd.Timedelta(days=1), pd.Timedelta(days=2)],
    [pd.Timedelta(days=-2), pd.Timedelta(days=3)],
    [pd.Timedelta(weeks=1), pd.Timedelta(weeks=2)],
    [pd.Timedelta(weeks=-2), pd.Timedelta(weeks=3)],
    [pd.Timedelta(weeks=1), pd.Timedelta(seconds=2)],
    [pd.Timedelta(weeks=-2), pd.Timedelta(seconds=3)],
]


@pytest.mark.parametrize(
    "op_fun",
    [
        operator.eq, operator.ne, operator.lt, operator.le, operator.gt,
        operator.ge, operator.add, operator.sub, operator.floordiv,
        operator.truediv, operator.mod,
    ],
)
def test_duration(op_fun: Any) -> None:
    pairs_T = list(zip(*_DURATION_PAIRS))
    df = pd.DataFrame({"a": pairs_T[0], "b": pairs_T[1]})
    if op_fun in (operator.floordiv, operator.truediv, operator.mod):
        df.loc[df["b"] == pd.Timedelta(0), "b"] = pd.Timedelta(1)
    _check_pandas_pathway_return_the_same(df, op_fun)


@pytest.mark.parametrize(
    "op_fun",
    [operator.floordiv, operator.truediv, operator.mod],
)
def test_duration_div_zero(op_fun: Any) -> None:
    pairs = [
        [pd.Timedelta(-763723)],
        [pd.Timedelta(-980333)],
        [pd.Timedelta(milliseconds=1)],
    ]
    for pair in pairs:
        pw.internals.parse_graph.G.clear()
        from pathway_tpu.internals.errors import clear_errors

        clear_errors()
        df = pd.DataFrame({"a": [pair[0]], "b": [pd.Timedelta(0)]})
        table = table_from_pandas(df)
        table.select(c=op_fun(pw.this["a"], pw.this["b"]))
        with pytest.raises(ZeroDivisionError):
            run_all()


@pytest.mark.parametrize("is_naive", [True, False])
@pytest.mark.parametrize(
    "op_fun",
    [
        operator.eq, operator.ne, operator.lt, operator.le, operator.gt,
        operator.ge, operator.sub,
    ],
)
def test_date_time(op_fun: Any, is_naive: bool) -> None:
    pairs = [
        ["1960-02-03 08:00:00.000000000", "2023-03-25 16:43:21.123456789"],
        ["2008-02-29 08:00:00.000000000", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 12:00:00.000000000", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 12:00:00.000000001", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 12:00:00.123456789", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 16:43:21.123456788", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 16:43:21.123456789", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 17:00:01.987000000", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 18:43:21.123456789", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 22:59:59.999999999", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 23:00:00.000000001", "2023-03-25 16:43:21.123456789"],
        ["2023-03-25 23:59:59.999999999", "2023-03-25 16:43:21.123456789"],
        ["2023-03-26 00:00:00.000000001", "2023-03-25 16:43:21.123456789"],
        ["2023-03-26 12:00:00.000000001", "2023-03-25 16:43:21.123456789"],
        ["2123-03-26 12:00:00.000000001", "2023-03-25 16:43:21.123456789"],
        ["2123-03-31 23:00:00.000000001", "2023-03-25 16:43:21.123456789"],
    ]
    fmt = "%Y-%m-%d %H:%M:%S.%f"
    if not is_naive:
        fmt += "%z"
        pairs = [[a + "+01:30", b + "-00:30"] for a, b in pairs]
    pairs_T = list(zip(*pairs))
    df = pd.DataFrame(
        {
            "a": pd.to_datetime(pairs_T[0], format=fmt),
            "b": pd.to_datetime(pairs_T[1], format=fmt),
        }
    )
    _check_pandas_pathway_return_the_same(df, op_fun)


@pytest.mark.parametrize("is_naive", [True, False])
@pytest.mark.parametrize("op_fun", [operator.add, operator.sub])
def test_date_time_and_duration(op_fun: Any, is_naive: bool) -> None:
    pairs = [
        ["1960-02-03 08:00:00.000000000", pd.Timedelta(-1)],
        ["2008-02-29 08:00:00.000000000", pd.Timedelta(1)],
        ["2023-03-25 12:00:00.000000000", pd.Timedelta(825347)],
        ["2023-03-25 12:00:00.000000001", pd.Timedelta(249333862623082067)],
        ["2023-03-25 12:00:00.123456789", pd.Timedelta(-462593511970998050)],
        ["2023-03-25 16:43:21.123456788", pd.Timedelta(days=3)],
        ["2023-03-25 16:43:21.123456789", pd.Timedelta(hours=20)],
        ["2023-03-25 17:00:01.987000000", pd.Timedelta(weeks=12)],
        ["2023-03-25 18:43:21.123456789", pd.Timedelta(days=-10)],
        ["2023-03-25 22:59:59.999999999", pd.Timedelta(hours=-34)],
        ["2023-03-25 23:00:00.000000001", pd.Timedelta(minutes=-3)],
        ["2023-03-25 23:59:59.999999999", pd.Timedelta(1)],
        ["2023-03-26 00:00:00.000000001", pd.Timedelta(-1345)],
        ["2023-03-26 01:59:59.999999999", pd.Timedelta(hours=-1)],
        ["2023-03-26 01:59:59.999999999", pd.Timedelta(-2)],
        ["2023-03-26 01:59:59.999999999", pd.Timedelta(-1)],
        ["2023-03-26 01:59:59.999999999", pd.Timedelta(1)],
        ["2023-03-26 01:59:59.999999999", pd.Timedelta(2)],
        ["2023-03-26 01:59:59.999999999", pd.Timedelta(hours=1)],
        ["2023-03-26 03:00:00.000000001", pd.Timedelta(hours=-1)],
        ["2023-03-26 03:00:00.000000001", pd.Timedelta(-2)],
        ["2023-03-26 03:00:00.000000001", pd.Timedelta(-1)],
        ["2023-03-26 03:00:00.000000001", pd.Timedelta(1)],
        ["2023-03-26 03:00:00.000000001", pd.Timedelta(1)],
        ["2023-03-26 03:00:00.000000001", pd.Timedelta(hours=1)],
        ["2023-03-26 12:00:00.000000001", pd.Timedelta(seconds=1)],
        ["2123-03-26 12:00:00.000000001", pd.Timedelta(seconds=-971716231)],
        ["2123-03-31 23:00:00.000000001", pd.Timedelta(0)],
    ]
    fmt = "%Y-%m-%d %H:%M:%S.%f"
    pairs_T = list(zip(*pairs))
    df = pd.DataFrame(
        {
            "a": pd.to_datetime(pairs_T[0], format=fmt),
            "b": pairs_T[1],
        }
    )
    if not is_naive:
        df["a"] = df["a"].dt.tz_localize(tz.UTC)
    _check_pandas_pathway_return_the_same(df, op_fun)
    if op_fun == operator.add:
        df["a"], df["b"] = df["b"], df["a"]
        del df["c"]
        _check_pandas_pathway_return_the_same(df, op_fun)


@pytest.mark.parametrize(
    "op_fun,dtype",
    [
        (operator.mul, int),
        (operator.floordiv, int),
        (operator.truediv, int),
        (operator.mul, float),
        (operator.truediv, float),
    ],
)
def test_duration_and_int(op_fun: Any, dtype: Any) -> None:
    pairs = [
        [pd.Timedelta(0), 0],
        [pd.Timedelta(1), 0],
        [pd.Timedelta(0), 1],
        [pd.Timedelta(2), 1],
        [pd.Timedelta(2), 0],
        [pd.Timedelta(2), -1],
        [pd.Timedelta(-2), -2],
        [pd.Timedelta(10), 3],
        [pd.Timedelta(10), -3],
        [pd.Timedelta(-10), 3],
        [pd.Timedelta(-10), -3],
        [pd.Timedelta(11), 3],
        [pd.Timedelta(11), -3],
        [pd.Timedelta(-11), 3],
        [pd.Timedelta(-11), -3],
        [pd.Timedelta(-331399), -227463],
        [pd.Timedelta(253173), -207184],
        [pd.Timedelta(-741012), -856821],
        [pd.Timedelta(-935893), 341112],
        [pd.Timedelta(-284786), -559808],
        [pd.Timedelta(825347), 802488],
        [pd.Timedelta(-778696), 740473],
        [pd.Timedelta(-763723), 431098],
        [pd.Timedelta(-980333), 562122],
        [pd.Timedelta(milliseconds=1), -96],
        [pd.Timedelta(milliseconds=-2), 88],
        [pd.Timedelta(seconds=1), -3],
        [pd.Timedelta(seconds=-2), -60],
        [pd.Timedelta(minutes=1), 54],
        [pd.Timedelta(minutes=-2), 44],
        [pd.Timedelta(hours=1), -31],
        [pd.Timedelta(hours=-2), 60],
        [pd.Timedelta(days=1), -91],
        [pd.Timedelta(days=-2), 28],
        [pd.Timedelta(weeks=1), -90],
        [pd.Timedelta(weeks=-2), -65],
        [pd.Timedelta(weeks=1), 10],
        [pd.Timedelta(weeks=-2), -45],
    ]
    if op_fun in {operator.floordiv, operator.truediv}:
        pairs = [[a, b if b != 0 else 1] for a, b in pairs]
    # explicit ns resolution: pandas 3 infers timedelta64[us] for python
    # Timedelta lists and silently truncates sub-microsecond components
    expected = table_from_pandas(
        pd.DataFrame(
            {
                "c": pd.Series(
                    # as_unit("ns"): kwarg-built Timedelta scalars carry us
                    # resolution on pandas 3 while the input COLUMN infers
                    # ns (sub-us pairs force it) — normalize so both sides
                    # compute at ns
                    [op_fun(a.as_unit("ns"), dtype(b)) for a, b in pairs],
                    dtype="timedelta64[ns]",
                )
            }
        )
    )
    pairs_T = list(zip(*pairs))
    df = pd.DataFrame({"a": pairs_T[0], "b": pairs_T[1]})
    df["b"] = df["b"].astype(dtype)
    table = table_from_pandas(df)
    result = table.select(c=op_fun(table.a, table.b))
    assert_table_equality(result, expected)
    if op_fun == operator.mul:
        result_2 = table.select(c=op_fun(table.b, table.a))
        assert_table_equality(result_2, expected)


def test_duration_and_div_zero() -> None:
    pairs = [
        [pd.Timedelta(-763723)],
        [pd.Timedelta(-980333)],
        [pd.Timedelta(milliseconds=1)],
    ]
    for pair in pairs:
        pw.internals.parse_graph.G.clear()
        from pathway_tpu.internals.errors import clear_errors

        clear_errors()
        df = pd.DataFrame({"a": [pair[0]], "b": [0]})
        table = table_from_pandas(df)
        table.select(c=pw.this["a"] // pw.this["b"])
        with pytest.raises(ZeroDivisionError):
            run_all()


@pytest.mark.parametrize(
    "const",
    [
        datetime.datetime(2023, 5, 15, 10, 51),
        pd.Timestamp(2023, 5, 15, 10, 51),
        datetime.timedelta(days=1),
        pd.Timedelta(days=1),
    ],
)
def test_datetime_naive_sub_const(const: Any) -> None:
    datetimes = [
        "2023-05-15 01:59:59.999999999",
        "2023-05-15 11:59:59.999999999",
    ]
    fmt = "%Y-%m-%d %H:%M:%S.%f"
    df = pd.DataFrame({"a": datetimes})
    table = table_from_pandas(df)
    table_with_dt = table.select(a=table.a.dt.strptime(fmt))
    table_pw = table_with_dt.select(a=table_with_dt.a - const)
    df_new = pd.DataFrame(
        {"a": pd.to_datetime(datetimes, format=fmt) - const}
    )
    table_pd = table_from_pandas(df_new)
    assert_table_equality(table_pw, table_pd)


@pytest.mark.parametrize(
    "const",
    [
        datetime.datetime(2023, 5, 15, 10, 51, tzinfo=tz.UTC),
        datetime.datetime(
            2023, 5, 15, 10, 51, tzinfo=tz.gettz("America/New_York")
        ),
        datetime.datetime(
            2023, 5, 15, 10, 51, tzinfo=tz.gettz("Europe/Warsaw")
        ),
        pd.Timestamp(2023, 5, 15, 10, 51).tz_localize(tz.UTC),
        pd.Timestamp(2023, 5, 15, 10, 51).tz_localize("America/New_York"),
        pd.Timestamp(2023, 5, 15, 10, 51).tz_localize("Europe/Warsaw"),
        datetime.timedelta(days=1),
        datetime.timedelta(microseconds=1),
        datetime.timedelta(seconds=1),
        datetime.timedelta(minutes=1),
        datetime.timedelta(hours=1),
        datetime.timedelta(weeks=1),
        pd.Timedelta(days=1),
        pd.Timedelta(milliseconds=1),
    ],
)
def test_datetime_utc_sub_const(const: Any) -> None:
    datetimes = [
        "2023-05-15 01:59:59.999999999-02:00",
        "2023-05-15 11:59:59.999999999-02:00",
        "2023-05-15 12:51:00.000000000-02:00",
    ]
    fmt = "%Y-%m-%d %H:%M:%S.%f%z"
    df = pd.DataFrame({"a": datetimes})
    table = table_from_pandas(df)
    table_with_dt = table.select(a=table.a.dt.strptime(fmt))
    table_pw = table_with_dt.select(a=table_with_dt.a - const)
    df_new = pd.DataFrame(
        {"a": pd.to_datetime(datetimes, format=fmt) - const}
    )
    table_pd = table_from_pandas(df_new)
    assert_table_equality(table_pw, table_pd)


def run_matrix_multiplcation(
    pairs: list[tuple[np.ndarray, np.ndarray]], dtype: type
) -> None:
    pairs_T = list(zip(*pairs))
    a = [a_i.astype(dtype) for a_i in pairs_T[0]]
    b = [b_i.astype(dtype) for b_i in pairs_T[1]]
    t = table_from_pandas(
        pd.DataFrame({"a": a, "b": b, "i": list(range(len(a)))})
    )
    res = t.select(pw.this.i, c=t.a @ t.b)
    res_pd = table_to_pandas(res).sort_values(by="i")["c"]
    expected = [a_i @ b_i for a_i, b_i in zip(a, b)]
    for res_i, exp_i in zip(res_pd, expected):
        # 1d@1d yields a scalar; normalize so .shape exists on both sides
        res_i, exp_i = np.asarray(res_i), np.asarray(exp_i)
        if dtype == float:
            assert np.isclose(res_i, exp_i, rtol=1e-15, atol=0.0).all()
        else:
            assert (res_i == exp_i).all()
        assert res_i.shape == exp_i.shape


@pytest.mark.parametrize("dtype", [int, float])
def test_matrix_multiplication_2d_by_2d(dtype: type) -> None:
    np.random.seed(42)
    r = np.random.randn
    pairs: list[tuple[np.ndarray, np.ndarray]] = [
        (r(3, 3), r(3, 3)),
        (r(4, 2), r(2, 3)),
        (r(4, 1), r(1, 4)),
        (r(1, 3), r(3, 1)),
        (r(0, 4), r(4, 5)),
        (r(0, 0), r(0, 1)),
        (r(0, 0), r(0, 0)),
        (r(0, 2), r(2, 0)),
        (np.array([[1, 2], [3, 4], [5, 6]]), np.array([[1, 2], [3, 4]])),
    ]
    run_matrix_multiplcation(pairs, dtype)


@pytest.mark.parametrize("dtype", [int, float])
def test_matrix_multiplication_2d_by_1d(dtype: type) -> None:
    np.random.seed(42)
    r = np.random.randn
    pairs: list[tuple[np.ndarray, np.ndarray]] = [
        (r(3, 3), r(3)),
        (r(4, 2), r(2)),
        (r(4, 4), r(4)),
        (r(1, 3), r(3)),
        (r(4, 0), r(0)),
        (r(0, 2), r(2)),
        (np.array([[1, 2], [3, 4], [5, 6]]), np.array([1, 2])),
    ]
    run_matrix_multiplcation(pairs, dtype)


@pytest.mark.parametrize("dtype", [int, float])
def test_matrix_multiplication_1d_by_2d(dtype: type) -> None:
    np.random.seed(42)
    r = np.random.randn
    pairs: list[tuple[np.ndarray, np.ndarray]] = [
        (r(3), r(3, 3)),
        (r(2), r(2, 3)),
        (r(2), r(2, 4)),
        (r(3), r(3, 1)),
        (r(0), r(0, 3)),
        (r(3), r(3, 0)),
        (np.array([1, 2]), np.array([[1, 2], [3, 4]])),
    ]
    run_matrix_multiplcation(pairs, dtype)


@pytest.mark.parametrize("dtype", [int, float])
def test_matrix_multiplication_1d_by_1d(dtype: type) -> None:
    pairs: list[tuple[np.ndarray, np.ndarray]] = [
        (np.ones(2), np.ones(2)),
        (np.ones(3), np.ones(3)),
        (np.ones(4), np.ones(4)),
        (np.ones(0), np.ones(0)),
        (np.array([1, 2]), np.array([1, 2])),
    ]
    run_matrix_multiplcation(pairs, dtype)


@pytest.mark.parametrize(
    "a,b",
    [
        (np.zeros((2, 3)), np.zeros((4, 2))),
        (np.zeros((2, 3)), np.zeros(2)),
        (np.zeros(3), np.zeros((2, 3))),
    ],
)
def test_matrix_multiplication_errors_on_shapes_mismatch(a, b) -> None:
    t = table_from_pandas(pd.DataFrame({"a": [a], "b": [b]}))
    t.select(c=t.a @ t.b)
    with pytest.raises(ValueError):
        run_all()


def test_optional_int_vs_float():
    table = T(
        """
    a | b
    1 | 1.0
      | 2.0
    3 | 3.5
    """
    )
    result = table.select(resA=table.a == table.b, resB=table.a != table.b)
    expected = T(
        """
    resA  | resB
    True  | False
    False | True
    False | True
    """
    )
    assert_table_equality(result, expected)


def test_int_vs_optional_float():
    table = T(
        """
    a | b
    1 | 1.0
    2 |
    3 | 3.5
    """
    )
    result = table.select(resA=table.a == table.b, resB=table.a != table.b)
    expected = T(
        """
    resA  | resB
    True  | False
    False | True
    False | True
    """
    )
    assert_table_equality(result, expected)


def test_optional_int_addition():
    table = T(
        """
    a | b
    1 | 1
      | 2
    3 |
    """
    )
    result = (
        table.filter(pw.this.a.is_not_none())
        .filter(pw.this.b.is_not_none())
        .select(resA=pw.this.a + pw.this.b)
    )
    expected = T(
        """
    resA
    2
    """
    )
    assert_table_equality(result, expected)


def test_tuples():
    table = T(
        """
    a | b
    1 | 1
    2 | 3
    4 | 3
    """
    ).with_columns(
        x=pw.make_tuple(pw.this.a, pw.this.b),
        y=pw.make_tuple(pw.this.b, pw.this.a),
    )
    result = table.select(
        pw.this.a,
        pw.this.b,
        eq=pw.this.x == pw.this.y,
        ne=pw.this.x != pw.this.y,
        lt=pw.this.x < pw.this.y,
        le=pw.this.x <= pw.this.y,
        gt=pw.this.x > pw.this.y,
        ge=pw.this.x >= pw.this.y,
    )
    expected = T(
        """
    a | b |  eq   |   ne  |   lt  |   le  |   gt  |   ge
    1 | 1 |  True | False | False |  True | False |  True
    2 | 3 | False |  True |  True |  True | False | False
    4 | 3 | False |  True | False | False |  True |  True
    """
    )
    assert_table_equality(result, expected)


@pytest.mark.parametrize(
    "op",
    [operator.eq, operator.ne, operator.lt, operator.le, operator.gt,
     operator.ge],
)
def test_tuples_error_on_incorrect_types(op):
    table = T(
        """
    a | b
    1 | a
    2 | b
    4 | c
    """
    ).with_columns(
        x=pw.make_tuple(pw.this.a, pw.this.a),
        y=pw.make_tuple(pw.this.a, pw.this.b),
    )
    with pytest.raises(
        TypeError,
        match=re.escape(
            f"Pathway does not support using binary operator {op.__name__} "
            "on columns of types tuple[int, int], tuple[int, str]."
        ),
    ):
        table.select(z=op(pw.this.x, pw.this.y))


def test_lists_lexicographical():
    def make_list(n) -> list[int]:
        return list(range(n))

    table = T(
        """
    a | b
    5 | 5
    2 | 3
    4 | 3
    """
    ).with_columns(
        x=pw.apply(make_list, pw.this.a),
        y=pw.apply(make_list, pw.this.b),
    )
    result = table.select(
        pw.this.a,
        pw.this.b,
        eq=pw.this.x == pw.this.y,
        ne=pw.this.x != pw.this.y,
        lt=pw.this.x < pw.this.y,
        le=pw.this.x <= pw.this.y,
        gt=pw.this.x > pw.this.y,
        ge=pw.this.x >= pw.this.y,
    )
    expected = T(
        """
    a | b |  eq   |   ne  |   lt  |   le  |   gt  |   ge
    5 | 5 |  True | False | False |  True | False |  True
    2 | 3 | False |  True |  True |  True | False | False
    4 | 3 | False |  True | False | False |  True |  True
    """
    )
    assert_table_equality(result, expected)


@pytest.mark.parametrize("cast", ["a", "b"])
def test_tuples_int_float(cast: str):
    table = (
        T(
            """
    a | b
    1 | 1
    2 | 3
    4 | 3
    """
        )
        .with_columns(**{cast: pw.cast(float, pw.this[cast])})
        .with_columns(
            x=pw.make_tuple(pw.this.a, pw.this.b),
            y=pw.make_tuple(pw.this.b, pw.this.a),
        )
    )
    result = table.select(
        pw.this.a,
        pw.this.b,
        eq=pw.this.x == pw.this.y,
        ne=pw.this.x != pw.this.y,
        lt=pw.this.x < pw.this.y,
        le=pw.this.x <= pw.this.y,
        gt=pw.this.x > pw.this.y,
        ge=pw.this.x >= pw.this.y,
    )
    expected = T(
        """
    a | b |  eq   |   ne  |   lt  |   le  |   gt  |   ge
    1 | 1 |  True | False | False |  True | False |  True
    2 | 3 | False |  True |  True |  True | False | False
    4 | 3 | False |  True | False | False |  True |  True
    """
    ).with_columns(**{cast: pw.cast(float, pw.this[cast])})
    assert_table_equality(result, expected)


def test_tuples_none():
    table = T(
        """
    a | b
    1 |
      |
    1 | 1
    """
    ).with_columns(
        x=pw.make_tuple(pw.this.a, pw.this.b),
        y=pw.make_tuple(pw.this.b, pw.this.a),
    )
    result = table.select(
        pw.this.a,
        pw.this.b,
        eq=pw.this.x == pw.this.y,
        ne=pw.this.x != pw.this.y,
    )
    expected = T(
        """
    a | b |  eq   |   ne
    1 |   | False |  True
      |   |  True | False
    1 | 1 |  True | False
    """
    )
    assert_table_equality(result, expected)


@pytest.mark.parametrize(
    "op", [operator.lt, operator.le, operator.gt, operator.ge]
)
def test_tuples_none_cmp(op):
    table = T(
        """
    a | b
    1 |
      |
    1 | 1
    """
    ).with_columns(
        x=pw.make_tuple(pw.this.a, pw.this.b),
        y=pw.make_tuple(pw.this.b, pw.this.a),
    )
    with pytest.raises(
        TypeError,
        match=re.escape(
            f"Pathway does not support using binary operator {op.__name__} "
            "on columns of types tuple[int | None, int | None], "
            "tuple[int | None, int | None].",
        ),
    ):
        table.select(z=op(pw.this.x, pw.this.y))
