"""Pallas KNN kernel: exactness vs the XLA scoring path (interpret mode on
the CPU backend; the driver bench compares both compiled on TPU).
Reference: src/external_integration/brute_force_knn_integration.rs:22."""

import numpy as np
import pytest


def _random_corpus(n, d, seed=0):
    rng = np.random.default_rng(seed)
    corpus = rng.normal(size=(n, d)).astype(np.float32)
    valid = np.ones(n, dtype=bool)
    valid[n // 3] = False  # a deleted slot must never be returned
    return corpus, valid


def test_pallas_dense_topk_matches_xla():
    import jax.numpy as jnp

    from pathway_tpu.ops import pallas_topk as pt
    from pathway_tpu.ops.knn import dense_topk_prepared, prepare_corpus

    n, d, k = 2048, 64, 7
    corpus, valid = _random_corpus(n, d)
    queries = np.random.default_rng(1).normal(size=(5, d)).astype(np.float32)

    prep, c2 = prepare_corpus(jnp.asarray(corpus), "cosine")
    s_ref, i_ref = dense_topk_prepared(
        jnp.asarray(queries), prep, c2, jnp.asarray(valid), k, metric="cosine"
    )
    s_pl, i_pl = pt.pallas_dense_topk(
        jnp.asarray(queries),
        prep,
        jnp.asarray(valid),
        k,
        metric="cosine",
        interpret=True,
    )
    assert (np.asarray(i_ref) == np.asarray(i_pl)).all()
    assert np.allclose(np.asarray(s_ref), np.asarray(s_pl), atol=1e-6)
    assert (np.asarray(i_pl) != n // 3).all()


def test_index_pallas_kernel_matches_xla():
    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    rng = np.random.default_rng(2)
    vecs = rng.normal(size=(300, 16)).astype(np.float32)

    def build(kernel):
        ix = TpuDenseKnnIndex(
            dimensions=16, reserved_space=1024, kernel=kernel
        )
        for i in range(len(vecs)):
            ix.upsert(i, vecs[i], None)
        ix.remove(123)
        return ix

    queries = [(vecs[7], 5, None), (vecs[123], 5, None)]
    res_x = build("xla").search(queries)
    res_p = build("pallas").search(queries)
    for rx, rp in zip(res_x, res_p):
        assert [r[0] for r in rx] == [r[0] for r in rp]
        assert np.allclose(
            [r[1] for r in rx], [r[1] for r in rp], atol=1e-6
        )
    assert res_p[0][0][0] == 7
    assert all(r[0] != 123 for r in res_p[1])


def test_pallas_padded_k10_interpret_matches_xla():
    """Run the PADDED kernel at k=10 — the exact BENCH_r02 crash shape
    (k not lane-aligned; KP pads to 128 and the caller slices back) — in
    interpret mode, so the pad+slice arithmetic is verified on CPU even
    while the TPU backend is unavailable.  Scores in the padding lanes
    must never leak into the merged top-k."""
    import jax.numpy as jnp

    from pathway_tpu.ops import pallas_topk as pt
    from pathway_tpu.ops.knn import dense_topk_prepared, prepare_corpus

    n, d, k = 2048, 32, 10
    assert pt._kpad(k) == 128 and pt._kpad(k) != k  # genuinely padded
    # lane-boundary pins: exactly-aligned k pads to itself, one past the
    # boundary jumps a full lane width (the BENCH_r02 crash was k=10
    # emitted UNpadded — these keep the ladder honest at its edges)
    assert pt._kpad(1) == 128
    assert pt._kpad(128) == 128
    assert pt._kpad(129) == 256
    corpus, valid = _random_corpus(n, d, seed=5)
    queries = np.random.default_rng(6).normal(size=(3, d)).astype(np.float32)
    prep, c2 = prepare_corpus(jnp.asarray(corpus), "cosine")
    s_ref, i_ref = dense_topk_prepared(
        jnp.asarray(queries), prep, c2, jnp.asarray(valid), k, metric="cosine"
    )
    s_pl, i_pl = pt.pallas_dense_topk(
        jnp.asarray(queries),
        prep,
        jnp.asarray(valid),
        k,
        metric="cosine",
        interpret=True,
    )
    assert s_pl.shape == (3, k) and i_pl.shape == (3, k)
    assert (np.asarray(i_ref) == np.asarray(i_pl)).all()
    assert np.allclose(np.asarray(s_ref), np.asarray(s_pl), atol=1e-6)
    # block-level: per-block candidate tiles slice the KP padding away
    sc, ix = pt.pallas_block_topk(
        jnp.asarray(queries).astype(prep.dtype), prep, jnp.asarray(valid),
        k, interpret=True,
    )
    assert sc.shape == (3, n // pt.BLK, k)
    assert np.isfinite(np.asarray(sc)[:, :, 0]).all()
    # and the lowering gate accepts the padded layout for this shape
    pt.validate_lowering(bq=3, d=d, n=n, k=k)


def test_tpu_lowering_shape_gate():
    """Compiled-mode gate (VERDICT r2 item 2): every block spec the kernel
    will emit for the bench shapes must satisfy the Mosaic TPU rule (last
    two block dims divisible by (8, 128) or equal to the array dims), so a
    kernel that cannot lower on hardware fails the suite even on the CPU
    backend. The round-2 kernel shipped green with interpret=True and then
    crashed on TPU with exactly the shape this asserts."""
    from pathway_tpu.ops import pallas_topk as pt

    # bench shape (1M-row corpus, single query), batched queries, k > 128
    pt.validate_lowering(bq=1, d=384, n=977 * 1024, k=10)
    pt.validate_lowering(bq=16, d=384, n=64 * 1024, k=10)
    pt.validate_lowering(bq=7, d=128, n=2048, k=130)

    # the rule-checker itself must reject the round-2 failure shape:
    # block (1, 1, 10) over array (1, 977, 10) — middle dim 1 vs 977
    with pytest.raises(ValueError):
        pt.check_tpu_block_rules((1, 1, 10), (1, 977, 10))
    # and a lane dim neither 128-aligned nor equal to the array's
    with pytest.raises(ValueError):
        pt.check_tpu_block_rules((8, 10), (8, 2048))


def test_pallas_compiled_on_tpu():
    """When a real TPU is attached (driver bench environment), actually
    compile and run the kernel with interpret=False and compare against
    the XLA path — the hard gate the shape assertion approximates."""
    import jax

    if jax.default_backend() not in ("tpu",):
        pytest.skip("no TPU attached; shape gate covers lowering rules")
    import jax.numpy as jnp

    from pathway_tpu.ops import pallas_topk as pt
    from pathway_tpu.ops.knn import dense_topk_prepared, prepare_corpus

    # k=5 (generic) and k=10 (the exact BENCH_r02 crash shape): both must
    # COMPILE on hardware now that the output tiles are lane-padded
    for n, d, k in ((2048, 128, 5), (2048, 32, 10)):
        corpus, valid = _random_corpus(n, d)
        queries = np.random.default_rng(3).normal(
            size=(4, d)
        ).astype(np.float32)
        prep, c2 = prepare_corpus(jnp.asarray(corpus), "cosine")
        s_ref, i_ref = dense_topk_prepared(
            jnp.asarray(queries), prep, c2, jnp.asarray(valid), k,
            metric="cosine",
        )
        s_pl, i_pl = pt.pallas_dense_topk(
            jnp.asarray(queries), prep, jnp.asarray(valid), k,
            metric="cosine",
        )
        assert (np.asarray(i_ref) == np.asarray(i_pl)).all()


def test_kernel_env_var_and_validation(monkeypatch):
    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    monkeypatch.setenv("PATHWAY_KNN_KERNEL", "pallas")
    assert TpuDenseKnnIndex(dimensions=4).kernel == "pallas"
    monkeypatch.delenv("PATHWAY_KNN_KERNEL")
    assert TpuDenseKnnIndex(dimensions=4).kernel == "xla"
    with pytest.raises(ValueError):
        TpuDenseKnnIndex(dimensions=4, kernel="cuda")
