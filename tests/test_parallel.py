"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest


def _mesh(n=8):
    from pathway_tpu.parallel.mesh import make_mesh

    return make_mesh(n, axis_names=("data",))


def test_sharded_topk_matches_dense():
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import dense_topk, sharded_topk

    mesh = _mesh(8)
    rng = np.random.default_rng(0)
    corpus = rng.normal(size=(64, 16)).astype(np.float32)
    valid = np.ones(64, dtype=bool)
    queries = rng.normal(size=(4, 16)).astype(np.float32)

    s_ref, i_ref = dense_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid), 5,
        metric="cosine", bf16=False,
    )
    s_sh, i_sh = sharded_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid), 5,
        mesh=mesh, metric="cosine", bf16=False,
    )
    assert (np.asarray(i_ref) == np.asarray(i_sh)).all()
    assert np.allclose(np.asarray(s_ref), np.asarray(s_sh), atol=1e-5)


def test_exchange_by_shard():
    from pathway_tpu.parallel.collectives import exchange_by_shard

    mesh = _mesh(8)
    vals = np.arange(32, dtype=np.float32).reshape(16, 2)
    dest = (np.arange(16) % 8).astype(np.int32)
    blocks, counts = exchange_by_shard(vals, dest, mesh)
    assert counts.sum() == 16
    for s in range(8):
        rows = blocks[s, : counts[s]]
        # each shard received exactly the rows addressed to it
        expect = vals[dest == s]
        assert sorted(map(tuple, rows)) == sorted(map(tuple, expect))


def test_ragged_all_to_all_exact():
    """Typed columns survive the exchange bit-for-bit and land on the
    right shard (u64 keys, f64 values, i64 diffs)."""
    from pathway_tpu.parallel.exchange import (
        exchange_rows,
        pack_columns,
        unpack_columns,
    )

    mesh = _mesh(8)
    rng = np.random.default_rng(0)
    n = 1000
    keys = rng.integers(0, 2**63, size=n).astype(np.uint64)
    vals = rng.normal(size=n)
    diffs = rng.choice([-1, 1], size=n).astype(np.int64)
    dest = (keys % 8).astype(np.int32)

    w, spec = pack_columns([keys, vals, diffs])
    k2, v2, d2 = unpack_columns(w, spec)
    assert (k2 == keys).all() and (v2 == vals).all() and (d2 == diffs).all()

    blocks = exchange_rows([keys, vals, diffs], dest, mesh)
    got = {}
    for s, (bk, bv, bd) in enumerate(blocks):
        assert ((bk % 8) == s).all(), f"shard {s} received foreign rows"
        for k, v, d in zip(bk, bv, bd):
            got[int(k)] = (float(v), int(d))
    assert len(got) == len(set(keys.tolist()))
    for k, v, d in zip(keys, vals, diffs):
        assert got[int(k)] == (float(v), int(d))


def test_sharded_knn_index():
    """TpuDenseKnnIndex with a mesh — corpus rows sharded over devices."""
    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    mesh = _mesh(8)
    ix = TpuDenseKnnIndex(dimensions=8, mesh=mesh, reserved_space=16)
    rng = np.random.default_rng(1)
    vecs = rng.normal(size=(40, 8)).astype(np.float32)
    for i in range(40):
        ix.upsert(i, vecs[i], None)
    res = ix.search([(vecs[7], 3, None)])
    assert res[0][0][0] == 7


def test_dryrun_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


# ---------------------------------------------------------------------------
# Engine-level sharding: per-shard state + device exchange


def _with_engine_mesh(n=8):
    from pathway_tpu.parallel import mesh as mesh_mod

    mesh_mod.set_engine_mesh(_mesh(n))
    return mesh_mod


def test_sharded_groupby_matches_single_shard():
    """Same pipeline, sharded vs unsharded engine: identical results, and
    each shard's keyed state is disjoint (the Exchange invariant)."""
    import pathway_tpu as pw
    from pathway_tpu.engine.sharded import ShardedGroupByExec
    from pathway_tpu.internals import parse_graph
    from pathway_tpu.parallel import mesh as mesh_mod

    class S(pw.Schema):
        word: str
        v: int

    rows = [(f"w{i % 17}", i % 5) for i in range(300)]

    def build_and_run():
        t = pw.debug.table_from_rows(S, rows)
        res = t.groupby(t.word).reduce(
            t.word, s=pw.reducers.sum(t.v), c=pw.reducers.count()
        )
        return pw.debug.table_to_dicts(res)

    keys0, cols0 = build_and_run()
    try:
        _with_engine_mesh(8)
        keys1, cols1 = build_and_run()
        rt = parse_graph.G.last_runtime
        sharded_execs = [
            ex
            for ex in rt.execs.values()
            if isinstance(ex, ShardedGroupByExec)
        ]
        assert sharded_execs, "engine mesh set but groupby did not shard"
        owned = sharded_execs[0].shard_group_keys()
        assert sum(len(s) for s in owned) == 17
        for i in range(len(owned)):
            for j in range(i + 1, len(owned)):
                assert not (owned[i] & owned[j]), "shard state overlaps"
        assert rt.frontier_syncs > 0  # frontier all-reduce ran per tick
    finally:
        mesh_mod.set_engine_mesh(None)
    assert sorted(keys0) == sorted(keys1)
    assert cols0 == cols1


def test_sharded_groupby_device_exchange_path():
    """Numeric rows travel through the real device all-to-all."""
    import pathway_tpu as pw
    from pathway_tpu.engine import sharded
    from pathway_tpu.engine.sharded import ShardedGroupByExec
    from pathway_tpu.internals import parse_graph
    from pathway_tpu.parallel import mesh as mesh_mod

    class S(pw.Schema):
        g: int
        v: float

    rows = [(i % 13, float(i) / 7.0) for i in range(600)]

    def build_and_run():
        t = pw.debug.table_from_rows(S, rows)
        res = t.groupby(t.g).reduce(
            t.g, s=pw.reducers.sum(t.v), c=pw.reducers.count()
        )
        return pw.debug.table_to_dicts(res)

    keys0, cols0 = build_and_run()
    old_min = sharded.DEVICE_EXCHANGE_MIN_ROWS
    try:
        sharded.DEVICE_EXCHANGE_MIN_ROWS = 1
        _with_engine_mesh(8)
        keys1, cols1 = build_and_run()
        rt = parse_graph.G.last_runtime
        ex = next(
            e for e in rt.execs.values() if isinstance(e, ShardedGroupByExec)
        )
        assert ex.router.device_exchanges >= 1, (
            "numeric groupby never used the device all-to-all"
        )
    finally:
        sharded.DEVICE_EXCHANGE_MIN_ROWS = old_min
        mesh_mod.set_engine_mesh(None)
    assert sorted(keys0) == sorted(keys1)
    assert cols0 == cols1


def test_sharded_join_matches_single_shard():
    import pathway_tpu as pw
    from pathway_tpu.engine.sharded import ShardedJoinExec
    from pathway_tpu.internals import parse_graph
    from pathway_tpu.parallel import mesh as mesh_mod

    class L(pw.Schema):
        k: str
        a: int

    class R(pw.Schema):
        k: str
        b: int

    lrows = [(f"k{i % 11}", i) for i in range(80)]
    rrows = [(f"k{i % 7}", i * 10) for i in range(40)]

    def build_and_run():
        lt = pw.debug.table_from_rows(L, lrows)
        rt_ = pw.debug.table_from_rows(R, rrows)
        j = lt.join(rt_, lt.k == rt_.k).select(
            lt.k, pw.left.a, pw.right.b
        )
        return pw.debug.table_to_dicts(j)

    keys0, cols0 = build_and_run()
    try:
        _with_engine_mesh(8)
        keys1, cols1 = build_and_run()
        rt = parse_graph.G.last_runtime
        assert any(
            isinstance(e, ShardedJoinExec) for e in rt.execs.values()
        ), "engine mesh set but join did not shard"
    finally:
        mesh_mod.set_engine_mesh(None)
    assert sorted(keys0) == sorted(keys1)
    assert cols0 == cols1


def test_cli_spawn_sets_engine_shards(tmp_path):
    """`pathway-tpu spawn -t N prog` runs the program with an N-shard
    engine mesh instead of redundant copies (reference: PATHWAY_THREADS
    workers, src/engine/dataflow/config.rs:88-121)."""
    import subprocess
    import sys

    prog = tmp_path / "prog.py"
    prog.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from pathway_tpu.parallel.mesh import get_engine_mesh\n"
        "em = get_engine_mesh()\n"
        "assert em is not None, 'engine mesh not configured'\n"
        "print('shards:', em[0].shape['data'])\n"
    )
    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "pathway_tpu.cli",
            "spawn",
            "-t",
            "4",
            "--",
            sys.executable,
            str(prog),
        ],
        capture_output=True,
        text=True,
        timeout=120,
        env={
            **{
                k: v
                for k, v in __import__("os").environ.items()
                if k not in ("XLA_FLAGS", "PATHWAY_ENGINE_SHARDS")
            },
            "PYTHONPATH": "/root/repo",
        },
    )
    assert out.returncode == 0, out.stderr
    assert "shards: 4" in out.stdout


def test_sharded_window_matches_single_shard():
    """Per-instance tumbling-window aggregation at 8 engine shards equals
    the unsharded result; the temporal buffer state is spread across
    shards (VERDICT r3 item 6 — the reference centralizes postponed rows
    on one worker, time_column.rs:44-47)."""
    import pathway_tpu as pw
    from pathway_tpu.engine.sharded import ShardedBufferExec
    from pathway_tpu.internals import parse_graph

    class S(pw.Schema):
        instance: int
        t: int
        v: int

    rows = [(i % 5, i % 40, i) for i in range(400)]

    def build_and_run():
        t = pw.debug.table_from_rows(S, rows)
        res = t.windowby(
            t.t,
            window=pw.temporal.tumbling(duration=10),
            instance=t.instance,
            behavior=pw.temporal.common_behavior(delay=5),
        ).reduce(
            pw.this._pw_instance,
            start=pw.this._pw_window_start,
            s=pw.reducers.sum(pw.this.v),
        )
        return pw.debug.table_to_dicts(res)

    keys0, cols0 = build_and_run()
    parse_graph.G.clear()
    mesh_mod = _with_engine_mesh(8)
    try:
        keys1, cols1 = build_and_run()
        rt = parse_graph.G.last_runtime
        bufs = [
            e
            for e in rt.execs.values()
            if isinstance(e, ShardedBufferExec)
        ]
        assert bufs, "expected a sharded buffer exec"
        # buffer state was actually SPREAD across shards (held empties
        # after the final flush, so assert on ever-touched keys): disjoint
        # ownership, more than one shard populated
        touched = bufs[0].shard_touched_keys()
        populated = [s for s in touched if s]
        assert len(populated) >= 2, "buffer rows all landed on one shard"
        for i in range(len(touched)):
            for j in range(i + 1, len(touched)):
                assert not (touched[i] & touched[j]), "key on two shards"
        assert sum(cols1["s"].values()) == sum(cols0["s"].values())
        assert dict(cols0["s"]) == dict(cols1["s"])
        assert dict(cols0["start"]) == dict(cols1["start"])
    finally:
        mesh_mod.set_engine_mesh(None)
        parse_graph.G.clear()


def test_sharded_sort_matches_single_shard():
    """Instance-sharded prev/next pointers at 8 shards equal the
    unsharded result; each instance's order lives on exactly one shard."""
    import pathway_tpu as pw
    from pathway_tpu.engine.sharded import ShardedSortExec
    from pathway_tpu.internals import parse_graph

    class S(pw.Schema):
        instance: int
        k: int

    rows = [((i * 7) % 6, (i * 13) % 97) for i in range(200)]

    def build_and_run():
        t = pw.debug.table_from_rows(S, rows)
        res = t.sort(key=t.k, instance=t.instance)
        return pw.debug.table_to_dicts(res)

    keys0, cols0 = build_and_run()
    parse_graph.G.clear()
    mesh_mod = _with_engine_mesh(8)
    try:
        keys1, cols1 = build_and_run()
        rt = parse_graph.G.last_runtime
        sorts = [
            e for e in rt.execs.values() if isinstance(e, ShardedSortExec)
        ]
        assert sorts, "expected a sharded sort exec"
        insts = sorts[0].shard_instances()
        populated = [s for s in insts if s]
        assert len(populated) >= 2, "instances all landed on one shard"
        for i in range(len(insts)):
            for j in range(i + 1, len(insts)):
                assert not (insts[i] & insts[j]), "instance on two shards"
        assert dict(cols0["prev"]) == dict(cols1["prev"])
        assert dict(cols0["next"]) == dict(cols1["next"])
    finally:
        mesh_mod.set_engine_mesh(None)
        parse_graph.G.clear()
