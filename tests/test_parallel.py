"""Multi-chip sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest


def _mesh(n=8):
    from pathway_tpu.parallel.mesh import make_mesh

    return make_mesh(n, axis_names=("data",))


def test_sharded_topk_matches_dense():
    import jax.numpy as jnp

    from pathway_tpu.ops.knn import dense_topk, sharded_topk

    mesh = _mesh(8)
    rng = np.random.default_rng(0)
    corpus = rng.normal(size=(64, 16)).astype(np.float32)
    valid = np.ones(64, dtype=bool)
    queries = rng.normal(size=(4, 16)).astype(np.float32)

    s_ref, i_ref = dense_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid), 5,
        metric="cosine", bf16=False,
    )
    s_sh, i_sh = sharded_topk(
        jnp.asarray(queries), jnp.asarray(corpus), jnp.asarray(valid), 5,
        mesh=mesh, metric="cosine", bf16=False,
    )
    assert (np.asarray(i_ref) == np.asarray(i_sh)).all()
    assert np.allclose(np.asarray(s_ref), np.asarray(s_sh), atol=1e-5)


def test_exchange_by_shard():
    import jax

    from pathway_tpu.parallel.collectives import exchange_by_shard
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh(8)
    vals = np.arange(32, dtype=np.float32).reshape(16, 2)
    dest = (np.arange(16) % 8).astype(np.int32)
    v = jax.device_put(vals, NamedSharding(mesh, P("data", None)))
    d = jax.device_put(dest, NamedSharding(mesh, P("data")))
    gathered, keep = exchange_by_shard(v, d, mesh)
    # with replicated output, each row's keep-mask marks its destination
    assert np.asarray(keep).shape == (16,)


def test_sharded_knn_index():
    """TpuDenseKnnIndex with a mesh — corpus rows sharded over devices."""
    from pathway_tpu.stdlib.indexing._index_impls import TpuDenseKnnIndex

    mesh = _mesh(8)
    ix = TpuDenseKnnIndex(dimensions=8, mesh=mesh, reserved_space=16)
    rng = np.random.default_rng(1)
    vecs = rng.normal(size=(40, 8)).astype(np.float32)
    for i in range(40):
        ix.upsert(i, vecs[i], None)
    res = ix.search([(vecs[7], 3, None)])
    assert res[0][0][0] == 7


def test_dryrun_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
