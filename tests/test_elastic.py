"""Shard Flux (pathway_tpu/elastic/): live elastic resharding.

Covers: the reshard planner's hash-ring delta (minimal moves,
conservation), the N→M→N randomized property (resharded folded output
bit-equal to the uninterrupted run — inserts, retracts, updates, ties,
mid-transfer deletions), the SegmentFerry (authenticated round-trip,
content-addressed resume, auth rejection, per-segment MAC), the
two-phase handover barrier (commit/rollback/incarnation fencing), the
mesh-plane store re-partition (1→2 split of a real persisted run), the
serving plane's live writer reshard + transition guard + router map
swap, the generation plane's KV split, the ``kill=ferry:N`` Fault Forge
directive (slow: real subprocess SIGKILL mid-ferry, barrier rolls
back), and the ``elastic-resharding`` Graph Doctor rule.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import pathway_tpu as pw  # noqa: F401  (conftest clears its graph)
from pathway_tpu.elastic import handover as ho
from pathway_tpu.elastic import planner
from pathway_tpu.elastic.ferry import FerryReceiver, FerryError, ferry_files
from pathway_tpu.engine.batch import DiffBatch
from pathway_tpu.engine.nodes import GroupByNode, InputNode
from pathway_tpu.engine.reducers import ReducerSpec
from pathway_tpu.engine.runtime import StaticSource
from pathway_tpu.engine.sharded import ShardedGroupByExec, shard_of

REPO = pathlib.Path(__file__).resolve().parent.parent


# --- planner ---------------------------------------------------------------


def test_plan_identity_moves_nothing():
    p = planner.plan_reshard(3, 3)
    assert p.moved_slots == 0 and p.moves == ()


@pytest.mark.parametrize("n_old,n_new", [(2, 3), (3, 2), (1, 3), (4, 5)])
def test_plan_moves_exactly_the_differing_slots(n_old, n_new):
    p = planner.plan_reshard(n_old, n_new)
    old = planner.slot_owners(n_old)
    new = planner.slot_owners(n_new)
    differing = int((old != new).sum())
    assert p.moved_slots == differing
    # conservation + correctness of every (src, dst) bucket
    for m in p.moves:
        assert m.src != m.dst
        mask = (old == m.src) & (new == m.dst)
        assert m.n_slots == int(mask.sum())
    # a grow never moves more than everything; 1→M moves (M-1)/M
    assert 0 < p.moved_fraction <= 1.0
    if n_old == 1:
        assert p.moved_fraction == pytest.approx(
            (n_new - 1) / n_new, abs=1e-3
        )


def test_split_arrangement_routes_by_jk_owner():
    from pathway_tpu.engine.arrangement import Arrangement

    rng = np.random.default_rng(7)
    jks = rng.integers(0, 2**63, size=500, dtype=np.uint64)
    arr = Arrangement(1)
    arr.append(
        jks,
        jks,
        np.ones(500, np.int64),
        [np.arange(500).astype(object)],
    )
    parts = planner.split_arrangement(arr, 3)
    total = 0
    for s, part in enumerate(parts):
        rows = part.entries()
        total += len(rows)
        if len(rows):
            assert (
                shard_of(np.asarray(rows.jk, np.uint64), 3) == s
            ).all()
    assert total == len(arr.entries())


# --- the N→M→N property (satellite: randomized bit-equality) ---------------


def _gb_node():
    gin = InputNode(StaticSource(["k", "v"]), ["k", "v"])
    return GroupByNode(
        gin,
        ["k"],
        {
            "cnt": ReducerSpec(kind="count", arg_cols=()),
            "s": ReducerSpec(kind="sum", arg_cols=("v",)),
        },
    )


def _sharded(node, n):
    ex = ShardedGroupByExec(node, SimpleNamespace(shape={"data": n}), "data")
    ex.enable_state_ledger()
    return ex


def _fold(rows):
    """Fold an emitted diff stream into current state per row key —
    the bit-equality surface (insert overwrites, matching retraction
    removes)."""
    state: dict = {}
    for key, diff, vals in rows:
        if diff > 0:
            state[key] = vals
        elif state.get(key) == vals:
            del state[key]
    return state


def _random_phases(seed: int, n_phases: int = 3):
    """Random insert/retract/update traffic with ties and deletions;
    retractions always match a live row (engine contract)."""
    rng = np.random.default_rng(seed)
    live: list[tuple[int, tuple]] = []
    next_key = 1
    phases = []
    for _p in range(n_phases):
        events = []
        for _ in range(rng.integers(30, 60)):
            op = rng.random()
            if op < 0.6 or not live:
                k = next_key
                next_key += 1
                # heavy key-collision pressure: few distinct groups +
                # tied values
                row = (f"g{int(rng.integers(0, 9))}", int(rng.integers(0, 4)))
                live.append((k, row))
                events.append((k, 1, row))
            elif op < 0.8:
                i = int(rng.integers(0, len(live)))
                k, row = live.pop(i)
                events.append((k, -1, row))  # deletion (incl. mid-transfer)
            else:
                i = int(rng.integers(0, len(live)))
                k, row = live[i]
                new_row = (row[0], int(rng.integers(0, 4)))
                live[i] = (k, new_row)
                events.append((k, -1, row))
                events.append((k, 1, new_row))
        phases.append(events)
    return phases


def _feed(ex, t, events):
    out = []
    for b in ex.process(t, [[DiffBatch.from_rows(events, ["k", "v"])]]):
        out.extend(b.iter_rows())
    return out


def _handoff(ex_src, node, n_new):
    """snapshot → elastic re-partition → load into a fresh N_new-shard
    exec (exactly the restore path engine/sharded.py takes when
    PATHWAY_ENGINE_SHARDS changed between runs)."""
    arranged = ex_src.arranged_state()
    assert arranged is not None
    residual, arrs = arranged
    ex_dst = _sharded(node, n_new)
    assert ex_dst.check_arranged_state(residual, arrs)
    ex_dst.load_arranged_state(residual, arrs)
    return ex_dst


@pytest.mark.parametrize("n,m", [(2, 3), (3, 2), (1, 4)])
@pytest.mark.parametrize("seed", [3, 11, 42])
def test_reshard_n_m_n_bit_equal_to_uninterrupted(n, m, seed):
    phases = _random_phases(seed)
    node = _gb_node()

    # uninterrupted reference: one N-shard exec sees all phases
    ref = _sharded(node, n)
    ref_out = []
    for t, events in enumerate(phases):
        ref_out.extend(_feed(ref, t, events))

    # subject: N → (handoff) → M → (handoff) → N mid-run
    subj_out = []
    ex = _sharded(node, n)
    subj_out.extend(_feed(ex, 0, phases[0]))
    ex = _handoff(ex, node, m)  # grow/shrink 1
    subj_out.extend(_feed(ex, 1, phases[1]))
    ex = _handoff(ex, node, n)  # and back
    subj_out.extend(_feed(ex, 2, phases[2]))

    assert _fold(subj_out) == _fold(ref_out)
    # per-shard ownership is disjoint and matches the hash partition
    owned = ex.shard_group_keys()
    for s, keys in enumerate(owned):
        if keys:
            arr = np.asarray(sorted(keys), dtype=np.uint64)
            assert (shard_of(arr, n) == s).all()


def test_same_count_snapshot_unchanged_path():
    """N→N restore must not take the elastic branch (the established
    path stays byte-identical)."""
    node = _gb_node()
    ex = _sharded(node, 2)
    _feed(ex, 0, [(1, 1, ("a", 1)), (2, 1, ("b", 2))])
    residual, arrs = ex.arranged_state()
    ex2 = _sharded(node, 2)
    assert ex2.check_arranged_state(residual, arrs)
    ex2.load_arranged_state(residual, arrs)
    assert ex2.shard_group_keys() == ex.shard_group_keys()


# --- SegmentFerry ----------------------------------------------------------


@pytest.fixture()
def job_secret(monkeypatch):
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "elastic-test-secret")
    yield "elastic-test-secret"


def test_ferry_roundtrip_places_files(tmp_path, job_secret):
    recv = FerryReceiver(str(tmp_path / "dst"))
    try:
        files = [
            ("segments/a/0.seg", b"alpha" * 100),
            ("segments/b/1.seg", b"beta" * 50),
            ("manifest.json", b'{"v":1}'),
        ]
        stats = ferry_files(
            recv.host, recv.port, files, transfer_id="t1"
        )
        assert stats["committed"] and stats["segments_sent"] == 3
        assert stats["segments_resumed"] == 0
        for name, blob in files:
            assert (tmp_path / "dst" / name).read_bytes() == blob
        assert "t1" in recv.received
    finally:
        recv.close()


def test_ferry_resume_ships_only_missing(tmp_path, job_secret):
    recv = FerryReceiver(str(tmp_path / "dst"))
    try:
        files = [(f"f{i}", bytes([i]) * 64) for i in range(4)]
        # first attempt stages everything but never commits (a torn
        # transfer: the sender died before the commit frame)
        s1 = ferry_files(
            recv.host, recv.port, files, transfer_id="t2", commit=False
        )
        assert s1["segments_sent"] == 4 and not s1["committed"]
        assert not recv.received  # nothing placed: rollback-able
        # retry resumes content-addressed: zero re-sent bytes
        s2 = ferry_files(recv.host, recv.port, files, transfer_id="t2")
        assert s2["segments_sent"] == 0
        assert s2["segments_resumed"] == 4
        assert s2["committed"]
        for name, blob in files:
            assert (tmp_path / "dst" / name).read_bytes() == blob
    finally:
        recv.close()


def test_ferry_rejects_wrong_secret(tmp_path, monkeypatch):
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "secret-A")
    recv = FerryReceiver(str(tmp_path / "dst"))
    try:
        monkeypatch.setenv("PATHWAY_DCN_SECRET", "secret-B")
        with pytest.raises(FerryError, match="authentication"):
            ferry_files(
                recv.host, recv.port, [("x", b"y")], transfer_id="t3"
            )
        assert not (tmp_path / "dst" / "x").exists()
    finally:
        recv.close()


def test_ferry_abort_discards_staging(tmp_path, job_secret):
    recv = FerryReceiver(str(tmp_path / "dst"))
    try:
        ferry_files(
            recv.host,
            recv.port,
            [("f", b"data")],
            transfer_id="t4",
            commit=False,
        )
        assert recv.staged("t4")
        recv.abort("t4")
        assert not recv.staged("t4")
    finally:
        recv.close()


# --- two-phase handover ----------------------------------------------------


def test_handover_commit_and_rollback(tmp_path):
    h = ho.TwoPhaseHandover(str(tmp_path))
    assert h.committed is None
    cur = h.ensure_committed(2)
    assert cur == ho.OwnershipMap(2, 0)
    nxt = h.begin(3)
    assert nxt.n_shards == 3 and nxt.incarnation == 1
    # the committed map is UNCHANGED while in transition (a crash here
    # leaves the old topology in force)
    assert h.committed == ho.OwnershipMap(2, 0)
    assert h.in_transition
    with pytest.raises(ho.HandoverError):
        h.begin(4)  # one transition at a time
    h.rollback()
    assert h.committed == ho.OwnershipMap(2, 0)
    assert not h.in_transition
    h.begin(3)
    done = h.commit()
    assert done == ho.OwnershipMap(3, 1)
    assert h.committed == ho.OwnershipMap(3, 1)
    # incarnations are monotone across reshardings (zombie fencing)
    h.begin(5)
    assert h.commit().incarnation == 2


# --- mesh plane: store re-partition ---------------------------------------


def _run_persisted_wordcount(base: pathlib.Path, words: list[str]):
    """One single-process streaming run with snapshots — produces the
    per-rank store layout reshard_stores consumes."""
    import pathway_tpu as pw

    (base / "in").mkdir(parents=True, exist_ok=True)
    with open(base / "in" / "w.jsonl", "w") as f:
        for w in words:
            f.write(json.dumps({"word": w}) + "\n")
    out_file = base / "out.jsonl"

    class S(pw.Schema):
        word: str

    t = pw.io.jsonlines.read(
        str(base / "in"), schema=S, mode="streaming"
    )
    r = t.groupby(t.word).reduce(t.word, count=pw.reducers.count())
    pw.io.jsonlines.write(r, str(out_file))

    def watch():
        deadline = time.monotonic() + 60
        want = len(set(words))
        while time.monotonic() < deadline:
            try:
                got = {
                    json.loads(line)["word"]
                    for line in open(out_file)
                    if line.strip()
                }
            except OSError:
                got = set()
            if len(got) >= want:
                break
            time.sleep(0.05)
        rt = pw.internals.parse_graph.G.runtime
        if rt is not None:
            rt.stop()

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(base / "pstorage")),
        snapshot_every=1,
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=20)


def test_reshard_stores_splits_one_rank_into_two(tmp_path, job_secret):
    from pathway_tpu.elastic.mesh import reshard_stores
    from pathway_tpu.persistence.backends import FilesystemStore

    words = [f"w{i % 13}" for i in range(60)]
    _run_persisted_wordcount(tmp_path, words)
    src = str(tmp_path / "pstorage")
    dsts = [str(tmp_path / "new0"), str(tmp_path / "new1")]
    # rank 0 keeps its own root in place: resize-in-place is the
    # production shape (new0 here keeps the test readable)
    stats = reshard_stores([src], dsts, via_wire=True)
    assert stats["plan"]["n_old"] == 1 and stats["plan"]["n_new"] == 2
    assert stats["total_rows"] > 0
    # a 1→2 split moves ~half the key space — and ONLY that
    assert 0 < stats["moved_rows"] < stats["total_rows"]
    assert 0 < stats["bytes_ferried"] <= stats["bytes_total_segments"]
    assert stats["ferry"] and stats["ferry"][0]["committed"]
    # each new store holds a restorable generation whose arrangement
    # rows are exactly the jk ranges that rank owns under n=2
    from pathway_tpu.persistence._runtime_glue import PersistenceDriver
    from pathway_tpu.persistence.segments import load_arrangement

    import pickle

    seen_jks: list[np.ndarray] = []
    for p, root in enumerate(dsts):
        store = FilesystemStore(root)
        meta = json.loads(store.get("metadata.json").decode())
        snap = meta["state"]
        assert snap["gen"] == stats["generation"]
        for ident, cls in snap["nodes"].items():
            blob = pickle.loads(
                store.get(PersistenceDriver._state_key(snap["gen"], ident))
            )
            if not (isinstance(blob, dict) and blob.get("__pw_arranged__")):
                continue
            for name, man in blob["manifests"].items():
                arr = load_arrangement(
                    man,
                    lambda sid, n=name, e=man["epoch"], i=ident,
                    s=store: s.get_buffer(
                        PersistenceDriver._segment_key(i, n, e, sid)
                    ),
                )
                rows = arr.entries()
                if len(rows):
                    jks = np.asarray(rows.jk, np.uint64)
                    assert (shard_of(jks, 2) == p).all()
                    seen_jks.append(jks)
    assert seen_jks, "no arranged state landed in the new stores"


def _arranged_rows(root):
    """Consolidated (jk, key) -> summed diff across every arranged node
    in a store — the fold-equality fingerprint for reshard round-trips."""
    from pathway_tpu.persistence._runtime_glue import PersistenceDriver
    from pathway_tpu.persistence.backends import FilesystemStore
    from pathway_tpu.persistence.segments import load_arrangement

    import pickle

    store = FilesystemStore(root)
    meta = json.loads(store.get("metadata.json").decode())
    snap = meta["state"]
    out: dict = {}
    for ident in snap["nodes"]:
        blob = pickle.loads(
            store.get(PersistenceDriver._state_key(snap["gen"], ident))
        )
        if not (isinstance(blob, dict) and blob.get("__pw_arranged__")):
            continue
        for name, man in blob["manifests"].items():
            arr = load_arrangement(
                man,
                lambda sid, n=name, e=man["epoch"], i=ident,
                s=store: s.get_buffer(
                    PersistenceDriver._segment_key(i, n, e, sid)
                ),
            )
            rows = arr.entries()
            for jk, key, cnt in zip(rows.jk, rows.key, rows.count):
                k = (ident, name, int(jk), int(key))
                out[k] = out.get(k, 0) + int(cnt)
    return {k: v for k, v in out.items() if v != 0}


def test_reshard_segment_level_split_and_intact_merge(tmp_path, job_secret):
    """Segment-level ownership: a 1→2 split slices straddler segments
    (counted), and the 2→1 merge back ships every segment INTACT — no
    row decode — while the round-tripped state stays value-equal."""
    from pathway_tpu.elastic.mesh import reshard_stores

    words = [f"w{i % 13}" for i in range(60)]
    _run_persisted_wordcount(tmp_path, words)
    src = str(tmp_path / "pstorage")
    before = _arranged_rows(src)
    assert before

    two = [str(tmp_path / "two0"), str(tmp_path / "two1")]
    up = reshard_stores([src], two, via_wire=False)
    # a 13-key segment straddles both new owners, so the split path ran
    assert up["segments_split"] >= 1
    handled = (
        up["segments_split"]
        + up["segments_shipped_intact"]
        + up["segments_kept"]
    )
    assert handled >= 1
    assert up["transfer_seconds"] > 0

    one = [str(tmp_path / "one0")]
    down = reshard_stores(two, one, via_wire=False)
    # n_new == 1: every segment is wholly owned by rank 0 — the merge
    # must never decode a row
    assert down["segments_split"] == 0
    assert down["segments_shipped_intact"] >= 1  # rank 1's segments move
    assert down["moved_rows"] > 0

    after = _arranged_rows(one[0])
    assert after == before
    from pathway_tpu.elastic.handover import HandoverError
    from pathway_tpu.elastic.mesh import reshard_stores
    from pathway_tpu.persistence.backends import FilesystemStore

    # two synthetic stores; rank 1 (to be retired) has a log tail newer
    # than its snapshot
    for r in range(2):
        st = FilesystemStore(str(tmp_path / f"p{r}"))
        st.put(
            "metadata.json",
            json.dumps(
                {
                    "last_time": 9 if r == 1 else 5,
                    "chunks": {},
                    "live_chunks": {"input-0": [3]} if r == 1 else {},
                    "state": {
                        "gen": 1,
                        "time": 5,
                        "nodes": {},
                        "segment_keys": [],
                    },
                }
            ).encode(),
        )
    with pytest.raises(HandoverError, match="retires"):
        reshard_stores(
            [str(tmp_path / "p0"), str(tmp_path / "p1")],
            [str(tmp_path / "n0")],
            via_wire=False,
        )


# --- serving plane: live writer reshard + router swap ----------------------


def test_delta_stream_reshard_fences_old_map_and_serves_new(
    tmp_path, job_secret
):
    from pathway_tpu.parallel.replicate import (
        DeltaStreamClient,
        DeltaStreamServer,
    )

    srv = DeltaStreamServer(0, ring_ticks=64, n_shards=1)
    applied: dict[int, list] = {1: [], 2: []}
    try:
        keys = np.arange(1, 33, dtype=np.uint64)
        b = DiffBatch(
            keys,
            np.ones(len(keys), np.int64),
            {"v": np.arange(len(keys)).astype(object)},
        )
        srv.publish(0, [b])

        # an unsharded subscriber on the OLD map
        old_client = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            1,
            from_tick=-1,
            on_deltas=lambda t, bs: applied[1].append((t, bs)),
        )
        old_client.start()
        deadline = time.monotonic() + 20
        while not applied[1] and time.monotonic() < deadline:
            time.sleep(0.02)
        assert applied[1], "old-map subscriber never caught up"

        res = srv.reshard(3)
        assert res == {"old": 1, "new": 3, "incarnation": 1}
        # transition guard: the old-map subscriber redials, sees the
        # new shard count in the suback, and fences itself with a
        # sticky config_error instead of mis-applying
        deadline = time.monotonic() + 20
        while old_client.config_error is None and (
            time.monotonic() < deadline
        ):
            time.sleep(0.05)
        assert old_client.config_error is not None
        assert "3 shard(s)" in old_client.config_error

        # a member on the NEW map receives exactly its key range —
        # including the re-split ring replay of tick 0
        new_client = DeltaStreamClient(
            "127.0.0.1",
            srv.port,
            2,
            from_tick=-1,
            on_deltas=lambda t, bs: applied[2].append((t, bs)),
            shard=1,
            expect_shards=3,
        )
        new_client.start()
        deadline = time.monotonic() + 20
        while not any(
            bs for _t, bs in applied[2]
        ) and time.monotonic() < deadline:
            time.sleep(0.02)
        got_keys = [
            int(k)
            for _t, bs in applied[2]
            for bb in bs
            for k, _d, _v in bb.iter_rows()
        ]
        assert got_keys, "new-map subscriber got no ring replay"
        assert (
            shard_of(np.asarray(got_keys, np.uint64), 3) == 1
        ).all()
        old_client.close()
        new_client.close()
    finally:
        srv.close()


def test_router_swap_shard_map_before_start(job_secret):
    from pathway_tpu.serving.router import FailoverRouter

    r = FailoverRouter(["http://127.0.0.1:1"])
    assert r.n_shards == 1
    r.swap_shard_map(
        [["http://127.0.0.1:1"], ["http://127.0.0.1:2"]]
    )
    assert r.n_shards == 2
    assert [ep.shard for ep in r.endpoints] == [0, 1]
    with pytest.raises(ValueError):
        r.swap_shard_map([[]])  # torn maps stay rejected


# --- kill=ferry (Fault Forge) ----------------------------------------------


def test_kill_ferry_spec_parses_and_rejects_at():
    from pathway_tpu.testing import faults

    p = faults.FaultPlan("kill=ferry:2", 0, 0)
    assert p.directives[0].args["ferry"] == "2"
    with pytest.raises(faults.FaultSpecError):
        faults.FaultPlan("kill=ferry:2,at:head", 0, 0)
    # incarnation gating: a retry under a bumped incarnation runs free
    p1 = faults.FaultPlan("kill=ferry:1", 0, 1)
    p1.on_ferry_segment(5)  # inc 1 vs default inc 0: no exit


_FERRY_KILL_CHILD = """
import os, sys
sys.path.insert(0, {repo!r})
from pathway_tpu.elastic.ferry import ferry_files
files = [(f"f{{i}}", bytes([i]) * 128) for i in range(5)]
ferry_files("127.0.0.1", int(sys.argv[1]), files, transfer_id="chaos")
print("FERRY-DONE", flush=True)
"""


@pytest.mark.slow
def test_kill_ferry_mid_handoff_rolls_back(tmp_path, job_secret):
    """Satellite acceptance: a rank killed mid-ferry (deterministic on
    the segment-transfer counter) leaves the two-phase barrier
    rollback-able — the old ownership map stays committed, the staged
    transfer resumes content-addressed on retry."""
    h = ho.TwoPhaseHandover(str(tmp_path))
    h.ensure_committed(2)
    h.begin(3)  # transition open; commit would happen after the ferry
    recv = FerryReceiver(str(tmp_path / "dst"))
    try:
        env = dict(os.environ)
        env["PATHWAY_FAULTS"] = "kill=ferry:2"
        env["PATHWAY_DCN_SECRET"] = "elastic-test-secret"
        proc = subprocess.run(
            [
                sys.executable,
                "-c",
                _FERRY_KILL_CHILD.format(repo=str(REPO)),
                str(recv.port),
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 23, proc.stderr[-2000:]
        assert "FERRY-DONE" not in proc.stdout
        # the transfer never committed: nothing placed, two segments
        # staged — and the OLD ownership map still rules
        assert not recv.received
        assert len(recv.staged("chaos")) == 2
        h.rollback()
        assert h.committed == ho.OwnershipMap(2, 0)
        # retry (fault-free: the supervisor bumps the incarnation)
        # resumes from the staged half and completes; only then commit
        env["PATHWAY_MESH_INCARNATION"] = "1"
        proc2 = subprocess.run(
            [
                sys.executable,
                "-c",
                _FERRY_KILL_CHILD.format(repo=str(REPO)),
                str(recv.port),
            ],
            env=env,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc2.returncode == 0, proc2.stderr[-2000:]
        assert "chaos" in recv.received
        h.begin(3)
        assert h.commit() == ho.OwnershipMap(3, 1)
    finally:
        recv.close()


# --- Graph Doctor rule -----------------------------------------------------


def test_elastic_resharding_rule(monkeypatch):
    from pathway_tpu.analysis import run_doctor

    # single-rank: silent
    t = pw.debug.table_from_markdown(
        """
        k | v
        a | 1
        b | 2
        """
    )
    t2 = pw.debug.table_from_markdown(
        """
          | k | v
        9 | c | 3
        """
    )
    # update_rows keeps both sides' rows as monolithic keyed state
    # (UpdateRowsExec has no arranged_state): reshard-pinned
    merged = t.update_rows(t2)
    pw.io.null.write(merged)
    assert not run_doctor().by_rule("elastic-resharding")
    # multi-rank: the monolithic exec pins the group to log-replay
    # resizes — WARNING once, INFO naming the exec
    monkeypatch.setenv("PATHWAY_PROCESSES", "2")
    diags = run_doctor().by_rule("elastic-resharding")
    from pathway_tpu.analysis import Severity

    assert any(d.severity == Severity.WARNING for d in diags)
    infos = [d for d in diags if d.severity == Severity.INFO]
    assert any("UpdateRowsNode" in d.message for d in infos)


def test_reshard_capable_resolution():
    node = _gb_node()
    assert planner.reshard_capable(node) is True


# --- mesh plane e2e: supervised 2 -> 3 rank resize (slow) ------------------

@pytest.mark.slow
def test_supervised_group_resizes_2_to_3_with_zero_replay(
    tmp_path, job_secret
):
    """The tentpole acceptance: a supervised 2-rank group resizes to 3
    ranks mid-run via GroupSupervisor.resize + reshard_stores — the
    grown group restores with ``replayed_events == 0`` (state moved,
    log untouched) and the folded output is bit-equal to the
    uninterrupted totals."""
    from pathway_tpu.elastic.mesh import reshard_stores
    from pathway_tpu.parallel.supervisor import GroupSupervisor
    from pathway_tpu.testing.chaos import (
        RESHARD_WORKER_SCRIPT,
        fold_diff_stream,
        free_dcn_port,
    )

    base = tmp_path / "work"
    for pid in range(3):
        (base / f"in{pid}").mkdir(parents=True)
    script = tmp_path / "worker.py"
    script.write_text(RESHARD_WORKER_SCRIPT)
    port = free_dcn_port(3)

    def write_words(pid, fname, words):
        with open(base / f"in{pid}" / fname, "w") as f:
            for w in words:
                f.write(json.dumps({"word": w}) + "\n")

    phase1 = {
        0: ["a", "b", "a", "c", "a"],
        1: ["b", "c", "d", "a", "d"],
    }
    for pid, words in phase1.items():
        write_words(pid, "f1.jsonl", words)
    env = {
        "PW_TEST_DIR": str(base),
        "PATHWAY_DCN_PORT": str(port),
        "PATHWAY_DCN_SECRET": "elastic-test-secret",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
    }
    roots = [str(base / f"pstorage{p}") for p in range(3)]
    sup = GroupSupervisor(
        [sys.executable, str(script)],
        2,
        env=env,
        max_restarts=1,
        grace_s=25.0,  # graceful SIGTERM stop: the final covering
        # snapshot must land before any SIGKILL escalation
        log_dir=str(base / "logs"),
    )
    th = threading.Thread(target=sup.run, daemon=True)
    th.start()
    try:
        # wait until the phase-1 totals are durably processed (plus a
        # breath of idle ticks so the per-tick snapshot covers the log)
        p1_expected = {("a",): (3 + 1,), ("b",): (2,), ("c",): (2,),
                       ("d",): (2,)}
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            folded = fold_diff_stream(
                [base / f"out{p}_inc0.jsonl" for p in range(2)], ["word"]
            )
            if folded == p1_expected:
                break
            time.sleep(0.2)
        assert folded == p1_expected, folded
        # phase-1 freeze: resize SIGTERMs the group, the workers stop
        # gracefully at a tick boundary, and the final commit snapshots
        # — the handoff cut covers the whole durable log
        sup.resize(
            3, reshard=lambda: reshard_stores(roots[:2], roots)
        )
        deadline = time.monotonic() + 120
        while (
            not any(e[1] == "group-resize" for e in sup.events)
            and time.monotonic() < deadline
        ):
            time.sleep(0.1)
        assert any(e[1] == "group-resize" for e in sup.events), sup.events
        assert not any(e[1] == "resize-rollback" for e in sup.events), (
            sup.events
        )

        # phase 2: traffic to every rank, including the NEW one
        phase2 = {0: ["a", "e"], 1: ["e", "b"], 2: ["f", "a", "d"]}
        for pid, words in phase2.items():
            write_words(pid, "f2.jsonl", words)
        expected = {
            ("a",): (6,), ("b",): (3,), ("c",): (2,), ("d",): (3,),
            ("e",): (2,), ("f",): (1,),
        }
        # fold INCARNATION-major: within one incarnation each word's
        # updates come from exactly one rank (disjoint ownership), and
        # all inc-0 activity strictly precedes inc-1 — rank-major order
        # could fold a re-homed key's update before its install
        out_paths = [
            base / f"out{p}_inc{i}.jsonl"
            for i in range(2)
            for p in range(3)
        ]
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            folded = fold_diff_stream(out_paths, ["word"])
            if folded == expected:
                break
            time.sleep(0.2)
        assert folded == expected, folded
        (base / "STOP").touch()
        th.join(timeout=90)
        assert not th.is_alive(), "supervised group never stopped"
        # the grown group restored from MOVED state, not the log
        replayed = {}
        for p in range(3):
            log = base / "logs" / f"rank{p}-inc1.log"
            for line in log.read_text().splitlines():
                if line.startswith("REPLAYED "):
                    replayed[p] = int(line.split()[1])
        assert replayed == {0: 0, 1: 0, 2: 0}, replayed
    finally:
        (base / "STOP").touch()
        sup.stop()
        th.join(timeout=30)
