"""Ported reference custom-reducer + sorting suites (reference:
python/pathway/tests/test_reducers.py, test_sorting.py)."""

import math

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T
from ref_utils import (
    assert_table_equality,
    assert_table_equality_wo_index,
    assert_table_equality_wo_types,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    yield
    pw.internals.parse_graph.G.clear()


class CustomCntAccumulator(pw.BaseCustomAccumulator):
    def __init__(self, cnt):
        self.cnt = cnt

    @classmethod
    def from_row(cls, val):
        return cls(1)

    def update(self, other):
        self.cnt += other.cnt

    def compute_result(self) -> int:
        return self.cnt


custom_cnt = pw.reducers.udf_reducer(CustomCntAccumulator)


def test_custom_count_static():
    left = T(
        """
            pet  |  owner  | age
            dog  | Alice   | 10
            dog  | Bob     | 9
            cat  | Alice   | 8
            dog  | Bob     | 7
        """
    )
    left_res = left.groupby(left.pet).reduce(left.pet, cnt=custom_cnt())
    assert_table_equality(
        left_res,
        T(
            """
                pet | cnt
                dog | 3
                cat | 1
            """,
            id_from=["pet"],
        ),
    )


def test_custom_count_dynamic():
    left = T(
        """
            pet  |  owner  | age | __time__ | __diff__
            dog  | Alice   | 10  | 0        | 1
            dog  | Bob     | 9   | 0        | 1
            cat  | Alice   | 8   | 0        | 1
            dog  | Bob     | 7   | 0        | 1
            dog  | Bob     | 7   | 2        | -1
            cat  | Bob     | 9   | 4        | 1
        """
    )
    left_res = left.groupby(left.pet).reduce(left.pet, cnt=custom_cnt())
    assert_table_equality(
        left_res,
        T(
            """
                pet | cnt
                dog | 2
                cat | 2
            """,
            id_from=["pet"],
        ),
    )


def test_custom_count_null():
    left = T(
        """
            pet  |  owner  | age | __time__ | __diff__
            dog  | Alice   | 10  | 0        | 1
            dog  | Alice   | 10  | 2        | -1
        """
    )
    left_res = left.groupby(left.pet).reduce(cnt=custom_cnt())
    assert_table_equality(left_res, pw.Table.empty(cnt=int))


class CustomCntWithRetractAccumulator(CustomCntAccumulator):
    def retract(self, other) -> None:
        self.cnt -= other.cnt


custom_cnt_with_retract = pw.reducers.udf_reducer(
    CustomCntWithRetractAccumulator
)


def test_custom_count_retract_dynamic():
    left = T(
        """
            pet  |  owner  | age | __time__ | __diff__
            dog  | Alice   | 10  | 0        | 1
            dog  | Bob     | 9   | 0        | 1
            cat  | Alice   | 8   | 0        | 1
            dog  | Bob     | 7   | 0        | 1
            dog  | Bob     | 7   | 2        | -1
            cat  | Bob     | 9   | 4        | 1
        """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet, cnt=custom_cnt_with_retract()
    )
    assert_table_equality(
        left_res,
        T(
            """
                pet | cnt
                dog | 2
                cat | 2
            """,
            id_from=["pet"],
        ),
    )


def test_custom_count_retract_null():
    left = T(
        """
            pet  |  owner  | age | __time__ | __diff__
            dog  | Alice   | 10  | 0        | 1
            dog  | Alice   | 10  | 2        | -1
        """
    )
    left_res = left.groupby(left.pet).reduce(cnt=custom_cnt_with_retract())
    assert_table_equality(left_res, pw.Table.empty(cnt=int))


class CustomMeanStdevAccumulator(pw.BaseCustomAccumulator):
    def __init__(self, sum, sum2, count):
        self.sum = sum
        self.sum2 = sum2
        self.count = count

    @classmethod
    def from_row(cls, row):
        [a] = row
        return CustomMeanStdevAccumulator(a, a * a, 1)

    def update(self, other):
        self.sum += other.sum
        self.sum2 += other.sum2
        self.count += other.count

    def compute_result(self) -> tuple[float, float]:
        mean = self.sum / self.count
        stdev = math.sqrt(self.sum2 / self.count - mean**2)
        return mean, stdev


custom_mean_stdev = pw.reducers.udf_reducer(CustomMeanStdevAccumulator)


def test_custom_mean_stdev():
    left = T(
        """
            pet  |  owner  | age
            cat  | Alice   | 10
            dog  | Bob     | 9
            cat  | Alice   | 8
            dog  | Bob     | 7
        """
    )
    left_res = left.groupby(left.pet).reduce(
        left.pet, mean_stdev=custom_mean_stdev(pw.this.age)
    )
    left_res = left_res.with_columns(
        mean=pw.this.mean_stdev[0], stdev=pw.this.mean_stdev[1]
    ).without(pw.this.mean_stdev)
    assert_table_equality_wo_types(
        left_res,
        T(
            """
                pet | mean | stdev
                dog | 8    | 1
                cat | 9    | 1
            """,
            id_from=["pet"],
        ),
    )


def test_stateful_single_nullary():
    left = T(
        """
            pet  |  owner  | age
            dog  | Alice   | 10
            dog  | Bob     | 9
            cat  | Alice   | 8
            dog  | Bob     | 7
        """
    )

    @pw.reducers.stateful_single
    def count(state):
        return state + 1 if state is not None else 1

    left_res = left.groupby(left.pet).reduce(left.pet, cnt=count())
    assert_table_equality_wo_types(
        left_res,
        T(
            """
                pet | cnt
                dog | 3
                cat | 1
            """,
            id_from=["pet"],
        ),
    )


def test_stateful_many_nullary():
    left = T(
        """
            pet  |  owner  | age
            dog  | Alice   | 10
            dog  | Bob     | 9
            cat  | Alice   | 8
            dog  | Bob     | 7
        """
    )

    @pw.reducers.stateful_many
    def count(state, rows):
        new_state = state if state is not None else 0
        for row, cnt in rows:
            new_state += cnt
        return new_state if new_state != 0 else None

    left_res = left.groupby(left.pet).reduce(left.pet, cnt=count())
    assert_table_equality_wo_types(
        left_res,
        T(
            """
                pet | cnt
                dog | 3
                cat | 1
            """,
            id_from=["pet"],
        ),
    )


def test_stateful_single_unary():
    left = T(
        """
            pet  |  owner  | age
            dog  | Alice   | 10
            dog  | Bob     | 9
            cat  | Alice   | 8
            dog  | Bob     | 7
        """
    )

    @pw.reducers.stateful_single
    def lens(state, val):
        if state is None:
            return len(val)
        return state + len(val)

    left_res = left.groupby(left.pet).reduce(left.pet, lens=lens(left.owner))
    assert_table_equality_wo_types(
        left_res,
        T(
            """
                pet | lens
                dog | 11
                cat | 5
            """,
            id_from=["pet"],
        ),
    )


def test_stateful_many_unary():
    left = T(
        """
            pet  |  owner  | age
            dog  | Alice   | 10
            dog  | Bob     | 9
            cat  | Alice   | 8
            dog  | Bob     | 7
        """
    )

    @pw.reducers.stateful_many
    def lens(state, rows):
        new_state = state if state is not None else 0
        for [data], cnt in rows:
            new_state += len(data) * cnt
        return new_state if new_state != 0 else None

    left_res = left.groupby(left.pet).reduce(left.pet, lens=lens(left.owner))
    assert_table_equality_wo_types(
        left_res,
        T(
            """
                pet | lens
                dog | 11
                cat | 5
            """,
            id_from=["pet"],
        ),
    )


def test_stateful_single_binary():
    left = T(
        """
            pet  |  owner  | age
            dog  | Alice   | 10
            dog  | Bob     | 9
            cat  | Alice   | 8
            dog  | Bob     | 7
        """
    )

    @pw.reducers.stateful_single
    def lens(state, s, i):
        if state is None:
            return len(s) * i
        return state + len(s) * i

    left_res = left.groupby(left.pet).reduce(
        left.pet, lens=lens(left.owner, left.age)
    )
    assert_table_equality_wo_types(
        left_res,
        T(
            """
                pet | lens
                dog | 98
                cat | 40
            """,
            id_from=["pet"],
        ),
    )


def test_stateful_many_binary():
    left = T(
        """
            pet  |  owner  | age
            dog  | Alice   | 10
            dog  | Bob     | 9
            cat  | Alice   | 8
            dog  | Bob     | 7
        """
    )

    @pw.reducers.stateful_many
    def lens(state, rows):
        new_state = state if state is not None else 0
        for [s, i], cnt in rows:
            new_state += len(s) * i * cnt
        return new_state if new_state != 0 else None

    left_res = left.groupby(left.pet).reduce(
        left.pet, lens=lens(left.owner, left.age)
    )
    assert_table_equality_wo_types(
        left_res,
        T(
            """
                pet | lens
                dog | 98
                cat | 40
            """,
            id_from=["pet"],
        ),
    )


# --- sorting (reference: test_sorting.py) ----------------------------------


def test_argmin():
    t = T(
        """
        hash
        931894100059286216
        1339595727108001898
        1793254503348522670
        97653197660818656
        301593703415097707
        """,
    )
    r = t.reduce(key=pw.reducers.argmin(t.hash))
    assert_table_equality_wo_index(
        r,
        T(
            """
            key
            3
            """,
        ).with_columns(key=t.pointer_from(pw.this.key)),
    )


def test_prevnext_single_instance():
    nodes = T(
        """
            | key | instance
        1 |  1  | 42
        2 |  5  | 42
        3 |  3  | 42
        4 |  8  | 42
        5 |  2  | 42
        """
    )
    result = nodes.sort(key=nodes.key, instance=nodes.instance)
    assert_table_equality(
        result,
        T(
            """
                | next | prev
            1   |  5   |
            2   |  4   | 3
            3   |  2   | 5
            4   |      | 2
            5   |  3   | 1
            """,
        ).select(
            prev=nodes.pointer_from(pw.this.prev, optional=True),
            next=nodes.pointer_from(pw.this.next, optional=True),
        ),
    )


def test_prevnext_many_instance():
    nodes = T(
        """
          | key | instance
        1 |  1  | 42
        2 |  1  | 28
        3 |  5  | 42
        4 |  5  | 28
        5 |  3  | 42
        6 |  3  | 28
        7 |  8  | 42
        8 |  8  | 28
        9 |  2  | 42
        10|  2  | 28
        """
    )
    result = nodes.sort(key=nodes.key, instance=nodes.instance)
    assert_table_equality(
        result,
        T(
            """
                | next | prev
            1   |  9   |
            2   |  10   |
            3   |  7   | 5
            4   |  8   | 6
            5   |  3   | 9
            6   |  4   | 10
            7   |      | 3
            8   |      | 4
            9   |  5   | 1
            10   |  6   | 2
            """,
        ).select(
            prev=nodes.pointer_from(pw.this.prev, optional=True),
            next=nodes.pointer_from(pw.this.next, optional=True),
        ),
    )
