"""Full port of the reference outer-join suite (reference:
python/pathway/tests/test_joins.py — 39 functions: left/right/outer
joins, desugaring, set_id, chaining with and without conditions, smart
cols, universe asserts). Mechanical port: package and imports adapted,
fixtures kept identical."""

from __future__ import annotations

from typing import Optional

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.internals.parse_graph import G
from tests.ref_utils import (
    assert_table_equality,
    assert_table_equality_wo_index,
    assert_table_equality_wo_index_types,
)

def test_left_join_01():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        a   | t2_a  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        14  | 14    | 328
        """
    ).update_types(
        s=Optional[int],
        t2_a=Optional[int],
    )

    res = t1.join_left(t2, t1.a == t2.a).select(
        t1.a,
        t2_a=t2.a,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_universe_asserts():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    xxx = t1.join_left(t2, t1.a == t2.a)
    yyy = t1.join_left(t2, t1.a == t2.a)
    pw.universes.promise_are_equal(xxx, yyy)

    res_x = xxx.select(
        t1.a,
        t2_a=t2.a,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    res_y = yyy.select(
        t1.a,
        t2_a=t2.a,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    assert_table_equality_wo_index(res_x, res_y)


def test_left_join_015():
    t1 = T(
        """
            | a
          1 | 11
          2 | 12
          3 | 13
          4 | 14
        """
    )

    t2 = T(
        """
            | c
          1 | 11
          2 | 12
          3 | 13
          4 | 13
        """
    )

    expected = T(
        """
          | a
        1 | 11
        2 | 12
        3 | 13
        4 | 13
        5 |
        """
    )

    res = t1.join_left(t2, t1.a == t2.c).select(
        a=t2.c  # pw.require(t1.a + t2.c, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_02():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 13 | 214
        """
    )

    expected = T(
        """
        a   | t2_c  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        13  | 13    | 327
        14  |       |
        """
    )

    res = t1.join_left(t2, t1.a == t2.c).select(
        t1.a,
        t2_c=t2.c,
        s=pw.require(t1.b + t2.d, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_03():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 13 | 214
        """
    )

    expected = T(
        """
        a   | t1_a2  | s
        11  | 121    | 322
        12  | 144    | 324
        13  | 169    | 326
        13  | 169    | 327
        14  | 196    |
        """
    )

    res = t1.join_left(t2, t1.a == t2.c).select(
        t1.a,
        t1_a2=t1.a * t1.a,
        s=pw.require(t1.b + t2.d, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_right_join_01():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        a   | t2_a  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        14  | 14    | 328
        """
    ).update_types(
        a=Optional[int],
        s=Optional[int],
    )

    res = t1.join_right(t2, t1.a == t2.a).select(
        t1.a,
        t2_a=t2.a,
        s=pw.require(t1.b + t2.d, t1.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_right_join_02():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 13 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        a   | t2_c  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        13  | 13    | 327
            | 14    |
        """
    )

    res = t1.join_right(t2, t1.a == t2.c).select(
        t1.a,
        t2_c=t2.c,
        s=pw.require(t1.b + t2.d, t1.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_right_join_03():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 13 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        a   | t2_c2  | s
        11  | 121    | 322
        12  | 144    | 324
        13  | 169    | 326
        13  | 169    | 327
            | 196    |
        """
    )

    res = t1.join_right(t2, t1.a == t2.c).select(
        t1.a,
        t2_c2=t2.c * t2.c,
        s=pw.require(t1.b + t2.d, t1.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_empty_duplicates_01():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 13 | 212
          3 | 13 | 213
          4 | 13 | 214
        """
    )

    expected = T(
        """
        t2_c2  | s
        121    | 322
        169    | 325
        169    | 326
        169    | 327
               |
               |
        """
    )

    res = t1.join_left(t2, t1.a == t2.c).select(
        t2_c2=pw.require(t2.c * t2.c, t2.id),
        s=pw.require(t1.b + t2.d, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_duplicates_02():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 13 | 212
          3 | 13 | 213
          4 | 13 | 214
        """
    )

    expected = T(
        """
        t1_a2  | s
        121    | 122
        169    | 126
        169    | 126
        169    | 126
        144    | 124
        196    | 128
        """
    )

    res = t1.join_left(t2, t1.a == t2.c).select(
        t1_a2=t1.a * t1.a,
        s=t1.a + t1.b,
    )
    assert_table_equality_wo_index(res, expected)


def test_right_join_empty_duplicates_01():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 13 | 112
          3 | 13 | 113
          4 | 13 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        t1_a2  | s
        121    | 322
        169    | 325
        169    | 326
        169    | 327
               |
               |
        """
    )

    res = t1.join_right(t2, t1.a == t2.c).select(
        t1_a2=pw.require(t1.a * t1.a, t1.id),
        s=pw.require(t1.b + t2.d, t1.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_right_join_duplicates_02():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 13 | 112
          3 | 13 | 113
          4 | 13 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        t2_c2  | s
        121    | 222
        169    | 226
        169    | 226
        169    | 226
        144    | 224
        196    | 228
        """
    )

    res = t1.join_right(t2, t1.a == t2.c).select(t2_c2=t2.c * t2.c, s=t2.c + t2.d)
    assert_table_equality_wo_index(res, expected)


def test_left_join_this():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        a   | t2_a  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        14  | 14    | 328
        """
    ).update_types(
        t2_a=Optional[int],
        s=Optional[int],
    )

    res = t1.join_left(t2, t1.a == t2.a).select(
        pw.left.a,
        t2_a=t2.a,
        s=pw.require(pw.left.b + t2.d, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join_01():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        a   | t2_a  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        14  | 14    | 328
        """
    ).update_types(
        a=Optional[int],
        t2_a=Optional[int],
        s=Optional[int],
    )

    res = t1.join_outer(t2, t1.a == t2.a).select(
        t1.a,
        t2_a=t2.a,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join_02():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 13 | 214
        """
    )

    expected = T(
        """
        a   | t2_c  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        13  | 13    | 327
        14  |       |
        """
    ).update_types(a=Optional[int])

    res = t1.join_outer(t2, t1.a == t2.c).select(
        t1.a,
        t2_c=t2.c,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join_03():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 13 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        a   | t2_c  | s
        11  | 11    | 322
        12  | 12    | 324
        13  | 13    | 326
        13  | 13    | 327
            | 14    |
        """
    ).update_types(t2_c=Optional[int])

    res = t1.join_outer(t2, t1.a == t2.c).select(
        t1.a,
        t2_c=t2.c,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join_04():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 13 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 14 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
        a   | t2_c  | s
        11  | 11    | 322
        12  | 12    | 324
        13  |       |
        13  |       |
            | 14    |
            | 14    |
        """
    )

    res = t1.join_outer(t2, t1.a == t2.c).select(
        t1.a,
        t2_c=t2.c,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join_smart_cols():
    t1 = T(
        """
            | a
          1 | 11
          2 | 12
          3 | 13
          4 | 14
        """
    )

    t2 = T(
        """
            | a
          2 | 12
          3 | 13
          4 | 14
          5 | 15
        """
    )

    expected = T(
        """
         a | la  | ra
        11 | 11  |
        12 | 12  | 12
        13 | 13  | 13
        14 | 14  | 14
        15 |     | 15
        """
    ).update_types(a=Optional[int])

    res = t1.join_outer(t2, t1.a == t2.a).select(
        pw.this.a,
        la=pw.left.a,
        ra=pw.right.a,
    )
    assert_table_equality_wo_index(res, expected)


def test_chained_outer_join_smart_cols():
    t1 = T(
        """
            | a
          1 | 11
          2 | 12
          3 | 13
          4 | 14
        """
    )

    t2 = T(
        """
            | a
          2 | 12
          3 | 13
          4 | 14
          5 | 15
        """
    )

    t3 = T(
        """
            | a
          3 | 13
          4 | 14
          5 | 15
          6 | 16
        """
    )

    expected = T(
        """
         a | la  | ra | lla | lra
        11 | 11  |    |  11 |
        12 | 12  |    |  12 | 12
        13 | 13  | 13 |  13 | 13
        14 | 14  | 14 |  14 | 14
        15 | 15  | 15 |     | 15
        16 |     | 16 |     |
        """
    ).update_types(a=Optional[int])

    res = (
        t1.join_outer(t2, t1.a == t2.a)
        .join_outer(t3, pw.left.a == t3.a)
        .select(
            pw.this.a,
            la=pw.left.a,
            ra=pw.right.a,
            lla=t1.a,
            lra=t2.a,
        )
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_set_id_01():
    # ID-s pf t1 and t2 overlap, but are not equal
    # - equal sets of input ID could make test false positive,
    # - overlapping is more difficult to handle than completely disjoint
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          3 | 11 | 211
          4 | 12 | 212
          5 | 13 | 213
          6 | 14 | 214
        """
    )

    res1 = t1.join_left(t2, t1.a == t2.a, id=t1.id)
    assert G.universe_solver.query_are_equal(res1._universe, t1._universe)
    assert_table_equality(res1.select(), t1.select())

    with pytest.raises(KeyError):
        t1.join_left(t2, t1.a == t2.a, id=t2.id)


def test_left_join_set_id_02():
    # ID-s pf t1 and t2 overlap, but are not equal
    # - equal sets of input ID could make test false positive,
    # - overlapping is more difficult to handle than completely disjoint
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          3 | 11 | 211
          4 | 12 | 212
          5 | 13 | 213
          6 | 15 | 214
        """
    )
    # selecting A is relevant for this test;
    # once it behaved differently on select() and select select(t1.A)
    res = t1.join_left(t2, t1.a == t2.a, id=t1.id).select(t1.a)
    assert G.universe_solver.query_are_equal(res._universe, t1._universe)
    assert_table_equality(res.select(), t1.select())


def test_right_join_set_id_01():
    # ID-s pf t1 and t2 overlap, but are not equal
    # - equal sets of input ID could make test false positive,
    # - overlapping is more difficult to handle than completely disjoint
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          3 | 11 | 211
          4 | 12 | 212
          5 | 13 | 213
          6 | 14 | 214
        """
    )

    res2 = t1.join_right(t2, t1.a == t2.a, id=t2.id)
    assert G.universe_solver.query_are_equal(res2._universe, t2._universe)
    assert_table_equality(res2.select(), t2.select())

    with pytest.raises(KeyError):
        t1.join_right(t2, t1.a == t2.a, id=t1.id)


def test_right_join_set_id_02():
    # ID-s pf t1 and t2 overlap, but are not equal
    # - equal sets of input ID could make test false positive,
    # - overlapping is more difficult to handle than completely disjoint
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          3 | 11 | 211
          4 | 12 | 212
          5 | 13 | 213
          6 | 15 | 214
        """
    )

    res = t1.join_right(t2, t1.a == t2.a, id=t2.id)
    assert G.universe_solver.query_are_equal(res._universe, t2._universe)
    assert_table_equality(res.select(), t2.select())


def test_outer_join_set_id_01():
    # ID-s pf t1 and t2 overlap, but are not equal
    # - equal sets of input ID could make test false positive,
    # - overlapping is more difficult to handle than completely disjoint
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          3 | 11 | 211
          4 | 12 | 212
          5 | 13 | 213
          6 | 14 | 214
        """
    )

    with pytest.raises(KeyError):
        t1.join_outer(t2, t1.a == t2.a, id=t2.id)

    with pytest.raises(KeyError):
        t1.join_outer(t2, t1.a == t2.a, id=t1.id)


def test_outer_join_set_id_02():
    # ID-s pf t1 and t2 overlap, but are not equal
    # - equal sets of input ID could make test false positive,
    # - overlapping is more difficult to handle than completely disjoint
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          3 | 11 | 211
          4 | 12 | 212
          5 | 13 | 213
          6 | 14 | 214
        """
    )
    with pytest.raises(AssertionError):
        t1.join_outer(t2, t1.a == t2.a, id=t1.a)


def test_outer_join_desugaring_01():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 13 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 14 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
                  | a   | t2_c  | s
        1139487   | 11  | 11    | 322
        1243425   | 12  | 12    | 324
        2145425   | 13  |       |
        2145234   | 13  |       |
        1234412   |     | 14    |
        1541234   |     | 14    |
        """
    )

    res = t1.join_outer(t2, t1.a == t2.c).select(
        pw.left.a,
        t2_c=pw.right.c,
        s=pw.require(t1.b + t2.d, t1.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join_desugaring_02():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
                  | a   | t2_a  | s
        1139487   | 11  | 11    | 322
        1243425   | 12  | 12    | 324
        2145425   | 13  | 13    | 326
        1234412   | 14  | 14    | 328
        """
    ).update_types(
        a=Optional[int],
        t2_a=Optional[int],
        s=Optional[int],
    )

    res = t1.join_outer(t2, pw.left.a == pw.right.a).select(
        t1.a,
        t2_a=t2.a,
        s=pw.require(
            pw.left.b + pw.right.d,
            pw.left.id,
            pw.right.id,
        ),
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join_desugaring_03():
    # ID-s pf t1 and t2 overlap, but are not equal
    # - equal sets of input ID could make test false positive,
    # - overlapping is more difficult to handle than completely disjoint
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | a  | d
          3 | 11 | 211
          4 | 12 | 212
          5 | 13 | 213
          6 | 14 | 214
        """
    )

    with pytest.raises(KeyError):
        t1.join_outer(t2, t1.a == t2.a, id=pw.left.id)
    with pytest.raises(KeyError):
        t1.join_outer(t2, t1.a == t2.a, id=pw.right.id)


def test_right_join_desugaring_01():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 13 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
                  | a   | t2_c  | s
        1139487   | 11  | 11    | 322
        1243425   | 12  | 12    | 324
        2145425   | 13  | 13    | 326
        2145234   | 13  | 13    | 327
        1234412   |     | 14    |
        """
    )

    res = t1.join_right(t2, t1.a == pw.right.c).select(
        pw.left.a,
        t2_c=t2.c,
        s=pw.require(
            pw.left.b + pw.right.d,
            pw.left.id,
            pw.right.id,
        ),
    )
    assert_table_equality_wo_index(res, expected)


def test_left_join_desugaring_01():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 14 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 13 | 214
        """
    )

    expected = T(
        """
                  | a   | t2_c  | s
        1139487   | 11  | 11    | 322
        1243425   | 12  | 12    | 324
        2145425   | 13  | 13    | 326
        2145234   | 13  | 13    | 327
        1234412   | 14  |       |
        """
    )

    res = t1.join_left(t2, pw.left.a == t2.c).select(
        t1.a,
        t2_c=pw.right.c,
        s=pw.require(pw.left.b + t2.d, pw.left.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_right_join_wid_substitute_and_desugaring():
    t1 = T(
        """
            | a  | b
          1 | 11 | 111
          2 | 12 | 112
          3 | 13 | 113
          4 | 15 | 114
        """
    )

    t2 = T(
        """
            | c  | d
          1 | 11 | 211
          2 | 12 | 212
          3 | 13 | 213
          4 | 14 | 214
        """
    )

    expected = T(
        """
                  | a   | t2_c  | s
        1139487   | 11  | 11    | 322
        1243425   | 12  | 12    | 324
        2145425   | 13  | 13    | 326
        1234412   |     | 14    |
        """
    )

    res = t1.join_right(t2, t1.a == t2.c, id=t2.id).select(
        t1.a,
        t2_c=pw.right.c,
        s=pw.require(pw.left.b + t2.d, pw.left.id, t2.id),
    )
    assert_table_equality_wo_index(res, expected)


def test_outer_join_id():
    t1 = T(
        """
            | a  | b
          1 | a1 | b1
          2 | a2 | b2
        """
    )
    t2 = T(
        """
            | c  | d
          1 | c1 | d1
          3 | c3 | d3
        """
    )
    assert_table_equality(
        t1.join_outer(t2, t1.id == t2.id).select(id_col=pw.this.id),
        t1.join_outer(t2, t1.id == t2.id).select().select(id_col=pw.this.id),
    )


def test_outer_join_chaining_no_cond_leftsided():
    t1 = T(
        """
            | a  | b
          1 | a1 | b1
          2 | a2 | b2
        """
    )
    t2 = T(
        """
            | c  | d
          1 | c1 | d1
          2 | c2 | d2
        """
    )

    t3 = T(
        """
            | e  | f
          1 | e1 | f1
          2 | e2 | f2
        """
    )
    expected = T(
        """
            a  | b  | c  | d  | e  | f
            a1 | b1 | c1 | d1 | e1 | f1
            a1 | b1 | c1 | d1 | e2 | f2
            a1 | b1 | c2 | d2 | e1 | f1
            a1 | b1 | c2 | d2 | e2 | f2
            a2 | b2 | c1 | d1 | e1 | f1
            a2 | b2 | c1 | d1 | e2 | f2
            a2 | b2 | c2 | d2 | e1 | f1
            a2 | b2 | c2 | d2 | e2 | f2
            """
    )
    for tmp in [t1.join(t2), t1.join_outer(t2), t1.join_left(t2), t1.join_right(t2)]:
        for tmp2 in [
            tmp.join(t3),
            tmp.join_outer(t3),
            tmp.join_left(t3),
            tmp.join_right(t3),
        ]:
            from pathway_tpu.internals.joins import JoinMode

            if tmp._join_mode == JoinMode.INNER and tmp2._join_mode == JoinMode.INNER:
                assert_table_equality_wo_index(tmp2.select(*pw.this), expected)
            else:
                assert_table_equality_wo_index_types(tmp2.select(*pw.this), expected)


def test_outer_join_chaining_some_cond():
    t1 = T(
        """
            | a  | b
          1 | a1 | b1
          2 | a2 | b2
        """
    )
    t2 = T(
        """
            | c  | d
          1 | c1 | d1
          3 | c3 | d3
        """
    )

    t3 = T(
        """
            | e  | f
          2 | e2 | f2
          3 | e3 | f3
        """
    )

    assert_table_equality_wo_index(
        t1.join_outer(t2.join_outer(t3, t2.id == t3.id), t1.id == t2.id).select(
            *pw.this
        ),
        T(
            """
         a  | b  | c  | d  | e  | f
            |    |    |    | e2 | f2
            |    | c3 | d3 | e3 | f3
         a1 | b1 | c1 | d1 |    |
         a2 | b2 |    |    |    |
        """
        ),
    )


def test_outer_join_chaining_no_cond_rightsided():
    t1 = T(
        """
            | a  | b
          1 | a1 | b1
          2 | a2 | b2
        """
    )
    t2 = T(
        """
            | c  | d
          1 | c1 | d1
          2 | c2 | d2
        """
    )

    t3 = T(
        """
            | e  | f
          1 | e1 | f1
          2 | e2 | f2
        """
    )

    expected = T(
        """
        a  | b  | c  | d  | e  | f
        a1 | b1 | c1 | d1 | e1 | f1
        a1 | b1 | c1 | d1 | e2 | f2
        a1 | b1 | c2 | d2 | e1 | f1
        a1 | b1 | c2 | d2 | e2 | f2
        a2 | b2 | c1 | d1 | e1 | f1
        a2 | b2 | c1 | d1 | e2 | f2
        a2 | b2 | c2 | d2 | e1 | f1
        a2 | b2 | c2 | d2 | e2 | f2
        """
    )

    for tmp in [t2.join(t3), t2.join_outer(t3), t2.join_left(t3), t2.join_right(t3)]:
        for tmp2 in [
            t1.join(tmp),
            t1.join_outer(tmp),
            t1.join_left(tmp),
            t1.join_right(tmp),
        ]:
            from pathway_tpu.internals.joins import JoinMode

            if tmp._join_mode == JoinMode.INNER and tmp2._join_mode == JoinMode.INNER:
                assert_table_equality_wo_index(tmp2.select(*pw.this), expected)
            else:
                assert_table_equality_wo_index_types(tmp2.select(*pw.this), expected)


def test_outer_join_chaining_cond():
    t1 = T(
        """
            | a  | col
          1 | a1 | 1
          2 | a2 | 2
          3 | a3 | 3
          4 | a4 | 4
        """
    )

    t2 = T(
        """
            | b  | col
          1 | b1 | 1
          3 | b3 | 3
          5 | b5 | 5
          7 | b7 | 7
        """
    )

    t3 = T(
        """
            | c  | col
          1 | c1 | 1
          2 | c2 | 2
          5 | c5 | 5
          6 | c6 | 6
        """
    )
    assert_table_equality_wo_index(
        t1.join_outer(t2, t1.col == t2.col)
        .join_outer(t3, t1.col == t3.col)
        .select(t1.a, t2.b, t3.c, col1=t1.col, col2=t2.col, col3=t3.col),
        T(
            """
         a  | b  | c  | col1 | col2 | col3
            |    | c5 |      |      | 5
            |    | c6 |      |      | 6
            | b5 |    |      | 5    |
            | b7 |    |      | 7    |
         a1 | b1 | c1 | 1    | 1    | 1
         a2 |    | c2 | 2    |      | 2
         a3 | b3 |    | 3    | 3    |
         a4 |    |    | 4    |      |
        """
        ),
    )


def test_leftjoin_chain_assign_id():
    left_table = T(
        """
           | a  | b
        1  | a1 | b1
        2  | a2 | b2
        3  | a3 | b3
        4  | a4 | b4
        """
    )

    middle_table = T(
        """
            | bb  | c
        11  | b2 | c2
        12  | b3 | c3
        13  | b4 | c4
        14  | b5 | c5
        """
    )

    right_table = T(
        """
           | cc  | d
        21 | c3 | d3
        22 | c4 | d4
        23 | c5 | d5
        24 | c6 | d6
        """
    )

    assert_table_equality(
        left_table.join_left(middle_table, pw.left.b == pw.right.bb, id=pw.left.id)
        .join_left(right_table, pw.left.c == pw.right.cc, id=pw.left.id)
        .select(*pw.this),
        T(
            """
          | a  | b  | bb | c  | cc | d
        1 | a1 | b1 |    |    |    |
        2 | a2 | b2 | b2 | c2 |    |
        3 | a3 | b3 | b3 | c3 | c3 | d3
        4 | a4 | b4 | b4 | c4 | c4 | d4
        """
        ),
    )


def test_joins_typing_on():
    left_table = pw.Table.empty(col=int)
    right_table = pw.Table.empty(col=str)
    with pytest.raises(expected_exception=TypeError):
        left_table.join(right_table, left_table.col == right_table.col)


def test_use_other_column_after_left_join_preserving_universe():
    t1 = pw.debug.table_from_markdown(
        """
        a | b
        1 | 2
        3 | 4
        5 | 3
    """
    )
    t2 = pw.debug.table_from_markdown(
        """
        b |  c
        2 | 10
        4 | 11
    """
    )
    t3 = t1.select(a=pw.this.a + 1)
    res = (
        t1.join_left(t2, pw.left.b == pw.right.b, id=pw.left.id).select(
            pw.left.b, pw.right.c
        )
        + t3
    )
    expected = T(
        """
        b |  c | a
        2 | 10 | 2
        4 | 11 | 4
        3 |    | 6
    """
    )
    assert_table_equality(res, expected)
