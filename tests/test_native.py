"""Native kernel parity: the C++ hashing/consolidation must be
byte-identical to the pure-Python fallback (persisted snapshots written by
either path must resume under the other).
(reference native analog: src/engine/value.rs Key::for_values)."""

import numpy as np
import pytest

from pathway_tpu.internals import api
from pathway_tpu.internals.native import get_native

nat = get_native()
pytestmark = pytest.mark.skipif(
    nat is None, reason="native extension unavailable (no g++?)"
)

VALUES = [
    None,
    True,
    False,
    0,
    1,
    -1,
    2**62,
    -(2**62),
    2**70,
    -(2**100),
    1.0,
    -1.5,
    3.14159,
    float("nan"),
    float("inf"),
    "",
    "hello",
    "ünïcødé",
    b"",
    b"\x00\xff",
    (),
    (1, 2),
    ("a", (None, 2.0)),
    [1, "x"],
    api.Pointer(12345),
    {"k": 1},
    float(2**53),
    float(-(2**53) + 1),
    np.int64(7),
    np.float64(2.5),
    np.array([1.0, 2.0]),
]


def test_hash_parity_all_value_shapes():
    for v in VALUES:
        t = (v,)
        assert nat.hash_value(t) == api._hash_bytes(api._value_bytes(t)), v


def test_int_float_key_equivalence():
    assert nat.hash_value((1,)) == nat.hash_value((1.0,))
    assert api.ref_scalar(1) == api.ref_scalar(1.0)


def test_batch_column_hashing_matches_scalar():
    cols = [list(range(100)), [f"s{i}" for i in range(100)]]
    arr = api.ref_scalars_columns(cols, 100)
    for i in (0, 37, 99):
        assert arr[i] == int(api.ref_scalar(cols[0][i], cols[1][i]))


def test_native_consolidate_groups_and_drops_zeros():
    keys = np.array([1, 2, 1, 3, 2, 1], dtype=np.uint64)
    vh = np.array([9, 8, 9, 7, 8, 5], dtype=np.uint64)
    diffs = np.array([1, 1, -1, 1, 1, 1], dtype=np.int64)
    idx_b, d_b = nat.consolidate(keys.tobytes(), vh.tobytes(), diffs.tobytes())
    idx = np.frombuffer(idx_b, dtype=np.int64)
    d = np.frombuffer(d_b, dtype=np.int64)
    # (1,9): +1-1 dropped; (2,8): 1+1=2; (3,7): 1; (1,5): 1
    assert idx.tolist() == [1, 3, 5]
    assert d.tolist() == [2, 1, 1]


def test_consolidate_fallback_matches_native():
    """Same-key rows with values differing from the first-seen entry must
    cancel identically in both paths (review regression)."""
    import os
    from pathway_tpu.engine.batch import DiffBatch

    rows = [
        (1, 1, ("a",)),
        (1, 1, ("b",)),
        (1, -1, ("b",)),
        (2, 1, (float("nan"),)),
        (2, -1, (float("nan"),)),
    ]
    b = DiffBatch.from_rows(rows, ["v"])
    native_out = sorted(
        (k, d, repr(v)) for k, d, v in b.consolidate().iter_rows()
    )
    os.environ["PATHWAY_NO_NATIVE"] = "1"
    try:
        import pathway_tpu.internals.native as nmod

        saved = (nmod._native, nmod._tried)
        nmod._native, nmod._tried = None, True
        py_out = sorted(
            (k, d, repr(v)) for k, d, v in b.consolidate().iter_rows()
        )
    finally:
        nmod._native, nmod._tried = saved
        del os.environ["PATHWAY_NO_NATIVE"]
    assert native_out == py_out == [(1, 1, "('a',)")]


def test_diffbatch_consolidate_native_path():
    from pathway_tpu.engine.batch import DiffBatch

    b = DiffBatch.from_rows(
        [(1, 1, ("a",)), (2, 1, ("b",)), (1, -1, ("a",)), (1, 1, ("a2",))],
        ["v"],
    )
    out = b.consolidate()
    got = sorted((int(k), int(d), vals) for k, d, vals in out.iter_rows())
    assert got == [(1, 1, ("a2",)), (2, 1, ("b",))]


def test_match_fk_against_numpy_reference():
    """The C hash-probe join match must produce exactly the pair order of
    the numpy sort+searchsorted fallback (left-input order; equal-key
    right rows in right-input order)."""
    import numpy as np

    from pathway_tpu.internals.api import _get_native

    nat = _get_native()
    if nat is None or not hasattr(nat, "match_fk"):
        import pytest

        pytest.skip("native module not built")
    rng = np.random.default_rng(7)
    for n_l, n_r, keyspace in [(100, 50, 20), (5000, 3000, 1000), (200_000, 50_000, 40_000)]:
        jks_l = rng.integers(0, keyspace, size=n_l).astype(np.uint64)
        jks_r = rng.integers(0, keyspace, size=n_r).astype(np.uint64)
        li_b, ri_b = nat.match_fk(
            np.ascontiguousarray(jks_l), np.ascontiguousarray(jks_r)
        )
        li = np.frombuffer(li_b, np.int64)
        ri = np.frombuffer(ri_b, np.int64)
        order_r = np.argsort(jks_r, kind="stable")
        jr = jks_r[order_r]
        lo = np.searchsorted(jr, jks_l, "left")
        hi = np.searchsorted(jr, jks_l, "right")
        counts = hi - lo
        total = int(counts.sum())
        li2 = np.repeat(np.arange(n_l), counts)
        starts = np.repeat(lo, counts)
        offs = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
        ri2 = order_r[starts + offs]
        assert (li == li2).all() and (ri == ri2).all()


def test_join_live_cols_pruning_correctness():
    """Bulk join output must be identical whether or not the pointer
    columns are pruned: a select reading only data columns (pruned) and a
    select reading _left_id/_right_id ids (not pruned) both correct."""
    import numpy as np

    import pathway_tpu as pw

    pw.internals.parse_graph.G.clear()
    n_l, n_r = 2000, 500

    class L(pw.Schema):
        k: int
        a: int

    class R(pw.Schema):
        k: int
        b: int

    rng = np.random.default_rng(5)
    lk = rng.integers(0, n_r, size=n_l)
    lt = pw.debug.table_from_rows(L, [(int(lk[i]), i) for i in range(n_l)])
    rt = pw.debug.table_from_rows(R, [(int(i), i * 10) for i in range(n_r)])
    jr = lt.join(rt, lt.k == rt.k)
    pruned = jr.select(lt.a, rt.b)
    _, cols = pw.debug.table_to_dicts(pruned)
    assert len(cols["a"]) == n_l
    assert sorted(cols["a"].values()) == list(range(n_l))
    # ids still work when selected (liveness keeps the pointer columns)
    pw.internals.parse_graph.G.clear()
    lt = pw.debug.table_from_rows(L, [(int(lk[i]), i) for i in range(n_l)])
    rt = pw.debug.table_from_rows(R, [(int(i), i * 10) for i in range(n_r)])
    jr = lt.join(rt, lt.k == rt.k)
    with_ids = jr.select(lt.a, left_id=lt.id, right_id=rt.id)
    _, cols = pw.debug.table_to_dicts(with_ids)
    assert len(cols["left_id"]) == n_l
    assert all(v is not None for v in cols["left_id"].values())
    assert all(v is not None for v in cols["right_id"].values())
