"""Port of the reference streaming-window behavior suite (reference:
python/pathway/tests/temporal/test_windows_stream.py — 12 functions):
sliding windows under common_behavior(delay, cutoff, keep_results) and
exactly_once_behavior over a live generated stream, checked against a
simulated buffer/freeze/forget ledger entry by entry."""

from __future__ import annotations

import typing

import pathway_tpu as pw
from tests.ref_utils import (
    DiffEntry,
    assert_key_entries_in_stream_consistent,
    assert_stream_equal,
)


class TimeColumnInputSchema(pw.Schema):
    time: int
    value: int


def get_windows(duration: int, hop: int, time: int):
    lowest_time = time - duration
    lower_time = lowest_time - lowest_time % hop + hop

    ret: list[tuple[int, int]] = []
    while lower_time <= time:
        ret.append((lower_time, lower_time + duration))
        lower_time += hop

    return ret


def generate_buffer_output(input_stream: list, duration, hop, delay, cutoff):
    now = 0
    buffer = {}
    output = []
    for entry in input_stream:
        last_time = now
        now = max(now, entry["time"])

        to_process: list = []
        windows = get_windows(duration, hop, entry["time"])
        for _pw_window_start, _pw_window_end in windows:
            shard = None
            window = (shard, _pw_window_start, _pw_window_end)
            freeze_threshold = window[2] + cutoff
            if freeze_threshold <= now:
                continue

            threshold = window[1] + delay

            if threshold <= now:
                to_process.append((window, entry))
            else:
                key = (window, entry["value"])
                buffer[key] = entry

        bufkeys = list(buffer.keys())

        for window, value in bufkeys:
            entry = buffer[(window, value)]
            threshold = window[1] + delay
            if last_time != now and threshold <= now and threshold > last_time:
                to_process.append((window, entry))
                buffer.pop((window, value))

        output.extend(to_process)

    # flush buffer
    bufkeys = list(buffer.keys())
    for window, value in bufkeys:
        entry = buffer.pop((window, value))
        output.append((window, entry))

    return output


def _input_stream():
    return pw.demo.generate_custom_stream(
        {
            "time": lambda x: (x // 2) % 17,
            "value": lambda x: x,
        },
        schema=TimeColumnInputSchema,
        nb_rows=68,
        autocommit_duration_ms=5,
        input_rate=25,
    )


def test_keep_results_manual():
    t = _input_stream()
    gb = t.windowby(
        t.time,
        window=pw.temporal.sliding(duration=5, hop=3),
        behavior=pw.temporal.common_behavior(
            delay=0, cutoff=0, keep_results=True
        ),
    )

    expected_entries = []
    simulated_state: dict = {}
    max_global_time = 0
    for i in range(68):
        time = (i // 2) % 17
        max_global_time = max(time, max_global_time)

        value = i
        order = i
        window_borders = get_windows(duration=5, hop=3, time=time)

        for _pw_window_start, _pw_window_end in window_borders:
            shard = None
            window = (shard, _pw_window_start, _pw_window_end)
            pk_row = {
                "_pw_window": window,
                "_pw_window_start": _pw_window_start,
                "_pw_window_end": _pw_window_end,
                "_pw_instance": shard,
            }

            entry_id = DiffEntry.create_id_from(gb, pk_row)

            max_value = value
            max_time = time

            old_entry_state = simulated_state.get(entry_id)

            if old_entry_state is not None:
                # cutoff
                if max_global_time < typing.cast(
                    int, old_entry_state.row["_pw_window_end"]
                ):
                    expected_entries.append(
                        DiffEntry.create(
                            gb, pk_row, order, False, old_entry_state.row
                        )
                    )
                max_value = max(
                    max_value,
                    typing.cast(int, old_entry_state.row["max_value"]),
                )
                max_time = max(
                    max_time, typing.cast(int, old_entry_state.row["max_time"])
                )

            row = {
                "_pw_window_end": _pw_window_end,
                "max_value": max_value,
                "max_time": max_time,
            }
            insert_entry = DiffEntry.create(gb, pk_row, order, True, row)

            if max_global_time < typing.cast(
                int, insert_entry.row["_pw_window_end"]
            ):
                simulated_state[entry_id] = insert_entry
                expected_entries.append(insert_entry)

    result = gb.reduce(
        pw.this._pw_window_end,
        max_time=pw.reducers.max(pw.this.time),
        max_value=pw.reducers.max(pw.this.value),
    )
    assert_key_entries_in_stream_consistent(expected_entries, result)

    pw.run(autocommit_duration_ms=5)


def create_windowby_scenario(duration, hop, delay, cutoff, keep_results):
    t = _input_stream()
    gb = t.windowby(
        t.time,
        window=pw.temporal.sliding(duration=duration, hop=hop),
        behavior=pw.temporal.common_behavior(
            delay=delay, cutoff=cutoff, keep_results=keep_results
        ),
    )

    result = gb.reduce(
        pw.this._pw_window_end,
        max_time=pw.reducers.max(pw.this.time),
        max_value=pw.reducers.max(pw.this.value),
    )
    return result


def generate_expected(duration, hop, delay, cutoff, keep_results, result_table):
    entries = []
    for i in range(68):
        entries.append({"value": i, "time": (i // 2) % 17})
    buf_out = generate_buffer_output(
        entries, duration=duration, hop=hop, delay=delay, cutoff=cutoff
    )

    simulated_state: dict = {}
    expected_entries = []
    max_global_time = 0

    for (window, in_entry) in buf_out:
        pk_row = {
            "_pw_window": window,
            "_pw_window_start": window[1],
            "_pw_window_end": window[2],
            "_pw_instance": window[0],
        }

        entry_id = DiffEntry.create_id_from(result_table, pk_row)

        order = in_entry["value"]
        max_value = in_entry["value"]
        max_window_time = in_entry["time"]
        max_global_time = max(
            max(in_entry["time"], window[1] + delay), max_global_time
        )
        old_entry_state = simulated_state.get(entry_id)

        if old_entry_state is not None:
            expected_entries.append(
                DiffEntry.create(
                    result_table, pk_row, order, False, old_entry_state.row
                )
            )

            max_value = max(
                max_value, typing.cast(int, old_entry_state.row["max_value"])
            )
            max_window_time = max(
                max_window_time,
                typing.cast(int, old_entry_state.row["max_time"]),
            )

        row = {
            "_pw_window_end": window[2],
            "max_value": max_value,
            "max_time": max_window_time,
        }
        insert_entry = DiffEntry.create(result_table, pk_row, order, True, row)

        simulated_state[entry_id] = insert_entry
        expected_entries.append(insert_entry)
    if not keep_results:
        for entry in simulated_state.values():
            if entry.row["_pw_window_end"] + cutoff <= max_global_time:
                expected_entries.append(entry.final_cleanup_entry())
    return expected_entries


def parameterized_test(duration, hop, delay, cutoff, keep_results):
    result_table = create_windowby_scenario(
        duration, hop, delay, cutoff, keep_results
    )
    expected = generate_expected(
        duration, hop, delay, cutoff, keep_results, result_table
    )
    assert_key_entries_in_stream_consistent(expected, result_table)
    pw.run(autocommit_duration_ms=5)


def test_keep_results():
    parameterized_test(5, 3, 0, 0, True)


def test_remove_results():
    parameterized_test(5, 3, 0, 0, False)


def test_non_zero_delay_keep_results():
    parameterized_test(5, 3, 1, 0, True)


def test_non_zero_delay_remove_results():
    parameterized_test(5, 3, 1, 0, False)


def test_non_zero_buffer_keep_results():
    parameterized_test(5, 3, 0, 1, True)


def test_non_zero_buffer_remove_results():
    parameterized_test(5, 3, 0, 1, False)


def test_non_zero_delay_non_zero_buffer_keep_results():
    parameterized_test(5, 3, 1, 1, True)


def test_high_delay_high_buffer_keep_results():
    parameterized_test(5, 3, 5, 6, True)


def test_non_zero_delay_non_zero_buffer_remove_results():
    parameterized_test(5, 3, 1, 1, False)


def _create_expected_for_exactly_once(result):
    expected = []
    duration = 5
    for i, window_end in enumerate([2, 5, 8, 11, 14]):
        pk_row: dict = {
            "_pw_window": (None, window_end - duration, window_end),
            "_pw_window_start": window_end - duration,
            "_pw_window_end": window_end,
            "_pw_instance": None,
        }

        row: dict = {
            "_pw_window_end": window_end,
            "max_time": window_end - 1,
            "max_value": 2 * window_end - 1,
        }

        expected.append(DiffEntry.create(result, pk_row, i, True, row))

    # flush buffer
    for order, window_end, max_time, max_value in (
        (17, 17, 16, 67),
        (20, 20, 16, 67),
    ):
        pk_row = {
            "_pw_window": (None, window_end - duration, window_end),
            "_pw_window_start": window_end - duration,
            "_pw_window_end": window_end,
            "_pw_instance": None,
        }
        row = {
            "_pw_window_end": window_end,
            "max_time": max_time,
            "max_value": max_value,
        }
        expected.append(DiffEntry.create(result, pk_row, order, True, row))
    return expected


def test_exactly_once():
    result = create_windowby_scenario(
        duration=5, hop=3, delay=6, cutoff=1, keep_results=True
    )
    expected = _create_expected_for_exactly_once(result)
    assert_stream_equal(expected, result)
    pw.run(autocommit_duration_ms=5)


def test_exactly_once_from_behavior():
    p = 17
    t = pw.demo.generate_custom_stream(
        {
            "time": lambda x: (x // 2) % p,
            "value": lambda x: x,
        },
        schema=TimeColumnInputSchema,
        nb_rows=4 * p,
        autocommit_duration_ms=5,
        input_rate=25,
    )
    gb = t.windowby(
        t.time,
        window=pw.temporal.sliding(duration=5, hop=3),
        behavior=pw.temporal.exactly_once_behavior(),
    )

    result = gb.reduce(
        pw.this._pw_window_end,
        max_time=pw.reducers.max(pw.this.time),
        max_value=pw.reducers.max(pw.this.value),
    )
    expected = _create_expected_for_exactly_once(result)
    assert_stream_equal(expected, result)
    pw.run(autocommit_duration_ms=5)
