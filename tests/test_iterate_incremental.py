"""Incremental iterate: 3 streaming ticks of edge updates into pagerank;
inner node_rows scale with the delta; results match a fresh static run."""

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts
from pathway_tpu.internals.iterate import IterateExec


def _chain_edges(n, prefix, t):
    # directed chain: rank mass propagates one hop per iteration, so the
    # fixpoint needs ~n depths — a real iterative workload
    lines = []
    for i in range(n - 1):
        lines.append(f"{prefix}{i} | {prefix}{i + 1} | {t}")
    return lines


def test_iterate_incremental_pagerank(monkeypatch):
    header = "u | v | __time__"
    rows = _chain_edges(40, "big", 2)
    rows += ["s0 | s1 | 2", "s1 | s2 | 2", "s2 | s3 | 2"]
    # tick 4/6: rewire inside the small (disconnected) component only
    rows += ["s0 | s2 | 4"]
    rows += ["s0 | s3 | 6"]
    edges = T("\n".join([header] + rows))

    per_tick = []
    orig = IterateExec.process

    def wrapped(self, t, inputs):
        before = sum(
            sum(d.runtime.stats.node_rows.values()) for d in self._depths
        )
        out = orig(self, t, inputs)
        after = sum(
            sum(d.runtime.stats.node_rows.values()) for d in self._depths
        )
        n_in = sum(len(b) for bs in inputs for b in bs)
        if n_in:
            per_tick.append((n_in, after - before))
        return out

    monkeypatch.setattr(IterateExec, "process", wrapped)
    res = pw.graphs.pagerank(edges, steps=50)
    _keys, cols = table_to_dicts(res)
    got = {cols["v"][k]: cols["rank"][k] for k in cols["v"]}
    monkeypatch.setattr(IterateExec, "process", orig)

    # ticks recorded: initial bulk + two delta ticks
    assert len(per_tick) == 3, per_tick
    bulk_rows = per_tick[0][1]
    for n_in, delta_rows in per_tick[1:]:
        # a 1-edge delta in a 3-node component must do FAR less inner work
        # than the 43-node bulk tick (it would be ~equal if the fixpoint
        # were recomputed from snapshots)
        assert delta_rows < bulk_rows / 5, (delta_rows, bulk_rows)

    # results identical to a fresh static run over the final edge set
    pw.internals.parse_graph.G.clear()
    final_rows = _chain_edges(40, "big", 0) + [
        "s0 | s1 | 0", "s1 | s2 | 0", "s2 | s3 | 0",
        "s0 | s2 | 0", "s0 | s3 | 0",
    ]
    edges2 = T("\n".join(["u | v"] + [r.rsplit("|", 1)[0].rstrip() for r in final_rows]))
    res2 = pw.graphs.pagerank(edges2, steps=50)
    _k2, cols2 = table_to_dicts(res2)
    want = {cols2["v"][k]: cols2["rank"][k] for k in cols2["v"]}
    assert set(got) == set(want)
    for v in want:
        assert abs(got[v] - want[v]) < 1e-9, (v, got[v], want[v])
