"""Surge Gate (pathway_tpu/serving) tests: config, admission, EDF
micro-batching, overload shedding, deadline drops, drain, and the
webserver lifecycle fix."""

import socket
import threading
import time
from collections import Counter

import pytest

import pathway_tpu as pw
from pathway_tpu.serving import (
    AdmissionController,
    DeadlineExceeded,
    MicroBatcher,
    QoSConfig,
    ShedError,
    TokenBucket,
    default_bucket_ladder,
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Req:
    def __init__(self, key, deadline):
        self.key = key
        self.vals = (key,)
        self.deadline = deadline
        self.enqueued_at = time.monotonic()


# --- config ----------------------------------------------------------------


def test_bucket_ladder():
    assert default_bucket_ladder(32) == (1, 2, 4, 8, 16, 32)
    cfg = QoSConfig(max_batch_size=32)
    assert cfg.bucket_for(1) == 1
    assert cfg.bucket_for(3) == 4
    assert cfg.bucket_for(32) == 32
    assert cfg.bucket_for(100) == 32  # clamped to the top rung
    custom = QoSConfig(max_batch_size=10, batch_buckets=(4, 10))
    assert custom.bucket_for(5) == 10


def test_qos_config_env_overrides(monkeypatch):
    monkeypatch.setenv("PATHWAY_SERVING_MAX_QUEUE", "7")
    monkeypatch.setenv("PATHWAY_SERVING_MAX_BATCH", "4")
    monkeypatch.setenv("PATHWAY_SERVING_MAX_WAIT_MS", "2.5")
    monkeypatch.setenv("PATHWAY_SERVING_RPS", "100")
    cfg = QoSConfig.from_env()
    assert cfg.max_queue == 7
    assert cfg.max_batch_size == 4
    assert cfg.max_wait_ms == 2.5
    assert cfg.rate_limit_rps == 100.0
    # base config survives where no env override exists
    base = QoSConfig(default_deadline_ms=1234.0)
    assert QoSConfig.from_env(base).default_deadline_ms == 1234.0
    monkeypatch.setenv("PATHWAY_SERVING_MAX_QUEUE", "nope")
    with pytest.raises(ValueError):
        QoSConfig.from_env()


def test_qos_config_env_empty_values(monkeypatch):
    # empty value on a mandatory knob = no override (common CI YAML
    # artifact); on a None-able knob = clear back to None
    monkeypatch.setenv("PATHWAY_SERVING_MAX_QUEUE", "")
    monkeypatch.setenv("PATHWAY_SERVING_MAX_WAIT_MS", "")
    monkeypatch.setenv("PATHWAY_SERVING_RPS", "")
    cfg = QoSConfig.from_env(QoSConfig(rate_limit_rps=5.0))
    assert cfg.max_queue == 256
    assert cfg.max_wait_ms == 5.0
    assert cfg.rate_limit_rps is None


def test_qos_config_validation():
    with pytest.raises(ValueError):
        QoSConfig(max_queue=0)
    with pytest.raises(ValueError):
        QoSConfig(priority="bogus")
    assert QoSConfig(max_dispatched=None).dispatch_window() == 64
    assert QoSConfig(max_dispatched=5).dispatch_window() == 5


# --- admission -------------------------------------------------------------


def test_token_bucket():
    tb = TokenBucket(rate=10.0, burst=2.0)
    now = time.monotonic()
    assert tb.try_acquire(now) == 0.0
    assert tb.try_acquire(now) == 0.0
    wait = tb.try_acquire(now)
    assert 0.0 < wait <= 0.1  # ~1/rate until the next token
    # tokens accrue with time
    assert tb.try_acquire(now + 0.2) == 0.0


def test_admission_queue_bound_and_reasons():
    ctl = AdmissionController(
        QoSConfig(max_queue=2, rate_limit_rps=None), route="/t"
    )
    ctl.admit()
    ctl.admit()
    with pytest.raises(ShedError) as e:
        ctl.admit()
    assert e.value.status == 429
    assert e.value.reason == "queue_full"
    assert e.value.retry_after_s > 0
    ctl.on_flushed(2)
    ctl.admit()  # space again
    ctl.start_drain()
    with pytest.raises(ShedError) as e:
        ctl.admit()
    assert e.value.status == 503
    assert e.value.reason == "draining"
    for _ in range(3):
        ctl.complete()
    assert ctl.wait_idle(1.0)


def test_admission_concurrency_cap():
    ctl = AdmissionController(QoSConfig(max_inflight=1), route="/c")
    ctl.admit()
    with pytest.raises(ShedError) as e:
        ctl.admit()
    assert e.value.reason == "concurrency"
    ctl.complete()
    ctl.admit()  # freed


def test_admission_rate_limit():
    ctl = AdmissionController(
        QoSConfig(rate_limit_rps=5.0, rate_limit_burst=1.0), route="/r"
    )
    ctl.admit()
    with pytest.raises(ShedError) as e:
        ctl.admit()
    assert e.value.reason == "rate_limit"
    assert 0 < e.value.retry_after_s <= 0.5


# --- micro-batcher ---------------------------------------------------------


def test_microbatcher_edf_order_and_expiry():
    got = []
    mb = MicroBatcher(
        QoSConfig(max_batch_size=8, max_wait_ms=20),
        dispatch=lambda rs: got.append([r.key for r in rs]),
        reject=lambda r, e: got.append(("rej", r.key, type(e).__name__)),
    )
    try:
        now = time.monotonic()
        mb.put(_Req(1, now + 5))
        mb.put(_Req(2, now + 1))
        mb.put(_Req(3, now + 3))
        deadline = time.time() + 2
        while not got and time.time() < deadline:
            time.sleep(0.01)
        assert got == [[2, 3, 1]]  # earliest deadline first
        mb.put(_Req(4, now - 1))  # already expired: dropped at flush
        deadline = time.time() + 2
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert got[1] == ("rej", 4, "DeadlineExceeded")
    finally:
        mb.close()


def test_microbatcher_flushes_full_batch_immediately():
    got = []
    mb = MicroBatcher(
        QoSConfig(max_batch_size=4, max_wait_ms=10_000),
        dispatch=lambda rs: got.append(len(rs)),
        reject=lambda r, e: None,
    )
    try:
        now = time.monotonic()
        for i in range(4):
            mb.put(_Req(i, now + 60))
        deadline = time.time() + 2
        while not got and time.time() < deadline:
            time.sleep(0.01)
        # size trigger fired long before the 10 s wait trigger
        assert got == [4]
    finally:
        mb.close()


def test_microbatcher_respects_dispatch_window():
    got = []
    cap = {"n": 2}  # like the gate: dispatch consumes window capacity

    def dispatch(rs):
        cap["n"] -= len(rs)
        got.append([r.key for r in rs])

    mb = MicroBatcher(
        QoSConfig(max_batch_size=8, max_wait_ms=5),
        dispatch=dispatch,
        reject=lambda r, e: got.append(("rej", r.key)),
        capacity=lambda: cap["n"],
    )
    try:
        now = time.monotonic()
        for i in range(5):
            mb.put(_Req(i, now + 60))
        deadline = time.time() + 2
        while not got and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)
        # only the window's worth released while capacity is exhausted
        assert got == [[0, 1]]
        cap["n"] = 8  # responses went out: window frees up
        mb.notify()
        deadline = time.time() + 2
        while len(got) < 2 and time.time() < deadline:
            time.sleep(0.01)
        assert got[1] == [2, 3, 4]
    finally:
        mb.close()


def test_microbatcher_close_rejects_queued():
    got = []
    mb = MicroBatcher(
        QoSConfig(max_batch_size=8, max_wait_ms=10_000),
        dispatch=lambda rs: got.append(len(rs)),
        reject=lambda r, e: got.append(("rej", r.key, type(e).__name__)),
    )
    now = time.monotonic()
    mb.put(_Req(1, now + 60))
    mb.close(reject_queued=ShedError(503, "shutdown", 1.0))
    assert ("rej", 1, "ShedError") in got


# --- gate accounting (review regressions) ----------------------------------


class _FakeSession:
    """Minimal InputSession stand-in for gate-level unit tests (no
    `priority` attribute, so the gate skips the scheduler wiring)."""

    def __init__(self, fail: bool = False):
        self.rows: list = []
        self.fail = fail

    def insert_batch(self, rows) -> None:
        if self.fail:
            raise RuntimeError("insert failed")
        self.rows.extend(rows)


def _pending(key, deadline):
    from pathway_tpu.serving.gate import PendingRequest

    return PendingRequest(key, (key,), deadline)


def test_abandoned_request_skipped_and_window_slot_not_leaked():
    """Client disconnect while the request is still queued: the flush
    must skip the row (never reaches the engine) and must not claim a
    dispatch-window slot — a leaked slot would wedge the gate for good
    once _dispatch_capacity() hits zero."""
    from pathway_tpu.serving.gate import SurgeGate

    session = _FakeSession()
    gate = SurgeGate(
        QoSConfig(max_batch_size=4, max_wait_ms=5), session, route="/ab"
    )
    try:
        now = time.monotonic()
        live = _pending(1, now + 60)
        gone = _pending(2, now + 60)
        gate.submit(live)
        gate.submit(gone)
        # handler teardown on cancellation: abandon, then complete
        assert gone.abandon()
        gate.complete(gone.key, was_dispatched=False)
        deadline = time.time() + 2
        while not session.rows and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.05)  # a wrong dispatch of `gone` would land now
        assert [r[0] for r in session.rows] == [1]
        assert gone.abandon()  # still abandoned, never flipped
        assert gate._dispatched_pending == 1  # only the live request
        assert gate.queue_depth == 0  # both left the queue exactly once
        gate.complete(live.key, was_dispatched=not live.abandon())
        assert gate._dispatched_pending == 0
        assert gate.inflight == 0
    finally:
        gate.close()


def test_dispatch_wins_abandon_race_claims_slot():
    """The losing side of the teardown race must see was_dispatched:
    once the batcher claimed the request, abandon() returns False and
    the handler releases the window slot it owns."""
    req = _pending(1, time.monotonic() + 60)
    assert req.try_mark_dispatched()
    assert not req.abandon()  # handler: owes the slot
    assert req.was_dispatched
    req2 = _pending(2, time.monotonic() + 60)
    assert req2.abandon()
    assert not req2.try_mark_dispatched()  # batcher: skip entirely
    assert not req2.was_dispatched


def test_submit_shutdown_race_does_not_leak_queue_depth():
    """Batcher already closed but admission not yet draining: the
    ShedError path must roll back BOTH admission counters."""
    from pathway_tpu.serving.gate import SurgeGate

    session = _FakeSession()
    gate = SurgeGate(QoSConfig(), session, route="/cl")
    try:
        gate.batcher.close()
        with pytest.raises(ShedError):
            gate.submit(_pending(1, time.monotonic() + 60))
        assert gate.queue_depth == 0
        assert gate.inflight == 0
    finally:
        gate.close()


def test_failed_dispatch_decrements_queue_depth_exactly_once():
    """Engine insert raising mid-flush: the rejected batch must leave
    the queue exactly once — requests queued behind it keep their
    admission accounting (no phantom queue capacity)."""
    from pathway_tpu.serving.gate import SurgeGate

    session = _FakeSession(fail=True)
    gate = SurgeGate(QoSConfig(), session, route="/ff")
    try:
        for _ in range(6):  # 4 about to flush + 2 queued behind them
            gate.admission.admit()
        now = time.monotonic()
        batch = [_pending(i, now + 60) for i in range(4)]
        with pytest.raises(RuntimeError):
            gate._dispatch(batch)
        # the batcher's catch-all then rejects the failed batch
        for r in batch:
            gate._reject(r, RuntimeError("insert failed"))
        assert gate.queue_depth == 2
        # the handlers observe was_dispatched and release their slots
        for r in batch:
            assert not r.abandon()
            gate.complete(r.key, was_dispatched=True)
        assert gate._dispatched_pending == 0
    finally:
        gate.close()


def test_collected_gate_stops_batcher_thread():
    """A graph torn down without an explicit stop must not leak a flush
    thread per endpoint: the batcher holds its gate weakly and a
    finalizer closes the thread once the gate is collected."""
    import gc

    from pathway_tpu.serving.gate import SurgeGate

    gate = SurgeGate(QoSConfig(), _FakeSession(), route="/gc")
    thread = gate.batcher._thread
    assert thread.is_alive()
    del gate
    for _ in range(3):
        gc.collect()
    thread.join(timeout=5)
    assert not thread.is_alive()


# --- REST end-to-end -------------------------------------------------------


def _serve_slow_pipeline(qos, sleep_s=0.25):
    """rest_connector + a deliberately slow per-row UDF; returns
    (port, run_thread, seen_texts)."""
    import requests  # noqa: F401  (ensures dep present before server up)

    from pathway_tpu.io.http import rest_connector

    seen: list[str] = []

    class QuerySchema(pw.Schema):
        text: str

    @pw.udf
    def slow_upper(text: str) -> str:
        seen.append(text)
        time.sleep(sleep_s)
        return text.upper()

    port = _free_port()
    queries, writer = rest_connector(
        host="127.0.0.1",
        port=port,
        schema=QuerySchema,
        route="/upper",
        qos=qos,
    )
    writer(
        queries.select(query_id=queries.id, result=slow_upper(queries.text))
    )
    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    return port, t, seen


def _await_up(port, route="/upper", payload=None, timeout=20):
    import requests

    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            r = requests.post(
                f"http://127.0.0.1:{port}{route}",
                json=payload or {"text": "warmup"},
                timeout=5,
            )
            if r.status_code == 200:
                return
        except Exception:
            pass
        time.sleep(0.2)
    raise RuntimeError("server did not come up")


def test_rest_overload_sheds_429_with_retry_after():
    import requests

    qos = QoSConfig(
        max_batch_size=2,
        max_wait_ms=5,
        max_queue=3,
        max_dispatched=2,
        default_deadline_ms=30_000,
    )
    port, t, _seen = _serve_slow_pipeline(qos)
    try:
        _await_up(port)
        results = []

        def worker(i):
            try:
                r = requests.post(
                    f"http://127.0.0.1:{port}/upper",
                    json={"text": f"w{i}"},
                    timeout=30,
                )
                results.append(
                    (r.status_code, r.headers.get("Retry-After"), r.json())
                )
            except Exception as e:  # pragma: no cover - diagnostics
                results.append(("err", None, str(e)))

        ws = [
            threading.Thread(target=worker, args=(i,)) for i in range(12)
        ]
        for w in ws:
            w.start()
        for w in ws:
            w.join()
        statuses = Counter(s for s, _, _ in results)
        assert statuses[200] >= 1
        assert statuses[429] >= 1, statuses  # explicit shed, not queueing
        assert "err" not in statuses, results
        for status, retry_after, body in results:
            if status == 429:
                assert retry_after is not None
                assert float(retry_after) >= 0
            if status == 200:
                assert body.startswith("W")
    finally:
        pw.internals.parse_graph.G.runtime.stop()
        t.join(timeout=10)


def test_rest_expired_deadline_never_dispatched():
    """A request whose deadline passes while stuck behind a full
    dispatch window is dropped server-side: 504, and the pipeline UDF
    never sees its payload."""
    import requests

    qos = QoSConfig(
        max_batch_size=1,
        max_wait_ms=2,
        max_queue=8,
        max_dispatched=1,
        default_deadline_ms=30_000,
    )
    port, t, seen = _serve_slow_pipeline(qos, sleep_s=0.4)
    try:
        _await_up(port)
        # occupy the dispatch window with slow requests...
        blockers = [
            threading.Thread(
                target=lambda i=i: __import__("requests").post(
                    f"http://127.0.0.1:{port}/upper",
                    json={"text": f"blocker{i}"},
                    timeout=30,
                ),
            )
            for i in range(3)
        ]
        for b in blockers:
            b.start()
        time.sleep(0.15)  # let blockers reach the engine
        # ...then a tight-deadline request that must expire while queued
        r = requests.post(
            f"http://127.0.0.1:{port}/upper",
            json={"text": "mustexpire"},
            headers={"x-pathway-deadline-ms": "50"},
            timeout=10,
        )
        assert r.status_code == 504
        for b in blockers:
            b.join()
        time.sleep(0.5)  # any wrong dispatch would have been seen by now
        assert "mustexpire" not in seen
    finally:
        pw.internals.parse_graph.G.runtime.stop()
        t.join(timeout=10)


def test_rest_drain_completes_admitted_requests():
    """Drain under in-flight load: every admitted request is answered,
    post-drain requests are refused, the listener closes."""
    import requests

    from pathway_tpu.serving import drain_all

    qos = QoSConfig(
        max_batch_size=4,
        max_wait_ms=5,
        max_queue=32,
        default_deadline_ms=30_000,
    )
    port, t, _seen = _serve_slow_pipeline(qos, sleep_s=0.05)
    try:
        _await_up(port)
        results = []
        stop_firing = threading.Event()

        def worker(i):
            while not stop_firing.is_set():
                try:
                    r = requests.post(
                        f"http://127.0.0.1:{port}/upper",
                        json={"text": f"d{i}"},
                        timeout=30,
                    )
                    results.append((r.status_code, r.json()))
                except Exception:
                    results.append(("conn", None))
                    return

        ws = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for w in ws:
            w.start()
        time.sleep(0.5)  # load in flight
        assert drain_all(grace_s=15)  # True = all gates went idle
        stop_firing.set()
        for w in ws:
            w.join(timeout=10)
        statuses = Counter(s for s, _ in results)
        assert statuses[200] >= 1
        # every non-200 is an explicit drain refusal or the closed
        # listener — nothing hung, nothing lost mid-pipeline
        assert set(statuses) <= {200, 503, "conn"}, statuses
        for status, body in results:
            if status == 200:
                assert body and body.startswith("D")
        # listener is really closed
        with pytest.raises(Exception):
            requests.post(
                f"http://127.0.0.1:{port}/upper",
                json={"text": "late"},
                timeout=2,
            )
    finally:
        pw.internals.parse_graph.G.runtime.stop()
        t.join(timeout=10)


def test_webserver_stop_releases_port_on_runtime_stop():
    """Satellite: runtime.stop() must close the aiohttp listener (the
    seed leaked the daemon thread + socket forever)."""
    import requests

    from pathway_tpu.io.http import rest_connector

    class QuerySchema(pw.Schema):
        text: str

    port = _free_port()
    queries, writer = rest_connector(
        host="127.0.0.1", port=port, schema=QuerySchema, route="/echo"
    )
    writer(queries.select(query_id=queries.id, result=queries.text))
    t = threading.Thread(target=pw.run, daemon=True)
    t.start()
    _await_up(port, route="/echo")
    pw.internals.parse_graph.G.runtime.stop()
    t.join(timeout=10)
    assert not t.is_alive()
    deadline = time.time() + 5
    closed = False
    while time.time() < deadline:
        try:
            requests.post(
                f"http://127.0.0.1:{port}/echo",
                json={"text": "x"},
                timeout=1,
            )
        except Exception:
            closed = True
            break
        time.sleep(0.1)
    assert closed, "webserver still accepting connections after stop"


def test_webserver_stop_during_startup_does_not_leak_thread(monkeypatch):
    """stop() racing the server thread's startup must still land: it
    waits for the loop to exist instead of silently skipping loop.stop
    (which left run_forever holding the port for the process lifetime,
    with the idempotence guard blocking any retry)."""
    import asyncio

    from pathway_tpu.io.http._server import PathwayWebserver

    real_new_loop = asyncio.new_event_loop

    def slow_new_loop():
        time.sleep(0.3)  # widen the window stop() must wait through
        return real_new_loop()

    monkeypatch.setattr(asyncio, "new_event_loop", slow_new_loop)
    ws = PathwayWebserver("127.0.0.1", _free_port())
    ws.start()
    ws.stop(timeout=10)
    assert not ws._thread.is_alive()


def test_non_finite_deadline_header_falls_back_to_default():
    """A 'nan' budget must not slip past the clamp (it would hang the
    handler and permanently leak a queue slot) — it reads as absent."""
    import requests

    qos = QoSConfig(max_batch_size=4, max_wait_ms=5, max_queue=8)
    port, t, _seen = _serve_slow_pipeline(qos, sleep_s=0.01)
    try:
        _await_up(port)
        for bad in ("nan", "inf", "-inf", "garbage"):
            r = requests.post(
                f"http://127.0.0.1:{port}/upper",
                json={"text": "ok"},
                headers={"x-pathway-deadline-ms": bad},
                timeout=10,
            )
            assert r.status_code == 200, (bad, r.status_code)
        from pathway_tpu.serving import gates

        assert all(g.queue_depth == 0 and g.inflight == 0 for g in gates())
    finally:
        pw.internals.parse_graph.G.runtime.stop()
        t.join(timeout=10)


def test_input_session_drain_bounds_upserts():
    """The bulk-chunk bound applies to upsert-fed sessions too, and the
    offset marker only surfaces once everything it covers drained."""
    from pathway_tpu.engine.runtime import InputSession

    sess = InputSession(["v"])
    sess.insert_batch(
        [(i, 1, (i,)) for i in range(3)], offsets={"at": 3}
    )
    for k in range(100, 110):
        sess.upsert(k, (k,))
    first = sess.drain(max_rows=5)
    assert len(first) == 5  # 3 rows + 2 upserts
    assert sess.last_offsets is None  # partial: offsets still pending
    rest = sess.drain(max_rows=100)
    assert len(rest) == 8
    assert sess.last_offsets == {"at": 3}
    assert {r[0] for r in first + rest} == set(range(3)) | set(
        range(100, 110)
    )


def test_gated_session_is_interactive_priority():
    from pathway_tpu.engine.runtime import InputSession
    from pathway_tpu.serving.gate import SurgeGate

    session = InputSession(["text"])
    assert session.priority == InputSession.PRIORITY_BULK
    gate = SurgeGate(QoSConfig(), session, route="/p")
    try:
        assert session.priority == InputSession.PRIORITY_INTERACTIVE
    finally:
        gate.close()
    session2 = InputSession(["text"])
    gate2 = SurgeGate(QoSConfig(priority="bulk"), session2, route="/p2")
    try:
        assert session2.priority == InputSession.PRIORITY_BULK
    finally:
        gate2.close()


def test_graph_doctor_serving_admission_rule():
    from pathway_tpu.analysis import run_doctor
    from pathway_tpu.io.http import rest_connector

    class QuerySchema(pw.Schema):
        text: str

    ungated, writer = rest_connector(
        host="127.0.0.1",
        port=_free_port(),
        schema=QuerySchema,
        route="/ungated",
    )
    writer(ungated.select(query_id=ungated.id, result=ungated.text))
    gated, writer2 = rest_connector(
        host="127.0.0.1",
        port=_free_port(),
        schema=QuerySchema,
        route="/gated",
        qos=QoSConfig(),
    )
    writer2(gated.select(query_id=gated.id, result=gated.text))
    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    hits = report.by_rule("serving-admission")
    assert len(hits) == 1  # exactly the ungated ingress


def test_serving_enabled_via_env_gates_rest_connector(monkeypatch):
    from pathway_tpu.io.http import rest_connector

    monkeypatch.setenv("PATHWAY_SERVING_ENABLED", "1")
    monkeypatch.setenv("PATHWAY_SERVING_MAX_QUEUE", "5")

    class QuerySchema(pw.Schema):
        text: str

    queries, writer = rest_connector(
        host="127.0.0.1",
        port=_free_port(),
        schema=QuerySchema,
        route="/env",
    )
    writer(queries.select(query_id=queries.id, result=queries.text))
    from pathway_tpu.analysis import run_doctor

    report = run_doctor(list(pw.internals.parse_graph.G.outputs))
    assert not report.by_rule("serving-admission")


def test_knn_skips_expired_queries(monkeypatch):
    """Deadline propagation through the tick: the external-index exec
    answers expired queries empty without calling the index."""
    from pathway_tpu.serving import deadline as sdl
    from pathway_tpu.stdlib.indexing.data_index import DataIndex
    from pathway_tpu.stdlib.indexing.nearest_neighbors import USearchKnn

    import numpy as np

    @pw.udf
    def emb(text: str) -> np.ndarray:
        v = np.zeros(4, dtype=np.float32)
        for ch in str(text).lower():
            v[ord(ch) % 4] += 1.0
        n = np.linalg.norm(v)
        return v / n if n > 0 else v

    from pathway_tpu.debug import T, table_to_dicts

    docs = T(
        """
        text
        apple
        banana
        """
    )
    docs = docs.with_columns(embedding=emb(docs.text))
    index = DataIndex(
        docs, USearchKnn(docs.embedding, dimensions=4)
    )
    queries = T(
        """
        qtext | k
        apple | 1
        """
    )
    queries = queries.with_columns(_q=emb(queries.qtext))
    # register an expired deadline for the query row key
    [qkey] = list(table_to_dicts(queries)[0])
    sdl.register(int(qkey), time.monotonic() - 1.0)
    try:
        jr = index.query_as_of_now(queries._q, number_of_matches=queries.k)
        from pathway_tpu.internals.thisclass import right
        from pathway_tpu.stdlib.indexing.colnames import _SCORE

        out = jr.select(score=right[_SCORE])
        _keys, cols = table_to_dicts(out)
        # expired query got the empty reply without a search: no match
        # scores (a live query would carry a non-empty score tuple)
        assert cols["score"] and all(not v for v in cols["score"].values())
    finally:
        sdl.unregister(int(qkey))
