"""Ported reference core-join tests
(reference: python/pathway/tests/test_common.py join section) — empty
selects over joins, id= assignment from either side (with duplicate-key
errors), multi-condition joins, instance joins, condition-order and
operator validation, self-join rejection, cross joins."""

from __future__ import annotations

import operator

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T

from tests.ref_utils import (
    assert_table_equality,
    assert_table_equality_wo_index,
    run_all,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    from pathway_tpu.internals.errors import clear_errors

    clear_errors()
    yield
    pw.internals.parse_graph.G.clear()


def test_empty_join():
    left = T(
        """
                col | on
            1 | a   | 11
            2 | b   | 12
            3 | c   | 13
        """
    )
    right = T(
        """
                col | on
            1 | d   | 12
            2 | e   | 13
            3 | f   | 14
        """,
    )
    joined = left.join(right, left.on == right.on).select()
    assert_table_equality_wo_index(
        joined,
        T(
            """
                |
            2   |
            3   |
            """
        ).select(),
    )


def test_join_left_assign_id():
    left = T(
        """
                col | on
            1 | a   | 11
            2 | b   | 12
            3 | c   | 13
            4 | d   | 13
        """
    )
    right = T(
        """
                col | on
            1 | d   | 12
            2 | e   | 13
            3 | f   | 14
        """,
    )
    joined = left.join(right, left.on == right.on, id=left.id).select(
        lcol=left.col, rcol=right.col
    )
    assert_table_equality(
        joined,
        T(
            """
        | lcol | rcol
        2 |  b |    d
        3 |  c |    e
        4 |  d |    e
    """
        ),
    )
    with pytest.raises((AssertionError, TypeError, ValueError)):
        left.join(right, left.on == right.on, id=left.on)
    left.join(right, left.on == right.on, id=right.id).select(
        lcol=left.col, rcol=right.col
    )
    with pytest.raises(KeyError):
        run_all()


def test_join_right_assign_id():
    left = T(
        """
                col | on
            1 | a   | 11
            2 | b   | 12
            3 | c   | 13
        """
    )
    right = T(
        """
                col | on
            0 | c   | 12
            1 | d   | 12
            2 | e   | 13
            3 | f   | 14
        """,
    )
    joined = left.join(right, left.on == right.on, id=right.id).select(
        lcol=left.col, rcol=right.col
    )
    assert_table_equality(
        joined,
        T(
            """
          | lcol | rcol
        0 |    b |    c
        1 |    b |    d
        2 |    c |    e
    """
        ),
    )
    with pytest.raises((AssertionError, TypeError, ValueError)):
        left.join(right, left.on == right.on, id=right.on)
    left.join(right, left.on == right.on, id=left.id).select(
        lcol=left.col, rcol=right.col
    )
    with pytest.raises(KeyError):
        run_all()


def test_join():
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |   9 |    L
        13  |   1 |   Tom |   8 |   XL
        """
    )
    expected = T(
        """
            owner_name | L | R  | age
            Bob        | 2 | 12 |   9
            """,
    ).with_columns(
        L=t1.pointer_from(pw.this.L),
        R=t2.pointer_from(pw.this.R),
    )
    res = t1.join(t2, t1.pet == t2.pet, t1.owner == t2.owner).select(
        owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age
    )
    assert_table_equality_wo_index(
        res,
        expected,
    )


def test_join_instance():
    t1 = T(
        """
            | owner | age | instance
        1   | Alice |  10 | 1
        2   |   Bob |   9 | 1
        3   |   Tom |   8 | 1
        4   | Alice |  10 | 2
        5   |   Bob |   9 | 2
        6   |   Tom |   8 | 2
        """
    )
    t2 = T(
        """
            | owner | age | size | instance
        11  | Alice |  10 |    M | 1
        12  |   Bob |   9 |    L | 1
        13  |   Tom |   8 |   XL | 1
        14  | Alice |  10 |    M | 2
        15  |   Bob |   9 |    L | 2
        16  |   Tom |   8 |   XL | 2
        """
    )
    expected = T(
        """
            owner_name | L | R  | age
            Alice      | 1 | 11 |  10
            Bob        | 2 | 12 |   9
            Tom        | 3 | 13 |   8
            Alice      | 4 | 14 |  10
            Bob        | 5 | 15 |   9
            Tom        | 6 | 16 |   8
            """,
    ).with_columns(
        L=t1.pointer_from(pw.this.L),
        R=t2.pointer_from(pw.this.R),
    )
    res = t1.join(
        t2,
        t1.owner == t2.owner,
        left_instance=t1.instance,
        right_instance=t2.instance,
    ).select(owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age)
    assert_table_equality_wo_index(
        res,
        expected,
    )


def test_join_swapped_condition():
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        1   |   3 | Alice |  10 |    M
        2   |   1 |   Bob |   9 |    L
        3   |   1 |   Tom |   8 |   XL
        """
    )
    with pytest.raises(ValueError):
        t1.join(t2, t2.pet == t1.pet).select(
            owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age
        )


@pytest.mark.parametrize(
    "op",
    [operator.ne, operator.lt, operator.gt, operator.le, operator.ge],
)
def test_join_illegal_operator_in_condition(op):
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |   9 |    L
        13  |   1 |   Tom |   8 |   XL
        """
    )
    with pytest.raises((ValueError, TypeError)):
        t1.join(t2, op(t1.pet, t2.pet)).select(t1.owner)


def test_join_default():
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |   9 |    L
        13  |   1 |   Tom |   8 |   XL
        """
    )
    res = t1.join(t2, t1.pet == t2.pet).select(
        owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age
    )
    expected = T(
        """
            owner_name  | L | R  | age
            Bob         | 1 | 12 | 10
            Tom         | 1 | 13 | 10
            Bob         | 2 | 12 |  9
            Tom         | 2 | 13 |  9
        """,
    ).with_columns(
        L=t1.pointer_from(pw.this.L),
        R=t2.pointer_from(pw.this.R),
    )
    assert_table_equality_wo_index(res, expected)


def test_join_self():
    input = T(
        """
        foo   | bar
        1     | 1
        1     | 2
        1     | 3
        """
    )
    with pytest.raises(Exception):
        input.join(input, input.foo == input.bar)


def test_join_select_no_columns():
    left = T(
        """
           | a
        1  | 1
        2  | 2
        """
    )
    right = T(
        """
           | b
        1  | foo
        2  | bar
        """
    )
    ret = left.join(right, left.id == right.id).select().select(col=42)
    assert_table_equality_wo_index(
        ret,
        T(
            """
                | col
            1   | 42
            2   | 42
            """
        ),
    )


def test_cross_join():
    t1 = T(
        """
            | pet | owner | age
        1   |   1 | Alice |  10
        2   |   1 |   Bob |   9
        3   |   2 | Alice |   8
        """
    )
    t2 = T(
        """
            | pet | owner | age | size
        11  |   3 | Alice |  10 |    M
        12  |   1 |   Bob |  9  |    L
        13  |   1 |   Tom |  8  |   XL
        """
    )
    res = t1.join(t2).select(
        owner_name=t2.owner, L=t1.id, R=t2.id, age=t1.age
    )
    expected = T(
        """
            owner_name  | L | R | age
            Alice       | 1 | 11 |  10
            Bob         | 1 | 12 |  10
            Tom         | 1 | 13 |  10
            Alice       | 2 | 11 |   9
            Bob         | 2 | 12 |   9
            Tom         | 2 | 13 |   9
            Alice       | 3 | 11 |   8
            Bob         | 3 | 12 |   8
            Tom         | 3 | 13 |   8
        """,
    ).with_columns(
        L=t1.pointer_from(pw.this.L),
        R=t2.pointer_from(pw.this.R),
    )
    assert_table_equality_wo_index(res, expected)


def test_empty_join_2():
    t1 = T(
        """
        v1
        1
        2
        """,
    )
    t2 = T(
        """
        v2
        10
        20
        """,
    )
    t = t1.join(t2).select(t1.v1, t2.v2)
    expected_t = T(
        """
        v1  | v2
        1   | 10
        1   | 20
        2   | 10
        2   | 20
        """,
    )
    assert_table_equality_wo_index(t, expected_t)
