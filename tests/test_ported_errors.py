"""Ported reference error-model tests (reference:
python/pathway/tests/test_errors.py, 1,493 LoC). Adaptations: key strings
inside messages (duplicate-key ids) are engine-specific and matched
loosely; everything else ports verbatim."""

from unittest import mock

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T
from ref_utils import (
    assert_stream_equality_wo_index,
    assert_table_equality_wo_index,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    pw.internals.errors.clear_errors()
    yield
    pw.internals.parse_graph.G.clear()
    pw.internals.errors.clear_errors()


def test_division_by_zero():
    t1 = T(
        """
        a | b | c
        3 | 3 | 1
        4 | 0 | 2
        5 | 5 | 0
        6 | 2 | 3
    """
    )
    t2 = t1.select(x=pw.this.a // pw.this.b)
    t3 = t1.select(y=pw.this.a // pw.this.c)
    t4 = t1.select(
        pw.this.a, x=pw.fill_error(t2.x, -1), y=pw.fill_error(t3.y, -1)
    )
    expected = T(
        """
        a |  x |  y
        3 |  1 |  3
        4 | -1 |  2
        5 |  1 | -1
        6 |  3 |  2
    """
    )
    expected_errors = T(
        """
        message
        division by zero
        division by zero
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (t4, pw.global_error_log().select(pw.this.message)),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_removal_of_error():
    t1 = T(
        """
          | a | b | __time__ | __diff__
        1 | 6 | 2 |     2    |     1
        2 | 5 | 0 |     4    |     1
        3 | 4 | 2 |     6    |     1
        2 | 5 | 0 |     8    |    -1
    """
    )
    t2 = t1.with_columns(c=pw.this.a // pw.this.b)
    expected = T(
        """
        a | b | c
        4 | 2 | 2
        6 | 2 | 3
    """
    )
    expected_errors = T(
        """
        message
        division by zero
        division by zero
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (t2, pw.global_error_log().select(pw.this.message)),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_filter_with_error_in_condition():
    t1 = pw.debug.table_from_markdown(
        """
        a | b
        6 | 2
        5 | 5
        4 | 0
        3 | 3
    """
    )
    t2 = t1.with_columns(x=pw.this.a // pw.this.b)
    res = t2.filter(pw.this.x > 0)
    expected = T(
        """
        a | b | x
        3 | 3 | 1
        5 | 5 | 1
        6 | 2 | 3
    """
    )
    expected_errors = T(
        """
        message
        division by zero
        Error value encountered in filter condition, skipping the row
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (res, pw.global_error_log().select(pw.this.message)),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_filter_with_error_in_other_column():
    t1 = pw.debug.table_from_markdown(
        """
        a | b
        3 | 3
        4 | 0
        5 | 5
        6 | 2
    """
    )
    t2 = t1.with_columns(x=pw.this.a // pw.this.b)
    res = t2.filter(pw.this.a > 0)
    expected = T(
        """
        a | b |  x
        3 | 3 |  1
        4 | 0 | -1
        5 | 5 |  1
        6 | 2 |  3
    """
    )
    expected_errors = T(
        """
        message
        division by zero
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (
            res.with_columns(x=pw.fill_error(pw.this.x, -1)),
            pw.global_error_log().select(pw.this.message),
        ),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_inner_join_with_error_in_condition():
    t1 = pw.debug.table_from_markdown(
        """
        a | c
        1 | 1
        2 | 0
        3 | 1
    """
    ).with_columns(a=pw.this.a // pw.this.c)
    t2 = pw.debug.table_from_markdown(
        """
        b
        1
        1
        2
    """
    )
    res = t1.join(t2, pw.left.a == pw.right.b).select(
        pw.left.a, pw.left.c, pw.right.b
    )
    expected = T(
        """
        a | c | b
        1 | 1 | 1
        1 | 1 | 1
    """
    )
    expected_errors = T(
        """
        message
        division by zero
        Error value encountered in join condition, skipping the row
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (res, pw.global_error_log().select(pw.this.message)),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_left_join_with_error_in_condition():
    t1 = pw.debug.table_from_markdown(
        """
        a | c
        1 | 1
        2 | 0
        3 | 1
    """
    ).with_columns(a=pw.this.a // pw.this.c)
    t2 = pw.debug.table_from_markdown(
        """
        b
        1
        1
        1
        2
    """
    )
    res = t1.join_left(t2, pw.left.a == pw.right.b).select(
        a=pw.fill_error(pw.left.a, -1), c=pw.left.c, b=pw.right.b
    )
    expected = T(
        """
        a | c | b
        1 | 1 | 1
        1 | 1 | 1
        1 | 1 | 1
       -1 | 0 |
        3 | 1 |
    """
    )
    expected_errors = T(
        """
        message
        division by zero
        Error value encountered in join condition, skipping the row
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (res, pw.global_error_log().select(pw.this.message)),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_local_logs():
    t1 = T(
        """
        a | b | c
        3 | 3 | a
        4 | 0 | 2
        5 | 5 | 0
        6 | 2 | 3
    """
    )
    with pw.local_error_log() as error_log_1:
        t2 = t1.select(x=pw.this.a // pw.this.b)
    with pw.local_error_log() as error_log_2:
        t3 = t1.select(y=pw.this.c.str.parse_int())
    t4 = t1.select(
        pw.this.a,
        x=pw.fill_error(t2.x, -1),
        y=pw.fill_error(t3.y, -1),
        z=pw.this.a // t3.y,
    )
    assert_table_equality_wo_index(
        (
            t4.with_columns(z=pw.fill_error(pw.this.z, -1)),
            pw.global_error_log().select(pw.this.message),
            error_log_1.select(pw.this.message),
            error_log_2.select(pw.this.message),
        ),
        (
            T(
                """
            a |  x |  y |  z
            3 |  1 | -1 | -1
            4 | -1 |  2 |  2
            5 |  1 |  0 | -1
            6 |  3 |  3 |  2
            """
            ),
            T(
                """
            message
            division by zero
            """,
                split_on_whitespace=False,
            ),
            T(
                """
            message
            division by zero
            """,
                split_on_whitespace=False,
            ),
            T(
                """
            message
            parse error: cannot parse "a" to int: invalid digit found in string
            """,
                split_on_whitespace=False,
            ),
        ),
        terminate_on_error=False,
    )


def test_subscribe():
    t1 = T(
        """
        a | b
        3 | 3
        4 | 0
        5 | 5
        6 | 2
    """
    )
    t2 = t1.with_columns(x=pw.this.a // pw.this.b)
    on_change = mock.Mock()
    pw.io.subscribe(t2, on_change=on_change)
    pw.run(terminate_on_error=False, monitoring_level=pw.MonitoringLevel.NONE)
    assert on_change.call_count == 3


@pytest.mark.parametrize("sync", [True, False])
def test_udf(sync: bool) -> None:
    t1 = T(
        """
        a | b
        3 | 3
        4 | 0
        5 | 5
        6 | 2
    """
    )
    if sync:

        @pw.udf(deterministic=True)
        def div(a: int, b: int) -> int:
            return a // b

    else:

        @pw.udf(deterministic=True)
        async def div(a: int, b: int) -> int:
            return a // b

    t2 = t1.select(pw.this.a, x=div(pw.this.a, pw.this.b))
    res = t2.with_columns(x=pw.fill_error(pw.this.x, -1))
    expected = T(
        """
        a |  x
        3 |  1
        4 | -1
        5 |  1
        6 |  3
    """
    )
    expected_errors = T(
        """
        message
        ZeroDivisionError: integer division or modulo by zero
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (res, pw.global_error_log().select(pw.this.message)),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_remove_errors():
    t1 = T(
        """
        a | b | c
        3 | 3 | 1
        4 | 0 | 2
        5 | 5 | 0
        6 | 2 | 3
    """
    )
    t2 = t1.select(x=pw.this.a // pw.this.b)
    t3 = t1.select(y=pw.this.a // pw.this.c)
    t4 = t1.select(pw.this.a, x=t2.x, y=t3.y)
    res = t4.remove_errors()
    assert_table_equality_wo_index(
        res,
        T(
            """
            a | x | y
            3 | 1 | 3
            6 | 3 | 2
            """
        ),
        terminate_on_error=False,
    )


def test_remove_errors_identity():
    t1 = T(
        """
        a | b | c
        3 | 3 | 1
        4 | 1 | 2
        5 | 5 | 1
        6 | 2 | 3
    """
    )
    t2 = t1.select(x=pw.this.a // pw.this.b)
    t3 = t1.select(y=pw.this.a // pw.this.c)
    t4 = t1.select(pw.this.a, x=t2.x, y=t3.y)
    res = t4.remove_errors()
    assert_table_equality_wo_index(
        res,
        T(
            """
            a | x | y
            3 | 1 | 3
            4 | 4 | 2
            5 | 1 | 5
            6 | 3 | 2
            """
        ),
        terminate_on_error=False,
    )


def test_groupby_with_error_in_grouping_column():
    t = T(
        """
        a | b | d
        1 | 1 | 1
        1 | 2 | 0
        1 | 3 | 1
        2 | 4 | 1
        2 | 5 | 1
    """
    ).with_columns(a=pw.this.a // pw.this.d, b=pw.this.b // pw.this.d)
    res = t.groupby(pw.this.a).reduce(
        pw.this.a, b_sum=pw.reducers.sum(pw.this.b)
    )
    expected = T(
        """
        a | b_sum
        1 |   4
        2 |   9
    """
    )
    expected_errors = T(
        """
        message
        division by zero
        division by zero
        Error value encountered in grouping columns, skipping the row
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (res, pw.global_error_log().select(pw.this.message)),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_groupby_skip_errors():
    @pw.reducers.stateful_single
    def stateful_sum(state, val):
        if state is None:
            return val
        return state + val

    t = T(
        """
        a | b |  c  | d | e
        1 | 1 | 1.5 | 1 | 1
        1 | 2 | 2.5 | 0 | 1
        1 | 3 | 3.5 | 1 | 0
        2 | 4 | 4.5 | 1 | 1
        2 | 5 | 5.5 | 1 | 0
    """
    ).with_columns(b=pw.this.b // pw.this.d, c=pw.this.c / pw.this.e)
    res = (
        t.groupby(pw.this.a, _skip_errors=True)
        .reduce(
            pw.this.a,
            i_sum=pw.reducers.sum(pw.this.b),
            i_avg=pw.reducers.avg(pw.this.b),
            i_min=pw.reducers.min(pw.this.b),
            f_sum=pw.reducers.sum(pw.this.c),
            f_avg=pw.reducers.avg(pw.this.c),
            f_min=pw.reducers.min(pw.this.c),
            cnt=pw.reducers.count(),
            st_sum=stateful_sum(pw.this.b),
        )
        .update_types(st_sum=int)
    )
    expected = T(
        """
        a | i_sum | i_avg | i_min | f_sum | f_avg | f_min | cnt | st_sum
        1 |   4   |   2   |   1   |   4   |   2   |  1.5  |  3  |   4
        2 |   9   |  4.5  |   4   |  4.5  |  4.5  |  4.5  |  2  |   9
    """
    )
    assert_table_equality_wo_index(res, expected, terminate_on_error=False)


def test_groupby_propagate_errors():
    @pw.reducers.stateful_single
    def stateful_sum(state, val):
        if state is None:
            return val
        return state + val

    t = T(
        """
        a | b |  c  | d | e
        1 | 1 | 1.5 | 1 | 1
        1 | 2 | 2.5 | 0 | 1
        1 | 3 | 3.5 | 1 | 0
        2 | 4 | 4.5 | 1 | 1
        2 | 5 | 5.5 | 1 | 0
    """
    ).with_columns(b=pw.this.b // pw.this.d, c=pw.this.c / pw.this.e)
    res = (
        t.groupby(pw.this.a, _skip_errors=False)
        .reduce(
            pw.this.a,
            i_sum=pw.fill_error(pw.reducers.sum(pw.this.b), -1),
            i_avg=pw.fill_error(pw.reducers.avg(pw.this.b), -1),
            i_min=pw.fill_error(pw.reducers.min(pw.this.b), -1),
            f_sum=pw.fill_error(pw.reducers.sum(pw.this.c), -1),
            f_avg=pw.fill_error(pw.reducers.avg(pw.this.c), -1),
            f_min=pw.fill_error(pw.reducers.min(pw.this.c), -1),
            cnt=pw.reducers.count(),
            st_sum=pw.fill_error(stateful_sum(pw.this.b), -1),
        )
        .update_types(st_sum=int)
    )
    expected = T(
        """
        a | i_sum | i_avg | i_min | f_sum | f_avg | f_min | cnt | st_sum
        1 |  -1   |  -1   |  -1   |  -1   |  -1   |  -1   |  3  |  -1
        2 |   9   |  4.5  |   4   |  -1   |  -1   |  -1   |  2  |   9
    """
    ).update_types(f_sum=float, f_avg=float, f_min=float)
    assert_table_equality_wo_index(res, expected, terminate_on_error=False)


def test_groupby_stateful_with_error():
    @pw.reducers.stateful_single
    def stateful_sum(state, val):
        if val == 2:
            raise ValueError("Value 2 encountered")
        if state is None:
            return val
        return state + val

    t = T(
        """
        a | b
        1 | 1
        2 | 2
        1 | 3
        2 | 4
        1 | 5
    """
    )
    res = (
        t.groupby(pw.this.a)
        .reduce(pw.this.a, b=pw.fill_error(stateful_sum(pw.this.b), -1))
        .update_types(b=int)
    )
    expected = T(
        """
        a |  b
        1 |  9
        2 | -1
    """
    )
    expected_errors = T(
        """
        message
        ValueError: Value 2 encountered
    """,
        split_on_whitespace=False,
    )
    assert_table_equality_wo_index(
        (res, pw.global_error_log().select(pw.this.message)),
        (expected, expected_errors),
        terminate_on_error=False,
    )


def test_groupby_recovers_from_errors():
    @pw.reducers.stateful_single
    def stateful_sum(state, val):
        if state is None:
            return val
        return state + val

    t = T(
        """
          | b |  c  | d | e | __time__ | __diff__
        1 | 1 | 1.5 | 1 | 1 |     2    |     1
        2 | 2 | 2.5 | 0 | 1 |     4    |     1
        3 | 3 | 3.5 | 1 | 0 |     6    |     1
        2 | 2 | 2.5 | 0 | 1 |     8    |    -1
        3 | 3 | 3.5 | 1 | 0 |    10    |    -1
    """
    ).with_columns(b=pw.this.b // pw.this.d, c=pw.this.c / pw.this.e)
    res = (
        t.groupby(_skip_errors=False)
        .reduce(
            i_sum=pw.fill_error(pw.reducers.sum(pw.this.b), -1),
            i_avg=pw.fill_error(pw.reducers.avg(pw.this.b), -1),
            i_min=pw.fill_error(pw.reducers.min(pw.this.b), -1),
            f_sum=pw.fill_error(pw.reducers.sum(pw.this.c), -1),
            f_avg=pw.fill_error(pw.reducers.avg(pw.this.c), -1),
            f_min=pw.fill_error(pw.reducers.min(pw.this.c), -1),
            cnt=pw.reducers.count(),
            st_sum=pw.fill_error(stateful_sum(pw.this.b), -1),
        )
        .update_types(st_sum=int)
    )
    expected = T(
        """
          | i_sum | i_avg | i_min | f_sum | f_avg | f_min | cnt | st_sum | __time__ | __diff__
        1 |   1   |   1   |   1   |  1.5  |  1.5  |  1.5  |  1  |   1    |     2    |     1
        1 |   1   |   1   |   1   |  1.5  |  1.5  |  1.5  |  1  |   1    |     4    |    -1
        1 |  -1   |  -1   |  -1   |  4.0  |  2.0  |  1.5  |  2  |  -1    |     4    |     1
        1 |  -1   |  -1   |  -1   |  4.0  |  2.0  |  1.5  |  2  |  -1    |     6    |    -1
        1 |  -1   |  -1   |  -1   | -1.0  | -1.0  | -1.0  |  3  |  -1    |     6    |     1
        1 |  -1   |  -1   |  -1   | -1.0  | -1.0  | -1.0  |  3  |  -1    |     8    |    -1
        1 |   4   |   2   |   1   | -1.0  | -1.0  | -1.0  |  2  |  -1    |     8    |     1
        1 |   4   |   2   |   1   | -1.0  | -1.0  | -1.0  |  2  |  -1    |    10    |    -1
        1 |   1   |   1   |   1   |  1.5  |  1.5  |  1.5  |  1  |  -1    |    10    |     1
    """
    ).update_types(i_avg=float)
    assert_stream_equality_wo_index(res, expected, terminate_on_error=False)


def test_unique_reducer():
    t = T(
        """
        a | b | __time__ | __diff__
        1 | 1 |     2    |     1
        1 | 2 |     2    |     1
        2 | 3 |     2    |     1
        1 | 2 |     4    |    -1
    """
    )
    res = t.groupby(pw.this.a).reduce(
        pw.this.a, b=pw.fill_error(pw.reducers.unique(pw.this.b), -1)
    )
    expected = T(
        """
        a |  b
        1 |  1
        2 |  3
    """
    )
    assert_table_equality_wo_index(res, expected, terminate_on_error=False)


def test_global_error_first_operator():
    # reading the global log before anything errors: empty table, no crash
    log = pw.global_error_log().select(pw.this.message)
    from ref_utils import _capture

    rows = _capture(log)
    assert rows == {}
