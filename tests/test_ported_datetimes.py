"""Ported reference datetime expression tests
(reference: python/pathway/tests/expressions/test_datetimes.py) — the `.dt`
namespace at nanosecond precision: duration components (truncate toward
zero), field extraction, timestamps, chrono-style %f/%3f/%6f/%9f
strftime/strptime, timezone conversions across DST, wall-clock arithmetic,
round/floor, from_timestamp, pw.Duration / pw.DateTime* constructors.

Adaptations from the reference: pandas-3 removed the single-letter offset
aliases (H/T/S/L/U/N), so the round/floor frequency strings use their
modern spellings; the deprecation-warning helper is pytest's built-in."""

from __future__ import annotations

import datetime
import operator
import re
from typing import Any

import numpy as np
import pandas as pd
import pytest
from dateutil import tz

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown, table_from_pandas
from pathway_tpu.internals import dtype as dt

from tests.ref_utils import (
    assert_table_equality,
    assert_table_equality_wo_index,
    run_all,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    from pathway_tpu.internals.errors import clear_errors

    clear_errors()
    yield
    pw.internals.parse_graph.G.clear()


@pytest.mark.parametrize(
    "method_name,unit",
    [
        ("nanoseconds", 1),
        ("microseconds", 1_000),
        ("milliseconds", 1_000_000),
        ("seconds", 1_000_000_000),
        ("minutes", 60 * 1_000_000_000),
        ("hours", 3600 * 1_000_000_000),
        ("days", 24 * 3600 * 1_000_000_000),
        ("weeks", 7 * 24 * 3600 * 1_000_000_000),
    ],
)
def test_duration(method_name: str, unit: int) -> None:
    df = pd.DataFrame(
        {
            "a": [
                pd.Timedelta(0),
                pd.Timedelta(-1),
                pd.Timedelta(-2),
                pd.Timedelta(1),
                pd.Timedelta(2),
                pd.Timedelta(microseconds=-2),
                pd.Timedelta(microseconds=3),
                pd.Timedelta(milliseconds=-2),
                pd.Timedelta(milliseconds=3),
                pd.Timedelta(seconds=-2),
                pd.Timedelta(seconds=3),
                pd.Timedelta(minutes=-2),
                pd.Timedelta(minutes=3),
                pd.Timedelta(hours=-2),
                pd.Timedelta(hours=3),
                pd.Timedelta(days=-2),
                pd.Timedelta(days=3),
                pd.Timedelta(weeks=-2),
                pd.Timedelta(weeks=3),
                pd.Timedelta(906238033887173888),
                pd.Timedelta(-25028201030208546),
                pd.Timedelta(-560647988758320624),
                pd.Timedelta(21569578082613316),
                pd.Timedelta(461037051895230252),
                pd.Timedelta(888145670672098607),
                pd.Timedelta(-916627150335519587),
                pd.Timedelta(-74827964329550952),
                pd.Timedelta(-126273201490715187),
                pd.Timedelta(125605450924133901),
            ]
        }
    )
    table = table_from_pandas(df)
    table_pw = table.select(a=getattr(table.a.dt, method_name)())
    df_new = pd.DataFrame({"a": (df.a.values / unit).astype(np.int64)})
    table_pd = table_from_pandas(df_new)

    assert_table_equality(table_pw, table_pd)


_DT_DATA = [
    "1960-02-03 08:00:00.000000000",
    "1960-02-03 08:00:00.123456789",
    "2008-02-29 08:00:00.000000000",
    "2023-03-25 12:00:00.000000000",
    "2023-03-25 12:00:00.000000001",
    "2023-03-25 12:00:00.123456789",
    "2023-03-25 16:43:21.000123000",
    "2023-03-25 17:00:01.987000000",
    "2023-03-25 22:59:59.999999999",
    "2023-03-25 23:00:00.000000001",
    "2023-03-25 23:59:59.999999999",
    "2023-03-26 00:00:00.000000001",
    "2023-03-26 12:00:00.000000001",
    "2123-03-26 12:00:00.000000001",
    "2123-03-31 23:00:00.000000001",
]


@pytest.mark.parametrize("is_naive", [True, False])
@pytest.mark.parametrize(
    "method_name",
    [
        "nanosecond",
        "microsecond",
        "millisecond",
        "second",
        "minute",
        "hour",
        "day",
        "month",
        "year",
    ],
)
def test_date_time(method_name: str, is_naive: bool) -> None:
    data = list(_DT_DATA)
    fmt = "%Y-%m-%d %H:%M:%S.%f"
    if not is_naive:
        data = [entry + "-02:00" for entry in data[:-2]]
        fmt += "%z"
    df = pd.DataFrame({"a": pd.to_datetime(data, format=fmt)})
    if not is_naive:
        df.a = df.a.dt.tz_convert(tz.UTC)
    if method_name == "nanosecond":
        series_new = df.a.dt.nanosecond + df.a.dt.microsecond * 1000
    elif method_name == "millisecond":
        series_new = df.a.dt.microsecond // 1000
    else:
        series_new = getattr(df.a.dt, method_name)
    df_new = pd.DataFrame({"a": series_new})
    table_pd = table_from_pandas(df_new)

    table = table_from_pandas(
        pd.DataFrame({"a": pd.to_datetime(data, format=fmt)})
    )
    table_pw = table.select(a=getattr(table.a.dt, method_name)())
    assert_table_equality(table_pw, table_pd)


@pytest.mark.parametrize("is_naive", [True, False])
def test_timestamp(is_naive: bool) -> None:
    data = list(_DT_DATA)
    fmt = "%Y-%m-%d %H:%M:%S.%f"
    if not is_naive:
        data = [entry + "-02:00" for entry in data[:-2]]
        fmt += "%z"
    series = pd.to_datetime(pd.Series(data), format=fmt)
    if not is_naive:
        series = series.dt.tz_convert(tz.UTC)
    df = pd.DataFrame({"ns": series.values.astype(np.int64)})
    table_pd = table_from_pandas(df).select(
        nounit=pw.this.ns,
        ns=pw.this.ns / 1,
        us=pw.this.ns / 1000,
        ms=pw.this.ns / 1e6,
        s=pw.this.ns / 1e9,
    )

    table = table_from_pandas(
        pd.DataFrame({"a": pd.to_datetime(data, format=fmt)})
    )

    with pytest.deprecated_call():
        nounit = table.a.dt.timestamp()

    table_pw = table.select(
        nounit=nounit,
        ns=pw.this.a.dt.timestamp(unit="ns"),
        us=pw.this.a.dt.timestamp(unit="us"),
        ms=pw.this.a.dt.timestamp(unit="ms"),
        s=pw.this.a.dt.timestamp(unit="s"),
    )
    assert_table_equality(table_pw, table_pd)


def test_timestamp_without_unit_deprecated() -> None:
    table = table_from_markdown(
        """
        time
          2
    """
    ).select(ts=pw.this.time.dt.from_timestamp(unit="s"))

    with pytest.deprecated_call(
        match=re.escape(
            "Not specyfying the `unit` argument of the `timestamp()` "
            "method is deprecated. Please specify its value. Without "
            "specifying, it will default to 'ns'."
        ),
    ):
        table.select(time=pw.this.ts.dt.timestamp())


@pytest.mark.parametrize("is_naive", [True, False])
@pytest.mark.parametrize(
    "fmt_out",
    [
        "%a",
        "%A",
        "%w",
        "%d",
        "%b",
        "%B",
        "%m",
        "%y",
        "%Y",
        "%H",
        "%I",
        "%p",
        "%M",
        "%S",
        "%f",
        "%j",
        "%U",
        "%W",
        "%%%Y",
        "%G",
        "%u",
        "%V",
        "%Y-%m-%d %H:%M:%S.%f",
        "%Y-%m-%d %H:%M:%S.%%f",
        "%%H:%%M:%%S",  # %%sth must not be expanded to values
    ],
)
def test_strftime(fmt_out: str, is_naive: bool) -> None:
    data = [
        "1960-02-03 08:00:00.000000000",
        "2008-02-29 08:00:00.000000000",
        "2023-03-25 12:00:00.000000000",
        "2023-03-25 12:00:00.000000001",
        "2023-03-25 12:00:00.123456789",
        "2023-03-25 16:43:21.000123000",
        "2023-03-25 17:00:01.987000000",
        "2023-03-25 23:59:59.999999999",
        "2023-03-26 01:59:59.999999999",
        "2023-03-26 03:00:00.000000001",
        "2023-03-26 04:00:00.000000001",
        "2023-03-26 12:00:00.000000001",
        "2123-03-26 12:00:00.000000001",
    ]
    fmt_in = "%Y-%m-%d %H:%M:%S.%f"
    if not is_naive:
        data = [entry + "-02:00" for entry in data]
        fmt_in += "%z"
    df = pd.DataFrame({"ts": pd.to_datetime(data, format=fmt_in)})
    if is_naive:
        df_converted = df
    else:
        df_converted = pd.DataFrame({"ts": df.ts.dt.tz_convert(tz.UTC)})
    df_new = pd.DataFrame({"txt": df_converted.ts.dt.strftime(fmt_out)})
    table = table_from_pandas(df)
    fmt_out_pw = fmt_out.replace("%f", "%6f")
    fmt_out_pw = fmt_out_pw.replace("%%6f", "%%f")
    table_pw = table.select(txt=table.ts.dt.strftime(fmt_out_pw))
    table_pd = table_from_pandas(df_new)
    assert_table_equality(table_pw, table_pd)


def test_strftime_with_format_in_column() -> None:
    pairs = [
        ("1960-02-03T12:45:12.000000", "%Y-%m-%d %H:%M:%S"),
        ("2023-03-25T16:43:21.000000", "%Y-%m-%dT%H:%M:%S"),
        ("2023-03-25T16:43:21.567891", "%Y-%m-%dT%H:%M:%S.%6f"),
        ("2023-05-12T11:14:45.000000", "%H:%M:%S %Y-%m-%d"),
    ]
    expected = table_from_pandas(
        pd.DataFrame(
            {
                "date_str": [
                    "1960-02-03 12:45:12",
                    "2023-03-25T16:43:21",
                    "2023-03-25T16:43:21.567891",
                    "11:14:45 2023-05-12",
                ]
            }
        )
    )
    fmt_in = "%Y-%m-%dT%H:%M:%S.%6f"
    pairs_T = list(zip(*pairs))
    df = pd.DataFrame({"ts": pairs_T[0], "fmt": pairs_T[1]})
    table = table_from_pandas(df)
    table_with_datetime = table.with_columns(date=table.ts.dt.strptime(fmt_in))
    res = table_with_datetime.select(
        date_str=table_with_datetime.date.dt.strftime(table_with_datetime.fmt)
    )
    assert_table_equality_wo_index(res, expected)


@pytest.mark.parametrize(
    "data,fmt",
    [
        (["1960-02-03", "2023-03-25", "2023-03-26", "2123-03-26"], "%Y-%m-%d"),
        (["03.02.1960", "25.03.2023", "26.03.2023", "26.03.2123"], "%d.%m.%Y"),
        (["02.03.1960", "03.25.2023", "03.26.2023", "03.26.2123"], "%m.%d.%Y"),
        (["12:34:00", "01:22:12", "13:00:34", "23:59:59"], "%H:%M:%S"),
        (
            ["12:34:00 PM", "01:22:12 AM", "01:00:34 PM", "11:59:59 PM"],
            "%I:%M:%S %p",
        ),
        (
            ["12:34:00.000000000", "01:22:12.123456789", "13:00:34.111111111"],
            "%H:%M:%S.%f",
        ),
        (["2023-03-25 16:43:21", "2023-03-26 16:43:21"], "%Y-%m-%d %H:%M:%S"),
        (["2023-03-25T16:43:21", "2023-03-26T16:43:21"], "%Y-%m-%dT%H:%M:%S"),
        (
            ["2023-03-25 04:43:21 AM", "2023-03-26 04:43:21 PM"],
            "%Y-%m-%d %I:%M:%S %p",
        ),
        (
            [
                "1900-01-01 00:00:00.396",
                "1900-01-01 00:00:00.396093123",
                "2023-03-25 16:43:21.123456789",
                "2023-03-26 16:43:21.123456789",
                "2023-03-26 16:43:21.12",
            ],
            "%Y-%m-%d %H:%M:%S.%f",
        ),
        (
            [
                "1900-01-01 %f00:00:00.396",
                "1900-01-01 %f00:00:00.396093123",
                "2023-03-25 %f16:43:21.123456789",
                "2023-03-26 %f16:43:21.123456789",
                "2023-03-26 %f16:43:21.12",
            ],
            "%Y-%m-%d %%f%H:%M:%S.%f",
        ),
    ],
)
def test_strptime_naive(data: list[str], fmt: str) -> None:
    df = pd.DataFrame({"ts": pd.to_datetime(data, format=fmt)})
    table_pd = table_from_pandas(df)
    table = table_from_pandas(pd.DataFrame({"a": data}))
    table_pw = table.select(ts=table.a.dt.strptime(fmt))
    assert_table_equality(table_pw, table_pd)


@pytest.mark.parametrize(
    "data,fmt",
    [
        (["1960-02-03", "2023-03-25", "2023-03-26", "2123-03-26"], "%Y-%m-%d"),
        (["03.02.1960", "25.03.2023", "26.03.2023", "26.03.2123"], "%d.%m.%Y"),
        (
            ["12:34:00 PM", "01:22:12 AM", "01:00:34 PM", "11:59:59 PM"],
            "%I:%M:%S %p",
        ),
        (
            ["12:34:00.000000", "01:22:12.12345", "13:00:34.11"],
            "%H:%M:%S.%f",
        ),
        (["2023-03-25 16:43:21", "2023-03-26 16:43:21"], "%Y-%m-%d %H:%M:%S"),
        (
            [
                "1900-01-01 00:00:00.396",
                "1900-01-01 00:00:00.396093",
                "2023-03-25 16:43:21.123456",
                "2023-03-26 16:43:21.123456",
                "2023-03-26 16:43:21.12",
            ],
            "%Y-%m-%d %H:%M:%S.%f",
        ),
    ],
)
def test_strptime_naive_with_python_datetime(data: list[str], fmt: str) -> None:
    table = table_from_pandas(pd.DataFrame({"a": data})).select(
        ts=pw.this.a.dt.strptime(fmt)
    )

    @pw.udf
    def parse_datetime(date_str: str) -> dt.DATE_TIME_NAIVE:
        return datetime.datetime.strptime(date_str, fmt)

    expected = table_from_pandas(pd.DataFrame({"a": data})).select(
        ts=parse_datetime(pw.this.a)
    )
    assert_table_equality(table, expected)


@pytest.mark.parametrize(
    "data,fmt",
    [
        (
            ["2023-03-25 16:43:21+0123", "2023-03-26 16:43:21+0123"],
            "%Y-%m-%d %H:%M:%S%z",
        ),
        (
            ["2023-03-25 16:43:21+01:23", "2023-03-26 16:43:21+01:23"],
            "%Y-%m-%d %H:%M:%S%:z",
        ),
        (
            ["2023-03-25T16:43:21+01:23", "2023-03-26T16:43:21+01:23"],
            "%Y-%m-%dT%H:%M:%S%z",
        ),
        (
            ["2023-03-25 04:43:21 AM +01:23", "2023-03-26 04:43:21 PM +01:23"],
            "%Y-%m-%d %I:%M:%S %p %z",
        ),
        (
            [
                "1900-01-01 00:00:00.396-11:05",
                "1900-01-01 00:00:00.396093123-11:05",
                "2023-03-25 16:43:21.123456789-11:05",
                "2023-03-26 16:43:21.123456789-11:05",
                "2023-03-26 16:43:21.12-11:05",
            ],
            "%Y-%m-%d %H:%M:%S.%f%z",
        ),
        (
            [
                "1900%f01-01 00:00:00.396-11:05",
                "1900%f01-01 00:00:00.396093123-11:05",
                "2023%f03-25 16:43:21.123456789-11:05",
                "2023%f03-26 16:43:21.123456789-11:05",
                "2023%f03-26 16:43:21.12-11:05",
            ],
            "%Y%%f%m-%d %H:%M:%S.%f%z",
        ),
    ],
)
def test_strptime_time_zone_aware(data: list[str], fmt: str) -> None:
    pandas_fmt = fmt.replace("%:z", "%z")  # pandas does not support %:z
    df = pd.DataFrame({"ts": pd.to_datetime(data, format=pandas_fmt)})
    table_pd = table_from_pandas(df)
    table = table_from_pandas(pd.DataFrame({"a": data}))
    table_pw = table.select(ts=table.a.dt.strptime(fmt))
    assert_table_equality(table_pw, table_pd)


def test_strptime_with_format_in_column() -> None:
    pairs = [
        ("1960-02-03 12:45:12", "%Y-%m-%d %H:%M:%S"),
        ("2023-03-25T16:43:21", "%Y-%m-%dT%H:%M:%S"),
        ("2023-03-25T16:43:21.567891234", "%Y-%m-%dT%H:%M:%S.%f"),
        ("11:14:45 2023-05-12", "%H:%M:%S %Y-%m-%d"),
    ]
    expected = table_from_markdown(
        """
         |      date_str
       1 | 1960-02-03T12:45:12.000000
       2 | 2023-03-25T16:43:21.000000
       3 | 2023-03-25T16:43:21.567891
       4 | 2023-05-12T11:14:45.000000
    """
    )
    fmt_out = "%Y-%m-%dT%H:%M:%S.%6f"
    pairs_T = list(zip(*pairs))
    df = pd.DataFrame({"ts": pairs_T[0], "fmt": pairs_T[1]})
    table = table_from_pandas(df)
    table_with_datetime = table.select(
        date=table.ts.dt.strptime(table.fmt, contains_timezone=False)
    )
    res = table_with_datetime.select(
        date_str=table_with_datetime.date.dt.strftime(fmt_out)
    )
    assert_table_equality_wo_index(res, expected)


def test_strptime_naive_errors_on_wrong_specifier() -> None:
    table_from_pandas(pd.DataFrame({"a": ["2023-03-26 16:43:21-12"]})).select(
        t=pw.this.a.dt.strptime("%Y-%m-%d %H:%M:%S-%f")
    )
    with pytest.raises(
        ValueError,
        match=re.escape(
            'parse error: cannot use format "%Y-%m-%d %H:%M:%S-%f": '
            'using "%f" without the leading dot is not supported'
        ),
    ):
        run_all()


def test_strptime_naive_errors_on_wrong_format() -> None:
    table_from_pandas(pd.DataFrame({"a": ["2023-03-26T16:43:21.12"]})).select(
        t=pw.this.a.dt.strptime("%Y-%m-%d %H:%M:%S.%f")
    )
    with pytest.raises(
        ValueError,
        match=re.escape(
            'parse error: cannot parse date "2023-03-26T16:43:21.12" '
            'using format "%Y-%m-%d %H:%M:%S%.f"'
        ),
    ):
        run_all()


def test_strptime_utc_errors_on_wrong_specifier() -> None:
    table_from_pandas(
        pd.DataFrame({"a": ["2023-03-26 16:43:21-12+0100"]})
    ).select(t=pw.this.a.dt.strptime("%Y-%m-%d %H:%M:%S-%f%z"))
    with pytest.raises(
        ValueError,
        match=re.escape(
            'parse error: cannot use format "%Y-%m-%d %H:%M:%S-%f%z": '
            'using "%f" without the leading dot is not supported'
        ),
    ):
        run_all()


def test_strptime_utc_errors_on_wrong_format() -> None:
    table_from_pandas(
        pd.DataFrame({"a": ["2023-03-26T16:43:21.12-0100"]})
    ).select(t=pw.this.a.dt.strptime("%Y-%m-%d %H:%M:%S.%f%z"))
    with pytest.raises(
        ValueError,
        match=re.escape(
            'parse error: cannot parse date "2023-03-26T16:43:21.12-0100" '
            'using format "%Y-%m-%d %H:%M:%S%.f%z"'
        ),
    ):
        run_all()


def test_date_time_naive_to_utc() -> None:
    table = table_from_markdown(
        """
           |         date_string
         1 | 2023-03-25T12:00:00.000000000
         2 | 2023-03-25T23:00:00.000000000
         3 | 2023-03-26T00:00:00.000000000
         4 | 2023-03-26T01:00:00.000000000
         5 | 2023-03-26T01:59:59.999999999
         6 | 2023-03-26T02:00:00.000000000
         7 | 2023-03-26T02:00:00.000000001
         8 | 2023-03-26T02:30:00.000000000
         9 | 2023-03-26T02:59:59.999999999
        10 | 2023-03-26T03:00:00.000000000
        11 | 2023-03-26T03:00:00.000000001
        12 | 2023-03-26T03:30:00.000000000
        13 | 2023-03-26T04:00:00.000000000
        14 | 2023-10-28T23:00:00.000000000
        15 | 2023-10-29T01:00:00.000000000
        16 | 2023-10-29T01:59:59.999999999
        17 | 2023-10-29T02:00:00.000000000
        18 | 2023-10-29T02:00:00.000000001
        19 | 2023-10-29T02:00:30.000000000
        20 | 2023-10-29T02:59:59.999999999
        21 | 2023-10-29T03:00:00.000000000
        22 | 2023-10-29T03:00:00.000000001
        23 | 2023-10-29T03:30:00.000000000
        24 | 2023-10-29T04:00:00.000000000
    """
    )

    expected = table_from_markdown(
        """
           |         date_string
         1 | 2023-03-25T11:00:00.000000000+0000
         2 | 2023-03-25T22:00:00.000000000+0000
         3 | 2023-03-25T23:00:00.000000000+0000
         4 | 2023-03-26T00:00:00.000000000+0000
         5 | 2023-03-26T00:59:59.999999999+0000
         6 | 2023-03-26T01:00:00.000000000+0000
         7 | 2023-03-26T01:00:00.000000000+0000
         8 | 2023-03-26T01:00:00.000000000+0000
         9 | 2023-03-26T01:00:00.000000000+0000
        10 | 2023-03-26T01:00:00.000000000+0000
        11 | 2023-03-26T01:00:00.000000001+0000
        12 | 2023-03-26T01:30:00.000000000+0000
        13 | 2023-03-26T02:00:00.000000000+0000
        14 | 2023-10-28T21:00:00.000000000+0000
        15 | 2023-10-28T23:00:00.000000000+0000
        16 | 2023-10-28T23:59:59.999999999+0000
        17 | 2023-10-29T01:00:00.000000000+0000
        18 | 2023-10-29T01:00:00.000000001+0000
        19 | 2023-10-29T01:00:30.000000000+0000
        20 | 2023-10-29T01:59:59.999999999+0000
        21 | 2023-10-29T02:00:00.000000000+0000
        22 | 2023-10-29T02:00:00.000000001+0000
        23 | 2023-10-29T02:30:00.000000000+0000
        24 | 2023-10-29T03:00:00.000000000+0000
    """
    )
    fmt_in = "%Y-%m-%dT%H:%M:%S.%f"
    fmt_out = "%Y-%m-%dT%H:%M:%S.%f%z"
    table_with_datetime = table.select(t=table.date_string.dt.strptime(fmt_in))
    table_utc = table_with_datetime.select(
        t=table_with_datetime.t.dt.to_utc("Europe/Warsaw")
    )
    res = table_utc.select(date_string=table_utc.t.dt.strftime(fmt_out))

    assert_table_equality(res, expected)


def test_date_time_utc_to_naive() -> None:
    table = table_from_markdown(
        """
           |         date_string
         1 | 2023-03-25T11:00:00.000000000+0000
         2 | 2023-03-25T22:00:00.000000000+0000
         3 | 2023-03-25T23:00:00.000000000+0000
         4 | 2023-03-26T00:00:00.000000000+0000
         5 | 2023-03-26T00:59:59.999999999+0000
         6 | 2023-03-26T01:00:00.000000000+0000
         7 | 2023-03-26T01:00:00.000001000+0000
         8 | 2023-03-26T01:30:00.000000000+0000
         9 | 2023-03-26T02:00:00.000000000+0000
        10 | 2023-10-28T21:00:00.000000000+0000
        11 | 2023-10-28T23:00:00.000000000+0000
        12 | 2023-10-28T23:59:59.999999999+0000
        13 | 2023-10-29T00:00:00.000000000+0000
        14 | 2023-10-29T00:00:00.000001000+0000
        15 | 2023-10-29T00:00:30.000000000+0000
        16 | 2023-10-29T00:59:59.999999999+0000
        17 | 2023-10-29T01:00:00.000000000+0000
        18 | 2023-10-29T01:00:00.000001000+0000
        19 | 2023-10-29T01:00:30.000000000+0000
        20 | 2023-10-29T01:59:59.999999999+0000
        21 | 2023-10-29T02:00:00.000000000+0000
        22 | 2023-10-29T02:00:00.000001000+0000
        23 | 2023-10-29T02:30:00.000000000+0000
        24 | 2023-10-29T03:00:00.000000000+0000
    """
    )

    expected = table_from_markdown(
        """
           |         date_string
         1 | 2023-03-25T12:00:00.000000
         2 | 2023-03-25T23:00:00.000000
         3 | 2023-03-26T00:00:00.000000
         4 | 2023-03-26T01:00:00.000000
         5 | 2023-03-26T01:59:59.999999
         6 | 2023-03-26T03:00:00.000000
         7 | 2023-03-26T03:00:00.000001
         8 | 2023-03-26T03:30:00.000000
         9 | 2023-03-26T04:00:00.000000
        10 | 2023-10-28T23:00:00.000000
        11 | 2023-10-29T01:00:00.000000
        12 | 2023-10-29T01:59:59.999999
        13 | 2023-10-29T02:00:00.000000
        14 | 2023-10-29T02:00:00.000001
        15 | 2023-10-29T02:00:30.000000
        16 | 2023-10-29T02:59:59.999999
        17 | 2023-10-29T02:00:00.000000
        18 | 2023-10-29T02:00:00.000001
        19 | 2023-10-29T02:00:30.000000
        20 | 2023-10-29T02:59:59.999999
        21 | 2023-10-29T03:00:00.000000
        22 | 2023-10-29T03:00:00.000001
        23 | 2023-10-29T03:30:00.000000
        24 | 2023-10-29T04:00:00.000000
    """
    )
    fmt_in = "%Y-%m-%dT%H:%M:%S.%f%z"
    fmt_out = "%Y-%m-%dT%H:%M:%S.%6f"
    table_utc = table.select(t=table.date_string.dt.strptime(fmt_in))
    table_local = table_utc.select(
        t=table_utc.t.dt.to_naive_in_timezone("Europe/Warsaw")
    )
    res = table_local.select(date_string=table_local.t.dt.strftime(fmt_out))

    assert_table_equality(res, expected)


@pytest.mark.parametrize("op", [operator.add, operator.sub])
def test_add_sub_in_timezone(op: Any) -> None:
    pairs = [
        ["2023-03-26 01:00:00", pd.Timedelta(minutes=30)],
        ["2023-03-26 01:00:00", pd.Timedelta(hours=1)],
        ["2023-03-26 01:00:00", pd.Timedelta(minutes=90)],
        ["2023-03-26 01:00:00", pd.Timedelta(hours=2)],
        ["2023-03-26 01:43:00", pd.Timedelta(minutes=16)],
        ["2023-03-26 01:43:00", pd.Timedelta(minutes=17)],
        ["2023-03-26 01:43:00", pd.Timedelta(hours=1)],
        ["2023-03-26 03:02:00", pd.Timedelta(minutes=-2)],
        ["2023-03-26 03:02:00", pd.Timedelta(minutes=-3)],
        ["2023-10-29 01:59:00", pd.Timedelta(minutes=1)],
        ["2023-10-29 01:59:00", pd.Timedelta(hours=1)],
        ["2023-10-29 01:59:00", pd.Timedelta(hours=2)],
        ["2023-10-29 02:00:00", pd.Timedelta(minutes=1)],
        ["2023-10-29 02:00:00", pd.Timedelta(minutes=-1)],
    ]

    expected = table_from_pandas(
        pd.DataFrame(
            {
                "date_string": [
                    "2023-03-26 01:30:00",
                    "2023-03-26 03:00:00",
                    "2023-03-26 03:30:00",
                    "2023-03-26 04:00:00",
                    "2023-03-26 01:59:00",
                    "2023-03-26 03:00:00",
                    "2023-03-26 03:43:00",
                    "2023-03-26 03:00:00",
                    "2023-03-26 01:59:00",
                    "2023-10-29 02:00:00",
                    "2023-10-29 02:59:00",
                    "2023-10-29 02:59:00",
                    "2023-10-29 02:01:00",
                    "2023-10-29 02:59:00",
                ]
            }
        )
    )

    timezone = "Europe/Warsaw"
    fmt = "%Y-%m-%d %H:%M:%S"
    pairs_T = list(zip(*pairs))
    df = pd.DataFrame({"ts": pairs_T[0], "duration": pairs_T[1]})

    table = table_from_pandas(df)
    table = table.with_columns(ts=table.ts.dt.strptime(fmt))

    if op == operator.add:
        res = table.select(
            res=table.ts.dt.add_duration_in_timezone(table.duration, timezone)
        )
    else:
        res = table.select(
            res=table.ts.dt.subtract_duration_in_timezone(
                -table.duration, timezone
            )
        )

    table_pw = res.select(date_string=res.res.dt.strftime(fmt))

    assert_table_equality(table_pw, expected)


def test_date_time_sub_in_timezone() -> None:
    table = table_from_markdown(
        """
           |           a         |           b
         1 | 2023-03-26T01:00:00 | 2023-03-26T00:55:00
         2 | 2023-03-26T03:00:00 | 2023-03-26T01:55:00
         3 | 2023-03-26T01:56:00 | 2023-03-26T03:01:00
         4 | 2023-03-26T04:00:00 | 2023-03-26T01:00:00
         5 | 2023-03-26T04:00:00 | 2023-03-26T03:00:00
         6 | 2023-10-29T01:59:00 | 2023-10-29T02:00:00
         7 | 2023-10-29T02:59:00 | 2023-10-29T02:59:00
         8 | 2023-10-29T02:59:00 | 2023-10-29T02:00:00
         9 | 2023-10-29T02:30:00 | 2023-10-29T01:30:00
    """
    )
    expected = table_from_markdown(
        """
           | diff
         1 |   5
         2 |   5
         3 |  -5
         4 | 120
         5 |  60
         6 | -61
         7 |   0
         8 |  59
         9 | 120
        """
    )
    timezone = "Europe/Warsaw"
    fmt = "%Y-%m-%dT%H:%M:%S"
    parsed = table.select(
        a=table.a.dt.strptime(fmt), b=table.b.dt.strptime(fmt)
    )
    res = parsed.select(
        diff=parsed.a.dt.subtract_date_time_in_timezone(
            parsed.b, timezone
        ).dt.minutes()
    )
    assert_table_equality(res, expected)


@pytest.mark.parametrize("is_naive", [True, False])
@pytest.mark.parametrize(
    "round_to",
    [
        pd.Timedelta(days=1),
        pd.Timedelta(hours=2),
        pd.Timedelta(hours=1),
        pd.Timedelta(minutes=20),
        pd.Timedelta(minutes=1),
        pd.Timedelta(seconds=1),
        pd.Timedelta(minutes=43),
        pd.Timedelta(seconds=19),
        # pandas-3 spellings of the reference's (removed) offset aliases
        "D",
        "2h3min",
        "min",
        "s",
        "14ms22us",
        "us",
        "ns",
    ],
)
@pytest.mark.parametrize("method_name", ["round", "floor"])
def test_date_time_round(
    method_name: str, round_to: pd.Timedelta | str, is_naive: bool
) -> None:
    data = [
        "2020-03-04 11:13:00.345612",
        "2020-03-04 12:13:00.345612",
        "2020-03-04 12:00:00.0",
        "2020-03-04 11:59:59.999999999",
        "2020-03-04 13:22:23.0",
        "2023-05-19 13:56:23.0",
        "2023-05-19 13:56:23.123456789",
        "2023-05-01 09:10:11.121314",
    ]
    fmt = "%Y-%m-%d %H:%M:%S.%f"
    if not is_naive:
        data = [entry + "+00:00" for entry in data]
        fmt += "%z"
    df = pd.DataFrame({"date": data})
    table = table_from_pandas(df)
    table = table.select(date=table.date.dt.strptime(fmt=fmt))
    res = table.select(rounded=getattr(table.date.dt, method_name)(round_to))

    expected = table_from_pandas(
        pd.DataFrame(
            {
                "rounded": getattr(
                    pd.to_datetime(df.date, format=fmt).dt, method_name
                )(round_to)
            }
        )
    )

    assert_table_equality(res, expected)


@pytest.mark.parametrize(
    "method_with_args",
    [
        ("dt.nanosecond",),
        ("dt.seconds",),
        ("dt.strftime", "%Y-%m-%d %H:%M:%S"),
        ("dt.strptime", "%Y-%m-%d %H:%M:%S"),
    ],
)
def test_fail_if_used_with_wrong_type(method_with_args: tuple[str]) -> None:
    method = method_with_args[0]
    namespace, method_name = method.split(".")
    args = method_with_args[1:]
    table = table_from_pandas(pd.DataFrame({"a": [1, 2, 3]}))
    with pytest.raises(AttributeError):
        table.select(
            a=getattr(getattr(table.a, namespace), method_name)(*args)
        )


def test_from_timestamp_ns() -> None:
    fmt = "%Y-%m-%dT%H:%M:%S.%f"
    table = table_from_markdown(
        """
      | timestamp
    1 |    10
    2 | 1685969950453404012
    """
    )
    expected = table_from_markdown(
        """
      | date
    1 | 1970-01-01T00:00:00.000000010
    2 | 2023-06-05T12:59:10.453404012
    """
    ).with_columns(date=pw.this.date.dt.strptime(fmt))
    table = table.select(date=pw.this.timestamp.dt.from_timestamp(unit="ns"))

    assert_table_equality(table, expected)


def test_from_timestamp_us() -> None:
    fmt = "%Y-%m-%dT%H:%M:%S.%f"
    table = table_from_markdown(
        """
      | timestamp
    1 |    10
    2 | 1685969950453404
    """
    )
    expected = table_from_markdown(
        """
      | date
    1 | 1970-01-01T00:00:00.000010000
    2 | 2023-06-05T12:59:10.453404000
    """
    ).with_columns(date=pw.this.date.dt.strptime(fmt))
    table = table.select(date=pw.this.timestamp.dt.from_timestamp(unit="us"))

    assert_table_equality(table, expected)


def test_from_timestamp_ms() -> None:
    fmt = "%Y-%m-%dT%H:%M:%S.%f"
    table = table_from_markdown(
        """
      | timestamp
    1 |    10
    2 | 1685969950453
    """
    )
    expected = table_from_markdown(
        """
      | date
    1 | 1970-01-01T00:00:00.010000000
    2 | 2023-06-05T12:59:10.453000000
    """
    ).with_columns(date=pw.this.date.dt.strptime(fmt))
    table = table.select(date=pw.this.timestamp.dt.from_timestamp(unit="ms"))

    assert_table_equality(table, expected)


def test_from_timestamp_s() -> None:
    fmt = "%Y-%m-%dT%H:%M:%S"
    table = table_from_markdown(
        """
      | timestamp
    1 |    10
    2 | 1685969950
    """
    )
    expected = table_from_markdown(
        """
      | date
    1 | 1970-01-01T00:00:10
    2 | 2023-06-05T12:59:10
    """
    ).with_columns(date=pw.this.date.dt.strptime(fmt))
    table = table.select(date=pw.this.timestamp.dt.from_timestamp(unit="s"))

    assert_table_equality(table, expected)


def test_from_timestamp_s_utc() -> None:
    fmt = "%Y-%m-%dT%H:%M:%S%z"
    table = table_from_markdown(
        """
      | timestamp
    1 |    10
    2 | 1685969950
    """
    )
    expected = table_from_markdown(
        """
      | date
    1 | 1970-01-01T00:00:10+00:00
    2 | 2023-06-05T12:59:10+00:00
    """
    ).with_columns(date=pw.this.date.dt.strptime(fmt))
    table = table.select(
        date=pw.this.timestamp.dt.utc_from_timestamp(unit="s")
    )

    assert_table_equality(table, expected)


@pytest.mark.parametrize("is_naive", [True, False])
def test_weekday(is_naive: bool) -> None:
    data = [
        "1960-02-03 08:00:00.000000000",
        "2008-02-29 08:00:00.000000000",
        "2023-03-25 12:00:00.000000000",
        "2023-03-25 12:00:00.000000001",
        "2023-03-25 12:00:00.123456789",
        "2023-03-25 16:43:21.000123000",
        "2023-03-25 17:00:01.987000000",
        "2023-03-25 23:59:59.999999999",
        "2023-03-26 01:59:59.999999999",
        "2023-03-26 03:00:00.000000001",
        "2023-03-26 04:00:00.000000001",
        "2023-03-26 12:00:00.000000001",
        "2123-03-26 12:00:00.000000001",
    ]
    fmt_in = "%Y-%m-%d %H:%M:%S.%f"
    if not is_naive:
        data = [entry + "-02:00" for entry in data]
        fmt_in += "%z"
    df = pd.DataFrame({"ts": pd.to_datetime(data, format=fmt_in)})
    if is_naive:
        df_converted = df
    else:
        df_converted = pd.DataFrame({"ts": df.ts.dt.tz_convert(tz.UTC)})
    df_new = pd.DataFrame({"txt": df_converted.ts.dt.weekday})
    table = table_from_pandas(df)
    table_pw = table.select(txt=table.ts.dt.weekday())
    table_pd = table_from_pandas(df_new)
    assert_table_equality(table_pw, table_pd)


def test_pathway_duration():
    values = [
        (1, ["W"]),
        (1, ["D", "day", "days"]),
        (24, ["h", "hr", "hour", "hours"]),
        (24 * 60, ["m", "min", "minute", "minutes"]),
        (24 * 60 * 60, ["s", "sec", "second", "seconds"]),
        (
            24 * 60 * 60 * 1000,
            ["ms", "millisecond", "milliseconds", "millis", "milli"],
        ),
        (
            24 * 60 * 60 * 1000 * 1000,
            ["us", "microsecond", "microsecond", "micros", "micro"],
        ),
        (
            24 * 60 * 60 * 1000 * 1000 * 1000,
            ["ns", "nanosecond", "nanoseconds", "nanos", "nano"],
        ),
    ]

    markdown = "value | unit\n"
    for value, units in values:
        for unit in units:
            markdown += f"{value} | {unit}\n"
    t = table_from_markdown(markdown)

    result = t.select(value=pw.this.value.dt.to_duration(pw.this.unit))

    assert_table_equality(
        result,
        table_from_pandas(
            pd.DataFrame(
                {
                    "value": [
                        pd.Timedelta(f"{v} {u}")
                        for v, units in values
                        for u in units
                    ]
                }
            )
        ),
    )


def test_pathway_duration_from_udf():
    t = table_from_markdown(
        """
        value
        1
    """
    )

    @pw.udf
    def to_duration(a) -> pw.Duration:
        return pw.Duration(days=a)

    result = t.select(value=to_duration(pw.this.value))
    assert_table_equality(
        result,
        table_from_pandas(pd.DataFrame({"value": [pd.Timedelta(days=1)]})),
    )


def test_pathway_datetimes():
    @pw.udf
    def to_naive(year, month, day) -> pw.DateTimeNaive:
        return pw.DateTimeNaive(year=year, month=month, day=day)

    @pw.udf
    def to_utc(year, month, day) -> pw.DateTimeUtc:
        return pw.DateTimeUtc(year=year, month=month, day=day, tz=tz.UTC)

    t = table_from_markdown(
        """
        year | month | day
        2023 |   8   |  12
    """
    )

    result = t.select(value=to_naive(pw.this.year, pw.this.month, pw.this.day))
    assert_table_equality(
        result,
        table_from_pandas(
            pd.DataFrame({"value": [pd.Timestamp(year=2023, month=8, day=12)]})
        ),
    )

    result = t.select(value=to_utc(pw.this.year, pw.this.month, pw.this.day))
    assert_table_equality(
        result,
        table_from_pandas(
            pd.DataFrame(
                {"value": [pd.Timestamp(year=2023, month=8, day=12, tz=tz.UTC)]}
            )
        ),
    )
