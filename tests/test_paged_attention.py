"""Ragged paged-attention kernel: interpret-mode shape pins + the
decode-vs-pure-JAX-twin differential (the pallas_topk k-pad pattern
applied to the generation plane's kernel — interpret-green is not
lowerable-green, so the static 8x128 gate runs on every shape the
decoder will emit)."""

import numpy as np
import pytest


def _rand_case(b, h, p, dp, n_pages, max_pages, seed):
    rng = np.random.default_rng(seed)
    q = rng.normal(size=(b, h, dp)).astype(np.float32)
    k = rng.normal(size=(n_pages, h, p, dp)).astype(np.float32)
    v = rng.normal(size=(n_pages, h, p, dp)).astype(np.float32)
    pt = rng.integers(1, n_pages, size=(b, max_pages)).astype(np.int32)
    sl = rng.integers(0, max_pages * p + 1, size=(b,)).astype(np.int32)
    return q, k, v, pt, sl


@pytest.mark.parametrize(
    "b,h,p,dp,n_pages,max_pages",
    [
        (4, 4, 8, 128, 16, 3),  # the decoder's default layout
        (1, 2, 16, 256, 8, 4),  # multi-lane head_dim
        (8, 4, 8, 128, 32, 5),
        (3, 1, 4, 128, 7, 2),  # page_size below the sublane width
    ],
)
def test_kernel_matches_twin_ragged(b, h, p, dp, n_pages, max_pages):
    """The Pallas kernel (interpret mode) and the jitted pure-JAX twin
    agree over ragged page counts — including zero-length (padded
    batch) slots, which must come back exactly zero."""
    from pathway_tpu.ops import paged_attention as pa

    q, k, v, pt, sl = _rand_case(b, h, p, dp, n_pages, max_pages, b * 31)
    sl[0] = 0  # always include an empty slot
    scale = 1.0 / np.sqrt(32.0)
    ref = np.asarray(
        pa.paged_attention_ref(q, k, v, pt, sl, sm_scale=scale)
    )
    out = np.asarray(
        pa.paged_attention(q, k, v, pt, sl, sm_scale=scale, interpret=True)
    )
    assert np.allclose(ref, out, atol=2e-6), np.abs(ref - out).max()
    assert (out[0] == 0.0).all()  # empty slot zero-fills
    pa.validate_lowering(b, h, p, dp, n_pages, max_pages)


def test_ragged_boundary_lengths():
    """Sequence lengths at the exact page boundaries (0, P, P+1, full)
    mask precisely: equality with a dense masked-softmax oracle."""
    from pathway_tpu.ops import paged_attention as pa

    b, h, p, dp, n_pages, max_pages = 4, 2, 8, 128, 12, 3
    q, k, v, pt, sl = _rand_case(b, h, p, dp, n_pages, max_pages, 99)
    sl[:] = [0, p, p + 1, max_pages * p]
    out = np.asarray(
        pa.paged_attention(q, k, v, pt, sl, sm_scale=0.2, interpret=True)
    )
    # dense oracle in numpy
    for i in range(b):
        n = int(sl[i])
        if n == 0:
            assert (out[i] == 0.0).all()
            continue
        kk = np.concatenate(
            [k[pt[i, j]] for j in range(max_pages)], axis=1
        )[:, :n]  # [H, n, Dp]
        vv = np.concatenate(
            [v[pt[i, j]] for j in range(max_pages)], axis=1
        )[:, :n]
        s = np.einsum("hd,hnd->hn", q[i], kk) * 0.2
        w = np.exp(s - s.max(axis=1, keepdims=True))
        w /= w.sum(axis=1, keepdims=True)
        o = np.einsum("hn,hnd->hd", w, vv)
        assert np.allclose(o, out[i], atol=2e-5)


def test_lane_pad_boundaries():
    """The lane ladder's edges (the pallas_topk _kpad pins, applied to
    head_dim)."""
    from pathway_tpu.ops.paged_attention import lane_pad

    assert lane_pad(1) == 128
    assert lane_pad(32) == 128  # the decoder default's pad
    assert lane_pad(128) == 128  # aligned: pads to itself
    assert lane_pad(129) == 256  # one past: a full lane width


def test_lowering_gate_rejects_unpadded_head_dim():
    """The 8x128 rule statically: an UNpadded head_dim (the BENCH_r02
    class of failure — interpret-green, crashes at Mosaic lowering)
    must be rejected by the gate even on the CPU backend."""
    from pathway_tpu.ops import paged_attention as pa

    # decoder shapes that must lower
    pa.validate_lowering(8, 4, 16, 128, 64, 16)
    pa.validate_lowering(1, 1, 8, 256, 4, 2)
    # raw head_dim 32: not a lane multiple
    with pytest.raises(ValueError, match="lane-padded"):
        pa.validate_lowering(8, 4, 16, 32, 64, 16)
    # and the shared rule checker still rejects a bad block outright
    from pathway_tpu.ops.pallas_topk import check_tpu_block_rules

    with pytest.raises(ValueError):
        check_tpu_block_rules((1, 4, 7, 128), (16, 4, 16, 128))


def test_decode_step_pallas_vs_ref_twin():
    """The full decode step through the Pallas kernel (interpret) and
    through the pure-JAX twin produce the same logits AND the same
    KV-pool contents — the kernel can serve as a drop-in on TPU."""
    import jax.numpy as jnp

    from pathway_tpu.xpacks.llm import decoder as dec

    cfg = dec.DecoderConfig(
        dim=64, n_layers=1, n_heads=2, head_dim=32, ffn_dim=128,
        max_len=64, page_size=8,
    )
    params = dec.init_params(cfg, seed=3)
    toks = dec.encode_text("paged")
    outs = {}
    pools = {}
    for kernel in ("ref", "pallas"):
        k_pool, v_pool = dec.empty_pools(cfg, n_pages=6)
        pt = np.zeros((1, cfg.max_pages), np.int32)
        pt[0, :3] = [1, 2, 3]
        logits_seq = []
        for i, t in enumerate(toks + [65, 66]):
            logits, k_pool, v_pool = dec.decode_step(
                params,
                np.array([t], np.int32),
                np.array([i], np.int32),
                k_pool,
                v_pool,
                jnp.asarray(pt),
                np.array([i + 1], np.int32),
                cfg=cfg,
                kernel=kernel,
                interpret=True,
            )
            logits_seq.append(np.asarray(logits)[0])
        outs[kernel] = np.stack(logits_seq)
        pools[kernel] = (np.asarray(k_pool), np.asarray(v_pool))
    assert np.allclose(outs["ref"], outs["pallas"], atol=1e-4), np.abs(
        outs["ref"] - outs["pallas"]
    ).max()
    for a, b in zip(pools["ref"], pools["pallas"]):
        assert np.allclose(a, b, atol=1e-4)


def test_twin_page_table_indirection():
    """Two different page tables naming the same physical content give
    identical outputs — the attention depends on the mapped pages, not
    their physical ids (the restore-path invariant: a restored pool
    with different page ids reproduces the run)."""
    from pathway_tpu.ops import paged_attention as pa

    b, h, p, dp, n_pages, max_pages = 2, 2, 8, 128, 10, 2
    q, k, v, pt, sl = _rand_case(b, h, p, dp, n_pages, max_pages, 5)
    sl[:] = [11, 13]
    out1 = np.asarray(pa.paged_attention_ref(q, k, v, pt, sl, sm_scale=1.0))
    # permute physical pages, remap the table accordingly
    perm = np.random.default_rng(6).permutation(n_pages)
    inv = np.argsort(perm)
    k2, v2 = k[perm], v[perm]
    pt2 = inv[pt].astype(np.int32)
    out2 = np.asarray(
        pa.paged_attention_ref(q, k2, v2, pt2, sl, sm_scale=1.0)
    )
    assert np.allclose(out1, out2, atol=1e-6)
