"""IVF scale-out index (VERDICT r3 item 10; design note: ops/ivf.py;
reference counterpart: usearch HNSW, usearch_integration.rs:20)."""

import numpy as np
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts
from pathway_tpu.stdlib.indexing._index_impls import IvfKnnIndex


def _vec_table(rows):
    import pathway_tpu.debug as dbg

    schema = pw.schema_from_types(name=str, vec=np.ndarray)
    return dbg.table_from_rows(
        schema, [(n, np.asarray(v, dtype=np.float32)) for n, v in rows]
    )


DOCS = [
    ("a", [1.0, 0.0, 0.0]),
    ("b", [0.0, 1.0, 0.0]),
    ("c", [0.0, 0.0, 1.0]),
    ("d", [0.9, 0.1, 0.0]),
]


def test_ivf_data_index_query_small_exact():
    """Below min_train the IVF index scores exactly — the DataIndex matrix
    result matches the brute-force index bit for bit."""
    docs = _vec_table(DOCS)
    queries = _vec_table([("q1", [1.0, 0.0, 0.0]), ("q2", [0.0, 1.0, 0.0])])
    from pathway_tpu.stdlib.indexing import DataIndex, IvfKnn

    index = DataIndex(docs, IvfKnn(docs.vec, dimensions=3))
    result = index.query_as_of_now(queries.vec, number_of_matches=2).select(
        qname=pw.left.name, names=pw.right.name
    )
    _keys, cols = table_to_dicts(result)
    by_q = {cols["qname"][k]: cols["names"][k] for k in cols["qname"]}
    assert by_q["q1"] == ("a", "d")
    assert by_q["q2"][0] == "b"


def test_ivf_metadata_filter():
    import pathway_tpu.debug as dbg

    schema = pw.schema_from_types(name=str, vec=np.ndarray, meta=dict)
    docs = dbg.table_from_rows(
        schema,
        [
            ("a", np.asarray([1.0, 0.0], np.float32), {"lang": "en"}),
            ("b", np.asarray([0.9, 0.1], np.float32), {"lang": "fr"}),
        ],
    )
    queries = T(
        """
        qname | filter
        q1    | lang=='fr'
        """
    ).select(
        qname=pw.this.qname,
        filter=pw.this.filter,
        vec=pw.apply_with_type(
            lambda _: np.asarray([1.0, 0.0], np.float32),
            np.ndarray,
            pw.this.qname,
        ),
    )
    from pathway_tpu.stdlib.indexing import DataIndex, IvfKnn

    index = DataIndex(docs, IvfKnn(docs.vec, docs.meta, dimensions=2))
    result = index.query_as_of_now(
        queries.vec, number_of_matches=1, metadata_filter=queries["filter"]
    ).select(names=pw.right.name)
    _keys, cols = table_to_dicts(result)
    assert list(cols["names"].values()) == [("b",)]


def test_ivf_trained_engine_path():
    """With min_train lowered, the DataIndex query runs through the real
    two-level path (centroids + inverted lists) and still finds the right
    neighbors on clustered data."""
    rng = np.random.default_rng(0)
    centers = rng.normal(size=(8, 16)).astype(np.float32) * 5
    rows = []
    for i in range(512):
        c = i % 8
        rows.append(
            (f"d{i}", centers[c] + rng.normal(size=16).astype(np.float32) * 0.05)
        )
    docs = _vec_table(rows)
    queries = _vec_table([("q", centers[3])])
    from pathway_tpu.stdlib.indexing import DataIndex, IvfKnn

    inner = IvfKnn(
        docs.vec, dimensions=16, min_train=256, n_clusters=8, n_probe=2
    )
    index = DataIndex(docs, inner)
    result = index.query_as_of_now(queries.vec, number_of_matches=5).select(
        names=pw.right.name
    )
    _keys, cols = table_to_dicts(result)
    names = list(cols["names"].values())[0]
    assert len(names) == 5
    # every match must come from cluster 3
    assert all(int(n[1:]) % 8 == 3 for n in names), names


def test_ivf_recall_at_scale():
    """300k clustered vectors, direct index object: recall@10 vs exact
    brute force >= 0.95, probing only ~sqrt(C) of the lists."""
    rng = np.random.default_rng(1)
    n, dim, n_centers = 300_000, 16, 64
    centers = rng.normal(size=(n_centers, dim)).astype(np.float32) * 3
    assign = rng.integers(0, n_centers, size=n)
    data = centers[assign] + rng.normal(size=(n, dim)).astype(np.float32) * 0.3
    index = IvfKnnIndex(dimensions=dim, metric="cosine", min_train=4096)
    for i in range(n):
        index.upsert(i, data[i], None)
    queries = data[rng.choice(n, size=50, replace=False)]
    res = index.search([(q, 10, None) for q in queries])
    assert index.centroids is not None, "index never trained"
    # exact reference
    dn = data / np.linalg.norm(data, axis=1, keepdims=True)
    hits = total = 0
    for qi, q in enumerate(queries):
        qn = q / np.linalg.norm(q)
        sims = dn @ qn
        exact = set(np.argpartition(-sims, 10)[:10].tolist())
        got = {k for k, _s in res[qi]}
        hits += len(exact & got)
        total += 10
    recall = hits / total
    assert recall >= 0.95, recall


def test_ivf_remove_and_update():
    index = IvfKnnIndex(dimensions=2, metric="cosine", min_train=10**9)
    index.upsert(1, [1.0, 0.0], None)
    index.upsert(2, [0.0, 1.0], None)
    res = index.search([([1.0, 0.0], 1, None)])
    assert res[0][0][0] == 1
    index.remove(1)
    res = index.search([([1.0, 0.0], 1, None)])
    assert res[0][0][0] == 2
    index.upsert(2, [1.0, 0.0], None)  # move key 2
    res = index.search([([1.0, 0.0], 1, None)])
    assert res[0][0][0] == 2 and res[0][0][1] > -1e-6


def test_ivf_snapshot_roundtrip():
    rng = np.random.default_rng(2)
    index = IvfKnnIndex(dimensions=4, metric="cosine", min_train=32)
    for i in range(64):
        index.upsert(i, rng.normal(size=4).astype(np.float32), None)
    index.search([(rng.normal(size=4).astype(np.float32), 3, None)])
    state = index.state_dict()
    import pickle

    restored = IvfKnnIndex(dimensions=4, metric="cosine", min_train=32)
    restored.load_state(pickle.loads(pickle.dumps(state)))
    q = rng.normal(size=4).astype(np.float32)
    assert index.search([(q, 5, None)]) == restored.search([(q, 5, None)])


def test_ivf_device_index_recall_and_speed():
    """IvfDeviceIndex (cluster-sorted device corpus, spilled assignment,
    bucketed fine scoring) reaches >=0.95 recall@10 on mixture data — the
    shape real embedding corpora have (reference ANN tier: usearch HNSW,
    src/external_integration/usearch_integration.rs:20)."""
    import numpy as np

    from pathway_tpu.ops.ivf import IvfDeviceIndex

    rng = np.random.default_rng(0)
    n, dim, k = 20_000, 64, 10
    centers = rng.normal(size=(200, dim)).astype(np.float32)
    asn = rng.integers(0, 200, size=n)
    corpus = (centers[asn] + 0.35 * rng.normal(size=(n, dim))).astype(
        np.float32
    )
    ix = IvfDeviceIndex(corpus, n_probe=16, spill=2)
    cn = corpus / np.linalg.norm(corpus, axis=1, keepdims=True)
    qs = corpus[rng.choice(n, 10)] + 0.1 * rng.normal(
        size=(10, dim)
    ).astype(np.float32)
    hits = 0
    for q in qs:
        _s, ids = ix.query(q, k)
        assert len(set(ids.tolist())) == k  # spilled replicas deduped
        qn = q / np.linalg.norm(q)
        exact = np.argpartition(-(cn @ qn), k - 1)[:k]
        hits += len(set(ids.tolist()) & set(exact.tolist()))
    assert hits / (10 * k) >= 0.95
