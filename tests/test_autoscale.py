"""Flux Pilot (pathway_tpu/autoscale/) tests — the SLO-driven
autoscaler that closes the control loop over Shard Flux.

Covers: the pure hysteresis policy by brute force (a scale-down NEVER
fires while any burn exceeds 1.0; asymmetric windows; cooldown and
in-flight holds; min/max bounds), controller saw-tooth immunity (no
flapping across an oscillating burn), cooldown serialization under
sustained pressure, rollback journaling + lockout, the forecaster's
trend and diurnal lead time (scale-up fires BEFORE the raw signal
crosses), predictive scale-up through the controller, the plane
doctor's ``autoscale-coverage`` rule, and the tier-1 in-process e2e:
a real persisted store scaled 1→2 on surge and 2→1 on drain through
``reshard_stores``, with both transitions journaled and the restored
state value-equal to the original.
"""

from __future__ import annotations

import math
import time

import pytest

import pathway_tpu as pw  # noqa: F401  (conftest clears its graph)
from pathway_tpu.autoscale import (
    DOWN,
    HOLD,
    UP,
    AutoscaleConfig,
    AutoscaleController,
    AutoscalePolicy,
    CallbackActuator,
    Decision,
    LoadForecaster,
    PlaneObservation,
    arm_controller,
    get_controller,
    reset_controller,
)
from pathway_tpu.observability.journal import journal, reset_journal
from pathway_tpu.observability.registry import MetricsRegistry
from pathway_tpu.observability.signals import reset_sampler

_AUTOSCALE_VARS = (
    "PATHWAY_AUTOSCALE_MIN_RANKS",
    "PATHWAY_AUTOSCALE_MAX_RANKS",
    "PATHWAY_AUTOSCALE_UP_WINDOW_S",
    "PATHWAY_AUTOSCALE_DOWN_WINDOW_S",
    "PATHWAY_AUTOSCALE_COOLDOWN_S",
    "PATHWAY_AUTOSCALE_LOW_WATER",
    "PATHWAY_AUTOSCALE_STEP",
    "PATHWAY_AUTOSCALE_HORIZON_S",
    "PATHWAY_AUTOSCALE_INTERVAL_MS",
)
_SLO_VARS = (
    "PATHWAY_SLO_SHED_RATE",
    "PATHWAY_SLO_STALENESS_S",
    "PATHWAY_SLO_TOK_S",
    "PATHWAY_SLO_TTFT_P99_MS",
)


@pytest.fixture(autouse=True)
def _pilot_env(monkeypatch):
    for var in _AUTOSCALE_VARS + _SLO_VARS + (
        "PATHWAY_JOURNAL_PATH",
        "PATHWAY_SERVING_SHARD_MAP",
        "PATHWAY_TENANT_QOS",
    ):
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv("PATHWAY_DCN_SECRET", "flux-pilot-test-secret")
    reset_journal()
    reset_sampler()
    reset_controller()
    yield
    reset_controller()
    reset_sampler()
    reset_journal()


def _cfg(**kw) -> AutoscaleConfig:
    kw.setdefault("min_ranks", 1)
    kw.setdefault("max_ranks", 4)
    kw.setdefault("up_window_s", 15.0)
    kw.setdefault("down_window_s", 120.0)
    kw.setdefault("cooldown_s", 60.0)
    kw.setdefault("low_water", 0.5)
    kw.setdefault("step", 1)
    kw.setdefault("horizon_s", 30.0)
    return AutoscaleConfig(**kw)


class _Sampler:
    """Scripted burn source presented through the SignalSampler burn
    contract (burn_rates() → {signal: {..., 'burn': x}})."""

    def __init__(self, burn=None):
        self.burn = burn

    def burn_rates(self):
        if self.burn is None:
            return {}
        return {
            "shed_rate": {
                "target": 0.01,
                "direction": "max",
                "window_avg": self.burn * 0.01,
                "burn": self.burn,
            }
        }


# --- policy: pure-function properties --------------------------------------


def test_policy_down_never_fires_under_burn_brute_force():
    """The hard guard, checked exhaustively: whatever the duration
    markers, predictor, cooldown, or rank count claim, a scale-down
    never fires while any burn exceeds 1.0."""
    policy = AutoscalePolicy(_cfg())
    checked = 0
    for burn in (1.0001, 1.01, 1.5, 3.0, 50.0):
        for high_for in (0.0, 5.0, 15.0, 300.0):
            for drained_for in (0.0, 120.0, 100000.0):
                for predicted in (None, 0.1, 0.49, 1.2, 9.0):
                    for cooldown in (0.0, 10.0):
                        for in_flight in (False, True):
                            for ranks in (1, 2, 3, 4):
                                d = policy.decide(
                                    PlaneObservation(
                                        mono=1000.0,
                                        ranks=ranks,
                                        max_burn=burn,
                                        burn_high_for_s=high_for,
                                        drained_for_s=drained_for,
                                        predicted_burn=predicted,
                                        cooldown_remaining_s=cooldown,
                                        action_in_flight=in_flight,
                                    )
                                )
                                assert d.action != DOWN, (burn, d)
                                checked += 1
    assert checked == 5 * 4 * 3 * 5 * 2 * 2 * 4


def test_policy_up_needs_sustained_burn_or_forecast():
    policy = AutoscalePolicy(_cfg(up_window_s=15.0))

    def obs(**kw):
        kw.setdefault("mono", 0.0)
        kw.setdefault("ranks", 1)
        return PlaneObservation(**kw)

    # a short spike holds
    d = policy.decide(obs(max_burn=2.0, burn_high_for_s=5.0))
    assert d.action == HOLD
    # sustained past the window scales up
    d = policy.decide(obs(max_burn=2.0, burn_high_for_s=15.0))
    assert d == Decision(UP, 2, d.reason)
    # the forecast alone scales up — zero sustained seconds required
    d = policy.decide(
        obs(max_burn=0.4, burn_high_for_s=0.0, predicted_burn=1.3)
    )
    assert d.action == UP and "predicted" in d.reason


def test_policy_down_needs_long_drain_and_quiet_forecast():
    policy = AutoscalePolicy(_cfg(down_window_s=120.0, low_water=0.5))

    def obs(**kw):
        kw.setdefault("mono", 0.0)
        kw.setdefault("ranks", 2)
        return PlaneObservation(**kw)

    # below low-water but not for long enough
    assert (
        policy.decide(obs(max_burn=0.2, drained_for_s=60.0)).action == HOLD
    )
    # long enough, but inside the band (above low-water) — holds
    assert (
        policy.decide(obs(max_burn=0.8, drained_for_s=500.0)).action == HOLD
    )
    # drained, but the forecast sits above low-water — the down is
    # blocked without (yet) firing an up
    d = policy.decide(
        obs(max_burn=0.2, drained_for_s=130.0, predicted_burn=0.8)
    )
    assert d.action == HOLD
    # a forecast past 1.0 flips the drained plane straight to UP
    d = policy.decide(
        obs(max_burn=0.2, drained_for_s=130.0, predicted_burn=1.4)
    )
    assert d.action == UP
    # drained with a quiet forecast — scales down
    d = policy.decide(
        obs(max_burn=0.2, drained_for_s=130.0, predicted_burn=0.3)
    )
    assert d == Decision(DOWN, 1, d.reason)


def test_policy_holds_blind_pinned_cooldown_and_bounds():
    policy = AutoscalePolicy(_cfg())
    base = dict(mono=0.0, ranks=2)
    # no burn data → never act blind
    assert (
        policy.decide(PlaneObservation(max_burn=None, **base)).action == HOLD
    )
    # cooldown and in-flight dominate everything
    hot = dict(max_burn=5.0, burn_high_for_s=1000.0)
    assert (
        policy.decide(
            PlaneObservation(cooldown_remaining_s=1.0, **hot, **base)
        ).action
        == HOLD
    )
    assert (
        policy.decide(
            PlaneObservation(action_in_flight=True, **hot, **base)
        ).action
        == HOLD
    )
    # bounds clamp
    at_max = PlaneObservation(mono=0.0, ranks=4, **hot)
    assert AutoscalePolicy(_cfg()).decide(at_max).action == HOLD
    at_min = PlaneObservation(
        mono=0.0, ranks=1, max_burn=0.1, drained_for_s=1000.0
    )
    assert policy.decide(at_min).action == HOLD
    # pinned config never acts
    pinned = AutoscalePolicy(_cfg(min_ranks=2, max_ranks=2))
    assert pinned.decide(PlaneObservation(**base, **hot)).action == HOLD


def test_config_from_env(monkeypatch):
    monkeypatch.setenv("PATHWAY_AUTOSCALE_MIN_RANKS", "2")
    monkeypatch.setenv("PATHWAY_AUTOSCALE_MAX_RANKS", "8")
    monkeypatch.setenv("PATHWAY_AUTOSCALE_UP_WINDOW_S", "5")
    monkeypatch.setenv("PATHWAY_AUTOSCALE_LOW_WATER", "0.25")
    cfg = AutoscaleConfig.from_env()
    assert cfg.min_ranks == 2 and cfg.max_ranks == 8
    assert cfg.up_window_s == 5.0 and cfg.low_water == 0.25
    # garbage falls back to defaults instead of crashing the plane
    monkeypatch.setenv("PATHWAY_AUTOSCALE_MAX_RANKS", "lots")
    assert AutoscaleConfig.from_env().max_ranks == 4


# --- controller: saw-tooth, cooldown, rollback ------------------------------


def test_controller_sawtooth_never_flaps():
    """A burn oscillating across the whole band faster than either
    window produces ZERO actions over ten minutes of virtual time."""
    sampler = _Sampler()
    reg = MetricsRegistry()
    ctrl = AutoscaleController(
        CallbackActuator(lambda m: None),
        ranks=2,
        config=_cfg(up_window_s=15.0, down_window_s=120.0, cooldown_s=30.0),
        sampler=sampler,
        registry=reg,
    )
    t0 = time.monotonic()
    for s in range(600):
        sampler.burn = 1.6 if (s // 10) % 2 == 0 else 0.2
        ctrl.step(t0 + s)
    assert ctrl.resizes == 0 and ctrl.ranks == 2
    flaps = reg.get("pathway_autoscale_flaps_total")
    assert flaps.labels().value == 0.0


def test_controller_cooldown_serializes_scale_ups():
    """Under sustained burn the controller steps up through the band
    one cooldown at a time — never a burst of resizes."""
    sampler = _Sampler(burn=3.0)
    reg = MetricsRegistry()
    ctrl = AutoscaleController(
        CallbackActuator(lambda m: None),
        ranks=1,
        config=_cfg(up_window_s=15.0, cooldown_s=30.0, max_ranks=4),
        sampler=sampler,
        registry=reg,
    )
    t0 = time.monotonic()
    sizes = []
    for s in range(200):
        ctrl.step(t0 + s)
        sizes.append(ctrl.ranks)
    assert ctrl.ranks == 4
    # strictly monotone growth, one rank at a time
    assert all(b - a in (0, 1) for a, b in zip(sizes, sizes[1:]))
    # consecutive ups are separated by at least the cooldown
    ups = [s for s, (a, b) in enumerate(zip(sizes, sizes[1:])) if b > a]
    assert all(b - a >= 30 for a, b in zip(ups, ups[1:]))
    holds = reg.get("pathway_autoscale_cooldown_holds_total")
    assert holds.labels().value > 0


def test_controller_rollback_journals_and_locks_out():
    sampler = _Sampler(burn=2.0)
    reg = MetricsRegistry()

    def failing(m):
        raise RuntimeError("ferry died mid-transfer")

    ctrl = AutoscaleController(
        CallbackActuator(failing),
        ranks=1,
        config=_cfg(up_window_s=2.0, cooldown_s=60.0),
        sampler=sampler,
        registry=reg,
    )
    t0 = time.monotonic()
    for s in range(5):
        ctrl.step(t0 + s)
    assert ctrl.ranks == 1 and ctrl.resizes == 0
    kinds = [e["kind"] for e in journal().events()]
    assert "autoscale-rollback" in kinds
    assert reg.get("pathway_autoscale_rollbacks_total").labels().value == 1.0
    # the failure armed the cooldown: the next steps hold even though
    # the burn is still high (no hammering a failing transfer)
    before = ctrl.resizes
    for s in range(5, 20):
        ctrl.step(t0 + s)
    assert ctrl.resizes == before
    rb = [e for e in journal().events(kinds=["autoscale-rollback"])]
    assert rb[0]["data"]["from_ranks"] == 1
    assert rb[0]["data"]["to_ranks"] == 2


def test_controller_rank_seconds_integrates():
    sampler = _Sampler(burn=0.6)
    reg = MetricsRegistry()
    ctrl = AutoscaleController(
        CallbackActuator(lambda m: None),
        ranks=3,
        config=_cfg(),
        sampler=sampler,
        registry=reg,
    )
    t0 = time.monotonic()
    for s in range(11):
        ctrl.step(t0 + s)
    # 3 ranks for 10 virtual seconds
    rs = reg.get("pathway_autoscale_rank_seconds_total").labels().value
    assert rs == pytest.approx(30.0)


# --- predictor: trend and diurnal lead time ---------------------------------


def test_predictor_trend_leads_a_ramp():
    f = LoadForecaster(tau_s=10.0)
    for s in range(120):
        f.observe(float(s), 0.2 + 0.005 * s)  # +0.005/s ramp
    now = 119.0
    current = 0.2 + 0.005 * 119
    ahead = f.forecast(60.0, now)
    assert ahead is not None and ahead > current + 0.15
    # the crossing is seen within the horizon, well before the raw
    # signal gets there ((1.0 - current) / 0.005 ≈ 41 s out)
    lead = f.lead_crossing(1.0, 120.0, now)
    assert lead is not None and 0 < lead < 120.0


def _diurnal_burn(t: float, period: float = 240.0) -> float:
    return 0.2 + 1.1 * max(0.0, math.sin(2 * math.pi * t / period))


def test_predictor_diurnal_profile_gives_lead_time():
    """After two observed cycles, the forecast crosses 1.0 while the
    raw signal is still far below it — the lead the scale-up rides."""
    period = 240.0
    f = LoadForecaster(tau_s=20.0, period_s=period, buckets=48)
    t = 0.0
    while t < 2 * period:
        f.observe(t, _diurnal_burn(t, period))
        t += 2.0
    # early in cycle three: raw burn still low, surge ~30 s out
    now = 2 * period + 5.0
    raw = _diurnal_burn(now, period)
    assert raw < 0.5
    ahead = f.forecast(40.0, now)
    assert ahead is not None and ahead > 1.0
    assert f.state()["profile_coverage"] == 1.0


def test_predictor_seeds_from_signal_ring():
    from pathway_tpu.observability.signals import SignalRing

    ring = SignalRing(64)
    for s in range(32):
        ring.append(1000.0 + s, 100.0 + s, 0.1 * s)
    f = LoadForecaster(tau_s=5.0)
    f.seed(ring.points())
    st = f.state()
    assert st["observations"] == 32
    assert st["level"] == pytest.approx(3.1, abs=0.5)
    assert st["slope"] > 0


def test_controller_predictive_scale_up_fires_before_the_surge():
    """The closed loop: a predictor warmed on two diurnal cycles makes
    the controller journal a scale-up while the observed burn is STILL
    below 1.0 — capacity lands ahead of the modeled surge."""
    period = 240.0
    predictor = LoadForecaster(tau_s=20.0, period_s=period, buckets=48)
    for s in range(0, int(2 * period), 2):
        predictor.observe(float(s), _diurnal_burn(float(s), period))
    sampler = _Sampler()
    ctrl = AutoscaleController(
        CallbackActuator(lambda m: None),
        ranks=1,
        config=_cfg(up_window_s=15.0, cooldown_s=20.0, horizon_s=40.0),
        sampler=sampler,
        predictor=predictor,
        registry=MetricsRegistry(),
    )
    # drive cycle three on the same virtual clock the predictor learned
    up_at_burn = None
    for s in range(int(2 * period), int(2 * period) + 120):
        sampler.burn = _diurnal_burn(float(s), period)
        d = ctrl.step(float(s))
        if d.action == UP:
            up_at_burn = sampler.burn
            break
    assert up_at_burn is not None, "predictive scale-up never fired"
    assert up_at_burn < 1.0, f"scale-up fired late (burn {up_at_burn})"
    ev = journal().events(kinds=["autoscale-decision"])
    assert ev and ev[-1]["data"]["predicted_burn"] > 1.0
    assert ev[-1]["data"]["max_burn"] < 1.0


# --- plane doctor: autoscale-coverage ---------------------------------------


def test_autoscale_coverage_warns_on_unwatched_resizable_plane(monkeypatch):
    from pathway_tpu.analysis.doctor import run_plane_doctor

    monkeypatch.setenv(
        "PATHWAY_SERVING_SHARD_MAP", "127.0.0.1:9001|127.0.0.1:9002"
    )
    report = run_plane_doctor(rules=["autoscale-coverage"])
    hits = report.by_rule("autoscale-coverage")
    assert len(hits) == 1 and hits[0].severity.name == "WARNING"
    assert "no Flux Pilot controller" in hits[0].message
    # arming a controller (with an SLO target) clears it
    monkeypatch.setenv("PATHWAY_SLO_SHED_RATE", "0.01")
    arm_controller(
        CallbackActuator(lambda m: None),
        ranks=1,
        config=_cfg(),
        registry=MetricsRegistry(),
    )
    report = run_plane_doctor(rules=["autoscale-coverage"])
    assert not report.by_rule("autoscale-coverage")


def test_autoscale_coverage_warns_on_blind_controller(monkeypatch):
    from pathway_tpu.analysis.doctor import run_plane_doctor

    arm_controller(
        CallbackActuator(lambda m: None),
        ranks=1,
        config=_cfg(),
        registry=MetricsRegistry(),
    )
    report = run_plane_doctor(rules=["autoscale-coverage"])
    hits = report.by_rule("autoscale-coverage")
    assert len(hits) == 1 and hits[0].severity.name == "WARNING"
    assert "zero PATHWAY_SLO_" in hits[0].message
    monkeypatch.setenv("PATHWAY_SLO_SHED_RATE", "0.01")
    assert not run_plane_doctor(rules=["autoscale-coverage"]).by_rule(
        "autoscale-coverage"
    )


def test_autoscale_coverage_info_when_pinned(monkeypatch):
    from pathway_tpu.analysis.doctor import run_plane_doctor

    monkeypatch.setenv("PATHWAY_SLO_SHED_RATE", "0.01")
    arm_controller(
        CallbackActuator(lambda m: None),
        ranks=2,
        config=_cfg(min_ranks=2, max_ranks=2),
        registry=MetricsRegistry(),
    )
    hits = run_plane_doctor(rules=["autoscale-coverage"]).by_rule(
        "autoscale-coverage"
    )
    assert len(hits) == 1 and hits[0].severity.name == "INFO"
    assert "pinned" in hits[0].message


def test_arm_and_reset_global_controller():
    assert get_controller() is None
    c = arm_controller(
        CallbackActuator(lambda m: None),
        ranks=1,
        config=_cfg(),
        registry=MetricsRegistry(),
    )
    assert get_controller() is c
    st = c.status()
    assert st["armed"] and st["ranks"] == 1 and st["actuator"] == "callback"
    reset_controller()
    assert get_controller() is None


# --- tier-1 e2e: surge → 1→2 → drain → 2→1 over a real store ---------------


def test_autoscale_e2e_resizes_real_store_and_preserves_state(
    tmp_path, monkeypatch
):
    """The whole loop against a real persisted run: a surge scales the
    store 1→2 through ``reshard_stores`` (journaled decision + applied),
    the drain scales it 2→1, and the final single-rank store holds
    exactly the original consolidated state."""
    from test_elastic import _arranged_rows, _run_persisted_wordcount

    from pathway_tpu.elastic.mesh import reshard_stores

    words = [f"w{i % 13}" for i in range(60)]
    _run_persisted_wordcount(tmp_path, words)
    src = str(tmp_path / "pstorage")
    before = _arranged_rows(src)
    assert before

    roots = {1: [src]}

    def resize(m: int) -> None:
        cur = max(roots)
        new = [str(tmp_path / f"r{m}_{i}") for i in range(m)]
        reshard_stores(roots[cur], new, via_wire=False)
        roots[m] = new

    sampler = _Sampler()
    reg = MetricsRegistry()
    ctrl = AutoscaleController(
        CallbackActuator(resize, label="reshard_stores"),
        ranks=1,
        config=_cfg(
            max_ranks=2,
            up_window_s=2.0,
            down_window_s=4.0,
            cooldown_s=1.0,
            low_water=0.5,
        ),
        sampler=sampler,
        registry=reg,
    )
    t0 = time.monotonic()
    # surge: burn 3.0 sustained past the up window
    sampler.burn = 3.0
    t = t0
    for _ in range(6):
        ctrl.step(t)
        t += 1.0
    assert ctrl.ranks == 2 and 2 in roots
    # drain: burn 0.1 sustained past the (longer) down window
    sampler.burn = 0.1
    for _ in range(10):
        ctrl.step(t)
        t += 1.0
    assert ctrl.ranks == 1
    assert ctrl.resizes == 2

    # both transitions journaled, decision before applied, no rollback
    ev = journal().events(
        kinds=["autoscale-decision", "autoscale-applied", "autoscale-rollback"]
    )
    kinds = [e["kind"] for e in ev]
    assert kinds == [
        "autoscale-decision",
        "autoscale-applied",
        "autoscale-decision",
        "autoscale-applied",
    ]
    assert [e["data"]["action"] for e in ev] == ["up", "up", "down", "down"]
    assert ev[1]["data"]["seconds"] > 0
    # the reshard itself journaled its commits with transfer accounting
    commits = journal().events(kinds=["reshard-commit"])
    assert len(commits) == 2
    assert all(c["data"]["transfer_seconds"] > 0 for c in commits)

    # the scaled-down store restores the exact original state
    after = _arranged_rows(roots[1][0])
    assert after == before
    assert reg.get("pathway_autoscale_rollbacks_total").labels().value == 0
