"""Ported temporal-join tests (reference:
python/pathway/tests/temporal/{test_interval_joins,test_asof_joins}.py)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import assert_table_equality_wo_index


def _interval_tables():
    t1 = T(
        """
      | a | t
    0 | 1 | -1
    1 | 2 | 0
    2 | 3 | 2
    3 | 4 | 3
    4 | 5 | 7
    5 | 6 | 13
    """
    )
    t2 = T(
        """
      | b | t
    0 | 1 | 2
    1 | 2 | 5
    2 | 3 | 6
    3 | 4 | 10
    4 | 5 | 15
    """
    )
    return t1, t2


def test_interval_join_inner_maxdiff_1():
    t1, t2 = _interval_tables()
    res = t1.interval_join_inner(
        t2, t1.t, t2.t, pw.temporal.interval(-1, 1)
    ).select(t1.a, b=t2.b)
    expected = T(
        """
        a | b
        3 | 1
        4 | 1
        5 | 3
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_interval_join_left_maxdiff_1():
    t1, t2 = _interval_tables()
    res = t1.interval_join_left(
        t2, t1.t, t2.t, pw.temporal.interval(-1, 1)
    ).select(t1.a, b=pw.require(t2.b, t2.id))
    expected = T(
        """
        a | b
        3 | 1
        4 | 1
        5 | 3
        1 |
        2 |
        6 |
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_interval_join_inner_maxdiff_2():
    t1, t2 = _interval_tables()
    res = t1.interval_join_inner(
        t2, t1.t, t2.t, pw.temporal.interval(-2, 2)
    ).select(t1.a, b=t2.b)
    expected = T(
        """
        a | b
        2 | 1
        3 | 1
        4 | 1
        4 | 2
        5 | 2
        5 | 3
        6 | 5
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_interval_join_non_symmetric():
    t1, t2 = _interval_tables()
    res = t1.interval_join_inner(
        t2, t1.t, t2.t, pw.temporal.interval(0, 3)
    ).select(t1.a, b=t2.b)
    # pairs where 0 <= t2.t - t1.t <= 3
    expected = T(
        """
        a | b
        1 | 1
        2 | 1
        3 | 1
        3 | 2
        4 | 2
        4 | 3
        5 | 4
        6 | 5
        """
    )
    assert_table_equality_wo_index(res, expected)


def test_asof_left():
    t1 = T(
        """
            | K | val |  t
        1   | 0 | 1   |  1
        2   | 0 | 2   |  4
        3   | 0 | 3   |  5
        4   | 0 | 4   |  6
        5   | 0 | 5   |  7
        6   | 0 | 6   |  11
        7   | 0 | 7   |  12
        8   | 1 | 8   |  5
        9   | 1 | 9   |  7
    """
    )
    t2 = T(
        """
            | K | val | t
        21   | 1 | 7  | 2
        22   | 1 | 3  | 8
        23   | 0 | 0  | 2
        24   | 0 | 6  | 3
        25   | 0 | 2  | 7
        26   | 0 | 3  | 8
        27   | 0 | 9  | 9
        28   | 0 | 7  | 13
        29   | 0 | 4  | 14
        """
    )
    res = t1.asof_join(
        t2,
        t1.t,
        t2.t,
        t1.K == t2.K,
        how=pw.JoinMode.LEFT,
        defaults={t2.val: -1},
    ).select(
        t=t1.t,
        val_right=t2.val,
        combo=t1.val * 2 + t2.val,
    )
    # backward asof: latest t2 row with t2.t <= t1.t per key
    expected = T(
        """
 t  | val_right | combo
  1 | -1        | 1
  4 | 6         | 10
  5 | 6         | 12
  6 | 6         | 14
  7 | 2         | 12
 11 | 9         | 21
 12 | 9         | 23
  5 | 7         | 23
  7 | 7         | 25
          """
    )
    assert_table_equality_wo_index(res, expected)


def test_window_join_inner():
    t1 = T(
        """
        a | t
        1 | 1
        2 | 5
        3 | 12
        """
    )
    t2 = T(
        """
        b | t
        7 | 2
        8 | 6
        9 | 15
        """
    )
    res = t1.window_join_inner(
        t2, t1.t, t2.t, pw.temporal.tumbling(duration=5)
    ).select(t1.a, b=t2.b)
    # windows [0,5): (1,7); [5,10): (2,8); [10,15): none; [15,20): none
    expected = T(
        """
        a | b
        1 | 7
        2 | 8
        """
    )
    assert_table_equality_wo_index(res, expected)
