"""answer_with_geometric_rag_strategy_from_index (VERDICT r3 item 9;
reference: xpacks/llm/question_answering.py:162-215) — fake-LLM test of
the doc-count doubling loop."""

import pathway_tpu as pw
from pathway_tpu.debug import table_to_dicts
from pathway_tpu.xpacks.llm.question_answering import (
    answer_with_geometric_rag_strategy_from_index,
)


class _FakeChat:
    """Answers only once enough documents are in the prompt; records the
    document counts of every call so the geometric growth is checkable."""

    def __init__(self, needed_doc: str):
        self.needed_doc = needed_doc
        self.calls: list[str] = []

    def func(self, prompt: str) -> str:
        self.calls.append(prompt)
        if self.needed_doc in prompt:
            return "the answer is 42"
        return "No information found."


def _doc_index():
    class D(pw.Schema):
        doc: str

    docs = pw.debug.table_from_rows(
        D, [(f"document number {i} about topic {i}",) for i in range(8)]
    )

    @pw.udf
    def fake_embed(text: str):
        import numpy as np

        # deterministic embedding: doc i points along axis i; other
        # text hashes to an axis
        v = np.zeros(8, dtype=np.float32)
        words = text.split()
        if len(words) > 2 and words[2].isdigit():
            v[int(words[2]) % 8] = 1.0
        else:
            v[hash(text) % 8] = 1.0
        return v

    from pathway_tpu.stdlib.indexing.vector_document_index import (
        default_brute_force_knn_document_index,
    )

    return docs, default_brute_force_knn_document_index(
        docs.doc, docs, embedder=fake_embed, dimensions=8
    )


def test_geometric_rag_from_index_doubles_docs():
    docs, index = _doc_index()

    class Q(pw.Schema):
        question: str

    queries = pw.debug.table_from_rows(Q, [("about topic 3",)])
    # the fake embedder maps this question to... whatever; the needed doc
    # is ranked somewhere in the top-4, so 1-doc and 2-doc prompts fail
    # and the loop must double up to 4
    chat = _FakeChat("document number 2")
    answers = answer_with_geometric_rag_strategy_from_index(
        queries.question,
        index,
        "doc",
        chat,
        n_starting_documents=1,
        factor=2,
        max_iterations=4,
    )
    _keys, cols = table_to_dicts(answers.table.select(a=answers))
    vals = list(cols["a"].values())
    assert vals == ["the answer is 42"], (vals, chat.calls)
    # doubling loop: successive calls carry geometrically more documents
    counts = [c.count("document number") for c in chat.calls]
    assert counts[0] == 1
    assert all(b >= a for a, b in zip(counts, counts[1:])), counts
    assert len(counts) >= 2, counts


def test_geometric_rag_from_index_no_answer_is_none():
    docs, index = _doc_index()

    class Q(pw.Schema):
        question: str

    queries = pw.debug.table_from_rows(Q, [("anything",)])
    chat = _FakeChat("THIS DOC DOES NOT EXIST")
    answers = answer_with_geometric_rag_strategy_from_index(
        queries.question,
        index,
        "doc",
        chat,
        n_starting_documents=1,
        factor=2,
        max_iterations=3,
    )
    _keys, cols = table_to_dicts(answers.table.select(a=answers))
    assert list(cols["a"].values()) == [None]
    counts = [c.count("document number") for c in chat.calls]
    assert len(counts) == 3, counts  # all max_iterations exhausted
    assert counts == sorted(counts), counts
