"""Ported expression-namespace tests (reference:
python/pathway/tests/expressions/{test_string,test_numerical,
test_datetimes}.py) — the .str/.num/.dt method surface."""

import datetime

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T


def _col(expr_builder, rows, colname="a"):
    import pathway_tpu as _pw

    class S(_pw.Schema):
        a: _pw.internals.dtype.ANY  # type: ignore[valid-type]

    t = _pw.debug.table_from_rows(S, [(r,) for r in rows])
    res = t.select(out=expr_builder(t))
    _k, cols = _pw.debug.table_to_dicts(res)
    return list(cols["out"].values())


def test_str_strip():
    vals = _col(
        lambda t: t.a.str.strip(),
        ["   abc", "   def   ", "ab   cd  ", "xy  zt", "zy  "],
    )
    assert sorted(vals) == sorted(["abc", "def", "ab   cd", "xy  zt", "zy"])


def test_str_count_find():
    vals = _col(lambda t: t.a.str.count("o"), ["Zoo", "World", "Hello"])
    assert sorted(vals) == [1, 1, 2]
    vals = _col(lambda t: t.a.str.find("l"), ["Hello", "World", "abc"])
    assert sorted(vals) == [-1, 2, 3]


def test_str_parse_int():
    vals = _col(
        lambda t: t.a.str.parse_int(),
        ["10", "0", "-1", "-2", "4294967297", "35184372088833"],
    )
    assert sorted(vals) == sorted([10, 0, -1, -2, 2**32 + 1, 2**45 + 1])
    assert all(isinstance(v, int) for v in vals)


def test_str_parse_float():
    vals = _col(
        lambda t: t.a.str.parse_float(), ["10.345", "-1.99", "4294967297"]
    )
    assert sorted(vals) == sorted([10.345, -1.99, float(2**32 + 1)])


def test_str_parse_bool():
    vals = _col(
        lambda t: t.a.str.parse_bool(),
        ["On", "true", "1", "Yes", "off", "False", "0", "no"],
    )
    assert vals.count(True) == 4 and vals.count(False) == 4


def test_str_upper_lower_swap_title():
    assert _col(lambda t: t.a.str.upper(), ["aBc"]) == ["ABC"]
    assert _col(lambda t: t.a.str.lower(), ["aBc"]) == ["abc"]
    assert _col(lambda t: t.a.str.swapcase(), ["aBc"]) == ["AbC"]
    assert _col(lambda t: t.a.str.title(), ["hello world"]) == [
        "Hello World"
    ]


def test_str_slice_replace_split():
    assert _col(lambda t: t.a.str.slice(1, 3), ["abcde"]) == ["bc"]
    assert _col(lambda t: t.a.str.replace("a", "z"), ["banana"]) == [
        "bznznz"
    ]
    out = _col(lambda t: t.a.str.split(","), ["x,y,z"])
    assert list(out[0]) == ["x", "y", "z"]


def test_str_starts_ends_len():
    assert _col(lambda t: t.a.str.startswith("ab"), ["abc", "xbc"]) == [
        True,
        False,
    ]
    assert _col(lambda t: t.a.str.endswith("bc"), ["abc", "abx"]) == [
        True,
        False,
    ]
    assert _col(lambda t: t.a.str.len(), ["abc", ""]) == [3, 0]


def test_num_round_abs():
    assert _col(lambda t: t.a.num.round(1), [1.26, -2.34]) == [1.3, -2.3]
    assert _col(lambda t: t.a.num.abs(), [-5, 3]) == [5, 3]


def test_num_fill_na():
    vals = _col(lambda t: t.a.num.fill_na(0.0), [1.5, None, 2.5])
    assert sorted(v for v in vals) == [0.0, 1.5, 2.5]


def test_dt_accessors():
    d = datetime.datetime(2023, 5, 15, 10, 13, 23)
    assert _col(lambda t: t.a.dt.year(), [d]) == [2023]
    assert _col(lambda t: t.a.dt.month(), [d]) == [5]
    assert _col(lambda t: t.a.dt.day(), [d]) == [15]
    assert _col(lambda t: t.a.dt.hour(), [d]) == [10]
    assert _col(lambda t: t.a.dt.minute(), [d]) == [13]
    assert _col(lambda t: t.a.dt.second(), [d]) == [23]


def test_dt_strptime_strftime():
    vals = _col(
        lambda t: t.a.dt.strptime("%Y-%m-%d %H:%M:%S"),
        ["2023-03-25 12:00:00"],
    )
    assert vals == [datetime.datetime(2023, 3, 25, 12, 0, 0)]
    d = datetime.datetime(2023, 3, 25, 12, 0, 0)
    assert _col(lambda t: t.a.dt.strftime("%Y/%m/%d"), [d]) == [
        "2023/03/25"
    ]


def test_dt_timedelta_arithmetic():
    d1 = datetime.datetime(2023, 1, 2)
    d2 = datetime.datetime(2023, 1, 1)

    class S(pw.Schema):
        a: pw.internals.dtype.ANY  # type: ignore[valid-type]
        b: pw.internals.dtype.ANY  # type: ignore[valid-type]

    t = pw.debug.table_from_rows(S, [(d1, d2)])
    res = t.select(diff=t.a - t.b)
    _k, cols = pw.debug.table_to_dicts(res)
    assert list(cols["diff"].values()) == [datetime.timedelta(days=1)]
