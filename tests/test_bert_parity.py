"""Pretrained-checkpoint path: prove the safetensors→flax loader and the
WordPiece tokenizer are exact against the torch/HF reference implementations
(fully offline — the checkpoint is generated locally with random weights,
which exercises every weight tensor and the full computation graph; with a
real MiniLM checkpoint on disk the same code path loads it).
Reference: python/pathway/xpacks/llm/embedders.py:270
(SentenceTransformerEmbedder loads sentence-transformers checkpoints)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

VOCAB = (
    ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    + ["the", "quick", "brown", "fox", "jump", "##s", "##ed", "over", "lazy",
       "dog", "un", "##friend", "##ly", "hello", "world", ",", ".", "!",
       "2023", "##0", "a", "b", "c"]
)


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    """A tiny BertModel with random weights, saved HF-style."""
    d = tmp_path_factory.mktemp("bert_ckpt")
    cfg = transformers.BertConfig(
        vocab_size=len(VOCAB),
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=64,
        max_position_embeddings=64,
    )
    torch.manual_seed(0)
    model = transformers.BertModel(cfg)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    (d / "vocab.txt").write_text("\n".join(VOCAB) + "\n")
    return d, model


def test_flax_bert_matches_torch_forward(checkpoint):
    d, tmodel = checkpoint
    from pathway_tpu.xpacks.llm._bert import load_bert_checkpoint

    fmodel, params = load_bert_checkpoint(str(d))

    rng = np.random.default_rng(0)
    ids = rng.integers(5, len(VOCAB), size=(3, 10)).astype(np.int32)
    mask = np.ones((3, 10), dtype=np.float32)
    mask[1, 7:] = 0.0  # ragged row exercises the attention-mask bias
    mask[2, 4:] = 0.0

    with torch.no_grad():
        out = tmodel(
            input_ids=torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask.astype(np.int64)),
        ).last_hidden_state.numpy()
    # sentence-transformers pooling on the torch side
    pooled = (out * mask[:, :, None]).sum(1) / mask.sum(1, keepdims=True)
    expected = pooled / np.linalg.norm(pooled, axis=-1, keepdims=True)

    got = np.asarray(fmodel.apply(params, ids, mask))
    assert np.allclose(got, expected, atol=2e-5), (
        np.abs(got - expected).max()
    )


def test_encoder_runtime_uses_pretrained(checkpoint):
    d, tmodel = checkpoint
    from pathway_tpu.xpacks.llm._encoder import EncoderRuntime

    rt = EncoderRuntime(model_path=str(d))
    assert rt.pretrained
    assert rt.dim == 32
    ids = np.array([[2, 5, 6, 3]], dtype=np.int32)
    mask = np.ones((1, 4), dtype=np.float32)
    out = rt.forward_ids(ids, mask)
    assert out.shape == (1, 32)
    assert np.isfinite(out).all()
    # pooled embedding is L2-normalized
    assert abs(np.linalg.norm(out[0]) - 1.0) < 1e-5


def test_wordpiece_matches_bert_tokenizer(checkpoint):
    d, _ = checkpoint
    from pathway_tpu.xpacks.llm._tokenizer import WordPieceTokenizer

    ref = transformers.BertTokenizer(str(d / "vocab.txt"))
    wp = WordPieceTokenizer(str(d / "vocab.txt"))
    cases = [
        "the quick brown fox jumps over the lazy dog",
        "Hello, World!",
        "unfriendly foxes jumped.",
        "THE QUICK   fox",
        "20230 dogs",
        "café résumé",  # accents strip to cafe/resume -> [UNK]s
        "",
        "hello\nworld",  # \t\n\r are whitespace, not stripped controls
        "the\tquick\r\nfox",
        "hello\x00world\x7f!",  # real controls ARE stripped
        "hello world",  # unicode thin space (Zs)
        "hello [SEP] world [MASK]",  # literal special tokens pass through
        "hello\u4e16\u754cworld",  # CJK chars isolate into own tokens
    ]
    for text in cases:
        expected = ref(text)["input_ids"]
        got = wp.encode(text, max_len=64)
        assert got == expected, (text, got, expected)


def test_sentence_transformer_embedder_loads_checkpoint(checkpoint):
    d, _ = checkpoint
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    emb = SentenceTransformerEmbedder(model=str(d))
    assert emb.runtime.pretrained
    from pathway_tpu.xpacks.llm._tokenizer import HashingTokenizer

    # a real vocab-backed tokenizer must be selected (HF adapter when
    # transformers can load it, else our WordPiece) — never hashing
    assert not isinstance(emb.tokenizer, HashingTokenizer)
    v = emb._embed_batch(["hello world", "the quick brown fox"])
    assert len(v) == 2 and v[0].shape == (32,)
    # deterministic: same text -> same embedding
    v2 = emb._embed_batch(["hello world"])
    assert np.allclose(v[0], v2[0], atol=1e-6)


def test_semantic_ranking_with_real_checkpoint():
    """With an actual trained MiniLM on disk, embeddings must rank a
    paraphrase above an unrelated sentence (skips when no checkpoint is
    cached — the loader's correctness is covered by the parity tests)."""
    from pathway_tpu.xpacks.llm._bert import _find_model_dir

    name = "sentence-transformers/all-MiniLM-L6-v2"
    if _find_model_dir(name) is None:
        pytest.skip("no local MiniLM checkpoint available")
    from pathway_tpu.xpacks.llm.embedders import SentenceTransformerEmbedder

    emb = SentenceTransformerEmbedder(model=name)
    v = emb._embed_batch(
        [
            "a cat sat on the mat",
            "a kitten is resting on a rug",
            "quarterly financial results beat expectations",
        ]
    )
    close = float(np.dot(v[0], v[1]))
    far = float(np.dot(v[0], v[2]))
    assert close > far + 0.1, (close, far)
