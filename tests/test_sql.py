"""pw.sql tests (reference: python/pathway/tests/test_sql.py, 1,822 LoC —
representative coverage of the supported subset)."""

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T, table_to_dicts


def _vals(res, col):
    _k, cols = table_to_dicts(res)
    return sorted(cols[col].values())


def test_select_arithmetic_and_alias():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        """
    )
    res = pw.sql("SELECT a + b AS s, b - a AS d FROM tab", tab=t)
    assert _vals(res, "s") == [3, 7]
    assert _vals(res, "d") == [1, 1]


def test_select_star_and_where():
    t = T(
        """
        a | b
        1 | 2
        3 | 4
        5 | 0
        """
    )
    res = pw.sql("SELECT * FROM tab WHERE a > 1 AND b <> 0", tab=t)
    assert _vals(res, "a") == [3]


def test_where_or_not_in_between():
    t = T(
        """
        v
        1
        2
        3
        4
        5
        """
    )
    assert _vals(pw.sql("SELECT v FROM t WHERE v IN (1, 3)", t=t), "v") == [1, 3]
    assert _vals(
        pw.sql("SELECT v FROM t WHERE v NOT IN (1, 3)", t=t), "v"
    ) == [2, 4, 5]
    assert _vals(
        pw.sql("SELECT v FROM t WHERE v BETWEEN 2 AND 4", t=t), "v"
    ) == [2, 3, 4]
    assert _vals(
        pw.sql("SELECT v FROM t WHERE NOT (v = 1 OR v = 5)", t=t), "v"
    ) == [2, 3, 4]


def test_group_by_having():
    t = T(
        """
        g | v
        a | 1
        a | 2
        b | 3
        b | 4
        c | 10
        """
    )
    res = pw.sql(
        "SELECT g, SUM(v) AS total, COUNT(*) AS n FROM t GROUP BY g",
        t=t,
    )
    _k, cols = table_to_dicts(res)
    got = {cols["g"][k]: (cols["total"][k], cols["n"][k]) for k in cols["g"]}
    assert got == {"a": (3, 2), "b": (7, 2), "c": (10, 1)}

    res2 = pw.sql(
        "SELECT g, SUM(v) AS total FROM t GROUP BY g HAVING SUM(v) > 5",
        t=t,
    )
    assert _vals(res2, "total") == [7, 10]


def test_join_on_with_aliases():
    people = T(
        """
        name  | city_id
        alice | 1
        bob   | 2
        """
    )
    cities = T(
        """
        cid | city
        1   | paris
        2   | tokyo
        """
    )
    res = pw.sql(
        "SELECT p.name, c.city FROM people p JOIN cities c ON p.city_id = c.cid",
        people=people,
        cities=cities,
    )
    _k, cols = table_to_dicts(res)
    got = {cols["name"][k]: cols["city"][k] for k in cols["name"]}
    assert got == {"alice": "paris", "bob": "tokyo"}


def test_left_join_null_and_is_null():
    orders = T(
        """
        oid | cust
        1   | a
        2   | zz
        """
    )
    custs = T(
        """
        cust | tier
        a    | gold
        """
    )
    res = pw.sql(
        "SELECT o.oid, c.tier FROM orders o LEFT JOIN custs c ON o.cust = c.cust",
        orders=orders,
        custs=custs,
    )
    _k, cols = table_to_dicts(res)
    got = {cols["oid"][k]: cols["tier"][k] for k in cols["oid"]}
    assert got == {1: "gold", 2: None}
    res2 = pw.sql(
        "SELECT o.oid FROM orders o LEFT JOIN custs c ON o.cust = c.cust "
        "WHERE c.tier IS NULL",
        orders=orders,
        custs=custs,
    )
    assert _vals(res2, "oid") == [2]


def test_composite_key_join():
    a = T(
        """
        k | j | x
        1 | 1 | p
        1 | 2 | q
        """
    )
    b = T(
        """
        k | j | y
        1 | 1 | P
        1 | 2 | Q
        """
    )
    res = pw.sql(
        "SELECT a.x, b.y FROM a JOIN b ON a.k = b.k AND a.j = b.j",
        a=a,
        b=b,
    )
    _k, cols = table_to_dicts(res)
    got = {cols["x"][k]: cols["y"][k] for k in cols["x"]}
    assert got == {"p": "P", "q": "Q"}


def test_three_table_join_with_colliding_column():
    a = T(
        """
        k | v
        1 | 10
        """
    )
    b = T(
        """
        k | v
        1 | 77
        2 | 88
        """
    )
    c = T(
        """
        v  | z
        77 | hit
        10 | wrong
        """
    )
    # b.v in the second ON must bind to b's v (renamed after the first
    # join), not a's v
    res = pw.sql(
        "SELECT a.k, c.z FROM a JOIN b ON a.k = b.k JOIN c ON b.v = c.v",
        a=a,
        b=b,
        c=c,
    )
    assert _vals(res, "z") == ["hit"]


def test_union_and_union_all():
    t1 = T(
        """
        v
        1
        2
        """
    )
    t2 = T(
        """
        v
        2
        3
        """
    )
    assert _vals(pw.sql("SELECT v FROM a UNION SELECT v FROM b", a=t1, b=t2), "v") == [1, 2, 3]
    assert _vals(
        pw.sql("SELECT v FROM a UNION ALL SELECT v FROM b", a=t1, b=t2), "v"
    ) == [1, 2, 2, 3]


def test_intersect_and_except():
    t1 = T(
        """
        v
        1
        2
        3
        """
    )
    t2 = T(
        """
        v
        2
        3
        4
        """
    )
    assert _vals(
        pw.sql("SELECT v FROM a INTERSECT SELECT v FROM b", a=t1, b=t2), "v"
    ) == [2, 3]
    assert _vals(
        pw.sql("SELECT v FROM a EXCEPT SELECT v FROM b", a=t1, b=t2), "v"
    ) == [1]


def test_distinct():
    t = T(
        """
        v
        1
        1
        2
        """
    )
    assert _vals(pw.sql("SELECT DISTINCT v FROM t", t=t), "v") == [1, 2]


def test_case_when():
    t = T(
        """
        v
        1
        5
        10
        """
    )
    res = pw.sql(
        "SELECT v, CASE WHEN v < 3 THEN 'low' WHEN v < 8 THEN 'mid' "
        "ELSE 'high' END AS bucket FROM t",
        t=t,
    )
    _k, cols = table_to_dicts(res)
    got = {cols["v"][k]: cols["bucket"][k] for k in cols["v"]}
    assert got == {1: "low", 5: "mid", 10: "high"}


def test_string_literal_and_quotes():
    t = T(
        """
        name
        ana
        bo
        """
    )
    res = pw.sql("SELECT name FROM t WHERE name = 'ana'", t=t)
    assert _vals(res, "name") == ["ana"]


def test_errors():
    t = T(
        """
        v
        1
        """
    )
    with pytest.raises(ValueError):
        pw.sql("SELECT nope FROM t", t=t)
    with pytest.raises(ValueError):
        pw.sql("SELECT v FROM missing", t=t)
    with pytest.raises(ValueError):
        pw.sql("SELECT v FROM t HAVING v > 1", t=t)
