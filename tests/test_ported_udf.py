"""Ported reference UDF suite (reference: python/pathway/tests/test_udf.py):
decorator/class forms, async executors, propagate_none, timeouts,
in-memory caching."""

import asyncio
import threading
from unittest import mock

import pytest

import pathway_tpu as pw
from pathway_tpu.debug import T
from ref_utils import assert_table_equality, run_all


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    yield
    pw.internals.parse_graph.G.clear()


class _AsyncBarrier:
    """Single-use stand-in for asyncio.Barrier (3.11+) so the suite runs
    on the box's 3.10: all parties block in wait() until the last one
    arrives.  Event-based, so it needs no running-loop handshake."""

    def __init__(self, parties: int):
        self._parties = parties
        self._arrived = 0
        self._release = asyncio.Event()

    async def wait(self) -> int:
        self._arrived += 1
        n = self._arrived
        if n >= self._parties:
            self._release.set()
        await self._release.wait()
        return n


def _async_barrier(parties: int):
    barrier_cls = getattr(asyncio, "Barrier", None)
    return barrier_cls(parties) if barrier_cls else _AsyncBarrier(parties)


def test_udf():
    @pw.udf
    def inc(a: int) -> int:
        return a + 1

    input = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        """
    )
    result = input.select(ret=inc(pw.this.a))
    assert_table_equality(
        result,
        T(
            """
            ret
            2
            3
            4
            """,
        ),
    )


def test_udf_class():
    class Inc(pw.UDF):
        def __init__(self, inc) -> None:
            super().__init__()
            self.inc = inc

        def __wrapped__(self, a: int) -> int:
            return a + self.inc

    input = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        """
    )
    inc = Inc(2)
    result = input.select(ret=inc(pw.this.a))
    assert_table_equality(
        result,
        T(
            """
            ret
            3
            4
            5
            """,
        ),
    )


def test_udf_async():
    barrier = _async_barrier(3)

    @pw.udf
    async def inc(a: int) -> int:
        await barrier.wait()
        return a + 3

    input = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        """
    )
    result = input.select(ret=inc(pw.this.a))
    assert_table_equality(
        result,
        T(
            """
            ret
            4
            5
            6
            """,
        ),
    )


def test_udf_sync_with_async_executor():
    barrier = threading.Barrier(3, timeout=10)

    @pw.udf(executor=pw.udfs.async_executor())
    def inc(a: int) -> int:
        barrier.wait()
        return a + 3

    input = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        """
    )
    result = input.select(ret=inc(pw.this.a))
    assert_table_equality(
        result,
        T(
            """
            ret
            4
            5
            6
            """,
        ),
    )


def test_udf_async_class():
    class Inc(pw.UDF):
        def __init__(self, inc, **kwargs) -> None:
            super().__init__(**kwargs)
            self.inc = inc

        async def __wrapped__(self, a: int) -> int:
            await asyncio.sleep(0.1)
            return a + self.inc

    input = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        """
    )
    inc = Inc(40)
    result = input.select(ret=inc(pw.this.a))
    assert_table_equality(
        result,
        T(
            """
            ret
            41
            42
            43
            """,
        ),
    )


def test_udf_propagate_none():
    internal_add = mock.Mock()

    @pw.udf(propagate_none=True)
    def add(a: int, b: int) -> int:
        assert a is not None
        assert b is not None
        internal_add()
        return a + b

    input = T(
        """
        a | b
        1 | 6
        2 |
          | 8
        """
    )
    result = input.select(ret=add(pw.this.a, pw.this.b))
    assert_table_equality(
        result,
        T(
            """
            ret
            7
            None
            None
            """,
        ),
    )
    internal_add.assert_called_once()


def test_udf_too_fast_for_timeout():
    @pw.udf(executor=pw.udfs.async_executor(timeout=10.0))
    async def inc(a: int) -> int:
        return a + 1

    input = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        """
    )
    result = input.select(ret=inc(pw.this.a))
    assert_table_equality(
        result,
        T(
            """
            ret
            2
            3
            4
            """,
        ),
    )


@pytest.mark.parametrize("sync", [True, False])
def test_udf_in_memory_cache(sync: bool) -> None:
    internal_inc = mock.Mock()

    if sync:

        @pw.udf(cache_strategy=pw.udfs.InMemoryCache())
        def inc(a: int) -> int:
            internal_inc(a)
            return a + 1

    else:

        @pw.udf(cache_strategy=pw.udfs.InMemoryCache())
        async def inc(a: int) -> int:
            await asyncio.sleep(a / 10)
            internal_inc(a)
            return a + 1

    input = pw.debug.table_from_markdown(
        """
        a
        1
        2
        3
        1
        2
        """
    )
    result = input.select(ret=inc(pw.this.a))
    assert_table_equality(
        result,
        T(
            """
            ret
            2
            3
            4
            2
            3
            """,
        ),
    )
    assert internal_inc.call_count == 3
    internal_inc.assert_has_calls(
        [mock.call(1), mock.call(2), mock.call(3)], any_order=True
    )


def test_async_udf_propagate_none():
    internal_add = mock.Mock()

    @pw.udf(propagate_none=True)
    async def add(a: int, b: int) -> int:
        assert a is not None
        assert b is not None
        internal_add()
        return a + b

    input = T(
        """
        a | b
        1 | 6
        2 |
          | 8
        """
    )
    result = input.select(ret=add(pw.this.a, pw.this.b))
    assert_table_equality(
        result,
        T(
            """
            ret
            7
            None
            None
            """,
        ),
    )
    internal_add.assert_called_once()
