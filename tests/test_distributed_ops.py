"""Cross-process exchange coverage for EVERY stateful operator type
(VERDICT r4 item 2): a 2-process group and a 1-process run execute the
same pipelines over the same (sharded) inputs; the union of per-process
outputs must equal the single-process result exactly — keys included,
since row keys are value hashes and identical on every process
(reference: the universal Exchange pact moves every operator's rows
between timely workers, external/timely-dataflow/timely/src/dataflow/
channels/pact.rs:56-59; src/engine/dataflow/operators.rs:415 Reshard)."""

from __future__ import annotations

import json
import os
import random
import socket
import subprocess
import sys
import textwrap

import pytest

# every op section prints one "RESULT <tag> <json>" line; rows shard
# round-robin by PATHWAY_PROCESS_ID so each process feeds a disjoint slice
_OPS_WORKER = textwrap.dedent(
    """
    import json, os
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    N = int(os.environ.get("PATHWAY_PROCESSES", "1"))
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))
    G = pw.internals.parse_graph.G

    def mine(rows):
        # keep the GLOBAL row index as an explicit primary key: row keys
        # are then value hashes identical across any process count
        return [(i, *r) for i, r in enumerate(rows) if i % N == PID]

    def emit(tag, table):
        keys, cols = pw.debug.table_to_dicts(table)
        names = sorted(cols)
        out = {str(k): [str(cols[c].get(k)) for c in names] for k in keys}
        print("RESULT " + tag + " " + json.dumps(out, sort_keys=True),
              flush=True)

    # --- deduplicate (route by instance hash) ---------------------------
    G.clear()
    class SD(pw.Schema):
        idx: int = pw.column_definition(primary_key=True)
        v: int
        inst: int
    t = pw.debug.table_from_rows(SD, mine([(i, i % 3) for i in range(20)]))
    emit("dedup", t.deduplicate(
        value=t.v, instance=t.inst, acceptor=lambda new, old: new > old))

    # --- sort: per-instance chains + instance-less global order ---------
    G.clear()
    class SS(pw.Schema):
        idx: int = pw.column_definition(primary_key=True)
        v: int
        inst: int
    t = pw.debug.table_from_rows(
        SS, mine([((i * 7) % 13, i % 2) for i in range(12)]))
    emit("sort_inst", t.sort(key=t.v, instance=t.inst))
    # aligned join-back: prev/next rows must live on the process feeding
    # the input row, or this multi-input row-wise select sees half a row
    G.clear()
    t = pw.debug.table_from_rows(
        SS, mine([((i * 7) % 13, i % 2) for i in range(12)]))
    s = t.sort(key=t.v, instance=t.inst)
    emit("sort_align", t.select(t.v, t.inst, p=s.prev, nx=s.next))
    G.clear()
    t = pw.debug.table_from_rows(
        SS, mine([((i * 5) % 11, 0) for i in range(10)]))
    emit("sort_global", t.sort(key=t.v))

    # --- update_rows (route both sides by row key) ----------------------
    G.clear()
    class SU(pw.Schema):
        k: int = pw.column_definition(primary_key=True)
        val: str
    l = pw.debug.table_from_rows(
        SU, [r[1:] for r in mine([(i, f"L{i}") for i in range(8)])])
    r = pw.debug.table_from_rows(
        SU, [r[1:] for r in mine([(i, f"R{i}") for i in range(4, 11)])])
    emit("update_rows", l.update_rows(r))

    # --- universe set ops ----------------------------------------------
    G.clear()
    a = pw.debug.table_from_rows(
        SU, [r[1:] for r in mine([(i, f"A{i}") for i in range(10)])])
    b = pw.debug.table_from_rows(
        SU, [r[1:] for r in mine([(i, f"B{i}") for i in range(5, 15)])])
    emit("intersect", a.intersect(b))
    G.clear()
    a = pw.debug.table_from_rows(
        SU, [r[1:] for r in mine([(i, f"A{i}") for i in range(10)])])
    b = pw.debug.table_from_rows(
        SU, [r[1:] for r in mine([(i, f"B{i}") for i in range(5, 15)])])
    emit("difference", a.difference(b))

    # --- ix (route lookups to the pointed-at row's owner) ---------------
    G.clear()
    class SA(pw.Schema):
        name: str = pw.column_definition(primary_key=True)
        genus: str
    class SB(pw.Schema):
        bird: str = pw.column_definition(primary_key=True)
        ref: str
    animals = pw.debug.table_from_rows(
        SA, [r[1:] for r in mine([(f"a{i}", f"g{i}") for i in range(8)])])
    birds = pw.debug.table_from_rows(
        SB, [r[1:] for r in mine([(f"b{i}", f"a{(i * 3) % 8}")
                                  for i in range(8)])])
    birds = birds.with_columns(ptr=animals.pointer_from(birds.ref))
    emit("ix", birds.select(latin=animals.ix(birds.ptr).genus))

    # --- gradual_broadcast (threshold table fed on the LAST process
    #     only: replication must carry it everywhere) --------------------
    G.clear()
    class SV(pw.Schema):
        idx: int = pw.column_definition(primary_key=True)
        v: int
    class ST(pw.Schema):
        tid: int = pw.column_definition(primary_key=True)
        lower: int
        value: int
        upper: int
    data = pw.debug.table_from_rows(SV, mine([(i,) for i in range(30)]))
    thr = pw.debug.table_from_rows(
        ST, [(0, 0, 7, 10)] if PID == N - 1 else [])
    thr_prep = thr
    emit("gbcast", data._gradual_broadcast(
        thr, thr.lower, thr.value, thr.upper))

    # --- windowby + behavior: delay=5 buffers rows, cutoff forgets;
    #     the release watermark must be the GROUP max time --------------
    G.clear()
    class SW(pw.Schema):
        idx: int = pw.column_definition(primary_key=True)
        inst: int
        t: int
        v: int
    rows = [(i % 3, (i * 11) % 40, i) for i in range(60)]
    t = pw.debug.table_from_rows(SW, mine(rows))
    emit("window_behavior", t.windowby(
        t.t,
        window=pw.temporal.tumbling(duration=10),
        instance=t.inst,
        behavior=pw.temporal.common_behavior(delay=5),
    ).reduce(
        pw.this._pw_instance,
        start=pw.this._pw_window_start,
        s=pw.reducers.sum(pw.this.v),
    ))

    # --- iterate (fixpoint centralizes on process 0) --------------------
    G.clear()
    class SI(pw.Schema):
        idx: int = pw.column_definition(primary_key=True)
        v: int
    t = pw.debug.table_from_rows(
        SI, mine([(5,), (7,), (12,), (20,)]))
    res = pw.iterate(
        lambda tab: tab.select(
            tab.idx, v=pw.if_else(tab.v > 10, tab.v - 3, tab.v)),
        tab=t,
    )
    emit("iterate", res)

    print("WORKER-DONE", flush=True)
    """
)

# aligned consumption of a key-preserving iterate result: run in its own
# worker because iterate output universes only align with their input via
# with_universe_of
_ITER_ALIGN_WORKER = textwrap.dedent(
    """
    import json, os
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    N = int(os.environ.get("PATHWAY_PROCESSES", "1"))
    PID = int(os.environ.get("PATHWAY_PROCESS_ID", "0"))

    class SI(pw.Schema):
        idx: int = pw.column_definition(primary_key=True)
        v: int

    rows = [(i, 5 + 4 * i) for i in range(6)]
    t = pw.debug.table_from_rows(
        SI, [r for i, r in enumerate(rows) if i % N == PID])
    res = pw.iterate(
        lambda tab: tab.select(
            tab.idx, v=pw.if_else(tab.v > 10, tab.v - 3, tab.v)),
        tab=t,
    ).with_universe_of(t)
    final = t.select(orig=t.v, done=res.v)
    keys, cols = pw.debug.table_to_dicts(final)
    out = {str(k): [str(cols[c].get(k)) for c in sorted(cols)] for k in keys}
    print("RESULT iter_align " + json.dumps(out, sort_keys=True), flush=True)
    print("WORKER-DONE", flush=True)
    """
)


def _free_base_port(n: int) -> int:
    for _ in range(50):
        base = random.randint(20000, 40000)
        ok = True
        for off in range(n):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", base + off))
            except OSError:
                ok = False
            finally:
                s.close()
            if not ok:
                break
        if ok:
            return base
    raise RuntimeError("no free port range")


def _run_group(script_path, n, timeout=240):
    port = _free_base_port(n)
    secret = f"ops-test-{port}"
    procs = []
    for pid in range(n):
        env = dict(os.environ)
        env.update(
            PATHWAY_PROCESSES=str(n),
            PATHWAY_PROCESS_ID=str(pid),
            PATHWAY_DCN_PORT=str(port),
            PATHWAY_DCN_SECRET=secret,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=os.path.dirname(os.path.dirname(__file__)),
        )
        env.pop("XLA_FLAGS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script_path)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results: dict[str, dict] = {}
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"n={n} pid={pid} failed:\n{out[-4000:]}"
        assert "WORKER-DONE" in out
        for line in out.splitlines():
            if not line.startswith("RESULT "):
                continue
            _r, tag, payload = line.split(" ", 2)
            part = json.loads(payload)
            merged = results.setdefault(tag, {})
            for k, v in part.items():
                assert k not in merged or merged[k] == v, (
                    f"{tag}: key {k} emitted on two processes with "
                    f"different values: {merged[k]} vs {v}"
                )
                merged[k] = v
    return results


def test_two_process_iterate_aligned_consumer(tmp_path):
    script = tmp_path / "iter_align_worker.py"
    script.write_text(_ITER_ALIGN_WORKER)
    single = _run_group(script, 1)
    double = _run_group(script, 2)
    assert double == single and single["iter_align"]


def test_two_process_stateful_ops_match_single_process(tmp_path):
    script = tmp_path / "ops_worker.py"
    script.write_text(_OPS_WORKER)
    single = _run_group(script, 1)
    double = _run_group(script, 2)
    assert set(single) == set(double)
    for tag in sorted(single):
        assert double[tag] == single[tag], (
            f"{tag}: 2-process union != single-process result\n"
            f"single={json.dumps(single[tag], sort_keys=True)[:2000]}\n"
            f"double={json.dumps(double[tag], sort_keys=True)[:2000]}"
        )


# ---------------------------------------------------------------------------
# kill/restart for a newly-exchanged op: deduplicate keeps its accepted
# value per instance across a crash of the whole group (reference recovery
# model: whole-cluster restart from the persisted frontier,
# src/persistence/state.rs:291)

_DEDUP_KILL_WORKER = textwrap.dedent(
    """
    import os, json, threading, time, pathlib
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pathway_tpu as pw

    pid = int(os.environ["PATHWAY_PROCESS_ID"])
    base = pathlib.Path(os.environ["PW_TEST_DIR"])
    in_dir = base / f"in{pid}"
    pdir = base / f"pstorage{pid}"
    out_file = base / f"out{pid}_{os.environ['PW_PHASE']}.jsonl"
    stop_file = base / "STOP"
    die_after = int(os.environ.get("PW_DIE_AFTER_ROWS", "0"))

    class S(pw.Schema):
        sensor: str
        value: int

    t = pw.io.jsonlines.read(str(in_dir), schema=S, mode="streaming")
    d = t.deduplicate(
        value=t.value, instance=t.sensor,
        acceptor=lambda new, old: new > old, name="dedup_max",
    )
    pw.io.jsonlines.write(d, str(out_file))

    def watch():
        while True:
            time.sleep(0.05)
            try:
                n = sum(1 for _ in open(out_file))
            except OSError:
                n = 0
            if die_after and n >= die_after:
                os._exit(17)
            if stop_file.exists():
                rt = pw.internals.parse_graph.G.runtime
                if rt is not None:
                    rt.stop()
                return

    threading.Thread(target=watch, daemon=True).start()
    cfg = pw.persistence.Config.simple_config(
        pw.persistence.Backend.filesystem(str(pdir)),
    )
    pw.run(persistence_config=cfg, autocommit_duration_ms=20)
    print("CLEAN-EXIT", flush=True)
    """
)


def _run_kill_group(script_path, n, port, extra_env, timeout=120):
    secret = f"dedupkill-{port}"
    procs = []
    for pid in range(n):
        env = dict(os.environ)
        env.update(
            PATHWAY_PROCESSES=str(n),
            PATHWAY_PROCESS_ID=str(pid),
            PATHWAY_DCN_PORT=str(port),
            PATHWAY_DCN_SECRET=secret,
            JAX_PLATFORMS="cpu",
            PYTHONPATH=os.path.dirname(os.path.dirname(__file__)),
        )
        env.pop("XLA_FLAGS", None)
        env.update(extra_env(pid) or {})
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script_path)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    try:
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_process_dedup_kill_restart(tmp_path):
    base = tmp_path / "work"
    for pid in range(2):
        (base / f"in{pid}").mkdir(parents=True)
    script = tmp_path / "worker.py"
    script.write_text(_DEDUP_KILL_WORKER)
    port = _free_base_port(2)

    def write_rows(pid, fname, rows):
        with open(base / f"in{pid}" / fname, "w") as f:
            for sensor, value in rows:
                f.write(json.dumps({"sensor": sensor, "value": value}) + "\n")

    write_rows(0, "f1.jsonl", [("a", 3), ("b", 10), ("a", 7), ("c", 1)])
    write_rows(1, "f1.jsonl", [("b", 2), ("c", 5), ("a", 6), ("d", 4)])

    # phase 1: process 1 dies after 2 emitted rows; the group fail-stops
    procs, outs = _run_kill_group(
        script, 2, port,
        lambda pid: {
            "PW_TEST_DIR": str(base),
            "PW_PHASE": "1",
            **({"PW_DIE_AFTER_ROWS": "2"} if pid == 1 else {}),
        },
    )
    assert procs[1].returncode == 17, outs[1][-2000:]
    assert procs[0].returncode != 0, outs[0][-2000:]

    # phase 2: more input (some values lower — must NOT regress the
    # accepted max), full-group restart from persisted dedup state
    write_rows(0, "f2.jsonl", [("a", 2), ("d", 9)])
    write_rows(1, "f2.jsonl", [("b", 11), ("e", 8)])
    (base / "STOP").parent.mkdir(exist_ok=True)
    import threading
    import time as _time

    def stopper():
        _time.sleep(12)
        (base / "STOP").touch()

    threading.Thread(target=stopper, daemon=True).start()
    procs, outs = _run_kill_group(
        script, 2, _free_base_port(2),
        lambda pid: {"PW_TEST_DIR": str(base), "PW_PHASE": "2"},
    )
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"pid={pid} failed:\n{out[-3000:]}"
        assert "CLEAN-EXIT" in out

    # fold the phase-2 diff streams (dedup state re-emits on restart, so
    # phase 2 alone carries the full final state)
    state: dict[str, int] = {}
    for pid in range(2):
        for line in open(base / f"out{pid}_2.jsonl"):
            o = json.loads(line)
            if o["diff"] > 0:
                state[o["sensor"]] = o["value"]
            elif state.get(o["sensor"]) == o["value"]:
                del state[o["sensor"]]
    assert state == {"a": 7, "b": 11, "c": 5, "d": 9, "e": 8}
