"""Port of the reference asof-join suite (reference:
python/pathway/tests/temporal/test_asof_joins.py - 15 functions).
Mechanical port: package/imports adapted, fixtures and assertions kept
identical so outputs are checked against the reference's expected data."""

from __future__ import annotations

import pytest

import pathway_tpu as pw
from pathway_tpu.internals.dtype import DATE_TIME_NAIVE, DATE_TIME_UTC
from pathway_tpu.internals.joins import JoinMode
from pathway_tpu.debug import table_from_markdown as T
from tests.ref_utils import (
    assert_table_equality_wo_index,
    assert_table_equality_wo_index_types,
)


def test_asof_left():
    t1 = T(
        """
            | K | val |  t
        1   | 0 | 1   |  1
        2   | 0 | 2   |  4
        3   | 0 | 3   |  5
        4   | 0 | 4   |  6
        5   | 0 | 5   |  7
        6   | 0 | 6   |  11
        7   | 0 | 7   |  12
        8   | 1 | 8   |  5
        9   | 1 | 9   |  7
    """
    )

    t2 = T(
        """
            | K | val | t
        21   | 1 | 7  | 2
        22   | 1 | 3  | 8
        23   | 0 | 0  | 2
        24   | 0 | 6  | 3
        25   | 0 | 2  | 7
        26   | 0 | 3  | 8
        27   | 0 | 9  | 9
        28   | 0 | 7  | 13
        29   | 0 | 4  | 14
        """
    )
    res = t1.asof_join(
        t2,
        t1.t * 2,
        t2.t * 2,
        t1.K == t2.K,
        how=pw.JoinMode.LEFT,
        defaults={t2.val: -1},
    ).select(
        pw.this.instance,
        pw.this.t,
        val_right=t2.val,
        val_left_times_2_plus_val_right=t1.val * 2 + t2.val,
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
 instance | t  | val_right | val_left_times_2_plus_val_right
 0        |  2 | -1        | 1
 0        |  8 | 6         | 10
 0        | 10 | 6         | 12
 0        | 12 | 6         | 14
 0        | 14 | 2         | 12
 0        | 22 | 9         | 21
 0        | 24 | 9         | 23
 1        | 10 | 7         | 23
 1        | 14 | 7         | 25
          """
        ),
    )


def test_asof_full():
    t1 = T(
        """
            | K | val |  t
        1   | 0 | 1   |  1
        2   | 0 | 2   |  4
        3   | 0 | 3   |  5
        4   | 0 | 4   |  6
        5   | 0 | 5   |  7
        6   | 0 | 6   |  11
        7   | 0 | 7   |  12
        8   | 1 | 8   |  5
        9   | 1 | 9   |  7
    """
    )

    t2 = T(
        """
             | K | val | t
        21   | 1 | 7  | 2
        22   | 1 | 3  | 8
        23   | 0 | 0  | 2
        24   | 0 | 6  | 3
        25   | 0 | 2  | 7
        26   | 0 | 3  | 8
        27   | 0 | 9  | 9
        28   | 0 | 7  | 13
        29   | 0 | 4  | 14
        """
    )
    res = t1.asof_join(
        t2,
        t1.t,
        t2.t,
        t1.K == t2.K,
        how=pw.JoinMode.OUTER,
        defaults={t1.val: 0, t2.val: 0},
    ).select(
        pw.this.instance,
        pw.this.side,
        pw.this.t,
        val_v1=t1.val,
        val_v2=t2.val,
        sum=t1.val + t2.val,
    )

    assert_table_equality_wo_index(
        res,
        T(
            """
instance | side  | t  | val_v1 | val_v2 | sum
0        | False | 1  | 1      | 0      | 1
0        | False | 4  | 2      | 6      | 8
0        | False | 5  | 3      | 6      | 9
0        | False | 6  | 4      | 6      | 10
0        | False | 7  | 5      | 6      | 11
0        | False | 11 | 6      | 9      | 15
0        | False | 12 | 7      | 9      | 16
0        | True  | 2  | 1      | 0      | 1
0        | True  | 3  | 1      | 6      | 7
0        | True  | 7  | 5      | 2      | 7
0        | True  | 8  | 5      | 3      | 8
0        | True  | 9  | 5      | 9      | 14
0        | True  | 13 | 7      | 7      | 14
0        | True  | 14 | 7      | 4      | 11
1        | False | 5  | 8      | 7      | 15
1        | False | 7  | 9      | 7      | 16
1        | True  | 2  | 0      | 7      | 7
1        | True  | 8  | 9      | 3      | 12
"""
        ),
    )


def test_asof_left_forward():
    t1 = T(
        """
            | K | val |  t
        1   | 0 | 1   |  1
        2   | 0 | 2   |  4
        3   | 0 | 3   |  5
        4   | 0 | 4   |  6
        5   | 0 | 5   |  7
        6   | 0 | 6   |  11
        7   | 0 | 7   |  12
        8   | 1 | 8   |  5
        9   | 1 | 9   |  7
        10  | 1 | 10  |  20
    """
    )

    t2 = T(
        """
             | K | val | t
        21   | 1 | 7  | 2
        22   | 1 | 3  | 8
        23   | 0 | 0  | 2
        24   | 0 | 6  | 3
        25   | 0 | 2  | 7
        26   | 0 | 3  | 8
        27   | 0 | 9  | 9
        28   | 0 | 7  | 13
        29   | 0 | 4  | 14
        """
    )
    res = t1.asof_join(
        t2,
        t1.t * 2,
        t2.t * 2,
        t1.K == t2.K,
        how=pw.JoinMode.LEFT,
        direction=pw.temporal._asof_join.Direction.FORWARD,
        defaults={t2.val: 100},
    ).select(
        pw.this.instance,
        pw.this.t,
        val_right=t2.val,
        val_left_times_2_plus_val_right=t1.val * 2 + t2.val,
    )
    assert_table_equality_wo_index(
        res,
        T(
            """
instance | t  | val_right | val_left_times_2_plus_val_right
0        |  2 | 0         | 2
0        |  8 | 2         | 6
0        | 10 | 2         | 8
0        | 12 | 2         | 10
0        | 14 | 2         | 12
0        | 22 | 7         | 19
0        | 24 | 7         | 21
1        | 10 | 3         | 19
1        | 14 | 3         | 21
1        | 40 | 100       | 120
          """
        ),
    )


def test_asof_left_nearest():
    t1 = T(
        """
            |  t
        1   |  1
        2   |  20
        3   |  40
        4   |  60
        5   |  80
    """
    )

    t2 = T(
        """
            | t
        23  | -15
        24  | 10
        26  | 35
        27  | 45
        28  | 50
        """
    )
    res = t1.asof_join(
        t2,
        t1.t * 2,
        t2.t * 2,
        how=pw.JoinMode.LEFT,
        direction=pw.temporal._asof_join.Direction.NEAREST,
    ).select(
        pw.this.instance,
        pw.this.t,
        t_right=t2.t,
    )
    assert_table_equality_wo_index_types(
        res,
        T(
            """
 instance |   t | t_right
          |   2 | 10
          |  40 | 10
          |  80 | 45
          | 120 | 50
          | 160 | 50
          """
        ),
    )


# @pytest.mark.parametrize("join_mode", [JoinMode.LEFT, JoinMode.RIGHT, JoinMode.OUTER])
def test_multiple_keys():
    t1 = T(
        """
         | k1 | k2 |  t
       1 |  1 |  1 |  3
       2 |  1 |  1 |  5
       3 |  1 |  1 |  7
       4 |  1 |  2 |  2
       5 |  1 |  2 |  6
       6 |  2 |  1 | 10
       7 |  2 |  1 | 11
       8 |  2 |  1 | 13
       9 |  2 |  2 | -4
      10 |  2 |  2 | -1
      11 |  2 |  2 |  0
    """
    )

    t2 = T(
        """
         | k1 | k2 |  t
       1 |  1 |  1 |  4
       2 |  1 |  2 |  1
       3 |  1 |  2 |  3
       4 |  2 |  1 | 12
       5 |  2 |  2 | -3
       6 |  2 |  2 | -2
    """
    )

    expected = T(
        """
         | k1 | k2 | lt | rt
       1 |  1 |  1 |  3 |
       2 |  1 |  1 |  5 |  4
       3 |  1 |  1 |  7 |  4
       4 |  1 |  2 |  2 |  1
       5 |  1 |  2 |  6 |  3
       6 |  2 |  1 | 10 |
       7 |  2 |  1 | 11 |
       8 |  2 |  1 | 13 | 12
       9 |  2 |  2 | -4 |
      10 |  2 |  2 | -1 | -2
      11 |  2 |  2 |  0 | -2
    """
    )

    result = t1.asof_join(
        t2,
        pw.left.t,
        pw.right.t,
        pw.left.k1 == pw.right.k1,
        pw.left.k2 == pw.right.k2,
        how=JoinMode.LEFT,
    ).select(k1=pw.left.k1, k2=pw.left.k2, lt=pw.left.t, rt=pw.right.t)

    assert_table_equality_wo_index(result, expected)


def test_with_timestamps():
    fmt = "%Y-%m-%dT%H:%M:%S"
    t1 = T(
        """
         |  t
       1 |  2023-05-10T13:01:00
       2 |  2023-05-10T13:03:00
       3 |  2023-05-10T13:05:00
       4 |  2023-05-10T13:07:00
    """
    ).with_columns(t=pw.this.t.dt.strptime(fmt))

    t2 = T(
        """
         |  t
       1 |  2023-05-10T13:02:00
       2 |  2023-05-10T13:04:00
    """
    ).with_columns(t=pw.this.t.dt.strptime(fmt))

    expected = T(
        """
         |          lt          |        rt
       1 |  2023-05-10T13:01:00 |
       2 |  2023-05-10T13:03:00 | 2023-05-10T13:02:00
       3 |  2023-05-10T13:05:00 | 2023-05-10T13:04:00
       4 |  2023-05-10T13:07:00 | 2023-05-10T13:04:00
    """
    ).with_columns(
        lt=pw.this.lt.dt.strptime(fmt),
        rt=pw.require(
            pw.this.rt.dt.strptime(fmt),
            pw.this.rt,
        ),
    )

    result = t1.asof_join(t2, t1.t, t2.t, how=JoinMode.LEFT).select(lt=t1.t, rt=t2.t)
    assert_table_equality_wo_index(result, expected)


@pytest.mark.parametrize(
    "left_type,right_type",
    [
        (int, DATE_TIME_UTC),
        (DATE_TIME_NAIVE, int),
        (float, DATE_TIME_NAIVE),
        (DATE_TIME_NAIVE, DATE_TIME_UTC),
    ],
)
def test_incorrect_args(left_type, right_type):
    t1 = pw.Table.empty(t=left_type)

    t2 = pw.Table.empty(t=right_type)
    with pytest.raises(
        TypeError,
        match=r"Arguments \(t_left, t_right\) have to be of types .* but are of types .*",
    ):
        t1.asof_join(
            t2,
            t1.t,
            t2.t,
            how=pw.JoinMode.LEFT,
        )


def test_more_asof_left():
    t1 = T(
        """
       | k1 |  t
     1 |  1 |  3
     2 |  1 |  5
     3 |  1 |  7
     4 |  2 |  2
     5 |  2 |  6
     6 |  3 | 10
     7 |  3 | 11
     8 |  3 | 13
     9 |  4 | -4
    10 |  4 | -1
    11 |  4 |  0
    """
    )

    t2 = T(
        """
      | k1 |  t
    1 |  1 |  4
    2 |  2 |  1
    3 |  2 |  3
    4 |  3 | 12
    5 |  4 | -3
    6 |  4 | -2
    """
    )
    t3 = t1.asof_join(t2, t1.t, t2.t, t1.k1 == t2.k1, how=JoinMode.LEFT).select(
        k1=t1.k1, lt=t1.t, rt=t2.t
    )
    assert_table_equality_wo_index(
        t3,
        T(
            """
    k1 | lt | rt
    1  | 3  |
    1  | 5  | 4
    1  | 7  | 4
    2  | 2  | 1
    2  | 6  | 3
    3  | 10 |
    3  | 11 |
    3  | 13 | 12
    4  | -4 |
    4  | -1 | -2
    4  | 0  | -2
    """
        ),
    )


def test_more_asof_right():
    t1 = T(
        """
       | k1 |  t
     1 |  1 |  3
     2 |  1 |  5
     3 |  1 |  7
     4 |  2 |  2
     5 |  2 |  6
     6 |  3 | 10
     7 |  3 | 11
     8 |  3 | 13
     9 |  4 | -4
    10 |  4 | -1
    11 |  4 |  0
    """
    )

    t2 = T(
        """
      | k1 |  t
    1 |  1 |  4
    2 |  2 |  1
    3 |  2 |  3
    4 |  3 | 12
    5 |  4 | -3
    6 |  4 | -2
    """
    )
    t3 = t1.asof_join(t2, t1.t, t2.t, t1.k1 == t2.k1, how=JoinMode.RIGHT).select(
        k1=t1.k1, lt=t1.t, rt=t2.t
    )
    assert_table_equality_wo_index(
        t3,
        T(
            """
    k1 | lt | rt
       |    | 1
    1  | 3  | 4
    2  | 2  | 3
    3  | 11 | 12
    4  | -4 | -3
    4  | -4 | -2
    """
        ),
    )


def test_more_asof_full():
    t1 = T(
        """
       | k1 |  t
     1 |  1 |  3
     2 |  1 |  5
     3 |  1 |  7
     4 |  2 |  2
     5 |  2 |  6
     6 |  3 | 10
     7 |  3 | 11
     8 |  3 | 13
     9 |  4 | -4
    10 |  4 | -1
    11 |  4 |  0
    """
    )

    t2 = T(
        """
      | k1 |  t
    1 |  1 |  4
    2 |  2 |  1
    3 |  2 |  3
    4 |  3 | 12
    5 |  4 | -3
    6 |  4 | -2
    """
    )
    t3 = t1.asof_join(t2, t1.t, t2.t, t1.k1 == t2.k1, how=JoinMode.OUTER).select(
        k1=t1.k1, lt=t1.t, rt=t2.t
    )
    assert_table_equality_wo_index(
        t3,
        T(
            """
    k1 | lt | rt
       |    | 1
    1  | 3  |
    1  | 3  | 4
    1  | 5  | 4
    1  | 7  | 4
    2  | 2  | 1
    2  | 2  | 3
    2  | 6  | 3
    3  | 10 |
    3  | 11 |
    3  | 11 | 12
    3  | 13 | 12
    4  | -4 |
    4  | -4 | -3
    4  | -4 | -2
    4  | -1 | -2
    4  | 0  | -2
    """
        ),
    )


def test_asof_joins_typing_on():
    left_table = pw.Table.empty(timestamp=int, col=int)
    right_table = pw.Table.empty(timestamp=int, col=str)
    with pytest.raises(expected_exception=TypeError):
        left_table.asof_join_outer(
            right_table,
            left_table.timestamp,
            right_table.timestamp,
            left_table.col == right_table.col,
        )


def test_asof_join_left():
    t1 = T(
        """
        val
          0
         10
         20
         29
         30
    """
    )

    t2 = T(
        """
        val
          0
         10
         20
         30
    """
    )

    expected = T(
        """
          l |  r
          0 |  0
         10 | 10
         20 | 20
         29 | 20
         30 | 30
    """
    ).update_types(r=int | None)

    table = t1.asof_join(
        t2,
        t1.val,
        t2.val,
        how=pw.JoinMode.LEFT,
        direction=pw.temporal.Direction.BACKWARD,
    ).select(l=pw.left.val, r=pw.right.val)

    assert_table_equality_wo_index(table, expected)


@pytest.mark.parametrize("mode", [pw.JoinMode.LEFT, pw.JoinMode.RIGHT])
@pytest.mark.parametrize(
    "dir",
    [
        pw.temporal.Direction.BACKWARD,
        pw.temporal.Direction.FORWARD,
        pw.temporal.Direction.NEAREST,
    ],
)
def test_asof_join_eq(mode, dir):
    t1 = T(
        """
        val
          0
         10
         20
         30
    """
    )

    t2 = T(
        """
        val
          0
         10
         20
         30
    """
    )

    col_name = "r" if mode == pw.JoinMode.LEFT else "l"
    expected = T(
        """
          l |  r
          0 |  0
         10 | 10
         20 | 20
         30 | 30
    """
    ).update_types(**{col_name: int | None})

    table = t1.asof_join(t2, t1.val, t2.val, how=mode, direction=dir).select(
        l=pw.left.val, r=pw.right.val
    )

    assert_table_equality_wo_index(table, expected)


def test_asof_join_instance():
    t1 = T(
        """
        val | i
          0 | 0
         10 | 1
         20 | 1
         25 | 1
         30 | 0
    """
    )

    t2 = T(
        """
        val | i
          0 | 1
         10 | 0
         20 | 1
         30 | 1
    """
    )

    expected = T(
        """
          l |  r
          0 |
         10 |  0
         20 | 20
         25 | 20
         30 | 10
    """
    ).update_types(r=int | None)

    table = t1.asof_join(
        t2,
        t1.val,
        t2.val,
        how=pw.JoinMode.LEFT,
        direction=pw.temporal.Direction.BACKWARD,
        left_instance=t1.i,
        right_instance=t2.i,
    ).select(l=pw.left.val, r=pw.right.val)

    assert_table_equality_wo_index(table, expected)


def test_preserves_column_names():
    table_l = T(
        """
         a | x
         0 | 1
         2 | 1
         4 | 1
         6 | 1
         8 | 1
        10 | 1
        12 | 1
    """
    )

    table_r = T(
        """
         b | y
         1 | 2
         5 | 2
        11 | 2
    """
    )

    expected = T(
        """
         a | x |  b | y
         0 | 1 |    |
         2 | 1 |  1 | 2
         4 | 1 |  1 | 2
         6 | 1 |  5 | 2
         8 | 1 |  5 | 2
        10 | 1 |  5 | 2
        12 | 1 | 11 | 2
    """
    )

    res = table_l.asof_join(table_r, table_l.a, table_r.b, how=pw.JoinMode.LEFT).select(
        **pw.left, **pw.right
    )

    assert_table_equality_wo_index(res, expected)
