"""Ported reference JSON tests
(reference: python/pathway/tests/test_json.py) — Json column access
(`[]` total with null propagation, `.get` with defaults), engine-strict
as_* conversions, UDF-level Json delegation, flatten, serialization of
datetimes, CSV/jsonlines round-trips, unpack_col_dict."""

from __future__ import annotations

import datetime
import re
from typing import Any, Optional

import pandas as pd
import pytest

import pathway_tpu as pw
from pathway_tpu.debug import table_from_markdown as T
from pathway_tpu.debug import table_from_pandas

from tests.ref_utils import (
    assert_table_equality,
    assert_table_equality_wo_index,
    run_all,
)


@pytest.fixture(autouse=True)
def _fresh_graph():
    pw.internals.parse_graph.G.clear()
    from pathway_tpu.internals.errors import clear_errors

    clear_errors()
    yield
    pw.internals.parse_graph.G.clear()


def _json_table_from_list(data):
    class _JsonSubject(pw.io.python.ConnectorSubject):
        def __init__(self, data: list[dict[str, Any]]) -> None:
            super().__init__()
            self.data = data

        def run(self) -> None:
            for key, row in enumerate(self.data):
                self.next(
                    key=key + 1,
                    **{name: pw.Json(value) for name, value in row.items()},
                )

    schema = pw.schema_builder(
        columns={
            "key": pw.column_definition(dtype=int, primary_key=True),
            **{
                name: pw.column_definition(dtype=pw.Json) for name in data[0]
            },
        }
    )

    return pw.io.python.read(_JsonSubject(data), schema=schema).without(
        pw.this.key
    )


def _json_table(**kwargs) -> pw.Table:
    return _json_table_from_list(
        [dict(zip(kwargs, v)) for v in zip(*kwargs.values())]
    )


def _optional_json_table(**kwargs) -> pw.Table:
    @pw.udf
    def filter_null(col: pw.Json) -> pw.Json | None:
        if col == pw.Json(None):
            return None
        return col

    table = _json_table(**kwargs)

    return table.select(
        **{name: filter_null(pw.this[name]) for name in kwargs}
    )


def test_json_get_simple():
    input = _json_table(data=[{"field": 1}, {"field": 2}])

    result = input.select(ret=pw.this.data.get("field"))

    assert_table_equality(
        _optional_json_table(ret=[1, 2]),
        result,
    )


def test_json_get_none():
    input = _json_table(data=[{}])

    with pytest.raises(
        TypeError, match=re.escape(f"Cannot get from {pw.Json | None}.")
    ):
        input.select(result=pw.this.data.get("a").get("b"))


def test_json_get_default():
    input = _json_table(
        data=[
            {"a": {"b": 1}},
            {"a": {"b": None}},
            {"a": {}},
            {"a": [1, 2, 3]},
            {"a": 42},
            {"a": None},
            {},
            [1, 2, 3],
            None,
            1,
            "foo",
        ]
    )

    result = input.select(result=pw.this.data.get("a", default={"b": 42}))

    assert_table_equality(
        _json_table(
            result=[
                {"b": 1},
                {"b": None},
                {},
                [1, 2, 3],
                42,
                None,
                {"b": 42},
                {"b": 42},
                {"b": 42},
                {"b": 42},
                {"b": 42},
            ]
        ),
        result,
    )


def test_json_dict_get_int_index():
    input = _json_table(data=[{"a": 1}])

    result = input.select(result=pw.this.data.get(1))

    assert_table_equality(
        T(
            """
                | result
            1   |
            """
        ).update_types(result=Optional[pw.Json]),
        result,
    )


def test_json_array_get_str_index():
    input = _json_table(data=[{"a": [1, 2, 3]}])

    result = input.select(result=pw.this.data["a"].get("foo"))

    assert_table_equality(
        T(
            """
                | result
            1   |
            """
        ).update_types(result=Optional[pw.Json]),
        result,
    )


def test_json_get_wrong_default():
    input = _json_table(data=[{"a": {"b": 1}}])

    with pytest.raises(
        TypeError,
        match=re.escape(
            rf"Default must be of type {pw.Json | None}, found {int}."
        ),
    ):
        input.select(result=pw.this.data.get("a", 42).get("b"))


def test_json_get_item():
    input = _json_table(
        data=[
            {"a": {"b": 1}},
            {"a": {"b": None}},
            {},
            {"a": {}},
            {"a": [1, 2, 3]},
            {"a": 42},
            {"a": None},
        ]
    )

    result = input.select(result=pw.this.data["a"]["b"])

    assert_table_equality(
        _json_table(result=[1, None, None, None, None, None, None]),
        result,
    )


def test_json_get_array_index():
    input = _json_table(
        index=[0, 1, 2],
        data=[
            {"field": [1, 2, 3]},
            {"field": [4, 5, 6]},
            {"field": [7, 8, 9]},
        ],
    )

    result = input.select(
        result=pw.this.data["field"][pw.this.index.as_int()]
    )

    assert_table_equality(
        _json_table(result=[1, 5, 9]),
        result,
    )


@pytest.mark.parametrize("index", [-1, -4, 3])
def test_json_get_array_index_out_of_bounds(index):
    input = _json_table(data=[{"field": [0, 1, 2]}])

    result = input.select(result=pw.this.data["field"][index])

    assert_table_equality(
        _json_table(result=[None]),
        result,
    )


def test_json_get_item_optional_json():
    input = _json_table(data=[{}])

    with pytest.raises(
        TypeError,
        match=re.escape(f"Cannot get from {pw.Json | None}."),
    ):
        input.select(result=pw.this.data.get("a")["b"])


@pytest.mark.parametrize(
    "from_,to_,method",
    [
        (
            [{"field": 42}, {"field": -1}, {"field": None}, {}],
            [42, -1, None, None],
            pw.ColumnExpression.as_int,
        ),
        (
            [
                {"field": 1.5},
                {"field": 10},
                {"field": 0},
                {"field": -1},
                {"field": 2**32 + 1},
                {"field": 2**45 + 1},
                {"field": None},
                {},
            ],
            [
                1.5,
                10.0,
                0.0,
                -1.0,
                float(2**32 + 1),
                float(2**45 + 1),
                None,
                None,
            ],
            pw.ColumnExpression.as_float,
        ),
        (
            [
                {"field": "foo"},
                {"field": "42"},
                {"field": "true"},
                {"field": None},
                {},
            ],
            ["foo", "42", "true", None, None],
            pw.ColumnExpression.as_str,
        ),
        (
            [{"field": True}, {"field": False}, {"field": None}, {}],
            [True, False, None, None],
            pw.ColumnExpression.as_bool,
        ),
    ],
)
def test_json_as_type(from_, to_, method):
    to_dtype = type(to_[0])

    input = _json_table(data=from_)

    result = input.select(result=method(pw.this.data.get("field")))

    expected = table_from_pandas(
        pd.DataFrame(
            {"key": list(range(1, len(to_) + 1)), "result": to_}
        ),
        schema=pw.schema_builder(
            columns={
                "key": pw.column_definition(primary_key=True, dtype=int),
                "result": pw.column_definition(dtype=Optional[to_dtype]),
            }
        ),
    ).without(pw.this.key)

    assert_table_equality(result, expected)


@pytest.mark.parametrize("value", ["42", "foo", 1.6, True])
def test_json_as_int_wrong_values(value):
    input = _json_table(data=[{"field": value}])

    input.select(result=pw.this.data.get("field").as_int())

    with pytest.raises(ValueError):
        run_all()


@pytest.mark.parametrize("value", ["42", "foo", True])
def test_json_as_float_wrong_values(value):
    input = _json_table(data=[{"field": value}])

    input.select(result=pw.this.data.get("field").as_float())

    with pytest.raises(ValueError):
        run_all()


@pytest.mark.parametrize("value", [1, 1.6, True])
def test_json_as_str_wrong_values(value):
    input = _json_table(data=[{"field": value}])

    input.select(result=pw.this.data.get("field").as_str())

    with pytest.raises(ValueError):
        run_all()


@pytest.mark.parametrize("value", [1, 0, 1.6, "1", "0", "true", "True"])
def test_json_as_bool_wrong_values(value):
    input = _json_table(data=[{"field": value}])

    input.select(result=pw.this.data.get("field").as_bool())

    with pytest.raises(ValueError):
        run_all()


def test_json_input():
    table = _json_table_from_list(
        [
            {
                "a": {"field": 1},
                "b": 2,
                "c": 1.5,
                "d": True,
                "e": "foo",
                "f": [1, 2, 3],
            }
        ]
    )

    result = table.select(
        a=pw.this.a["field"].as_int(),
        b=pw.this.b.as_int(),
        c=pw.this.c.as_float(),
        d=pw.this.d.as_bool(),
        e=pw.this.e.as_str(),
        f=pw.this.f[1].as_int(),
    )

    assert_table_equality(
        T(
            """
                | a | b | c   | d    | e    | f
            1   | 1 | 2 | 1.5 | True | foo  | 2
            """
        ).update_types(
            a=Optional[int],
            b=Optional[int],
            c=Optional[float],
            d=Optional[bool],
            e=Optional[str],
            f=Optional[int],
        ),
        result,
    )


def test_json_apply():
    table = _json_table(a=[1, 2, 3])

    @pw.udf
    def map(a: pw.Json) -> int:
        assert isinstance(a.value, int)
        return a.value + 1

    result = table.select(ret=map(**table))

    assert_table_equality(
        T(
            """
                | ret
            1   | 2
            2   | 3
            3   | 4
            """
        ),
        result,
    )


def test_json_flatten():
    input = _json_table(
        data=[[1, 2], [3], [4, 5]],
    )

    result = input.flatten(pw.this.data).select(
        data=pw.this.data.as_int()
    )

    assert_table_equality_wo_index(
        T(
            """
                | data
            1   | 1
            2   | 2
            3   | 3
            4   | 4
            5   | 5
            """
        ).update_types(data=Optional[int]),
        result,
    )


@pytest.mark.parametrize(
    "value",
    [1, 0, 1.6, "1", "0", "true", {"field": [1]}],
)
def test_json_flatten_wrong_values(value):
    input = _json_table(
        data=[value],
    )

    input.flatten(pw.this.data)

    with pytest.raises(ValueError, match=r"Pathway can't flatten this Json.*"):
        run_all()


def test_json_udf_array_getitem():
    table = _json_table(
        a=[{"field": [1]}, {"field": [2]}, {"field": [3]}]
    )

    @pw.udf
    def map(a: pw.Json) -> int:
        value = a["field"][0].as_int()
        assert isinstance(value, int)
        return value + 1

    result = table.select(ret=map(**table))

    assert_table_equality(
        T(
            """
                | ret
            1   | 2
            2   | 3
            3   | 4
            """
        ),
        result,
    )


def test_json_udf_str_getitem():
    table = _json_table(
        a=[{"field": "foo"}, {"field": "bar"}, {"field": "baz"}]
    )

    @pw.udf
    def map(a: pw.Json) -> str:
        value = a["field"][0].as_str()
        assert isinstance(value, str)
        return value

    result = table.select(ret=map(**table))

    assert_table_equality(
        T(
            """
                | ret
            1   | f
            2   | b
            3   | b
            """
        ),
        result,
    )


def test_json_udf_number_getitem():
    table = _json_table(a=[1, 2, 3])

    @pw.udf
    def map(a: pw.Json) -> int:
        a["field"]
        return 42

    table.select(ret=map(**table))

    with pytest.raises(TypeError):
        run_all()


@pytest.mark.parametrize(
    "values,method",
    [
        ([0, 1, -1], pw.Json.as_int),
        ([1.0, 3.14, -1.2, -1, 42], pw.Json.as_float),
        (["foo", "bar", "baz"], pw.Json.as_str),
        ([True, False], pw.Json.as_bool),
        ([[1, 2, 3], [3, 4, 5]], pw.Json.as_list),
        ([{"a": "foo"}, {"b": "bar"}], pw.Json.as_dict),
    ],
)
def test_json_udf_as_type(values, method):
    to_dtype = type(values[0])
    table = _json_table(data=values)

    @pw.udf
    def map(value: pw.Json):
        return method(value)

    result = table.select(ret=map(pw.this.data)).update_types(ret=to_dtype)

    expected = table_from_pandas(
        pd.DataFrame(
            {"key": list(range(1, len(values) + 1)), "ret": values}
        ),
        schema=pw.schema_builder(
            columns={
                "key": pw.column_definition(primary_key=True, dtype=int),
                "ret": pw.column_definition(dtype=to_dtype),
            }
        ),
    ).without(pw.this.key)

    assert_table_equality(result, expected)


@pytest.mark.parametrize(
    "value",
    [None, 1, 42, "42", 3.14, True, [1, 2, 3], {"a": "foo"}],
)
@pytest.mark.parametrize(
    "_type,method",
    [
        (int, pw.Json.as_int),
        (float, pw.Json.as_float),
        (str, pw.Json.as_str),
        (bool, pw.Json.as_bool),
        (list, pw.Json.as_list),
        (dict, pw.Json.as_dict),
    ],
)
def test_json_udf_as_type_wrong_values(value, _type, method):
    if isinstance(value, _type):
        return
    if isinstance(value, int) and _type == float:
        return

    table = _json_table(a=[{"field": value}])

    @pw.udf
    def map(a: pw.Json) -> Any:
        return method(a["field"])

    table.select(ret=map(**table))

    with pytest.raises(ValueError, match="Cannot convert Json.*"):
        run_all()


def test_json_type():
    table = _json_table(
        a=[{"field": 1}], b=[2], c=[1.5], d=[True], e="foo", f=[[1, 2, 3]]
    )

    @pw.udf
    def assert_types(**kwargs) -> bool:
        return all(isinstance(arg, pw.Json) for arg in kwargs.values())

    result = table.select(ret=assert_types(**table))

    assert_table_equality(
        T(
            """
                | ret
            1   | True
            """
        ),
        result,
    )


def test_json_recursive():
    table = T(
        """
            | value
        1   | 1
        2   | 2
        3   | 3
        """
    )

    @pw.udf
    def wrap(value: int) -> pw.Json:
        j = pw.Json(pw.Json(pw.Json(value)))
        assert isinstance(j.value, int)
        return j

    result = table.select(ret=wrap(pw.this.value).as_int())

    assert_table_equality(
        T(
            """
                | ret
            1   | 1
            2   | 2
            3   | 3
            """
        ).update_types(ret=Optional[int]),
        result,
    )


def test_json_nested():
    table = T(
        """
            | value
        1   | foo
        2   | bar
        3   | baz
        """
    )

    @pw.udf
    def wrap(value: int) -> pw.Json:
        j = pw.Json(pw.Json([pw.Json(value)]))
        assert isinstance(j[0].as_str(), str)
        return j

    result = table.select(ret=wrap(pw.this.value).get(0).as_str())

    assert_table_equality(
        result,
        T(
            """
                | ret
            1   | foo
            2   | bar
            3   | baz
            """
        ).update_types(ret=Optional[str]),
    )


@pytest.mark.parametrize(
    "data,_type",
    [
        ([0, 1.0, -1.5, "0", "0.0", True], float),
        ([0, 1.0, -1.5, "0", True], int),
        ([True, 1.5, 42, 0, "", "1", "0", [42], {}], bool),
    ],
)
def test_json_coerce(data, _type):
    @pw.udf(return_type=_type)
    def coerce(value: pw.Json):
        result = _type(value)
        assert isinstance(result, _type)
        return result

    table = _json_table(data=data).select(ret=coerce(pw.this.data))

    expected = pw.debug.table_from_rows(
        schema=pw.schema_builder(
            columns={
                "ret": pw.column_definition(dtype=_type),
            }
        ),
        rows=[(_type(x),) for x in data],
    )

    assert_table_equality_wo_index(
        table,
        expected,
    )


def test_json_iter():
    table = _json_table(
        data=[{"field": [1, 2, 3]}, {"field": [4, 5, 6]}]
    )

    @pw.udf
    def sum_(a: pw.Json) -> int:
        return sum(x.as_int() for x in a["field"])

    result = table.select(ret=sum_(pw.this.data))

    assert_table_equality(
        T(
            """
                | ret
            1   | 6
            2   | 15
            """
        ).update_types(ret=int),
        result,
    )


def test_json_iter_wrong_value():
    table = _json_table(data=[{"field": 42}])

    @pw.udf
    def sum_(value: pw.Json) -> int:
        return sum(x.as_int() for x in value["field"])

    table.select(ret=sum_(pw.this.data))

    with pytest.raises(TypeError, match="'int' object is not iterable"):
        run_all()


def test_json_len():
    table = _json_table(
        data=[
            {"field": [1, 2, 3]},
            {"field": {"foo": 1, "bar": [1, 2, 3]}},
        ]
    )

    @pw.udf
    def len_(value: pw.Json) -> int:
        return len(value["field"])

    result = table.select(ret=len_(pw.this.data))

    assert_table_equality(
        T(
            """
                | ret
            1   | 3
            2   | 2
            """
        ).update_types(ret=int),
        result,
    )


def test_json_len_wrong_value():
    table = _json_table(data=[{"field": 42}])

    @pw.udf
    def len_(value: pw.Json) -> int:
        return len(value["field"])

    table.select(ret=len_(pw.this.data))

    with pytest.raises(TypeError, match="object of type 'int' has no len()"):
        run_all()


def test_json_index():
    table = _json_table(data=[{"field": 42}])

    @pw.udf
    def bin_(value: pw.Json) -> str:
        return bin(value["field"])

    result = table.select(ret=bin_(pw.this.data))

    assert_table_equality(
        T(
            """
                | ret
            1   | 0b101010
            """
        ).update_types(ret=str),
        result,
    )


def test_json_index_wrong_value():
    table = _json_table(data=[{"field": 42.5}])

    @pw.udf
    def bin_(value: pw.Json) -> str:
        return bin(value["field"])

    table.select(ret=bin_(pw.this.data))

    with pytest.raises(
        TypeError, match="'float' object cannot be interpreted as an integer"
    ):
        run_all()


def test_json_reversed():
    table = _json_table(
        data=[{"field": ["foo", "bar"]}, {"field": {"baz": 42, "foo": 42}}]
    )

    @pw.udf
    def reversed_(value: pw.Json) -> pw.Json:
        result = reversed(value["field"])
        return next(result)

    result = table.select(ret=reversed_(pw.this.data))

    assert_table_equality(result, _json_table(ret=["bar", "foo"]))


def test_json_reversed_wrong_value():
    table = _json_table(data=[{"field": 42}])

    @pw.udf
    def reversed_(value: pw.Json) -> pw.Json:
        result = reversed(value["field"])
        return next(result)

    table.select(ret=reversed_(pw.this.data))

    with pytest.raises(TypeError, match="'int' object is not reversible"):
        run_all()


def test_json_datetime_serialization():
    class InputSchema(pw.Schema):
        a: pw.Json
        b: pw.PyObjectWrapper[dict]
        c: pw.PyObjectWrapper[dict]

    @pw.udf
    def to_json(obj: pw.PyObjectWrapper[dict]) -> pw.Json:
        return pw.Json(obj.value)

    @pw.udf
    def to_json_wrapped(obj) -> pw.Json:
        return pw.Json({k: pw.Json(v) for k, v in obj.value.items()})

    obj = {
        "dtn": datetime.datetime(2025, 3, 14, 10, 13),
        "dt": datetime.datetime(
            2025,
            3,
            14,
            10,
            13,
            microsecond=123456,
            tzinfo=datetime.timezone.utc,
        ),
        "pdn": pd.Timestamp("2025-03-14"),
        "pd": pd.Timestamp("2025-03-14T00:00+00:00"),
        "pwn": pw.DateTimeNaive("2025-03-14T10:13:00.123456789"),
        "pw": pw.DateTimeUtc("2025-03-14T10:13:00.123456000+00:00"),
        "dur": pd.Timedelta("4 days 2 microseconds"),
    }

    rows = [
        {"a": obj, "b": pw.wrap_py_object(obj), "c": pw.wrap_py_object(obj)}
    ]

    table = pw.debug.table_from_rows(
        InputSchema,
        [tuple(row.values()) for row in rows],
    ).select(
        a=pw.this.a, b=to_json(pw.this.b), c=to_json_wrapped(pw.this.c)
    )

    expected = {
        "dtn": "2025-03-14T10:13:00.000000000",
        "dt": "2025-03-14T10:13:00.123456000+00:00",
        "pdn": "2025-03-14T00:00:00.000000000",
        "pd": "2025-03-14T00:00:00.000000000+00:00",
        "pwn": "2025-03-14T10:13:00.123456789",
        "pw": "2025-03-14T10:13:00.123456000+00:00",
        "dur": 345600000002000,
    }

    keys, result = pw.debug.table_to_dicts(table)

    for col_name in ["a", "b", "c"]:
        val = result[col_name][keys[0]]
        assert isinstance(val, pw.Json)
        assert val.as_dict() == expected


def test_json_serde(tmp_path):
    class ObjectSchema(pw.Schema):
        dtn: pw.DateTimeNaive
        dt: pw.DateTimeUtc
        pdn: pw.DateTimeNaive
        pd: pw.DateTimeUtc
        pwn: pw.DateTimeNaive
        pw: pw.DateTimeUtc
        dur: pw.Duration
        text: str

    class TableSchema(ObjectSchema):
        nested: pw.Json

    obj = {
        "dtn": datetime.datetime(2025, 3, 14, 10, 13),
        "dt": datetime.datetime(
            2025,
            3,
            14,
            10,
            13,
            microsecond=123456,
            tzinfo=datetime.timezone.utc,
        ),
        "pdn": pd.Timestamp("2025-03-14"),
        "pd": pd.Timestamp("2025-03-14T00:00+00:00"),
        "pwn": pw.DateTimeNaive("2025-03-14T10:13:00.123456789"),
        "pw": pw.DateTimeUtc("2025-03-14T10:13:00.123456000+00:00"),
        "dur": pd.Timedelta("4 days 2 microseconds"),
        "text": "2025-03-14T00:00+00:00",
    }

    obj["nested"] = obj.copy()

    def prepare():
        pw.internals.parse_graph.G.clear()
        table = pw.debug.table_from_rows(
            TableSchema,
            [(*(obj.values()), obj)],
        )
        pw.io.jsonlines.write(table, tmp_path / "input.jsonl")
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)

    def read():
        pw.internals.parse_graph.G.clear()
        table = pw.io.jsonlines.read(
            tmp_path / "input.jsonl", schema=TableSchema, mode="static"
        )
        expected = pw.debug.table_from_rows(
            TableSchema,
            [(*(obj.values()), obj)],
        )
        assert_table_equality_wo_index(table, expected)

    prepare()
    read()


def test_json_unpack_col_dict(tmp_path):
    class ObjectSchema(pw.Schema):
        dtn: pw.DateTimeNaive
        dt: pw.DateTimeUtc
        pdn: pw.DateTimeNaive
        pd: pw.DateTimeUtc
        pwn: pw.DateTimeNaive
        pw: pw.DateTimeUtc
        null_dt: Optional[pw.DateTimeUtc]
        dur: pw.Duration
        null_dur: Optional[pw.Duration]
        text: str

    class TableSchema(pw.Schema):
        obj: pw.Json

    obj = {
        "dtn": datetime.datetime(2025, 3, 14, 10, 13),
        "dt": datetime.datetime(
            2025,
            3,
            14,
            10,
            13,
            microsecond=123456,
            tzinfo=datetime.timezone.utc,
        ),
        "pdn": pd.Timestamp("2025-03-14"),
        "pd": pd.Timestamp("2025-03-14T00:00+00:00"),
        "pwn": pw.DateTimeNaive("2025-03-14T10:13:00.123456789"),
        "pw": pw.DateTimeUtc("2025-03-14T10:13:00.123456000+00:00"),
        "null_dt": None,
        "dur": pd.Timedelta("4 days 2 microseconds"),
        "null_dur": None,
        "text": "2025-03-14T00:00+00:00",
    }

    def prepare():
        pw.internals.parse_graph.G.clear()
        table = pw.debug.table_from_rows(
            TableSchema,
            [(obj,)],
        )
        pw.io.jsonlines.write(table, tmp_path / "input.jsonl")
        pw.run(monitoring_level=pw.MonitoringLevel.NONE)

    def read():
        pw.internals.parse_graph.G.clear()
        table = pw.io.jsonlines.read(
            tmp_path / "input.jsonl", schema=TableSchema, mode="static"
        )
        result = pw.utils.col.unpack_col_dict(table.obj, ObjectSchema)
        expected = pw.debug.table_from_rows(
            ObjectSchema,
            [tuple(obj.values())],
        )
        assert_table_equality_wo_index(result, expected)

    prepare()
    read()


@pytest.mark.parametrize(
    "_type",
    [int, float, bool, str, pw.DateTimeNaive, pw.DateTimeUtc, pw.Duration],
)
def test_json_unpack_col_null(_type):
    class TableSchema(pw.Schema):
        obj: pw.Json

    table = pw.debug.table_from_rows(
        TableSchema,
        [({"col": None},)],
    )

    pw.utils.col.unpack_col_dict(
        table.obj,
        pw.schema_builder(
            columns={
                "col": pw.column_definition(dtype=_type),
            }
        ),
    )

    with pytest.raises(ValueError, match="cannot unwrap if there is None value"):
        run_all()


@pytest.mark.parametrize("delimiter", [",", ";", "\t"])
def test_json_in_csv(tmp_path, delimiter: str):
    # (reference: test_json.py test_json_in_csv) — csv cells typed as
    # pw.Json parse as JSON values after csv unquoting
    values = [
        ('"{""a"": 1,""b"": ""foo"", ""c"": null, ""d"": [1,2,3]}"', dict),
        ('"[1,2,3]"', list),
        ("[]", list),
        ("1", int),
        ('"42"', int),
        ("1.5", float),
        ('""""""', str),
        ('"""42"""', str),
        ('"""foo"""', str),
        ('"""true"""', str),
        ("true", bool),
        ('"false"', bool),
        ("null", type(None)),
    ]

    if delimiter != ",":
        values += [
            ('{"field": 1, "b": "foo", "c": null, "d": [1,2,3]}', dict),
            ("[1,2,3]", list),
        ]

    headers = [f"c{i}" for i in range(0, len(values))]
    input_path = tmp_path / "input.csv"
    input_path.write_text(
        delimiter.join(headers)
        + "\n"
        + delimiter.join(v[0] for v in values)
        + "\n"
    )

    schema = pw.schema_builder(
        {name: pw.column_definition(dtype=pw.Json) for name in headers}
    )
    table = pw.io.csv.read(
        input_path,
        schema=schema,
        mode="static",
        csv_settings=pw.io.csv.CsvParserSettings(delimiter=delimiter),
    )

    @pw.udf
    def assert_types(**kwargs) -> bool:
        result = all(isinstance(arg, pw.Json) for arg in kwargs.values())
        for v, t in zip(kwargs.values(), [v[1] for v in values]):
            assert isinstance(v.value, t)
        return result

    result = table.select(ret=assert_types(**table))

    assert_table_equality_wo_index(
        T(
            """
                | ret
            1   | True
            """
        ),
        result,
    )
